// SPDX-License-Identifier: MIT
pragma solidity ^0.8.24;

/// @title TopdownMessenger — on-chain fixture for IPC top-down proofs
///
/// The deployable counterpart of the Python fixture world
/// (`ipc_proofs_tpu/fixtures.py`) and of benchmark config 5
/// (`benchmarks/run_configs.py`): a minimal FEVM contract whose storage and
/// event shapes are exactly what the proof engines target.
///
/// Proof-relevant invariants (checked by the framework's tests/benchmarks):
///
/// 1. `subnets` occupies storage slot 0, so the nonce for a subnet lives at
///    `keccak256(abi.encode(subnetId, uint256(0)))` — the slot the framework
///    computes with `compute_mapping_slot` (`ipc_proofs_tpu/state/storage.py`).
/// 2. The nonce is incremented BEFORE each emission, so after `trigger(id, n)`
///    the stored nonce equals the `nonce` field of the last emitted event —
///    a storage proof and an event proof over the same checkpoint must agree.
/// 3. `subnetId` is an indexed bytes32, so it lands in topic1 uninterpreted;
///    event proofs match on `keccak256("NewTopDownMessage(bytes32,uint256)")`
///    as topic0 and the raw subnet id as topic1.
///
/// Reference parity: topdown-messenger/src/TopdownMessenger.sol:1-33 (same
/// ABI, storage layout, and emission order; independent implementation).
contract TopdownMessenger {
    /// Slot 0: per-subnet top-down message nonce. A bare uint256 mapping has
    /// the same storage layout as a single-field struct mapping: the value
    /// sits directly at the mapping slot hash.
    mapping(bytes32 => uint256) public subnets;

    event NewTopDownMessage(bytes32 indexed subnetId, uint256 nonce);

    /// Emit `count` top-down messages for `subnetId`, bumping the nonce
    /// before each emission (invariant 2 above).
    function trigger(bytes32 subnetId, uint256 count) external {
        uint256 nonce = subnets[subnetId];
        for (uint256 i = 0; i < count; i++) {
            unchecked {
                nonce += 1;
            }
            emit NewTopDownMessage(subnetId, nonce);
        }
        subnets[subnetId] = nonce;
    }

    /// Convenience read: current nonce for a subnet.
    function topDownNonce(bytes32 subnetId) external view returns (uint256) {
        return subnets[subnetId];
    }
}
