// SPDX-License-Identifier: MIT
pragma solidity ^0.8.24;

import {TopdownMessenger} from "../TopdownMessenger.sol";

/// Forge tests for the proof-relevant invariants the framework targets
/// (the reference's Foundry project ships zero tests; these pin the three
/// invariants documented in TopdownMessenger.sol and mirrored by the
/// Python model in ipc_proofs_tpu/fixtures.py + tests/test_contracts.py).
///
/// Minimal-interface note: written against forge-std's Test conventions
/// but depending only on built-in `assert`-style checks plus the vm
/// record-logs cheatcode, so it needs no lib beyond forge-std.
interface Vm {
    function load(address target, bytes32 slot) external view returns (bytes32);
    function recordLogs() external;
    struct Log {
        bytes32[] topics;
        bytes data;
        address emitter;
    }
    function getRecordedLogs() external returns (Log[] memory);
}

contract TopdownMessengerTest {
    Vm constant vm = Vm(address(uint160(uint256(keccak256("hevm cheat code")))));

    TopdownMessenger messenger;
    bytes32 constant SUBNET = bytes32("subnet-a");

    function setUp() public {
        messenger = new TopdownMessenger();
    }

    /// Invariant 1: the nonce for a subnet lives at
    /// keccak256(abi.encode(subnetId, uint256(0))) — slot-0 mapping layout,
    /// the exact slot ipc_proofs_tpu.state.storage.compute_mapping_slot
    /// derives and the storage proofs target.
    function test_slot0_mapping_layout() public {
        messenger.trigger(SUBNET, 3);
        bytes32 slot = keccak256(abi.encode(SUBNET, uint256(0)));
        bytes32 raw = vm.load(address(messenger), slot);
        assert(uint256(raw) == 3);
        assert(messenger.topDownNonce(SUBNET) == 3);
    }

    /// Invariant 2: the nonce increments BEFORE each emission, so the
    /// stored nonce equals the last emitted event's nonce, and a batch of
    /// `count` emissions carries nonces prev+1 .. prev+count.
    function test_pre_increment_emission_order() public {
        messenger.trigger(SUBNET, 2); // prev = 2
        vm.recordLogs();
        messenger.trigger(SUBNET, 3);
        Vm.Log[] memory logs = vm.getRecordedLogs();
        assert(logs.length == 3);
        bytes32 topic0 = keccak256("NewTopDownMessage(bytes32,uint256)");
        for (uint256 i = 0; i < logs.length; i++) {
            assert(logs[i].topics.length == 2);
            assert(logs[i].topics[0] == topic0); // invariant 3: sig topic
            assert(logs[i].topics[1] == SUBNET); // raw indexed bytes32
            assert(abi.decode(logs[i].data, (uint256)) == 2 + i + 1);
        }
        assert(messenger.topDownNonce(SUBNET) == 5); // storage == last nonce
    }
}
