#!/usr/bin/env python
"""Headline benchmark: event-proofs/sec over a 4096-tipset batch.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The measured quantity is BASELINE.json config 2: batch event-proof
generation (sparse filter, ~1% receipt match rate) — the padded
[tipset, receipt, event] match pipeline plus the per-receipt reduce, on the
best available platform (TPU chip if the axon backend initializes, else XLA
CPU). ``vs_baseline`` compares against the reference's architecture: a
single-threaded scalar decode+match loop over the same events, measured
in-process (the reference publishes no numbers — BASELINE.md).

Extra diagnostics go to stderr; stdout carries exactly the one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _log(*args):
    print(*args, file=sys.stderr, flush=True)




def _scalar_baseline_proofs_per_sec(
    topic0: bytes, topic1: bytes, total_events: int, proofs_per_pass: int, sample: int = 20000
) -> float:
    """The reference-architecture baseline: one thread, one Python object per
    event, decode + match per event (events/generator.rs:217-233 shape)."""
    from ipc_proofs_tpu.backend.cpu import CpuBackend
    from ipc_proofs_tpu.fixtures import EventFixture

    events = []
    for i in range(sample // 2):
        events.append(
            EventFixture(emitter=1001, signature="NewTopDownMessage(bytes32,uint256)",
                         topic1="calib-subnet-1").to_stamped()
        )
        events.append(
            EventFixture(emitter=1001, signature="Other(uint256)", topic1="nope").to_stamped()
        )
    backend = CpuBackend(use_native=False)
    start = time.perf_counter()
    backend.event_match_mask(events, topic0, topic1, 1001)
    elapsed = time.perf_counter() - start
    per_event = elapsed / len(events)
    pass_time = per_event * total_events
    return proofs_per_pass / pass_time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--platform", default="auto", help="auto|default|cpu")
    parser.add_argument("--tipsets", type=int, default=4096)
    parser.add_argument("--receipts", type=int, default=16)
    parser.add_argument("--events", type=int, default=4)
    parser.add_argument("--match-rate", type=float, default=0.01)
    parser.add_argument(
        "--iters", type=int, default=20,
        help="lower bound for the slope-timing k_large loop length "
        "(full runs floor it at 105 passes for resolution; --quick floors at 13)",
    )
    parser.add_argument("--probe-timeout", type=float, default=240.0)
    parser.add_argument("--quick", action="store_true", help="small shapes for smoke runs")
    args = parser.parse_args()

    if args.quick:
        args.tipsets, args.iters = min(args.tipsets, 256), min(args.iters, 5)

    from ipc_proofs_tpu.utils.platform import pick_platform

    platform = pick_platform(args.platform, args.probe_timeout, log=_log)
    if platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    devices = jax.devices()
    _log(f"bench: devices = {devices}")

    from ipc_proofs_tpu.parallel.mesh import make_mesh
    from ipc_proofs_tpu.parallel.pipeline import sharded_match_pipeline, synthetic_event_batch
    from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature

    topic0 = hash_event_signature("NewTopDownMessage(bytes32,uint256)")
    topic1 = ascii_to_bytes32("calib-subnet-1")

    t_build = time.perf_counter()
    batch = synthetic_event_batch(
        args.tipsets, args.receipts, args.events,
        topic0, topic1, emitter=1001, match_rate=args.match_rate, seed=42,
    )
    total_events = args.tipsets * args.receipts * args.events
    _log(
        f"bench: batch [{args.tipsets}×{args.receipts}×{args.events}] = "
        f"{total_events} events built in {time.perf_counter() - t_build:.2f}s"
    )

    n_dev = len(devices)
    sp = 2 if (n_dev % 2 == 0 and n_dev > 1) else 1
    mesh = make_mesh(n_dev, sp=sp)
    jitted, shard_batch = sharded_match_pipeline(mesh)
    sharded_args = shard_batch(batch, topic0, topic1, 1001)

    # warmup / compile; the true per-pass count for reporting
    t_compile = time.perf_counter()
    hits, mask, count = jitted(*sharded_args)
    proofs_per_pass = int(count)
    _log(
        f"bench: compile+first pass {time.perf_counter() - t_compile:.2f}s, "
        f"{proofs_per_pass} matching proofs per pass"
    )

    # Slope-timed in-jit loop: the chip sits behind a high-latency tunnel
    # (~60 ms/dispatch) and block_until_ready is unreliable on the axon
    # platform, so per-call timing measures the link, not the kernel.
    # See ipc_proofs_tpu/utils/timing.py.
    import jax.numpy as jnp

    from ipc_proofs_tpu.utils.timing import measure_pass_seconds

    def one_pass(i, topics, n_topics, emitters, valid, s0, s1, actor):
        # XOR the loop index into the topic words: iteration-dependent input
        # (no hoisting), and the count depends on the real match output.
        _, _, c = jitted(topics ^ i.astype(topics.dtype), n_topics, emitters, valid, s0, s1, actor)
        return c.astype(jnp.int32)

    if args.quick:
        k_small, k_large = 3, max(args.iters, 13)
    else:
        k_small, k_large = 5, max(args.iters, 105)
    pt = measure_pass_seconds(one_pass, sharded_args, k_small=k_small, k_large=k_large)
    pass_time = pt.seconds
    proofs_per_sec = proofs_per_pass / pass_time
    events_per_sec = total_events / pass_time
    _log(
        f"bench: slope timing k={pt.k_small}/{pt.k_large} "
        f"(t={pt.t_small*1e3:.1f}/{pt.t_large*1e3:.1f} ms) → "
        f"{pass_time*1e6:.1f} us/pass, "
        f"{events_per_sec:,.0f} events/s scanned, {proofs_per_sec:,.0f} proofs/s"
    )

    baseline = _scalar_baseline_proofs_per_sec(topic0, topic1, total_events, proofs_per_pass)
    _log(f"bench: scalar single-thread baseline ≈ {baseline:,.0f} proofs/s")

    print(
        json.dumps(
            {
                "metric": "event_proofs_per_sec_4k_tipset_batch",
                "value": round(proofs_per_sec, 1),
                "unit": "proofs/s",
                "vs_baseline": round(proofs_per_sec / baseline, 2) if baseline > 0 else None,
            }
        )
    )


if __name__ == "__main__":
    main()
