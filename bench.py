#!/usr/bin/env python
"""Headline benchmark: END-TO-END event proofs over a 4096-tipset-pair range.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

The measured quantity is the BASELINE.json north star, measured honestly:
the FULL pipeline over a 4096-pair synthetic range (~1 % receipt match rate)
on the best available platform —

  generate:  Phase A host scan (native C walker over receipts/events AMTs)
             → Phase B device match mask (one jitted dispatch)
             → Phase C pass-2 witness recording (host)
             → Phase D merged witness materialization
  verify:    batched witness-CID recompute (device or scalar, whichever the
             backend picks for the batch size) → offline replay of every
             proof (grouped batch verifier)

The e2e number includes every host decode, device transfer, and readback a
real user pays (warmed jit caches; compile excluded by a warmup pass at the
same shapes). ``vs_baseline`` compares against the reference architecture —
a single-thread scalar decode+match+record+verify over the same world,
measured in-process on a subrange and scaled (the reference publishes no
numbers — BASELINE.md).

Watchdog structure: the tunneled chip on this environment can stall not
just at initialization (the probe's job) but MID-RUN — observed as a
dispatch that never returns, hanging the whole benchmark so no JSON is
ever printed. The default invocation therefore runs as an ORCHESTRATOR:
every measurement leg executes in its own subprocess (``--leg NAME``) under
a timeout, so a stalled device call costs one leg, not the artifact. When a
device leg times out on the chip platform, the remaining device legs (and
an immediate e2e retry) downgrade to CPU, and the final JSON records which
legs ran, timed out, or fell back (``legs`` / ``watchdog_fallback``).

Extra diagnostics go to stderr; stdout carries exactly the one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def _log(*args):
    print(*args, file=sys.stderr, flush=True)


SIG = "NewTopDownMessage(bytes32,uint256)"
TOPIC1 = "calib-subnet-1"
ACTOR = 1001

LEGS = (
    "e2e", "kernel", "cid", "baseline", "native_baseline", "serve",
    "witness", "resilience", "durability", "observability", "storage",
    "asyncfetch", "cluster", "standing", "fleetobs", "onchip", "backfill",
    "zerocopy", "hostkill", "overload", "registry",
)

# per-leg watchdog timeouts in seconds: (full, quick). Device legs budget
# for tunnel init (~40 s) + jit compile (~40 s) on top of the measurement.
_LEG_TIMEOUTS = {
    "e2e": (480.0, 240.0),
    "kernel": (330.0, 180.0),
    "cid": (480.0, 240.0),
    "baseline": (900.0, 420.0),
    "native_baseline": (420.0, 240.0),
    "serve": (300.0, 150.0),
    "witness": (300.0, 150.0),
    "resilience": (300.0, 150.0),
    "durability": (300.0, 150.0),
    "observability": (300.0, 150.0),
    "storage": (300.0, 150.0),
    "asyncfetch": (300.0, 150.0),
    "cluster": (420.0, 240.0),
    "standing": (420.0, 240.0),
    "fleetobs": (420.0, 240.0),
    "onchip": (480.0, 240.0),
    "backfill": (420.0, 240.0),
    "zerocopy": (420.0, 240.0),
    "hostkill": (420.0, 240.0),
    "overload": (300.0, 150.0),
    "registry": (300.0, 150.0),
}


def _parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--platform", default="auto", help="auto|default|cpu")
    parser.add_argument("--tipsets", type=int, default=4096, help="tipset pairs in the range")
    parser.add_argument("--receipts", type=int, default=16)
    parser.add_argument("--events", type=int, default=4)
    parser.add_argument("--match-rate", type=float, default=0.01)
    parser.add_argument(
        "--kernel-iters", type=int, default=20,
        help="lower bound for the secondary kernel-slope loop (full runs "
        "floor it at 105 passes; --quick floors at 13)",
    )
    parser.add_argument("--baseline-pairs", type=int, default=128,
                        help="subrange size for the scalar baseline measurement")
    parser.add_argument(
        "--e2e-reps", type=int, default=5,
        help="measured e2e passes; the headline is the best (--quick uses 3)",
    )
    parser.add_argument(
        "--threads", type=int, default=None,
        help="ONE thread budget for the e2e leg's range engine, partitioned "
        "over scan/record/verify workers + native scan fan-out "
        "(default: the process affinity core count)",
    )
    parser.add_argument(
        "--scan-threads", type=int, default=None,
        help="legacy: pin the e2e pipeline's scan+match worker count",
    )
    parser.add_argument(
        "--pipeline-depth", type=int, default=2,
        help="chunks buffered between the e2e pipeline's stages",
    )
    parser.add_argument(
        "--serve-requests", type=int, default=256,
        help="closed-loop requests for the serve leg (--quick uses 96)",
    )
    parser.add_argument(
        "--serve-concurrency", type=int, default=32,
        help="client threads for the serve leg's closed loop",
    )
    parser.add_argument(
        "--probe-timeout", type=float, default=150.0,
        help="per-attempt chip-probe timeout; a healthy tunnel initializes "
        "in 10-40 s, and 3 retried attempts must finish inside the driver's "
        "bench budget so a dead tunnel still yields a (CPU) artifact",
    )
    parser.add_argument(
        "--cluster-pairs", type=int, default=16,
        help="demo-world pairs for the cluster leg (--quick uses 8)",
    )
    parser.add_argument(
        "--cluster-requests", type=int, default=64,
        help="closed-loop generate requests per shard-count in the cluster "
        "leg (--quick uses 32)",
    )
    parser.add_argument("--quick", action="store_true", help="small shapes for smoke runs")
    parser.add_argument(
        "--profile", default=None, metavar="DIR",
        help="emit a jax.profiler trace of one measured e2e pass into DIR",
    )
    parser.add_argument(
        "--leg", default=None, choices=LEGS,
        help="run ONE measurement leg in this process and print its partial "
        "JSON (internal: the orchestrator spawns these under watchdogs)",
    )
    parser.add_argument(
        "--leg-timeout-mult", type=float,
        default=float(os.environ.get("IPC_BENCH_LEG_TIMEOUT_MULT", "1.0")),
        help="scale every per-leg watchdog timeout",
    )
    args = parser.parse_args(argv)
    if args.quick:
        args.tipsets = min(args.tipsets, 256)
        args.baseline_pairs = min(args.baseline_pairs, 32)
        args.kernel_iters = min(args.kernel_iters, 5)
        args.serve_requests = min(args.serve_requests, 96)
    return args


def _setup_platform(args) -> str:
    """Resolve the platform for THIS process and configure jax; returns the
    actual jax platform name ('tpu' / 'cpu' / ...)."""
    from ipc_proofs_tpu.utils.platform import pick_platform

    platform = pick_platform(args.platform, args.probe_timeout, log=_log)
    if platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    _log(f"bench: devices = {jax.devices()}")
    # the ACTUAL platform — if the chip plugin fails fast (not a hang), jax
    # silently falls back to CPU, and every leg must label its numbers with
    # what it really ran on, not what was requested
    return jax.devices()[0].platform


# --------------------------------------------------------------------------
# measurement legs (each runnable standalone via --leg NAME)
# --------------------------------------------------------------------------


def _leg_e2e(args) -> dict:
    """The headline: best-of-n end-to-end generate+verify at the bench shape,
    measured TWICE — serial (flat generation, then staged verification) and
    stage-overlapped (scan ∥ record ∥ verify on the bounded-queue pipeline)
    — so the artifact reports the pipelined headline next to the serial
    figure and their ratio. Returns every headline JSON field except the
    baseline ratios."""
    jax_platform = _setup_platform(args)
    import gc

    import jax

    from ipc_proofs_tpu.backend import get_backend
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import (
        generate_and_verify_range_overlapped,
        generate_event_proofs_for_range,
    )
    from ipc_proofs_tpu.utils.metrics import Metrics

    # --- build the range world (setup, not measured) ------------------------
    t0 = time.perf_counter()
    bs, pairs, n_matching = build_range_world(
        args.tipsets, args.receipts, args.events, args.match_rate
    )
    total_events = args.tipsets * args.receipts * args.events
    _log(
        f"bench: world [{args.tipsets} pairs × {args.receipts} rcpt × "
        f"{args.events} ev] = {total_events} events, {n_matching} matching "
        f"receipts, built in {time.perf_counter() - t0:.1f}s"
    )

    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR)
    backend = get_backend("tpu")

    # honest host introspection: cpu_count is the machine; the affinity mask
    # is what THIS process may actually use (containers/cgroups shrink it)
    host_cores = os.cpu_count() or 1
    host_cores_affinity = (
        len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity")
        else host_cores
    )
    # the bench resolves the SAME budget the drivers would and passes the
    # split explicitly, so the artifact records the real parallelism
    from ipc_proofs_tpu.utils.threads import resolve_thread_budget

    budget = resolve_thread_budget(
        threads=args.threads, scan_threads=args.scan_threads
    )
    scan_threads = budget.scan_workers
    pipeline_depth = max(1, args.pipeline_depth)
    # pipelined chunking: enough chunks in flight to feed every scan worker
    # plus the queue depth, floored so tiny worlds still form a pipeline
    pipe_chunk = max(1, min(1024, len(pairs) // max(4, 2 * scan_threads)))
    # IPC_BENCH_OVERLAP_VERIFY=0 is the escape hatch back to serial-only
    measure_pipelined = os.environ.get("IPC_BENCH_OVERLAP_VERIFY", "") != "0"

    def _run_serial(metrics):
        t0 = time.perf_counter()
        bundle = generate_event_proofs_for_range(
            bs, pairs, spec, match_backend=backend, metrics=metrics
        )
        t_gen = time.perf_counter() - t0
        results, vstages = _staged_verify(bundle, backend)
        assert all(results) and len(results) == len(bundle.event_proofs)
        return bundle, t_gen, sum(vstages.values()), vstages

    def _run_pipelined(metrics):
        # scan (scan_threads workers) ∥ record ∥ verify in ONE bounded-queue
        # executor; bundle + verdicts bit-identical to serial (tests pin it)
        t0 = time.perf_counter()
        bundle, chunk_out = generate_and_verify_range_overlapped(
            bs, pairs, spec, chunk_size=pipe_chunk,
            verify_chunk=lambda b: _staged_verify(b, backend),
            match_backend=backend, metrics=metrics,
            scan_threads=scan_threads, pipeline_depth=pipeline_depth,
            record_workers=budget.record_workers,
            verify_workers=budget.verify_workers,
            threads=args.threads,
        )
        t_wall = time.perf_counter() - t0
        results = [r for res, _ in chunk_out for r in res]
        assert all(results) and len(results) == len(bundle.event_proofs)
        vstages: dict = {}
        for _, chunk_stages in chunk_out:
            for name, seconds in chunk_stages.items():
                vstages[name] = vstages.get(name, 0.0) + seconds
        return bundle, t_wall, sum(vstages.values()), vstages

    # --- warmup: compile every jit kernel at BOTH measurement shapes --------
    # (the flat driver matches one range-sized batch; the pipelined driver
    # matches pipe_chunk-sized batches — separate jit shapes). The second
    # pipelined pass settles allocator pools at the headline shape so the
    # measured reps sample the plateau, not the ramp.
    t0 = time.perf_counter()
    _run_serial(Metrics())
    _log(f"bench: serial warmup (incl. jit compile) {time.perf_counter() - t0:.1f}s")
    if measure_pipelined:
        t0 = time.perf_counter()
        _run_pipelined(Metrics())
        _run_pipelined(Metrics())
        _log(f"bench: pipelined warmup ×2 {time.perf_counter() - t0:.1f}s")

    # optional profiler trace of one representative pass (not measured)
    if args.profile:
        from ipc_proofs_tpu.utils.profiling import maybe_profile

        with maybe_profile(args.profile):
            if measure_pipelined:
                _run_pipelined(Metrics())
            else:
                _run_serial(Metrics())

    # --- measured end-to-end passes (best of n — steady state, GC settled) --
    n_reps = 3 if args.quick else args.e2e_reps

    def _measure(run) -> tuple:
        best = None
        walls: list[float] = []
        for _ in range(n_reps):
            gc.collect()
            metrics = Metrics()
            bundle, t_wall, t_verify, vstages = run(metrics)
            walls.append(t_wall)
            if best is None or t_wall < best[0]:
                best = (t_wall, t_verify, bundle, metrics, vstages)
        return best, walls

    serial_best, serial_walls = _measure(_run_serial)
    pipe_best, pipe_walls = (None, [])
    if measure_pipelined:
        pipe_best, pipe_walls = _measure(_run_pipelined)

    # headline = the pipelined pipeline when measured (the serial figure
    # rides along for the speedup ratio); serial otherwise
    t_e2e, t_verify, bundle, metrics, vstages = pipe_best or serial_best
    rep_walls = pipe_walls or serial_walls
    n_proofs = len(bundle.event_proofs)
    serial_wall = serial_best[0]

    # NOTE: under the pipelined engine stages overlap across worker threads,
    # so busy sums (stages_ms) can exceed the e2e wall; stages_wall_ms is
    # each stage's interval-union wall — the honest per-stage clock. e2e
    # rates always divide by the measured WALL.
    snap = metrics.snapshot()
    gtimers = snap["timers"]
    stages = {
        "scan": gtimers.get("range_scan", {}).get("total_s", 0.0),
        "match": gtimers.get("range_match", {}).get("total_s", 0.0),
        "record": gtimers.get("range_record", {}).get("total_s", 0.0),
        **vstages,
    }
    stages_wall = {
        name: timer["wall_s"]
        for name, timer in gtimers.items()
        if name.startswith("range_")
    }
    stage_str = " ".join(f"{k}={v * 1000:.0f}ms" for k, v in stages.items())
    proofs_per_sec = n_proofs / t_e2e
    events_per_sec = total_events / t_e2e
    serial_proofs_per_sec = n_proofs / serial_wall
    speedup = serial_wall / t_e2e if pipe_best is not None else None
    _log(
        f"bench: e2e wall {t_e2e * 1e3:.0f}ms (verify busy {t_verify * 1e3:.0f}ms "
        f"concurrent) → {n_proofs} proofs, {len(bundle.blocks)} witness blocks "
        f"({bundle.witness_bytes()} B)"
    )
    _log(f"bench: stages {stage_str}")
    _log(
        f"bench: {proofs_per_sec:,.0f} proofs/s e2e pipelined vs "
        f"{serial_proofs_per_sec:,.0f} serial"
        + (f" ({speedup:.2f}x)" if speedup else "")
    )

    # the C scanner sizes its own intra-chunk thread pool; report it next to
    # the pipeline's scan workers rather than conflating the two
    from ipc_proofs_tpu.backend.native import load_scan_ext

    _scan_ext = load_scan_ext()
    native_scan_threads = (
        int(_scan_ext.scan_threads())
        if _scan_ext is not None and hasattr(_scan_ext, "scan_threads")
        else None
    )

    return {
        "metric": "event_proofs_per_sec_4k_range_e2e",
        "value": round(proofs_per_sec, 1),
        "unit": "proofs/s",
        "platform": jax_platform,
        "devices": len(jax.devices()),
        "host_cores": host_cores,
        "host_cores_affinity": host_cores_affinity,
        # the pipeline's effective per-stage worker counts for this leg,
        # plus the ONE budget they were partitioned from
        "scan_threads": scan_threads if pipe_best is not None else 1,
        "record_workers": budget.record_workers if pipe_best is not None else 1,
        "verify_workers": budget.verify_workers if pipe_best is not None else 1,
        "effective_threads": budget.total,
        "native_scan_threads": native_scan_threads,
        "pipeline_depth": pipeline_depth if pipe_best is not None else None,
        "pipeline_chunk": pipe_chunk if pipe_best is not None else len(pairs),
        "events_per_sec_e2e": round(events_per_sec, 1),
        "proofs": n_proofs,
        # busy sums can exceed the e2e wall when stages overlap;
        # stages_wall_ms is the per-stage interval-union wall
        "stages_ms": {k: round(v * 1000, 1) for k, v in stages.items()},
        "stages_wall_ms": {k: round(v * 1000, 1) for k, v in stages_wall.items()},
        "stages_overlap": pipe_best is not None,
        "gen_verify_overlap": pipe_best is not None,
        "overlap_efficiency": snap.get("overlap_efficiency"),
        # the serial figure measured in the SAME process at the same shape,
        # and the headline's ratio to it — the honest single-host speedup
        "serial_proofs_per_sec": round(serial_proofs_per_sec, 1),
        "serial_e2e_reps_s": [round(w, 4) for w in serial_walls],
        "pipeline_speedup_vs_serial": round(speedup, 3) if speedup else None,
        # measurement policy, recorded so the headline is auditable: warm
        # passes per variant, best of n_reps; every rep's wall kept for
        # honesty (the spread is the noise the 'best' is picked from)
        "e2e_policy": f"warm-bestof{n_reps}-serial+pipelined",
        "e2e_reps_s": [round(w, 4) for w in rep_walls],
        "_platform": jax_platform,
    }


def _leg_kernel(args) -> dict:
    """The round-1 headline, kept as a secondary line: the jitted mask
    kernel's slope-timed throughput (tunnel RTT cancelled)."""
    jax_platform = _setup_platform(args)
    import jax
    import jax.numpy as jnp

    from ipc_proofs_tpu.parallel.mesh import make_mesh
    from ipc_proofs_tpu.parallel.pipeline import sharded_match_pipeline, synthetic_event_batch
    from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature
    from ipc_proofs_tpu.utils.timing import measure_pass_seconds

    topic0 = hash_event_signature(SIG)
    topic1 = ascii_to_bytes32(TOPIC1)
    batch = synthetic_event_batch(
        args.tipsets, args.receipts, args.events,
        topic0, topic1, emitter=ACTOR, match_rate=args.match_rate, seed=42,
    )
    n_dev = len(jax.devices())
    sp = 2 if (n_dev % 2 == 0 and n_dev > 1) else 1
    mesh = make_mesh(n_dev, sp=sp)
    jitted, shard_batch = sharded_match_pipeline(mesh)
    sharded_args = shard_batch(batch, topic0, topic1, ACTOR)
    _hits, _mask, count = jitted(*sharded_args)  # compile + warm

    def one_pass(i, topics, n_topics, emitters, valid, s0, s1, actor):
        _, _, c = jitted(topics ^ i.astype(topics.dtype), n_topics, emitters, valid, s0, s1, actor)
        return c.astype(jnp.int32)

    if args.quick:
        k_small, k_large = 3, max(args.kernel_iters, 13)
    else:
        k_small, k_large = 5, max(args.kernel_iters, 105)
    pt = measure_pass_seconds(one_pass, sharded_args, k_small=k_small, k_large=k_large)
    total_events = args.tipsets * args.receipts * args.events
    rate = total_events / pt.seconds
    _log(
        f"bench: device mask kernel (slope k={pt.k_small}/{pt.k_large}): "
        f"{pt.seconds * 1e6:.1f} us/pass, {rate:,.0f} events/s "
        f"({int(count)} matches/pass)"
    )
    return {
        "device_mask_kernel_events_per_sec": round(rate, 1),
        "_platform": jax_platform,
    }


def _leg_cid(args) -> dict:
    """Witness-verify CIDs/sec (BASELINE config 4's kernel): blake2b-256
    over 200-byte IPLD nodes — config 4's OWN block size
    (`benchmarks/run_configs.py` config 4). On-chip: the two-block Pallas
    kernel when the chip accepts it, else the XLA scan kernel,
    slope-timed. Off-chip: the C++ batch hasher — the backend the
    verifier actually selects there (`witness_cid_kernel` labels which
    path produced the number)."""
    jax_platform = _setup_platform(args)
    import numpy as np

    from ipc_proofs_tpu.core.hashes import blake2b_256

    native = scan = None
    if jax_platform != "tpu":
        from ipc_proofs_tpu.backend.native import load_native, load_scan_ext

        native = load_native()
        scan = load_scan_ext()
        if scan is not None and not hasattr(scan, "verify_blake2b_blocks"):
            scan = None

    n = 20_000 if args.quick else 200_000
    if jax_platform != "tpu" and native is None and scan is None:
        # no native paths at all: tiny-shape XLA fallback so the leg
        # finishes inside its watchdog instead of timing out to null
        n = min(n, 20_000)
    rng = np.random.default_rng(1)
    payload = rng.integers(0, 256, size=(n, 200), dtype=np.uint8)
    messages = [payload[i].tobytes() for i in range(n)]

    if native is not None or scan is not None:
        # Off-chip, the leg measures the best backend the verifier would
        # ACTUALLY pick on this platform — the scan-ext in-place batch
        # verify when built, else the C++ batch hasher. Timing the XLA
        # emulation of the device kernel here produced a meaningless
        # ~4-orders-slower number that burned 3 min of watchdog budget
        # (round-4 artifact: 11.8k CIDs/s, 184 s on one core).
        candidates = []
        if scan is not None:
            digests = [blake2b_256(m) for m in messages]
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                assert scan.verify_blake2b_blocks(digests, messages) is True
                best = min(best, time.perf_counter() - t0)
            candidates.append((n / best, "scan-ext-verify"))
        if native is not None:
            assert native.blake2b256_batch(messages[:1])[0] == blake2b_256(messages[0])
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                native.blake2b256_batch(messages)
                best = min(best, time.perf_counter() - t0)
            candidates.append((n / best, "cpp-batch"))
        rate, kernel = max(candidates)
        _log(f"bench: witness-CID recompute ({kernel}, best-of-3): {rate:,.0f} CIDs/s")
        return {
            "witness_cid_kernel_per_sec": round(rate, 1),
            "witness_cid_kernel": kernel,
            "_platform": jax_platform,
        }

    from ipc_proofs_tpu.ops.cid_bench import blake2b_cid_bench_setup
    from ipc_proofs_tpu.utils.timing import measure_pass_seconds

    one_pass, fn_args, first, kernel = blake2b_cid_bench_setup(messages)
    assert first[0].tobytes() == blake2b_256(messages[0])
    pt = measure_pass_seconds(one_pass, fn_args, k_small=3, k_large=13 if args.quick else 23)
    rate = n / pt.seconds
    _log(
        f"bench: witness-CID recompute ({kernel} kernel, slope "
        f"k={pt.k_small}/{pt.k_large}): {rate:,.0f} CIDs/s"
    )
    return {
        "witness_cid_kernel_per_sec": round(rate, 1),
        "witness_cid_kernel": kernel,
        "_platform": jax_platform,
    }


def _leg_baseline(args) -> dict:
    """Scalar reference-architecture baseline (host-only; no device)."""
    t0 = time.perf_counter()
    baseline = _scalar_baseline(
        min(args.baseline_pairs, args.tipsets), args.receipts, args.events
    )
    _log(
        f"bench: scalar reference-architecture baseline ≈ {baseline:,.1f} "
        f"proofs/s e2e (measured in {time.perf_counter() - t0:.1f}s)"
    )
    return {"scalar_baseline_proofs_per_sec": round(baseline, 1)}


def _leg_native_baseline(args) -> dict:
    """Language-fair native baseline (host-only; no device)."""
    t0 = time.perf_counter()
    native_baseline = _native_baseline(
        min(args.baseline_pairs, args.tipsets), args.receipts, args.events
    )
    _log(
        f"bench: native (C-primitive, per-pair) reference-architecture "
        f"baseline ≈ {native_baseline:,.1f} proofs/s e2e "
        f"(measured in {time.perf_counter() - t0:.1f}s)"
    )
    return {"native_baseline_proofs_per_sec": round(native_baseline, 1)}


def _leg_serve(args) -> dict:
    """Closed-loop load test of the serving daemon (host-only, hermetic):
    micro-batched throughput through `serve.ProofService` vs the same
    requests verified per-request sequentially. Each request is a
    single-proof bundle over a shared synthetic chain — the shape an
    individual client actually sends — so the measured win is exactly the
    coalescing (shared witness load + grouped replay across requests)."""
    import threading

    from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
    from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
    from ipc_proofs_tpu.proofs.generator import (
        EventProofSpec,
        StorageProofSpec,
        generate_proof_bundle,
    )
    from ipc_proofs_tpu.serve import ProofService, ServiceConfig
    from ipc_proofs_tpu.state.storage import calculate_storage_slot

    slot = calculate_storage_slot(TOPIC1, 0)
    # enough messages that the shared group work (exec-order reconstruction,
    # witness load, header decodes) dominates per-proof replay — that shared
    # work is exactly what coalescing amortizes across the batch
    n_events = 384 if args.quick else 768
    world = build_chain(
        [ContractFixture(actor_id=ACTOR, storage={slot: (42).to_bytes(2, "big")})],
        [
            [EventFixture(emitter=ACTOR, signature=SIG, topic1=TOPIC1,
                          data=i.to_bytes(32, "big"))]
            for i in range(n_events)
        ],
    )
    full = generate_proof_bundle(
        world.store, world.parent, world.child,
        [StorageProofSpec(actor_id=ACTOR, slot=slot)],
        [EventProofSpec(event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR)],
    )
    requests = [
        UnifiedProofBundle(
            storage_proofs=[], event_proofs=[full.event_proofs[i % n_events]],
            blocks=full.blocks,
        )
        for i in range(args.serve_requests)
    ]

    # --- per-request sequential comparator (one replay per request) --------
    from ipc_proofs_tpu.serve import sequential_verify_baseline

    sequential_verify_baseline(requests[:4])  # warm caches/extensions
    t0 = time.perf_counter()
    seq = sequential_verify_baseline(requests)
    t_seq = time.perf_counter() - t0
    assert all(r.all_valid() for r in seq)
    seq_rps = len(requests) / t_seq

    # --- micro-batched closed loop at --serve-concurrency ------------------
    service = ProofService(
        store=world.store,
        config=ServiceConfig(
            max_batch=args.serve_concurrency, max_wait_ms=4.0,
            queue_capacity=max(512, 2 * args.serve_requests), workers=2,
        ),
    )
    it = iter(range(len(requests)))
    it_lock = threading.Lock()
    failures: list = []

    def client():
        while True:
            with it_lock:
                i = next(it, None)
            if i is None:
                return
            resp = service.verify(requests[i])
            if not resp.all_valid():
                failures.append(i)

    threads = [
        threading.Thread(target=client) for _ in range(args.serve_concurrency)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    t_batched = time.perf_counter() - t0
    assert not failures, f"serve leg: {len(failures)} requests failed verification"
    batched_rps = len(requests) / t_batched

    snap = service.metrics_snapshot()
    service.drain()
    lat = snap.get("histograms", {}).get("serve.latency_ms.verify", {})
    batch_hist = snap.get("histograms", {}).get("serve.batch_size.verify", {})
    speedup = batched_rps / seq_rps if seq_rps else None
    _log(
        f"bench: serve closed-loop c={args.serve_concurrency}: "
        f"{batched_rps:,.0f} req/s micro-batched vs {seq_rps:,.0f} req/s "
        f"per-request sequential ({speedup:.2f}×); p99 "
        f"{lat.get('p99', float('nan')):.1f}ms, mean batch "
        f"{batch_hist.get('mean', float('nan')):.1f}"
    )
    return {
        "serve_batched_rps": round(batched_rps, 1),
        "serve_sequential_rps": round(seq_rps, 1),
        "serve_speedup_vs_sequential": round(speedup, 2) if speedup else None,
        "serve_concurrency": args.serve_concurrency,
        "serve_requests": len(requests),
        "serve_p99_latency_ms": lat.get("p99"),
        "serve_mean_batch": round(batch_hist.get("mean", 0.0), 2),
        "serve_rejections": sum(
            v for k, v in snap.get("counters", {}).items()
            if k.startswith("serve.rejected")
        ),
    }


def _leg_witness(args) -> dict:
    """Substantiate the witness savings: the two-pass vs single-pass
    recording win (BASELINE ~60 % row), plus the witness-diet layers —
    bytes/proof under cross-request aggregation at K ∈ {1, 16, 256},
    the consecutive-epoch delta ratio, and the zlib framing ratio."""
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.event_generator import single_pass_witness_cids
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range

    n = min(64, args.tipsets)
    bs, pairs, _ = build_range_world(
        n, args.receipts, args.events, args.match_rate, base_height=30_000_000
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR)
    bundle = generate_event_proofs_for_range(bs, pairs, spec)
    two_pass_bytes = bundle.witness_bytes()

    needed = set()
    for pair in pairs:
        needed |= single_pass_witness_cids(bs, pair.parent, pair.child)
    single_pass_bytes = 0
    for cid in needed:
        raw = bs.get(cid)
        if raw is not None:
            single_pass_bytes += len(raw)

    pct = 100.0 * (1.0 - two_pass_bytes / single_pass_bytes)
    _log(
        f"bench: witness ({n} pairs): two-pass {two_pass_bytes:,} B vs "
        f"single-pass {single_pass_bytes:,} B → {pct:.1f}% reduction"
    )

    # --- the witness diet (ROADMAP item 1) ---------------------------------
    # the diet layers need non-trivial bundles: at the sparse default
    # --match-rate most single-pair bundles are empty, so measure on a
    # small match-dense world (same shape knobs, floor on the match rate)
    import base64

    from ipc_proofs_tpu.witness import (
        aggregate_range_bundle,
        compress_blocks,
        pack_blocks,
    )
    from ipc_proofs_tpu.witness.delta import encode_delta

    def wire_bytes(obj) -> int:
        return len(json.dumps(obj, sort_keys=True, separators=(",", ":")))

    diet_n = 8
    dbs, dpairs, _ = build_range_world(
        diet_n, args.receipts, args.events, max(args.match_rate, 0.5),
        base_height=30_000_000,
    )

    # aggregation: wire bytes per claim at K co-tipset claims — the claim
    # table maps repeated claims onto shared spans, so the witness (and the
    # proofs) serialize once no matter how many claims reference them
    distinct_n = 4
    solo = generate_event_proofs_for_range(dbs, dpairs[:1], spec)
    distinct = generate_event_proofs_for_range(dbs, dpairs[:distinct_n], spec)
    bytes_per_proof = {}
    for k in (1, 16, 256):
        d = min(distinct_n, k)
        bundle_k = solo if d == 1 else distinct
        agg = aggregate_range_bundle(
            bundle_k, dpairs, list(range(d)),
            claim_indexes=[i % d for i in range(k)],
        )
        total = wire_bytes(
            {"bundle": bundle_k.to_json_obj(), "claims": agg.claims_json()}
        )
        bytes_per_proof[k] = round(total / k, 1)

    # delta witnesses: epoch N+1 shipped against the client's acked
    # epoch-N base — a range subscriber's base grows one tipset per
    # epoch, so the delta re-ships the (small) proofs but only the new
    # tipset's witness blocks
    prefix = [
        generate_event_proofs_for_range(dbs, dpairs[: i + 1], spec)
        for i in range(diet_n)
    ]
    ratios = []
    for base, nxt in zip(prefix, prefix[1:]):
        dobj = encode_delta(nxt, base.cid_set(), base.digest())
        ratios.append(
            wire_bytes({"bundle_delta": dobj})
            / wire_bytes({"bundle": nxt.to_json_obj()})
        )
    delta_ratio = sum(ratios) / len(ratios)

    # compressed framing: zlib frame over the canonical CID ordering
    frame = compress_blocks(distinct.blocks, "zlib")
    compressed_ratio = len(base64.b64decode(frame["frame"])) / len(
        pack_blocks(distinct.blocks)
    )

    _log(
        f"bench: witness diet: {bytes_per_proof[1]:,.0f} B/proof at K=1 → "
        f"{bytes_per_proof[16]:,.0f} at K=16 → {bytes_per_proof[256]:,.0f} "
        f"at K=256; delta ratio {delta_ratio:.3f} "
        f"({len(ratios)} consecutive epochs), zlib ratio {compressed_ratio:.3f}"
    )
    return {
        "witness_reduction_pct": round(pct, 1),
        "witness_two_pass_bytes": two_pass_bytes,
        "witness_single_pass_bytes": single_pass_bytes,
        "witness_sample_pairs": n,
        "witness_bytes_per_proof_k1": bytes_per_proof[1],
        "witness_bytes_per_proof_k16": bytes_per_proof[16],
        "witness_bytes_per_proof_k256": bytes_per_proof[256],
        "witness_delta_ratio": round(delta_ratio, 4),
        "witness_compressed_ratio": round(compressed_ratio, 4),
    }


def _leg_resilience(args) -> dict:
    """Fault-tolerance measurements (host-only, hermetic): range-proof
    throughput through the full failover client stack — `LotusClient`
    (retries) → `EndpointPool` (breakers, integrity verification) →
    `RpcBlockstore` — against in-process Lotus sessions, three ways:

    - fault-free, integrity checks ON (the production configuration);
    - fault-free, integrity checks OFF (isolates the multihash-recompute
      overhead → ``integrity_overhead_pct``);
    - under a seeded 10 % injected fault rate with two endpoints
      (``proofs_per_sec_at_fault_rate`` — what resilience costs when the
      chain actually misbehaves);

    plus ``recovery_ms``: wall time for a block read to fail over from a
    dead primary to a healthy secondary, breaker included."""
    import gc
    import random as _random

    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined
    from ipc_proofs_tpu.store.failover import EndpointPool
    from ipc_proofs_tpu.store.faults import FaultPlan, FaultySession, LocalLotusSession
    from ipc_proofs_tpu.store.rpc import LotusClient, RpcBlockstore
    from ipc_proofs_tpu.utils.metrics import Metrics

    n_pairs = 16 if args.quick else 48
    bs, pairs, _ = build_range_world(
        n_pairs, args.receipts, args.events, 0.05,
        signature=SIG, topic1=TOPIC1, actor_id=ACTOR, base_height=40_000_000,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR)

    def _client(session, seed=0, **kw):
        kw.setdefault("max_retries", 3)
        return LotusClient(
            "http://bench-resilience", session=session,
            backoff_base_s=0.0005, backoff_max_s=0.002,
            rng=_random.Random(seed), **kw,
        )

    def _run(store, metrics=None):
        t0 = time.perf_counter()
        bundle = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=8, metrics=metrics,
            scan_threads=1, scan_retries=2, force_pipeline=True,
        )
        return bundle, time.perf_counter() - t0

    def _best_of(store, reps=2):
        best = None
        for _ in range(reps):
            gc.collect()
            bundle, wall = _run(store)
            if best is None or wall < best[1]:
                best = (bundle, wall)
        return best

    # --- fault-free, integrity verification ON (production config) ----------
    verified_store = RpcBlockstore(_client(LocalLotusSession(bs)))
    _run(verified_store)  # warm (jit compile, extension load)
    bundle, t_verified = _best_of(verified_store)
    n_proofs = len(bundle.event_proofs)
    fault_free_rate = n_proofs / t_verified

    # --- fault-free, integrity verification OFF ------------------------------
    # the "pool already verifies" escape hatch doubles as the counterfactual:
    # same stack, multihash recompute skipped
    unverified_client = _client(LocalLotusSession(bs))
    unverified_client.verifies_integrity = True
    _, t_unverified = _best_of(RpcBlockstore(unverified_client))
    overhead_pct = 100.0 * (t_verified - t_unverified) / t_unverified

    # --- throughput at a 10 % injected fault rate ----------------------------
    # two faulty endpoints behind the pool; a typed abort (fault schedule too
    # hostile for the retry budget) just moves to the next seed — the metric
    # is the throughput of a run that SURVIVES faults, and seeds are fixed so
    # the artifact is reproducible
    fault_rate = 0.1
    faulted_rate = None
    faulted_metrics = Metrics()
    for seed in range(10):
        clients = [
            _client(
                FaultySession(
                    LocalLotusSession(bs),
                    FaultPlan(seed * 101 + i, fault_rate=fault_rate),
                    sleep=lambda s: None,
                ),
                seed=seed + i,
                metrics=faulted_metrics,
            )
            for i in range(2)
        ]
        pool = EndpointPool(
            clients, breaker_threshold=3, breaker_reset_s=0.05,
            metrics=faulted_metrics,
        )
        try:
            fb, wall = _run(RpcBlockstore(pool, metrics=faulted_metrics))
        except (RuntimeError, ConnectionError, TimeoutError, OSError):
            continue
        finally:
            pool.close()
        assert fb.to_json() == bundle.to_json(), "faulted bundle diverged"
        faulted_rate = len(fb.event_proofs) / wall
        break

    # --- failover recovery latency ------------------------------------------
    # dead primary (every post raises), healthy secondary; fresh pool per rep
    # so each measurement starts with a closed breaker
    class _DeadSession:
        def post(self, url, json=None, timeout=None, headers=None):
            raise ConnectionError("dead endpoint")

    probe_cid = bundle.blocks[0].cid
    recovery_s = float("inf")
    for rep in range(5):
        dead = _client(_DeadSession(), seed=rep, max_retries=1)
        healthy = _client(LocalLotusSession(bs), seed=rep)
        pool = EndpointPool([dead, healthy], breaker_threshold=1)
        # pin the dead endpoint as the routed-first candidate so the rep
        # really measures detect + fail over, not a lucky healthy-first pick
        pool._endpoints[0].score = 2.0
        t0 = time.perf_counter()
        data = pool.chain_read_obj(probe_cid)
        recovery_s = min(recovery_s, time.perf_counter() - t0)
        assert data == bundle.blocks[0].data
        pool.close()

    counters = faulted_metrics.snapshot()["counters"]
    _log(
        f"bench: resilience ({n_pairs} pairs): {fault_free_rate:,.1f} proofs/s "
        f"fault-free verified (integrity overhead {overhead_pct:.1f}%), "
        + (f"{faulted_rate:,.1f} proofs/s at {fault_rate:.0%} faults"
           if faulted_rate else f"no surviving run at {fault_rate:.0%} faults")
        + f", recovery {recovery_s * 1000:.2f}ms "
        f"(retries={counters.get('rpc.retries', 0)}, "
        f"integrity_failures={counters.get('rpc.integrity_failures', 0)})"
    )
    return {
        "resilience_fault_free_proofs_per_sec": round(fault_free_rate, 1),
        "integrity_overhead_pct": round(overhead_pct, 2),
        "proofs_per_sec_at_fault_rate": (
            round(faulted_rate, 1) if faulted_rate else None
        ),
        "resilience_fault_rate": fault_rate,
        "recovery_ms": round(recovery_s * 1000, 3),
    }


def _leg_durability(args) -> dict:
    """Durability measurements (host-only, hermetic): what the write-ahead
    job journal (`ipc_proofs_tpu/jobs/`) costs and buys on the pipelined
    range driver:

    - ``durability_journal_overhead_pct`` — the journal's attributable
      cost (``jobs.commit_us``: thread-CPU time of serialize + checksum +
      write + fsync per committed chunk, timed where it happens) as a
      share of the un-journaled run's wall clock. Direct attribution, not
      wall-clock subtraction: the commit work runs in the pipeline's
      record stage and largely overlaps the scan of the next chunk, so
      subtracting two ~0.5 s runs is dominated by scheduler noise on
      shared hosts (observed ±8 % swings either sign) while the commit
      CPU time is stable. CPU-time attribution is an *upper bound* on the
      added critical path: it counts every cycle a commit steals from
      compute while excluding the GIL/IO waits that overlap productive
      scanning. The journaled bundle must stay byte-identical to the
      plain run;
    - ``durability_resume_ms`` — wall time for a fully-committed job to
      resume: replay the journal, skip every chunk, merge the final bundle
      (the crash-recovery happy path measured end to end);
    - ``durability_replay_chunks_per_sec`` — journal replay throughput
      (`jobs.resume_ms` over `jobs.chunks_replayed`)."""
    import gc
    import shutil
    import tempfile

    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.jobs import JOBS_JOURNAL_NAME
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined
    from ipc_proofs_tpu.utils.metrics import Metrics

    # leg-local shape, heavier per pair than the orchestrator defaults: the
    # journal writes one fsync'd record per CHUNK, so the honest overhead
    # number needs chunks with representative work in them — against a
    # ~3 ms toy chunk the fsync dominates and the ratio measures the disk,
    # not the design
    n_pairs = 48 if args.quick else 96
    chunk_size = 8 if args.quick else 16
    bs, pairs, _ = build_range_world(
        n_pairs, 48, 8, 0.1,
        signature=SIG, topic1=TOPIC1, actor_id=ACTOR, base_height=50_000_000,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR)

    def _run(job_dir=None, metrics=None):
        t0 = time.perf_counter()
        bundle = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=chunk_size, metrics=metrics,
            scan_threads=1, force_pipeline=True, job_dir=job_dir,
        )
        return bundle, time.perf_counter() - t0

    workdir = tempfile.mkdtemp(prefix="bench_durability_")
    try:
        _run()  # warm (jit compile, extension load)
        plain_bundle, t_plain = None, None
        for _ in range(3):
            gc.collect()
            bundle, wall = _run()
            if t_plain is None or wall < t_plain:
                plain_bundle, t_plain = bundle, wall

        # attributable journal cost: best-of-3 of the per-run total of
        # jobs.commit_us (each run gets a fresh job dir — every chunk
        # commits, nothing resumes)
        commit_s = None
        journaled_bundle = None
        for rep in range(3):
            gc.collect()
            jm = Metrics()
            journaled_bundle, _ = _run(
                os.path.join(workdir, f"job{rep}"), metrics=jm
            )
            rep_s = jm.snapshot()["counters"].get("jobs.commit_us", 0) / 1e6
            if commit_s is None or rep_s < commit_s:
                commit_s = rep_s
        assert journaled_bundle.to_json() == plain_bundle.to_json(), (
            "journaled bundle diverged from the plain run"
        )
        overhead_pct = 100.0 * commit_s / t_plain

        # resume latency: a fully-committed job re-run end to end
        resume_dir = os.path.join(workdir, "resume_job")
        _run(resume_dir)
        resume_metrics = Metrics()
        resumed_bundle, t_resume = _run(resume_dir, metrics=resume_metrics)
        assert resumed_bundle.to_json() == plain_bundle.to_json(), (
            "resumed bundle diverged from the plain run"
        )
        counters = resume_metrics.snapshot()["counters"]
        chunks_replayed = counters.get("jobs.chunks_replayed", 0)
        replay_ms = counters.get("jobs.resume_ms", 0)
        n_chunks = (n_pairs + chunk_size - 1) // chunk_size
        assert chunks_replayed == n_chunks, (chunks_replayed, n_chunks)
        replay_rate = (
            chunks_replayed / (replay_ms / 1000.0) if replay_ms > 0 else None
        )
        journal_bytes = os.path.getsize(
            os.path.join(resume_dir, JOBS_JOURNAL_NAME)
        )
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    _log(
        f"bench: durability ({n_pairs} pairs, {n_chunks} chunks): journal "
        f"overhead {overhead_pct:.2f}% ({commit_s * 1000:.1f}ms commit time "
        f"on a {t_plain * 1000:.0f}ms run, {journal_bytes} journal bytes), "
        f"resume {t_resume * 1000:.1f}ms e2e "
        f"(replay {replay_ms}ms for {chunks_replayed} chunks)"
    )
    return {
        "durability_journal_overhead_pct": round(overhead_pct, 2),
        "durability_resume_ms": round(t_resume * 1000, 2),
        "durability_replay_chunks_per_sec": (
            round(replay_rate, 1) if replay_rate is not None else None
        ),
        "durability_journal_bytes": journal_bytes,
        "durability_chunks": n_chunks,
    }


def _leg_observability(args) -> dict:
    """Observability measurements (host-only, hermetic): what the trace
    spine (`ipc_proofs_tpu/obs/`) costs when fully enabled:

    - ``trace_overhead_pct`` — wall-clock cost of running the pipelined
      range driver with the span collector enabled (every stage, RPC, and
      journal span recorded) vs. the always-on default (flight ring
      only). Off/on reps are interleaved and each side takes its best-of-4
      so a load spike on a shared host lands on both sides instead of
      biasing one; clamped at 0 because the delta is within scheduler
      noise when the spine is doing its job. The budget is ≤ 3 %;
    - ``spans_per_proof`` — spans recorded per event proof produced, the
      tracing "weight" of one unit of useful work;
    - ``observability_spans_recorded`` / ``observability_spans_dropped``
      — collector totals for the traced run (drops mean the capacity
      default is too small for this workload shape)."""
    import gc

    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.obs import disable_tracing, enable_tracing
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined
    from ipc_proofs_tpu.utils.metrics import Metrics

    n_pairs = 48 if args.quick else 96
    chunk_size = 8 if args.quick else 16
    bs, pairs, _ = build_range_world(
        n_pairs, 48, 8, 0.1,
        signature=SIG, topic1=TOPIC1, actor_id=ACTOR, base_height=60_000_000,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR)

    def _run(metrics):
        t0 = time.perf_counter()
        bundle = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=chunk_size, metrics=metrics,
            scan_threads=1, force_pipeline=True,
        )
        return bundle, time.perf_counter() - t0

    disable_tracing()  # baseline = the always-on default (flight ring only)
    _run(Metrics())  # warm (jit compile, extension load)
    # interleave off/on reps: a load spike on a shared host hits both
    # sides instead of biasing whichever mode happened to run during it
    t_off = t_on = None
    spans_recorded = spans_dropped = 0
    bundle_off = bundle_on = None
    try:
        for _ in range(4):
            gc.collect()
            disable_tracing()
            bundle_off, wall = _run(Metrics())
            if t_off is None or wall < t_off:
                t_off = wall
            gc.collect()
            m = Metrics()
            enable_tracing(metrics=m)
            bundle_on, wall = _run(m)
            counters = m.snapshot()["counters"]
            if t_on is None or wall < t_on:
                t_on = wall
                spans_recorded = counters.get("trace.spans_recorded", 0)
                spans_dropped = counters.get("trace.spans_dropped", 0)
    finally:
        disable_tracing()
    assert bundle_on.to_json() == bundle_off.to_json(), (
        "traced bundle diverged from the untraced run"
    )

    n_proofs = len(bundle_on.event_proofs)
    overhead_pct = max(0.0, 100.0 * (t_on - t_off) / t_off)
    spans_per_proof = spans_recorded / n_proofs if n_proofs else None
    _log(
        f"bench: observability ({n_pairs} pairs, {n_proofs} proofs): trace "
        f"overhead {overhead_pct:.2f}% ({t_on * 1000:.0f}ms traced vs "
        f"{t_off * 1000:.0f}ms untraced), {spans_recorded} spans recorded "
        f"({spans_dropped} dropped), {spans_per_proof:.1f} spans/proof"
    )
    return {
        "trace_overhead_pct": round(overhead_pct, 2),
        "spans_per_proof": (
            round(spans_per_proof, 2) if spans_per_proof is not None else None
        ),
        "observability_spans_recorded": spans_recorded,
        "observability_spans_dropped": spans_dropped,
        "observability_pairs": n_pairs,
    }


def _leg_storage(args) -> dict:
    """Tiered-store measurements (host-only, hermetic): what the disk tier
    (`ipc_proofs_tpu/storex/`) and the chain-follow prefetch buy on a
    range request whose blocks live behind an RPC with real latency:

    - ``cold_vs_warm_speedup`` — wall-clock ratio of a cold-RPC run
      (every block over `LotusClient`, per-call simulated network delay)
      to a disk-warm run after a simulated restart (fresh memory cache,
      same segment files). The warm run must issue ZERO RPC calls and
      produce a byte-identical bundle — both asserted, not assumed;
    - ``disk_hit_ratio`` — fraction of the warm run's block reads served
      (multihash-verified) from the disk tier;
    - ``prefetch_hit_ratio`` — fraction of a request's block reads served
      locally after the `ChainFollower` pre-warmed the tipset spines into
      a fresh store (the follower only walks the spine + first-level
      links, so this is < 1 by design — it measures how much of a real
      request the follower anticipates)."""
    import gc
    import shutil
    import tempfile

    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined
    from ipc_proofs_tpu.store.faults import LocalLotusSession
    from ipc_proofs_tpu.store.rpc import LotusClient, RpcBlockstore
    from ipc_proofs_tpu.storex import ChainFollower, SegmentStore, TieredBlockstore
    from ipc_proofs_tpu.utils.metrics import Metrics

    n_pairs = 12 if args.quick else 32
    bs, pairs, _ = build_range_world(
        n_pairs, 32, 8, 0.1,
        signature=SIG, topic1=TOPIC1, actor_id=ACTOR, base_height=70_000_000,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR)

    # every RPC pays this much simulated network latency, so cold-vs-warm
    # measures fetch avoidance against a realistic wire, not dict lookups
    delay_s = 0.0002

    class _SlowSession:
        def __init__(self, inner):
            self._inner = inner

        def post(self, url, data=None, headers=None, timeout=None):
            time.sleep(delay_s)
            return self._inner.post(url, data=data, headers=headers, timeout=timeout)

    def _client(metrics):
        return LotusClient(
            "http://bench-storage",
            session=_SlowSession(LocalLotusSession(bs)),
            metrics=metrics,
        )

    def _run(store, metrics=None):
        t0 = time.perf_counter()
        bundle = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=8, metrics=metrics,
            scan_threads=1, force_pipeline=True,
        )
        return bundle, time.perf_counter() - t0

    workdir = tempfile.mkdtemp(prefix="bench_storage_")
    try:
        _run(bs)  # warm (jit compile, extension load) off the wire entirely

        # --- cold: every block over RPC, no disk tier -----------------------
        t_cold = rpc_cold = None
        bundle_cold = None
        for _ in range(2):
            gc.collect()
            m = Metrics()
            bundle_cold, wall = _run(RpcBlockstore(_client(m)), metrics=m)
            calls = m.snapshot()["counters"].get("rpc.calls", 0)
            if t_cold is None or wall < t_cold:
                t_cold, rpc_cold = wall, calls

        # --- populate the disk tier, then restart into it -------------------
        store_dir = os.path.join(workdir, "store")
        m_pop = Metrics()
        disk = SegmentStore(store_dir, metrics=m_pop)
        _run(TieredBlockstore(RpcBlockstore(_client(m_pop)), disk, metrics=m_pop))
        disk.close()

        # fresh SegmentStore + empty memory cache over the same files: the
        # restart path — the index rebuilds from the segment frames
        t_warm = rpc_warm = None
        hit_ratio = None
        disk_bytes = disk_entries = 0
        bundle_warm = None
        for _ in range(2):
            gc.collect()
            m = Metrics()
            disk = SegmentStore(store_dir, metrics=m)
            tiered = TieredBlockstore(
                RpcBlockstore(_client(m)), disk, metrics=m
            )
            bundle_warm, wall = _run(tiered, metrics=m)
            counters = m.snapshot()["counters"]
            calls = counters.get("rpc.calls", 0)
            if t_warm is None or wall < t_warm:
                t_warm, rpc_warm = wall, calls
                d_hits = counters.get("storex.disk_hits", 0)
                d_misses = counters.get("storex.disk_misses", 0)
                hit_ratio = d_hits / (d_hits + d_misses) if d_hits + d_misses else None
                stats = disk.stats()
                disk_bytes, disk_entries = stats["bytes"], stats["entries"]
            disk.close()
        assert bundle_warm.to_json() == bundle_cold.to_json(), (
            "disk-warm bundle diverged from the cold-RPC run"
        )
        assert rpc_warm == 0, f"disk-warm run issued {rpc_warm} RPC calls"

        # --- follower prefetch into a fresh store ---------------------------
        m = Metrics()
        disk = SegmentStore(os.path.join(workdir, "follow"), metrics=m)
        tiered = TieredBlockstore(RpcBlockstore(_client(m)), disk, metrics=m)
        follower = ChainFollower(_client(m), tiered, metrics=m)
        for pair in pairs:
            follower.prefetch_tipset(pair.parent)
            follower.prefetch_tipset(pair.child)
        counters = m.snapshot()["counters"]
        prefetched = counters.get("follow.blocks_prefetched", 0)
        h0, mi0 = tiered.hits, tiered.misses
        dh0 = counters.get("storex.disk_hits", 0)
        bundle_follow, _ = _run(tiered, metrics=m)
        counters = m.snapshot()["counters"]
        served_mem = tiered.hits - h0
        served_disk = counters.get("storex.disk_hits", 0) - dh0
        total_gets = served_mem + (tiered.misses - mi0)
        prefetch_ratio = (
            (served_mem + served_disk) / total_gets if total_gets else None
        )
        disk.close()
        assert bundle_follow.to_json() == bundle_cold.to_json(), (
            "follower-prefetched bundle diverged from the cold-RPC run"
        )
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    speedup = t_cold / t_warm if t_warm else None
    _log(
        f"bench: storage ({n_pairs} pairs): cold {t_cold * 1000:.0f}ms "
        f"({rpc_cold} RPC calls) vs disk-warm {t_warm * 1000:.0f}ms "
        f"({rpc_warm} RPC calls) = {speedup:.2f}x; disk_hit_ratio "
        f"{hit_ratio:.3f} over {disk_entries} blocks ({disk_bytes}B); "
        f"follower prefetched {prefetched} blocks → prefetch_hit_ratio "
        f"{prefetch_ratio:.3f}"
    )
    return {
        "cold_vs_warm_speedup": round(speedup, 2) if speedup else None,
        "disk_hit_ratio": round(hit_ratio, 4) if hit_ratio is not None else None,
        "prefetch_hit_ratio": (
            round(prefetch_ratio, 4) if prefetch_ratio is not None else None
        ),
        "storage_cold_rpc_calls": rpc_cold,
        "storage_warm_rpc_calls": rpc_warm,
        "storage_prefetched_blocks": prefetched,
        "storage_disk_bytes": disk_bytes,
        "storage_pairs": n_pairs,
    }


def _leg_asyncfetch(args) -> dict:
    """Async fetch plane (host-only, hermetic): what JSON-RPC batching +
    speculative HAMT/AMT prefetch buy on a COLD range request whose blocks
    live behind a wire with real per-round-trip latency:

    - ``cold_rpc_roundtrips_per_proof`` — HTTP round-trips per proof with
      the fetch plane underneath (one batch array POST per dispatcher
      wave; `rpc.calls` ticks once per round-trip, batch or not);
    - ``sync_rpc_roundtrips_per_proof`` — the SAME request through the
      sync walker (`RpcBlockstore` demand path, one `ChainReadObj` per
      block) against the same endpoint;
    - ``cold_speedup_vs_sync_walker`` — wall-clock ratio (best-of-N);
    - ``speculate_waste_pct`` — speculative blocks fetched but never
      consumed, as a % of speculative fetches (mis-speculation is a
      counted cost, never an error).

    Byte identity between the plane bundle and the sync-walker bundle is
    asserted, not assumed — the plane changes when blocks arrive, never
    what any get returns."""
    import gc

    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined
    from ipc_proofs_tpu.store.faults import LocalLotusSession
    from ipc_proofs_tpu.store.fetchplane import FetchPlane, PlaneBlockstore
    from ipc_proofs_tpu.store.rpc import LotusClient, RpcBlockstore
    from ipc_proofs_tpu.utils.metrics import Metrics

    n_pairs = 12 if args.quick else 32
    bs, pairs, _ = build_range_world(
        n_pairs, 32, 8, 0.1,
        signature=SIG, topic1=TOPIC1, actor_id=ACTOR, base_height=80_000_000,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR)

    # every round-trip pays this much simulated wire latency — a batch
    # array pays it ONCE for the whole wave, which is the entire point.
    # 2ms is a conservative same-region RPC latency; below ~0.5ms the
    # dispatcher handoff overhead drowns the signal and the leg measures
    # thread scheduling instead of wire behaviour.
    delay_s = 0.002

    class _SlowSession:
        def __init__(self, inner):
            self._inner = inner

        def post(self, url, data=None, headers=None, timeout=None):
            time.sleep(delay_s)
            return self._inner.post(url, data=data, headers=headers, timeout=timeout)

    def _client(metrics):
        return LotusClient(
            "http://bench-asyncfetch",
            session=_SlowSession(LocalLotusSession(bs)),
            metrics=metrics,
        )

    def _run(store, metrics=None):
        t0 = time.perf_counter()
        bundle = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=8, metrics=metrics,
            scan_threads=2, force_pipeline=True,
        )
        return bundle, time.perf_counter() - t0

    _run(bs)  # warm (jit compile, extension load) off the wire entirely

    # --- sync walker: one ChainReadObj per demand block ---------------------
    t_sync = rpc_sync = None
    bundle_sync = None
    for _ in range(2):
        gc.collect()
        m = Metrics()
        bundle_sync, wall = _run(RpcBlockstore(_client(m)), metrics=m)
        calls = m.snapshot()["counters"].get("rpc.calls", 0)
        if t_sync is None or wall < t_sync:
            t_sync, rpc_sync = wall, calls

    # --- fetch plane: batched want-queue + speculative prefetch -------------
    t_plane = rpc_plane = batch_calls = None
    waste_pct = None
    bundle_plane = None
    for _ in range(2):
        gc.collect()
        m = Metrics()
        # depth=2 chases grandchildren of every decoded HAMT/AMT interior
        # node — the sweet spot for this world: depth=1 leaves most of the
        # serial walk exposed, depth=3 mostly fetches blocks the proofs
        # never touch (waste without any extra latency hidden).
        plane = FetchPlane(
            _client(m), local={}, speculate_depth=2, metrics=m
        )
        bundle_plane, wall = _run(PlaneBlockstore(plane), metrics=m)
        plane.close()
        counters = m.snapshot()["counters"]
        calls = counters.get("rpc.calls", 0)
        if t_plane is None or wall < t_plane:
            t_plane, rpc_plane = wall, calls
            batch_calls = counters.get("rpc.batch_calls", 0)
            waste_pct = plane.stats()["waste_pct"]
    assert bundle_plane.to_json() == bundle_sync.to_json(), (
        "fetch-plane bundle diverged from the sync-walker run"
    )

    n_proofs = len(bundle_sync.event_proofs)
    cold_rt = rpc_plane / n_proofs if n_proofs else None
    sync_rt = rpc_sync / n_proofs if n_proofs else None
    speedup = t_sync / t_plane if t_plane else None
    _log(
        f"bench: asyncfetch ({n_pairs} pairs, {n_proofs} proofs): plane "
        f"{t_plane * 1000:.0f}ms ({rpc_plane} round-trips, {batch_calls} "
        f"batch POSTs) vs sync walker {t_sync * 1000:.0f}ms ({rpc_sync} "
        f"round-trips) = {speedup:.2f}x; "
        f"{cold_rt:.2f} vs {sync_rt:.2f} round-trips/proof; "
        f"speculate_waste {waste_pct:.1f}%"
    )
    return {
        "cold_rpc_roundtrips_per_proof": (
            round(cold_rt, 2) if cold_rt is not None else None
        ),
        "sync_rpc_roundtrips_per_proof": (
            round(sync_rt, 2) if sync_rt is not None else None
        ),
        "cold_speedup_vs_sync_walker": (
            round(speedup, 2) if speedup is not None else None
        ),
        "speculate_waste_pct": (
            round(waste_pct, 2) if waste_pct is not None else None
        ),
        "asyncfetch_batch_calls": batch_calls,
        "asyncfetch_cold_rpc_calls": rpc_plane,
        "asyncfetch_sync_rpc_calls": rpc_sync,
        "asyncfetch_pairs": n_pairs,
    }


def _leg_cluster(args) -> dict:
    """Sharded serve plane (host-only, REAL processes): aggregate generate
    throughput through the consistent-hash router at 1 vs 4 shard child
    processes over one shared demo world + shared ``--store-dir``.

    - ``aggregate_proofs_per_sec`` — event proofs/s through the 4-shard
      router under a closed-loop client load;
    - ``cluster_linearity_4shard`` — rps(4 shards) / (4 × rps(1 shard)).
      Shards are separate processes (own GILs), so on a multi-core host
      this measures real scaling; the ≥ 0.8 gate is enforced by
      ``tools/check_bench_schema.py`` only when host_cores > 2 (a 1-core
      host time-slices the shards — the artifact still records the
      honestly-measured number);
    - ``steal_events`` — work-steal placements observed during the load;
    - scatter-gather byte-identity (4-shard vs 1-shard vs single-process
      chunked driver) is ASSERTED here on every run, not gated.
    """
    import shutil
    import tempfile
    import threading

    from ipc_proofs_tpu.cluster import ClusterRouter, spawn_serve_shard
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked
    from ipc_proofs_tpu.utils.metrics import Metrics

    n_pairs = 8 if args.quick else args.cluster_pairs
    n_requests = 32 if args.quick else args.cluster_requests
    receipts, match_rate = 8, 0.25
    concurrency = 8

    # the same deterministic world the shard children rebuild — the
    # in-process comparator for the byte-identity assertion
    store, pairs, _ = build_range_world(
        n_pairs, receipts_per_pair=receipts, match_rate=match_rate,
        signature=SIG, topic1=TOPIC1,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1)
    direct = generate_event_proofs_for_range_chunked(
        store, list(pairs), spec, chunk_size=8
    )
    direct_json = json.dumps(direct.to_json_obj(), sort_keys=True)
    extra = [
        "--demo-receipts", str(receipts), "--demo-match-rate", str(match_rate),
    ]

    def measure(n_shards: int, store_dir: str) -> "tuple[float, dict, str]":
        shards = [
            spawn_serve_shard(
                f"s{k}", n_pairs, SIG, TOPIC1,
                store_dir=store_dir, extra_args=extra,
            )
            for k in range(n_shards)
        ]
        m = Metrics()
        router = ClusterRouter(
            {sh.name: sh.url for sh in shards}, pairs,
            steal_threshold=2, metrics=m,
        )
        try:
            # warm every shard (extension load, first-request jit paths)
            for k in range(len(pairs)):
                status, _ = router.generate(k % len(pairs))
                assert status == 200
            it = iter(range(n_requests))
            it_lock = threading.Lock()
            proofs = [0]
            failures: "list" = []

            def client():
                while True:
                    with it_lock:
                        i = next(it, None)
                    if i is None:
                        return
                    status, obj = router.generate(i % len(pairs))
                    if status != 200:
                        failures.append((i, obj))
                        return
                    with it_lock:
                        proofs[0] += obj["n_event_proofs"]

            threads = [
                threading.Thread(target=client) for _ in range(concurrency)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            assert not failures, f"cluster leg: {len(failures)} failures"
            # scatter-gather over the WHOLE table: must match the
            # single-process chunked driver byte for byte
            status, obj = router.generate_range(
                list(range(len(pairs))), chunk_size=8
            )
            assert status == 200, obj
            got = json.dumps(obj["bundle"], sort_keys=True)
            snap = m.snapshot()
            return (
                n_requests / wall,
                {"proofs": proofs[0], "wall": wall, "snap": snap},
                got,
            )
        finally:
            router.close()
            for sh in shards:
                sh.stop()

    workdir = tempfile.mkdtemp(prefix="bench_cluster_")
    try:
        rps1, _info1, bundle1 = measure(1, os.path.join(workdir, "st1"))
        rps4, info4, bundle4 = measure(4, os.path.join(workdir, "st4"))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    assert bundle1 == direct_json, (
        "1-shard scatter bundle diverged from the single-process driver"
    )
    assert bundle4 == direct_json, (
        "4-shard scatter bundle diverged from the single-process driver"
    )
    linearity = rps4 / (4 * rps1) if rps1 else None
    agg_proofs_per_sec = info4["proofs"] / info4["wall"]
    steals = info4["snap"]["counters"].get("cluster.steals", 0)
    _log(
        f"bench: cluster ({n_pairs} pairs, {n_requests} reqs, c={concurrency}): "
        f"{rps1:,.1f} req/s @1 shard vs {rps4:,.1f} req/s @4 shards "
        f"(linearity {linearity:.2f}); {agg_proofs_per_sec:,.0f} proofs/s "
        f"aggregate, {steals} steals; 4-shard bundle byte-identical ✓"
    )
    return {
        "aggregate_proofs_per_sec": round(agg_proofs_per_sec, 1),
        "cluster_linearity_4shard": round(linearity, 3) if linearity else None,
        "steal_events": int(steals),
        "cluster_rps_1shard": round(rps1, 1),
        "cluster_rps_4shard": round(rps4, 1),
        "cluster_pairs": n_pairs,
        "cluster_requests": n_requests,
    }


def _leg_backfill(args) -> dict:
    """Bulk backfill (host-only, REAL shard processes): deep-history
    throughput through the router's backfill engine at 1 vs 4 shard
    child processes over one shared demo world.

    Asserted on every run, never gated:
    - the streamed chunk sequence, folded client-side exactly as a
      consumer would, is byte-identical to the single-process chunked
      driver over the same pairs — at BOTH shard counts;
    - every window arrives exactly once through the cursor protocol.

    Measured numbers:
    - ``backfill_epochs_per_sec`` — epochs proven per second through the
      4-shard scatter (1-shard recorded alongside); gated > 0 by
      ``tools/check_bench_schema.py``;
    - ``backfill_ttfc_ms`` vs ``backfill_total_ms`` — time to FIRST
      streamed chunk vs job completion; the schema gate demands
      ttfc < total (incremental delivery is the point of the stream);
    - ``backfill_occupancy_pct`` — proving seconds per shard-lane
      second from the engine's busy/wall accounting (the device-side
      utilization a backfill achieves without an interactive load).
    """
    import shutil
    import tempfile

    from ipc_proofs_tpu.cluster import ClusterRouter, spawn_serve_shard
    from ipc_proofs_tpu.cluster.gather import BundleFold
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked
    from ipc_proofs_tpu.utils.metrics import Metrics

    n_pairs = 24 if args.quick else 64
    receipts, match_rate = 8, 0.25
    window_size = 4 if args.quick else 8
    n_windows = -(-n_pairs // window_size)

    store, pairs, _ = build_range_world(
        n_pairs, receipts_per_pair=receipts, match_rate=match_rate,
        signature=SIG, topic1=TOPIC1,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1)
    direct = generate_event_proofs_for_range_chunked(
        store, list(pairs), spec, chunk_size=window_size
    )
    direct_json = json.dumps(direct.to_json_obj(), sort_keys=True)
    extra = [
        "--demo-receipts", str(receipts), "--demo-match-rate", str(match_rate),
    ]

    def measure(n_shards: int, workdir: str) -> dict:
        shards = [
            spawn_serve_shard(
                f"s{k}", n_pairs, SIG, TOPIC1,
                store_dir=os.path.join(workdir, "store"), extra_args=extra,
            )
            for k in range(n_shards)
        ]
        m = Metrics()
        router = ClusterRouter(
            {sh.name: sh.url for sh in shards}, pairs,
            steal_threshold=2, metrics=m, spec=spec,
            backfill_jobs_dir=os.path.join(workdir, "jobs"),
            backfill_window_size=window_size,
        )
        try:
            # warm every shard (extension load, first-request jit paths)
            for k in range(2 * n_shards):
                status, _obj = router.generate(k % len(pairs))
                assert status == 200
            status, submitted = router.backfill_submit(
                {"pair_start": 0, "pair_end": n_pairs}
            )
            assert status == 200, submitted
            job_id = submitted["job_id"]
            # consume the stream through the real cursor protocol: each
            # poll acks what we already hold and long-polls for more
            cursor, chunks = 0, []
            while True:
                status, resp = router.backfill_chunks(
                    job_id, cursor, wait_s=10.0
                )
                assert status == 200, resp
                for ch in resp["chunks"]:
                    chunks.append(ch)
                    cursor = ch["cursor"]
                if resp["state"] != "running" and not resp["chunks"]:
                    break
            assert resp["state"] == "complete", resp
            assert len(chunks) == n_windows, (
                f"{len(chunks)} chunks streamed for {n_windows} windows"
            )
            # fold the stream exactly as a consumer would: must equal the
            # single-process chunked driver byte for byte
            fold = BundleFold(pairs, list(range(n_pairs)))
            for ch in chunks:
                fold.fold(UnifiedProofBundle.from_json_obj(ch["bundle"]))
            got = json.dumps(fold.seal().to_json_obj(), sort_keys=True)
            assert got == direct_json, (
                f"{n_shards}-shard backfill stream diverged from the "
                "single-process driver"
            )
            status, st = router.backfill_status(job_id)
            assert status == 200, st
            return st
        finally:
            router.close()
            for sh in shards:
                sh.stop()

    workdir = tempfile.mkdtemp(prefix="bench_backfill_")
    try:
        st1 = measure(1, os.path.join(workdir, "b1"))
        st4 = measure(4, os.path.join(workdir, "b4"))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    epochs1 = n_pairs / st1["wall_s"]
    epochs4 = n_pairs / st4["wall_s"]
    ttfc_ms = (st4["first_chunk_s"] or 0.0) * 1000.0
    total_ms = st4["wall_s"] * 1000.0
    occupancy = 100.0 * st4["busy_s"] / (4 * st4["wall_s"])
    _log(
        f"bench: backfill ({n_pairs} epochs, {n_windows} windows of "
        f"{window_size}): {epochs1:,.1f} epochs/s @1 shard vs "
        f"{epochs4:,.1f} epochs/s @4 shards; first chunk {ttfc_ms:,.0f}ms "
        f"vs total {total_ms:,.0f}ms; lane occupancy {occupancy:.0f}%; "
        "streamed fold byte-identical at both shard counts ✓"
    )
    return {
        "backfill_epochs_per_sec": round(epochs4, 2),
        "backfill_epochs_per_sec_1shard": round(epochs1, 2),
        "backfill_ttfc_ms": round(ttfc_ms, 1),
        "backfill_total_ms": round(total_ms, 1),
        "backfill_occupancy_pct": round(occupancy, 1),
        "backfill_windows": n_windows,
        "backfill_epochs": n_pairs,
        "backfill_shards": 4,
    }


def _leg_fleetobs(args) -> dict:
    """Fleet observability overhead (host-only, REAL processes): the same
    closed-loop generate load through a 2-shard router with the fleet
    observability plane OFF vs ON (federated metrics scraping, SLO
    watchdog, per-tenant accounting, head-sampled tracing with in-band
    span shipping at production rate 0.1).

    - ``fleetobs_overhead_pct`` — throughput cost of the plane; gated
      ≤ 3% by ``tools/check_bench_schema.py`` on current artifacts from
      hosts with spare cores (on ≤2-core hosts the scrape/watchdog
      threads time-slice the request loop, so the ratio is skipped);
    - correctness is ASSERTED on every run, never sampled: after the
      measured load, a fully-sampled scatter must graft every shard's
      shipped span subtree into ONE rooted tree in the router's
      collector (``fleetobs_stitched_spans`` of them), no orphans.

    Best-of-3 walls per mode: the closed loop over a small demo world is
    short, and the overhead ratio needs both numerators at their noise
    floor, not one lucky and one unlucky pass."""
    import threading

    from ipc_proofs_tpu.cluster import ClusterRouter, spawn_serve_shard
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.obs import disable_tracing, enable_tracing
    from ipc_proofs_tpu.obs.slo import SloWatchdog, default_targets
    from ipc_proofs_tpu.utils.metrics import Metrics

    n_pairs = 8 if args.quick else args.cluster_pairs
    n_requests = 32 if args.quick else args.cluster_requests
    receipts, match_rate = 8, 0.25
    concurrency, n_shards, reps = 8, 2, 3

    _store, pairs, _ = build_range_world(
        n_pairs, receipts_per_pair=receipts, match_rate=match_rate,
        signature=SIG, topic1=TOPIC1,
    )
    base_extra = [
        "--demo-receipts", str(receipts), "--demo-match-rate", str(match_rate),
    ]

    def closed_loop(router, observed: bool) -> float:
        it = iter(range(n_requests))
        it_lock = threading.Lock()
        failures: "list" = []

        def client():
            while True:
                with it_lock:
                    i = next(it, None)
                if i is None:
                    return
                status, obj = router.generate(
                    i % len(pairs),
                    tenant=f"team-{i % 3}" if observed else None,
                )
                if status != 200:
                    failures.append((i, obj))
                    return

        threads = [threading.Thread(target=client) for _ in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        assert not failures, f"fleetobs leg: {len(failures)} failures"
        return n_requests / wall

    def measure(observed: bool) -> "tuple[float, int, int]":
        extra = list(base_extra)
        if observed:
            extra += [
                "--trace-out", os.devnull, "--trace-sample", "0.1",
                "--slo", "on",
            ]
        shards = [
            spawn_serve_shard(f"s{k}", n_pairs, SIG, TOPIC1, extra_args=extra)
            for k in range(n_shards)
        ]
        m = Metrics()
        collector = slo = None
        if observed:
            collector = enable_tracing(metrics=m, sample=0.1)
            slo = SloWatchdog(m, default_targets(), interval_s=0.5)
        router = ClusterRouter(
            {sh.name: sh.url for sh in shards}, pairs, metrics=m,
            scrape_interval_s=0.25, scrape_timeout_s=5.0, slo=slo,
        )
        try:
            if observed:
                router.federation.start()
                slo.start()
            for k in range(len(pairs)):  # warm every shard
                status, _obj = router.generate(k % len(pairs))
                assert status == 200
            rps = max(closed_loop(router, observed) for _ in range(reps))
            grafted = scrapes = 0
            if observed:
                # outside the timed window: the stitching law, asserted
                collector = enable_tracing(metrics=m, sample=1.0)
                status, obj = router.generate_range(
                    list(range(len(pairs))), chunk_size=8
                )
                assert status == 200, obj
                tid = obj["trace_id"]
                spans = [
                    s for s in collector.snapshot() if s.trace_id == tid
                ]
                ids = {s.span_id for s in spans}
                roots = [
                    s for s in spans
                    if not s.parent_id or s.parent_id not in ids
                ]
                assert len(roots) == 1, (
                    "fleetobs leg: sampled scatter did not stitch into one "
                    f"rooted tree ({len(roots)} roots)"
                )
                grafted = sum(1 for s in spans if ":" in s.span_id)
                assert grafted > 0, "fleetobs leg: no shard subtrees grafted"
                scrapes = int(
                    m.snapshot()["counters"].get("fleet.scrapes", 0)
                )
            return rps, grafted, scrapes
        finally:
            router.close()
            if observed:
                disable_tracing()
            for sh in shards:
                sh.stop()

    rps_plain, _, _ = measure(False)
    rps_observed, grafted, scrapes = measure(True)
    overhead = (
        (rps_plain - rps_observed) / rps_plain * 100.0 if rps_plain else None
    )
    _log(
        f"bench: fleetobs ({n_pairs} pairs, {n_requests} reqs, "
        f"c={concurrency}): {rps_plain:,.1f} req/s plain vs "
        f"{rps_observed:,.1f} req/s observed ({overhead:+.2f}% overhead); "
        f"{grafted} spans grafted into one rooted tree ✓, {scrapes} scrapes"
    )
    return {
        "fleetobs_overhead_pct": round(overhead, 2) if overhead is not None else None,
        "fleetobs_rps_plain": round(rps_plain, 1),
        "fleetobs_rps_observed": round(rps_observed, 1),
        "fleetobs_stitched_spans": int(grafted),
        "fleetobs_scrapes": int(scrapes),
        "fleetobs_pairs": n_pairs,
        "fleetobs_requests": n_requests,
    }


def _leg_onchip(args) -> dict:
    """The on-chip half, sharded (PR 12): mesh-pjit event matching across
    every local device + device-batched multihash verification.

    Correctness is ASSERTED on every run, never sampled:
    - the mesh-sharded fingerprint match must be bit-identical to the
      single-device path over the same arrays;
    - `verify_blocks_batch` verdicts must equal the scalar
      `verify_block_bytes` loop — including deliberately corrupted blocks,
      every one of which must be caught;
    - cold-path integrity checking must issue ≤ 1 device dispatch per
      size-class chunk (the whole point of batching the verify plane).

    Measured numbers:
    - ``device_linearity_Nchip`` — rate(N devices) / (N × rate(1 device))
      for the match kernel; gated ≥ 0.8 by check_bench_schema only on
      multi-device hosts (a 1-device host still records the number — it
      honestly shows the pjit-path overhead against the plain-jit path);
    - ``batch_verify_speedup`` — scalar hashlib loop wall / batched device
      plane wall over the same blocks (recorded honestly: on a CPU-only
      host the XLA u32-lane emulation loses to hashlib and this is < 1);
    - ``verify_tuned_speedup`` — scalar wall / CHOSEN-lane wall after the
      per-host crossover autotune (`ops.verify_jax.autotune_crossover`).
      Asserted ≥ 0.8 every run: whatever lane the tuner picks must never
      be slower than scalar beyond noise — on CPU-only hosts that means
      ``verify_autotune_scalar_only`` is true and the ratio sits at ~1.
    """
    jax_platform = _setup_platform(args)
    import jax
    import numpy as np

    from ipc_proofs_tpu.backend.tpu import TpuBackend
    from ipc_proofs_tpu.core.cid import BLAKE2B_256, CID, DAG_CBOR
    from ipc_proofs_tpu.core.hashes import blake2b_256
    from ipc_proofs_tpu.ops.verify_jax import verify_blocks_batch
    from ipc_proofs_tpu.parallel.mesh import make_mesh
    from ipc_proofs_tpu.proofs.scan_native import topic_fingerprint
    from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature
    from ipc_proofs_tpu.store.rpc import verify_block_bytes
    from ipc_proofs_tpu.utils.metrics import Metrics

    # force the device path for the single-device comparator (the host
    # crossover would otherwise answer from numpy and time the wrong thing)
    os.environ["IPC_TPU_MATCH_MIN_EVENTS"] = "0"
    os.environ["IPC_VERIFY_MIN_BYTES"] = "0"

    topic0 = hash_event_signature(SIG)
    topic1 = ascii_to_bytes32(TOPIC1)
    fp_target = topic_fingerprint(topic0, topic1)
    n_dev = len(jax.devices())

    n_events = 1 << (16 if args.quick else 20)
    rng = np.random.default_rng(7)
    fp = rng.integers(0, 1 << 63, size=n_events, dtype=np.uint64)
    n_topics = rng.integers(2, 4, size=n_events).astype(np.int32)
    emitters = rng.integers(0, 50, size=n_events).astype(np.int64)
    valid = rng.random(n_events) < 0.95
    hit = rng.random(n_events) < args.match_rate
    fp[hit] = np.uint64(fp_target)  # plant real matches

    b1 = TpuBackend()
    bN = TpuBackend(mesh=make_mesh(n_dev))

    def match(backend):
        return np.asarray(
            backend.event_match_mask_fp(
                fp, n_topics, emitters, valid, topic0, topic1, None
            )
        )[:n_events]

    mask1 = match(b1)  # also warms each path's jit cache
    maskN = match(bN)
    assert np.array_equal(mask1, maskN), (
        "mesh-sharded match diverged from the single-device path"
    )
    assert mask1[valid & hit].all(), "planted matches were missed"

    def match_rate_of(backend) -> float:
        k = 3 if args.quick else 10
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _i in range(k):
                match(backend)
            best = min(best, time.perf_counter() - t0)
        return n_events * k / best

    rate_1 = match_rate_of(b1)
    rate_n = match_rate_of(bN)
    linearity = rate_n / (n_dev * rate_1)

    # --- batched multihash verification -------------------------------------
    n_blocks = 256 if args.quick else 1024
    block_bytes = 1024  # uniform size → one size class → minimal chunking
    payload = rng.integers(0, 256, size=(n_blocks, block_bytes), dtype=np.uint8)
    blocks = [payload[i].tobytes() for i in range(n_blocks)]
    cids = [CID.hash_of(b, codec=DAG_CBOR, mh_code=BLAKE2B_256) for b in blocks]
    corrupt = set(range(0, n_blocks, 37))
    for i in corrupt:  # flip one byte — every corruption must be caught
        blocks[i] = bytes([blocks[i][0] ^ 0x01]) + blocks[i][1:]

    m = Metrics()
    verify_blocks_batch(cids, blocks)  # warm (compile) outside the timing
    d0 = m.counter_value("verify.device_calls")
    got = verify_blocks_batch(cids, blocks, metrics=m)
    device_calls = m.counter_value("verify.device_calls") - d0
    n_chunks = -(-n_blocks // 512)  # _CHUNK_MAX_MSGS
    assert device_calls <= n_chunks, (
        f"cold-path verify used {device_calls} device calls for "
        f"{n_chunks} chunk(s)"
    )
    want = [verify_block_bytes(c, b) for c, b in zip(cids, blocks)]
    assert got == want, "batch verify verdicts diverged from the scalar path"
    assert all(not got[i] for i in corrupt), "a corrupted block slipped through"
    assert all(got[i] for i in range(n_blocks) if i not in corrupt)

    def best_of(fn) -> float:
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t_batch = best_of(lambda: verify_blocks_batch(cids, blocks))
    t_scalar = best_of(
        lambda: [verify_block_bytes(c, b) for c, b in zip(cids, blocks)]
    )
    speedup = t_scalar / t_batch
    assert blake2b_256(blocks[1]) == cids[1].digest  # sanity on the fixture

    # --- autotuned crossover: the lane the tuner PICKS must never lose ------
    # `batch_verify_speedup` above forces the device lane and records the
    # ratio honestly (< 1 on CPU-only hosts). The autotuner exists so
    # production never runs that losing lane: measure the per-host
    # crossover, persist it, and verify the CHOSEN lane is at least as
    # fast as scalar (beyond timing noise) on the same blocks.
    import shutil as _shutil
    import tempfile as _tempfile

    from ipc_proofs_tpu.ops import verify_jax as _vj

    tune_dir = _tempfile.mkdtemp(prefix="bench_autotune_")
    try:
        # drop the force-device override from the section above so the
        # tuned crossover (not env) governs lane choice
        os.environ.pop("IPC_VERIFY_MIN_BYTES", None)
        record = _vj.autotune_crossover(tune_dir, quick=args.quick, force=True)
        t_tuned = best_of(lambda: verify_blocks_batch(cids, blocks))
    finally:
        _shutil.rmtree(tune_dir, ignore_errors=True)
    tuned_speedup = t_scalar / t_tuned
    scalar_only = bool(record["scalar_only"])
    assert tuned_speedup >= 0.8, (
        f"autotuned verify lane ran {1 / tuned_speedup:.2f}× slower than "
        f"scalar (record: {record}) — the tuner must never pick a losing "
        "lane beyond noise"
    )

    _log(
        f"bench: onchip autotune: crossover "
        f"{'scalar-only' if scalar_only else record['min_bytes']}, chosen "
        f"lane {t_tuned * 1e3:.1f} ms vs scalar {t_scalar * 1e3:.1f} ms "
        f"(speedup {tuned_speedup:.2f}) over {len(record['samples'])} "
        "measured sizes"
    )
    _log(
        f"bench: onchip ({n_dev} device(s)): match {rate_1:,.0f} ev/s @1 vs "
        f"{rate_n:,.0f} ev/s @{n_dev} (linearity {linearity:.2f}); "
        f"verify {n_blocks}×{block_bytes}B in {device_calls} device call(s), "
        f"batch {t_batch*1e3:.1f} ms vs scalar {t_scalar*1e3:.1f} ms "
        f"(speedup {speedup:.2f}); mesh + batch verdicts bit-identical ✓"
    )
    return {
        "device_linearity_Nchip": round(linearity, 3),
        "batch_verify_speedup": round(speedup, 3),
        "onchip_devices": n_dev,
        "onchip_match_events": n_events,
        "onchip_verify_blocks": n_blocks,
        "onchip_device_calls": int(device_calls),
        "verify_tuned_speedup": round(tuned_speedup, 3),
        "verify_autotune_scalar_only": scalar_only,
        "verify_autotuned_min_bytes": int(record["min_bytes"]),
        "_platform": jax_platform,
    }


def _leg_standing(args) -> dict:
    """Standing queries (host-only, hermetic): push fan-out throughput and
    delivery lag at 1k and 10k subscriptions over one shared world.

    Subscriptions alternate between TWO distinct filters, so the
    amortization invariant is load-bearing: proofs generate once per
    distinct (pair, filter) and fan out to every subscriber —
    ``standing_generations_per_tipset`` can never exceed
    ``standing_distinct_filters`` regardless of subscriber count (gated
    host-shape-independently by ``tools/check_bench_schema.py``, and
    ASSERTED here on every run).

    - ``standing_proofs_pushed_per_sec_{1k,10k}`` — acked webhook pushes
      per second of matching+fan-out wall time (instant opener: this
      measures the streaming plane, not a sink's network);
    - ``standing_delivery_lag_{p50,p99}_ms`` — per-delivery lag from the
      tipset's match cycle starting to its webhook landing, at 10k subs.
    """
    import random
    import shutil
    import tempfile
    import threading

    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.subs import StandingQueries
    from ipc_proofs_tpu.utils.metrics import Metrics

    n_pairs = 3 if args.quick else 5
    receipts, match_rate = 8, 0.5
    store, pairs, _ = build_range_world(
        n_pairs, receipts_per_pair=receipts, match_rate=match_rate,
        signature=SIG, topic1=TOPIC1, actor_id=ACTOR,
    )
    filters = (
        {"signature": SIG, "topic1": TOPIC1},
        {"signature": SIG, "topic1": TOPIC1, "actor_id": ACTOR},
    )

    def measure(n_subs: int) -> dict:
        root = tempfile.mkdtemp(prefix="bench_standing_")
        m = Metrics()
        arrivals: "list[tuple[float, int]]" = []
        arrivals_lock = threading.Lock()

        def opener(url: str, body: bytes, timeout_s: float) -> int:
            tipset = json.loads(body)["tipset"]
            with arrivals_lock:
                arrivals.append((time.perf_counter(), tipset))
            return 200

        sq = StandingQueries(
            root, store=store, metrics=m, fsync=False,
            log_cap_bytes=1 << 30, push_max_inflight=8,
            opener=opener, sleep=lambda s: None, rng=random.Random(0),
        )
        try:
            for i in range(n_subs):
                sq.subscribe({
                    "filter": filters[i % len(filters)],
                    "target": {"mode": "webhook",
                               "url": f"http://sink.invalid/{i}"},
                })
            feed_t: "dict[int, float]" = {}
            t0 = time.perf_counter()
            for pair in pairs:
                feed_t[pair.child.height] = time.perf_counter()
                sq.matcher.match_pair(pair)
            sq.push.drain()  # wait for every webhook to land
            wall = time.perf_counter() - t0
            snap = m.snapshot()["counters"]
            lags_ms = sorted(
                (t - feed_t[ts]) * 1e3 for t, ts in arrivals
            )
            gens = snap.get("subs.generations", 0)
            tipsets = snap.get("subs.tipsets_matched", 0)
            gens_per_tipset = gens / tipsets if tipsets else None
            assert gens_per_tipset is not None and (
                gens_per_tipset <= len(filters)
            ), (
                f"standing leg: {gens_per_tipset} generations/tipset with "
                f"{len(filters)} distinct filters — fan-out did not amortize"
            )
            return {
                "pushed_per_sec": snap.get("subs.pushes", 0) / wall,
                "lags_ms": lags_ms,
                "gens_per_tipset": gens_per_tipset,
                "pushes": snap.get("subs.pushes", 0),
                "failures": snap.get("subs.push_failures", 0),
            }
        finally:
            sq.drain()
            shutil.rmtree(root, ignore_errors=True)

    r1k = measure(1_000)
    r10k = measure(10_000)
    assert not r1k["failures"] and not r10k["failures"], (
        "standing leg: instant-opener pushes must never exhaust retries"
    )

    def _pct(sorted_vals: "list[float]", q: float) -> "float | None":
        if not sorted_vals:
            return None
        return sorted_vals[int(q * (len(sorted_vals) - 1))]

    lag_p50 = _pct(r10k["lags_ms"], 0.50)
    lag_p99 = _pct(r10k["lags_ms"], 0.99)
    _log(
        f"bench: standing ({n_pairs} tipsets, {len(filters)} filters): "
        f"{r1k['pushed_per_sec']:,.0f} proofs pushed/s @1k subs, "
        f"{r10k['pushed_per_sec']:,.0f}/s @10k "
        f"(lag p50 {lag_p50:.1f} ms, p99 {lag_p99:.1f} ms; "
        f"{r10k['gens_per_tipset']:.1f} generations/tipset ≤ "
        f"{len(filters)} filters ✓)"
    )
    return {
        "standing_proofs_pushed_per_sec_1k": round(r1k["pushed_per_sec"], 1),
        "standing_proofs_pushed_per_sec_10k": round(r10k["pushed_per_sec"], 1),
        "standing_delivery_lag_p50_ms": (
            round(lag_p50, 3) if lag_p50 is not None else None
        ),
        "standing_delivery_lag_p99_ms": (
            round(lag_p99, 3) if lag_p99 is not None else None
        ),
        "standing_subscriptions": 10_000,
        "standing_tipsets": n_pairs,
        "standing_distinct_filters": len(filters),
        "standing_generations_per_tipset": round(r10k["gens_per_tipset"], 3),
    }


def _leg_zerocopy(args) -> dict:
    """Zero-copy streaming wire + per-tenant QoS (host-only, hermetic).

    Phase 1 — streaming: a disk-tier-warm service answers ``/v1/generate``
    over the chunked binary stream wire. Block payloads must leave as
    mmap-backed `memoryview` slices of segment frames, so the tentpole
    meter ``warm_block_bytes_copied_per_resp`` (copied block-payload bytes
    per streamed response) must be EXACTLY 0 on every host — gated
    host-shape-independently by ``tools/check_bench_schema.py``. Also
    reports ``stream_ttfb_ms`` (p50 time-to-first-byte: request written →
    first response byte readable — the chunk-as-produced win the buffered
    path structurally cannot have).

    Phase 2 — QoS fairness: one heavy tenant saturates the generate
    batcher from ``qos_heavy_concurrency`` closed-loop threads while a
    light tenant sends occasional single requests. The batcher's
    deficit-round-robin tenant queues must bound the light tenant's
    ``qos_light_tenant_p99_ms`` near one batch's service time instead of
    the heavy backlog's drain time (``qos_heavy_backlog_drain_ms``);
    the schema gate checks the ratio and skips (with a printed reason)
    on hosts with ≤ 2 cores, where there is no parallelism for fairness
    to arbitrate.
    """
    import os as _os
    import shutil
    import tempfile
    import threading

    from http.client import HTTPConnection

    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.serve import ProofService, ServiceConfig
    from ipc_proofs_tpu.serve.httpd import ProofHTTPServer
    from ipc_proofs_tpu.witness.stream import decode_bundle_stream

    n_pairs = 2 if args.quick else 4
    receipts = 8 if args.quick else 16
    store, pairs, _ = build_range_world(
        n_pairs, receipts_per_pair=receipts, events_per_receipt=2,
        match_rate=0.5, signature=SIG, topic1=TOPIC1, actor_id=ACTOR,
    )
    spec = EventProofSpec(
        event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR
    )
    root = tempfile.mkdtemp(prefix="bench-zerocopy-")
    try:
        service = ProofService(
            store=store, spec=spec,
            config=ServiceConfig(
                max_batch=8, max_wait_ms=2.0, workers=2, store_dir=root,
            ),
        )
        httpd = ProofHTTPServer(service, pairs=pairs).start()

        def post(obj):
            conn = HTTPConnection("127.0.0.1", httpd.port, timeout=120)
            t0 = time.perf_counter()
            conn.request(
                "POST", "/v1/generate", json.dumps(obj),
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            first = resp.read(1)
            ttfb_ms = (time.perf_counter() - t0) * 1e3
            data = first + resp.read()
            conn.close()
            return resp.status, data, ttfb_ms

        # warm pass: the buffered responses spill every block into the
        # disk tier's segment files — the frames the stream then slices
        for i in range(n_pairs):
            st, data, _ = post({"pair_index": i})
            assert st == 200, data[:200]

        reps = 16 if args.quick else 48
        c0 = service.metrics_snapshot()["counters"]
        ttfbs = []
        for r in range(reps):
            st, data, ttfb_ms = post({"pair_index": r % n_pairs, "stream": True})
            assert st == 200, data[:200]
            decode_bundle_stream(data)  # reassembly must verify, every time
            ttfbs.append(ttfb_ms)
        c1 = service.metrics_snapshot()["counters"]
        responses = c1.get("serve.stream.responses", 0) - c0.get(
            "serve.stream.responses", 0
        )
        copied = c1.get("serve.stream.copied_bytes", 0) - c0.get(
            "serve.stream.copied_bytes", 0
        )
        zero_copy = c1.get("serve.stream.zero_copy_bytes", 0) - c0.get(
            "serve.stream.zero_copy_bytes", 0
        )
        assert responses == reps, (responses, reps)
        ttfbs.sort()
        ttfb_p50 = ttfbs[len(ttfbs) // 2]
        httpd.shutdown(timeout=30)
        service.drain()

        # ---- phase 2: light tenant under a heavy tenant's flood ----------
        service = ProofService(
            store=store, spec=spec,
            config=ServiceConfig(max_batch=4, max_wait_ms=2.0, workers=2),
        )
        heavy_threads = 6
        light_reps = 10 if args.quick else 25
        stop = threading.Event()
        heavy_done = []

        def heavy():
            n = 0
            while not stop.is_set():
                service.generate(pairs[n % n_pairs], tenant="bulk-heavy")
                n += 1
            heavy_done.append(n)

        threads = [
            threading.Thread(target=heavy) for _ in range(heavy_threads)
        ]
        for t in threads:
            t.start()
        time.sleep(0.3)  # let the heavy backlog establish
        light_lat = []
        for i in range(light_reps):
            t0 = time.perf_counter()
            service.generate(pairs[i % n_pairs], tenant="interactive-light")
            light_lat.append((time.perf_counter() - t0) * 1e3)
        t_drain0 = time.perf_counter()
        stop.set()
        for t in threads:
            t.join()
        drain_ms = (time.perf_counter() - t_drain0) * 1e3
        service.drain()
        light_lat.sort()
        light_p50 = light_lat[len(light_lat) // 2]
        light_p99 = light_lat[max(0, int(len(light_lat) * 0.99) - 1)]
        heavy_requests = sum(heavy_done)
        _log(
            f"bench: zerocopy: {responses} streamed responses, "
            f"{copied / max(1, responses):.1f} copied B/resp "
            f"({zero_copy / max(1, responses):,.0f} zero-copy B/resp), "
            f"ttfb p50 {ttfb_p50:.1f}ms; light tenant p50 {light_p50:.1f}ms "
            f"p99 {light_p99:.1f}ms beside {heavy_requests} heavy requests "
            f"from {heavy_threads} threads"
        )
        return {
            "warm_block_bytes_copied_per_resp": round(
                copied / max(1, responses), 2
            ),
            "stream_ttfb_ms": round(ttfb_p50, 2),
            "qos_light_tenant_p99_ms": round(light_p99, 2),
            "qos_light_tenant_p50_ms": round(light_p50, 2),
            "qos_heavy_backlog_drain_ms": round(drain_ms, 2),
            "zerocopy_bytes_per_resp": round(zero_copy / max(1, responses)),
            "zerocopy_responses": responses,
            "qos_heavy_concurrency": heavy_threads,
            "qos_heavy_requests": heavy_requests,
            "zerocopy_host_cpus": _os.cpu_count(),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _leg_hostkill(args) -> dict:
    """Multi-host kill/recovery (host-only, in-process shards with REAL
    replicated disk tiers): a 2-shard replication_factor=2 cluster.

    - ``replica_repair_hit_rate`` — every rolled frame on one shard's
      disk corrupted in place; fraction of the resulting integrity
      evictions absorbed by the replica plane (peer refetch) instead of
      falling through to the Lotus stand-in. Accounting over the shard's
      own ``storex.*`` counters;
    - ``aggregate_proofs_per_sec_2host`` — event proofs/s through the
      2-shard replicated router under a closed-loop client load;
    - ``kill_recovery_ms`` — one shard killed mid-load; ms from the kill
      until a FULL scatter over every pair completes byte-identical to
      the single-process driver (failover re-dispatch on the survivor).
      Byte-identity of every answer is ASSERTED here on every run; the
      numeric gates live in ``tools/check_bench_schema.py`` and skip
      with a printed reason on small hosts.
    """
    import shutil
    import tempfile
    import threading

    from ipc_proofs_tpu.cluster import ClusterRouter, LocalShard
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked
    from ipc_proofs_tpu.serve.service import ServiceConfig
    from ipc_proofs_tpu.utils.metrics import Metrics

    n_pairs = 8 if args.quick else args.cluster_pairs
    n_requests = 32 if args.quick else args.cluster_requests
    receipts, match_rate = 8, 0.25
    concurrency = 4

    store, pairs, _ = build_range_world(
        n_pairs, receipts_per_pair=receipts, match_rate=match_rate,
        signature=SIG, topic1=TOPIC1,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1)
    direct_json = json.dumps(
        generate_event_proofs_for_range_chunked(
            store, list(pairs), spec, chunk_size=8
        ).to_json_obj(),
        sort_keys=True,
    )
    idxs = list(range(len(pairs)))

    workdir = tempfile.mkdtemp(prefix="bench_hostkill_")
    shard_metrics = [Metrics() for _ in range(2)]
    shards = [
        LocalShard(
            f"s{k}", store, pairs, spec,
            config=ServiceConfig(
                max_batch=8, max_wait_ms=5.0, workers=1,
                store_dir=os.path.join(workdir, f"s{k}"),
                store_owner=f"s{k}",
                store_segment_max_bytes=1,  # every spill rolls → replicable
                cache_max_bytes=1,  # force disk reads so corruption is seen
            ),
            metrics=shard_metrics[k],
        ).start()
        for k in range(2)
    ]
    m = Metrics()
    router = ClusterRouter(
        {sh.name: sh.url for sh in shards}, pairs,
        replication_factor=2, metrics=m, scrape_interval_s=60.0,
    )
    try:
        # warm the tier (spill every witness block), then mirror it
        status, obj = router.generate_range(idxs, chunk_size=8)
        assert status == 200, obj
        assert json.dumps(obj["bundle"], sort_keys=True) == direct_json
        summary = router.replicate_now()
        assert not summary["errors"], summary

        # read-repair: corrupt EVERY rolled frame on s0's disk in place
        s0_dir = os.path.join(workdir, "s0")
        flipped = 0
        for name in sorted(os.listdir(s0_dir)):
            if name.endswith(".blk"):
                path = os.path.join(s0_dir, name)
                size = os.path.getsize(path)
                with open(path, "r+b") as fh:
                    fh.seek(size - 1)
                    b = fh.read(1)
                    fh.seek(size - 1)
                    fh.write(bytes([b[0] ^ 0x40]))
                flipped += 1
        status, obj = router.generate_range(idxs, chunk_size=8)
        assert status == 200, obj
        assert json.dumps(obj["bundle"], sort_keys=True) == direct_json, (
            "post-corruption scatter diverged"
        )
        c0 = shard_metrics[0].snapshot()["counters"]
        repairs = c0.get("storex.replica_repairs", 0)
        misses = c0.get("storex.replica_repair_misses", 0)
        hit_rate = repairs / (repairs + misses) if (repairs + misses) else None

        # closed-loop load through the replicated pair → aggregate rate
        def load(n: int, failures: list, proofs: list):
            it = iter(range(n))
            it_lock = threading.Lock()

            def client():
                while True:
                    with it_lock:
                        i = next(it, None)
                    if i is None:
                        return
                    status, obj = router.generate(i % len(pairs))
                    if status != 200:
                        failures.append((i, obj))
                        return
                    with it_lock:
                        proofs[0] += obj["n_event_proofs"]

            threads = [
                threading.Thread(target=client) for _ in range(concurrency)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.perf_counter() - t0

        failures: list = []
        proofs = [0]
        wall = load(n_requests, failures, proofs)
        assert not failures, f"hostkill leg: {len(failures)} load failures"
        agg_2host = proofs[0] / wall

        # kill one host mid-load; time until a full scatter is whole again
        killer = threading.Timer(wall * 0.25, shards[1].kill)
        failures2: list = []
        killer.start()
        load_thread = threading.Thread(
            target=lambda: load(n_requests, failures2, [0])
        )
        load_thread.start()
        killer.join()
        t_kill = time.perf_counter()
        recovery_ms = None
        deadline = t_kill + 60.0
        while time.perf_counter() < deadline:
            status, obj = router.generate_range(idxs, chunk_size=8)
            if status == 200 and json.dumps(
                obj["bundle"], sort_keys=True
            ) == direct_json:
                recovery_ms = (time.perf_counter() - t_kill) * 1000.0
                break
        load_thread.join()
        assert recovery_ms is not None, "no identical scatter within 60s of kill"
        assert not failures2, (
            f"hostkill leg: {len(failures2)} wrong answers after kill"
        )
        failovers = m.snapshot()["counters"].get("cluster.shard_failovers", 0)
    finally:
        router.close()
        for sh in shards:
            try:
                sh.stop(timeout=10)
            except Exception:
                pass
        shutil.rmtree(workdir, ignore_errors=True)

    _log(
        f"bench: hostkill ({n_pairs} pairs, {n_requests} reqs): "
        f"{agg_2host:,.0f} proofs/s @2 replicated shards; {flipped} frames "
        f"corrupted → repair hit rate {hit_rate}; kill→whole in "
        f"{recovery_ms:,.0f} ms ({failovers} failovers); byte-identical ✓"
    )
    return {
        "aggregate_proofs_per_sec_2host": round(agg_2host, 1),
        "replica_repair_hit_rate": (
            round(hit_rate, 4) if hit_rate is not None else None
        ),
        "kill_recovery_ms": round(recovery_ms, 1),
        "hostkill_pairs": n_pairs,
        "hostkill_requests": n_requests,
        "hostkill_failovers": int(failovers),
    }


def _leg_overload(args) -> dict:
    """Overload survival (host-only, hermetic): a closed loop at ~2× the
    measured capacity against an ``--admit-gradient`` HTTP front end.

    Phase 1 measures capacity: C client threads, think-time 0. Phase 2
    doubles the thread count and adds (a) a light named tenant sending
    occasional requests and (b) a doomed stream of tight-deadline
    requests that must be refused/cancelled BEFORE burning a worker.

    The meters the schema gates ride on:

    - ``goodput_ratio_at_2x``: successful-response rate under 2× offered
      load / capacity rate. A serve plane that degrades gracefully sheds
      the excess and keeps doing its capacity's worth of real work
      (gated ≥ 0.8; skipped with a printed reason on ≤ 2-core hosts);
    - ``shed_rate``: fraction of overload-phase requests answered 429
      (tenant bucket or AIMD admission) — honest shedding, not queuing;
    - ``light_tenant_p99_ms_overload``: the named tenant's p99 while the
      anonymous pool floods — grace headroom + shed-other-first;
    - ``cancel_reclaim_pct``: of the doomed tight-deadline requests, the
      percentage whose work was reclaimed (refused at the door or
      dropped at dispatch) instead of generated-then-thrown-away.

    Shed 429 responses make a closed loop spin faster than real clients
    would; overload clients honor the response's Retry-After estimate up
    to 50 ms so the offered load stays ~2× rather than unbounded."""
    import os as _os
    import threading

    from http.client import HTTPConnection

    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.serve import ProofService, ServiceConfig
    from ipc_proofs_tpu.serve.httpd import ProofHTTPServer

    n_pairs = 2 if args.quick else 4
    receipts = 8 if args.quick else 12
    store, pairs, _ = build_range_world(
        n_pairs, receipts_per_pair=receipts, events_per_receipt=2,
        match_rate=0.5, signature=SIG, topic1=TOPIC1, actor_id=ACTOR,
    )
    spec = EventProofSpec(
        event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR
    )
    service = ProofService(
        store=store, spec=spec,
        config=ServiceConfig(
            max_batch=8, max_wait_ms=2.0, workers=2,
            admit_gradient=True, admit_initial=8,
            admit_delay_budget_ms=75.0,
            tenant_weights={"interactive": 4},
        ),
    )
    httpd = ProofHTTPServer(service, pairs=pairs).start()

    def post(obj, headers=None):
        conn = HTTPConnection("127.0.0.1", httpd.port, timeout=120)
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        try:
            conn.request("POST", "/v1/generate", json.dumps(obj), hdrs)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, data
        finally:
            conn.close()

    for i in range(n_pairs):  # warm every pair through the batcher once
        st, data = post({"pair_index": i})
        assert st == 200, data[:200]

    # ---- phase 1: capacity at C threads ------------------------------------
    cap_threads = 4
    cap_requests = 48 if args.quick else 128
    it = iter(range(cap_requests))
    it_lock = threading.Lock()

    def cap_client():
        while True:
            with it_lock:
                i = next(it, None)
            if i is None:
                return
            st, data = post({"pair_index": i % n_pairs})
            assert st == 200, data[:200]

    threads = [threading.Thread(target=cap_client) for _ in range(cap_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    capacity_rps = cap_requests / (time.perf_counter() - t0)

    # ---- phase 2: 2× closed loop + light tenant + doomed deadlines ---------
    c0 = service.metrics_snapshot()["counters"]
    duration_s = 2.0 if args.quick else 4.0
    stop = threading.Event()
    ok_count = [0]
    shed_count = [0]
    other_count = [0]
    count_lock = threading.Lock()

    def heavy_client():
        while not stop.is_set():
            st, data = post({"pair_index": 0})
            with count_lock:
                if st == 200:
                    ok_count[0] += 1
                elif st == 429:
                    shed_count[0] += 1
                else:
                    other_count[0] += 1
            if st == 429:
                try:
                    retry = float(json.loads(data).get("retry_after_s", 0.05))
                except (ValueError, AttributeError):
                    retry = 0.05
                stop.wait(min(retry, 0.05))

    light_lat: "list[float]" = []

    def light_client():
        while not stop.is_set():
            t0 = time.perf_counter()
            st, _ = post(
                {"pair_index": 1 % n_pairs},
                headers={"X-IPC-Tenant": "interactive"},
            )
            if st == 200:
                light_lat.append((time.perf_counter() - t0) * 1e3)
            stop.wait(0.02)

    doomed = [0]

    def doomed_client():
        # alternate below-floor (refused at the door, 5 ms default floor)
        # and mid-expiry budgets (admitted, then dropped at dispatch once
        # the overload queue delay eats the remainder)
        n = 0
        while not stop.is_set():
            ms = 1 if n % 2 == 0 else 15
            post({"pair_index": 0, "deadline_ms": ms})
            doomed[0] += 1
            n += 1
            stop.wait(0.03)

    workers = [
        threading.Thread(target=heavy_client) for _ in range(2 * cap_threads)
    ] + [threading.Thread(target=light_client), threading.Thread(target=doomed_client)]
    t0 = time.perf_counter()
    for t in workers:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in workers:
        t.join()
    elapsed = time.perf_counter() - t0
    snap = service.metrics_snapshot()
    c1 = snap["counters"]
    admit_limit = snap.get("gauges", {}).get("admit.limit")
    httpd.shutdown(timeout=30)
    service.drain()

    goodput_rps = ok_count[0] / elapsed
    goodput_ratio = goodput_rps / capacity_rps if capacity_rps else None
    answered = ok_count[0] + shed_count[0] + other_count[0]
    shed_rate = shed_count[0] / answered if answered else None
    light_lat.sort()
    light_p99 = (
        light_lat[max(0, int(len(light_lat) * 0.99) - 1)] if light_lat else None
    )
    reclaimed = (
        c1.get("serve.deadline_rejects", 0) - c0.get("serve.deadline_rejects", 0)
        + c1.get("serve.cancelled_inflight", 0)
        - c0.get("serve.cancelled_inflight", 0)
    )
    cancel_reclaim_pct = (
        round(100.0 * min(1.0, reclaimed / doomed[0]), 1) if doomed[0] else None
    )
    _log(
        f"bench: overload: capacity {capacity_rps:,.0f} req/s, goodput at 2x "
        f"{goodput_rps:,.0f} req/s (ratio "
        f"{goodput_ratio if goodput_ratio is None else round(goodput_ratio, 2)}), "
        f"shed {shed_count[0]}/{answered}, light p99 "
        f"{light_p99 if light_p99 is None else round(light_p99, 1)}ms, "
        f"{reclaimed}/{doomed[0]} doomed reclaimed"
    )
    return {
        "goodput_ratio_at_2x": (
            round(goodput_ratio, 3) if goodput_ratio is not None else None
        ),
        "shed_rate": round(shed_rate, 3) if shed_rate is not None else None,
        "light_tenant_p99_ms_overload": (
            round(light_p99, 2) if light_p99 is not None else None
        ),
        "cancel_reclaim_pct": cancel_reclaim_pct,
        "overload_capacity_rps": round(capacity_rps, 1),
        "overload_goodput_rps": round(goodput_rps, 1),
        "overload_requests": answered,
        "overload_doomed_requests": doomed[0],
        "overload_admit_limit_final": admit_limit,
        "overload_host_cpus": _os.cpu_count(),
    }


def _leg_registry(args) -> dict:
    """Proof provenance plane (host-only, hermetic): what the audit
    registry costs and what the fleet base directory buys.

    Three meters:

    - ``registry_append_overhead_pct``: one sealed IPR1 frame per served
      bundle, as a percentage of the request it rides on. Measured as a
      ratio of two costs on the SAME host — the direct per-append wall
      cost (a realistic serve record with a CID set, buffered write, no
      fsync) over the mean buffered ``/v1/generate`` request with the
      registry enabled — so the gate (< 1%) is host-shape independent:
      both numerator and denominator scale with the same machine.
    - ``registry_inclusion_proof_ms``: mean wall time to generate AND
      verify an O(log n) inclusion proof against the live root over a
      multi-thousand-record chain — the audit path's cost.
    - ``fleet_delta_hit_rate`` vs ``fleet_delta_baseline_hit_rate``: a
      4-shard scatter appends serve records + base acks to one shared
      registry dir, then every base lookup lands on a RANDOM shard (the
      failover case). The baseline is each shard's private
      `WitnessBaseCache` (hits only when the lookup happens to land on
      the serving shard, ~1/shards); the fleet directory answers from
      ANY shard's records (gated strictly above the baseline).
    """
    import hashlib as _hashlib
    import random as _random
    import tempfile

    from http.client import HTTPConnection

    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.registry import ProvenanceRegistry
    from ipc_proofs_tpu.registry.mmr import verify_inclusion
    from ipc_proofs_tpu.serve import ProofService, ServiceConfig
    from ipc_proofs_tpu.serve.httpd import ProofHTTPServer
    from ipc_proofs_tpu.witness.bases import WitnessBaseCache

    rng = _random.Random(20260807)

    def _digest(tag):
        return _hashlib.sha256(tag.encode()).hexdigest()

    def _cids(tag, k=3):
        return frozenset(
            _hashlib.sha256(f"{tag}-cid-{j}".encode()).digest() for j in range(k)
        )

    # ---- phase 1: append overhead as a fraction of a served request --------
    n_pairs = 2 if args.quick else 4
    receipts = 8 if args.quick else 12
    store, pairs, _ = build_range_world(
        n_pairs, receipts_per_pair=receipts, events_per_receipt=2,
        match_rate=0.5, signature=SIG, topic1=TOPIC1, actor_id=ACTOR,
    )
    spec = EventProofSpec(
        event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR
    )
    serve_requests = 32 if args.quick else 96
    with tempfile.TemporaryDirectory(prefix="bench-registry-") as reg_dir:
        service = ProofService(
            store=store, spec=spec,
            config=ServiceConfig(
                max_batch=8, max_wait_ms=2.0, workers=2,
                registry_dir=reg_dir, registry_owner="bench",
            ),
        )
        httpd = ProofHTTPServer(service, pairs=pairs).start()

        def post(obj):
            conn = HTTPConnection("127.0.0.1", httpd.port, timeout=120)
            try:
                conn.request(
                    "POST", "/v1/generate", json.dumps(obj),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                data = resp.read()
                return resp.status, data
            finally:
                conn.close()

        for i in range(n_pairs):  # warm every pair through the batcher once
            st, data = post({"pair_index": i})
            assert st == 200, data[:200]
        t0 = time.perf_counter()
        for i in range(serve_requests):
            st, data = post({"pair_index": i % n_pairs})
            assert st == 200, data[:200]
        serve_mean_s = (time.perf_counter() - t0) / serve_requests
        head = service.registry.head()
        assert head["size"] >= serve_requests, head  # every response sealed
        httpd.shutdown(timeout=30)
        service.drain()

    # the numerator: the same append the serve path pays, microbenched
    # directly (buffered write + chain link + tree append, no fsync)
    append_n = 512 if args.quick else 2048
    with tempfile.TemporaryDirectory(prefix="bench-registry-") as reg_dir:
        reg = ProvenanceRegistry(reg_dir, owner="bench")
        t0 = time.perf_counter()
        for i in range(append_n):
            reg.append_served(
                _digest(f"append-{i}"), trace=f"trace-{i}", tenant="bench",
                key=f"pair:{i % 8}", verdict="served", cids=_cids(f"append-{i}"),
            )
        append_mean_s = (time.perf_counter() - t0) / append_n
        append_overhead_pct = 100.0 * append_mean_s / serve_mean_s

        # ---- phase 2: inclusion-proof latency over the same chain ----------
        proof_n = 64 if args.quick else 200
        seqs = [rng.randrange(append_n) for _ in range(proof_n)]
        t0 = time.perf_counter()
        for seq in seqs:
            proof = reg.inclusion_proof(seq)
            assert verify_inclusion(
                bytes.fromhex(proof["leaf"]), proof["seq"], proof["size"],
                [bytes.fromhex(h) for h in proof["path"]],
                bytes.fromhex(proof["root"]),
            ), proof["seq"]
        inclusion_ms = 1000.0 * (time.perf_counter() - t0) / proof_n
        reg.close()

    # ---- phase 3: fleet base directory vs per-shard caches -----------------
    shards = 4
    filters = 16 if args.quick else 32
    epochs = 4
    with tempfile.TemporaryDirectory(prefix="bench-registry-") as fleet_dir:
        regs = [
            ProvenanceRegistry(fleet_dir, owner=f"shard-{s}")
            for s in range(shards)
        ]
        caches = [WitnessBaseCache(cap=filters * epochs) for _ in range(shards)]
        last = {}
        for e in range(epochs):
            for f in range(filters):
                s = rng.randrange(shards)
                digest = _digest(f"fleet-f{f}-e{e}")
                cids = _cids(f"fleet-f{f}-e{e}")
                regs[s].append_served(
                    digest, key=f"filter:{f}", verdict="pushed", cids=cids
                )
                regs[s].append_base_ack(
                    "bench", f"filter:{f}", f"sub-{f}", digest, e
                )
                caches[s].register(digest, cids)
                last[f] = digest
        fleet_hits = baseline_hits = 0
        for f in range(filters):
            lk = rng.randrange(shards)  # the shard failover lands on
            if caches[lk].lookup(last[f]) is not None:
                baseline_hits += 1
            d = regs[lk].fleet_acked_base("bench", f"filter:{f}", f"sub-{f}")
            if d == last[f] and regs[lk].lookup_base(d) is not None:
                fleet_hits += 1
        for reg in regs:
            reg.close()
    fleet_rate = fleet_hits / filters
    baseline_rate = baseline_hits / filters

    _log(
        f"bench: registry: append {append_mean_s * 1e6:,.1f}us over "
        f"{serve_mean_s * 1e3:,.1f}ms/request = "
        f"{append_overhead_pct:.3f}% overhead, inclusion proof "
        f"{inclusion_ms:.2f}ms @ {append_n} records, fleet base hit rate "
        f"{fleet_rate:.2f} vs per-shard {baseline_rate:.2f}"
    )
    return {
        "registry_append_overhead_pct": round(append_overhead_pct, 4),
        "registry_append_us": round(append_mean_s * 1e6, 2),
        "registry_inclusion_proof_ms": round(inclusion_ms, 3),
        "fleet_delta_hit_rate": round(fleet_rate, 3),
        "fleet_delta_baseline_hit_rate": round(baseline_rate, 3),
        "registry_chain_records": append_n,
        "registry_serve_requests": serve_requests,
        "registry_shards": shards,
        "registry_lookups": filters,
    }


_LEG_FNS = {
    "e2e": _leg_e2e,
    "kernel": _leg_kernel,
    "cid": _leg_cid,
    "baseline": _leg_baseline,
    "native_baseline": _leg_native_baseline,
    "serve": _leg_serve,
    "witness": _leg_witness,
    "resilience": _leg_resilience,
    "durability": _leg_durability,
    "observability": _leg_observability,
    "storage": _leg_storage,
    "asyncfetch": _leg_asyncfetch,
    "cluster": _leg_cluster,
    "standing": _leg_standing,
    "fleetobs": _leg_fleetobs,
    "onchip": _leg_onchip,
    "backfill": _leg_backfill,
    "zerocopy": _leg_zerocopy,
    "hostkill": _leg_hostkill,
    "overload": _leg_overload,
    "registry": _leg_registry,
}


# --------------------------------------------------------------------------
# shared measurement helpers
# --------------------------------------------------------------------------


def _staged_verify(bundle, backend):
    """Offline verification with per-stage timers; returns (results, stages)."""
    from ipc_proofs_tpu.core.cid import BLAKE2B_256
    from ipc_proofs_tpu.proofs.bundle import EventProofBundle
    from ipc_proofs_tpu.proofs.event_verifier import verify_event_proof
    from ipc_proofs_tpu.proofs.witness import load_witness_store

    stages = {}
    t0 = time.perf_counter()
    batch = [b for b in bundle.blocks if b.cid.mh_code == BLAKE2B_256]
    if batch and not backend.verify_block_cids(
        [b.cid.digest for b in batch], [b.data for b in batch]
    ):
        raise ValueError("witness CID mismatch")
    stages["verify_cids"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    store = load_witness_store(bundle.blocks, verify_cids=False)
    stages["load_witness"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    results = verify_event_proof(
        EventProofBundle(proofs=bundle.event_proofs, blocks=bundle.blocks),
        lambda e, c: True,
        lambda e, c: True,
        store=store,
    )
    stages["verify_replay"] = time.perf_counter() - t0
    return results, stages


def _scalar_baseline(n_pairs_sample: int, receipts: int, events: int) -> float:
    """Reference-architecture e2e rate (proofs/s): single thread, per-event
    Python decode + match (events/generator.rs:217-239 shape), scalar
    verify with per-proof replay, scalar CID recompute. Measured on a small
    subrange; rates are per-pair-linear so the rate transfers.

    Runs under `force_python_decoder` so the baseline is genuinely the
    Python scalar loop — without it the C DAG-CBOR extension accelerates
    the baseline too, and the reported multiple tracks the extension's
    build flags rather than the batch/fusion design. The compiled-language
    comparison lives in ``vs_native_baseline``."""
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.bundle import EventProofBundle
    from ipc_proofs_tpu.proofs.event_verifier import verify_event_proof
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range
    from ipc_proofs_tpu.proofs.witness import load_witness_store

    import gc

    from ipc_proofs_tpu.core.dagcbor import force_python_decoder

    bs, pairs, _ = build_range_world(
        n_pairs_sample, receipts, events, base_height=10_000_000
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR)
    # best-of-2 with GC settled — the same steady-state methodology the
    # headline number uses, so the ratio doesn't swing with one-off GC
    # pauses on small hosts
    best = 0.0
    for _ in range(2):
        gc.collect()
        start = time.perf_counter()
        with force_python_decoder():
            bundle = generate_event_proofs_for_range(bs, pairs, spec, match_backend=None)
            # scalar verify, explicitly: per-block CID recompute on load and
            # the per-proof replay loop (batch=False) — the batch verifier is
            # this framework's own machinery, not the reference architecture's
            store = load_witness_store(bundle.blocks, verify_cids=True)
            results = verify_event_proof(
                EventProofBundle(proofs=bundle.event_proofs, blocks=bundle.blocks),
                lambda e, c: True,
                lambda e, c: True,
                store=store,
                batch=False,
            )
        elapsed = time.perf_counter() - start
        assert all(results) and len(results) == len(bundle.event_proofs)
        if elapsed > 0:
            best = max(best, len(bundle.event_proofs) / elapsed)
    return best


def _native_baseline(n_pairs_sample: int, receipts: int, events: int) -> float:
    """Language-fair baseline (proofs/s): the REFERENCE ARCHITECTURE — one
    (parent, child) pair per invocation, sequential over pairs
    (`src/proofs/generator.rs:43-78` runs specs in a plain loop) — but with
    every hot primitive on the same compiled C paths this framework uses
    (native scanner, native pass-2 walkers, C++ batch hashes, C dag-cbor).
    What it deliberately lacks is the range-level design: cross-pair
    batching, one fused match over the whole range, range-wide witness
    dedup, and phase overlap. ``vs_native_baseline`` therefore isolates the
    architectural win from the Python-vs-compiled language gap that
    ``vs_baseline`` (scalar Python reference loop) folds in."""
    from ipc_proofs_tpu.backend import get_backend
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range
    from ipc_proofs_tpu.proofs.trust import TrustPolicy
    from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle

    bs, pairs, _ = build_range_world(
        n_pairs_sample, receipts, events, base_height=20_000_000
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=TOPIC1, actor_id_filter=ACTOR)
    import gc

    cpu = get_backend("cpu")
    # warm the native extensions (build/load outside the measured region)
    generate_event_proofs_for_range(bs, [pairs[0]], spec, match_backend=cpu)
    best = 0.0
    for _ in range(2):  # best-of-2, GC settled (headline methodology)
        gc.collect()
        start = time.perf_counter()
        n = 0
        for pair in pairs:  # one pair per invocation, like the reference binary
            bundle = generate_event_proofs_for_range(bs, [pair], spec, match_backend=cpu)
            result = verify_proof_bundle(
                bundle, TrustPolicy.accept_all(), verify_witness_cids=True
            )
            assert result.all_valid()
            n += len(bundle.event_proofs)
        elapsed = time.perf_counter() - start
        if elapsed > 0:
            best = max(best, n / elapsed)
    return best


# --------------------------------------------------------------------------
# orchestrator
# --------------------------------------------------------------------------


# every headline key the e2e leg emits — the total-failure fallback nulls
# exactly this schema so consumers can always index the full key set
_E2E_SCHEMA_KEYS = (
    "value", "platform", "devices", "host_cores", "host_cores_affinity",
    "scan_threads", "record_workers", "verify_workers", "effective_threads",
    "native_scan_threads", "pipeline_depth",
    "pipeline_chunk", "events_per_sec_e2e", "proofs", "stages_ms",
    "stages_wall_ms", "stages_overlap", "gen_verify_overlap",
    "overlap_efficiency", "serial_proofs_per_sec", "serial_e2e_reps_s",
    "pipeline_speedup_vs_serial", "e2e_policy", "e2e_reps_s",
)


def worst_case_seconds(quick: bool, mult: float = 1.0) -> float:
    """Upper bound on one orchestrated run's leg-watchdog spend: every leg
    burning its full timeout, plus the e2e CPU retry after a stall. Callers
    wrapping the bench in their own subprocess timeout (run_configs config2)
    should bound ABOVE this so the orchestrator's degraded-but-honest JSON
    always gets to print."""
    idx = 1 if quick else 0
    worst = sum(t[idx] for t in _LEG_TIMEOUTS.values())
    worst += _LEG_TIMEOUTS["e2e"][idx]  # the CPU retry after a stall
    return worst * mult


def _leg_timeout(name: str, args) -> float:
    full, quick = _LEG_TIMEOUTS[name]
    return (quick if args.quick else full) * args.leg_timeout_mult


def _run_leg(name: str, args, platform: str) -> tuple:
    """Run one leg in a watchdogged subprocess; returns (dict|None, status).

    status: 'ok' | 'timeout' | 'error'. Child stderr streams through to
    this process's stderr; stdout's last line is the leg's JSON dict."""
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--leg", name,
        "--platform", platform,
        "--tipsets", str(args.tipsets),
        "--receipts", str(args.receipts),
        "--events", str(args.events),
        "--match-rate", str(args.match_rate),
        "--kernel-iters", str(args.kernel_iters),
        "--baseline-pairs", str(args.baseline_pairs),
        "--probe-timeout", str(args.probe_timeout),
        "--e2e-reps", str(args.e2e_reps),
        "--serve-requests", str(args.serve_requests),
        "--serve-concurrency", str(args.serve_concurrency),
        "--pipeline-depth", str(args.pipeline_depth),
    ]
    if args.scan_threads is not None:
        cmd += ["--scan-threads", str(args.scan_threads)]
    if args.threads is not None:
        cmd += ["--threads", str(args.threads)]
    if args.quick:
        cmd.append("--quick")
    if args.profile and name == "e2e":
        cmd += ["--profile", args.profile]
    timeout = _leg_timeout(name, args)
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            cmd, stdout=subprocess.PIPE, stderr=None, text=True, timeout=timeout
        )
    except subprocess.TimeoutExpired:
        _log(f"bench: leg {name!r} ({platform}) WATCHDOG TIMEOUT after {timeout:.0f}s")
        return None, f"timeout:{platform}"
    elapsed = time.monotonic() - t0
    if proc.returncode != 0:
        _log(f"bench: leg {name!r} ({platform}) exited rc={proc.returncode}")
        return None, f"error:{platform}"
    try:
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        out = json.loads(lines[-1])
    except (IndexError, ValueError) as exc:
        _log(f"bench: leg {name!r} produced unparseable output ({exc})")
        return None, f"error:{platform}"
    # the leg reports what it REALLY ran on ('_platform'); status strings
    # carry that, so a fast chip-init failure that silently fell back to
    # CPU can't masquerade as an on-chip number in the artifact
    actual = out.pop("_platform", platform)
    _log(f"bench: leg {name!r} ({actual}) done in {elapsed:.0f}s")
    return out, f"ok:{actual}"


def _orchestrate(args) -> None:
    """Run every leg under a watchdog; assemble and print the one JSON line."""
    from ipc_proofs_tpu.utils.platform import pick_platform

    platform = pick_platform(args.platform, args.probe_timeout, log=_log)

    legs_status: dict[str, str] = {}
    watchdog_fallback = False
    device_platform = platform  # downgraded to 'cpu' after a device-leg stall

    # --- headline e2e (device platform; retry once on CPU after a stall) ---
    e2e, status = _run_leg("e2e", args, device_platform)
    legs_status["e2e"] = status
    if e2e is None and device_platform != "cpu":
        # only a WATCHDOG TIMEOUT means the tunnel stalled — downgrade the
        # remaining device legs so they don't serially burn their timeouts
        # against a dead tunnel. A fast crash (rc!=0 / bad output) is NOT a
        # stall: keep the chip for the other legs.
        if status.startswith("timeout"):
            device_platform = "cpu"
            watchdog_fallback = True
        e2e, status = _run_leg("e2e", args, "cpu")
        legs_status["e2e"] += f" → {status}"
    if e2e is None:
        # even the CPU rerun failed — emit an honest artifact anyway, with
        # the FULL headline schema nulled (consumers index these keys)
        e2e = {
            "metric": "event_proofs_per_sec_4k_range_e2e",
            "unit": "proofs/s",
            **{k: None for k in _E2E_SCHEMA_KEYS},
        }

    # --- secondary device kernels ------------------------------------------
    kernel, status = _run_leg("kernel", args, device_platform)
    legs_status["kernel"] = status
    if status.startswith("timeout") and device_platform != "cpu":
        device_platform = "cpu"
        watchdog_fallback = True

    cid, status = _run_leg("cid", args, device_platform)
    legs_status["cid"] = status
    if status.startswith("timeout") and device_platform != "cpu":
        device_platform = "cpu"
        watchdog_fallback = True

    onchip, status = _run_leg("onchip", args, device_platform)
    legs_status["onchip"] = status
    if status.startswith("timeout") and device_platform != "cpu":
        device_platform = "cpu"
        watchdog_fallback = True

    # --- host-only baselines (never touch the tunnel) -----------------------
    baseline, status = _run_leg("baseline", args, "cpu")
    legs_status["baseline"] = status
    native, status = _run_leg("native_baseline", args, "cpu")
    legs_status["native_baseline"] = status

    # --- host-only serving + witness measurements ---------------------------
    serve, status = _run_leg("serve", args, "cpu")
    legs_status["serve"] = status
    witness, status = _run_leg("witness", args, "cpu")
    legs_status["witness"] = status
    resilience, status = _run_leg("resilience", args, "cpu")
    legs_status["resilience"] = status
    durability, status = _run_leg("durability", args, "cpu")
    legs_status["durability"] = status
    observability, status = _run_leg("observability", args, "cpu")
    legs_status["observability"] = status
    storage, status = _run_leg("storage", args, "cpu")
    legs_status["storage"] = status
    asyncfetch, status = _run_leg("asyncfetch", args, "cpu")
    legs_status["asyncfetch"] = status
    cluster, status = _run_leg("cluster", args, "cpu")
    legs_status["cluster"] = status
    standing, status = _run_leg("standing", args, "cpu")
    legs_status["standing"] = status
    fleetobs, status = _run_leg("fleetobs", args, "cpu")
    legs_status["fleetobs"] = status
    backfill, status = _run_leg("backfill", args, "cpu")
    legs_status["backfill"] = status
    zerocopy, status = _run_leg("zerocopy", args, "cpu")
    legs_status["zerocopy"] = status
    hostkill, status = _run_leg("hostkill", args, "cpu")
    legs_status["hostkill"] = status
    overload, status = _run_leg("overload", args, "cpu")
    legs_status["overload"] = status
    registry, status = _run_leg("registry", args, "cpu")
    legs_status["registry"] = status

    scalar_rate = (baseline or {}).get("scalar_baseline_proofs_per_sec")
    native_rate = (native or {}).get("native_baseline_proofs_per_sec")
    value = e2e.get("value")

    out = dict(e2e)
    out["vs_baseline"] = (
        round(value / scalar_rate, 2) if value and scalar_rate else None
    )
    out["vs_native_baseline"] = (
        round(value / native_rate, 2) if value and native_rate else None
    )
    out["scalar_baseline_proofs_per_sec"] = scalar_rate
    out["native_baseline_proofs_per_sec"] = native_rate
    out["device_mask_kernel_events_per_sec"] = (
        (kernel or {}).get("device_mask_kernel_events_per_sec")
    )
    out["witness_cid_kernel_per_sec"] = (
        (cid or {}).get("witness_cid_kernel_per_sec")
    )
    out["witness_cid_kernel"] = (cid or {}).get("witness_cid_kernel")
    _SERVE_KEYS = (
        "serve_batched_rps", "serve_sequential_rps",
        "serve_speedup_vs_sequential", "serve_concurrency", "serve_requests",
        "serve_p99_latency_ms", "serve_mean_batch", "serve_rejections",
    )
    for k in _SERVE_KEYS:
        out[k] = (serve or {}).get(k)
    _WITNESS_KEYS = (
        "witness_reduction_pct", "witness_two_pass_bytes",
        "witness_single_pass_bytes", "witness_sample_pairs",
        "witness_bytes_per_proof_k1", "witness_bytes_per_proof_k16",
        "witness_bytes_per_proof_k256", "witness_delta_ratio",
        "witness_compressed_ratio",
    )
    for k in _WITNESS_KEYS:
        out[k] = (witness or {}).get(k)
    _RESILIENCE_KEYS = (
        "resilience_fault_free_proofs_per_sec", "integrity_overhead_pct",
        "proofs_per_sec_at_fault_rate", "resilience_fault_rate",
        "recovery_ms",
    )
    for k in _RESILIENCE_KEYS:
        out[k] = (resilience or {}).get(k)
    _DURABILITY_KEYS = (
        "durability_journal_overhead_pct", "durability_resume_ms",
        "durability_replay_chunks_per_sec", "durability_journal_bytes",
        "durability_chunks",
    )
    for k in _DURABILITY_KEYS:
        out[k] = (durability or {}).get(k)
    _OBSERVABILITY_KEYS = (
        "trace_overhead_pct", "spans_per_proof",
        "observability_spans_recorded", "observability_spans_dropped",
        "observability_pairs",
    )
    for k in _OBSERVABILITY_KEYS:
        out[k] = (observability or {}).get(k)
    _STORAGE_KEYS = (
        "cold_vs_warm_speedup", "disk_hit_ratio", "prefetch_hit_ratio",
        "storage_cold_rpc_calls", "storage_warm_rpc_calls",
        "storage_prefetched_blocks", "storage_disk_bytes", "storage_pairs",
    )
    for k in _STORAGE_KEYS:
        out[k] = (storage or {}).get(k)
    _ASYNCFETCH_KEYS = (
        "cold_rpc_roundtrips_per_proof", "sync_rpc_roundtrips_per_proof",
        "cold_speedup_vs_sync_walker", "speculate_waste_pct",
        "asyncfetch_batch_calls", "asyncfetch_cold_rpc_calls",
        "asyncfetch_sync_rpc_calls", "asyncfetch_pairs",
    )
    for k in _ASYNCFETCH_KEYS:
        out[k] = (asyncfetch or {}).get(k)
    _CLUSTER_KEYS = (
        "cluster_linearity_4shard", "aggregate_proofs_per_sec",
        "steal_events", "cluster_rps_1shard", "cluster_rps_4shard",
        "cluster_pairs", "cluster_requests",
    )
    for k in _CLUSTER_KEYS:
        out[k] = (cluster or {}).get(k)
    _STANDING_KEYS = (
        "standing_proofs_pushed_per_sec_1k",
        "standing_proofs_pushed_per_sec_10k",
        "standing_delivery_lag_p50_ms", "standing_delivery_lag_p99_ms",
        "standing_subscriptions", "standing_tipsets",
        "standing_distinct_filters", "standing_generations_per_tipset",
    )
    for k in _STANDING_KEYS:
        out[k] = (standing or {}).get(k)
    _FLEETOBS_KEYS = (
        "fleetobs_overhead_pct", "fleetobs_rps_plain",
        "fleetobs_rps_observed", "fleetobs_stitched_spans",
        "fleetobs_scrapes", "fleetobs_pairs", "fleetobs_requests",
    )
    for k in _FLEETOBS_KEYS:
        out[k] = (fleetobs or {}).get(k)
    _ONCHIP_KEYS = (
        "device_linearity_Nchip", "batch_verify_speedup", "onchip_devices",
        "onchip_match_events", "onchip_verify_blocks", "onchip_device_calls",
        "verify_tuned_speedup", "verify_autotune_scalar_only",
        "verify_autotuned_min_bytes",
    )
    for k in _ONCHIP_KEYS:
        out[k] = (onchip or {}).get(k)
    _BACKFILL_KEYS = (
        "backfill_epochs_per_sec", "backfill_epochs_per_sec_1shard",
        "backfill_ttfc_ms", "backfill_total_ms", "backfill_occupancy_pct",
        "backfill_windows", "backfill_epochs", "backfill_shards",
    )
    for k in _BACKFILL_KEYS:
        out[k] = (backfill or {}).get(k)
    _ZEROCOPY_KEYS = (
        "warm_block_bytes_copied_per_resp", "stream_ttfb_ms",
        "qos_light_tenant_p99_ms", "qos_light_tenant_p50_ms",
        "qos_heavy_backlog_drain_ms", "zerocopy_bytes_per_resp",
        "zerocopy_responses", "qos_heavy_concurrency", "qos_heavy_requests",
        "zerocopy_host_cpus",
    )
    for k in _ZEROCOPY_KEYS:
        out[k] = (zerocopy or {}).get(k)
    _HOSTKILL_KEYS = (
        "aggregate_proofs_per_sec_2host", "replica_repair_hit_rate",
        "kill_recovery_ms", "hostkill_pairs", "hostkill_requests",
        "hostkill_failovers",
    )
    for k in _HOSTKILL_KEYS:
        out[k] = (hostkill or {}).get(k)
    _OVERLOAD_KEYS = (
        "goodput_ratio_at_2x", "shed_rate", "light_tenant_p99_ms_overload",
        "cancel_reclaim_pct", "overload_capacity_rps", "overload_goodput_rps",
        "overload_requests", "overload_doomed_requests",
        "overload_admit_limit_final", "overload_host_cpus",
    )
    for k in _OVERLOAD_KEYS:
        out[k] = (overload or {}).get(k)
    _REGISTRY_KEYS = (
        "registry_append_overhead_pct", "registry_append_us",
        "registry_inclusion_proof_ms", "fleet_delta_hit_rate",
        "fleet_delta_baseline_hit_rate", "registry_chain_records",
        "registry_serve_requests", "registry_shards", "registry_lookups",
    )
    for k in _REGISTRY_KEYS:
        out[k] = (registry or {}).get(k)
    out["legs"] = legs_status
    out["watchdog_fallback"] = watchdog_fallback
    print(json.dumps(out))


def main() -> None:
    args = _parse_args()
    if args.leg:
        print(json.dumps(_LEG_FNS[args.leg](args)))
        return
    _orchestrate(args)


if __name__ == "__main__":
    main()
