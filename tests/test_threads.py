"""Unit tests for the unified thread budget (utils/threads.py).

The budget collapses --threads / IPC_THREADS / --scan-threads /
IPC_SCAN_THREADS into ONE total and partitions it so that
``scan_workers × native_scan_threads`` never exceeds the total — the
oversubscription fix. Precedence: flag beats env, unified beats legacy.
"""

import pytest

from ipc_proofs_tpu.utils.threads import ThreadBudget, resolve_thread_budget


class TestPrecedence:
    def test_threads_flag_wins_over_everything(self):
        b = resolve_thread_budget(
            threads=8, scan_threads=None,
            env={"IPC_THREADS": "2", "IPC_SCAN_THREADS": "16"}, log=False,
        )
        assert b.total == 8 and b.source == "--threads"

    def test_ipc_threads_env_beats_legacy_knobs(self):
        b = resolve_thread_budget(
            env={"IPC_THREADS": "6", "IPC_SCAN_THREADS": "16"}, log=False
        )
        assert b.total == 6 and b.source == "IPC_THREADS"

    def test_legacy_flag_beats_legacy_env(self):
        # the env×flag oversubscription bug: before the budget, BOTH applied
        # (flag → stage workers, env → native fan-out, multiplied). Now the
        # flag wins and the env is only the fallback.
        b = resolve_thread_budget(
            scan_threads=4, env={"IPC_SCAN_THREADS": "16"}, log=False
        )
        assert b.total == 4 and b.source == "--scan-threads"
        assert b.scan_workers == 4  # historical meaning: pins the scan stage

    def test_legacy_env_fallback(self):
        b = resolve_thread_budget(env={"IPC_SCAN_THREADS": "5"}, log=False)
        assert b.total == 5 and b.source == "IPC_SCAN_THREADS"
        assert b.scan_workers == 5

    def test_affinity_default(self):
        b = resolve_thread_budget(env={}, log=False)
        assert b.source == "cpu-affinity" and b.total >= 1

    def test_non_integer_env_ignored(self):
        b = resolve_thread_budget(env={"IPC_THREADS": "lots"}, log=False)
        assert b.source == "cpu-affinity"

    def test_explicit_scan_threads_pins_stage_under_unified_total(self):
        b = resolve_thread_budget(threads=8, scan_threads=2, env={}, log=False)
        assert b.total == 8 and b.source == "--threads"
        assert b.scan_workers == 2
        assert b.native_scan_threads == 4  # 8 // 2


class TestPartition:
    @pytest.mark.parametrize("total", [1, 2, 3, 4, 7, 8, 16, 64])
    def test_no_oversubscription(self, total):
        b = resolve_thread_budget(threads=total, env={}, log=False)
        assert b.scan_workers * b.native_scan_threads <= b.total
        assert b.scan_workers >= 1 and b.record_workers >= 1
        assert b.verify_workers >= 1 and b.native_scan_threads >= 1

    def test_partition_shape_8(self):
        b = resolve_thread_budget(threads=8, env={}, log=False)
        assert b == ThreadBudget(
            total=8, scan_workers=4, record_workers=2, verify_workers=2,
            native_scan_threads=2, source="--threads",
        )

    def test_partition_shape_1(self):
        b = resolve_thread_budget(threads=1, env={}, log=False)
        assert (b.scan_workers, b.record_workers, b.verify_workers) == (1, 1, 1)
        assert b.native_scan_threads == 1

    def test_clamped_to_64(self):
        b = resolve_thread_budget(threads=1000, env={}, log=False)
        assert b.total == 64

    def test_budget_logged_once_per_resolution(self):
        # the package logger doesn't propagate to root, so assert on the
        # dedup registry: a repeated identical resolution adds nothing
        import ipc_proofs_tpu.utils.threads as threads_mod

        resolve_thread_budget(threads=63, env={})
        n = len(threads_mod._logged)
        assert n >= 1
        resolve_thread_budget(threads=63, env={})
        assert len(threads_mod._logged) == n
        resolve_thread_budget(threads=62, env={})
        assert len(threads_mod._logged) == n + 1
