"""Worker process for the two-process jax.distributed smoke test.

Run as: python tests/_multihost_worker.py <process_id> <num_processes>
<coordinator_port> <output_json_path>

Each process owns 2 virtual CPU devices (so the global mesh is dp=2 over
DCN-like process boundaries × sp=2 intra-process), initializes
jax.distributed against the localhost coordinator, takes its contiguous
half of the tipset range via host_local_pairs, assembles the GLOBAL
sharded arrays from process-local data, runs the sharded match pipeline
over the (2,2) mesh, and writes its view of the results (the replicated
proof count, the allgathered receipt-hit matrix, and its mesh facts) as
JSON for the parent test to compare against the single-process reference.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    proc_id, nprocs, port, out_path = (
        int(sys.argv[1]),
        int(sys.argv[2]),
        sys.argv[3],
        sys.argv[4],
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
    )
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = str(nprocs)
    os.environ["JAX_PROCESS_ID"] = str(proc_id)

    import jax

    # The env var alone is NOT enough on hosts with a device plugin: the
    # plugin registers at interpreter startup and distributed.initialize
    # would touch it (hanging forever against a dead tunnel) — the config
    # update forces CPU before any backend discovery (verify-skill gotcha).
    jax.config.update("jax_platforms", "cpu")

    from ipc_proofs_tpu.parallel.multihost import (
        global_mesh,
        host_local_pairs,
        initialize_distributed,
    )

    assert initialize_distributed() is True, "distributed init returned False"
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    assert jax.process_count() == nprocs
    assert jax.local_device_count() == 2
    assert jax.device_count() == 2 * nprocs

    mesh = global_mesh(sp=2)
    assert mesh.shape == {"dp": nprocs, "sp": 2}

    # the same synthetic world on every process (seeded)
    from ipc_proofs_tpu.parallel.pipeline import (
        match_pipeline,
        sharded_match_pipeline,
        synthetic_event_batch,
    )

    T, R, E = 8, 4, 4
    topic0, topic1 = b"\x11" * 32, b"\x22" * 32
    batch = synthetic_event_batch(T, R, E, topic0, topic1, match_rate=0.3, seed=7)

    # contiguous epoch shard for THIS host (the multi-host partitioning
    # under test), then global arrays assembled from process-local slices
    pairs = list(range(T))
    mine = host_local_pairs(pairs)
    assert mine, "process received an empty shard"
    sl = slice(mine[0], mine[-1] + 1)

    def globalize(local, spec):
        sharding = NamedSharding(mesh, spec)
        global_shape = (T,) + local.shape[1:]
        return jax.make_array_from_process_local_data(sharding, local, global_shape)

    g_topics = globalize(batch.topics[sl], P("dp", None, "sp", None, None))
    g_ntopics = globalize(batch.n_topics[sl], P("dp", None, "sp"))
    g_emitters = globalize(batch.emitters[sl], P("dp", None, "sp"))
    g_valid = globalize(batch.valid[sl], P("dp", None, "sp"))

    from ipc_proofs_tpu.parallel.pipeline import make_specs_u32

    spec0, spec1 = make_specs_u32(topic0, topic1)
    repl = NamedSharding(mesh, P())
    r_spec0 = multihost_utils.host_local_array_to_global_array(spec0, mesh, P())
    r_spec1 = multihost_utils.host_local_array_to_global_array(spec1, mesh, P())
    r_actor = multihost_utils.host_local_array_to_global_array(
        np.int32(-1), mesh, P()
    )
    del repl

    jitted, _shard = sharded_match_pipeline(mesh)
    hits, mask, count = jitted(
        g_topics, g_ntopics, g_emitters, g_valid, r_spec0, r_spec1, r_actor
    )

    # the replicated count is addressable everywhere; gather the sharded
    # hits so every process holds the full matrix
    full_hits = multihost_utils.process_allgather(hits, tiled=True)
    result = {
        "process_id": proc_id,
        "count": int(np.asarray(count)),
        "hits": np.asarray(full_hits).astype(int).ravel().tolist(),
        "my_pairs": mine,
        "devices": jax.device_count(),
        "mesh": dict(mesh.shape),
    }
    with open(out_path, "w") as fh:
        json.dump(result, fh)
    return 0


if __name__ == "__main__":
    sys.exit(main())
