"""Offline verification of the RFC 9380 SSWU hash-to-G2 construction.

Byte-level RFC vectors are unfetchable here (zero egress), so these tests
pin the construction by its mathematical invariants instead — each one
would fail with overwhelming probability if any vendored constant or
formula were wrong:

- SSWU outputs satisfy E2' (y² = x³ + A'x + B');
- the vendored 3-isogeny table maps E2' points ONTO E2 and is a group
  homomorphism (a corrupted constant would land off-curve; a different
  rational map would break additivity);
- the isogeny denominator's roots are roots of E2''s 3-division
  polynomial — the map's kernel is genuinely a 3-torsion subgroup, i.e.
  this is a degree-3 isogeny, the RFC's construction;
- ψ is derived (not vendored) and acts on G2 as the Frobenius eigenvalue;
- Budroni–Pintore clearing equals multiplication by the spec's h_eff
  scalar — two independently-derived cofactor clearings agreeing;
- hash_to_g2 outputs are r-torsion, deterministic, and DST-separated.
"""

import random

from ipc_proofs_tpu.crypto import bls
from ipc_proofs_tpu.crypto.bls import (
    _f2_add,
    _f2_inv,
    _f2_mul,
    _f2_neg,
    _f2_scalar,
    _f2_sqr,
    _f2_sqrt,
    _f2_sub,
    _iso3_map,
    _on_g2_twist,
    _pt_add,
    _pt_mul,
    _sswu_g2,
    _OPS2,
    _SSWU_A,
    _SSWU_B,
    clear_cofactor_g2,
    CURVE_ORDER,
    PRIME,
)

# RFC 9380 §8.8.2 effective cofactor for BLS12381G2 (vendored
# independently of the BP formula — the test asserts they agree)
H_EFF = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551


def _e2prime_is_on(p) -> bool:
    x, y = p
    rhs = _f2_add(_f2_add(_f2_mul(_f2_sqr(x), x), _f2_mul(_SSWU_A, x)), _SSWU_B)
    return _f2_sqr(y) == rhs


def _e2prime_add(p, q):
    """Affine addition on E2' (A' != 0, so the shared a=0 point ops don't
    apply)."""
    if p is None:
        return q
    if q is None:
        return p
    (x1, y1), (x2, y2) = p, q
    if x1 == x2 and y1 != y2:
        return None
    if p == q:
        num = _f2_add(_f2_scalar(_f2_sqr(x1), 3), _SSWU_A)
        den = _f2_scalar(y1, 2)
    else:
        num = _f2_sub(y2, y1)
        den = _f2_sub(x2, x1)
    lam = _f2_mul(num, _f2_inv(den))
    x3 = _f2_sub(_f2_sub(_f2_sqr(lam), x1), x2)
    y3 = _f2_sub(_f2_mul(lam, _f2_sub(x1, x3)), y1)
    return (x3, y3)


def _rand_u(rng):
    return (rng.randrange(PRIME), rng.randrange(PRIME))


class TestSSWU:
    def test_outputs_on_e2prime(self):
        rng = random.Random(1)
        for _ in range(8):
            assert _e2prime_is_on(_sswu_g2(_rand_u(rng)))

    def test_deterministic(self):
        u = (123, 456)
        assert _sswu_g2(u) == _sswu_g2(u)

    def test_exceptional_zero_input(self):
        # u = 0 hits the tv1 == 0 exceptional case
        assert _e2prime_is_on(_sswu_g2((0, 0)))


class TestIso3:
    def test_maps_onto_e2(self):
        rng = random.Random(2)
        for _ in range(8):
            pt = _iso3_map(_sswu_g2(_rand_u(rng)))
            assert pt is not None and _on_g2_twist(pt)

    def test_group_homomorphism(self):
        rng = random.Random(3)
        for _ in range(4):
            p = _sswu_g2(_rand_u(rng))
            q = _sswu_g2(_rand_u(rng))
            lhs = _iso3_map(_e2prime_add(p, q))
            rhs = _pt_add(_OPS2, _iso3_map(p), _iso3_map(q))
            assert lhs == rhs

    def test_kernel_is_three_torsion(self):
        """x_den = (x - x0)(x - x̄0): its roots must be roots of E2''s
        3-division polynomial ψ₃(x) = 3x⁴ + 6Ax² + 12Bx − A², proving the
        vendored map is a DEGREE-3 isogeny (not just any rational map)."""
        k20, k21 = bls._ISO3_X_DEN
        half = _f2_scalar(k21, pow(2, PRIME - 2, PRIME))
        disc = _f2_sub(_f2_sqr(half), k20)
        root = _f2_sqrt(disc)
        assert root is not None
        for sign in (root, _f2_neg(root)):
            x0 = _f2_sub(sign, half)
            x0_2 = _f2_sqr(x0)
            psi3 = _f2_sub(
                _f2_add(
                    _f2_add(
                        _f2_scalar(_f2_sqr(x0_2), 3),
                        _f2_scalar(_f2_mul(_SSWU_A, x0_2), 6),
                    ),
                    _f2_scalar(_f2_mul(_SSWU_B, x0), 12),
                ),
                _f2_sqr(_SSWU_A),
            )
            assert psi3 == (0, 0)


class TestCofactorClearing:
    def test_psi_eigenvalue_on_g2(self):
        gen = bls._G2
        eigen = _pt_mul(_OPS2, gen, (-bls._BLS_X) % CURVE_ORDER)
        assert bls._psi(gen) == eigen

    def test_bp_equals_h_eff(self):
        rng = random.Random(4)
        for _ in range(2):
            q = _iso3_map(_sswu_g2(_rand_u(rng)))
            assert clear_cofactor_g2(q) == _pt_mul(_OPS2, q, H_EFF)

    def test_outputs_r_torsion(self):
        h = bls.hash_to_g2(b"r-torsion probe")
        assert _on_g2_twist(h)
        assert _pt_mul(_OPS2, h, CURVE_ORDER) is None


class TestRFCVectors:
    """hash_to_curve outputs under the RFC 9380 example DST, pinned.

    The msg="" and msg="abc" outputs were independently confirmed against
    the RFC 9380 Appendix J.10.4 (BLS12381G2_XMD:SHA-256_SSWU_RO_) vectors
    during round-5 review; all three are pinned here so any regression in
    hash_to_field / SSWU / isogeny / cofactor clearing breaks loudly."""

    DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"

    VECTORS = {
        b"": (
            (0x0141EBFBDCA40EB85B87142E130AB689C673CF60F1A3E98D69335266F30D9B8D4AC44C1038E9DCDD5393FAF5C41FB78A,
             0x05CB8437535E20ECFFAEF7752BADDF98034139C38452458BAEEFAB379BA13DFF5BF5DD71B72418717047F5B0F37DA03D),
            (0x0503921D7F6A12805E72940B963C0CF3471C7B2A524950CA195D11062EE75EC076DAF2D4BC358C4B190C0C98064FDD92,
             0x12424AC32561493F3FE3C260708A12B7C620E7BE00099A974E259DDC7D1F6395C3C811CDD19F1E8DBF3E9ECFDCBAB8D6),
        ),
        b"abc": (
            (0x02C2D18E033B960562AAE3CAB37A27CE00D80CCD5BA4B7FE0E7A210245129DBEC7780CCC7954725F4168AFF2787776E6,
             0x139CDDBCCDC5E91B9623EFD38C49F81A6F83F175E80B06FC374DE9EB4B41DFE4CA3A230ED250FBE3A2ACF73A41177FD8),
            (0x1787327B68159716A37440985269CF584BCB1E621D3A7202BE6EA05C4CFE244AEB197642555A0645FB87BF7466B2BA48,
             0x00AA65DAE3C8D732D10ECD2C50F8A1BAF3001578F71C694E03866E9F3D49AC1E1CE70DD94A733534F106D4CEC0EDDD16),
        ),
        b"abcdef0123456789": (
            (0x121982811D2491FDE9BA7ED31EF9CA474F0E1501297F68C298E9F4C0028ADD35AEA8BB83D53C08CFC007C1E005723CD0,
             0x190D119345B94FBD15497BCBA94ECF7DB2CBFD1E1FE7DA034D26CBBA169FB3968288B3FAFB265F9EBD380512A71C3F2C),
            (0x05571A0F8D3C08D094576981F4A3B8EDA0A8E771FCDCC8ECCEAF1356A6ACF17574518ACB506E435B639353C2E14827C8,
             0x0BB5E7572275C567462D91807DE765611490205A941A5A6AF3B1691BFE596C31225D3AABDF15FAFF860CB4EF17C7C3BE),
        ),
    }

    def test_pinned_vectors(self):
        for msg, expected in self.VECTORS.items():
            assert bls.hash_to_g2(msg, dst=self.DST) == expected, msg


class TestHashToG2:
    def test_deterministic_and_message_separated(self):
        a = bls.hash_to_g2(b"message A")
        b = bls.hash_to_g2(b"message A")
        c = bls.hash_to_g2(b"message B")
        assert a == b
        assert a != c

    def test_dst_separated(self):
        a = bls.hash_to_g2(b"m", dst=b"DST-ONE")
        b = bls.hash_to_g2(b"m", dst=b"DST-TWO")
        assert a != b

    def test_default_dst_is_pop_ciphersuite(self):
        assert bls.DEFAULT_DST == b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
        assert bls.POP_DST == b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


class TestCanonicalPairing:
    def test_pairing_of_generators_has_order_r(self):
        e = bls.pairing(bls._G1, bls._G2)
        assert bls._f12_pow(e, CURVE_ORDER) == bls._F12_ONE
        assert e != bls._F12_ONE  # non-degenerate

    def test_negation_inverts(self):
        """e(-P, Q) = e(P, Q)^-1 — with the negative-x conjugation in
        place the map is the canonical optimal ate, not its inverse."""
        e = bls.pairing(bls._G1, bls._G2)
        e_neg = bls.pairing((bls._G1[0], (-bls._G1[1]) % PRIME), bls._G2)
        assert bls._f12_mul(e, e_neg) == bls._F12_ONE
