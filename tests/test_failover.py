"""EndpointPool semantics: failover routing, circuit-breaker lifecycle on
an injected clock, content-addressed integrity demotion, last-resort
routing of tripped endpoints, hedged reads, prefetch fail-soft, the
pipelined driver's single-core serial fallback and checkpoint/resume, and
degraded health reporting — all hermetic (LocalLotusSession, no network).
"""

import base64
import json
import os
import time

import pytest

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore, put_cbor
from ipc_proofs_tpu.store.failover import EndpointPool
from ipc_proofs_tpu.store.faults import LocalLotusSession
from ipc_proofs_tpu.store.rpc import (
    IntegrityError,
    LotusClient,
    RpcBlockstore,
    RpcError,
)
from ipc_proofs_tpu.utils.metrics import Metrics


class _Resp:
    def __init__(self, body):
        self._body = body

    def raise_for_status(self):
        pass

    def json(self):
        return self._body


class _Switchable:
    """A LocalLotusSession whose failure mode can be flipped mid-test:
    ``ok`` (serve honestly), ``dead`` (transport error), ``corrupt``
    (bit-flip every block), ``slow`` (sleep then serve)."""

    def __init__(self, store, mode="ok", slow_s=0.2):
        self._inner = LocalLotusSession(store)
        self.mode = mode
        self.slow_s = slow_s
        self.calls = 0

    def post(self, url, data=None, headers=None, timeout=None):
        self.calls += 1
        if self.mode == "dead":
            raise ConnectionError("endpoint down")
        if self.mode == "slow":
            time.sleep(self.slow_s)
        resp = self._inner.post(url, data=data, headers=headers, timeout=timeout)
        if self.mode != "corrupt":
            return resp
        body = dict(resp.json())
        result = body.get("result")
        if isinstance(result, str):
            raw = bytearray(base64.b64decode(result))
            raw[0] ^= 1
            body["result"] = base64.b64encode(bytes(raw)).decode("ascii")
        return _Resp(body)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _world():
    store = MemoryBlockstore()
    cid = put_cbor(store, {"k": b"value", "n": 7})
    return store, cid, store.get(cid)


def _client(session, **kw):
    kw.setdefault("max_retries", 1)  # failover is the pool's job in these tests
    return LotusClient("http://ep", session=session, **kw)


def _pool(sessions, **kw):
    kw.setdefault("breaker_threshold", 2)
    kw.setdefault("breaker_reset_s", 30.0)
    clock = kw.pop("clock", None) or _Clock()
    pool = EndpointPool(
        [_client(s) for s in sessions], clock=clock, **kw
    )
    return pool, clock


class TestFailoverRouting:
    def test_read_fails_over_to_healthy_endpoint(self):
        store, cid, raw = _world()
        dead, healthy = _Switchable(store, "dead"), _Switchable(store)
        m = Metrics()
        pool, _ = _pool([dead, healthy], metrics=m)
        assert pool.chain_read_obj(cid) == raw
        snaps = pool.health()["endpoints"]
        assert snaps[0]["failures"] == 1 and snaps[1]["successes"] == 1

    def test_request_exhaustion_raises_runtime_error(self):
        store, _, _ = _world()
        pool, _ = _pool([_Switchable(store, "dead"), _Switchable(store, "dead")])
        with pytest.raises(RuntimeError, match="all 2 endpoints failed"):
            pool.request("Filecoin.ChainHead", [])

    def test_rpc_error_is_authoritative_no_failover(self):
        # a node answering with a protocol error IS an answer — the pool
        # must not re-ask a replica (it would say the same thing)
        store, _, _ = _world()
        a, b = _Switchable(store), _Switchable(store)
        pool, _ = _pool([a, b])
        with pytest.raises(RpcError, match="-32601"):
            pool.request("Filecoin.NoSuchMethod", [])
        assert a.calls == 1 and b.calls == 0
        # and it counts as endpoint health, not failure
        assert pool.health()["endpoints"][0]["consecutive_failures"] == 0


class TestBreakerLifecycle:
    def test_threshold_opens_then_half_open_probe_closes(self):
        store, cid, raw = _world()
        flaky = _Switchable(store, "dead")
        m = Metrics()
        pool, clock = _pool([flaky], metrics=m, breaker_threshold=2)
        for _ in range(2):
            with pytest.raises(RuntimeError):
                pool.chain_read_obj(cid)
        assert pool.health()["status"] == "degraded"
        assert pool.health()["endpoints"][0]["breaker"] == "open"
        assert m.snapshot()["counters"]["failover.breaker_open"] == 1

        clock.advance(31.0)  # past breaker_reset_s
        flaky.mode = "ok"  # endpoint recovered
        assert pool.chain_read_obj(cid) == raw  # the half-open probe
        assert pool.health()["endpoints"][0]["breaker"] == "closed"
        assert pool.health()["status"] == "ok"

    def test_half_open_failure_reopens(self):
        store, cid, _ = _world()
        flaky = _Switchable(store, "dead")
        pool, clock = _pool([flaky], breaker_threshold=1)
        with pytest.raises(RuntimeError):
            pool.chain_read_obj(cid)
        clock.advance(31.0)
        with pytest.raises(RuntimeError):  # probe fails → open again
            pool.chain_read_obj(cid)
        assert pool.health()["endpoints"][0]["breaker"] == "open"

    def test_open_endpoint_sheds_load_but_is_last_resort(self):
        store, cid, raw = _world()
        dead, healthy = _Switchable(store, "dead"), _Switchable(store)
        pool, _ = _pool([dead, healthy], breaker_threshold=1)
        assert pool.chain_read_obj(cid) == raw  # dead tried first, fails over
        dead_calls = dead.calls
        assert dead_calls >= 1
        # while the breaker is open-in-window, routine reads skip the
        # tripped endpoint entirely...
        for _ in range(3):
            assert pool.chain_read_obj(cid) == raw
        assert dead.calls == dead_calls
        # ...but when every healthier endpoint fails, the tripped one is
        # still tried rather than the read being refused outright
        healthy.mode = "dead"
        dead.mode = "ok"
        assert pool.chain_read_obj(cid) == raw
        assert dead.calls == dead_calls + 1


class TestIntegrity:
    def test_corrupt_endpoint_demoted_and_read_recovers(self):
        store, cid, raw = _world()
        corrupt, healthy = _Switchable(store, "corrupt"), _Switchable(store)
        m = Metrics()
        pool, _ = _pool([corrupt, healthy], metrics=m)
        assert pool.chain_read_obj(cid) == raw  # served by the honest one
        snaps = pool.health()["endpoints"]
        assert snaps[0]["integrity_demotions"] == 1
        assert snaps[0]["breaker"] == "open"  # one lie trips immediately
        assert m.snapshot()["counters"]["rpc.integrity_failures"] == 1

    def test_all_corrupt_raises_integrity_error(self):
        store, cid, _ = _world()
        pool, _ = _pool([_Switchable(store, "corrupt"), _Switchable(store, "corrupt")])
        with pytest.raises(IntegrityError, match="multihash"):
            pool.chain_read_obj(cid)

    def test_rpc_blockstore_verifies_single_client(self):
        # without a pool the blockstore itself recomputes the multihash
        store, cid, _ = _world()
        m = Metrics()
        client = _client(_Switchable(store, "corrupt"))
        bs = RpcBlockstore(client, metrics=m)
        with pytest.raises(IntegrityError):
            bs.get(cid)
        assert m.snapshot()["counters"]["rpc.integrity_failures"] == 1

    def test_rpc_blockstore_trusts_verifying_pool(self):
        store, cid, raw = _world()
        pool, _ = _pool([_Switchable(store)])
        assert pool.verifies_integrity is True
        assert RpcBlockstore(pool).get(cid) == raw


class TestHedgedReads:
    def test_hedge_fires_and_wins_on_slow_primary(self):
        store, cid, raw = _world()
        slow, fast = _Switchable(store, "slow", slow_s=0.5), _Switchable(store)
        m = Metrics()
        # real clock here: the hedge delay is wall time inside futures
        pool = EndpointPool(
            [_client(slow), _client(fast)], hedge_ms=1.0, metrics=m,
        )
        try:
            t0 = time.perf_counter()
            assert pool.chain_read_obj(cid) == raw
            assert time.perf_counter() - t0 < 0.45  # did not wait out the primary
            counters = m.snapshot()["counters"]
            assert counters["rpc.hedges"] == 1
            assert counters["rpc.hedge_wins"] == 1
        finally:
            pool.close()

    def test_no_hedge_when_primary_is_fast(self):
        store, cid, raw = _world()
        m = Metrics()
        pool = EndpointPool(
            [_client(_Switchable(store)), _client(_Switchable(store))],
            hedge_ms=200.0, metrics=m,
        )
        try:
            assert pool.chain_read_obj(cid) == raw
            assert "rpc.hedges" not in m.snapshot()["counters"]
        finally:
            pool.close()


class TestPrefetchFailSoft:
    def test_prefetch_absorbs_failures_and_reports_them(self):
        store, cid, _ = _world()
        missing = CID.hash_of(b"no such block")
        m = Metrics()
        bs = RpcBlockstore(_client(_Switchable(store, "dead")), metrics=m)
        cache: dict = {}
        failures = bs.prefetch([cid, missing], cache)  # must NOT raise
        assert set(failures) == {cid, missing}
        assert cache == {}
        assert m.snapshot()["counters"]["rpc.prefetch_failures"] == 2

    def test_prefetch_clean_run_reports_nothing(self):
        store, cid, raw = _world()
        bs = RpcBlockstore(_client(_Switchable(store)))
        cache: dict = {}
        assert bs.prefetch([cid], cache) == {}
        assert cache[cid] == raw


def _range_world():
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec

    sig, t1, actor = "NewTopDownMessage(bytes32,uint256)", "calib-subnet-1", 1001
    bs, pairs, _ = build_range_world(
        4, 2, 1, 0.5, signature=sig, topic1=t1, actor_id=actor
    )
    spec = EventProofSpec(event_signature=sig, topic_1=t1, actor_id_filter=actor)
    return bs, pairs, spec


class TestSerialFallback:
    def test_single_core_host_runs_inline_bit_identically(self, monkeypatch):
        from ipc_proofs_tpu.proofs.range import (
            generate_event_proofs_for_range,
            generate_event_proofs_for_range_pipelined,
        )

        bs, pairs, spec = _range_world()
        reference = generate_event_proofs_for_range(bs, pairs, spec).to_json()
        monkeypatch.delenv("IPC_FORCE_PIPELINE", raising=False)
        monkeypatch.setattr(os, "cpu_count", lambda: 1)
        m = Metrics()
        bundle = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=2, metrics=m
        )
        assert bundle.to_json() == reference
        assert m.snapshot()["counters"]["range_pipeline_serial_fallback"] >= 1

    def test_force_pipeline_overrides_single_core(self, monkeypatch):
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined

        bs, pairs, spec = _range_world()
        monkeypatch.setattr(os, "cpu_count", lambda: 1)
        m = Metrics()
        generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=2, metrics=m, force_pipeline=True
        )
        assert "range_pipeline_serial_fallback" not in m.snapshot()["counters"]


class TestPipelinedCheckpoints:
    def test_checkpoint_then_resume_from_empty_store(self, tmp_path):
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined

        bs, pairs, spec = _range_world()
        ckpt = str(tmp_path / "ckpts")
        m1 = Metrics()
        first = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=2, metrics=m1, checkpoint_dir=ckpt,
            force_pipeline=True,
        )
        assert m1.snapshot()["counters"]["range_chunks_generated"] == 2
        assert len(os.listdir(ckpt)) == 2

        # a resume must not need the chain at all: hand it an EMPTY store
        m2 = Metrics()
        resumed = generate_event_proofs_for_range_pipelined(
            MemoryBlockstore(), pairs, spec, chunk_size=2, metrics=m2,
            checkpoint_dir=ckpt, force_pipeline=True,
        )
        assert resumed.to_json() == first.to_json()
        assert m2.snapshot()["counters"]["range_chunks_resumed"] == 2

    def test_checkpoints_are_spec_keyed(self, tmp_path):
        from ipc_proofs_tpu.proofs.generator import EventProofSpec
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined

        bs, pairs, spec = _range_world()
        ckpt = str(tmp_path / "ckpts")
        generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=2, checkpoint_dir=ckpt, force_pipeline=True
        )
        # a different spec must not resume another spec's chunks
        other = EventProofSpec(
            event_signature=spec.event_signature, topic_1="other-subnet",
            actor_id_filter=spec.actor_id_filter,
        )
        m = Metrics()
        generate_event_proofs_for_range_pipelined(
            bs, pairs, other, chunk_size=2, metrics=m, checkpoint_dir=ckpt,
            force_pipeline=True,
        )
        assert "range_chunks_resumed" not in m.snapshot()["counters"]
        assert len(os.listdir(ckpt)) == 4  # both specs checkpointed side by side


class TestServiceHealth:
    def test_health_reports_pool_degradation(self):
        from ipc_proofs_tpu.serve.service import ProofService

        store, cid, _ = _world()
        dead = _Switchable(store, "dead")
        pool, _ = _pool([dead, _Switchable(store)], breaker_threshold=1)
        service = ProofService(store=MemoryBlockstore(), endpoint_pool=pool)
        try:
            assert service.health()["status"] == "ok"
            pool.chain_read_obj(cid)  # trips the dead endpoint's breaker
            health = service.health()
            assert health["status"] == "degraded"
            assert health["endpoints"][0]["breaker"] == "open"
        finally:
            service.drain()


class TestDegradedMode:
    """The ``lotus_down`` posture: every breaker open ⇒ typed fail-fast
    (`DegradedError`), ONE synchronized half-open probe behind a jittered
    backoff gate, and in-place recovery the moment a probe lands."""

    def _down_pool(self, store):
        from ipc_proofs_tpu.utils.metrics import Metrics

        s0, s1 = _Switchable(store, "dead"), _Switchable(store, "dead")
        m = Metrics()
        pool, clock = _pool([s0, s1], breaker_threshold=1, metrics=m)
        return pool, clock, (s0, s1), m

    def test_entry_is_typed_and_counted(self):
        from ipc_proofs_tpu.store.failover import DegradedError

        store, cid, _ = _world()
        pool, _, _, m = self._down_pool(store)
        with pytest.raises(DegradedError) as exc:
            pool.chain_read_obj(cid)
        assert exc.value.error_type == "degraded"
        assert pool.lotus_down
        assert m.snapshot()["counters"]["degraded.entered"] == 1
        assert pool.health()["mode"] == "lotus_down"

    def test_single_probe_rest_suppressed_fail_fast(self):
        from ipc_proofs_tpu.store.failover import DegradedError

        store, cid, _ = _world()
        pool, _, (s0, s1), m = self._down_pool(store)
        with pytest.raises(DegradedError):
            pool.chain_read_obj(cid)  # enters lotus_down
        # the gate starts open: exactly ONE endpoint attempt (the pool
        # probe) goes out, the other is suppressed — and it fails, arming
        # the jittered backoff window
        calls0 = s0.calls + s1.calls
        with pytest.raises(DegradedError):
            pool.chain_read_obj(cid)
        assert (s0.calls + s1.calls) == calls0 + 1
        c = m.snapshot()["counters"]
        assert c["rpc.probe_suppressed"] >= 1
        # inside the backoff window NOTHING reaches an endpoint: pure
        # typed fail-fast (this is what keeps a dead upstream cheap)
        calls1 = s0.calls + s1.calls
        with pytest.raises(DegradedError):
            pool.chain_read_obj(cid)
        assert (s0.calls + s1.calls) == calls1
        assert m.snapshot()["counters"]["degraded.fail_fast"] >= 1

    def test_probe_success_recovers_without_restart(self):
        from ipc_proofs_tpu.store.failover import DegradedError

        store, cid, raw = _world()
        pool, clock, (s0, s1), m = self._down_pool(store)
        with pytest.raises(DegradedError):
            pool.chain_read_obj(cid)
        with pytest.raises(DegradedError):
            pool.chain_read_obj(cid)  # failed probe → backoff armed
        s0.mode = s1.mode = "ok"
        clock.advance(31.0)  # past breaker reset AND any probe jitter
        assert pool.chain_read_obj(cid) == raw
        assert not pool.lotus_down
        c = m.snapshot()["counters"]
        assert c["degraded.exited"] == 1
        assert pool.health()["status"] in ("ok", "degraded")
        assert pool.health().get("mode") != "lotus_down"


class TestRetryBudget:
    def test_pool_budget_stops_the_retry_ladder(self):
        """A pool-wide retries/second budget: once dry, every client's
        backoff ladder stops immediately (anti-retry-storm governor)."""
        from ipc_proofs_tpu.utils.metrics import Metrics

        store, cid, _ = _world()
        dead = _Switchable(store, "dead")
        clock = _Clock()
        m = Metrics()
        client = LotusClient(
            "http://ep", session=dead, max_retries=4,
            backoff_base_s=0.0, backoff_max_s=0.0,
        )
        pool = EndpointPool(
            [client], clock=clock, breaker_threshold=10,
            retry_budget_per_s=1.0, metrics=m,
        )
        # budget = 2·rate tokens with a frozen clock: the first two retry
        # sleeps spend them, the third is refused — 3 attempts total, not
        # max_retries' 4
        with pytest.raises(RuntimeError):
            pool.chain_read_obj(cid)
        assert dead.calls == 3
        assert m.snapshot()["counters"]["rpc.retry_budget_exhausted"] >= 1

    def test_unbudgeted_pool_retries_in_full(self):
        store, cid, _ = _world()
        dead = _Switchable(store, "dead")
        client = LotusClient(
            "http://ep", session=dead, max_retries=4,
            backoff_base_s=0.0, backoff_max_s=0.0,
        )
        pool = EndpointPool([client], clock=_Clock(), breaker_threshold=10)
        with pytest.raises(RuntimeError):
            pool.chain_read_obj(cid)
        assert dead.calls == 4
