"""Randomized acceptance-parity fuzz: CID codecs and the exec-order walker.

Same method as the verifier fuzzes (which found real divergences): drive
the scalar/Python implementation and its batched/C twin through the same
randomly mutated inputs and assert they accept and reject identically.

- CID strings: `PurePythonCID.from_string` vs the C `cids_from_strs`
  batch parser AND the native CID type's `from_string` (since round 5,
  `CID` *is* the C extension type when available — the pure-Python
  dataclass stays the scalar authority so the differential is real).
- CID bytes: `PurePythonCID.from_bytes` vs the C `make_cids` batch
  constructor and native `CID.from_bytes`.
- Execution orders: scalar `reconstruct_execution_order` per group vs the
  batched `reconstruct_execution_orders_batch` (whose contract maps a
  scalar raise to a per-group None) over corrupted witness stores.
"""

import random

import pytest

from ipc_proofs_tpu.backend.native import load_dagcbor_ext
from ipc_proofs_tpu.core.cid import CID, PurePythonCID
from ipc_proofs_tpu.proofs.exec_order import (
    reconstruct_execution_order,
    reconstruct_execution_orders_batch,
)
from ipc_proofs_tpu.proofs.scan_native import native_scan_available
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

from tests.test_batch_verifier import make_bundle


def _ext_or_skip(attr):
    ext = load_dagcbor_ext()
    if ext is None or not hasattr(ext, attr):
        pytest.skip(f"native {attr} unavailable")
    return ext


_B32 = "abcdefghijklmnopqrstuvwxyz234567"


def _mutate_str(rng: random.Random, s: str) -> str:
    kind = rng.randrange(7)
    if kind == 0 and s:  # substitute with base32 / invalid / uppercase char
        i = rng.randrange(len(s))
        ch = rng.choice(_B32 + _B32.upper() + "018!=. é")
        return s[:i] + ch + s[i + 1 :]
    if kind == 1 and s:
        return s[: rng.randrange(len(s))]  # truncate
    if kind == 2:
        return s + rng.choice(_B32)  # extend
    if kind == 3 and s:
        return rng.choice(["z", "f", "B", ""]) + s[1:]  # multibase prefix
    if kind == 4:
        return s.upper()
    if kind == 5:
        return s + "="  # base32 padding is not accepted unpadded-only
    return s  # unmutated valid string (keeps the accept regime exercised)


@pytest.mark.parametrize("seed", [11, 0xC1D])
def test_cid_string_codec_acceptance_parity(seed):
    ext = _ext_or_skip("cids_from_strs")
    rng = random.Random(seed)
    bases = [str(CID.hash_of(bytes([i]))) for i in range(8)]
    bases.append(str(CID.hash_of(b"raw", codec=0x55)))
    accepted = rejected = 0
    for _ in range(600):
        s = _mutate_str(rng, rng.choice(bases))
        if rng.random() < 0.3:
            s = _mutate_str(rng, s)
        try:
            scalar = ("ok", PurePythonCID.from_string(s))
        except ValueError:
            scalar = ("reject",)
        try:
            native = ("ok", CID.from_string(s))
        except ValueError:
            native = ("reject",)
        try:
            batch = ("ok", ext.cids_from_strs([s])[0])
        except ValueError:
            batch = ("reject",)
        assert scalar == native == batch, (
            f"CID string {s!r}: scalar={scalar} native={native} batch={batch}"
        )
        if scalar[0] == "ok":
            # canonical-form invariant: an accepted string IS its CID's
            # unique string form — the parity assert alone is blind to
            # malleability both implementations share (case aliasing,
            # non-zero trailing bits — both previously accepted)
            assert str(scalar[1]) == s, f"non-canonical string accepted: {s!r}"
            accepted += 1
        else:
            rejected += 1
    assert accepted and rejected  # both regimes exercised


def test_non_ascii_prefix_rejects_as_value_error():
    """A non-ASCII first character is NEGATIVE as a C signed char; the C
    parser's error path used to feed it to PyErr_Format's %c, which raises
    OverflowError itself — an exception-type leak at the boundary (found
    by the codec fuzz soak). Both parsers must reject with ValueError."""
    ext = _ext_or_skip("cids_from_strs")
    s = "é" + str(CID.hash_of(b"x"))[1:]
    with pytest.raises(ValueError):
        CID.from_string(s)
    with pytest.raises(ValueError):
        ext.cids_from_strs([s])


def test_non_minimal_varint_rejected_at_every_boundary():
    """A CID whose bytes encode the codec as a non-minimal varint
    (0xf1 0x00 instead of 0x71) is a SECOND encoding of the same CID —
    every parser (bytes-level in both implementations, and both string
    parsers) must reject it, matching go-varint / rust unsigned-varint.
    Until round 5 the bytes level tolerated-and-normalized it, which let
    the C walkers' raw spans disagree with the scalar canonical
    re-encodes (exec-order fuzz find, seed 876857442)."""
    from ipc_proofs_tpu.core.cid import _b32_encode_lower

    c = CID.hash_of(b"payload")
    noncanon = b"\x01\xf1\x00\xa0\xe4\x02\x20" + c.digest
    with pytest.raises(ValueError, match="non-canonical"):
        CID.from_bytes(noncanon)
    s = "b" + _b32_encode_lower(noncanon)
    with pytest.raises(ValueError, match="non-canonical"):
        CID.from_string(s)
    ext = _ext_or_skip("cids_from_strs")
    with pytest.raises(ValueError, match="non-canonical"):
        ext.cids_from_strs([s])


@pytest.mark.parametrize("seed", [5, 0xB17E5])
def test_cid_bytes_codec_acceptance_parity(seed):
    ext = _ext_or_skip("make_cids")
    rng = random.Random(seed)
    bases = [CID.hash_of(bytes([i])).to_bytes() for i in range(8)]
    accepted = rejected = 0
    for _ in range(600):
        raw = bytearray(rng.choice(bases))
        for _ in range(rng.randrange(1, 3)):
            kind = rng.randrange(4)
            if kind == 0 and raw:
                raw[rng.randrange(len(raw))] ^= 1 << rng.randrange(8)
            elif kind == 1 and raw:
                del raw[rng.randrange(len(raw))]
            elif kind == 2:
                raw.insert(rng.randrange(len(raw) + 1), rng.randrange(256))
        raw = bytes(raw)
        try:
            scalar = ("ok", PurePythonCID.from_bytes(raw))
        except ValueError:
            scalar = ("reject",)
        try:
            native = ("ok", CID.from_bytes(raw))
        except ValueError:
            native = ("reject",)
        try:
            batch = ("ok", ext.make_cids([raw])[0])
        except ValueError:
            batch = ("reject",)
        assert scalar == native == batch, (
            f"CID bytes {raw.hex()}: scalar={scalar} native={native} batch={batch}"
        )
        if scalar[0] == "ok":
            accepted += 1
        else:
            rejected += 1
    assert accepted and rejected


def _exec_groups_and_store():
    """Real witness store + per-proof parent-header groups from the event
    fixture world (one-block tipsets; TxMeta + both message AMTs present)."""
    bundle = make_bundle(n_pairs=3)
    store = MemoryBlockstore()
    for b in bundle.blocks:
        store.put_keyed(b.cid, b.data)
    seen = set()
    groups = []
    for p in bundle.proofs:
        key = tuple(p.parent_tipset_cids)
        if key not in seen:
            seen.add(key)
            groups.append([CID.from_string(c) for c in key])
    return store, groups, {b.cid: b.data for b in bundle.blocks}


@pytest.mark.parametrize("seed", [3, 0xE0, 876857442])
def test_exec_order_batch_scalar_parity_under_corruption(seed):
    # 876857442: round-5 soak find — a non-minimal multihash-code varint
    # in a message-CID link made the C walker's raw span disagree with the
    # scalar decode's canonical re-encode; both decoders now reject
    # non-minimal varints in CID bytes.
    if not native_scan_available():
        pytest.skip("native scan extension unavailable")
    rng = random.Random(seed)
    store, groups, raw_map = _exec_groups_and_store()
    cids = list(raw_map)
    none_groups = 0
    for _ in range(120):
        # corrupt a copy of the store: flip/truncate/extend/drop blocks
        mutated = MemoryBlockstore()
        drop = rng.choice(cids) if rng.random() < 0.3 else None
        for cid, raw in raw_map.items():
            if cid == drop:
                continue
            if rng.random() < 0.25:
                data = bytearray(raw)
                kind = rng.randrange(3)
                if kind == 0 and data:
                    data[rng.randrange(len(data))] ^= 1 << rng.randrange(8)
                elif kind == 1 and data:
                    del data[rng.randrange(len(data)) :]
                else:
                    data += b"\x00"
                raw = bytes(data)
            mutated.put_keyed(cid, raw)
        batch = reconstruct_execution_orders_batch(mutated, groups)
        assert batch is not None
        for g, group in enumerate(groups):
            try:
                scalar = [c.to_bytes() for c in reconstruct_execution_order(mutated, group)]
            except (KeyError, ValueError):
                scalar = None
            assert batch[g] == scalar, (
                f"group {g} diverged under seed={seed}: "
                f"batch={batch[g]!r} scalar={scalar!r}"
            )
            if scalar is None:
                none_groups += 1
    assert none_groups  # the corruption actually bit


class TestBundleJsonParsing:
    """`UnifiedProofBundle.from_json` consumes THE untrusted input (the
    bundle a verifier is asked to check). It must reject every malformed
    shape as ValueError — pre-hardening it leaked KeyError/TypeError from
    shape assumptions and performed no field type validation at all."""

    def _valid_obj(self):
        import json

        from tests.test_storage_batch_verifier import make_storage_bundle

        return json.loads(make_storage_bundle().to_json())

    def test_round_trip(self):
        import json

        from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle

        obj = self._valid_obj()
        bundle = UnifiedProofBundle.from_json_obj(obj)
        assert json.loads(bundle.to_json()) == obj

    def test_non_object_roots_rejected(self):
        from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle

        for garbage in ("[]", '"str"', "42", "null", "{}"):
            with pytest.raises(ValueError):
                UnifiedProofBundle.from_json(garbage)

    @pytest.mark.parametrize("seed", [2, 0xB0B])
    def test_randomized_structural_garbage_never_leaks(self, seed):
        import copy

        from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle

        rng = random.Random(seed)
        base = self._valid_obj()
        garbage_values = [
            None, True, False, -1, 3.5, "x", "", [], {}, [None], {"k": 1},
            "AAA!", 2**70, [2**70],
        ]

        def mutate(obj):
            doc = copy.deepcopy(obj)
            sites = []

            def walk(node):
                if isinstance(node, dict):
                    for k in node:
                        sites.append((node, k))
                        walk(node[k])
                elif isinstance(node, list):
                    for i in range(len(node)):
                        sites.append((node, i))
                        walk(node[i])

            walk(doc)
            container, key = rng.choice(sites)
            if rng.randrange(3) == 1 and isinstance(container, dict):
                del container[key]
            else:
                container[key] = rng.choice(garbage_values)
            return doc

        parsed = rejected = 0
        for _ in range(250):
            doc = mutate(base)
            if rng.random() < 0.3:
                doc = mutate(doc)
            try:
                UnifiedProofBundle.from_json_obj(doc)
                parsed += 1
            except ValueError:
                rejected += 1
            # anything else propagates and fails the test
        assert parsed and rejected


def test_base64_trailing_bits_rejected_at_trust_boundaries():
    """'AB==' and 'AA==' decode to the same byte under validate=True —
    non-canonical base64 would let distinct JSON documents carry one
    object. Both untrusted-input boundaries must reject it."""
    from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
    from ipc_proofs_tpu.proofs.cert import FinalityCertificate

    with pytest.raises(ValueError, match="non-canonical base64"):
        FinalityCertificate.from_json_obj(
            {"GPBFTInstance": 1, "ECChain": [], "Signers": "AB=="}
        )
    with pytest.raises(ValueError, match="non-canonical base64"):
        UnifiedProofBundle.from_json_obj(
            {
                "storage_proofs": [],
                "event_proofs": [],
                "blocks": [{"cid": str(CID.hash_of(b"x")), "data": "AB=="}],
            }
        )
    # the canonical sibling passes
    cert = FinalityCertificate.from_json_obj(
        {"GPBFTInstance": 1, "ECChain": [], "Signers": "AA=="}
    )
    assert cert.signers == b"\x00"
