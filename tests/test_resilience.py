"""Failure-detection / recovery tests: RPC retries, chunked resume.

The reference aborts on the first error with no retries and no partial
recovery (SURVEY.md §5); these tests pin the framework's improvements.
"""

import json

import pytest

from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import (
    TipsetPair,
    generate_event_proofs_for_range,
    generate_event_proofs_for_range_chunked,
)
from ipc_proofs_tpu.proofs.trust import TrustPolicy
from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore
from ipc_proofs_tpu.utils.metrics import Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"


def _range(n_pairs, store=None, base=50):
    bs = store or MemoryBlockstore()
    pairs = []
    for p in range(n_pairs):
        events = [[EventFixture(emitter=5, signature=SIG, topic1="s")]]
        world = build_chain(
            [ContractFixture(actor_id=5)], events, parent_height=base + 2 * p, store=bs
        )
        pairs.append(TipsetPair(world.parent, world.child))
    return bs, pairs


class TestChunkedResume:
    def test_chunked_equals_unchunked(self, tmp_path):
        bs, pairs = _range(7)
        spec = EventProofSpec(event_signature=SIG, topic_1="s", actor_id_filter=5)
        whole = generate_event_proofs_for_range(bs, pairs, spec)
        chunked = generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=3, checkpoint_dir=str(tmp_path / "ckpt")
        )
        assert {p.message_cid for p in whole.event_proofs} == {
            p.message_cid for p in chunked.event_proofs
        }
        assert [str(b.cid) for b in whole.blocks] == [str(b.cid) for b in chunked.blocks]
        assert verify_proof_bundle(chunked, TrustPolicy.accept_all()).all_valid()

    def test_resume_skips_finished_chunks(self, tmp_path):
        bs, pairs = _range(6)
        spec = EventProofSpec(event_signature=SIG, topic_1="s", actor_id_filter=5)
        ckpt = str(tmp_path / "ckpt")
        m1 = Metrics()
        first = generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=2, checkpoint_dir=ckpt, metrics=m1
        )
        assert m1.snapshot()["counters"]["range_chunks_generated"] == 3

        # second run must come entirely from checkpoints — even with an
        # EMPTY blockstore (nothing left to fetch)
        m2 = Metrics()
        resumed = generate_event_proofs_for_range_chunked(
            MemoryBlockstore(), pairs, spec, chunk_size=2, checkpoint_dir=ckpt, metrics=m2
        )
        counters = m2.snapshot()["counters"]
        assert counters["range_chunks_resumed"] == 3
        assert "range_chunks_generated" not in counters
        assert resumed.to_json() == first.to_json()

    def test_partial_checkpoint_recovers_rest(self, tmp_path):
        bs, pairs = _range(6)
        spec = EventProofSpec(event_signature=SIG, topic_1="s", actor_id_filter=5)
        ckpt = tmp_path / "ckpt"
        # simulate a crash after one finished chunk
        generate_event_proofs_for_range_chunked(
            bs, pairs[:2], spec, chunk_size=2, checkpoint_dir=str(ckpt)
        )
        assert list(ckpt.glob("chunk_*_0000.json"))
        m = Metrics()
        full = generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=2, checkpoint_dir=str(ckpt), metrics=m
        )
        counters = m.snapshot()["counters"]
        assert counters["range_chunks_resumed"] == 1
        assert counters["range_chunks_generated"] == 2
        assert len(full.event_proofs) == 6

    def test_checkpoints_keyed_by_request(self, tmp_path):
        """Checkpoints written for one request must NOT be resumed by a
        different one: adding storage specs to a re-run regenerates instead
        of silently reusing event-only chunk bundles."""
        from ipc_proofs_tpu.proofs.storage_batch import MappingSlotSpec

        bs, pairs = _range(4)
        spec = EventProofSpec(event_signature=SIG, topic_1="s", actor_id_filter=5)
        ckpt = tmp_path / "ckpt"
        generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=2, checkpoint_dir=str(ckpt)
        )
        m = Metrics()
        mixed = generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=2, checkpoint_dir=str(ckpt), metrics=m,
            storage_specs=[MappingSlotSpec(actor_id=5, key="k", slot_index=0)],
        )
        counters = m.snapshot()["counters"]
        assert "range_chunks_resumed" not in counters
        assert counters["range_chunks_generated"] == 2
        assert len(mixed.storage_proofs) == len(pairs)

    def test_checkpoints_keyed_by_range_identity(self, tmp_path):
        """Chunks of a DIFFERENT epoch range must not be resumed from a
        shared checkpoint dir even with identical specs."""
        bs, pairs_a = _range(2)
        spec = EventProofSpec(event_signature=SIG, topic_1="s", actor_id_filter=5)
        ckpt = tmp_path / "ckpt"
        generate_event_proofs_for_range_chunked(
            bs, pairs_a, spec, chunk_size=2, checkpoint_dir=str(ckpt)
        )
        bs2, pairs_b = _range(2, base=400)  # different heights/tipsets
        m = Metrics()
        out = generate_event_proofs_for_range_chunked(
            bs2, pairs_b, spec, chunk_size=2, checkpoint_dir=str(ckpt), metrics=m
        )
        counters = m.snapshot()["counters"]
        assert "range_chunks_resumed" not in counters
        assert counters["range_chunks_generated"] == 1
        assert {p.parent_epoch for p in out.event_proofs} == {
            pair.parent.height for pair in pairs_b
        }

    def test_checkpoint_files_are_valid_bundles(self, tmp_path):
        bs, pairs = _range(4)
        spec = EventProofSpec(event_signature=SIG, topic_1="s", actor_id_filter=5)
        ckpt = tmp_path / "ckpt"
        generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=2, checkpoint_dir=str(ckpt)
        )
        for path in sorted(ckpt.glob("chunk_*.json")):
            bundle = UnifiedProofBundle.from_json(path.read_text())
            assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).all_valid()


class FlakyClient:
    """requests-free stand-in that fails N times then succeeds."""

    def __init__(self, fail_times: int):
        self.fail_times = fail_times
        self.calls = 0

    def post(self, url, data=None, headers=None, timeout=None):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise ConnectionError("flaky network")

        class Resp:
            @staticmethod
            def raise_for_status():
                pass

            @staticmethod
            def json():
                return {"jsonrpc": "2.0", "result": "ok", "id": 1}

        return Resp()


class TestRpcRetries:
    def _client(self, fail_times):
        from ipc_proofs_tpu.store.rpc import LotusClient

        client = LotusClient(
            "http://fake",
            timeout_s=1.0,
            max_retries=3,
            session=FlakyClient(fail_times),
            metrics=Metrics(),
        )
        return client

    def test_retries_then_succeeds(self, monkeypatch):
        import time as time_module

        monkeypatch.setattr(time_module, "sleep", lambda s: None)
        client = self._client(fail_times=2)
        assert client.request("Filecoin.ChainHead", []) == "ok"
        assert client._session.calls == 3

    def test_exhausted_retries_raise(self, monkeypatch):
        import time as time_module

        monkeypatch.setattr(time_module, "sleep", lambda s: None)
        client = self._client(fail_times=10)
        with pytest.raises(RuntimeError, match="failed after 3 attempts"):
            client.request("Filecoin.ChainHead", [])

    def test_protocol_errors_not_retried(self):
        from ipc_proofs_tpu.store.rpc import RpcError

        client = self._client(fail_times=0)

        class ErrResp:
            @staticmethod
            def raise_for_status():
                pass

            @staticmethod
            def json():
                return {"error": {"code": -32601, "message": "method not found"}, "id": 1}

        class ErrSession:
            calls = 0

            def post(self, *a, **k):
                ErrSession.calls += 1
                return ErrResp()

        client._session = ErrSession()
        with pytest.raises(RpcError):
            client.request("Filecoin.Nope", [])
        assert ErrSession.calls == 1
