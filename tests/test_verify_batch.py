"""Device-batched multihash verification tests: the differential grid
pinning `verify_blocks_batch` verdict-identical to `verify_block_bytes`
over every supported multihash code × message size × corrupt-bit position
(on both the device and scalar lanes), plus the read-path wiring —
SegmentStore.get_many / verify_scan, the fetch plane's landed-wave batch
verify, and the chain follower's prefetch wave. All hermetic and tier-1
(JAX_PLATFORMS=cpu: the "device" lane is XLA-on-CPU, same kernels)."""

import pytest

from ipc_proofs_tpu.core.cid import (
    BLAKE2B_256,
    CID,
    DAG_CBOR,
    IDENTITY,
    KECCAK_256,
    SHA2_256,
)
from ipc_proofs_tpu.core.hashes import keccak256
from ipc_proofs_tpu.ops.verify_jax import batch_min_bytes, verify_blocks_batch
from ipc_proofs_tpu.store.rpc import verify_block_bytes
from ipc_proofs_tpu.utils.metrics import Metrics

UNKNOWN_CODE = 0x15  # no verifier for it: accepted by contract

# straddles the blake2b (128 B) and keccak (136 B) block boundaries
SIZES = (0, 1, 100, 127, 128, 129, 136, 137, 300, 1500)


def _cid_for(code: int, data: bytes) -> CID:
    if code == KECCAK_256:
        return CID(1, DAG_CBOR, KECCAK_256, keccak256(data))
    if code == UNKNOWN_CODE:
        return CID(1, DAG_CBOR, UNKNOWN_CODE, b"\x00" * 32)
    return CID.hash_of(data, mh_code=code)


def _flip(data: bytes, bit: int) -> bytes:
    byte, off = divmod(bit, 8)
    return data[:byte] + bytes([data[byte] ^ (1 << off)]) + data[byte + 1 :]


def _grid() -> "tuple[list[CID], list[bytes]]":
    """Every code × size, plus corrupt variants with a bit flipped at the
    start, middle, and end of the payload."""
    cids, blocks = [], []
    for code in (BLAKE2B_256, SHA2_256, KECCAK_256, IDENTITY, UNKNOWN_CODE):
        for size in SIZES:
            data = bytes((i * 31 + size + code) % 256 for i in range(size))
            cids.append(_cid_for(code, data))
            blocks.append(data)
            if size == 0:
                continue
            nbits = size * 8
            for bit in (0, nbits // 2, nbits - 1):
                cids.append(_cid_for(code, data))
                blocks.append(_flip(data, bit))
    return cids, blocks


class TestDifferentialGrid:
    @pytest.mark.parametrize("lane", ["device", "scalar"])
    def test_batch_equals_scalar_verdicts(self, lane, monkeypatch):
        monkeypatch.setenv(
            "IPC_VERIFY_MIN_BYTES", "0" if lane == "device" else "999999999"
        )
        cids, blocks = _grid()
        m = Metrics()
        got = verify_blocks_batch(cids, blocks, metrics=m)
        want = [verify_block_bytes(c, b) for c, b in zip(cids, blocks)]
        assert got == want
        counters = m.snapshot()["counters"]
        assert counters["verify.batch_blocks"] == len(cids)
        if lane == "device":
            assert counters["verify.device_calls"] >= 1
        else:
            assert counters.get("verify.device_calls", 0) == 0

    @pytest.mark.parametrize("lane", ["device", "scalar"])
    def test_every_flipped_bit_is_caught(self, lane, monkeypatch):
        """For the verified codes, EVERY corrupt variant must fail — one
        undetected bit flip is an integrity hole, not a rounding error."""
        monkeypatch.setenv(
            "IPC_VERIFY_MIN_BYTES", "0" if lane == "device" else "999999999"
        )
        cids, blocks = _grid()
        got = verify_blocks_batch(cids, blocks)
        for cid, data, ok in zip(cids, blocks, got):
            if cid.mh_code == UNKNOWN_CODE:
                assert ok is True  # unknown codes are accepted by contract
                continue
            expect = verify_block_bytes(cid, data)
            assert ok == expect, (cid.mh_code, len(data))
        # at least one corrupt variant exists per verified code and none pass
        for code in (BLAKE2B_256, SHA2_256, KECCAK_256, IDENTITY):
            bad = [
                ok
                for cid, data, ok in zip(cids, blocks, got)
                if cid.mh_code == code and not verify_block_bytes(cid, data)
            ]
            assert bad and not any(bad)

    def test_size_class_mix_one_huge_block(self, monkeypatch):
        """A single huge block must not inflate the small blocks' padding —
        and must not change anyone's verdict (size-class chunking)."""
        monkeypatch.setenv("IPC_VERIFY_MIN_BYTES", "0")
        small = [b"s%02d" % i * 20 for i in range(40)]
        huge = bytes(range(256)) * 64  # 16 KiB: a different pow2 class
        blocks = small + [huge]
        cids = [CID.hash_of(b) for b in blocks]
        m = Metrics()
        assert verify_blocks_batch(cids, blocks, metrics=m) == [True] * len(blocks)
        counters = m.snapshot()["counters"]
        assert counters["verify.device_calls"] == 2  # one per size class

    def test_empty_and_mismatched_inputs(self):
        assert verify_blocks_batch([], []) == []
        with pytest.raises(ValueError):
            verify_blocks_batch([CID.hash_of(b"x")], [])

    def test_crossover_default_sends_small_batches_scalar(self, monkeypatch):
        monkeypatch.delenv("IPC_VERIFY_MIN_BYTES", raising=False)
        assert batch_min_bytes() == 256 * 1024
        blocks = [b"tiny-%d" % i for i in range(4)]
        cids = [CID.hash_of(b) for b in blocks]
        m = Metrics()
        assert verify_blocks_batch(cids, blocks, metrics=m) == [True] * 4
        counters = m.snapshot()["counters"]
        assert counters.get("verify.device_calls", 0) == 0
        assert counters["verify.scalar_blocks"] == 4


class TestSegmentStoreWiring:
    def _blocks(self, n):
        return [
            (CID.hash_of((b"seg-%03d-" % i) * (i % 4 + 2)), (b"seg-%03d-" % i) * (i % 4 + 2))
            for i in range(n)
        ]

    def test_get_many_matches_scalar_gets(self, tmp_path, monkeypatch):
        monkeypatch.setenv("IPC_VERIFY_MIN_BYTES", "0")
        from ipc_proofs_tpu.storex import SegmentStore

        m = Metrics()
        store = SegmentStore(str(tmp_path), metrics=m, batch_verify=True)
        blocks = self._blocks(12)
        for cid, data in blocks:
            store.put(cid, data)
        missing = CID.hash_of(b"never stored")
        got = store.get_many([c for c, _ in blocks] + [missing])
        assert got == {c: d for c, d in blocks}
        counters = m.snapshot()["counters"]
        assert counters["storex.disk_hits"] == 12
        assert counters["storex.disk_misses"] == 1
        assert counters["verify.batch_calls"] == 1
        store.close()

    def test_get_many_evicts_multihash_liars(self, tmp_path):
        from ipc_proofs_tpu.storex import SegmentStore

        m = Metrics()
        store = SegmentStore(str(tmp_path), metrics=m, batch_verify=True)
        honest = self._blocks(3)
        for cid, data in honest:
            store.put(cid, data)
        liar = CID.hash_of(b"the bytes this cid claims")
        store.put(liar, b"entirely different bytes")  # frame CRC still valid
        got = store.get_many([c for c, _ in honest] + [liar])
        assert got == {c: d for c, d in honest}
        assert liar not in got
        counters = m.snapshot()["counters"]
        assert counters["storex.integrity_evictions"] == 1
        assert not store.contains(liar)  # dropped, same as a scalar get
        store.close()

    def test_verify_scan_drops_liars_at_open(self, tmp_path):
        from ipc_proofs_tpu.storex import SegmentStore

        store = SegmentStore(str(tmp_path))
        honest = self._blocks(4)
        for cid, data in honest:
            store.put(cid, data)
        liar = CID.hash_of(b"claimed content")
        store.put(liar, b"actual content")
        store.close()

        m = Metrics()
        reopened = SegmentStore(
            str(tmp_path), metrics=m, batch_verify=True, verify_scan=True
        )
        assert not reopened.contains(liar)
        for cid, data in honest:
            assert reopened.get(cid) == data
        assert m.snapshot()["counters"]["storex.integrity_evictions"] == 1
        reopened.close()


class TestFetchPlaneWiring:
    def test_landed_wave_batch_verifies(self, monkeypatch):
        monkeypatch.setenv("IPC_VERIFY_MIN_BYTES", "0")
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore
        from ipc_proofs_tpu.store.faults import LocalLotusSession
        from ipc_proofs_tpu.store.fetchplane import FetchPlane
        from ipc_proofs_tpu.store.rpc import IntegrityError, LotusClient

        blocks = [
            (CID.hash_of(b"plane-%d-" % i * 3), b"plane-%d-" % i * 3)
            for i in range(6)
        ]
        bs = MemoryBlockstore()
        for cid, data in blocks:
            bs.put_keyed(cid, data)
        liar = CID.hash_of(b"honest plane bytes")
        bs.put_keyed(liar, b"corrupt plane bytes")
        m = Metrics()
        client = LotusClient(
            "http://verify-batch-test", session=LocalLotusSession(bs), metrics=m
        )
        with FetchPlane(client, local={}, metrics=m, batch_verify=True) as plane:
            for cid, data in blocks:
                assert plane.get(cid) == data
            with pytest.raises(IntegrityError):
                plane.get(liar)
        counters = m.snapshot()["counters"]
        assert counters["verify.batch_calls"] >= 1
        assert counters["rpc.integrity_failures"] >= 1

    def test_batch_verify_off_is_the_default_scalar_path(self):
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore
        from ipc_proofs_tpu.store.faults import LocalLotusSession
        from ipc_proofs_tpu.store.fetchplane import FetchPlane
        from ipc_proofs_tpu.store.rpc import LotusClient

        cid = CID.hash_of(b"default-path block")
        bs = MemoryBlockstore()
        bs.put_keyed(cid, b"default-path block")
        m = Metrics()
        client = LotusClient(
            "http://verify-default-test", session=LocalLotusSession(bs), metrics=m
        )
        with FetchPlane(client, local={}, metrics=m) as plane:
            assert plane.get(cid) == b"default-path block"
        assert m.snapshot()["counters"].get("verify.batch_calls", 0) == 0


class TestFollowerWiring:
    def test_prefetch_wave_batch_verifies_and_skips_liars(self, monkeypatch):
        monkeypatch.setenv("IPC_VERIFY_MIN_BYTES", "0")
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore
        from ipc_proofs_tpu.store.faults import LocalLotusSession
        from ipc_proofs_tpu.store.rpc import LotusClient
        from ipc_proofs_tpu.storex import ChainFollower

        blocks = [
            (CID.hash_of(b"follow-%d-" % i * 4), b"follow-%d-" % i * 4)
            for i in range(5)
        ]
        bs = MemoryBlockstore()
        for cid, data in blocks:
            bs.put_keyed(cid, data)
        liar = CID.hash_of(b"honest follower bytes")
        bs.put_keyed(liar, b"corrupt follower bytes")
        m = Metrics()
        client = LotusClient(
            "http://follower-batch-test", session=LocalLotusSession(bs), metrics=m
        )
        local = MemoryBlockstore()
        follower = ChainFollower(client, local, metrics=m, batch_verify=True)
        out = follower._fetch_blocks([c for c, _ in blocks] + [liar])
        assert out == {c: d for c, d in blocks}
        assert local.get(liar) is None  # the liar never reached the store
        counters = m.snapshot()["counters"]
        assert counters["verify.batch_calls"] >= 1
        assert counters["follow.blocks_prefetched"] == 5
        assert counters["follow.errors"] == 1
