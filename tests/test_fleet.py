"""Fleet observability plane tests: tenant extraction + bounded top-K
accounting, snapshot merge math, the federated Prometheus exposition,
fail-soft federation scrapes, the router's fleet HTTP surfaces
(``/metrics.prom``, ``/metrics.json``, ``/v1/cluster/status``,
``/debug/flight``), cross-process span grafting, and the end-to-end
stitch: a sampled scatter through REAL subprocess shards collapses into
ONE rooted span tree in the router's collector. All tier-1."""

import json
import os
import re
import time
import urllib.request

import pytest

from ipc_proofs_tpu.cluster import (
    ClusterRouter,
    LocalShard,
    RouterHTTPServer,
    spawn_serve_shard,
)
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.obs import disable_tracing, enable_tracing
from ipc_proofs_tpu.obs.fleet import (
    FleetFederation,
    TenantLedger,
    extract_tenant,
    graft_spans,
    merge_counters,
    merge_flight_snapshots,
    merge_gauges,
    merge_histograms,
    render_fleet_prometheus,
)
from ipc_proofs_tpu.obs.flight import get_flight_recorder
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.utils.metrics import Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001


@pytest.fixture(scope="module")
def world():
    return build_range_world(
        4, 4, 2, 0.3, signature=SIG, topic1=SUBNET, actor_id=ACTOR,
        base_height=61_000,
    )


def _spec():
    return EventProofSpec(
        event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR
    )


def _http(url, body=None, headers=None, timeout=30):
    """(status, parsed-or-text, content_type) for one request; POSTs JSON
    when ``body`` is given."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, headers=dict(headers or {}))
    if data is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read().decode()
        ctype = resp.headers.get("Content-Type", "")
        parsed = json.loads(raw) if "json" in ctype else raw
        return resp.status, parsed, ctype


# Strict 0.0.4 exposition check (same contract test_obs pins for the
# single-process exposition, applied to the fleet render).
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\")*\})?"
    r" -?[0-9.e+-]+(\.[0-9]+)?$"
)


def _check_prom_text(text: str) -> "dict[str, str]":
    types: "dict[str, str]" = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            assert len(line.split(" ", 3)) == 4, line
        elif line.startswith("# TYPE "):
            _, _, family, kind = line.split(" ")
            assert kind in ("counter", "gauge", "summary"), line
            assert family not in types, f"duplicate TYPE for {family}"
            types[family] = kind
        else:
            assert _PROM_SAMPLE.fullmatch(line), f"malformed sample: {line!r}"
            name = line.split("{", 1)[0].split(" ", 1)[0]
            family = re.sub(r"_(total|sum|count)$", "", name)
            assert name in types or family in types, f"undeclared: {line!r}"
    return types


# --------------------------------------------------------------------------
# tenant extraction + bounded accounting
# --------------------------------------------------------------------------


class TestTenantLedger:
    def test_body_wins_over_header(self):
        assert extract_tenant(
            {"tenant": "acme"}, {"X-IPC-Tenant": "other"}
        ) == "acme"
        assert extract_tenant({}, {"X-IPC-Tenant": "acme-2"}) == "acme-2"

    def test_sanitized_and_bounded(self):
        # label-hostile characters collapse to _, length is capped
        assert extract_tenant({"tenant": 'a b/c"d'}, {}) == "a_b_c_d"
        assert extract_tenant({"tenant": "x" * 200}, {}) == "x" * 64

    def test_untenanted_is_none(self):
        assert extract_tenant({}, {}) is None
        assert extract_tenant({"tenant": ""}, {}) is None
        assert extract_tenant({"tenant": "   "}, {}) is None
        assert extract_tenant({"tenant": 7}, {}) is None
        assert extract_tenant(None, None) is None

    def test_top_k_overflow_pools_into_other(self):
        m = Metrics()
        ledger = TenantLedger(metrics=m, top_k=2)
        assert ledger.account("a", nbytes=10) == "a"
        assert ledger.account("b") == "b"
        # third distinct tenant overflows; earlier tenants keep their slot
        assert ledger.account("c", nbytes=5) == "other"
        assert ledger.account("a") == "a"
        assert ledger.account(None) == "other"  # anonymous also pools: K full
        assert ledger.known() == ["a", "b"]
        assert m.counter_value("tenant.requests.a") == 2
        assert m.counter_value("tenant.requests.other") == 2
        assert m.counter_value("tenant.bytes.a") == 10
        assert m.counter_value("tenant.bytes.other") == 5
        # zero-byte accounting must not create a bytes counter
        assert m.counter_value("tenant.bytes.b") == 0


# --------------------------------------------------------------------------
# merge math
# --------------------------------------------------------------------------


class TestMergeMath:
    def test_counters_and_gauges_sum(self):
        assert merge_counters(
            [{"a": 1, "b": 2}, {"a": 3}, None, {}]
        ) == {"a": 4, "b": 2}
        assert merge_gauges([{"depth": 2}, {"depth": 5}]) == {"depth": 7}

    def test_histograms_weighted_mean_and_max_tail(self):
        merged = merge_histograms(
            [
                {"lat": {"count": 2, "mean": 10.0, "p50": 10.0, "p99": 20.0}},
                {"lat": {"count": 6, "mean": 30.0, "p50": 25.0, "p99": 90.0}},
                {"lat": {"count": 0, "mean": 999.0, "p99": 999.0}},  # empty: skipped
            ]
        )
        assert merged["lat"]["count"] == 8
        assert merged["lat"]["mean"] == pytest.approx((10 * 2 + 30 * 6) / 8)
        # conservative fleet tail: the max across members
        assert merged["lat"]["p50"] == 25.0
        assert merged["lat"]["p99"] == 90.0

    def test_all_empty_histograms_vanish(self):
        assert merge_histograms([{"lat": {"count": 0, "mean": 1.0}}]) == {}


# --------------------------------------------------------------------------
# fleet prometheus exposition
# --------------------------------------------------------------------------


def _snap(counters=None, gauges=None, hists=None, uptime=1.0):
    out = {"counters": dict(counters or {}), "uptime_s": uptime}
    if gauges:
        out["gauges"] = dict(gauges)
    if hists:
        out["histograms"] = dict(hists)
    return out


class TestFleetPrometheus:
    def test_shard_labels_and_fleet_aggregates(self):
        text = render_fleet_prometheus(
            {
                "s0": _snap(
                    {"serve.requests": 3},
                    gauges={"serve.queue_depth.http": 2},
                    hists={"latency_ms": {"count": 2, "mean": 10.0,
                                          "p50": 10.0, "p99": 20.0}},
                ),
                "s1": _snap(
                    {"serve.requests": 5},
                    gauges={"serve.queue_depth.http": 1},
                    hists={"latency_ms": {"count": 2, "mean": 20.0,
                                          "p50": 18.0, "p99": 40.0}},
                ),
            },
            router_snap=_snap({"cluster.requests": 4}),
        )
        types = _check_prom_text(text)
        assert types["ipc_serve_requests_total"] == "counter"
        assert types["ipc_uptime_seconds"] == "gauge"
        assert types["ipc_latency_ms"] == "summary"
        assert 'ipc_serve_requests_total{shard="s0"} 3' in text
        assert 'ipc_serve_requests_total{shard="s1"} 5' in text
        assert 'ipc_serve_requests_total{shard="fleet"} 8' in text
        assert 'ipc_cluster_requests_total{shard="router"} 4' in text
        assert 'ipc_cluster_requests_total{shard="fleet"} 4' in text
        assert 'ipc_serve_queue_depth_http{shard="fleet"} 3' in text
        # merged fleet summary: max tail, count-weighted _sum, summed count
        assert 'ipc_latency_ms{shard="fleet",quantile="0.99"} 40' in text
        assert 'ipc_latency_ms_sum{shard="fleet"} 60' in text
        assert 'ipc_latency_ms_count{shard="fleet"} 4' in text

    def test_dead_shard_drops_out_but_fleet_serves(self):
        text = render_fleet_prometheus(
            {"s0": _snap({"serve.requests": 3}), "s1": None}
        )
        _check_prom_text(text)
        assert 'shard="s0"' in text
        assert 'shard="s1"' not in text
        assert 'ipc_serve_requests_total{shard="fleet"} 3' in text


# --------------------------------------------------------------------------
# federation scrape loop (injected fetch: no sockets)
# --------------------------------------------------------------------------


class _FakeShardNet:
    """In-memory shard fleet for FleetFederation's ``fetch`` hook."""

    def __init__(self):
        self.calls = []
        self.requests = 2

    def fetch(self, url, timeout_s):
        self.calls.append(url)
        if "dead" in url:
            raise OSError("connection refused")
        if url.endswith("/metrics.json"):
            return _snap({"serve.requests": self.requests})
        return {"status": "ok"}


class TestFleetFederation:
    def test_scrape_is_fail_soft_per_shard(self):
        net = _FakeShardNet()
        m = Metrics()
        urls = {"s0": "http://h/s0", "s1": "http://dead:1"}
        fed = FleetFederation(
            lambda: urls, metrics=m, interval_s=60.0, fetch=net.fetch
        )
        result = fed.scrape()
        good = result["shards"]["s0"]
        assert good["error"] is None
        assert good["metrics"]["counters"]["serve.requests"] == 2
        assert good["healthz"]["status"] == "ok"
        bad = result["shards"]["s1"]
        assert bad["metrics"] is None and bad["error"]
        assert m.counter_value("fleet.scrapes") == 2
        assert m.counter_value("fleet.scrape_errors") == 1

    def test_latest_caches_until_rescraped(self):
        net = _FakeShardNet()
        fed = FleetFederation(
            lambda: {"s0": "http://h/s0"},
            metrics=Metrics(), interval_s=60.0, fetch=net.fetch,
        )
        first = fed.latest()  # no cache yet: pull-through scrape
        n_calls = len(net.calls)
        assert fed.latest() is first  # cached, no new fetches
        assert len(net.calls) == n_calls
        net.requests = 9
        fed.scrape()
        assert (
            fed.latest()["shards"]["s0"]["metrics"]["counters"]["serve.requests"]
            == 9
        )

    def test_scrape_thread_lifecycle(self):
        net = _FakeShardNet()
        fed = FleetFederation(
            lambda: {"s0": "http://h/s0"},
            metrics=Metrics(), interval_s=0.01, fetch=net.fetch,
        )
        fed.start()
        fed.start()  # idempotent
        deadline = time.time() + 5.0
        while not net.calls and time.time() < deadline:
            time.sleep(0.01)
        fed.stop()
        assert net.calls, "scrape loop never ran"
        assert fed._thread is None


# --------------------------------------------------------------------------
# router fleet surfaces over real LocalShards + HTTP
# --------------------------------------------------------------------------


class TestRouterFleetSurfaces:
    @pytest.fixture(scope="class")
    def fleet(self, world):
        store, pairs, _ = world
        shards = [
            LocalShard(f"s{i}", store, pairs, _spec()).start()
            for i in range(2)
        ]
        router = ClusterRouter(
            {s.name: s.url for s in shards}, pairs,
            scrape_interval_s=60.0, scrape_timeout_s=5.0,
        )
        server = RouterHTTPServer(router).start()
        yield server.address, router, shards
        server.shutdown(timeout=10)
        for s in shards:
            try:
                s.stop(timeout=10)
            except Exception:
                pass

    def test_tenant_accounting_front_door_and_forwarded(self, fleet):
        base, router, shards = fleet
        st, obj, _ = _http(
            base + "/v1/generate", {"pair_index": 0, "tenant": "acme corp!"}
        )
        assert st == 200, obj
        st, obj, _ = _http(
            base + "/v1/generate", {"pair_index": 1},
            headers={"X-IPC-Tenant": "beta"},
        )
        assert st == 200, obj
        st, obj, _ = _http(base + "/v1/generate", {"pair_index": 2})
        assert st == 200, obj
        # front door: sanitized body tenant, header fallback, anonymous
        assert router.metrics.counter_value("tenant.requests.acme_corp_") == 1
        assert router.metrics.counter_value("tenant.requests.beta") == 1
        assert router.metrics.counter_value("tenant.requests.anonymous") >= 1
        assert router.metrics.counter_value("tenant.bytes.acme_corp_") > 0
        # forwarded: the owning shard accounted the SAME sanitized slot
        shard_counters = merge_counters(
            _http(s.url + "/metrics.json")[1].get("counters", {})
            for s in shards
        )
        assert shard_counters.get("tenant.requests.acme_corp_", 0) == 1
        assert shard_counters.get("tenant.requests.beta", 0) == 1

    def test_metrics_json_surface(self, fleet):
        base, _router, _shards = fleet
        st, snap, _ = _http(base + "/metrics.json")
        assert st == 200
        assert snap["counters"]["cluster.requests"] >= 1
        # the legacy route stays aliased
        st, snap2, _ = _http(base + "/metrics")
        assert st == 200 and "counters" in snap2

    def test_metrics_prom_surface(self, fleet):
        base, _router, _shards = fleet
        st, text, ctype = _http(base + "/metrics.prom")
        assert st == 200
        assert ctype.startswith("text/plain; version=0.0.4")
        _check_prom_text(text)
        for label in ('shard="s0"', 'shard="s1"', 'shard="router"',
                      'shard="fleet"'):
            assert label in text, f"missing {label}"
        assert 'ipc_serve_accepted_generate_total{shard="fleet"}' in text

    def test_cluster_status_surface(self, fleet):
        base, _router, _shards = fleet
        st, obj, _ = _http(base + "/v1/cluster/status")
        assert st == 200
        assert set(obj["ring"]) == {"s0", "s1"}
        assert all(e["alive"] for e in obj["ring"].values())
        assert set(obj["shards"]) == {"s0", "s1"}
        for entry in obj["shards"].values():
            assert entry["status"] == "ok"
            assert entry["scrape_error"] is None
        assert obj["router"]["requests"] >= 1
        assert isinstance(obj["delivery_backlog"], int)
        assert isinstance(obj["store_disk_bytes"], int)
        assert "last_finalized_epoch" in obj

    def test_debug_flight_surface(self, fleet):
        base, _router, _shards = fleet
        st, obj, _ = _http(base + "/debug/flight")
        assert st == 200
        assert obj["shards"] == ["s0", "s1"]
        assert obj["failed"] == []
        assert obj["spans"], "fleet flight view has no spans"
        assert all("shard" in sp for sp in obj["spans"])
        walls = [sp.get("wall_ts", 0.0) for sp in obj["spans"]]
        assert walls == sorted(walls, reverse=True)  # newest-first

    def test_fleet_keeps_serving_when_a_shard_dies(self, fleet):
        # LAST in the class: kills s1 for everyone after it.
        base, router, shards = fleet
        shards[1].kill()
        result = router.federation.scrape()
        assert result["shards"]["s1"]["error"]
        assert result["shards"]["s1"]["metrics"] is None
        assert router.metrics.counter_value("fleet.scrape_errors") >= 1
        st, text, _ = _http(base + "/metrics.prom")
        assert st == 200
        _check_prom_text(text)
        assert 'shard="s0"' in text  # degraded, still a fleet view
        st, obj, _ = _http(base + "/v1/cluster/status")
        assert st == 200
        assert obj["shards"]["s1"]["status"] == "unreachable"
        assert obj["shards"]["s1"]["scrape_error"]
        assert obj["shards"]["s0"]["status"] == "ok"


# --------------------------------------------------------------------------
# cross-process span grafting
# --------------------------------------------------------------------------


@pytest.fixture()
def _clean_flight_ring():
    get_flight_recorder().clear()
    yield
    get_flight_recorder().clear()


class TestGraftSpans:
    def test_remap_rebase_and_graft_point(self, _clean_flight_ring):
        m = Metrics()
        collector = enable_tracing(metrics=m)
        try:
            shipped = [
                {"name": "http.generate", "trace_id": "t9", "span_id": "1",
                 "parent_id": "77",  # router-side id: NOT in the set
                 "ts_us": 5, "dur_us": 10, "wall_ts": 1000.0,
                 "thread": "srv", "attrs": {"pair": 3}},
                {"name": "serve.generate", "trace_id": "t9", "span_id": "2",
                 "parent_id": "1", "ts_us": 6, "dur_us": 5,
                 "wall_ts": 1000.1, "thread": "wkr"},
                "not-a-dict",
                {"trace_id": "t9", "span_id": "9"},  # no name: skipped
            ]
            assert graft_spans(shipped, "s0", metrics=m) == 2
            spans = {s.span_id: s for s in collector.snapshot()}
            assert set(spans) == {"s0:1", "s0:2"}
            # the out-of-set parent is the graft point, kept verbatim;
            # the in-set parent follows its child into the namespace
            assert spans["s0:1"].parent_id == "77"
            assert spans["s0:2"].parent_id == "s0:1"
            assert spans["s0:1"].attrs == {"pair": 3, "shard": "s0"}
            assert spans["s0:1"].thread_name == "s0/srv"
            assert spans["s0:1"].dur_us == 10
            assert all(s.sampled for s in spans.values())
            assert m.counter_value("fleet.spans_grafted") == 2
        finally:
            disable_tracing()

    def test_router_skips_same_pid_subtrees(self, world, _clean_flight_ring):
        """A LocalShard lives in the router's process: its spans are
        already on the spine, so grafting its shipped subtree would
        double-record every span."""
        _, pairs, _ = world
        router = ClusterRouter({"s0": "http://127.0.0.1:9"}, pairs)
        collector = enable_tracing(metrics=Metrics())
        try:
            ship = {"name": "http.generate", "trace_id": "t1", "span_id": "4",
                    "parent_id": "", "ts_us": 0, "dur_us": 1, "wall_ts": 1.0,
                    "thread": "srv"}
            same = {"ok": 1, "spans": [dict(ship)], "spans_pid": os.getpid()}
            router._graft_shard_spans("s0", same)
            assert "spans" not in same and "spans_pid" not in same  # stripped
            assert collector.snapshot() == []
            other = {"ok": 1, "spans": [dict(ship)],
                     "spans_pid": os.getpid() + 1}
            router._graft_shard_spans("s0", other)
            assert [s.span_id for s in collector.snapshot()] == ["s0:4"]
        finally:
            disable_tracing()
            router.close()


# --------------------------------------------------------------------------
# end-to-end stitch: subprocess shards → one rooted tree
# --------------------------------------------------------------------------


class TestEndToEndStitch:
    def test_sampled_scatter_collapses_into_one_rooted_tree(self):
        """The distributed-tracing law: a sampled ``generate_range``
        through REAL serve children ships each shard's span subtree back
        in-band, and the router grafts every one under its scatter spans
        — the collector holds exactly ONE rooted tree, no orphans."""
        n_pairs, receipts, match_rate = 4, 4, 0.5
        _store, pairs, _ = build_range_world(
            n_pairs, receipts_per_pair=receipts, match_rate=match_rate,
            signature=SIG, topic1=SUBNET,
        )
        m = Metrics()
        collector = enable_tracing(metrics=m)
        shards = []
        try:
            shards = [
                spawn_serve_shard(
                    f"s{k}", n_pairs, SIG, SUBNET,
                    extra_args=[
                        "--demo-receipts", str(receipts),
                        "--demo-match-rate", str(match_rate),
                        "--trace-out", os.devnull,
                        "--trace-sample", "1.0",
                    ],
                )
                for k in range(2)
            ]
            router = ClusterRouter(
                {s.name: s.url for s in shards}, pairs, metrics=m
            )
            try:
                status, obj = router.generate_range(
                    list(range(n_pairs)), chunk_size=2
                )
                assert status == 200, obj
                trace_id = obj["trace_id"]
                spans = [
                    s for s in collector.snapshot()
                    if s.trace_id == trace_id
                ]
                ids = {s.span_id for s in spans}
                roots = [
                    s for s in spans
                    if not s.parent_id or s.parent_id not in ids
                ]
                assert len(roots) == 1, sorted(
                    (s.name, s.span_id, s.parent_id) for s in roots
                )
                assert roots[0].name == "cluster.generate_range"
                grafted = [s for s in spans if ":" in s.span_id]
                assert grafted, "no shard subtrees were grafted"
                assert {s.span_id.split(":", 1)[0] for s in grafted} <= {
                    "s0", "s1"
                }
                assert {s.attrs.get("shard") for s in grafted} <= {"s0", "s1"}
                assert any(s.name == "http.generate_range" for s in grafted)
                assert m.counter_value("fleet.spans_grafted") >= len(grafted)
            finally:
                router.close()
        finally:
            disable_tracing()
            for s in shards:
                try:
                    s.stop(timeout_s=20.0)
                except Exception:
                    s.kill()
