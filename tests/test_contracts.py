"""Contract-parity tests: `contracts/TopdownMessenger.sol` vs the Python model.

The Foundry toolchain is absent in this environment (NOTES_r05.md), so the
forge test (`contracts/test/TopdownMessenger.t.sol`) cannot run here. These
tests assert the SAME three proof-relevant invariants offline:

1. slot-0 mapping layout — the nonce for a subnet lives at
   ``keccak256(abi.encode(subnetId, uint256(0)))``;
2. pre-increment emission — after ``trigger``, the stored nonce equals the
   last emitted event's nonce;
3. topic shape — topic0 is ``keccak256("NewTopDownMessage(bytes32,uint256)")``
   and topic1 the raw indexed bytes32 subnet id;

and additionally run BOTH proof engines over a fixture world built from the
modeled post-`trigger` state, checking that a storage proof and an event
proof over the same checkpoint agree — the parity the reference's Foundry
project (zero tests) never established. Reference:
``topdown-messenger/src/TopdownMessenger.sol:1-33``.
"""

import re
from pathlib import Path

from ipc_proofs_tpu.core.hashes import keccak256
from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
from ipc_proofs_tpu.proofs.event_verifier import create_event_filter
from ipc_proofs_tpu.proofs.generator import (
    EventProofSpec,
    StorageProofSpec,
    generate_proof_bundle,
)
from ipc_proofs_tpu.proofs.trust import TrustPolicy
from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle
from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature
from ipc_proofs_tpu.state.storage import calculate_storage_slot, compute_mapping_slot

_SOL = Path(__file__).resolve().parent.parent / "contracts" / "TopdownMessenger.sol"

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "subnet-a"
ACTOR = 7001


def _model_trigger(storage: dict, subnet32: bytes, count: int) -> list[int]:
    """The Solidity `trigger` body, modeled: returns emitted nonces."""
    slot = compute_mapping_slot(subnet32, 0)
    nonce = int.from_bytes(storage.get(slot, b""), "big")
    emitted = []
    for _ in range(count):
        nonce += 1  # pre-increment: bump BEFORE emit
        emitted.append(nonce)
    storage[slot] = nonce.to_bytes(32, "big")
    return emitted


class TestSourceInvariants:
    """Light static checks that the .sol source declares the shapes the
    model assumes — if the contract is edited incompatibly, these fail
    before any chain deploy would."""

    def test_subnets_is_first_state_variable(self):
        src = _SOL.read_text()
        body = src.split("contract TopdownMessenger", 1)[1]
        decls = re.findall(
            r"^\s*(mapping\([^)]*\)|uint\d*|bytes\d*|address|bool)\s+"
            r"(?:public\s+|private\s+|internal\s+)?(\w+)\s*;",
            body,
            re.M,
        )
        assert decls, "no state variable declarations found"
        kind, name = decls[0]
        assert name == "subnets" and kind.startswith("mapping(bytes32")

    def test_event_signature_and_emission_order(self):
        src = _SOL.read_text()
        assert "event NewTopDownMessage(bytes32 indexed subnetId, uint256 nonce)" in src
        body = src.split("function trigger", 1)[1].split("}", 2)[-2]
        # the nonce += 1 must textually precede the emit inside the loop
        bump = src.index("nonce += 1")
        emit = src.index("emit NewTopDownMessage")
        assert bump < emit

    def test_topic0_is_signature_keccak(self):
        assert hash_event_signature(SIG) == keccak256(SIG.encode())


class TestSlotLayout:
    def test_mapping_slot_is_solidity_abi_encoding(self):
        """compute_mapping_slot == keccak256(abi.encode(key, uint256(0)))
        — computed here from first principles (32-byte key ++ 32-byte
        zero-padded slot index), the layout `vm.load` would read."""
        key32 = ascii_to_bytes32(SUBNET)
        abi_encoded = key32 + (0).to_bytes(32, "big")
        assert compute_mapping_slot(key32, 0) == keccak256(abi_encoded)
        assert calculate_storage_slot(SUBNET, 0) == keccak256(abi_encoded)


class TestTriggerParity:
    def test_model_pre_increment(self):
        storage: dict = {}
        sub32 = ascii_to_bytes32(SUBNET)
        assert _model_trigger(storage, sub32, 3) == [1, 2, 3]
        assert _model_trigger(storage, sub32, 2) == [4, 5]
        slot = compute_mapping_slot(sub32, 0)
        assert int.from_bytes(storage[slot], "big") == 5  # storage == last nonce

    def test_storage_and_event_proofs_agree_after_trigger(self):
        """The forge test's invariant, proven through the PROOF ENGINES:
        build the post-trigger chain state, generate a storage proof of the
        nonce slot and event proofs of the emissions, verify both, and
        check the storage value equals the last event's nonce."""
        storage: dict = {}
        sub32 = ascii_to_bytes32(SUBNET)
        emitted = _model_trigger(storage, sub32, 3)
        events = [
            [
                EventFixture(
                    emitter=ACTOR,
                    signature=SIG,
                    topic1=SUBNET,
                    data=n.to_bytes(32, "big"),
                )
                for n in emitted
            ]
        ]
        world = build_chain(
            [ContractFixture(actor_id=ACTOR, storage=dict(storage))], events
        )
        slot = compute_mapping_slot(sub32, 0)
        bundle = generate_proof_bundle(
            world.store,
            world.parent,
            world.child,
            [StorageProofSpec(actor_id=ACTOR, slot=slot)],
            [EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)],
        )
        assert len(bundle.event_proofs) == len(emitted)
        result = verify_proof_bundle(
            bundle,
            TrustPolicy.accept_all(),
            event_filter=create_event_filter(SIG, SUBNET),
        )
        assert result.all_valid()
        stored_nonce = int(bundle.storage_proofs[0].value, 16)
        last_event_nonce = int.from_bytes(
            bytes.fromhex(bundle.event_proofs[-1].event_data.data.removeprefix("0x")),
            "big",
        )
        assert stored_nonce == last_event_nonce == emitted[-1]
