"""Batched storage verifier ↔ scalar verifier equivalence.

`verify_storage_proofs_batch` must return exactly the scalar loop's
verdicts — on valid bundles across every storage encoding, on every tamper
case, and on pruned witnesses — and raise where the scalar path raises.
"""

import dataclasses

import pytest

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
from ipc_proofs_tpu.ipld.hamt import hamt_get_batch
from ipc_proofs_tpu.proofs.generator import StorageProofSpec, generate_proof_bundle
from ipc_proofs_tpu.proofs.storage_verifier import (
    verify_storage_proof,
    verify_storage_proofs_batch,
)
from ipc_proofs_tpu.proofs.witness import load_witness_store
from ipc_proofs_tpu.state.storage import calculate_storage_slot
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

ACCEPT = lambda *_: True


def _native_or_skip():
    if hamt_get_batch(MemoryBlockstore(), [], [], []) is None:
        pytest.skip("native hamt_lookup_batch unavailable")


def make_storage_bundle(encodings=("direct",), n_slots=3):
    bs = MemoryBlockstore()
    contracts = []
    specs = []
    for c, enc in enumerate(encodings):
        storage = {}
        for i in range(n_slots):
            slot = calculate_storage_slot(f"sub-{c}-{i}", 0)
            storage[slot] = (c * 10 + i + 1).to_bytes(2, "big")
        contracts.append(
            ContractFixture(actor_id=100 + c, storage=storage, storage_encoding=enc)
        )
        for i in range(n_slots):
            specs.append(
                StorageProofSpec(
                    actor_id=100 + c, slot=calculate_storage_slot(f"sub-{c}-{i}", 0)
                )
            )
        # an absent slot too — proves the zero-value path
        specs.append(
            StorageProofSpec(
                actor_id=100 + c, slot=calculate_storage_slot(f"sub-{c}-absent", 7)
            )
        )
    world = build_chain(
        contracts, [[EventFixture(emitter=100, signature="E()", topic1="x")]], store=bs
    )
    bundle = generate_proof_bundle(bs, world.parent, world.child, specs, [])
    assert len(bundle.storage_proofs) == len(specs)
    return bundle


def both_paths(bundle, trust=ACCEPT):
    store = load_witness_store(bundle.blocks, verify_cids=False)
    scalar = [
        verify_storage_proof(p, bundle.blocks, trust, store=store)
        for p in bundle.storage_proofs
    ]
    batch = verify_storage_proofs_batch(store, bundle.storage_proofs, trust)
    assert batch is not None
    assert scalar == batch, f"scalar={scalar} batch={batch}"
    return batch


class TestStorageBatchEquivalence:
    def test_valid_bundle_all_encodings(self):
        _native_or_skip()
        bundle = make_storage_bundle(
            encodings=("direct", "wrapper_tuple", "wrapper_map", "inline")
        )
        assert all(both_paths(bundle))

    def test_trust_rejection_per_proof(self):
        _native_or_skip()
        bundle = make_storage_bundle()
        reject = lambda *_: False
        assert not any(both_paths(bundle, trust=reject))

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda p: dataclasses.replace(p, value="0x" + "ab" * 32),
            lambda p: dataclasses.replace(p, actor_id=p.actor_id + 1),
            lambda p: dataclasses.replace(
                p, parent_state_root=str(CID.hash_of(b"wrong-root"))
            ),
            lambda p: dataclasses.replace(
                p, actor_state_cid=str(CID.hash_of(b"wrong-actor-state"))
            ),
            lambda p: dataclasses.replace(
                p, storage_root=str(CID.hash_of(b"wrong-storage-root"))
            ),
            # NOTE: a child_epoch tamper alone is accepted under accept-all
            # trust in BOTH paths — epoch binding is the trust policy's job
            # (reference storage/verifier.rs anchors (epoch, cid) via the
            # policy closure only); covered by the epoch-binding case below.
        ],
    )
    def test_tampered_proof_fails_both_paths(self, mutate):
        _native_or_skip()
        bundle = make_storage_bundle()
        proofs = [mutate(bundle.storage_proofs[0]), *bundle.storage_proofs[1:]]
        patched = dataclasses.replace(bundle, storage_proofs=proofs)
        res = both_paths(patched)
        assert res[0] is False
        assert all(res[1:])

    def test_case_insensitive_value_compare(self):
        _native_or_skip()
        bundle = make_storage_bundle()
        p = bundle.storage_proofs[0]
        shouty = dataclasses.replace(p, value=p.value.upper().replace("0X", "0x"))
        patched = dataclasses.replace(
            bundle, storage_proofs=[shouty, *bundle.storage_proofs[1:]]
        )
        assert both_paths(patched)[0] is True

    def test_missing_state_root_block_false_both_paths(self):
        _native_or_skip()
        bundle = make_storage_bundle()
        pruned_blocks = [
            b
            for b in bundle.blocks
            if str(b.cid) != bundle.storage_proofs[0].parent_state_root
        ]
        assert len(pruned_blocks) == len(bundle.blocks) - 1
        store = load_witness_store(pruned_blocks, verify_cids=False)
        scalar = [
            verify_storage_proof(p, pruned_blocks, ACCEPT, store=store)
            for p in bundle.storage_proofs
        ]
        batch = verify_storage_proofs_batch(store, bundle.storage_proofs, ACCEPT)
        assert scalar == batch == [False] * len(bundle.storage_proofs)

    def test_missing_child_header_raises_both_paths(self):
        _native_or_skip()
        bundle = make_storage_bundle()
        child_str = bundle.storage_proofs[0].child_block_cid
        pruned = [b for b in bundle.blocks if str(b.cid) != child_str]
        store = load_witness_store(pruned, verify_cids=False)
        with pytest.raises(KeyError):
            for p in bundle.storage_proofs:
                verify_storage_proof(p, pruned, ACCEPT, store=store)
        with pytest.raises(KeyError):
            verify_storage_proofs_batch(store, bundle.storage_proofs, ACCEPT)

    def test_malformed_slot_hex_raises_both_paths(self):
        _native_or_skip()
        bundle = make_storage_bundle()
        bad = dataclasses.replace(bundle.storage_proofs[0], slot="0x1234")
        store = load_witness_store(bundle.blocks, verify_cids=False)
        with pytest.raises(ValueError):
            verify_storage_proof(bad, bundle.blocks, ACCEPT, store=store)
        with pytest.raises(ValueError):
            verify_storage_proofs_batch(store, [bad], ACCEPT)

    def test_unified_bundle_routes_through_batch(self):
        _native_or_skip()
        from ipc_proofs_tpu.proofs.trust import TrustPolicy
        from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle

        bundle = make_storage_bundle(encodings=("direct", "inline"))
        result = verify_proof_bundle(bundle, TrustPolicy.accept_all())
        assert all(result.storage_results)
        assert len(result.storage_results) == len(bundle.storage_proofs)


def test_epoch_binding_enforced_by_trust_policy_identically():
    """child_epoch tampering is caught by an epoch-binding trust policy,
    not by the replay — and identically on both paths."""
    _native_or_skip()
    bundle = make_storage_bundle()
    true_epoch = bundle.storage_proofs[0].child_epoch
    bound = lambda epoch, cid: epoch == true_epoch
    import dataclasses as dc

    tampered = dc.replace(
        bundle,
        storage_proofs=[
            dc.replace(bundle.storage_proofs[0], child_epoch=true_epoch + 5),
            *bundle.storage_proofs[1:],
        ],
    )
    res = both_paths(tampered, trust=bound)
    assert res[0] is False and all(res[1:])
