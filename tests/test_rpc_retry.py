"""LotusClient retry/timeout behavior: bounded full-jitter exponential
backoff on transport errors, fail-fast block-fetch deadline, retry
counters, retry of transient JSON-RPC codes (rate limits), and no retry on
semantic RpcError — all via an injected fake session (no `requests`
dependency) and an injected rng (deterministic backoff)."""

import base64
import random

import pytest

from ipc_proofs_tpu.store import rpc as rpc_mod
from ipc_proofs_tpu.store.rpc import LotusClient, RpcError
from ipc_proofs_tpu.utils.metrics import Metrics


class _MaxJitterRng:
    """Stands in for the client's backoff rng: always draws the upper
    bound, so tests can assert the exact exponential envelope."""

    def uniform(self, lo, hi):
        return hi


class _Response:
    def __init__(self, result=None, error=None):
        self._body = {"jsonrpc": "2.0", "result": result, "id": 1}
        if error is not None:
            self._body["error"] = error

    def raise_for_status(self):
        pass

    def json(self):
        return self._body


class _FlakySession:
    """Raises a transport error for the first ``fail_times`` posts, then
    answers with ``result``. Records every timeout the client passed."""

    def __init__(self, fail_times=0, result=None, error=None):
        self.fail_times = fail_times
        self.result = result
        self.error = error
        self.posts = 0
        self.timeouts: list[float] = []

    def post(self, endpoint, data=None, headers=None, timeout=None):
        self.posts += 1
        self.timeouts.append(timeout)
        if self.posts <= self.fail_times:
            raise ConnectionError(f"transport down (post {self.posts})")
        return _Response(result=self.result, error=self.error)


def _client(session, metrics, **kw):
    kw.setdefault("max_retries", 4)
    kw.setdefault("rng", _MaxJitterRng())
    return LotusClient("http://fake", session=session, metrics=metrics, **kw)


class TestRetries:
    def test_transport_errors_retry_then_succeed(self, monkeypatch):
        sleeps: list[float] = []
        monkeypatch.setattr(rpc_mod.time, "sleep", sleeps.append)
        m = Metrics()
        session = _FlakySession(fail_times=2, result="ok")
        client = _client(session, m, backoff_base_s=0.25, backoff_max_s=10.0)
        assert client.request("Filecoin.Thing", []) == "ok"
        assert session.posts == 3
        assert m.snapshot()["counters"]["rpc.retries"] == 2
        # exponential envelope: base * 2**attempt (rng pinned to the bound)
        assert sleeps == [0.25, 0.5]

    def test_backoff_is_bounded(self, monkeypatch):
        sleeps: list[float] = []
        monkeypatch.setattr(rpc_mod.time, "sleep", sleeps.append)
        m = Metrics()
        session = _FlakySession(fail_times=5, result="ok")
        client = _client(
            session, m, max_retries=6, backoff_base_s=1.0, backoff_max_s=3.0
        )
        assert client.request("Filecoin.Thing", []) == "ok"
        assert sleeps == [1.0, 2.0, 3.0, 3.0, 3.0]  # capped at backoff_max_s

    def test_backoff_is_full_jitter(self, monkeypatch):
        # with a real rng every sleep is uniform in [0, envelope]: never
        # above the exponential bound, and (over 5 draws with a seeded rng)
        # not all AT the bound — the thundering-herd fix is actually live
        sleeps: list[float] = []
        monkeypatch.setattr(rpc_mod.time, "sleep", sleeps.append)
        session = _FlakySession(fail_times=5, result="ok")
        client = _client(
            session, Metrics(), max_retries=6,
            backoff_base_s=1.0, backoff_max_s=3.0, rng=random.Random(7),
        )
        assert client.request("Filecoin.Thing", []) == "ok"
        envelopes = [1.0, 2.0, 3.0, 3.0, 3.0]
        assert len(sleeps) == len(envelopes)
        assert all(0.0 <= s <= e for s, e in zip(sleeps, envelopes))
        assert sleeps != envelopes

    def test_exhaustion_raises_and_counts_failure(self, monkeypatch):
        monkeypatch.setattr(rpc_mod.time, "sleep", lambda s: None)
        m = Metrics()
        session = _FlakySession(fail_times=99)
        client = _client(session, m, max_retries=3)
        with pytest.raises(RuntimeError, match="failed after 3 attempts"):
            client.request("Filecoin.Thing", [])
        assert session.posts == 3
        counters = m.snapshot()["counters"]
        assert counters["rpc.retries"] == 2  # sleeps between the 3 attempts
        assert counters["rpc.failures"] == 1

    def test_rpc_error_is_not_retried(self, monkeypatch):
        monkeypatch.setattr(
            rpc_mod.time, "sleep",
            lambda s: pytest.fail("must not sleep on protocol errors"),
        )
        m = Metrics()
        session = _FlakySession(error={"code": -32601, "message": "no such method"})
        client = _client(session, m)
        with pytest.raises(RpcError, match="-32601"):
            client.request("Filecoin.Nope", [])
        assert session.posts == 1
        assert "rpc.retries" not in m.snapshot()["counters"]


class _RateLimitedSession:
    """Returns a JSON-RPC error for the first ``error_times`` posts, then a
    result — a node shedding load, not a node that can't answer."""

    def __init__(self, error, error_times=2, result="ok"):
        self.error = error
        self.error_times = error_times
        self.result = result
        self.posts = 0

    def post(self, endpoint, data=None, headers=None, timeout=None):
        self.posts += 1
        if self.posts <= self.error_times:
            return _Response(error=self.error)
        return _Response(result=self.result)


class TestRetryableRpcCodes:
    """Transient protocol errors (rate limits) retry like transport faults;
    everything else at the protocol level stays fail-fast."""

    def test_rate_limit_code_is_retried(self, monkeypatch):
        monkeypatch.setattr(rpc_mod.time, "sleep", lambda s: None)
        m = Metrics()
        session = _RateLimitedSession({"code": 429, "message": "slow down"})
        client = _client(session, m)
        assert client.request("Filecoin.Thing", []) == "ok"
        assert session.posts == 3
        assert m.snapshot()["counters"]["rpc.retries"] == 2

    def test_rate_limit_message_marker_is_retried(self, monkeypatch):
        # some gateways send rate-limit text under a generic code
        monkeypatch.setattr(rpc_mod.time, "sleep", lambda s: None)
        session = _RateLimitedSession(
            {"code": 1, "message": "Too Many Requests, try later"}
        )
        client = _client(session, Metrics())
        assert client.request("Filecoin.Thing", []) == "ok"
        assert session.posts == 3

    def test_rate_limit_exhaustion_raises_runtime_error(self, monkeypatch):
        monkeypatch.setattr(rpc_mod.time, "sleep", lambda s: None)
        m = Metrics()
        session = _RateLimitedSession(
            {"code": 429, "message": "slow down"}, error_times=99
        )
        client = _client(session, m, max_retries=3)
        with pytest.raises(RuntimeError, match="failed after 3 attempts"):
            client.request("Filecoin.Thing", [])
        assert session.posts == 3
        assert m.snapshot()["counters"]["rpc.failures"] == 1

    def test_semantic_code_still_fails_fast(self, monkeypatch):
        monkeypatch.setattr(
            rpc_mod.time, "sleep",
            lambda s: pytest.fail("must not sleep on semantic errors"),
        )
        session = _RateLimitedSession({"code": 1, "message": "actor not found"})
        client = _client(session, Metrics())
        with pytest.raises(RpcError, match="actor not found"):
            client.request("Filecoin.Thing", [])
        assert session.posts == 1

    def test_custom_retryable_code_set(self, monkeypatch):
        monkeypatch.setattr(rpc_mod.time, "sleep", lambda s: None)
        session = _RateLimitedSession({"code": -777, "message": "custom transient"})
        client = _client(
            session, Metrics(), retryable_rpc_codes=frozenset({-777})
        )
        assert client.request("Filecoin.Thing", []) == "ok"
        assert session.posts == 3


class TestTimeouts:
    def test_block_fetch_uses_fail_fast_deadline(self):
        m = Metrics()
        raw = b"\x01\x02\x03"
        session = _FlakySession(result=base64.b64encode(raw).decode())
        client = _client(session, m, timeout_s=250.0, block_timeout_s=30.0)
        from ipc_proofs_tpu.core.cid import CID

        cid = CID.hash_of(b"block")
        assert client.chain_read_obj(cid) == raw
        assert session.timeouts == [30.0]  # not the general 250 s deadline

    def test_general_requests_keep_long_deadline(self):
        m = Metrics()
        session = _FlakySession(result={})
        client = _client(session, m, timeout_s=250.0, block_timeout_s=30.0)
        client.request("Filecoin.StateLookupID", [])
        assert session.timeouts == [250.0]

    def test_per_call_override_wins(self):
        m = Metrics()
        session = _FlakySession(result={})
        client = _client(session, m, timeout_s=250.0)
        client.request("Filecoin.Thing", [], timeout_s=5.0)
        assert session.timeouts == [5.0]
