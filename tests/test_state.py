"""State schema tests: addresses, headers, actors, events, storage slots."""

import pytest

from ipc_proofs_tpu.core.cid import CID, RAW
from ipc_proofs_tpu.core.dagcbor import encode as cbor_encode
from ipc_proofs_tpu.ipld.hamt import hamt_build
from ipc_proofs_tpu.state.actors import (
    ActorState,
    EvmStateLite,
    StateRoot,
    get_actor_state,
    parse_evm_state,
)
from ipc_proofs_tpu.state.address import Address, Protocol
from ipc_proofs_tpu.state.events import (
    ActorEvent,
    EventEntry,
    Receipt,
    StampedEvent,
    ascii_to_bytes32,
    extract_evm_log,
    hash_event_signature,
    left_pad_32,
)
from ipc_proofs_tpu.state.header import BlockHeader, extract_parent_state_root
from ipc_proofs_tpu.state.storage import (
    calculate_storage_slot,
    compute_mapping_slot,
    read_storage_slot,
)
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore, put_cbor


class TestAddress:
    def test_id_roundtrip(self):
        a = Address.new_id(1234)
        assert a.id() == 1234
        assert str(a) == "f01234"
        assert Address.from_string("f01234") == a
        assert Address.from_string("t01234") == a  # testnet normalization
        assert Address.from_bytes(a.to_bytes()) == a

    def test_id_bytes_form(self):
        # protocol byte 0x00 + uvarint payload — the state-tree HAMT key
        assert Address.new_id(0).to_bytes() == b"\x00\x00"
        assert Address.new_id(128).to_bytes() == b"\x00\x80\x01"

    def test_delegated_f410(self):
        eth = "52f864e96e8c85836c2df262ae34d2dc4df5953a"
        a = Address.from_eth_address(eth)
        assert a.protocol == Protocol.DELEGATED
        ns, sub = a.delegated_parts()
        assert ns == 10
        assert sub.hex() == eth
        s = str(a)
        assert s.startswith("f410f")
        assert Address.from_string(s) == a

    def test_checksum_rejected(self):
        a = Address.from_eth_address("52f864e96e8c85836c2df262ae34d2dc4df5953a")
        s = str(a)
        # corrupt a mid-payload character (the final char only holds base32
        # padding bits, which decode ignores)
        i = len(s) - 8
        corrupted = s[:i] + ("a" if s[i] != "a" else "b") + s[i + 1 :]
        with pytest.raises(ValueError):
            Address.from_string(corrupted)

    def test_eth_address_validation(self):
        with pytest.raises(ValueError):
            Address.from_eth_address("0x1234")


class TestHeader:
    def _header(self):
        return BlockHeader(
            parents=[CID.hash_of(b"p1"), CID.hash_of(b"p2")],
            height=100,
            parent_state_root=CID.hash_of(b"state"),
            parent_message_receipts=CID.hash_of(b"receipts"),
            messages=CID.hash_of(b"txmeta"),
            timestamp=1700000000,
        )

    def test_roundtrip(self):
        h = self._header()
        decoded = BlockHeader.decode(h.encode())
        assert decoded.parents == h.parents
        assert decoded.height == 100
        assert decoded.parent_state_root == h.parent_state_root
        assert decoded.parent_message_receipts == h.parent_message_receipts
        assert decoded.messages == h.messages
        assert decoded.encode() == h.encode()

    def test_is_16_tuple(self):
        from ipc_proofs_tpu.core.dagcbor import decode

        assert len(decode(self._header().encode())) == 16

    def test_extract_parent_state_root(self):
        h = self._header()
        assert extract_parent_state_root(h.encode()) == h.parent_state_root

    def test_cid_stable(self):
        assert self._header().cid() == self._header().cid()

    def test_decode_lite_matches_decode_on_valid_headers(self):
        h = self._header()
        raw = h.encode()
        lite = BlockHeader.decode_lite(raw)
        full = BlockHeader.decode(raw)
        for name in (
            "parents",
            "height",
            "parent_state_root",
            "parent_message_receipts",
            "messages",
            "timestamp",
            "fork_signaling",
            "parent_weight",
        ):
            assert getattr(lite, name) == getattr(full, name), name

    def test_decode_lite_refuses_reencode(self):
        import pytest

        from ipc_proofs_tpu.backend.native import load_dagcbor_ext

        ext = load_dagcbor_ext()
        if ext is None or not hasattr(ext, "decode_header"):
            pytest.skip("native decode_header unavailable")
        lite = BlockHeader.decode_lite(self._header().encode())
        with pytest.raises(ValueError, match="decode_lite"):
            lite.encode()
        with pytest.raises(ValueError, match="decode_lite"):
            lite.cid()

    def test_decode_lite_acceptance_differential(self):
        """decode_lite must accept/reject EXACTLY what decode does — checked
        over the valid header, every 1-byte truncation, several hundred
        random byte flips, and structurally interesting corruptions."""
        import random

        import pytest

        from ipc_proofs_tpu.backend.native import load_dagcbor_ext
        from ipc_proofs_tpu.core.dagcbor import encode as cbor_encode

        ext = load_dagcbor_ext()
        if ext is None or not hasattr(ext, "decode_header"):
            # without the native path decode_lite IS decode and the
            # differential would compare decode against itself
            pytest.skip("native decode_header unavailable")

        raw = self._header().encode()
        cases = [raw, raw + b"\x00"]  # valid + trailing byte
        cases += [raw[:k] for k in range(len(raw))]  # every truncation
        rng = random.Random(12345)
        for _ in range(400):
            mutated = bytearray(raw)
            for _ in range(rng.randint(1, 3)):
                mutated[rng.randrange(len(mutated))] = rng.randrange(256)
            cases.append(bytes(mutated))
        for _ in range(400):  # insert/delete mutations shift every later field
            mutated = bytearray(raw)
            for _ in range(rng.randint(1, 4)):
                k = rng.randrange(3)
                if k == 0:
                    mutated[rng.randrange(len(mutated))] = rng.randrange(256)
                elif k == 1 and len(mutated) > 1:
                    del mutated[rng.randrange(len(mutated))]
                else:
                    mutated.insert(rng.randrange(len(mutated) + 1), rng.randrange(256))
            cases.append(bytes(mutated))
        # structurally interesting: non-list, short list, bad utf-8 text,
        # non-string map key, f16, bad CID bytes in a tag
        cases.append(cbor_encode({"a": 1}))
        cases.append(cbor_encode([1, 2, 3]))
        cases.append(b"\x81\x63\xed\xa0\x80")  # [text(3) = lone surrogate]
        cases.append(b"\xa1\x01\x02")  # {1: 2} — int map key
        cases.append(b"\x81\xf9\x00\x14")  # [f16] — the decoder's quirk path
        cases.append(b"\x81\xd8\x2a\x44\x00\x01\x02\x03")  # bad CID bytes
        cases.append(b"\x81\xd8\x2b\x41\x00")  # tag 43
        cases.append(b"\x81\xd8\x2a\x81\x01")  # tag-42 over non-bytes
        # u64-length overflow probes (must error, never crash): a 16-array
        # whose first skipped field declares bytes/text of length 2^63+
        for head in (b"\x5b", b"\x7b", b"\xd8\x2a\x5b"):
            cases.append(
                b"\x90" + head + b"\x80" + b"\x00" * 7 + b"\x00" * 15
            )
        # deep-nesting DoS probe (must raise, never exhaust the C stack)
        cases.append(b"\x90" + b"\x81" * 200_000 + b"\x01" + b"\x00" * 15)
        # depth-cap BOUNDARY: an otherwise-valid header whose opaque
        # _ticket field nests to exactly the limit — decode_header consumes
        # the outer array outside parse_item and must account for that
        # level, or it accepts one level more than decode
        for k in (509, 510, 511, 512):
            ticket = 1
            for _ in range(k):
                ticket = [ticket]
            deep = BlockHeader(
                parents=[CID.hash_of(b"p")],
                height=1,
                parent_state_root=CID.hash_of(b"s"),
                parent_message_receipts=CID.hash_of(b"r"),
                messages=CID.hash_of(b"m"),
                _ticket=ticket,
            )
            cases.append(deep.encode())
        # non-minimal CID varint inside a SKIPPED opaque field: the
        # validating skip must reject it exactly like the full decode
        # (round-5 review find — cid_bytes_valid was still tolerant after
        # the decode paths went strict)
        canon = CID.hash_of(b"x").to_bytes()
        noncanon_cid = b"\x01\xf1\x00" + canon[2:]  # codec 0x71 as 2 bytes
        bad_link = (
            b"\xd8\x2a\x58" + bytes([len(noncanon_cid) + 1]) + b"\x00" + noncanon_cid
        )
        base = self._header()
        base._ticket = None
        raw16 = base.encode()
        assert raw16[0] == 0x90 and raw16[1] == 0xF6  # 16-array, null ticket
        cases.append(raw16[:1] + bad_link + raw16[2:])  # ticket -> bad link

        agree = 0
        for case in cases:
            try:
                full = BlockHeader.decode(case)
                full_err = None
            except (ValueError, KeyError) as e:
                full, full_err = None, type(e)
            try:
                lite = BlockHeader.decode_lite(case)
                lite_err = None
            except (ValueError, KeyError) as e:
                lite, lite_err = None, type(e)
            if full_err is not None:
                assert lite_err is not None, (
                    f"decode rejected but decode_lite accepted: {case.hex()}"
                )
            else:
                assert lite_err is None, (
                    f"decode accepted but decode_lite rejected ({lite_err}): {case.hex()}"
                )
                assert lite.parents == full.parents
                assert lite.height == full.height
                agree += 1
        assert agree >= 1  # the valid header at minimum


class TestActors:
    def test_state_root_roundtrip(self):
        sr = StateRoot(version=5, actors=CID.hash_of(b"actors"), info=CID.hash_of(b"info"))
        decoded = StateRoot.decode(cbor_encode(sr.to_tuple()))
        assert decoded == sr

    def test_actor_state_4_and_5_tuple(self):
        code, state = CID.hash_of(b"code"), CID.hash_of(b"head")
        a4 = ActorState.from_tuple([code, state, 7, b"\x00\x64"])
        assert a4.balance == 100 and a4.delegated_address is None
        a5 = ActorState.from_tuple([code, state, 7, b"\x00\x64", b"\x04\x0a" + b"\xaa" * 20])
        assert a5.delegated_address is not None

    def test_get_actor_state_walks_hamt(self):
        bs = MemoryBlockstore()
        addr = Address.new_id(1001)
        actor = ActorState(
            code=CID.hash_of(b"evmcode"),
            state=CID.hash_of(b"evmstate"),
            call_seq_num=1,
            balance=0,
        )
        actors_root = hamt_build(bs, {addr.to_bytes(): actor.to_tuple()})
        state_root_cid = put_cbor(
            bs, StateRoot(version=5, actors=actors_root, info=CID.hash_of(b"info")).to_tuple()
        )
        loaded = get_actor_state(bs, state_root_cid, addr)
        assert loaded.state == actor.state
        with pytest.raises(KeyError):
            get_actor_state(bs, state_root_cid, Address.new_id(9999))

    def test_parse_evm_state_v6_and_v5(self):
        bytecode, storage = CID.hash_of(b"bc", codec=RAW), CID.hash_of(b"storage")
        bh = b"\xbb" * 32
        v6 = cbor_encode([bytecode, bh, storage, None, 9, None])
        parsed = parse_evm_state(v6)
        assert parsed.contract_state == storage and parsed.nonce == 9
        v5 = cbor_encode([bytecode, bh, storage, 3, None])
        parsed5 = parse_evm_state(v5)
        assert parsed5.contract_state == storage and parsed5.nonce == 3

    def test_parse_evm_state_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_evm_state(cbor_encode([1, 2]))


class TestEvents:
    def _evm_event_compact(self, topic0, topic1, data=b"\x01" * 8):
        return ActorEvent(
            entries=[
                EventEntry(0, "t1", 0x55, topic0),
                EventEntry(0, "t2", 0x55, topic1),
                EventEntry(0, "d", 0x55, data),
            ]
        )

    def test_extract_compact_form(self):
        t0 = hash_event_signature("NewTopDownMessage(bytes32,uint256)")
        t1 = ascii_to_bytes32("subnet-1")
        log = extract_evm_log(self._evm_event_compact(t0, t1))
        assert log is not None
        assert log.topics == [t0, t1]
        assert log.data == b"\x01" * 8

    def test_extract_concatenated_form(self):
        t0, t1 = b"\xaa" * 32, b"\xbb" * 32
        ev = ActorEvent(
            entries=[
                EventEntry(0, "topics", 0x55, t0 + t1),
                EventEntry(0, "data", 0x55, b"\xfe"),
            ]
        )
        log = extract_evm_log(ev)
        assert log.topics == [t0, t1] and log.data == b"\xfe"

    def test_extract_rejects_bad_shapes(self):
        # misaligned concatenated topics
        assert extract_evm_log(ActorEvent([EventEntry(0, "topics", 0x55, b"\x01" * 33)])) is None
        # wrong-size compact topic
        assert extract_evm_log(ActorEvent([EventEntry(0, "t1", 0x55, b"\x01" * 31)])) is None
        # no topic entries at all
        assert extract_evm_log(ActorEvent([EventEntry(0, "other", 0x55, b"")])) is None

    def test_stamped_event_cbor_roundtrip(self):
        se = StampedEvent(emitter=42, event=self._evm_event_compact(b"\x00" * 32, b"\x01" * 32))
        assert StampedEvent.from_cbor(se.to_cbor()).emitter == 42

    def test_stamped_event_decode_rejects_wrong_field_types(self):
        """fvm_shared's Entry is {flags:u64, key:String, codec:u64,
        value:RawBytes} and StampedEvent's emitter is a u64: wrong CBOR
        majors must reject at decode exactly like serde / the native
        scanner (round-5 soak find: a text entry value crashed the scalar
        replay's hex compare where the native scan rejected)."""
        import pytest

        good = [0, "t1", 0x55, b"\x01" * 32]
        for bad_entry in (
            [0, "t1", 0x55, "text-not-bytes"],  # value must be bytes
            [0, b"t1", 0x55, b"\x01" * 32],  # key must be text
            [0, 7, 0x55, b"\x01" * 32],
            ["x", "t1", 0x55, b"\x01" * 32],  # flags must be u64
            [-1, "t1", 0x55, b"\x01" * 32],
            [0, "t1", "y", b"\x01" * 32],  # codec must be u64
            [0, "t1", True, b"\x01" * 32],
        ):
            with pytest.raises(ValueError):
                StampedEvent.from_cbor([5, [bad_entry]])
        for bad_emitter in ("5", b"\x05", -1, True, None, 1.0):
            with pytest.raises(ValueError):
                StampedEvent.from_cbor([bad_emitter, [good]])
        with pytest.raises(ValueError):
            StampedEvent.from_cbor([5, "entries-not-an-array"])
        assert StampedEvent.from_cbor([5, [good]]).event.entries[0].key == "t1"

    def test_receipt_cbor_roundtrip(self):
        r = Receipt(exit_code=0, return_data=b"ok", gas_used=555, events_root=CID.hash_of(b"ev"))
        rt = Receipt.from_cbor(r.to_cbor())
        assert rt == r
        r_no_events = Receipt(exit_code=1, return_data=b"", gas_used=0, events_root=None)
        assert Receipt.from_cbor(r_no_events.to_cbor()).events_root is None

    def test_helpers(self):
        assert ascii_to_bytes32("abc")[:3] == b"abc"
        assert len(ascii_to_bytes32("abc")) == 32
        assert left_pad_32(b"\x01") == b"\x00" * 31 + b"\x01"
        assert left_pad_32(b"\xff" * 40) == b"\xff" * 32


class TestStorage:
    SLOT = calculate_storage_slot("calib-subnet-1", 0)

    def test_mapping_slot_math(self):
        # keccak(key32 ++ be32(index)) — check against a manual computation
        from ipc_proofs_tpu.core.hashes import keccak256

        key = ascii_to_bytes32("calib-subnet-1")
        assert self.SLOT == keccak256(key + b"\x00" * 31 + b"\x00")
        assert compute_mapping_slot(key, 1) == keccak256(key + b"\x00" * 31 + b"\x01")

    def test_direct_hamt_encoding_c(self):
        bs = MemoryBlockstore()
        value = (5).to_bytes(2, "big")
        root = hamt_build(bs, {self.SLOT: value, b"\x01" * 32: b"\xff"})
        assert read_storage_slot(bs, root, self.SLOT) == value
        assert read_storage_slot(bs, root, b"\x02" * 32) is None

    def test_inline_small_map_a3(self):
        bs = MemoryBlockstore()
        root = put_cbor(bs, {"v": [[self.SLOT, b"\x2a"]]})
        assert read_storage_slot(bs, root, self.SLOT) == b"\x2a"
        assert read_storage_slot(bs, root, b"\x03" * 32) is None

    def test_inline_tuple_a2(self):
        bs = MemoryBlockstore()
        root = put_cbor(bs, [b"params", {"v": [[self.SLOT, b"\x07"]]}])
        assert read_storage_slot(bs, root, self.SLOT) == b"\x07"

    def test_inline_tuple_list_a1(self):
        bs = MemoryBlockstore()
        root = put_cbor(bs, [b"params", [{"v": [[self.SLOT, b"\x08"]]}]])
        assert read_storage_slot(bs, root, self.SLOT) == b"\x08"

    def test_wrapper_tuple_b1(self):
        bs = MemoryBlockstore()
        inner = hamt_build(bs, {self.SLOT: b"\x09"}, bit_width=5)
        root = put_cbor(bs, [inner, 5])
        assert read_storage_slot(bs, root, self.SLOT) == b"\x09"

    def test_wrapper_map_b2(self):
        bs = MemoryBlockstore()
        inner = hamt_build(bs, {self.SLOT: b"\x0a"}, bit_width=4)
        root = put_cbor(bs, {"root": inner, "bitwidth": 4})
        assert read_storage_slot(bs, root, self.SLOT) == b"\x0a"

    def test_slot_key_must_be_32(self):
        bs = MemoryBlockstore()
        root = hamt_build(bs, {})
        with pytest.raises(ValueError):
            read_storage_slot(bs, root, b"\x00")

    def test_non_bytes_slot_values_reject_not_leak(self):
        """Round-5 soak find: slot values are byte buffers everywhere in
        the cascade. A text-valued SmallMap is NOT a SmallMap (the arm
        falls through — here to arm C, which rejects the dict root as a
        non-HAMT node), and a text value inside a slot HAMT is a decode
        error in the selected arm. Neither may leak a TypeError."""
        bs = MemoryBlockstore()
        root = put_cbor(bs, {"v": [[self.SLOT, "text-not-bytes"]]})
        with pytest.raises(ValueError):
            read_storage_slot(bs, root, self.SLOT)
        bs2 = MemoryBlockstore()
        inner = hamt_build(bs2, {self.SLOT: b"\x09"}, bit_width=5)
        # corrupt the bucket value to CBOR text, re-keying the block under
        # its new CID so the store stays consistent
        from ipc_proofs_tpu.core.dagcbor import decode as cbor_decode
        from ipc_proofs_tpu.core.dagcbor import encode as cbor_encode

        node = cbor_decode(bs2.get(inner))
        node[1][0][0][1] = "text-not-bytes"
        bad_inner = put_cbor(bs2, node)
        root2 = put_cbor(bs2, [bad_inner, 5])
        with pytest.raises(ValueError, match="must be bytes"):
            read_storage_slot(bs2, root2, self.SLOT)


class TestDecodeHeaderLiteNative:
    """The C ``decode_header_lite`` re-implements the 16-field walk with its
    own keep mask and folded validation — pin its acceptance against the
    full Python decode differentially (error FAMILY may narrow from
    UnicodeDecodeError to its ValueError parent on skipped text fields;
    accept/reject and field values must agree exactly)."""

    def _raw(self):
        from ipc_proofs_tpu.core.cid import CID
        from ipc_proofs_tpu.state.header import BlockHeader

        return BlockHeader(
            parents=[CID.hash_of(b"p1"), CID.hash_of(b"p2")],
            height=991,
            parent_state_root=CID.hash_of(b"sr"),
            parent_message_receipts=CID.hash_of(b"rr"),
            messages=CID.hash_of(b"mm"),
        ).encode()

    def test_acceptance_differential_vs_full_decode(self):
        import random

        import pytest

        from ipc_proofs_tpu.state.header import (
            BlockHeader,
            _native_decode_header_lite,
        )

        lite = _native_decode_header_lite()
        if lite is False:
            pytest.skip("native decode_header_lite unavailable")
        raw = self._raw()
        cases = [raw, raw + b"\x00"]
        cases += [raw[:k] for k in range(len(raw))]
        rng = random.Random(8495)
        for _ in range(600):
            mutated = bytearray(raw)
            for _ in range(rng.randint(1, 4)):
                k = rng.randrange(3)
                if k == 0:
                    mutated[rng.randrange(len(mutated))] = rng.randrange(256)
                elif k == 1 and len(mutated) > 1:
                    del mutated[rng.randrange(len(mutated))]
                else:
                    mutated.insert(rng.randrange(len(mutated) + 1), rng.randrange(256))
            cases.append(bytes(mutated))
        accepted = 0
        for case in cases:
            try:
                full = BlockHeader.decode(case)
                full_err = None
            except ValueError:  # UnicodeDecodeError is a ValueError subclass
                full, full_err = None, ValueError
            try:
                out = lite(case)
                lite_err = None
            except ValueError:
                out, lite_err = None, ValueError
            assert (full_err is None) == (lite_err is None), case.hex()
            if full_err is None:
                parents, height, psr, pmr, msgs = out
                assert parents == full.parents
                assert height == full.height
                assert psr == full.parent_state_root
                assert pmr == full.parent_message_receipts
                assert msgs == full.messages
                accepted += 1
        assert accepted >= 1  # the valid header itself
