"""Chaos differential (tools/chaos.py at test scale): under any seeded
fault schedule the pipelined range driver either emits a bundle
byte-identical to the fault-free run or raises a typed error — never a
silently different bundle. Bit-flipped blocks in particular must ALWAYS be
caught by CID verification before they can reach a witness."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import chaos
from ipc_proofs_tpu.store.faults import FAULT_KINDS, FaultPlan


@pytest.fixture(scope="module")
def world():
    return chaos.build_world(n_pairs=6, receipts_per_pair=3, events_per_receipt=2)


class TestFaultPlanDeterminism:
    def test_same_seed_same_schedule(self):
        a, b = FaultPlan(7, fault_rate=0.5), FaultPlan(7, fault_rate=0.5)
        assert [a.draw() for _ in range(200)] == [b.draw() for _ in range(200)]

    def test_different_seeds_differ(self):
        a, b = FaultPlan(7, fault_rate=0.5), FaultPlan(8, fault_rate=0.5)
        assert [a.draw() for _ in range(200)] != [b.draw() for _ in range(200)]

    def test_snapshot_accounts_for_every_draw(self):
        plan = FaultPlan(3, fault_rate=0.3)
        kinds = [plan.draw() for _ in range(500)]
        snap = plan.snapshot()
        assert snap["calls_seen"] == 500
        assert snap["faults_injected"] == sum(k is not None for k in kinds)
        assert sum(snap["by_kind"].values()) == snap["faults_injected"]
        assert set(snap["by_kind"]) <= set(FAULT_KINDS)


class TestChaosDifferential:
    def test_identical_or_typed_error_over_seed_grid(self, world):
        # the committed invariant at pinned seeds; tools/chaos.py re-runs
        # the same harness at soak scale with fresh seeds
        store, pairs, spec, reference = world
        counts = {"identical": 0, "typed_error": 0}
        for seed in range(20):
            for rate in (0.05, 0.4):
                res = chaos.chaos_run(
                    store, pairs, spec, reference, seed, fault_rate=rate
                )
                assert res["outcome"] in counts, res  # no divergent/untyped
                counts[res["outcome"]] += 1
        assert counts["identical"] > 0  # faults absorbed at least once
        assert counts["typed_error"] > 0  # hostile regime exercised too

    def test_bitflips_never_reach_a_bundle(self, world):
        # bit-flips only: any completed run had every flip caught by CID
        # verification and re-fetched — the bundle must be byte-identical
        store, pairs, spec, reference = world
        import random

        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_pipelined
        from ipc_proofs_tpu.store.failover import DegradedError, EndpointPool
        from ipc_proofs_tpu.store.faults import FaultySession, LocalLotusSession
        from ipc_proofs_tpu.store.rpc import IntegrityError, LotusClient, RpcBlockstore
        from ipc_proofs_tpu.utils.metrics import Metrics

        class _TickClock:
            # breaker reset / probe-wave decisions count pool operations
            # instead of wall time: on a loaded host real elapsed time can
            # keep every breaker open long enough that all 12 seeds degrade
            # and the non-vacuity assertion below goes hollow
            def __init__(self, step_s=0.002):
                self._t, self._step = 0.0, step_s

            def __call__(self):
                self._t += self._step
                return self._t

        flips_seen = completed = 0
        for seed in range(12):
            m = Metrics()
            plans = [
                FaultPlan(seed * 31 + i, fault_rate=0.25, kinds=("bitflip",))
                for i in range(2)
            ]
            clients = [
                LotusClient(
                    f"http://bf-{i}",
                    session=FaultySession(LocalLotusSession(store), plans[i],
                                          sleep=lambda s: None),
                    max_retries=2, backoff_base_s=0.0005, backoff_max_s=0.001,
                    rng=random.Random(seed + i), metrics=m,
                )
                for i in range(2)
            ]
            pool = EndpointPool(clients, breaker_threshold=3,
                                breaker_reset_s=0.01, metrics=m,
                                clock=_TickClock())
            try:
                bundle = generate_event_proofs_for_range_pipelined(
                    RpcBlockstore(pool, metrics=m), pairs, spec, chunk_size=3,
                    scan_threads=1, scan_retries=2, force_pipeline=True,
                    metrics=m,
                )
            except (IntegrityError, DegradedError):
                # typed refusal is always acceptable — IntegrityError when
                # every endpoint served corrupt bytes, DegradedError when
                # the flips tripped every breaker (lotus_down fail-fast)
                continue
            finally:
                pool.close()
            completed += 1
            assert bundle.to_json() == reference, f"seed {seed} diverged"
            injected = sum(
                p.snapshot()["by_kind"].get("bitflip", 0) for p in plans
            )
            flips_seen += injected
            # every injected flip was detected (counted), none slipped through
            assert m.snapshot()["counters"].get("rpc.integrity_failures", 0) == injected
        assert completed > 0 and flips_seen > 0  # non-vacuous

    def test_run_grid_summary_shape(self, world):
        del world  # run_grid builds its own (smaller) world
        summary = chaos.run_grid(1234, runs=3, fault_rates=(0.05, 0.5), n_pairs=4)
        assert summary["ok"] is True
        assert summary["runs"] == 6
        assert summary["violations"] == []
        assert summary["total_faults_injected"] > 0


class TestShardTransportChaos:
    def test_shard_fault_plan_is_seeded(self):
        a = chaos.ShardFaultPlan(7, fault_rate=0.5)
        b = chaos.ShardFaultPlan(7, fault_rate=0.5)
        assert [a.draw() for _ in range(200)] == [b.draw() for _ in range(200)]
        snap = a.snapshot()
        assert sum(snap["by_kind"].values()) == snap["faults_injected"]
        assert set(snap["by_kind"]) <= set(chaos.ShardFaultPlan.KINDS)

    def test_identical_or_typed_over_shard_transport_grid(self):
        """The cluster-door invariant: seeded drop/delay/truncate on the
        shard HTTP transport — BOTH the buffered and the cut-through
        streamed door — yields byte-identical bundles (failover
        absorbed) or a typed error, never divergence or an untyped
        escape. The pinned-seed committed form of
        ``python tools/chaos.py SEED --shards``."""
        summary = chaos.run_shard_grid(
            20260807, runs=3, fault_rates=(0.1, 0.4, 0.7), n_pairs=6
        )
        assert summary["ok"] is True, summary["violations"]
        assert summary["counts"]["divergent"] == 0
        assert summary["counts"]["untyped_error"] == 0
        assert summary["counts"]["identical"] > 0
        assert summary["total_faults_injected"] > 0
