"""Bulk backfill engine tests (`ipc_proofs_tpu.backfill`).

The differential grid pins the subsystem's one law: for ANY window
size, node placement, filter, or completion order, the sealed backfill
bundle is byte-identical to `generate_event_proofs_for_range_chunked`
over the same pairs — windows fold through the gather merge law, which
is partition-independent. On top of that: deterministic scheduling and
work-ahead feeding, the long-poll cursor/ack streaming protocol
(first chunk lands before the job completes), journal resume (including
SIGKILL kill points via the tools/crashtest.py harness), the
micro-batcher's low-priority lane, and the `/v1/backfill` HTTP door.
All hermetic and tier-1."""

import json
import os
import sys
import threading
import time
from http.client import HTTPConnection

import pytest

from ipc_proofs_tpu.backfill import (
    BackfillEngine,
    BackfillError,
    local_window_runner,
)
from ipc_proofs_tpu.backfill.scheduler import (
    WorkAheadFeeder,
    plan_windows,
    window_ring_key,
)
from ipc_proofs_tpu.cluster import HashRing, LocalShard
from ipc_proofs_tpu.cluster.gather import BundleFold
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import (
    TipsetPair,
    generate_event_proofs_for_range_chunked,
)
from ipc_proofs_tpu.serve.batcher import MicroBatcher
from ipc_proofs_tpu.utils.metrics import Metrics

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools"))

import crashtest  # noqa: E402

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001


@pytest.fixture(scope="module")
def world64():
    """The acceptance fixture: a 64-epoch (tipset-pair) demo world."""
    return build_range_world(
        64, 3, 2, 0.2, signature=SIG, topic1=SUBNET, actor_id=ACTOR,
        base_height=42_000,
    )


def _spec(filtered: bool = True):
    return EventProofSpec(
        event_signature=SIG,
        topic_1=SUBNET,
        actor_id_filter=(ACTOR if filtered else None),
    )


def _canonical(bundle: UnifiedProofBundle) -> str:
    return json.dumps(bundle.to_json_obj(), sort_keys=True)


@pytest.fixture(scope="module")
def direct64(world64):
    """Chunked-driver comparators over all 64 pairs, by filter flavor."""
    store, pairs, _ = world64
    return {
        filtered: _canonical(
            generate_event_proofs_for_range_chunked(
                store, list(pairs), _spec(filtered), chunk_size=8
            )
        )
        for filtered in (True, False)
    }


class TestScheduler:
    def test_plan_is_deterministic_and_covers_the_range(self, world64):
        _, pairs, _ = world64
        a = plan_windows(pairs, 3, 61, 8, ["s0", "s1", "s2"])
        b = plan_windows(pairs, 3, 61, 8, ["s2", "s1", "s0"])  # node order
        assert a == b
        assert [w.index for w in a] == list(range(len(a)))
        # contiguous half-open cover of [3, 61)
        assert a[0].lo == 3 and a[-1].hi == 61
        for prev, nxt in zip(a, a[1:]):
            assert prev.hi == nxt.lo
        assert all(1 <= w.n_epochs <= 8 for w in a)

    def test_placement_follows_the_ring(self, world64):
        _, pairs, _ = world64
        nodes = ["s0", "s1", "s2"]
        ring = HashRing(nodes, vnodes=64)
        for w in plan_windows(pairs, 0, 64, 8, nodes):
            assert w.node == ring.node_for(window_ring_key(pairs, w.lo))

    def test_plan_validation(self, world64):
        _, pairs, _ = world64
        with pytest.raises(ValueError, match="window_size"):
            plan_windows(pairs, 0, 8, 0, ["s0"])
        with pytest.raises(ValueError, match="out of bounds"):
            plan_windows(pairs, 0, len(pairs) + 1, 8, ["s0"])
        with pytest.raises(ValueError, match="out of bounds"):
            plan_windows(pairs, 5, 5, 8, ["s0"])
        with pytest.raises(ValueError, match="node"):
            plan_windows(pairs, 0, 8, 4, [])

    def test_feeder_primes_work_ahead_windows_once(self, world64):
        _, pairs, _ = world64
        windows = plan_windows(pairs, 0, 16, 4, ["local"])

        class Plane:
            def __init__(self):
                self.batches = []

            def prime(self, cids):
                self.batches.append(list(cids))

        plane = Plane()
        feeder = WorkAheadFeeder(plane, pairs, windows, work_ahead=2)
        assert feeder.on_window_start(0) == 2  # windows 1 and 2 primed
        assert len(plane.batches) == 1 and plane.batches[0]
        # idempotent: the same future windows never re-prime
        assert feeder.on_window_start(1) == 1  # only window 3 is new
        assert feeder.on_window_start(3) == 0  # nothing left ahead
        # done windows are skipped, not primed
        feeder2 = WorkAheadFeeder(plane, pairs, windows, work_ahead=2)
        assert feeder2.on_window_start(0, done={1, 2}) == 1  # window 3

    def test_feeder_is_a_noop_without_a_plane(self, world64):
        _, pairs, _ = world64
        windows = plan_windows(pairs, 0, 8, 4, ["local"])
        assert WorkAheadFeeder(None, pairs, windows).on_window_start(0) == 0
        assert (
            WorkAheadFeeder(object(), pairs, windows).on_window_start(0) == 0
        )


def _run_local(world, filtered, window_size, nodes=("local",), **kw):
    store, pairs, _ = world
    spec = _spec(filtered)
    engine = BackfillEngine(
        pairs,
        spec,
        local_window_runner(store, spec),
        window_size=window_size,
        nodes=nodes,
        **kw,
    )
    try:
        job = engine.submit(0, len(pairs))
        return job, engine, engine.job(job.job_id).result(timeout=300.0)
    finally:
        engine.close(timeout=60.0)


class TestByteIdentity:
    """The differential grid: window_size × placement × filter."""

    @pytest.mark.parametrize("filtered", [True, False])
    @pytest.mark.parametrize("window_size", [1, 8, 64])
    def test_grid_matches_chunked_driver(
        self, world64, direct64, window_size, filtered
    ):
        _, _, bundle = _run_local(
            world64, filtered, window_size, nodes=("s0", "s1", "s2")
        )
        assert _canonical(bundle) == direct64[filtered]

    def test_placement_does_not_change_bytes(self, world64, direct64):
        _, _, one_node = _run_local(world64, True, 8, nodes=("solo",))
        _, _, three = _run_local(world64, True, 8, nodes=("a", "b", "c"))
        assert _canonical(one_node) == _canonical(three) == direct64[True]

    def test_parallel_completion_order_does_not_change_bytes(
        self, world64, direct64
    ):
        job, _, bundle = _run_local(
            world64, True, 5, window_parallelism=4
        )
        assert _canonical(bundle) == direct64[True]
        st = job.status()
        assert st["state"] == "complete"
        assert st["windows_done"] == st["windows_total"] == 13
        assert st["epochs_done"] == 64

    def test_resume_replays_journal_and_is_identical(
        self, world64, direct64, tmp_path
    ):
        store, pairs, _ = world64
        spec = _spec(True)
        jobs_dir = str(tmp_path / "jobs")
        first, _, bundle = _run_local(
            world64, True, 8, jobs_dir=jobs_dir
        )
        assert _canonical(bundle) == direct64[True]
        assert first.status()["windows_replayed"] == 0

        metrics = Metrics()
        engine = BackfillEngine(
            pairs,
            spec,
            local_window_runner(store, spec),
            jobs_dir=jobs_dir,
            window_size=8,
            metrics=metrics,
        )
        try:
            job = engine.submit(0, len(pairs))
            assert job.job_id == first.job_id  # manifest-keyed identity
            again = job.result(timeout=300.0)
        finally:
            engine.close(timeout=60.0)
        assert _canonical(again) == direct64[True]
        st = job.status()
        assert st["windows_replayed"] == st["windows_total"] == 8
        counters = metrics.snapshot()["counters"]
        assert counters.get("backfill.jobs_resumed") == 1
        assert counters.get("backfill.windows_replayed") == 8
        assert "backfill.windows" not in counters  # nothing regenerated


class TestStreaming:
    def test_first_chunk_streams_before_completion(self, world64):
        store, pairs, _ = world64
        spec = _spec(True)
        inner = local_window_runner(store, spec)
        release = threading.Event()

        def gated(window, wpairs):
            if window.index > 0:
                assert release.wait(timeout=60.0)
            return inner(window, wpairs)

        engine = BackfillEngine(
            pairs, spec, gated, window_size=16
        )
        try:
            job = engine.submit(0, len(pairs))
            out = job.chunks_after(0, wait_s=60.0)
            # the first window's chunk is here while windows 1..3 are gated
            assert out["state"] == "running"
            assert len(out["chunks"]) == 1
            chunk = out["chunks"][0]
            assert chunk["cursor"] == 1
            assert chunk["window"]["lo"] == 0 and chunk["window"]["hi"] == 16
            assert chunk["bundle"] is not None
            assert job.status()["first_chunk_s"] is not None
            release.set()
            job.result(timeout=300.0)
        finally:
            release.set()
            engine.close(timeout=60.0)

    def test_cursor_ack_protocol_and_fold_identity(self, world64, direct64):
        store, pairs, _ = world64
        spec = _spec(True)
        engine = BackfillEngine(
            pairs, spec, local_window_runner(store, spec), window_size=8
        )
        try:
            job = engine.submit(0, len(pairs))
            job.wait(timeout=300.0)

            # drain the stream the way a real client does: poll, fold, ack
            fold = BundleFold(pairs, list(range(len(pairs))))
            cursor, n_chunks = 0, 0
            while True:
                out = job.chunks_after(cursor, wait_s=5.0)
                for chunk in out["chunks"]:
                    fold.fold(
                        UnifiedProofBundle.from_json_obj(chunk["bundle"])
                    )
                    cursor = chunk["cursor"]
                    n_chunks += 1
                if not out["chunks"] and out["state"] != "running":
                    break
            assert n_chunks == 8
            assert _canonical(fold.seal()) == direct64[True]

            # acked payloads are dropped from memory (the journal keeps
            # the bytes); metadata survives for status/history
            replay = job.chunks_after(0, wait_s=0.0)
            assert replay["acked"] == 8
            assert [c["cursor"] for c in replay["chunks"]] == list(
                range(1, 9)
            )
            assert all("bundle" not in c for c in replay["chunks"])
            assert job.ack_through(8) == 0  # idempotent: nothing left
        finally:
            engine.close(timeout=60.0)

    def test_partial_ack_drops_only_older_payloads(self, world64):
        store, pairs, _ = world64
        spec = _spec(True)
        engine = BackfillEngine(
            pairs, spec, local_window_runner(store, spec), window_size=16
        )
        try:
            job = engine.submit(0, len(pairs))
            job.wait(timeout=300.0)
            out = job.chunks_after(2, wait_s=0.0)  # acks cursors 1 and 2
            assert [c["cursor"] for c in out["chunks"]] == [3, 4]
            assert all(c["bundle"] is not None for c in out["chunks"])
            again = job.chunks_after(0, wait_s=0.0)
            held = {c["cursor"]: ("bundle" in c) for c in again["chunks"]}
            assert held == {1: False, 2: False, 3: True, 4: True}
        finally:
            engine.close(timeout=60.0)


class TestPriorityLane:
    def test_low_lane_waits_behind_all_interactive_work(self):
        """Deterministic lane-order check: with both lanes populated
        while the worker is blocked, every interactive request dispatches
        before ANY low-priority one."""
        order = []
        gate = threading.Event()
        first = threading.Event()

        def flush(batch):
            first.set()
            assert gate.wait(timeout=30.0)
            order.extend(p.payload for p in batch)
            for p in batch:
                p.complete(p.payload)

        metrics = Metrics()
        mb = MicroBatcher(
            flush, max_batch=2, max_wait_ms=0.0, name="t", metrics=metrics
        )
        try:
            mb.submit("plug")  # occupies the worker at the gate
            assert first.wait(timeout=30.0)
            lows = [
                mb.submit(f"low-{i}", low_priority=True) for i in range(3)
            ]
            highs = [mb.submit(f"hi-{i}") for i in range(3)]
            gate.set()
            for p in highs + lows:
                p.result(timeout=30.0)
        finally:
            mb.close(drain=False)
        body = order[1:]  # drop the plug
        n_hi = len(highs)
        assert all(x.startswith("hi-") for x in body[:n_hi])
        assert all(x.startswith("low-") for x in body[n_hi:])
        counters = metrics.snapshot()["counters"]
        assert counters["serve.accepted_low.t"] == 3
        assert counters["serve.accepted.t"] == 4

    def test_interactive_latency_survives_backfill_saturation(self, world64):
        """Starvation check on the REAL service: a backfill job saturating
        the single worker's low lane must not starve interactive
        generates — each interactive request waits at most one in-flight
        window, so p99 stays bounded while the job is still running."""
        from ipc_proofs_tpu.serve.service import ProofService, ServiceConfig

        store, pairs, _ = world64
        spec = _spec(True)
        svc = ProofService(
            store=store,
            spec=spec,
            config=ServiceConfig(max_batch=4, max_wait_ms=1.0, workers=1),
        )
        engine = BackfillEngine(
            pairs,
            spec,
            lambda w, wp: svc.submit_range_window(wp).result(),
            window_size=2,  # small windows bound interactive wait
        )
        try:
            job = engine.submit(0, len(pairs))
            lat_ms = []
            for i in range(12):
                t0 = time.monotonic()
                resp = svc.generate(
                    TipsetPair(
                        parent=pairs[i % len(pairs)].parent,
                        child=pairs[i % len(pairs)].child,
                    ),
                    timeout_s=60.0,
                )
                lat_ms.append((time.monotonic() - t0) * 1000.0)
                assert resp.bundle is not None
            # the backfill must actually have been competing for the worker
            assert job.status()["state"] == "running" or (
                job.status()["windows_done"] > 0
            )
            lat_ms.sort()
            p99 = lat_ms[max(0, int(len(lat_ms) * 0.99) - 1)]
            # generous: one demo-world window is tens of ms; starvation
            # (backfill draining first) would push this into the minutes
            assert p99 < 30_000.0, f"interactive p99 {p99:.0f}ms under backfill"
            job.result(timeout=300.0)
        finally:
            engine.close(timeout=60.0)
            svc.drain(timeout=60.0)


class TestHTTPDoor:
    @pytest.fixture()
    def shard(self, world64, tmp_path):
        store, pairs, _ = world64
        shard = LocalShard(
            "bf0",
            store,
            pairs,
            _spec(True),
            backfill_jobs_dir=str(tmp_path / "jobs"),
            backfill_window_size=16,
        ).start()
        yield shard
        shard.stop(timeout=30)

    def _post(self, shard, path, obj):
        conn = HTTPConnection("127.0.0.1", shard.httpd.port, timeout=60)
        conn.request(
            "POST", path, json.dumps(obj), {"Content-Type": "application/json"}
        )
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())

    def _get(self, shard, path):
        conn = HTTPConnection("127.0.0.1", shard.httpd.port, timeout=60)
        conn.request("GET", path, None, {})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())

    def test_submit_stream_and_fold(self, shard, world64, direct64):
        _, pairs, _ = world64
        status, st = self._post(
            shard, "/v1/backfill", {"pair_start": 0, "pair_end": len(pairs)}
        )
        assert status == 200
        job_id = st["job_id"]
        assert st["windows_total"] == 4

        fold = BundleFold(pairs, list(range(len(pairs))))
        cursor, n_chunks, state = 0, 0, "running"
        deadline = time.monotonic() + 300.0
        while time.monotonic() < deadline:
            status, out = self._get(
                shard,
                f"/v1/backfill/{job_id}/chunks?cursor={cursor}&wait_s=10",
            )
            assert status == 200
            for chunk in out["chunks"]:
                fold.fold(UnifiedProofBundle.from_json_obj(chunk["bundle"]))
                cursor = chunk["cursor"]
                n_chunks += 1
            state = out["state"]
            if not out["chunks"] and state != "running":
                break
        assert state == "complete"
        assert n_chunks == 4
        assert _canonical(fold.seal()) == direct64[True]

        # status door + jobs listing see the same job
        status, st = self._get(shard, f"/v1/backfill/{job_id}")
        assert status == 200 and st["state"] == "complete"
        status, listing = self._get(shard, "/v1/backfill")
        assert status == 200
        assert [j["job_id"] for j in listing["jobs"]] == [job_id]

        # idempotent re-submit: same manifest → same job, already done
        status, st2 = self._post(
            shard, "/v1/backfill", {"pair_start": 0, "pair_end": len(pairs)}
        )
        assert status == 200 and st2["job_id"] == job_id

    def test_validation_and_unknown_job(self, shard, world64):
        _, pairs, _ = world64
        for bad in (
            {"pair_start": 0},  # missing end
            {"pair_start": 3, "pair_end": 2},
            {"pair_start": 0, "pair_end": len(pairs) + 1},
            {"pair_start": True, "pair_end": 4},
            {"pair_start": 0, "pair_end": 4, "window_size": 0},
            {"pair_start": 0, "pair_end": 4, "sub_id": 7},
        ):
            status, out = self._post(shard, "/v1/backfill", bad)
            assert status == 400, bad
            assert "error" in out
        status, out = self._get(shard, "/v1/backfill/bf-nope")
        assert status == 404
        status, out = self._get(shard, "/v1/backfill/bf-nope/chunks?cursor=0")
        assert status == 404

    def test_disabled_without_jobs_dir(self, world64):
        store, pairs, _ = world64
        shard = LocalShard("plain", store, pairs, _spec(True)).start()
        try:
            status, out = self._get(shard, "/v1/backfill")
            assert status == 404 and "disabled" in out["error"]
            status, out = self._post(
                shard, "/v1/backfill", {"pair_start": 0, "pair_end": 2}
            )
            assert status == 404 and "disabled" in out["error"]
        finally:
            shard.stop(timeout=30)


class TestCrashResume:
    """SIGKILL-at-window-boundary resume, via the crashtest harness: a
    real child process running the journaled engine is SIGKILLed at a
    window commit (or torn mid-record), resumed, and must reproduce the
    chunked-driver reference byte-for-byte, replaying every committed
    window instead of regenerating it."""

    @pytest.mark.parametrize("seed", [20260806])
    def test_backfill_sigkill_grid(self, seed):
        summary = crashtest.run_backfill_grid(
            seed, points=4, n_pairs=10, window_size=2
        )
        assert summary["ok"], summary["violations"]
        assert summary["counts"] == {"identical": summary["points"]}
        torn = [t for _, t in summary["kill_points"] if t is not None]
        assert torn and len(torn) < summary["points"]

    def test_boundary_kill_point_detail(self, tmp_path):
        shape = {
            "pairs": 8, "chunk_size": 2, "receipts": 3, "events": 2,
            "match_rate": 0.3,
        }
        store, pairs, spec = crashtest._build_world(8, 3, 2, 0.3)
        reference = generate_event_proofs_for_range_chunked(
            store, pairs, spec, chunk_size=2
        ).to_json()
        res = crashtest.backfill_crash_run(
            reference, shape, crash_at=1, torn=None,
            workdir=str(tmp_path), tag="t",
        )
        assert res["outcome"] == "identical", res
        assert res["records_after_crash"] == 2
        assert res["windows_replayed"] == 2
        assert res["chunks_replayed"] == 2
        assert not res["torn_tail"]


class TestEngineLifecycle:
    def test_closed_engine_rejects_submissions(self, world64):
        store, pairs, _ = world64
        spec = _spec(True)
        engine = BackfillEngine(
            pairs, spec, local_window_runner(store, spec), window_size=8
        )
        engine.close()
        with pytest.raises(BackfillError, match="closed"):
            engine.submit(0, 8)

    def test_runner_failure_is_a_typed_job_failure(self, world64):
        _, pairs, _ = world64

        def broken(window, wpairs):
            raise RuntimeError("device fell over")

        engine = BackfillEngine(
            pairs, _spec(True), broken, window_size=8
        )
        try:
            job = engine.submit(0, 16)
            with pytest.raises(BackfillError, match="device fell over"):
                job.result(timeout=60.0)
            assert job.status()["state"] == "failed"
        finally:
            engine.close(timeout=30.0)
