"""JAX kernel equivalence tests: device kernels vs scalar golden models."""

import numpy as np
import pytest

from ipc_proofs_tpu.core.hashes import blake2b_256, keccak256

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ipc_proofs_tpu.ops.blake2b_jax import blake2b256_blocks  # noqa: E402
from ipc_proofs_tpu.ops.keccak_jax import keccak256_blocks  # noqa: E402
from ipc_proofs_tpu.ops.match_jax import event_match_mask, receipts_with_match  # noqa: E402
from ipc_proofs_tpu.ops.pack import digests_to_bytes, pad_blake2b, pad_keccak  # noqa: E402

MESSAGES = [
    b"",
    b"abc",
    b"Transfer(address,address,uint256)",
    b"NewTopDownMessage(bytes32,uint256)",
    bytes(range(135)),
    bytes(range(136)),  # exactly one keccak rate block of data
    bytes(range(137)),
    bytes(128),  # one blake2b block exactly
    bytes(129),
    (b"\xa5" * 300),  # multi-block for both
    (b"\x42" * 1024),
]


class TestKeccakJax:
    def test_matches_golden_model(self):
        blocks, counts = pad_keccak(MESSAGES)
        digests = digests_to_bytes(keccak256_blocks(jnp.asarray(blocks), jnp.asarray(counts)))
        for msg, digest in zip(MESSAGES, digests):
            assert digest == keccak256(msg), f"keccak mismatch for len={len(msg)}"

    def test_jit_compiles_once_per_shape(self):
        fn = jax.jit(keccak256_blocks)
        blocks, counts = pad_keccak([b"hello", b"world"])
        out1 = fn(jnp.asarray(blocks), jnp.asarray(counts))
        out2 = fn(jnp.asarray(blocks), jnp.asarray(counts))
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))

    def test_large_batch(self):
        msgs = [f"event-sig-{i}(uint256)".encode() for i in range(256)]
        blocks, counts = pad_keccak(msgs)
        digests = digests_to_bytes(keccak256_blocks(jnp.asarray(blocks), jnp.asarray(counts)))
        for msg, digest in zip(msgs, digests):
            assert digest == keccak256(msg)


class TestBlake2bJax:
    def test_matches_golden_model(self):
        blocks, counts, lengths = pad_blake2b(MESSAGES)
        digests = digests_to_bytes(
            blake2b256_blocks(jnp.asarray(blocks), jnp.asarray(counts), jnp.asarray(lengths))
        )
        for msg, digest in zip(MESSAGES, digests):
            assert digest == blake2b_256(msg), f"blake2b mismatch for len={len(msg)}"

    def test_cid_recompute_batch(self):
        # The witness-verification primitive: recompute CIDs of IPLD blocks
        from ipc_proofs_tpu.core.cid import CID

        payloads = [f"block-{i}".encode() * (i + 1) for i in range(64)]
        blocks, counts, lengths = pad_blake2b(payloads)
        digests = digests_to_bytes(
            blake2b256_blocks(jnp.asarray(blocks), jnp.asarray(counts), jnp.asarray(lengths))
        )
        for payload, digest in zip(payloads, digests):
            assert CID.hash_of(payload).digest == digest


class TestMatchMask:
    def _topics_tensor(self, topic_list):
        # topic_list: list of list[bytes32]
        n = len(topic_list)
        out = np.zeros((n, 2, 8), dtype=np.uint32)
        n_topics = np.zeros(n, dtype=np.int32)
        for i, topics in enumerate(topic_list):
            n_topics[i] = len(topics)
            for j, topic in enumerate(topics[:2]):
                out[i, j] = np.frombuffer(topic, dtype="<u4")
        return jnp.asarray(out), jnp.asarray(n_topics)

    def test_mask_semantics(self):
        t0, t1 = b"\xaa" * 32, b"\xbb" * 32
        other = b"\xcc" * 32
        topics, n_topics = self._topics_tensor(
            [[t0, t1], [t0, other], [other, t1], [t0], [t0, t1]]
        )
        emitters = jnp.asarray(np.array([7, 7, 7, 7, 9], dtype=np.int32))
        valid = jnp.asarray(np.array([True, True, True, True, True]))
        spec0 = jnp.asarray(np.frombuffer(t0, dtype="<u4"))
        spec1 = jnp.asarray(np.frombuffer(t1, dtype="<u4"))
        mask = event_match_mask(topics, n_topics, emitters, valid, spec0, spec1, actor_id_filter=7)
        np.testing.assert_array_equal(np.asarray(mask), [True, False, False, False, False])
        mask_nofilter = event_match_mask(topics, n_topics, emitters, valid, spec0, spec1)
        np.testing.assert_array_equal(
            np.asarray(mask_nofilter), [True, False, False, False, True]
        )

    def test_receipt_any_reduce(self):
        mask = jnp.asarray(np.array([True, False, False, True, False]))
        receipt_ids = jnp.asarray(np.array([0, 0, 1, 2, 2], dtype=np.int32))
        hits = receipts_with_match(mask, receipt_ids, 4)
        np.testing.assert_array_equal(np.asarray(hits), [True, False, True, False])
