"""Tier-1 gate on bench reporting: every checked-in BENCH_*.json must pass
`tools/check_bench_schema.py`, and the newest must carry the full current
e2e key set (overlap flags, serial comparison, host introspection) — so a
leg that stops emitting a key fails here, not at artifact-consumption
time."""

import glob
import json
import os
import re
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from check_bench_schema import (  # noqa: E402
    check_artifact,
    cluster_gate_skip_reason,
    fleetobs_gate_skip_reason,
    hostkill_gate_skip_reason,
    main,
    onchip_gate_skip_reason,
    speedup_gate_skip_reason,
    witnessdiet_gate_skip_reason,
)

ARTIFACTS = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))


def _round_key(path):
    m = re.search(r"BENCH_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


NEWEST = max(ARTIFACTS, key=_round_key, default=None)


class TestCheckedInArtifacts:
    def test_artifacts_exist(self):
        assert ARTIFACTS, "no BENCH_*.json artifacts checked in"

    @pytest.mark.parametrize("path", ARTIFACTS, ids=os.path.basename)
    def test_artifact_passes_schema(self, path):
        with open(path) as fh:
            obj = json.load(fh)
        assert check_artifact(obj) == []

    def test_newest_has_full_current_schema(self):
        with open(NEWEST) as fh:
            obj = json.load(fh)
        assert check_artifact(obj, require_current=True) == [], NEWEST

    def test_newest_reports_overlap_flags(self):
        """The stage-overlapped engine is the headline path: the current
        artifact must say so and carry the serial comparison."""
        with open(NEWEST) as fh:
            obj = json.load(fh)
        assert obj["stages_overlap"] is True
        assert obj["gen_verify_overlap"] is True
        assert obj["serial_proofs_per_sec"] is not None
        assert obj["pipeline_speedup_vs_serial"] is not None
        assert obj["host_cores"] == obj["host_cores"] and obj["host_cores"] >= 1

    def test_cli_accepts_all_artifacts(self, capsys):
        assert main(ARTIFACTS) == 0
        assert main(["--require-current", NEWEST]) == 0
        capsys.readouterr()


class TestSyntheticRegressions:
    def _current(self):
        with open(NEWEST) as fh:
            return json.load(fh)

    def test_missing_core_key_fails(self):
        obj = self._current()
        del obj["value"]
        assert any("value" in p for p in check_artifact(obj))

    def test_type_drift_fails(self):
        obj = self._current()
        obj["proofs"] = "656"  # stringified number = consumer breakage
        assert any("proofs" in p for p in check_artifact(obj))

    def test_bool_does_not_pass_as_number(self):
        obj = self._current()
        obj["events_per_sec_e2e"] = True
        assert any("events_per_sec_e2e" in p for p in check_artifact(obj))

    def test_non_numeric_stage_fails(self):
        obj = self._current()
        obj["stages_ms"]["scan"] = "31ms"
        assert any("stages_ms" in p for p in check_artifact(obj))

    def test_dropped_current_key_fails_only_current_mode(self):
        obj = self._current()
        del obj["gen_verify_overlap"]
        assert check_artifact(obj) == []  # old vintages may lack it
        assert any(
            "gen_verify_overlap" in p
            for p in check_artifact(obj, require_current=True)
        )

    def test_null_schema_artifact_is_valid_noncurrent(self):
        """The orchestrator's total-failure artifact (all keys null) must
        still validate — honesty is part of the schema."""
        obj = {
            "metric": "event_proofs_per_sec_4k_range_e2e",
            "unit": "proofs/s",
            **{k: None for k in (
                "value", "platform", "devices", "host_cores", "scan_threads",
                "pipeline_chunk", "events_per_sec_e2e", "proofs", "stages_ms",
                "stages_overlap", "e2e_policy", "e2e_reps_s",
            )},
        }
        assert check_artifact(obj) == []

    def test_legacy_wrapper_rejected_as_current(self):
        obj = {"cmd": "python bench.py", "rc": 0, "tail": "", "n": 1, "parsed": None}
        assert check_artifact(obj) == []
        assert check_artifact(obj, require_current=True) != []


class TestSpeedupGate:
    """pipeline_speedup_vs_serial ≥ 1.0 is enforced (require_current) on
    hosts with spare cores, and skipped WITH A REASON on 1–2 core hosts."""

    def _current(self):
        with open(NEWEST) as fh:
            return json.load(fh)

    def test_sub_serial_speedup_fails_on_multicore_host(self):
        obj = self._current()
        obj["host_cores"] = 8
        obj["pipeline_speedup_vs_serial"] = 0.62  # the r07–r10 regression
        assert check_artifact(obj) == []  # non-current vintages unaffected
        problems = check_artifact(obj, require_current=True)
        assert any("speedup gate" in p for p in problems), problems

    def test_speedup_at_or_above_one_passes(self):
        obj = self._current()
        obj["host_cores"] = 8
        obj["pipeline_speedup_vs_serial"] = 1.0
        assert not any(
            "speedup gate" in p
            for p in check_artifact(obj, require_current=True)
        )

    def test_missing_speedup_fails_on_multicore_host(self):
        obj = self._current()
        obj["host_cores"] = 4
        obj["pipeline_speedup_vs_serial"] = None
        problems = check_artifact(obj, require_current=True)
        assert any("speedup gate" in p for p in problems), problems

    @pytest.mark.parametrize("cores", [1, 2, None])
    def test_gate_skipped_with_reason_on_small_hosts(self, cores):
        obj = self._current()
        obj["host_cores"] = cores
        obj["pipeline_speedup_vs_serial"] = 0.5
        reason = speedup_gate_skip_reason(obj)
        assert reason is not None and str(cores) in reason
        assert not any(
            "speedup gate" in p
            for p in check_artifact(obj, require_current=True)
        )

    def test_gate_applies_above_two_cores(self):
        obj = self._current()
        obj["host_cores"] = 3
        assert speedup_gate_skip_reason(obj) is None

    def test_cli_prints_skip_reason(self, tmp_path, capsys):
        obj = self._current()
        obj["host_cores"] = 1
        obj["pipeline_speedup_vs_serial"] = 0.5
        path = tmp_path / "BENCH_small_host.json"
        path.write_text(json.dumps(obj))
        main(["--require-current", str(path)])  # rc covered elsewhere
        out = capsys.readouterr().out
        assert "speedup gate SKIPPED" in out and "host_cores=1" in out


class TestClusterGate:
    """cluster_linearity_4shard ≥ 0.8 is enforced (require_current) on
    hosts with spare cores, and skipped WITH A REASON on 1–2 core hosts
    where four shard processes time-slice the same cores."""

    def _current(self):
        with open(NEWEST) as fh:
            return json.load(fh)

    def test_sublinear_scaling_fails_on_multicore_host(self):
        obj = self._current()
        obj["host_cores"] = 8
        obj["pipeline_speedup_vs_serial"] = 1.2  # keep the other gate green
        obj["cluster_linearity_4shard"] = 0.4
        assert check_artifact(obj) == []  # non-current vintages unaffected
        problems = check_artifact(obj, require_current=True)
        assert any("cluster gate" in p for p in problems), problems

    def test_linearity_at_or_above_gate_passes(self):
        obj = self._current()
        obj["host_cores"] = 8
        obj["cluster_linearity_4shard"] = 0.8
        assert not any(
            "cluster gate" in p
            for p in check_artifact(obj, require_current=True)
        )

    def test_missing_linearity_fails_on_multicore_host(self):
        obj = self._current()
        obj["host_cores"] = 4
        obj["cluster_linearity_4shard"] = None
        problems = check_artifact(obj, require_current=True)
        assert any("cluster gate" in p for p in problems), problems

    @pytest.mark.parametrize("cores", [1, 2, None])
    def test_gate_skipped_with_reason_on_small_hosts(self, cores):
        obj = self._current()
        obj["host_cores"] = cores
        obj["cluster_linearity_4shard"] = 0.2
        reason = cluster_gate_skip_reason(obj)
        assert reason is not None and str(cores) in reason
        assert not any(
            "cluster gate" in p
            for p in check_artifact(obj, require_current=True)
        )

    def test_gate_applies_above_two_cores(self):
        obj = self._current()
        obj["host_cores"] = 3
        assert cluster_gate_skip_reason(obj) is None

    def test_cli_prints_skip_reason(self, tmp_path, capsys):
        obj = self._current()
        obj["host_cores"] = 1
        obj["cluster_linearity_4shard"] = 0.2
        path = tmp_path / "BENCH_small_cluster_host.json"
        path.write_text(json.dumps(obj))
        main(["--require-current", str(path)])
        out = capsys.readouterr().out
        assert "cluster gate SKIPPED" in out and "host_cores=1" in out


class TestOnchipGate:
    """device_linearity_Nchip ≥ 0.8 is enforced (require_current) on
    multi-device hosts, and skipped WITH A REASON when the mesh and the
    single-device comparator share one chip (ratio = pjit overhead, not
    device scaling)."""

    def _current(self):
        with open(NEWEST) as fh:
            obj = json.load(fh)
        # keep the unrelated gates green whatever vintage NEWEST is
        obj["host_cores"] = 8
        obj["pipeline_speedup_vs_serial"] = 1.2
        obj["cluster_linearity_4shard"] = 0.9
        obj["batch_verify_speedup"] = 1.5
        return obj

    def test_sublinear_scaling_fails_on_multidevice_host(self):
        obj = self._current()
        obj["onchip_devices"] = 4
        obj["device_linearity_Nchip"] = 0.4
        assert check_artifact(obj) == []  # non-current vintages unaffected
        problems = check_artifact(obj, require_current=True)
        assert any("onchip gate" in p for p in problems), problems

    def test_linearity_at_or_above_gate_passes(self):
        obj = self._current()
        obj["onchip_devices"] = 4
        obj["device_linearity_Nchip"] = 0.8
        assert not any(
            "onchip gate" in p
            for p in check_artifact(obj, require_current=True)
        )

    def test_missing_linearity_fails_on_multidevice_host(self):
        obj = self._current()
        obj["onchip_devices"] = 4
        obj["device_linearity_Nchip"] = None
        problems = check_artifact(obj, require_current=True)
        assert any("onchip gate" in p for p in problems), problems

    @pytest.mark.parametrize("devices", [1, 0, None])
    def test_gate_skipped_with_reason_on_single_device(self, devices):
        obj = self._current()
        obj["onchip_devices"] = devices
        obj["device_linearity_Nchip"] = 0.2
        reason = onchip_gate_skip_reason(obj)
        assert reason is not None and str(devices) in reason
        assert not any(
            "onchip gate" in p
            for p in check_artifact(obj, require_current=True)
        )

    def test_gate_applies_above_one_device(self):
        obj = self._current()
        obj["onchip_devices"] = 2
        assert onchip_gate_skip_reason(obj) is None

    def test_cli_prints_skip_reason(self, tmp_path, capsys):
        obj = self._current()
        obj["onchip_devices"] = 1
        obj["device_linearity_Nchip"] = 0.2
        path = tmp_path / "BENCH_single_chip_host.json"
        path.write_text(json.dumps(obj))
        main(["--require-current", str(path)])
        out = capsys.readouterr().out
        assert "onchip gate SKIPPED" in out and "onchip_devices=1" in out


class TestWitnessDietGate:
    """K=16 aggregated bytes/proof strictly below K=1 AND consecutive-epoch
    delta ratio < 1.0 are enforced (require_current) on every artifact that
    carries the witness-diet keys — wire accounting is host-shape
    independent, so only artifacts predating the leg skip."""

    def _current(self):
        with open(NEWEST) as fh:
            return json.load(fh)

    def test_aggregation_must_beat_k1(self):
        obj = self._current()
        obj["witness_bytes_per_proof_k16"] = obj["witness_bytes_per_proof_k1"]
        assert check_artifact(obj) == []  # non-current vintages unaffected
        problems = check_artifact(obj, require_current=True)
        assert any("witness-diet gate" in p for p in problems), problems

    def test_delta_must_beat_full_reship(self):
        obj = self._current()
        obj["witness_delta_ratio"] = 1.0
        problems = check_artifact(obj, require_current=True)
        assert any("witness_delta_ratio=1.0" in p for p in problems), problems

    def test_missing_diet_key_fails_current(self):
        obj = self._current()
        obj["witness_delta_ratio"] = None
        problems = check_artifact(obj, require_current=True)
        assert any("witness-diet gate" in p for p in problems), problems

    def test_current_artifact_passes(self):
        obj = self._current()
        assert witnessdiet_gate_skip_reason(obj) is None
        assert not any(
            "witness-diet gate" in p
            for p in check_artifact(obj, require_current=True)
        )

    def test_gate_skipped_only_for_prediet_vintages(self, tmp_path, capsys):
        obj = self._current()
        for key in (
            "witness_bytes_per_proof_k1", "witness_bytes_per_proof_k16",
            "witness_bytes_per_proof_k256", "witness_delta_ratio",
            "witness_compressed_ratio",
        ):
            obj.pop(key, None)
        reason = witnessdiet_gate_skip_reason(obj)
        assert reason is not None and "predates" in reason
        assert not any(
            "witness-diet gate" in p for p in check_artifact(obj)
        )
        path = tmp_path / "BENCH_prediet_vintage.json"
        path.write_text(json.dumps(obj))
        main([str(path)])  # old vintages validate without --require-current
        out = capsys.readouterr().out
        assert "FAIL" not in out


class TestFleetObsGate:
    """fleetobs_overhead_pct ≤ 3 is enforced (require_current) whenever
    the host has spare cores (host_cores > 2); on smaller hosts the
    scrape/watchdog threads time-slice the request loop, so the ratio is
    skipped with a printed reason. The ≥1-stitched-span check is
    correctness and applies regardless of host shape; only artifacts
    predating the leg skip everything."""

    def _current(self):
        with open(NEWEST) as fh:
            obj = json.load(fh)
        # gate inputs are set explicitly so the tests pin gate SEMANTICS,
        # not the vintage or host shape of the checked-in artifact
        obj["host_cores"] = 8
        obj["fleetobs_overhead_pct"] = 1.2
        obj["fleetobs_rps_plain"] = 100.0
        obj["fleetobs_rps_observed"] = 98.8
        obj["fleetobs_stitched_spans"] = 12
        return obj

    def test_overhead_above_three_pct_fails(self):
        obj = self._current()
        obj["fleetobs_overhead_pct"] = 3.5
        assert check_artifact(obj) == []  # non-current vintages unaffected
        problems = check_artifact(obj, require_current=True)
        assert any("fleetobs gate" in p for p in problems), problems

    def test_overhead_at_or_below_gate_passes(self):
        obj = self._current()
        for ovh in (3.0, 0.4, -24.0):  # observed may beat plain (noise)
            obj["fleetobs_overhead_pct"] = ovh
            assert not any(
                "fleetobs gate" in p
                for p in check_artifact(obj, require_current=True)
            ), ovh

    def test_missing_overhead_fails_current(self):
        obj = self._current()
        obj["fleetobs_overhead_pct"] = None
        problems = check_artifact(obj, require_current=True)
        assert any("fleetobs gate" in p for p in problems), problems

    def test_zero_stitched_spans_fails_current(self):
        obj = self._current()
        obj["fleetobs_stitched_spans"] = 0
        problems = check_artifact(obj, require_current=True)
        assert any("fleetobs_stitched_spans=0" in p for p in problems), problems

    def test_overhead_gate_skips_without_spare_cores(self):
        obj = self._current()
        obj["host_cores"] = 1
        obj["fleetobs_overhead_pct"] = 19.22  # contention, not plane cost
        reason = fleetobs_gate_skip_reason(obj)
        assert reason is not None and "time-slice" in reason
        problems = check_artifact(obj, require_current=True)
        assert not any("fleetobs_overhead_pct" in p for p in problems)
        # stitching is correctness, not perf: still enforced on 1 core
        obj["fleetobs_stitched_spans"] = 0
        problems = check_artifact(obj, require_current=True)
        assert any("fleetobs_stitched_spans=0" in p for p in problems)

    def test_gate_skipped_only_for_prefleet_vintages(self, tmp_path, capsys):
        obj = self._current()
        for key in (
            "fleetobs_overhead_pct", "fleetobs_rps_plain",
            "fleetobs_rps_observed", "fleetobs_stitched_spans",
            "fleetobs_scrapes", "fleetobs_pairs", "fleetobs_requests",
        ):
            obj.pop(key, None)
        reason = fleetobs_gate_skip_reason(obj)
        assert reason is not None and "predates" in reason
        assert not any("fleetobs gate" in p for p in check_artifact(obj))
        path = tmp_path / "BENCH_prefleet_vintage.json"
        path.write_text(json.dumps(obj))
        main(["--require-current", str(path)])
        out = capsys.readouterr().out
        assert "fleetobs gate SKIPPED" in out


class TestHostkillGate:
    """kill_recovery_ms ≤ 10 s, replica_repair_hit_rate ≥ 0.99, and
    aggregate_proofs_per_sec_2host > 0 are enforced (require_current) on
    hosts with spare cores, and skipped WITH A REASON on 1–2 core hosts
    where the shards, load clients, and recovery probe time-slice the
    same core."""

    def _current(self):
        with open(NEWEST) as fh:
            obj = json.load(fh)
        # a multicore shape that keeps the OTHER core-gated gates green
        obj["host_cores"] = 8
        obj.setdefault("pipeline_speedup_vs_serial", 1.2)
        if not isinstance(obj.get("pipeline_speedup_vs_serial"), (int, float)):
            obj["pipeline_speedup_vs_serial"] = 1.2
        for key, good in (
            ("cluster_linearity_4shard", 0.9),
            ("fleetobs_overhead_pct", 1.0),
            ("trace_overhead_pct", 1.0),
            ("qos_light_tenant_p99_ms", 10.0),
            ("kill_recovery_ms", 120.0),
            ("replica_repair_hit_rate", 1.0),
            ("aggregate_proofs_per_sec_2host", 500.0),
        ):
            val = obj.get(key)
            if not isinstance(val, (int, float)) or isinstance(val, bool):
                obj[key] = good
        return obj

    def test_slow_recovery_fails_on_multicore_host(self):
        obj = self._current()
        obj["kill_recovery_ms"] = 60_000.0
        problems = check_artifact(obj, require_current=True)
        assert any("hostkill gate" in p and "kill_recovery_ms" in p
                   for p in problems), problems

    def test_repair_misses_fail_on_multicore_host(self):
        obj = self._current()
        obj["replica_repair_hit_rate"] = 0.5  # half the evictions hit Lotus
        problems = check_artifact(obj, require_current=True)
        assert any("replica_repair_hit_rate" in p for p in problems), problems

    def test_idle_replicated_pair_fails(self):
        obj = self._current()
        obj["aggregate_proofs_per_sec_2host"] = 0
        problems = check_artifact(obj, require_current=True)
        assert any("aggregate_proofs_per_sec_2host" in p
                   for p in problems), problems

    def test_good_values_pass(self):
        obj = self._current()
        assert not any(
            "hostkill gate" in p
            for p in check_artifact(obj, require_current=True)
        )

    def test_missing_keys_fail_on_multicore_host(self):
        obj = self._current()
        obj["kill_recovery_ms"] = None
        problems = check_artifact(obj, require_current=True)
        assert any("hostkill gate" in p and "kill_recovery_ms" in p
                   for p in problems), problems

    @pytest.mark.parametrize("cores", [1, 2, None])
    def test_gate_skipped_with_reason_on_small_hosts(self, cores):
        obj = self._current()
        obj["host_cores"] = cores
        obj["kill_recovery_ms"] = 60_000.0
        obj["replica_repair_hit_rate"] = 0.1
        reason = hostkill_gate_skip_reason(obj)
        assert reason is not None and str(cores) in reason
        assert not any(
            "hostkill gate" in p
            for p in check_artifact(obj, require_current=True)
        )

    def test_gate_applies_above_two_cores(self):
        obj = self._current()
        obj["host_cores"] = 3
        assert hostkill_gate_skip_reason(obj) is None

    def test_gate_skipped_for_prehostkill_vintages(self):
        obj = self._current()
        for key in (
            "kill_recovery_ms", "replica_repair_hit_rate",
            "aggregate_proofs_per_sec_2host", "hostkill_pairs",
            "hostkill_requests", "hostkill_failovers",
        ):
            obj.pop(key, None)
        reason = hostkill_gate_skip_reason(obj)
        assert reason is not None and "predates" in reason
        assert not any("hostkill gate" in p for p in check_artifact(obj))

    def test_cli_prints_skip_reason(self, tmp_path, capsys):
        obj = self._current()
        obj["host_cores"] = 1
        path = tmp_path / "BENCH_small_hostkill_host.json"
        path.write_text(json.dumps(obj))
        main(["--require-current", str(path)])
        out = capsys.readouterr().out
        assert "hostkill gate SKIPPED" in out and "host_cores=1" in out
