"""Async fetch plane tests: JSON-RPC batch framing (out-of-order ids,
partial errors, no-batch endpoints), the want-queue plane itself
(speculation accounting, verify-before-use, tier short-circuit), the
sync-walker vs plane bit-identity grid, EndpointPool batch demux, the
prefetch reroute, follower depth-2 prefetch, and a seeded chaos run in
batched mode. All hermetic and tier-1."""

import json
import threading
import time
import types

import pytest

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.core.dagcbor import encode as dagcbor_encode
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import (
    generate_event_proofs_for_range,
    generate_event_proofs_for_range_chunked,
    generate_event_proofs_for_range_pipelined,
)
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore
from ipc_proofs_tpu.store.failover import DegradedError, EndpointPool
from ipc_proofs_tpu.store.faults import FaultPlan, FaultySession, LocalLotusSession
from ipc_proofs_tpu.store.fetchplane import FetchPlane, PlaneBlockstore, _child_links
from ipc_proofs_tpu.store.rpc import (
    IntegrityError,
    LotusClient,
    RpcBlockstore,
    RpcError,
)
from ipc_proofs_tpu.utils.metrics import Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001

# errors the batched stack is allowed to surface under faults — anything
# else escaping is a harness finding (mirrors tools/chaos.py)
TYPED_ERRORS = (IntegrityError, RpcError, RuntimeError, ConnectionError,
                TimeoutError, OSError)


class _HttpStatusError(Exception):
    """requests.HTTPError stand-in: carries .response.status_code."""

    def __init__(self, status: int):
        super().__init__(f"HTTP {status}")
        self.response = types.SimpleNamespace(status_code=status)


def _blocks(n: int, tag: bytes = b"blk") -> "list[tuple[CID, bytes]]":
    out = []
    for i in range(n):
        data = (tag + b"-%04d-" % i) * (i % 5 + 2)
        out.append((CID.hash_of(data), data))
    return out


def _store_with(blocks) -> MemoryBlockstore:
    bs = MemoryBlockstore()
    for cid, data in blocks:
        bs.put_keyed(cid, data)
    return bs


def _client(bs, metrics=None, **kw):
    return LotusClient(
        "http://fetchplane-test", session=LocalLotusSession(bs, **kw),
        metrics=metrics or Metrics(),
    )


def _wait_until(cond, timeout_s: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return cond()


@pytest.fixture(scope="module")
def world():
    bs, pairs, _ = build_range_world(
        6, 4, 2, 0.2, signature=SIG, topic1=SUBNET, actor_id=ACTOR,
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
    reference = generate_event_proofs_for_range(bs, pairs, spec).to_json()
    return bs, pairs, spec, reference


# ---------------------------------------------------------------------------
# JSON-RPC batch framing (LotusClient.chain_read_obj_many)


class TestBatchFraming:
    def test_out_of_order_ids_demuxed(self):
        # LocalLotusSession deliberately shuffles batch replies — the demux
        # must reassemble by id, not by position
        blocks = _blocks(16)
        bs = _store_with(blocks)
        m = Metrics()
        client = _client(bs, m)
        got = client.chain_read_obj_many([c for c, _ in blocks])
        assert got == [d for _, d in blocks]
        counters = m.snapshot()["counters"]
        assert counters["rpc.calls"] == 1  # ONE round-trip for 16 blocks
        assert counters["rpc.batch_calls"] == 1
        assert counters["rpc.batched_reads"] == 16
        assert client._session.batch_calls == 1

    def test_missing_block_is_none_in_place(self):
        blocks = _blocks(4)
        bs = _store_with(blocks[:3])  # last block absent from the chain
        got = _client(bs).chain_read_obj_many([c for c, _ in blocks])
        assert got[:3] == [d for _, d in blocks[:3]]
        assert got[3] is None

    def test_empty_and_singleton_skip_batch_framing(self):
        blocks = _blocks(2)
        bs = _store_with(blocks)
        client = _client(bs)
        assert client.chain_read_obj_many([]) == []
        assert client.chain_read_obj_many([blocks[0][0]]) == [blocks[0][1]]
        assert client._session.batch_calls == 0  # singleton went sequential

    def test_partial_error_entry_refetched_sequentially(self):
        # one id inside an otherwise healthy batch answers with an error
        # member: that id (and only that id) refetches through the
        # sequential path, so the caller still sees every block
        blocks = _blocks(8)
        bs = _store_with(blocks)

        class _OneErrorSession(LocalLotusSession):
            def post(self, url, data=None, headers=None, timeout=None):
                resp = super().post(url, data=data, headers=headers, timeout=timeout)
                body = resp.json()
                if isinstance(body, list):
                    body[0] = {
                        "jsonrpc": "2.0",
                        "error": {"code": -32000, "message": "backend flake"},
                        "id": body[0]["id"],
                    }
                return resp

        m = Metrics()
        client = LotusClient(
            "http://partial", session=_OneErrorSession(bs), metrics=m
        )
        got = client.chain_read_obj_many([c for c, _ in blocks])
        assert got == [d for _, d in blocks]
        counters = m.snapshot()["counters"]
        assert counters["rpc.batch_item_retries"] == 1
        assert counters["rpc.calls"] == 2  # the batch + one sequential retry

    def test_unanswered_id_refetched_sequentially(self):
        blocks = _blocks(6)
        bs = _store_with(blocks)

        class _DropOneSession(LocalLotusSession):
            def post(self, url, data=None, headers=None, timeout=None):
                resp = super().post(url, data=data, headers=headers, timeout=timeout)
                body = resp.json()
                if isinstance(body, list) and len(body) > 1:
                    body.pop()  # server silently drops one reply
                return resp

        m = Metrics()
        client = LotusClient("http://drop", session=_DropOneSession(bs), metrics=m)
        got = client.chain_read_obj_many([c for c, _ in blocks])
        assert got == [d for _, d in blocks]
        assert m.snapshot()["counters"]["rpc.batch_item_retries"] == 1

    def test_transient_5xx_retries_and_does_not_demote(self):
        # one 503 (gateway blip) must NOT conclude the capability probe:
        # the batch retries under backoff, succeeds, and the endpoint
        # stays batch-capable
        blocks = _blocks(6)
        bs = _store_with(blocks)

        class _FlakyOnceSession(LocalLotusSession):
            flaked = False

            def post(self, url, data=None, headers=None, timeout=None):
                body = json.loads(data) if data else {}
                if isinstance(body, list) and not self.flaked:
                    self.flaked = True
                    raise _HttpStatusError(503)
                return super().post(url, data=data, headers=headers, timeout=timeout)

        m = Metrics()
        client = LotusClient(
            "http://flaky", session=_FlakyOnceSession(bs), metrics=m,
            max_retries=3, backoff_base_s=0.0, backoff_max_s=0.0,
        )
        got = client.chain_read_obj_many([c for c, _ in blocks])
        assert got == [d for _, d in blocks]
        assert client.supports_batch is True  # NOT demoted to sequential
        counters = m.snapshot()["counters"]
        assert counters["rpc.batch_calls"] == 1
        assert counters.get("rpc.batch_unsupported", 0) == 0

    def test_framing_4xx_concludes_probe_negative(self):
        # a 405 to the array payload IS a framing rejection: probe
        # concludes once, reads degrade to sequential and still succeed
        blocks = _blocks(4)
        bs = _store_with(blocks)

        class _Reject405Session(LocalLotusSession):
            def post(self, url, data=None, headers=None, timeout=None):
                body = json.loads(data) if data else {}
                if isinstance(body, list):
                    raise _HttpStatusError(405)
                return super().post(url, data=data, headers=headers, timeout=timeout)

        m = Metrics()
        client = LotusClient(
            "http://reject", session=_Reject405Session(bs), metrics=m
        )
        got = client.chain_read_obj_many([c for c, _ in blocks])
        assert got == [d for _, d in blocks]
        assert client.supports_batch is False
        assert m.snapshot()["counters"]["rpc.batch_unsupported"] == 1

    def test_confirmed_endpoint_survives_later_4xx(self):
        # hundreds of successful batch calls then a proxy answers one with
        # a 400: a batch-CONFIRMED endpoint is never demoted — the error
        # retries and the next wave ships batched again
        blocks = _blocks(5)
        bs = _store_with(blocks)

        class _LateRejectSession(LocalLotusSession):
            reject_next = False

            def post(self, url, data=None, headers=None, timeout=None):
                body = json.loads(data) if data else {}
                if isinstance(body, list) and self.reject_next:
                    self.reject_next = False
                    raise _HttpStatusError(400)
                return super().post(url, data=data, headers=headers, timeout=timeout)

        m = Metrics()
        session = _LateRejectSession(bs)
        client = LotusClient(
            "http://late", session=session, metrics=m,
            max_retries=3, backoff_base_s=0.0, backoff_max_s=0.0,
        )
        cids = [c for c, _ in blocks]
        assert client.chain_read_obj_many(cids) == [d for _, d in blocks]
        assert client.supports_batch is True
        session.reject_next = True
        assert client.chain_read_obj_many(cids) == [d for _, d in blocks]
        assert client.supports_batch is True  # still batch-capable
        assert m.snapshot()["counters"].get("rpc.batch_unsupported", 0) == 0
        assert m.snapshot()["counters"]["rpc.batch_calls"] == 2

    def test_no_batch_endpoint_probe_concludes_once(self):
        # an old gateway answers array payloads with one "invalid request"
        # object: the capability probe concludes negative ONCE, and every
        # later call goes straight to sequential reads (no re-probing)
        blocks = _blocks(5)
        bs = _store_with(blocks)
        m = Metrics()
        client = _client(bs, m, batch=False)
        assert client.supports_batch is None  # unprobed
        got = client.chain_read_obj_many([c for c, _ in blocks])
        assert got == [d for _, d in blocks]
        assert client.supports_batch is False
        first_calls = client._session.calls  # 1 rejected array + 5 sequential
        assert first_calls == 6
        got = client.chain_read_obj_many([c for c, _ in blocks])
        assert got == [d for _, d in blocks]
        # second wave never retried the array framing
        assert client._session.calls == first_calls + 5
        assert m.snapshot()["counters"]["rpc.batch_unsupported"] == 1


# ---------------------------------------------------------------------------
# the fetch plane itself


class TestFetchPlane:
    def test_demand_gets_are_correct_and_batched(self):
        blocks = _blocks(10)
        bs = _store_with(blocks)
        m = Metrics()
        with FetchPlane(_client(bs, m), local={}, metrics=m) as plane:
            into: dict = {}
            fails = plane.fetch_into([c for c, _ in blocks], into)
            assert fails == {}
            assert into == dict(blocks)
            # a second demand hits the local tier, no new RPC
            calls_before = m.snapshot()["counters"]["rpc.calls"]
            assert plane.get(blocks[0][0]) == blocks[0][1]
            assert m.snapshot()["counters"]["rpc.calls"] == calls_before
        counters = m.snapshot()["counters"]
        assert counters["fetch.batches"] >= 1
        assert counters["fetch.batched_blocks"] == 10

    def test_tier_short_circuit_never_touches_rpc(self):
        blocks = _blocks(3)
        bs = _store_with(blocks)
        client = _client(bs)
        with FetchPlane(client, local=dict(blocks)) as plane:
            for cid, data in blocks:
                assert plane.get(cid) == data
        assert client._session.calls == 0

    def test_speculation_lands_and_demand_consumes(self):
        blocks = _blocks(6, tag=b"spec")
        bs = _store_with(blocks)
        m = Metrics()
        with FetchPlane(_client(bs, m), local={}, speculate_depth=1, metrics=m) as plane:
            plane.offer_links([c for c, _ in blocks])
            assert _wait_until(
                lambda: plane.stats()["speculative_fetched"]
                + m.snapshot()["counters"].get("fetch.speculative_used", 0) >= 6
            )
            for cid, data in blocks:
                assert plane.get(cid) == data
            stats = plane.stats()
            # every speculative fetch was consumed — whether via promotion,
            # landing, or a tier hit on the landed block
            assert stats["waste_pct"] == 0.0
            assert stats["in_flight"] == 0
        assert m.snapshot()["counters"].get("fetch.speculative_wasted", 0) == 0

    def test_mis_speculation_is_counted_never_raised(self):
        blocks = _blocks(5, tag=b"waste")
        bs = _store_with(blocks)
        m = Metrics()
        plane = FetchPlane(_client(bs, m), local={}, speculate_depth=1, metrics=m)
        plane.speculate([c for c, _ in blocks])
        assert _wait_until(lambda: plane.stats()["speculative_fetched"] == 5)
        plane.close()
        stats = plane.stats()
        assert stats["speculative_wasted"] == 5
        assert stats["waste_pct"] == 100.0
        assert m.snapshot()["counters"]["fetch.speculative_wasted"] == 5

    def test_speculate_depth_zero_disables_offers(self):
        blocks = _blocks(4)
        bs = _store_with(blocks)
        client = _client(bs)
        with FetchPlane(client, local={}, speculate_depth=0) as plane:
            plane.offer_links([c for c, _ in blocks])
            time.sleep(0.05)
            assert plane.stats()["speculative_fetched"] == 0
        assert client._session.calls == 0

    def test_plane_chases_links_to_speculate_depth(self):
        # root -> {a, b} -> c: at depth 2 the plane fetches root, a and b
        # on its own, but never chases into c (depth 3)
        leaf_c = dagcbor_encode({"leaf": "c"})
        cid_c = CID.hash_of(leaf_c)
        node_a = dagcbor_encode([cid_c])
        cid_a = CID.hash_of(node_a)
        node_b = dagcbor_encode({"x": 1})
        cid_b = CID.hash_of(node_b)
        root = dagcbor_encode({"kids": [cid_a, cid_b]})
        cid_root = CID.hash_of(root)
        bs = _store_with([])
        for cid, data in ((cid_c, leaf_c), (cid_a, node_a), (cid_b, node_b), (cid_root, root)):
            bs.put_keyed(cid, data)
        assert _child_links(root) == [cid_a, cid_b]
        local: dict = {}
        plane = FetchPlane(_client(bs), local=local, speculate_depth=2)
        plane.speculate([cid_root])
        assert _wait_until(lambda: plane.stats()["speculative_fetched"] == 3)
        plane.close()
        assert cid_root in local and cid_a in local and cid_b in local
        assert cid_c not in local  # depth 3 is past the budget

    def test_speculative_integrity_failure_discards_then_demand_raises(self):
        # a lying endpoint serves corrupt bytes: the speculative copy is
        # discarded before anything observes it; the demand refetch gets
        # the same lie and raises the typed IntegrityError
        good = b"honest block bytes"
        cid = CID.hash_of(good)
        bs = MemoryBlockstore()
        bs.put_keyed(cid, b"corrupt " + good)
        m = Metrics()
        plane = FetchPlane(_client(bs, m), local={}, speculate_depth=1, metrics=m)
        plane.speculate([cid])
        assert _wait_until(
            lambda: m.snapshot()["counters"].get(
                "fetch.speculative_integrity_drops", 0
            ) == 1
        )
        with pytest.raises(IntegrityError):
            plane.get(cid)
        plane.close()
        counters = m.snapshot()["counters"]
        assert counters["fetch.speculative_integrity_drops"] == 1
        assert counters["rpc.integrity_failures"] >= 1

    def test_demand_on_inflight_failed_speculation_raises_not_hangs(self):
        # THE coalesce race: a demand get attaches to a speculative want
        # that has already drained into a dispatcher batch; the fetch then
        # fails verification. The waiter must get the typed IntegrityError
        # via a demand-lane rerun — never wait forever on a want the plane
        # silently forgot.
        good = b"honest bytes for the in-flight race"
        cid = CID.hash_of(good)
        bs = MemoryBlockstore()
        bs.put_keyed(cid, b"corrupt " + good)  # the endpoint always lies
        m = Metrics()
        inner = _client(bs, m)
        gate = threading.Event()
        entered = threading.Event()

        class _GatedClient:
            verifies_integrity = False
            endpoint = "http://gated"

            def chain_read_obj_many(self, cids):
                entered.set()
                assert gate.wait(5.0)
                return inner.chain_read_obj_many(cids)

            def chain_read_obj(self, c):
                return inner.chain_read_obj(c)

        plane = FetchPlane(
            _GatedClient(), local={}, speculate_depth=1, workers=1, metrics=m
        )
        try:
            plane.speculate([cid])
            assert entered.wait(5.0)  # the speculative fetch is in flight
            outcome: list = []

            def _demand():
                try:
                    outcome.append(plane.get(cid))
                except Exception as exc:
                    outcome.append(exc)

            t = threading.Thread(target=_demand)
            t.start()
            time.sleep(0.05)  # let the demand coalesce onto the want
            gate.set()
            t.join(timeout=10.0)
            assert not t.is_alive(), "demand get hung on a failed speculative want"
            assert isinstance(outcome[0], IntegrityError)
        finally:
            gate.set()
            plane.close()

    def test_transient_failure_during_coalesced_speculation_recovers(self):
        # same race, transient flavor: the in-flight speculative batch dies
        # with a transport error while a demand waiter is attached. The
        # want re-lanes to demand and the retry delivers the actual bytes —
        # not None (which would read as "block absent") and not a hang.
        blocks = _blocks(1, tag=b"tr")
        cid, data = blocks[0]
        bs = _store_with(blocks)
        m = Metrics()
        inner = _client(bs, m)
        gate = threading.Event()
        entered = threading.Event()
        fail_state = {"batch": True, "scalar": 1}

        class _FlakyGatedClient:
            verifies_integrity = False
            endpoint = "http://flaky-gated"

            def chain_read_obj_many(self, cids):
                entered.set()
                assert gate.wait(5.0)
                if fail_state["batch"]:
                    fail_state["batch"] = False
                    raise ConnectionError("injected batch outage")
                return inner.chain_read_obj_many(cids)

            def chain_read_obj(self, c):
                if fail_state["scalar"] > 0:
                    fail_state["scalar"] -= 1
                    raise ConnectionError("injected scalar outage")
                return inner.chain_read_obj(c)

        plane = FetchPlane(
            _FlakyGatedClient(), local={}, speculate_depth=1, workers=1, metrics=m
        )
        try:
            plane.speculate([cid])
            assert entered.wait(5.0)
            outcome: list = []

            def _demand():
                try:
                    outcome.append(plane.get(cid))
                except Exception as exc:
                    outcome.append(exc)

            t = threading.Thread(target=_demand)
            t.start()
            time.sleep(0.05)
            gate.set()
            t.join(timeout=10.0)
            assert not t.is_alive(), "demand get hung after transient batch failure"
            assert outcome[0] == data
        finally:
            gate.set()
            plane.close()

    def test_cached_blockstore_serves_as_local_tier(self):
        # CachedBlockstore exposes the get_local/has_local/put_local
        # surface: landings deposit into its cache and the short-circuit
        # reads it back without touching RPC again
        from ipc_proofs_tpu.store.blockstore import CachedBlockstore

        blocks = _blocks(4, tag=b"cbl")
        bs = _store_with(blocks)
        client = _client(bs)
        local = CachedBlockstore(MemoryBlockstore())
        with FetchPlane(client, local=local, metrics=Metrics()) as plane:
            for cid, data in blocks:
                assert plane.get(cid) == data
            calls = client._session.calls
            for cid, data in blocks:  # warm pass: all local, zero RPC
                assert plane.get(cid) == data
            assert client._session.calls == calls
        for cid, data in blocks:
            assert local.get_local(cid) == data
            assert local.has_local(cid)
        assert local._inner.get(blocks[0][0]) is None  # cache only, never inner

    def test_demand_integrity_failure_is_typed(self):
        good = b"another honest block"
        cid = CID.hash_of(good)
        bs = MemoryBlockstore()
        bs.put_keyed(cid, good + b" tampered")
        with FetchPlane(_client(bs), local={}) as plane:
            with pytest.raises(IntegrityError):
                plane.get(cid)

    def test_concurrent_demands_coalesce_into_batches(self):
        blocks = _blocks(32, tag=b"conc")
        bs = _store_with(blocks)
        m = Metrics()
        plane = FetchPlane(_client(bs, m), local={}, batch_max=64, metrics=m)
        results: dict = {}
        errors: list = []

        def _worker(chunk):
            try:
                for cid, data in chunk:
                    results[cid] = plane.get(cid) == data
            except Exception as exc:  # pragma: no cover - surfaced below
                errors.append(exc)

        threads = [
            threading.Thread(target=_worker, args=(blocks[i::4],)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        plane.close()
        assert not errors
        assert len(results) == 32 and all(results.values())
        counters = m.snapshot()["counters"]
        # concurrent walkers rode shared round-trips: strictly fewer
        # round-trips than blocks
        assert counters["rpc.calls"] < 32

    def test_close_fails_outstanding_and_rejects_new_wants(self):
        blocks = _blocks(2)
        bs = _store_with(blocks)
        plane = FetchPlane(_client(bs), local={})
        assert plane.get(blocks[0][0]) == blocks[0][1]
        plane.close()
        with pytest.raises(RuntimeError):
            plane.get(blocks[1][0])
        plane.close()  # idempotent

    def test_plane_blockstore_facade(self):
        blocks = _blocks(3)
        bs = _store_with(blocks)
        store = PlaneBlockstore(FetchPlane(_client(bs), local={}))
        try:
            assert store.get(blocks[0][0]) == blocks[0][1]
            assert store.has(blocks[1][0])
            into: dict = {}
            assert store.prefetch([c for c, _ in blocks], into) == {}
            assert into == dict(blocks)
            with pytest.raises(NotImplementedError):
                store.put_keyed(blocks[0][0], blocks[0][1])
        finally:
            store.close()


# ---------------------------------------------------------------------------
# bit-identity grid: sync walker vs fetch plane × speculate-depth × chunk


class TestBitIdentityGrid:
    @pytest.mark.parametrize("depth", [0, 1, 2])
    @pytest.mark.parametrize("chunk_size", [3, 8])
    def test_grid_bundles_are_byte_identical(self, world, depth, chunk_size):
        bs, pairs, spec, reference = world
        m_sync = Metrics()
        sync = generate_event_proofs_for_range_chunked(
            RpcBlockstore(_client(bs, m_sync), metrics=m_sync), pairs, spec,
            chunk_size=chunk_size, metrics=m_sync,
        )
        assert sync.to_json() == reference
        m = Metrics()
        plane = FetchPlane(_client(bs, m), local={}, speculate_depth=depth, metrics=m)
        try:
            got = generate_event_proofs_for_range_chunked(
                PlaneBlockstore(plane), pairs, spec,
                chunk_size=chunk_size, metrics=m,
            )
        finally:
            plane.close()
        assert got.to_json() == reference
        if depth >= 1:
            # the measurable claim: the plane needs fewer round-trips than
            # one-call-per-block walking for the same byte-identical bundle
            assert (
                m.snapshot()["counters"]["rpc.calls"]
                < m_sync.snapshot()["counters"]["rpc.calls"]
            )

    def test_pipelined_driver_identical_through_plane(self, world):
        bs, pairs, spec, reference = world
        m = Metrics()
        plane = FetchPlane(_client(bs, m), local={}, speculate_depth=1, metrics=m)
        try:
            got = generate_event_proofs_for_range_pipelined(
                PlaneBlockstore(plane), pairs, spec, chunk_size=3,
                metrics=m, scan_threads=2, force_pipeline=True,
            )
        finally:
            plane.close()
        assert got.to_json() == reference

    def test_no_batch_endpoint_still_byte_identical(self, world):
        # plane over an endpoint that rejects batch framing: capability
        # probe degrades to sequential reads, bundle unchanged
        bs, pairs, spec, reference = world
        m = Metrics()
        client = _client(bs, m, batch=False)
        plane = FetchPlane(client, local={}, speculate_depth=1, metrics=m)
        try:
            got = generate_event_proofs_for_range_chunked(
                PlaneBlockstore(plane), pairs, spec, chunk_size=4, metrics=m,
            )
        finally:
            plane.close()
        assert got.to_json() == reference
        assert client.supports_batch is False
        assert m.snapshot()["counters"]["rpc.batch_unsupported"] == 1


# ---------------------------------------------------------------------------
# EndpointPool batch semantics


class TestEndpointPoolBatch:
    def _pool(self, sessions, m, **kw):
        clients = [
            LotusClient(f"http://ep-{i}", session=s, metrics=m)
            for i, s in enumerate(sessions)
        ]
        return EndpointPool(clients, breaker_threshold=3, breaker_reset_s=0.01,
                            metrics=m, **kw)

    def test_integrity_demux_keeps_good_blocks_and_demotes_liar(self):
        blocks = _blocks(8, tag=b"pool")
        bs_good = _store_with(blocks)
        # endpoint 0 lies about exactly one block; its 7 good blocks must
        # be KEPT (content addressing trusts bytes, not servers), only the
        # corrupt one refetches from endpoint 1 — and the liar is demoted
        bs_liar = _store_with(blocks)
        bs_liar.put_keyed(blocks[3][0], b"lie " + blocks[3][1])
        m = Metrics()
        pool = self._pool([LocalLotusSession(bs_liar), LocalLotusSession(bs_good)], m)
        try:
            got = pool.chain_read_obj_many([c for c, _ in blocks])
        finally:
            pool.close()
        assert got == [d for _, d in blocks]
        assert m.snapshot()["counters"]["rpc.integrity_failures"] >= 1
        assert pool._endpoints[0].demotions >= 1

    def test_transport_failure_rotates_whole_batch(self):
        blocks = _blocks(6, tag=b"rot")
        bs = _store_with(blocks)

        class _DeadSession:
            def post(self, url, data=None, headers=None, timeout=None):
                raise ConnectionError("endpoint down")

        m = Metrics()
        clients = [
            LotusClient("http://dead", session=_DeadSession(), metrics=m,
                        max_retries=1, backoff_base_s=0.0, backoff_max_s=0.0),
            LotusClient("http://live", session=LocalLotusSession(bs), metrics=m),
        ]
        pool = EndpointPool(clients, breaker_threshold=2, breaker_reset_s=0.01,
                            metrics=m)
        try:
            got = pool.chain_read_obj_many([c for c, _ in blocks])
        finally:
            pool.close()
        assert got == [d for _, d in blocks]

    def test_plane_over_pool_skips_duplicate_verification(self):
        # EndpointPool verifies per endpoint (verifies_integrity=True), so
        # the plane must trust its bytes — and still deliver them intact
        blocks = _blocks(5, tag=b"pv")
        bs = _store_with(blocks)
        m = Metrics()
        pool = self._pool([LocalLotusSession(bs)], m)
        plane = FetchPlane(pool, local={}, metrics=m)
        try:
            into: dict = {}
            assert plane.fetch_into([c for c, _ in blocks], into) == {}
            assert into == dict(blocks)
        finally:
            plane.close()
            pool.close()


# ---------------------------------------------------------------------------
# prefetch reroute (RpcBlockstore.prefetch through the batched path)


class TestPrefetchReroute:
    def test_prefetch_without_plane_ships_one_batch(self):
        blocks = _blocks(12, tag=b"pf")
        bs = _store_with(blocks)
        m = Metrics()
        store = RpcBlockstore(_client(bs, m), metrics=m)
        into: dict = {}
        assert store.prefetch([c for c, _ in blocks], into) == {}
        assert into == dict(blocks)
        counters = m.snapshot()["counters"]
        assert counters["rpc.batch_calls"] == 1  # ONE wave, not 12 calls
        assert counters["rpc.calls"] == 1

    def test_prefetch_with_attached_plane_rides_the_want_queue(self):
        blocks = _blocks(9, tag=b"pfp")
        bs = _store_with(blocks)
        m = Metrics()
        store = RpcBlockstore(_client(bs, m), metrics=m)
        plane = FetchPlane(store.client, local={}, metrics=m)
        store.attach_plane(plane)
        try:
            into: dict = {}
            assert store.prefetch([c for c, _ in blocks], into) == {}
            assert into == dict(blocks)
        finally:
            plane.close()
        counters = m.snapshot()["counters"]
        assert counters["fetch.wants"] >= 9  # went through the plane
        assert counters["rpc.calls"] < 9  # and rode batched round-trips

    def test_offer_links_forwards_only_with_plane(self):
        blocks = _blocks(3, tag=b"ol")
        bs = _store_with(blocks)
        m = Metrics()
        store = RpcBlockstore(_client(bs, m), metrics=m)
        store.offer_links([c for c, _ in blocks])  # no plane: dropped, no error
        plane = FetchPlane(store.client, local={}, speculate_depth=1, metrics=m)
        store.attach_plane(plane)
        try:
            store.offer_links([c for c, _ in blocks])
            assert _wait_until(lambda: plane.stats()["speculative_fetched"] == 3)
        finally:
            plane.close()


# ---------------------------------------------------------------------------
# follower depth-2 prefetch


class _DictTier:
    """Minimal store with the local-tier surface the follower drives."""

    def __init__(self):
        self.blocks: dict = {}

    def has_local(self, cid) -> bool:
        return cid in self.blocks

    def get_local(self, cid):
        return self.blocks.get(cid)

    def put_local(self, cid, data) -> None:
        self.blocks[cid] = data

    def get(self, cid):
        return self.blocks.get(cid)


class TestFollowerDepth2:
    def test_prefetch_warms_the_second_ring(self, world):
        from ipc_proofs_tpu.storex.follower import ChainFollower, _first_level_links

        bs, pairs, _, _ = world
        tier = _DictTier()
        m = Metrics()
        client = _client(bs, m)
        follower = ChainFollower(client, tier, metrics=m)
        tipset = pairs[0].parent
        follower.prefetch_tipset(tipset)
        # find actual level-2 CIDs: state root -> level1 node -> its links
        # (links the chain has no block for — e.g. actor code CIDs — are
        # unfetchable by anyone and excluded from the expectation)
        root = tipset.blocks[0].parent_state_root
        level2 = []
        for l1 in _first_level_links(bs.get(root)):
            data = bs.get(l1)
            if data is not None:
                level2.extend(
                    l2 for l2 in _first_level_links(data) if bs.get(l2) is not None
                )
        assert level2, "fixture has no depth-2 ring under the state root"
        warmed = sum(1 for cid in level2 if tier.has_local(cid))
        assert warmed == len(level2)  # the whole second ring landed
        # and the waves shipped as batch arrays, not per-block calls
        counters = m.snapshot()["counters"]
        assert counters["rpc.batch_calls"] >= 2
        assert counters["follow.blocks_prefetched"] == len(tier.blocks)

    def test_prefetch_is_idempotent_and_rpc_free_when_warm(self, world):
        from ipc_proofs_tpu.storex.follower import ChainFollower

        bs, pairs, _, _ = world
        tier = _DictTier()
        m = Metrics()
        client = _client(bs, m)
        follower = ChainFollower(client, tier, metrics=m)
        follower.prefetch_tipset(pairs[0].parent)
        calls = client._session.calls
        fetched = m.snapshot()["counters"]["follow.blocks_prefetched"]
        follower.prefetch_tipset(pairs[0].parent)
        # warm pass: every block that EXISTS is local, so nothing is
        # refetched and nothing lands; the only admissible extra wire is a
        # re-probe of links the chain has no block for (never satisfiable)
        assert m.snapshot()["counters"]["follow.blocks_prefetched"] == fetched
        assert client._session.calls <= calls + 1


# ---------------------------------------------------------------------------
# seeded chaos in batched mode


class TestChaosBatched:
    def test_identical_or_typed_error_under_faults(self, world):
        bs, pairs, spec, reference = world
        import random as _random

        outcomes = {"identical": 0, "typed_error": 0}
        for seed in range(8):
            for rate in (0.05, 0.35):
                m = Metrics()
                plans = [
                    FaultPlan(seed * 77 + i, fault_rate=rate) for i in range(2)
                ]
                clients = [
                    LotusClient(
                        f"http://chaos-batch-{i}",
                        session=FaultySession(
                            LocalLotusSession(bs), plans[i], sleep=lambda s: None
                        ),
                        metrics=m, max_retries=2,
                        backoff_base_s=0.0005, backoff_max_s=0.002,
                        rng=_random.Random(seed + i),
                    )
                    for i in range(2)
                ]
                pool = EndpointPool(clients, breaker_threshold=3,
                                    breaker_reset_s=0.01, metrics=m)
                plane = FetchPlane(pool, local={}, speculate_depth=1, metrics=m)
                try:
                    bundle = generate_event_proofs_for_range_pipelined(
                        PlaneBlockstore(plane), pairs, spec, chunk_size=3,
                        metrics=m, scan_threads=1, scan_retries=2,
                        force_pipeline=True,
                    )
                except TYPED_ERRORS:
                    outcomes["typed_error"] += 1
                    continue
                finally:
                    plane.close()
                    pool.close()
                # a completed run must be BYTE-identical — a batched, faulty
                # wire is never allowed to change what a proof says
                assert bundle.to_json() == reference, f"seed {seed} diverged"
                outcomes["identical"] += 1
        assert outcomes["identical"] > 0  # non-vacuous: faults were absorbed

    def test_batch_corruption_is_caught_by_the_pool(self, world):
        # bitflip-only plans: any completed run had every flip caught and
        # refetched; the flip count must equal the integrity-failure count
        bs, pairs, spec, reference = world
        import random as _random

        completed = flips = 0
        for seed in range(6):
            m = Metrics()
            plans = [
                FaultPlan(seed * 13 + i, fault_rate=0.2, kinds=("bitflip",))
                for i in range(2)
            ]
            clients = [
                LotusClient(
                    f"http://bf-batch-{i}",
                    session=FaultySession(
                        LocalLotusSession(bs), plans[i], sleep=lambda s: None
                    ),
                    metrics=m, max_retries=2,
                    backoff_base_s=0.0005, backoff_max_s=0.001,
                    rng=_random.Random(seed + i),
                )
                for i in range(2)
            ]
            pool = EndpointPool(clients, breaker_threshold=3,
                                breaker_reset_s=0.01, metrics=m)
            plane = FetchPlane(pool, local={}, speculate_depth=1, metrics=m)
            try:
                bundle = generate_event_proofs_for_range_pipelined(
                    PlaneBlockstore(plane), pairs, spec, chunk_size=3,
                    metrics=m, scan_threads=1, scan_retries=2,
                    force_pipeline=True,
                )
            except (IntegrityError, DegradedError):
                # typed refusal is always acceptable — IntegrityError when
                # every endpoint served corrupt bytes, DegradedError when
                # the flips tripped every breaker (lotus_down fail-fast)
                continue
            finally:
                plane.close()
                pool.close()
            completed += 1
            assert bundle.to_json() == reference, f"seed {seed} diverged"
            injected = sum(
                p.snapshot()["by_kind"].get("bitflip", 0) for p in plans
            )
            flips += injected
            assert (
                m.snapshot()["counters"].get("rpc.integrity_failures", 0)
                == injected
            )
        assert completed > 0 and flips > 0  # non-vacuous
