"""Execution-order semantics: BLS-before-secp, tipset order, first-seen dedup,
TxMeta CID recompute — and the two-pass witness-size optimization."""

import pytest

from ipc_proofs_tpu.core.cid import CID, RAW
from ipc_proofs_tpu.ipld.amt import amt_build_v0
from ipc_proofs_tpu.proofs.chain import Tipset
from ipc_proofs_tpu.proofs.exec_order import (
    build_execution_order,
    reconstruct_execution_order,
)
from ipc_proofs_tpu.state.header import BlockHeader
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore, put_cbor


def _msg(i: int) -> CID:
    return CID.hash_of(f"m{i}".encode(), codec=RAW)


def _header(store, bls, secp, height=10) -> tuple[CID, BlockHeader]:
    bls_root = amt_build_v0(store, bls)
    secp_root = amt_build_v0(store, secp)
    txmeta = put_cbor(store, [bls_root, secp_root])
    header = BlockHeader(
        parents=[CID.hash_of(b"gp")],
        height=height,
        parent_state_root=CID.hash_of(b"sr"),
        parent_message_receipts=CID.hash_of(b"rc"),
        messages=txmeta,
    )
    raw = header.encode()
    cid = CID.hash_of(raw)
    store.put_keyed(cid, raw)
    return cid, header


class TestExecOrder:
    def test_bls_before_secp_within_block(self):
        bs = MemoryBlockstore()
        cid, header = _header(bs, bls=[_msg(1), _msg(2)], secp=[_msg(3), _msg(4)])
        tipset = Tipset(cids=[cid], blocks=[header], height=10)
        assert build_execution_order(bs, tipset) == [_msg(1), _msg(2), _msg(3), _msg(4)]

    def test_blocks_in_tipset_order(self):
        bs = MemoryBlockstore()
        c1, h1 = _header(bs, bls=[_msg(1)], secp=[_msg(2)])
        c2, h2 = _header(bs, bls=[_msg(3)], secp=[])
        tipset = Tipset(cids=[c1, c2], blocks=[h1, h2], height=10)
        assert build_execution_order(bs, tipset) == [_msg(1), _msg(2), _msg(3)]
        flipped = Tipset(cids=[c2, c1], blocks=[h2, h1], height=10)
        assert build_execution_order(bs, flipped) == [_msg(3), _msg(1), _msg(2)]

    def test_cross_block_dedup_keeps_first_occurrence(self):
        # The same message may appear in several blocks of a tipset; only the
        # first occurrence counts (reference events/utils.rs:76-90).
        bs = MemoryBlockstore()
        c1, h1 = _header(bs, bls=[_msg(1), _msg(2)], secp=[])
        c2, h2 = _header(bs, bls=[_msg(2), _msg(3)], secp=[_msg(1)])
        tipset = Tipset(cids=[c1, c2], blocks=[h1, h2], height=10)
        assert build_execution_order(bs, tipset) == [_msg(1), _msg(2), _msg(3)]

    def test_reconstruct_matches_build_and_verifies_txmeta(self):
        bs = MemoryBlockstore()
        c1, h1 = _header(bs, bls=[_msg(1)], secp=[_msg(2)])
        tipset = Tipset(cids=[c1], blocks=[h1], height=10)
        online = build_execution_order(bs, tipset)
        offline = reconstruct_execution_order(bs, [c1])
        assert online == offline

    def test_reconstruct_rejects_forged_txmeta(self):
        # A header whose TxMeta block bytes don't hash to the header's
        # `messages` CID must fail the recompute check.
        bs = MemoryBlockstore()
        cid, header = _header(bs, bls=[_msg(1)], secp=[])
        forged_bls = amt_build_v0(bs, [_msg(99)])
        forged_secp = amt_build_v0(bs, [])
        from ipc_proofs_tpu.core.dagcbor import encode

        # overwrite the TxMeta bytes under its ORIGINAL cid (tampered witness)
        bs.put_keyed(header.messages, encode([forged_bls, forged_secp]))
        with pytest.raises(ValueError, match="TxMeta mismatch"):
            reconstruct_execution_order(bs, [cid])


class TestTwoPassWitnessSavings:
    def test_two_pass_smaller_than_full_scan(self):
        """The witness must exclude event AMTs of non-matching receipts —
        the reference README's 60-80% savings claim, pinned structurally."""
        from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
        from ipc_proofs_tpu.proofs.generator import EventProofSpec, generate_proof_bundle

        sig = "NewTopDownMessage(bytes32,uint256)"
        big = b"\xee" * 400  # fat payloads make non-matching AMTs expensive
        events = [[EventFixture(emitter=1, signature=sig, topic1="hit", data=b"\x01" * 32)]]
        for i in range(20):
            events.append(
                [EventFixture(emitter=1, signature="Noise(uint256)", topic1="miss", data=big)]
            )
        world = build_chain([ContractFixture(actor_id=1)], events)
        bundle = generate_proof_bundle(
            world.store,
            world.parent,
            world.child,
            [],
            [EventProofSpec(event_signature=sig, topic_1="hit", actor_id_filter=1)],
        )
        assert len(bundle.event_proofs) == 1
        world_bytes = sum(len(d) for _, d in world.store.items())
        witness_bytes = bundle.witness_bytes()
        # sparse match (1 of 21 receipts) ⇒ witness ≪ full chain state
        assert witness_bytes < world_bytes * 0.5, (witness_bytes, world_bytes)
