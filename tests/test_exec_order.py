"""Execution-order semantics: BLS-before-secp, tipset order, first-seen dedup,
TxMeta CID recompute — and the two-pass witness-size optimization."""

import pytest

from ipc_proofs_tpu.core.cid import CID, RAW
from ipc_proofs_tpu.ipld.amt import amt_build_v0
from ipc_proofs_tpu.proofs.chain import Tipset
from ipc_proofs_tpu.proofs.exec_order import (
    build_execution_order,
    reconstruct_execution_order,
)
from ipc_proofs_tpu.state.header import BlockHeader
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore, put_cbor


def _msg(i: int) -> CID:
    return CID.hash_of(f"m{i}".encode(), codec=RAW)


def _header(store, bls, secp, height=10) -> tuple[CID, BlockHeader]:
    bls_root = amt_build_v0(store, bls)
    secp_root = amt_build_v0(store, secp)
    txmeta = put_cbor(store, [bls_root, secp_root])
    header = BlockHeader(
        parents=[CID.hash_of(b"gp")],
        height=height,
        parent_state_root=CID.hash_of(b"sr"),
        parent_message_receipts=CID.hash_of(b"rc"),
        messages=txmeta,
    )
    raw = header.encode()
    cid = CID.hash_of(raw)
    store.put_keyed(cid, raw)
    return cid, header


class TestExecOrder:
    def test_bls_before_secp_within_block(self):
        bs = MemoryBlockstore()
        cid, header = _header(bs, bls=[_msg(1), _msg(2)], secp=[_msg(3), _msg(4)])
        tipset = Tipset(cids=[cid], blocks=[header], height=10)
        assert build_execution_order(bs, tipset) == [_msg(1), _msg(2), _msg(3), _msg(4)]

    def test_blocks_in_tipset_order(self):
        bs = MemoryBlockstore()
        c1, h1 = _header(bs, bls=[_msg(1)], secp=[_msg(2)])
        c2, h2 = _header(bs, bls=[_msg(3)], secp=[])
        tipset = Tipset(cids=[c1, c2], blocks=[h1, h2], height=10)
        assert build_execution_order(bs, tipset) == [_msg(1), _msg(2), _msg(3)]
        flipped = Tipset(cids=[c2, c1], blocks=[h2, h1], height=10)
        assert build_execution_order(bs, flipped) == [_msg(3), _msg(1), _msg(2)]

    def test_cross_block_dedup_keeps_first_occurrence(self):
        # The same message may appear in several blocks of a tipset; only the
        # first occurrence counts (reference events/utils.rs:76-90).
        bs = MemoryBlockstore()
        c1, h1 = _header(bs, bls=[_msg(1), _msg(2)], secp=[])
        c2, h2 = _header(bs, bls=[_msg(2), _msg(3)], secp=[_msg(1)])
        tipset = Tipset(cids=[c1, c2], blocks=[h1, h2], height=10)
        assert build_execution_order(bs, tipset) == [_msg(1), _msg(2), _msg(3)]

    def test_reconstruct_matches_build_and_verifies_txmeta(self):
        bs = MemoryBlockstore()
        c1, h1 = _header(bs, bls=[_msg(1)], secp=[_msg(2)])
        tipset = Tipset(cids=[c1], blocks=[h1], height=10)
        online = build_execution_order(bs, tipset)
        offline = reconstruct_execution_order(bs, [c1])
        assert online == offline

    def test_reconstruct_rejects_forged_txmeta(self):
        # A header whose TxMeta block bytes don't hash to the header's
        # `messages` CID must fail the recompute check.
        bs = MemoryBlockstore()
        cid, header = _header(bs, bls=[_msg(1)], secp=[])
        forged_bls = amt_build_v0(bs, [_msg(99)])
        forged_secp = amt_build_v0(bs, [])
        from ipc_proofs_tpu.core.dagcbor import encode

        # overwrite the TxMeta bytes under its ORIGINAL cid (tampered witness)
        bs.put_keyed(header.messages, encode([forged_bls, forged_secp]))
        with pytest.raises(ValueError, match="TxMeta mismatch"):
            reconstruct_execution_order(bs, [cid])


class TestTwoPassWitnessSavings:
    def test_two_pass_smaller_than_full_scan(self):
        """The witness must exclude event AMTs of non-matching receipts —
        the reference README's 60-80% savings claim, pinned structurally."""
        from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
        from ipc_proofs_tpu.proofs.generator import EventProofSpec, generate_proof_bundle

        sig = "NewTopDownMessage(bytes32,uint256)"
        big = b"\xee" * 400  # fat payloads make non-matching AMTs expensive
        events = [[EventFixture(emitter=1, signature=sig, topic1="hit", data=b"\x01" * 32)]]
        for i in range(20):
            events.append(
                [EventFixture(emitter=1, signature="Noise(uint256)", topic1="miss", data=big)]
            )
        world = build_chain([ContractFixture(actor_id=1)], events)
        bundle = generate_proof_bundle(
            world.store,
            world.parent,
            world.child,
            [],
            [EventProofSpec(event_signature=sig, topic_1="hit", actor_id_filter=1)],
        )
        assert len(bundle.event_proofs) == 1
        world_bytes = sum(len(d) for _, d in world.store.items())
        witness_bytes = bundle.witness_bytes()
        # sparse match (1 of 21 receipts) ⇒ witness ≪ full chain state
        assert witness_bytes < world_bytes * 0.5, (witness_bytes, world_bytes)


class TestNativeExecOrderBatch:
    """The C walker (scan_ext.collect_exec_orders) must agree with the
    scalar reconstruction, including its caught-error degradation."""

    def _world(self):
        bs = MemoryBlockstore()
        h1, _ = _header(bs, [_msg(1), _msg(2)], [_msg(3)])
        h2, _ = _header(bs, [_msg(3), _msg(4)], [])  # dedup: m3 already seen
        h3, _ = _header(bs, [], [_msg(5)], height=11)
        return bs, [[h1, h2], [h3]]

    def test_matches_scalar(self):
        from ipc_proofs_tpu.proofs.exec_order import (
            reconstruct_execution_order,
            reconstruct_execution_orders_batch,
        )

        bs, groups = self._world()
        batch = reconstruct_execution_orders_batch(bs, groups)
        if batch is None:
            pytest.skip("native extension unavailable")
        for g, group in enumerate(groups):
            scalar = reconstruct_execution_order(bs, group)
            # C-side first-seen dedup must reproduce the scalar execution
            # order exactly (the _world fixture repeats m3 across blocks)
            assert batch[g] == [c.to_bytes() for c in scalar]

    def test_missing_txmeta_degrades_to_none(self):
        from ipc_proofs_tpu.proofs.exec_order import (
            reconstruct_execution_orders_batch,
        )

        bs, groups = self._world()
        # a header whose TxMeta block is absent from the store
        orphan = BlockHeader(
            parents=[CID.hash_of(b"gp")], height=12,
            parent_state_root=CID.hash_of(b"sr"),
            parent_message_receipts=CID.hash_of(b"rc"),
            messages=CID.hash_of(b"missing-txmeta"),
        )
        raw = orphan.encode()
        cid = CID.hash_of(raw)
        bs.put_keyed(cid, raw)
        batch = reconstruct_execution_orders_batch(bs, groups + [[cid]])
        if batch is None:
            pytest.skip("native extension unavailable")
        assert batch[0] is not None and batch[1] is not None
        assert batch[2] is None  # scalar raises KeyError → caught → None

    def test_non_canonical_txmeta_falls_back_scalar(self):
        from ipc_proofs_tpu.core.dagcbor import encode
        from ipc_proofs_tpu.proofs.exec_order import (
            reconstruct_execution_orders_batch,
        )

        bs = MemoryBlockstore()
        bls_root = amt_build_v0(bs, [_msg(7)])
        secp_root = amt_build_v0(bs, [])
        canonical = encode([bls_root, secp_root])
        # non-minimal byte-string head for the first tag-42 payload:
        # 0x58 len → 0x59 0x00 len (same value, longer head)
        idx = canonical.index(b"\x58")
        tampered = canonical[:idx] + b"\x59\x00" + canonical[idx + 1 :]
        tx_cid = CID.hash_of(tampered)
        bs.put_keyed(tx_cid, tampered)
        header = BlockHeader(
            parents=[CID.hash_of(b"gp")], height=13,
            parent_state_root=CID.hash_of(b"sr"),
            parent_message_receipts=CID.hash_of(b"rc"),
            messages=tx_cid,
        )
        raw = header.encode()
        hcid = CID.hash_of(raw)
        bs.put_keyed(hcid, raw)
        batch = reconstruct_execution_orders_batch(bs, [[hcid]])
        if batch is None:
            pytest.skip("native extension unavailable")
        # scalar recomputes the CANONICAL encoding → CID mismatch → ValueError
        # → None; the batch path must agree (via its scalar fallback)
        assert batch[0] is None

    def test_generation_walker_matches_python(self):
        from ipc_proofs_tpu.proofs.exec_order import (
            build_execution_order,
            collect_exec_orders_for_pairs,
        )

        bs, groups = self._world()
        txmeta_groups = []
        for group in groups:
            metas = []
            for hcid in group:
                metas.append(BlockHeader.decode(bs.get(hcid)).messages)
            txmeta_groups.append(metas)
        walks = collect_exec_orders_for_pairs(bs, txmeta_groups)
        if walks is None:
            pytest.skip("native extension unavailable")
        for g, group in enumerate(groups):
            headers = [BlockHeader.decode(bs.get(h)) for h in group]

            class FakeTipset:
                blocks = headers

            scalar = build_execution_order(bs, FakeTipset)
            order, touched = walks[g]
            assert order == [c.to_bytes() for c in scalar]
            assert len(touched) >= 2  # at least the TxMeta + AMT root blocks

    def test_malformed_parent_header_rejected_like_scalar(self):
        """The C walker only extracts the messages field; a header that
        BlockHeader.decode rejects (parents not CIDs here) must still
        degrade the group to None, exactly like the scalar ValueError."""
        import pytest as _pytest

        from ipc_proofs_tpu.core.dagcbor import encode
        from ipc_proofs_tpu.proofs.exec_order import (
            reconstruct_execution_order,
            reconstruct_execution_orders_batch,
        )

        bs = MemoryBlockstore()
        good, _ = _header(bs, [_msg(1)], [])
        # 16-tuple with a valid messages CID at index 10 but malformed
        # parents (index 5 not a CID list)
        txmeta = BlockHeader.decode(bs.get(good)).messages
        forged_fields = [None] * 16
        forged_fields[5] = ["not-a-cid"]
        forged_fields[6] = b""
        forged_fields[7] = 10
        forged_fields[8] = CID.hash_of(b"sr")
        forged_fields[9] = CID.hash_of(b"rc")
        forged_fields[10] = txmeta
        forged_fields[12] = 0
        forged_fields[14] = 0
        forged_fields[15] = b""
        raw = encode(forged_fields)
        forged = CID.hash_of(raw)
        bs.put_keyed(forged, raw)

        with _pytest.raises(ValueError):
            reconstruct_execution_order(bs, [good, forged])
        batch = reconstruct_execution_orders_batch(bs, [[good, forged], [good]])
        if batch is None:
            _pytest.skip("native extension unavailable")
        assert batch[0] is None  # scalar ValueError → caught → None
        assert batch[1] is not None


class TestBatchedTxmetaRecompute:
    def test_corrupt_txmeta_localizes_to_its_group(self):
        """A corrupted TxMeta block (bytes don't hash to the header's CID)
        must fail ONLY its group — the range-wide blake2b batch reports
        unclean and the scalar localization nulls exactly that group."""
        from ipc_proofs_tpu.proofs.exec_order import (
            reconstruct_execution_order,
            reconstruct_execution_orders_batch,
        )

        bs = MemoryBlockstore()
        h1, hdr1 = _header(bs, [_msg(21)], [_msg(22)])
        h2, _hdr2 = _header(bs, [_msg(23)], [])
        tx1 = hdr1.messages
        groups = [[h1], [h2]]
        clean = reconstruct_execution_orders_batch(bs, groups)
        if clean is None:
            pytest.skip("native extension unavailable")
        assert clean[0] is not None and clean[1] is not None

        # corrupt group 0's TxMeta bytes in place (same CID key)
        raw = bs.get(tx1)
        import ipc_proofs_tpu.core.dagcbor as dagcbor

        bls, secp = dagcbor.decode(raw)
        forged = dagcbor.encode([secp, bls])  # valid shape, wrong bytes
        bs.raw_map()[tx1.to_bytes()] = forged
        bs._blocks[tx1] = forged

        batch = reconstruct_execution_orders_batch(bs, groups)
        assert batch[0] is None  # corrupted group fails
        assert batch[1] is not None  # untouched group still verifies
        # scalar parity: the scalar reconstruction rejects the same group
        with pytest.raises(ValueError):
            reconstruct_execution_order(bs, [h1])
        assert [c.to_bytes() for c in reconstruct_execution_order(bs, [h2])] == batch[1]
