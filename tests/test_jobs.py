"""Write-ahead job journal tests: framing round-trips, torn-tail recovery,
corruption detection (bit flips, bad magic, duplicate records, manifest
mismatch), fail-soft degrade, and byte-identical resume through the real
range drivers. All hermetic and tier-1."""

import json
import os
import struct
import zlib

import pytest

from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.jobs import (
    JOBS_JOURNAL_NAME,
    JOBS_MANIFEST_NAME,
    JOURNAL_MAGIC,
    JournalError,
    JournalWriter,
    job_manifest,
    read_journal,
    resume_or_create,
)
from ipc_proofs_tpu.jobs.journal import encode_record
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import (
    generate_event_proofs_for_range_chunked,
    generate_event_proofs_for_range_pipelined,
)
from ipc_proofs_tpu.utils.metrics import Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001

_HEADER = struct.Struct("<4sII")


def _frame(payload: bytes) -> bytes:
    return _HEADER.pack(JOURNAL_MAGIC, len(payload), zlib.crc32(payload)) + payload


def _write_records(path, objs):
    with open(path, "ab") as fh:
        for obj in objs:
            fh.write(_frame(encode_record(obj)))


class TestJournalFraming:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "j.bin")
        w = JournalWriter(path)
        objs = [{"t": "chunk", "chunk": i, "x": "y" * i} for i in range(5)]
        for obj in objs:
            assert w.append(obj) is True
        w.close()
        records, good_offset, torn = read_journal(path)
        assert records == objs
        assert not torn
        assert good_offset == os.path.getsize(path)

    @pytest.mark.parametrize("cut", [1, 4, 11, 12, 13, 20])
    def test_torn_tail_is_recovered_not_fatal(self, tmp_path, cut):
        """A frame cut anywhere — inside the header or the payload — is
        crash residue: the reader keeps the good prefix and flags torn."""
        path = str(tmp_path / "j.bin")
        _write_records(path, [{"chunk": 0}])
        partial = _frame(encode_record({"chunk": 1, "pad": "z" * 40}))[:cut]
        with open(path, "ab") as fh:
            fh.write(partial)
        records, good_offset, torn = read_journal(path)
        assert records == [{"chunk": 0}]
        assert torn
        assert good_offset == os.path.getsize(path) - cut

    def test_bit_flip_in_complete_record_raises(self, tmp_path):
        path = str(tmp_path / "j.bin")
        _write_records(path, [{"chunk": 0, "bundle": "b" * 64}, {"chunk": 1}])
        with open(path, "r+b") as fh:
            fh.seek(_HEADER.size + 10)  # inside the first payload
            byte = fh.read(1)
            fh.seek(-1, os.SEEK_CUR)
            fh.write(bytes([byte[0] ^ 0x40]))
        with pytest.raises(JournalError, match="checksum mismatch"):
            read_journal(path)

    def test_bad_magic_raises(self, tmp_path):
        path = str(tmp_path / "j.bin")
        _write_records(path, [{"chunk": 0}])
        with open(path, "r+b") as fh:
            fh.write(b"XXXX")
        with pytest.raises(JournalError, match="bad journal magic"):
            read_journal(path)

    def test_non_json_payload_with_valid_crc_raises(self, tmp_path):
        """CRC-valid garbage (interleaved writer, not bit rot) is still a
        typed error — never a silently wrong record."""
        path = str(tmp_path / "j.bin")
        payload = b"\xff\xfenot json"
        with open(path, "wb") as fh:
            fh.write(_HEADER.pack(JOURNAL_MAGIC, len(payload), zlib.crc32(payload)))
            fh.write(payload)
        with pytest.raises(JournalError, match="not valid JSON"):
            read_journal(path)

    def test_empty_journal(self, tmp_path):
        path = str(tmp_path / "j.bin")
        open(path, "wb").close()
        assert read_journal(path) == ([], 0, False)


class _BrokenFile:
    """File stub whose writes fail like a full/read-only disk."""

    def __init__(self, err=28):  # ENOSPC
        self._err = err

    def write(self, data):
        raise OSError(self._err, os.strerror(self._err))

    def flush(self):
        pass

    def fileno(self):
        raise OSError(self._err, os.strerror(self._err))

    def close(self):
        pass


class TestFailSoft:
    def test_enospc_degrades_permanently_and_counts(self, tmp_path):
        path = str(tmp_path / "j.bin")
        metrics = Metrics()
        w = JournalWriter(path, metrics=metrics)
        assert w.append({"chunk": 0}) is True
        w._fh = _BrokenFile()  # disk fills mid-run
        assert w.append({"chunk": 1}) is False
        assert w.degraded
        # degrade is permanent: even if the disk recovers, a partial frame
        # may sit at the tail — appending after it would corrupt mid-file
        assert w.append({"chunk": 2}) is False
        w.close()
        counters = metrics.snapshot()["counters"]
        assert counters["jobs.journal_failures"] == 2
        # the record that made it before the failure is intact on disk
        records, _, torn = read_journal(path)
        assert records == [{"chunk": 0}] and not torn

    def test_degraded_job_still_finishes_with_correct_bundle(self, tmp_path):
        """End to end: journal on a read-only dir → run completes, bundle
        identical, failures counted, no exception."""
        store, pairs, _ = build_range_world(
            4, 2, 2, 0.3, signature=SIG, topic1=SUBNET, actor_id=ACTOR
        )
        spec = EventProofSpec(
            event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR
        )
        reference = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=2, scan_threads=2, force_pipeline=True
        )
        job_dir = tmp_path / "job"
        metrics = Metrics()
        job = resume_or_create(
            str(job_dir), job_manifest(b"spec", pairs, 2), metrics=metrics
        )
        job._writer._fh = _BrokenFile(30)  # EROFS from the first append on
        try:
            for i in range(2):
                assert job.commit_chunk(i, None, reference) is False
            assert job.degraded
            # the in-memory completed map still serves the run
            assert job.has_chunk(0) and job.has_chunk(1)
        finally:
            job.close()
        assert metrics.snapshot()["counters"]["jobs.journal_failures"] == 2


def _manifest(n_pairs=4, chunk_size=2):
    store, pairs, _ = build_range_world(
        n_pairs, 1, 1, 0.0, signature=SIG, topic1=SUBNET, actor_id=ACTOR
    )
    return job_manifest(b"params", pairs, chunk_size)


class TestResumeOrCreate:
    def test_fresh_dir_writes_manifest(self, tmp_path):
        man = _manifest()
        with resume_or_create(str(tmp_path / "job"), man) as job:
            assert job.completed == {}
        with open(tmp_path / "job" / JOBS_MANIFEST_NAME) as fh:
            assert json.load(fh) == man

    def test_manifest_mismatch_raises(self, tmp_path):
        job_dir = str(tmp_path / "job")
        resume_or_create(job_dir, _manifest(chunk_size=2)).close()
        with pytest.raises(JournalError, match="manifest mismatch"):
            resume_or_create(job_dir, _manifest(chunk_size=4))

    def test_duplicate_chunk_record_raises(self, tmp_path):
        job_dir = tmp_path / "job"
        man = _manifest()
        resume_or_create(str(job_dir), man).close()
        _write_records(
            str(job_dir / JOBS_JOURNAL_NAME),
            [
                {"t": "chunk", "chunk": 0, "digest": "d", "bundle": {}, "verify": None},
                {"t": "chunk", "chunk": 0, "digest": "d", "bundle": {}, "verify": None},
            ],
        )
        with pytest.raises(JournalError, match="duplicate journal record"):
            resume_or_create(str(job_dir), man)

    def test_chunk_index_out_of_range_raises(self, tmp_path):
        job_dir = tmp_path / "job"
        man = _manifest()  # n_chunks == 2
        resume_or_create(str(job_dir), man).close()
        _write_records(
            str(job_dir / JOBS_JOURNAL_NAME),
            [{"t": "chunk", "chunk": 7, "digest": "d", "bundle": {}, "verify": None}],
        )
        with pytest.raises(JournalError, match="outside"):
            resume_or_create(str(job_dir), man)

    def test_verdict_before_chunk_raises(self, tmp_path):
        job_dir = tmp_path / "job"
        man = _manifest()
        resume_or_create(str(job_dir), man).close()
        _write_records(
            str(job_dir / JOBS_JOURNAL_NAME),
            [{"t": "verdict", "chunk": 0, "digest": "d", "verify": 1}],
        )
        with pytest.raises(JournalError, match="precedes"):
            resume_or_create(str(job_dir), man)

    def test_unknown_record_type_raises(self, tmp_path):
        job_dir = tmp_path / "job"
        man = _manifest()
        resume_or_create(str(job_dir), man).close()
        _write_records(
            str(job_dir / JOBS_JOURNAL_NAME), [{"t": "mystery", "chunk": 0}]
        )
        with pytest.raises(JournalError, match="unknown journal record type"):
            resume_or_create(str(job_dir), man)

    def test_torn_tail_truncated_and_appendable(self, tmp_path):
        job_dir = tmp_path / "job"
        man = _manifest()
        resume_or_create(str(job_dir), man).close()
        jpath = str(job_dir / JOBS_JOURNAL_NAME)
        good = {"t": "chunk", "chunk": 0, "digest": "d", "bundle": {"k": 1}, "verify": None}
        _write_records(jpath, [good])
        committed_size = os.path.getsize(jpath)
        with open(jpath, "ab") as fh:  # crash mid-append of chunk 1
            fh.write(_frame(encode_record({"t": "chunk", "chunk": 1}))[:9])
        metrics = Metrics()
        with resume_or_create(str(job_dir), man, metrics=metrics) as job:
            assert set(job.completed) == {0}
            assert os.path.getsize(jpath) == committed_size  # tail gone
            assert job.commit_chunk(1, "d2", _FakeBundle({"k": 2})) is True
        records, _, torn = read_journal(jpath)
        assert [r["chunk"] for r in records] == [0, 1] and not torn
        assert metrics.snapshot()["counters"]["jobs.chunks_replayed"] == 1

    def test_resume_counters_and_gauge(self, tmp_path):
        job_dir = str(tmp_path / "job")
        man = _manifest()
        with resume_or_create(job_dir, man) as job:
            job.commit_chunk(0, "d0", _FakeBundle({"a": 1}), verify=7)
            job.commit_verdict(0, "d0", verify=9)
        metrics = Metrics()
        with resume_or_create(job_dir, man, metrics=metrics) as job:
            assert job.completed[0]["verify"] == 9  # verdict replayed on top
            snap = metrics.snapshot()
            assert snap["counters"]["jobs.chunks_replayed"] == 1
            assert "jobs.resume_ms" in snap["counters"]
            assert snap["gauges"]["jobs.journal_bytes"] == job.journal_bytes > 0

    def test_bundle_obj_digest_mismatch_raises(self, tmp_path):
        with resume_or_create(str(tmp_path / "job"), _manifest()) as job:
            job.commit_chunk(0, "aaa", _FakeBundle({}))
            assert job.bundle_obj(0, "aaa") == {}
            with pytest.raises(JournalError, match="different range"):
                job.bundle_obj(0, "bbb")


class _FakeBundle:
    def __init__(self, obj):
        self._obj = obj

    def to_json_obj(self):
        return self._obj


@pytest.fixture(scope="module")
def range_world():
    store, pairs, n_match = build_range_world(
        6, 3, 2, 0.3, signature=SIG, topic1=SUBNET, actor_id=ACTOR
    )
    spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)
    return store, pairs, spec


class TestRangeDriverResume:
    def test_pipelined_resume_byte_identical(self, tmp_path, range_world):
        store, pairs, spec = range_world
        reference = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=2, scan_threads=2, force_pipeline=True
        ).to_json()
        job_dir = str(tmp_path / "job")
        first = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=2, scan_threads=2,
            force_pipeline=True, job_dir=job_dir,
        )
        assert first.to_json() == reference
        metrics = Metrics()
        resumed = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=2, scan_threads=2,
            force_pipeline=True, job_dir=job_dir, metrics=metrics,
        )
        assert resumed.to_json() == reference
        counters = metrics.snapshot()["counters"]
        assert counters["jobs.chunks_replayed"] == 3
        assert counters["range_chunks_resumed"] == 3
        assert "range_chunks_generated" not in counters

    def test_chunked_resume_byte_identical(self, tmp_path, range_world):
        store, pairs, spec = range_world
        reference = generate_event_proofs_for_range_chunked(
            store, pairs, spec, chunk_size=2
        ).to_json()
        job_dir = str(tmp_path / "job")
        assert (
            generate_event_proofs_for_range_chunked(
                store, pairs, spec, chunk_size=2, job_dir=job_dir
            ).to_json()
            == reference
        )
        metrics = Metrics()
        resumed = generate_event_proofs_for_range_chunked(
            store, pairs, spec, chunk_size=2, job_dir=job_dir, metrics=metrics
        )
        assert resumed.to_json() == reference
        assert metrics.snapshot()["counters"]["range_chunks_resumed"] == 3

    def test_job_dir_bound_to_request(self, tmp_path, range_world):
        """Re-running with a different chunking against the same job dir is
        a different request: typed failure, never a silently spliced bundle."""
        store, pairs, spec = range_world
        job_dir = str(tmp_path / "job")
        generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=2, scan_threads=2,
            force_pipeline=True, job_dir=job_dir,
        )
        with pytest.raises(JournalError, match="manifest mismatch"):
            generate_event_proofs_for_range_pipelined(
                store, pairs, spec, chunk_size=3, scan_threads=2,
                force_pipeline=True, job_dir=job_dir,
            )

    def test_partial_journal_resume_generates_only_missing(
        self, tmp_path, range_world
    ):
        """Drop the last committed chunk record: the resume regenerates
        exactly that chunk and reuses the rest."""
        store, pairs, spec = range_world
        job_dir = tmp_path / "job"
        reference = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=2, scan_threads=2,
            force_pipeline=True, job_dir=str(job_dir),
        ).to_json()
        jpath = str(job_dir / JOBS_JOURNAL_NAME)
        records, _, _ = read_journal(jpath)
        assert len(records) == 3
        with open(jpath, "r+b") as fh:  # amputate the final record cleanly
            data = fh.read()
            last = _frame(encode_record(records[-1]))
            assert data.endswith(last)
            fh.truncate(len(data) - len(last))
        metrics = Metrics()
        resumed = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=2, scan_threads=2,
            force_pipeline=True, job_dir=str(job_dir), metrics=metrics,
        )
        assert resumed.to_json() == reference
        counters = metrics.snapshot()["counters"]
        assert counters["range_chunks_resumed"] == 2
        assert counters["range_chunks_generated"] == 1
        # the journal is whole again
        records, _, torn = read_journal(jpath)
        assert len(records) == 3 and not torn


class TestCompaction:
    def test_manual_compact_shrinks_and_replays_identically(self, tmp_path):
        """chunk+verdict records fold into one merged record per chunk;
        the swapped-in journal replays to the same completed map."""
        job_dir = str(tmp_path / "job")
        man = _manifest()  # n_chunks == 2
        metrics = Metrics()
        with resume_or_create(job_dir, man, metrics=metrics) as job:
            for i in range(2):
                job.commit_chunk(i, f"d{i}", _FakeBundle({"k": i}))
                job.commit_verdict(i, f"d{i}", {"ok": True})
            before = dict(job.completed)
            size_before = os.path.getsize(tmp_path / "job" / JOBS_JOURNAL_NAME)
            assert job.compact() is True
            assert job.compactions == 1
        jpath = str(tmp_path / "job" / JOBS_JOURNAL_NAME)
        assert os.path.getsize(jpath) < size_before
        records, _, torn = read_journal(jpath)
        assert not torn
        assert [r["chunk"] for r in records] == [0, 1]  # one record per chunk
        assert all(r["verify"] == {"ok": True} for r in records)
        with resume_or_create(job_dir, man) as job2:
            assert job2.completed == before
        counters = metrics.snapshot()["counters"]
        assert counters["jobs.compactions"] == 1
        assert metrics.snapshot()["gauges"]["jobs.journal_bytes"] == os.path.getsize(jpath)

    def test_compact_noop_when_empty(self, tmp_path):
        with resume_or_create(str(tmp_path / "job"), _manifest()) as job:
            assert job.compact() is False
            assert job.compactions == 0

    def test_compact_noop_when_degraded(self, tmp_path):
        with resume_or_create(str(tmp_path / "job"), _manifest()) as job:
            job.commit_chunk(0, "d", _FakeBundle({}))
            job._writer._fh = _BrokenFile(30)
            job.commit_chunk(1, "d", _FakeBundle({}))  # degrades the writer
            assert job.degraded
            assert job.compact() is False

    def test_auto_compaction_threshold_and_growth_guard(self, tmp_path):
        """threshold=1 → every commit is past the threshold, but the 1.5×
        growth guard keeps re-snapshots from firing on every append."""
        job_dir = str(tmp_path / "job")
        man = _manifest(n_pairs=8, chunk_size=2)  # n_chunks == 4
        with resume_or_create(job_dir, man, compact_threshold_bytes=1) as job:
            for i in range(4):
                job.commit_chunk(i, f"d{i}", _FakeBundle({"payload": "x" * 200}))
            assert job.compactions >= 1
            n_compactions = job.compactions
            assert n_compactions < 4  # the growth guard gated some commits
            before = dict(job.completed)
        jpath = str(tmp_path / "job" / JOBS_JOURNAL_NAME)
        records, _, torn = read_journal(jpath)
        assert not torn and len(records) == 4
        with resume_or_create(job_dir, man) as job2:
            assert job2.completed == before

    def test_env_var_arms_auto_compaction(self, tmp_path, monkeypatch):
        monkeypatch.setenv("IPC_JOURNAL_COMPACT_BYTES", "1")
        with resume_or_create(str(tmp_path / "job"), _manifest()) as job:
            job.commit_chunk(0, "d", _FakeBundle({"k": 0}))
            assert job.compactions == 1
        monkeypatch.setenv("IPC_JOURNAL_COMPACT_BYTES", "not-a-number")
        with resume_or_create(str(tmp_path / "job2"), _manifest()) as job:
            job.commit_chunk(0, "d", _FakeBundle({"k": 0}))
            assert job.compactions == 0  # malformed env ignored, warned

    def test_driver_run_with_compaction_is_byte_identical(
        self, tmp_path, range_world, monkeypatch
    ):
        """End to end: auto-compaction armed under the real pipelined
        driver — the bundle is unchanged and a resume replays the
        compacted journal to the same bytes."""
        store, pairs, spec = range_world
        reference = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=2, scan_threads=2, force_pipeline=True
        ).to_json()
        monkeypatch.setenv("IPC_JOURNAL_COMPACT_BYTES", "1")
        job_dir = str(tmp_path / "job")
        first = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=2, scan_threads=2,
            force_pipeline=True, job_dir=job_dir,
        )
        assert first.to_json() == reference
        metrics = Metrics()
        resumed = generate_event_proofs_for_range_pipelined(
            store, pairs, spec, chunk_size=2, scan_threads=2,
            force_pipeline=True, job_dir=job_dir, metrics=metrics,
        )
        assert resumed.to_json() == reference
        counters = metrics.snapshot()["counters"]
        assert counters["range_chunks_resumed"] == 3
        assert "range_chunks_generated" not in counters
