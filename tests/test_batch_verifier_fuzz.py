"""Seeded randomized differential fuzz: batch ↔ scalar event verification.

The parametrized tamper cases in test_batch_verifier.py pin known attack
shapes; this sweep drives BOTH verify paths through hundreds of randomly
mutated bundles — claim-field garbage (wrong/huge/negative/float indices,
malformed hex, swapped CIDs, shuffled proofs) and witness damage (dropped
and bit-flipped blocks) — asserting the grouped batch replay agrees with
the scalar loop on every verdict vector AND on every raised exception
(type and message). Any divergence is a parity bug by the module's own
contract (`event_verifier.verify_event_proof` docstring).
"""

import dataclasses
import random

import pytest

from ipc_proofs_tpu.core.cid import CID, RAW
from ipc_proofs_tpu.proofs.bundle import EventProofBundle, ProofBlock
from ipc_proofs_tpu.proofs.event_verifier import verify_event_proof
from ipc_proofs_tpu.proofs.scan_native import native_scan_available

from tests.test_batch_verifier import make_bundle

pytestmark = pytest.mark.skipif(
    not native_scan_available(), reason="native scan extension unavailable"
)


def _outcome(bundle, batch):
    """Run one path; capture ("ok", verdicts) or ("raise", type, message).

    Agreement is asserted on the outcome kind, the verdict vector, and the
    exception FAMILY (KeyError vs the ValueError family — the only classes
    the verifier's own error handling distinguishes). Exact types and
    messages are carried for debugging but not compared: the two paths
    parse malformed inputs through different implementations of the same
    acceptance set, which reject with different wordings ('truncated CID
    multihash digest' vs 'malformed CID bytes') and occasionally different
    ValueError subclasses (the decoders surface invalid CBOR text as
    UnicodeDecodeError, the scanner's validating skip as plain
    ValueError)."""
    accept = lambda *_: True
    try:
        return ("ok", verify_event_proof(bundle, accept, accept, batch=batch))
    except Exception as exc:  # noqa: BLE001 — parity includes the exception
        family = (
            "KeyError"
            if isinstance(exc, KeyError)
            else "ValueError"
            if isinstance(exc, ValueError)
            else type(exc).__name__
        )
        return ("raise", family, type(exc).__name__, str(exc))


def _comparable(outcome):
    """Collapse an outcome to what the parity contract actually promises.

    - ("ok", verdicts): verdict vectors must be identical.
    - both raise: the verifier aborts through exactly two families —
      KeyError (missing witness blocks) and ValueError (malformed bytes /
      claims). When a bundle carries SEVERAL independent fatal conditions,
      the two paths may surface different ones first (the batch path
      batch-parses every group's CID strings before any witness access;
      the scalar loop hits whatever its proof order reaches first), so
      both-raise-within-the-abort-family counts as agreement. Anything
      outside that family (TypeError, etc.) keeps its name — a path
      crashing in an unplanned way must never be masked.
    - one raises while the other returns verdicts: always a failure.
    """
    if outcome[0] == "ok":
        return outcome[:2]
    family = outcome[1]
    return ("raise", "abort" if family in ("KeyError", "ValueError") else family)


def _mutate_proof(rng: random.Random, proof):
    """One random claim-field mutation (returns a new EventProof)."""
    ed = proof.event_data
    choice = rng.randrange(12)
    if choice == 0:
        return dataclasses.replace(
            proof, exec_index=rng.choice([-1, 0, 3, 2**31, 2**63, 10**20])
        )
    if choice == 1:
        return dataclasses.replace(
            proof, event_index=rng.choice([-5, 1, 2**31 - 1, 2**40])
        )
    if choice == 2:  # JSON-plausible non-int indices
        as_float = (
            float(proof.exec_index)
            if isinstance(proof.exec_index, int)
            else 1.5  # proof already mutated to a non-number
        )
        return dataclasses.replace(
            proof, exec_index=rng.choice([as_float, "0", None])
        )
    if choice == 3:
        return dataclasses.replace(
            proof, child_epoch=proof.child_epoch + rng.choice([-1, 1, 1000])
        )
    if choice == 4:
        return dataclasses.replace(
            proof, parent_epoch=proof.parent_epoch + rng.choice([-1, 1])
        )
    if choice == 5:
        return dataclasses.replace(
            proof,
            message_cid=str(CID.hash_of(rng.randbytes(8), codec=RAW)),
        )
    if choice == 6:  # malformed CID strings
        return dataclasses.replace(
            proof,
            child_block_cid=rng.choice(
                ["", "b", "not-a-cid", proof.child_block_cid[:-1]]
            ),
        )
    if choice == 7:
        return dataclasses.replace(
            proof,
            parent_tipset_cids=rng.choice(
                [
                    [],
                    list(reversed(proof.parent_tipset_cids)) * 2,
                    [str(CID.hash_of(rng.randbytes(4)))],
                ]
            ),
        )
    if choice == 8:
        return dataclasses.replace(
            proof, event_data=dataclasses.replace(ed, emitter=rng.randrange(5000))
        )
    if choice == 9:
        topics = list(ed.topics)
        if topics:
            i = rng.randrange(len(topics))
            t = topics[i]
            topics[i] = rng.choice(
                [
                    t.upper().replace("0X", "0x"),
                    t[:-1],
                    t + "0",
                    t.removeprefix("0x"),
                    t[:6] + " " + t[6:],
                    "0x" + "cd" * 32,
                ]
            )
        return dataclasses.replace(
            proof, event_data=dataclasses.replace(ed, topics=topics)
        )
    if choice == 10:
        return dataclasses.replace(
            proof,
            event_data=dataclasses.replace(
                ed,
                data=rng.choice(
                    [ed.data + "ff", ed.data[:-1], "0x" + "0" * 63, ""]
                ),
            ),
        )
    return dataclasses.replace(
        proof, event_data=dataclasses.replace(ed, topics=ed.topics + [ed.data])
    )


def _mutate_bundle(rng: random.Random, proofs, blocks):
    """Apply one structural mutation; returns (proofs, blocks)."""
    kind = rng.randrange(10)
    if kind == 0 and blocks:  # drop a witness block
        drop = rng.randrange(len(blocks))
        return proofs, [b for i, b in enumerate(blocks) if i != drop]
    if kind == 1 and blocks:  # bit-flip inside a witness block (CID kept)
        i = rng.randrange(len(blocks))
        data = bytearray(blocks[i].data)
        if data:
            data[rng.randrange(len(data))] ^= 1 << rng.randrange(8)
        blocks = list(blocks)
        blocks[i] = ProofBlock(cid=blocks[i].cid, data=bytes(data))
        return proofs, blocks
    if kind == 2 and len(proofs) >= 2:  # cross-wire two proofs' claims
        i, j = rng.sample(range(len(proofs)), 2)
        proofs = list(proofs)
        proofs[i] = dataclasses.replace(
            proofs[i],
            message_cid=proofs[j].message_cid,
            exec_index=proofs[j].exec_index,
        )
        return proofs, blocks
    if kind == 3:  # duplicate a proof
        proofs = list(proofs) + [rng.choice(proofs)]
        return proofs, blocks
    if kind == 4:  # shuffle proof order (groups re-form differently)
        proofs = list(proofs)
        rng.shuffle(proofs)
        return proofs, blocks
    # default: mutate 1-3 random proofs' claim fields
    proofs = list(proofs)
    for _ in range(rng.randrange(1, 4)):
        i = rng.randrange(len(proofs))
        proofs[i] = _mutate_proof(rng, proofs[i])
    return proofs, blocks


class TestAdversarialWitnessBytes:
    """Crafted (not random) witness corruption in positions the C walker's
    TARGETED parse skips but the scalar replay's full decode reads. Before
    verify-side full-block validation (scan_ext Scan.validate), each of
    these scanned clean in the batch path while the scalar path rejected
    it — the exact batch-accepts/scalar-rejects soundness divergences from
    the round-4 review."""

    def _assert_agree(self, proofs, blocks):
        mutated = EventProofBundle(proofs=proofs, blocks=blocks)
        scalar = _outcome(mutated, batch=False)
        batch = _outcome(mutated, batch=True)
        assert _comparable(scalar) == _comparable(batch), (
            f"scalar={scalar!r} batch={batch!r}"
        )
        return scalar

    def test_unsupported_tag_in_skipped_receipt_field(self):
        """Tag 43 spliced into a receipt's return_data — a field the
        scanner skips; the scalar decode of the same node rejects it."""
        bundle = make_bundle(n_pairs=1)
        # receipt tuples in the fixture encode as [0, b'', gas, CID]:
        # 0x84 0x00 0x40 0x1a... — replace the empty return_data (0x40)
        # with tag 43 over a uint (0xd8 0x2b 0x00); arrays count items,
        # not bytes, so the node stays structurally parseable
        pattern = b"\x84\x00\x40\x1a"
        hit = next(
            (i for i, b in enumerate(bundle.blocks) if pattern in b.data), None
        )
        assert hit is not None, "fixture receipt-node shape changed"
        data = bundle.blocks[hit].data
        at = data.index(pattern)
        garbled = data[: at + 2] + b"\xd8\x2b\x00" + data[at + 3 :]
        blocks = list(bundle.blocks)
        blocks[hit] = ProofBlock(cid=blocks[hit].cid, data=garbled)
        outcome = self._assert_agree(bundle.proofs, blocks)
        # and the corruption must actually bite: not all-True anymore
        assert outcome[0] != "ok" or not all(outcome[1])

    def test_trailing_bytes_after_any_block(self):
        """A validly-framed block with garbage appended: cbor_decode
        rejects trailing bytes, so the batch walk must too."""
        bundle = make_bundle(n_pairs=1)
        for i in range(len(bundle.blocks)):
            blocks = list(bundle.blocks)
            blocks[i] = ProofBlock(cid=blocks[i].cid, data=blocks[i].data + b"\x00")
            self._assert_agree(bundle.proofs, blocks)

    def test_deep_nesting_bomb_does_not_crash(self):
        """A block of 100k nested arrays: the decoders cap nesting depth;
        the scanner's skip must consume a depth budget rather than the C
        stack (the pre-fix skip recursed uncapped — a segfault vector)."""
        bundle = make_bundle(n_pairs=1)
        bomb = b"\x81" * 100_000 + b"\x80"
        for i in range(len(bundle.blocks)):
            blocks = list(bundle.blocks)
            blocks[i] = ProofBlock(cid=blocks[i].cid, data=bomb)
            self._assert_agree(bundle.proofs, blocks)

    def test_huge_length_header_no_oob(self):
        """A bytes head claiming length 2^63: the bounds check must compare
        unsigned — a signed cast wraps negative, passes the check, and
        drives the parser out of bounds (crash) instead of rejecting."""
        bundle = make_bundle(n_pairs=1)
        huge = b"\x5b" + (1 << 63).to_bytes(8, "big")
        for i in range(len(bundle.blocks)):
            blocks = list(bundle.blocks)
            blocks[i] = ProofBlock(cid=blocks[i].cid, data=huge)
            self._assert_agree(bundle.proofs, blocks)

    def test_depth_boundary_with_tag_content_agrees(self):
        """Tag-42 content consumes a nesting level in the decoders; blocks
        with a tag at the 512-depth boundary must validate (or fail)
        identically in the scanner's skip."""
        from ipc_proofs_tpu.core.cid import CID as _CID

        cid_bytes = _CID.hash_of(b"x").to_bytes()
        tag42 = b"\xd8\x2a" + bytes([0x58, len(cid_bytes) + 1]) + b"\x00" + cid_bytes
        bundle = make_bundle(n_pairs=1)
        for n_arrays in (510, 511, 512):
            payload = b"\x81" * n_arrays + tag42
            blocks = list(bundle.blocks)
            blocks[0] = ProofBlock(cid=blocks[0].cid, data=payload)
            self._assert_agree(bundle.proofs, blocks)


def _run_differential(rng, seed, base, rounds):
    """Shared mutate-and-compare loop for the fixed-shape and shape-varied
    differentials: mutate (occasionally twice), run both verify paths,
    assert outcome parity. Returns (agree_raise, agree_ok) tallies."""
    agree_raise = agree_ok = 0
    for _ in range(rounds):
        proofs, blocks = _mutate_bundle(rng, base.proofs, base.blocks)
        if rng.random() < 0.3:
            proofs, blocks = _mutate_bundle(rng, proofs, blocks)
        mutated = EventProofBundle(proofs=proofs, blocks=blocks)
        scalar = _outcome(mutated, batch=False)
        batch = _outcome(mutated, batch=True)
        assert _comparable(scalar) == _comparable(batch), (
            f"divergence under seed={seed}: scalar={scalar!r} batch={batch!r}"
        )
        if scalar[0] == "raise":
            agree_raise += 1
        else:
            agree_ok += 1
    return agree_raise, agree_ok


@pytest.mark.parametrize("seed", [0xD1CE, 77310])
def test_shape_varied_mutation_differential(seed):
    """Same mutation machinery over base worlds of VARIED shape (pair
    count, claim encoding) — the fixed-shape differential below only ever
    explores one base world's acceptance territory. In-suite slice of the
    round-5 shape-varied soak (2,000 worlds x 120 mutants, clean)."""
    rng = random.Random(seed)
    agree_raise = agree_ok = 0
    for _ in range(4):
        base = make_bundle(
            n_pairs=rng.choice([1, 2, 3, 4]),
            encoding=rng.choice(["compact", "concat"]),
        )
        r, o = _run_differential(rng, seed, base, 30)
        agree_raise += r
        agree_ok += o
    assert agree_raise and agree_ok  # the sweep exercised both regimes


@pytest.mark.parametrize("seed", [0xF3, 0xBEEF, 2026, 106567516])
def test_randomized_mutation_differential(seed):
    # 106567516: round-5 soak find — a mutant whose event-entry value
    # decoded as CBOR text crashed the scalar replay's hex compare
    # (AttributeError) where the native scan rejects; StampedEvent.from_cbor
    # now rejects non-bytes values / non-text keys / non-u64 emitters.
    rng = random.Random(seed)
    agree_raise, agree_ok = _run_differential(rng, seed, make_bundle(n_pairs=2), 150)
    # sanity: the sweep actually exercised both regimes
    assert agree_raise and agree_ok
