"""Multi-host helpers (single-process + virtual-device behavior)."""

import pytest

jax = pytest.importorskip("jax")

from ipc_proofs_tpu.parallel.multihost import (  # noqa: E402
    global_mesh,
    host_local_pairs,
    initialize_distributed,
)


class TestMultihost:
    def test_initialize_noop_without_coordinator(self, monkeypatch):
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        assert initialize_distributed() is False

    def test_global_mesh_shapes(self):
        mesh = global_mesh(sp=2)
        assert mesh.axis_names == ("dp", "sp")
        assert mesh.shape["sp"] == 2
        assert mesh.shape["dp"] * 2 == len(jax.devices())
        with pytest.raises(ValueError):
            global_mesh(sp=3)

    def test_host_local_pairs_partitioning(self):
        pairs = list(range(10))
        shard0 = host_local_pairs(pairs, process_id=0, num_processes=3)
        shard1 = host_local_pairs(pairs, process_id=1, num_processes=3)
        shard2 = host_local_pairs(pairs, process_id=2, num_processes=3)
        assert shard0 + shard1 + shard2 == pairs
        assert max(len(shard0), len(shard1), len(shard2)) <= 4

    def test_host_local_pairs_defaults_to_jax_process(self):
        pairs = list(range(4))
        assert host_local_pairs(pairs) == pairs  # single process owns all


class TestTwoProcessDistributed:
    """A REAL two-process jax.distributed run on localhost CPU: coordinator
    + two workers, each with 2 virtual devices, a (dp=2, sp=2) global mesh
    whose dp axis crosses the process boundary, global arrays assembled
    from host-local shards, and the sharded match pipeline executed over
    the mesh. The combined result must equal the single-process reference
    — this exercises every line of parallel/multihost.py for real."""

    def test_two_process_match_equals_single_process(self, tmp_path):
        import json
        import os
        import socket
        import subprocess
        import sys

        import numpy as np

        # free localhost port for the coordinator
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
        env = {
            k: v
            for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_NUM_PROCESSES",
                         "JAX_PROCESS_ID", "JAX_COORDINATOR_ADDRESS")
        }
        outs = [tmp_path / "p0.json", tmp_path / "p1.json"]
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(i), "2", str(port), str(outs[i])],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
            for i in range(2)
        ]
        try:
            for p in procs:
                _, err = p.communicate(timeout=300)
                assert p.returncode == 0, err.decode(errors="replace")[-2000:]
        finally:
            for p in procs:
                p.kill()

        results = [json.loads(o.read_text()) for o in outs]
        # both processes observed the same global run
        assert results[0]["devices"] == results[1]["devices"] == 4
        assert results[0]["mesh"] == results[1]["mesh"] == {"dp": 2, "sp": 2}
        assert results[0]["count"] == results[1]["count"]
        assert results[0]["hits"] == results[1]["hits"]
        # the epoch range was partitioned contiguously and completely
        assert results[0]["my_pairs"] == [0, 1, 2, 3]
        assert results[1]["my_pairs"] == [4, 5, 6, 7]

        # single-process reference over the identical seeded world
        from ipc_proofs_tpu.parallel.pipeline import (
            make_specs_u32,
            match_pipeline,
            synthetic_event_batch,
        )

        batch = synthetic_event_batch(
            8, 4, 4, b"\x11" * 32, b"\x22" * 32, match_rate=0.3, seed=7
        )
        spec0, spec1 = make_specs_u32(b"\x11" * 32, b"\x22" * 32)
        ref_hits, _, ref_count = match_pipeline(
            batch.topics, batch.n_topics, batch.emitters, batch.valid,
            spec0, spec1, np.int32(-1),
        )
        assert results[0]["count"] == int(ref_count)
        assert results[0]["hits"] == np.asarray(ref_hits).astype(int).ravel().tolist()
