"""Multi-host helpers (single-process + virtual-device behavior)."""

import pytest

jax = pytest.importorskip("jax")

from ipc_proofs_tpu.parallel.multihost import (  # noqa: E402
    global_mesh,
    host_local_pairs,
    initialize_distributed,
)


class TestMultihost:
    def test_initialize_noop_without_coordinator(self, monkeypatch):
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        assert initialize_distributed() is False

    def test_global_mesh_shapes(self):
        mesh = global_mesh(sp=2)
        assert mesh.axis_names == ("dp", "sp")
        assert mesh.shape["sp"] == 2
        assert mesh.shape["dp"] * 2 == len(jax.devices())
        with pytest.raises(ValueError):
            global_mesh(sp=3)

    def test_host_local_pairs_partitioning(self):
        pairs = list(range(10))
        shard0 = host_local_pairs(pairs, process_id=0, num_processes=3)
        shard1 = host_local_pairs(pairs, process_id=1, num_processes=3)
        shard2 = host_local_pairs(pairs, process_id=2, num_processes=3)
        assert shard0 + shard1 + shard2 == pairs
        assert max(len(shard0), len(shard1), len(shard2)) <= 4

    def test_host_local_pairs_defaults_to_jax_process(self):
        pairs = list(range(4))
        assert host_local_pairs(pairs) == pairs  # single process owns all
