"""Unit tests for the host stage pipeline (`parallel.pipeline.run_pipeline`):
ordering under out-of-order completion, bounded-queue backpressure, error
propagation without deadlock, and thread-safe stage metrics."""

import threading
import time

import pytest

from ipc_proofs_tpu.parallel.pipeline import PipelineStage, run_pipeline
from ipc_proofs_tpu.utils.metrics import Metrics


def _run_with_deadline(fn, seconds=30.0):
    """Run fn on a thread with a join deadline: a deadlocked pipeline fails
    the test instead of hanging the whole tier-1 suite."""
    out: dict = {}

    def target():
        try:
            out["result"] = fn()
        except BaseException as exc:  # noqa: BLE001
            out["exc"] = exc

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(seconds)
    assert not t.is_alive(), "pipeline deadlocked (join deadline hit)"
    if "exc" in out:
        raise out["exc"]
    return out["result"]


class TestOrdering:
    def test_single_stage_identity_order(self):
        results = run_pipeline(list(range(50)), [PipelineStage("x", lambda v: v * 2)])
        assert results == [v * 2 for v in range(50)]

    def test_multi_worker_stage_preserves_input_order(self):
        """Workers finishing out of order (reverse-proportional sleeps) must
        still emit downstream in input order."""

        def slow_for_early(v):
            time.sleep(0.002 * (20 - v) if v < 20 else 0)
            return v

        seen_by_second_stage = []

        def record(v):
            seen_by_second_stage.append(v)
            return v

        results = run_pipeline(
            list(range(20)),
            [
                PipelineStage("jitter", slow_for_early, workers=4),
                PipelineStage("record", record, workers=1),
            ],
            depth=3,
        )
        assert results == list(range(20))
        assert seen_by_second_stage == list(range(20))

    def test_three_stages_compose(self):
        results = run_pipeline(
            list(range(10)),
            [
                PipelineStage("a", lambda v: v + 1, workers=3),
                PipelineStage("b", lambda v: v * 10, workers=2),
                PipelineStage("c", lambda v: v - 5),
            ],
            depth=1,
        )
        assert results == [(v + 1) * 10 - 5 for v in range(10)]

    def test_empty_items(self):
        assert run_pipeline([], [PipelineStage("x", lambda v: v)]) == []

    def test_more_workers_than_items(self):
        results = run_pipeline([7], [PipelineStage("x", lambda v: v + 1, workers=8)], depth=1)
        assert results == [8]

    def test_no_stages_raises(self):
        with pytest.raises(ValueError):
            run_pipeline([1, 2], [])


class TestBackpressure:
    def test_bounded_depth_limits_readahead(self):
        """With depth=2 a fast producer can run at most depth + workers
        items ahead of a slow consumer — never the whole input."""
        lock = threading.Lock()
        produced: list[int] = []
        consumed: list[int] = []
        max_lead = 0

        def produce(v):
            nonlocal max_lead
            with lock:
                produced.append(v)
                max_lead = max(max_lead, len(produced) - len(consumed))
            return v

        def consume(v):
            time.sleep(0.005)
            with lock:
                consumed.append(v)
            return v

        run_pipeline(
            list(range(30)),
            [PipelineStage("fast", produce, workers=1), PipelineStage("slow", consume)],
            depth=2,
        )
        # 1 in the producer, 2 buffered, 1 in the consumer (+1 slack)
        assert max_lead <= 5
        assert consumed == list(range(30))


class TestErrorPropagation:
    def test_worker_exception_propagates(self):
        class Boom(RuntimeError):
            pass

        def maybe_boom(v):
            if v == 7:
                raise Boom("worker died")
            return v

        def run():
            with pytest.raises(Boom, match="worker died"):
                run_pipeline(
                    list(range(100)),
                    [
                        PipelineStage("scan", maybe_boom, workers=4),
                        PipelineStage("record", lambda v: v),
                    ],
                    depth=2,
                )

        _run_with_deadline(run)

    def test_downstream_exception_cancels_blocked_producers(self):
        """A failure in the LAST stage must unwedge producers blocked on the
        bounded queue (the classic pipeline deadlock)."""

        def slow_fail(v):
            time.sleep(0.01)
            raise ValueError("sink failed")

        def run():
            with pytest.raises(ValueError, match="sink failed"):
                run_pipeline(
                    list(range(200)),
                    [
                        PipelineStage("produce", lambda v: bytes(1000), workers=2),
                        PipelineStage("sink", slow_fail),
                    ],
                    depth=1,
                )

        _run_with_deadline(run)

    def test_first_exception_wins(self):
        def boom(v):
            raise KeyError(v)

        def run():
            with pytest.raises(KeyError):
                run_pipeline(list(range(10)), [PipelineStage("boom", boom, workers=3)])

        _run_with_deadline(run)


class TestStageMetrics:
    def test_stage_timers_recorded_per_stage(self):
        m = Metrics()
        run_pipeline(
            list(range(8)),
            [
                PipelineStage("a", lambda v: time.sleep(0.005) or v, workers=4,
                              metrics_stage="pipe_a"),
                PipelineStage("b", lambda v: v, metrics_stage="pipe_b"),
            ],
            depth=2,
            metrics=m,
        )
        snap = m.snapshot()["timers"]
        assert snap["pipe_a"]["calls"] == 8
        assert snap["pipe_b"]["calls"] == 8
        # 4 workers sleeping concurrently: busy exceeds union wall
        assert snap["pipe_a"]["total_s"] > snap["pipe_a"]["wall_s"]


class TestDrainOnCancel:
    def test_queued_items_salvaged_before_reraise(self):
        """A drain_on_cancel sink must still run the entries already queued
        to it when an upstream stage fails — the journal-commit guarantee:
        finished chunks get recorded even though the run dies."""
        salvaged: list[int] = []
        gate = threading.Event()

        def scan(v):
            if v == 6:
                gate.wait(10)  # let earlier items queue up at the sink
                raise RuntimeError("scan died")
            return v

        def sink(v):
            salvaged.append(v)
            gate.set()  # sink is alive → earlier items flowed; now fail scan
            time.sleep(0.05)  # pin the worker so later items stay queued
            return v

        def run():
            with pytest.raises(RuntimeError, match="scan died"):
                run_pipeline(
                    list(range(7)),
                    [
                        PipelineStage("scan", scan, workers=2),
                        PipelineStage("sink", sink, drain_on_cancel=True),
                    ],
                    depth=4,
                )

        _run_with_deadline(run)
        # every item that reached the sink's queue before the failure ran;
        # exact count depends on timing, but nothing queued was dropped and
        # order is preserved for what did run
        assert salvaged == sorted(salvaged)
        assert salvaged and salvaged[0] == 0

    def test_no_drain_without_flag(self):
        """Default stages drop their queue on cancellation (old behavior)."""
        ran: list[int] = []
        started = threading.Event()

        def scan(v):
            if v == 0:
                return v
            started.wait(10)
            raise RuntimeError("boom")

        def sink(v):
            ran.append(v)
            started.set()
            time.sleep(0.2)  # keep the worker busy past the cancellation
            return v

        def run():
            with pytest.raises(RuntimeError, match="boom"):
                run_pipeline(
                    list(range(6)),
                    [
                        PipelineStage("scan", scan, workers=2),
                        PipelineStage("sink", sink),
                    ],
                    depth=4,
                )

        _run_with_deadline(run)
        assert len(ran) <= 2  # nothing salvaged beyond what was in-flight

    def test_drain_swallows_sink_exceptions(self):
        """Best-effort salvage: a sink that fails during drain must not mask
        the original pipeline exception."""
        gate = threading.Event()

        def scan(v):
            if v == 3:
                gate.wait(10)
                raise KeyError("original")
            return v

        calls: list[int] = []

        def sink(v):
            calls.append(v)
            gate.set()
            time.sleep(0.05)
            if v > 0:
                raise ValueError("sink broken during drain")
            return v

        def run():
            with pytest.raises(KeyError, match="original"):
                run_pipeline(
                    list(range(4)),
                    [
                        PipelineStage("scan", scan, workers=2),
                        PipelineStage("sink", sink, drain_on_cancel=True),
                    ],
                    depth=4,
                )

        _run_with_deadline(run)
        assert calls and calls[0] == 0


class TestMatchCoalescer:
    """Leader-based combining of concurrent fp-match device calls."""

    class _FakeBackend:
        """Elementwise fp predicate: concat-then-split must equal per-call."""

        def __init__(self, on_call=None):
            self.calls: list[int] = []
            self._on_call = on_call

        def event_match_mask_fp(self, fp, n_topics, emitters, valid,
                                topic0, topic1, actor_id):
            self.calls.append(len(fp))
            if self._on_call is not None:
                self._on_call()
            return (fp % 2 == 0) & (valid > 0)

    @staticmethod
    def _req(rng, n, key):
        import numpy as np

        from ipc_proofs_tpu.parallel.pipeline import _MatchReq

        fp = rng.integers(0, 1000, size=n, dtype=np.uint64)
        nt = rng.integers(1, 4, size=n, dtype=np.int32)
        em = rng.integers(0, 5, size=n, dtype=np.int64)
        valid = rng.integers(0, 2, size=n, dtype=np.int32)
        return _MatchReq(fp, nt, em, valid, key)

    def test_batched_run_splits_identically(self):
        """One concatenated device call, split at input offsets, equals the
        per-request masks — and only same-key requests combine."""
        import numpy as np

        from ipc_proofs_tpu.parallel.pipeline import MatchCoalescer

        rng = np.random.default_rng(7)
        key_a = (b"t0", b"t1", 7)
        key_b = (b"t0", b"other", None)
        reqs = [self._req(rng, n, key_a) for n in (3, 5, 1)]
        reqs += [self._req(rng, 4, key_b)]
        backend = self._FakeBackend()
        m = Metrics()
        c = MatchCoalescer(backend, metrics=m)
        c._run(list(reqs))

        reference = self._FakeBackend()
        for r in reqs:
            expect = reference.event_match_mask_fp(
                r.fp, r.n_topics, r.emitters, r.valid, *r.key
            )
            assert np.array_equal(r.result, expect), r.key
            assert r.done.is_set() and r.exc is None
        # key_a rode ONE concatenated call, key_b its own: 2 device calls,
        # each padded to its pow-2 dispatch bucket (PR 12 mesh padding)
        from ipc_proofs_tpu.ops.match_jax import pad_to_bucket

        assert sorted(backend.calls) == sorted([pad_to_bucket(4), pad_to_bucket(9)])
        assert m.snapshot()["counters"]["range_match_coalesced"] == 2

    def test_concurrent_callers_coalesce(self):
        """Four threads: the first holds the device lock until the other
        three have parked, so one follower-leader claims all three in a
        single concatenated call. Masks must equal the uncoalesced ones."""
        import numpy as np

        from ipc_proofs_tpu.parallel.pipeline import MatchCoalescer

        rng = np.random.default_rng(11)
        key = (b"sig", b"sub", 1)
        reqs = [self._req(rng, 2 + i, key) for i in range(4)]
        m = Metrics()
        holder: dict = {}

        def first_call_waits():
            if len(backend.calls) == 1:  # only the very first device call
                deadline = time.time() + 10
                while len(holder["c"]._pending) < 3 and time.time() < deadline:
                    time.sleep(0.001)

        backend = self._FakeBackend(on_call=first_call_waits)
        c = MatchCoalescer(backend, metrics=m)
        holder["c"] = c

        results: dict = {}

        def call(i, r):
            results[i] = c.match_fp(
                r.fp, r.n_topics, r.emitters, r.valid, *r.key
            )

        def run():
            threads = [
                threading.Thread(target=call, args=(i, r), daemon=True)
                for i, r in enumerate(reqs)
            ]
            threads[0].start()
            deadline = time.time() + 10
            while not backend.calls and time.time() < deadline:
                time.sleep(0.001)  # thread 0 is inside the device call
            for t in threads[1:]:
                t.start()
            for t in threads:
                t.join(15)
                assert not t.is_alive(), "coalescer deadlocked"

        _run_with_deadline(run)
        reference = self._FakeBackend()
        for i, r in enumerate(reqs):
            expect = reference.event_match_mask_fp(
                r.fp, r.n_topics, r.emitters, r.valid, *r.key
            )
            assert np.array_equal(results[i], expect), i
        assert len(backend.calls) == 2  # leader's own + one combined call
        assert m.snapshot()["counters"]["range_match_coalesced"] == 2

    def test_backend_exception_reaches_every_waiter(self):
        import numpy as np

        from ipc_proofs_tpu.parallel.pipeline import MatchCoalescer

        class _Boom:
            def event_match_mask_fp(self, *a):
                raise RuntimeError("device fell over")

        rng = np.random.default_rng(3)
        c = MatchCoalescer(_Boom())
        reqs = [self._req(rng, 3, (b"a", b"b", None)) for _ in range(2)]
        c._run(list(reqs))
        for r in reqs:
            assert isinstance(r.exc, RuntimeError) and r.done.is_set()
        with pytest.raises(RuntimeError, match="device fell over"):
            c.match_fp(reqs[0].fp, reqs[0].n_topics, reqs[0].emitters,
                       reqs[0].valid, b"a", b"b", None)
