"""End-to-end observability (`ipc_proofs_tpu/obs/`): span parentage and
contextvar propagation across pipeline workers and RPC retries, trace
isolation under concurrent serving (one connected tree per request, no
cross-request leakage), Perfetto/Chrome trace-event schema, strict
Prometheus text-exposition parsing, server_timing accounting, the
always-on flight recorder, JSON log lines, and the traceview summarizer.
"""

import json
import logging
import re
import threading
import time
import urllib.request

import pytest

from ipc_proofs_tpu.obs import (
    FlightLogHandler,
    chrome_trace_obj,
    current_context,
    disable_tracing,
    enable_tracing,
    get_collector,
    get_flight_recorder,
    render_prometheus,
    root_span,
    span,
    spans_for_trace,
    use_context,
    write_chrome_trace,
)
from ipc_proofs_tpu.utils.metrics import OBSERVABILITY_COUNTERS, Metrics


@pytest.fixture()
def collector():
    """Fresh opt-in span collector per test; always disabled after, and
    the (global) flight ring cleared so tests can't see each other."""
    get_flight_recorder().clear()
    c = enable_tracing(metrics=Metrics())
    try:
        yield c
    finally:
        disable_tracing()
        get_flight_recorder().clear()


# --------------------------------------------------------------------------
# span spine
# --------------------------------------------------------------------------


class TestSpanSpine:
    def test_nested_spans_share_trace_and_parent(self, collector):
        with span("outer") as outer:
            with span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
        spans = collector.snapshot()
        assert [s.name for s in spans] == ["inner", "outer"]  # exit order
        assert not spans[1].parent_id  # outer is the trace root

    def test_root_span_forces_new_trace(self, collector):
        with span("a") as a:
            with root_span("b") as b:
                assert b.trace_id != a.trace_id
                assert not b.parent_id

    def test_context_propagates_across_pipeline_workers(self, collector):
        from ipc_proofs_tpu.parallel.pipeline import PipelineStage, run_pipeline

        def work(v):
            with span("work"):
                return v * 2

        with root_span("job") as root:
            out = run_pipeline(
                list(range(16)),
                [PipelineStage("double", work, workers=4)],
            )
        assert out == [v * 2 for v in range(16)]
        works = [s for s in collector.snapshot() if s.name == "work"]
        assert len(works) == 16
        # every worker-thread span landed in the submitting trace
        assert {s.trace_id for s in works} == {root.trace_id}
        assert any(s.thread_id != root.thread_id for s in works)

    def test_rpc_retry_span_records_retries(self, collector):
        from tests.test_rpc_retry import _FlakySession, _client

        client = _client(_FlakySession(fail_times=2, result="ok"), Metrics())
        with root_span("req") as root:
            assert client.request("Filecoin.Thing", []) == "ok"
        rpc = [s for s in collector.snapshot() if s.name == "rpc.Filecoin.Thing"]
        assert len(rpc) == 1
        assert rpc[0].trace_id == root.trace_id
        assert rpc[0].parent_id == root.span_id
        assert rpc[0].attrs["retries"] == 2

    def test_use_context_none_is_noop(self, collector):
        with use_context(None):
            assert current_context() is None


# --------------------------------------------------------------------------
# Perfetto / Chrome trace-event export
# --------------------------------------------------------------------------


def _make_spans(collector, n=3):
    with root_span("root"):
        for i in range(n):
            with span(f"child{i}", {"i": i}):
                pass
    return collector.snapshot()


class TestPerfettoExport:
    def test_chrome_trace_schema(self, collector, tmp_path):
        spans = _make_spans(collector)
        path = tmp_path / "trace.json"
        n = write_chrome_trace(str(path), spans)
        assert n == len(spans)

        obj = json.loads(path.read_text())
        assert isinstance(obj["traceEvents"], list)
        complete = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        meta = [e for e in obj["traceEvents"] if e["ph"] == "M"]
        assert len(complete) == len(spans)
        assert {e["name"] for e in meta} >= {"process_name", "thread_name"}
        for e in complete:
            # the Chrome trace-event contract: name/ts/dur/pid/tid, µs ints
            assert isinstance(e["name"], str) and e["name"]
            assert isinstance(e["ts"], int) and e["ts"] >= 0
            assert isinstance(e["dur"], int) and e["dur"] >= 1
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
            assert re.fullmatch(r"[0-9a-f]{16}", e["args"]["trace_id"])
            assert e["args"]["span_id"]

    def test_children_nest_inside_root_interval(self, collector):
        spans = _make_spans(collector)
        events = chrome_trace_obj(spans)["traceEvents"]
        xs = {e["args"]["span_id"]: e for e in events if e["ph"] == "X"}
        root = next(e for e in xs.values() if e["name"] == "root")
        for e in xs.values():
            if e["args"].get("parent_id") == root["args"]["span_id"]:
                assert e["ts"] >= root["ts"]
                assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 1


# --------------------------------------------------------------------------
# trace sampling
# --------------------------------------------------------------------------


class TestTraceSampling:
    def _sampled_tracing(self, rate, metrics=None):
        get_flight_recorder().clear()
        return enable_tracing(metrics=metrics, sample=rate)

    def test_sample_zero_skips_collector_not_flight_ring(self):
        m = Metrics()
        col = self._sampled_tracing(0.0, m)
        try:
            with root_span("r"):
                with span("c"):
                    pass
            assert col.snapshot() == []
            # the flight recorder is exempt: crash forensics never sampled out
            ring = [s["name"] for s in get_flight_recorder().snapshot()["spans"]]
            assert set(ring) >= {"r", "c"}
            assert m.snapshot()["counters"]["trace.spans_sampled_out"] == 2
        finally:
            disable_tracing()
            get_flight_recorder().clear()

    def test_sample_one_keeps_everything(self):
        col = self._sampled_tracing(1.0)
        try:
            with root_span("r"):
                with span("c"):
                    pass
            assert {s.name for s in col.snapshot()} == {"r", "c"}
        finally:
            disable_tracing()
            get_flight_recorder().clear()

    def test_whole_trace_decision_children_inherit(self):
        # the decision is per TRACE (deterministic on trace_id), never per
        # span: a kept root keeps all descendants, a dropped root drops all
        col = self._sampled_tracing(0.5)
        try:
            for _ in range(20):
                with root_span("r"):
                    with span("c"):
                        pass
            by_trace: dict = {}
            for s in col.snapshot():
                by_trace.setdefault(s.trace_id, set()).add(s.name)
            assert all(names == {"r", "c"} for names in by_trace.values())
        finally:
            disable_tracing()
            get_flight_recorder().clear()

    def test_decision_deterministic_on_trace_id(self):
        from ipc_proofs_tpu.obs import trace as trace_mod

        trace_mod._sample_rate = 0.5
        try:
            tid = "80000000" + "0" * 8
            assert trace_mod._sample_decision(tid) is False  # 0.5 exactly → out
            assert trace_mod._sample_decision("0" * 16) is True
            # same id, same verdict, every time
            assert trace_mod._sample_decision(tid) == trace_mod._sample_decision(tid)
        finally:
            trace_mod._sample_rate = 1.0

    def test_sampling_propagates_to_pipeline_workers(self):
        # a dropped trace stays dropped inside stage worker threads — the
        # workers re-enter the submitting TraceContext, sampled bit included
        from ipc_proofs_tpu.parallel.pipeline import PipelineStage, run_pipeline

        m = Metrics()
        col = self._sampled_tracing(0.0, m)
        try:

            def work(v):
                with span("work"):
                    return v + 1

            with root_span("job"):
                out = run_pipeline(
                    list(range(8)), [PipelineStage("s", work, workers=3)]
                )
            assert out == list(range(1, 9))
            assert col.snapshot() == []
            assert m.snapshot()["counters"]["trace.spans_sampled_out"] >= 9
        finally:
            disable_tracing()
            get_flight_recorder().clear()


# --------------------------------------------------------------------------
# OTLP/JSON export
# --------------------------------------------------------------------------


class TestOtlpExport:
    def test_otlp_shape(self, collector, tmp_path):
        from ipc_proofs_tpu.obs import otlp_trace_obj, write_otlp_trace

        spans = _make_spans(collector)
        obj = otlp_trace_obj(spans)
        rs = obj["resourceSpans"]
        assert len(rs) == 1
        attrs = {a["key"]: a["value"]["stringValue"]
                 for a in rs[0]["resource"]["attributes"]}
        assert attrs["service.name"] == "ipc-proofs-tpu"
        scope = rs[0]["scopeSpans"][0]
        assert scope["scope"]["name"] == "ipc_proofs_tpu.obs"
        otlp = scope["spans"]
        assert len(otlp) == len(spans)
        roots = [s for s in otlp if "parentSpanId" not in s]
        assert len(roots) == 1 and roots[0]["name"] == "root"
        for s in otlp:
            # OTLP/JSON contract: hex ids at full width, ns times as strings
            assert re.fullmatch(r"[0-9a-f]{32}", s["traceId"])
            assert re.fullmatch(r"[0-9a-f]{16}", s["spanId"])
            assert s["kind"] == 1
            start, end = int(s["startTimeUnixNano"]), int(s["endTimeUnixNano"])
            assert isinstance(s["startTimeUnixNano"], str)  # int64-safe
            assert end >= start > 10**18  # plausibly nanoseconds since epoch
        child = next(s for s in otlp if s["name"] == "child0")
        assert child["parentSpanId"] == roots[0]["spanId"]
        i_attr = {a["key"]: a["value"]["stringValue"] for a in child["attributes"]}
        assert i_attr["i"] == "0"

        path = tmp_path / "trace.otlp.json"
        n = write_otlp_trace(str(path), spans)
        assert n == len(spans)
        assert json.loads(path.read_text()) == obj


# --------------------------------------------------------------------------
# Prometheus text exposition
# --------------------------------------------------------------------------

_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"  # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\""  # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\")*\})?"  # more labels
    r" -?[0-9.e+-]+(\.[0-9]+)?$"  # value
)


def _check_prom_text(text: str) -> "dict[str, str]":
    """Strict 0.0.4 line-format check; returns {family: TYPE}."""
    types: "dict[str, str]" = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            assert len(line.split(" ", 3)) == 4, line
        elif line.startswith("# TYPE "):
            _, _, family, kind = line.split(" ")
            assert kind in ("counter", "gauge", "summary"), line
            assert family not in types, f"duplicate TYPE for {family}"
            types[family] = kind
        else:
            assert _PROM_SAMPLE.fullmatch(line), f"malformed sample: {line!r}"
            name = line.split("{", 1)[0].split(" ", 1)[0]
            family = re.sub(r"_(total|sum|count)$", "", name)
            assert name in types or family in types, f"undeclared family: {line!r}"
    return types


class TestPrometheus:
    def test_render_parses_strictly(self):
        m = Metrics()
        m.count("serve.requests", 3)
        m.set_gauge("queue_depth", 7)
        with m.stage("verify"):
            pass
        m.observe("latency_ms", 12.5)
        m.observe("latency_ms", 2.0)
        text = render_prometheus(m.snapshot())
        types = _check_prom_text(text)
        # classic 0.0.4: counter TYPE lines carry the full _total name
        assert types["ipc_serve_requests_total"] == "counter"
        assert "ipc_serve_requests_total 3" in text
        assert types["ipc_uptime_seconds"] == "gauge"
        assert 'ipc_stage_calls_total{stage="verify"} 1' in text
        assert types["ipc_latency_ms"] == "summary"
        assert 'quantile="0.99"' in text
        # summary aggregation contract: _sum reconstructs from mean×count,
        # _count is the observation count — pinned so dashboards can rate()
        assert "ipc_latency_ms_sum 14.5" in text
        assert "ipc_latency_ms_count 2" in text

    def test_label_escaping(self):
        m = Metrics()
        with m.stage('we"ird\\stage'):
            pass
        _check_prom_text(render_prometheus(m.snapshot()))


# --------------------------------------------------------------------------
# concurrent serving: isolation + server_timing
# --------------------------------------------------------------------------


@pytest.fixture(scope="class")
def obs_server():
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.trust import TrustPolicy
    from ipc_proofs_tpu.serve import ProofHTTPServer, ProofService, ServiceConfig

    get_flight_recorder().clear()
    collector = enable_tracing(metrics=Metrics())
    sig, topic1 = "NewTopDownMessage(bytes32,uint256)", "calib-subnet-1"
    store, pairs, _ = build_range_world(4, signature=sig, topic1=topic1)
    svc = ProofService(
        store=store,
        spec=EventProofSpec(event_signature=sig, topic_1=topic1),
        trust_policy=TrustPolicy.accept_all(),
        config=ServiceConfig(max_batch=8, max_wait_ms=2.0, workers=2,
                             queue_capacity=256),
        metrics=Metrics(),
    )
    httpd = ProofHTTPServer(svc, port=0, pairs=pairs).start()
    try:
        yield httpd, collector
    finally:
        httpd.shutdown(timeout=10)
        disable_tracing()
        get_flight_recorder().clear()


def _post(base, path, obj):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    with urllib.request.urlopen(req) as resp:
        body = json.load(resp)
        header = resp.headers.get("Server-Timing")
    return body, header, (time.perf_counter() - t0) * 1e3


class TestServeTracing:
    N = 32

    def test_concurrent_requests_get_isolated_trees(self, obs_server):
        httpd, collector = obs_server
        results, errors = [], []

        def one(i):
            # 32 simultaneous connects can overflow the stdlib server's
            # accept backlog → kernel RST; a client retry is the remedy
            for attempt in range(3):
                try:
                    results.append(_post(httpd.address, "/v1/generate",
                                         {"pair_index": i % 4}))
                    return
                except ConnectionResetError:
                    time.sleep(0.05 * (attempt + 1))
                except Exception as exc:  # noqa: BLE001 — surfaced below
                    errors.append(exc)
                    return
            errors.append(ConnectionResetError(f"request {i}: 3 resets"))

        threads = [threading.Thread(target=one, args=(i,)) for i in range(self.N)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors and len(results) == self.N

        trace_ids = [body["trace_id"] for body, _, _ in results]
        assert len(set(trace_ids)) == self.N  # one fresh trace per request

        # the response is written INSIDE the http.generate span, so the
        # root lands in the collector a beat after the client returns —
        # wait for every trace's root instead of racing the handler exit
        deadline = time.time() + 5
        while True:
            spans = collector.snapshot()
            rooted = {s.trace_id for s in spans if s.name == "http.generate"}
            if set(trace_ids) <= rooted or time.time() > deadline:
                break
            time.sleep(0.01)
        by_trace = {}
        for s in spans:
            by_trace.setdefault(s.trace_id, []).append(s)
        for tid in trace_ids:
            tree = by_trace[tid]
            ids = {s.span_id for s in tree}
            roots = [s for s in tree if s.parent_id not in ids]
            # exactly one connected tree: a single root (the http span),
            # every other span's parent inside the same trace
            assert len(roots) == 1, [s.name for s in roots]
            assert roots[0].name == "http.generate"

        for body, header, wall_ms in results:
            timing = body["server_timing"]
            assert set(timing) >= {"queue_ms", "batch_wait_ms", "generate_ms"}
            assert all(v >= 0 for v in timing.values())
            total = sum(timing.values())
            # the accounted stages cover admission→completion, which the
            # client-observed wall strictly contains (plus HTTP overhead)
            assert total <= wall_ms * 1.1 + 10
            assert header and "generate;dur=" in header

    def test_single_request_timing_close_to_wall(self, obs_server):
        httpd, _ = obs_server
        body, _, wall_ms = _post(httpd.address, "/v1/generate", {"pair_index": 0})
        total = sum(body["server_timing"].values())
        assert total <= wall_ms  # accounted time can't exceed the wall
        assert total >= wall_ms * 0.5  # …and covers the bulk of it

    def test_flight_and_prom_endpoints(self, obs_server):
        httpd, _ = obs_server
        _post(httpd.address, "/v1/generate", {"pair_index": 0})
        flight = json.load(
            urllib.request.urlopen(f"{httpd.address}/debug/flight")
        )
        assert flight["spans"] and all("trace_id" in s for s in flight["spans"])
        prom = urllib.request.urlopen(
            f"{httpd.address}/metrics.prom"
        ).read().decode()
        types = _check_prom_text(prom)
        assert types.get("ipc_serve_batches_generate_total") == "counter"
        assert "ipc_uptime_seconds" in types


# --------------------------------------------------------------------------
# flight recorder + logs
# --------------------------------------------------------------------------


class TestFlightRecorder:
    def test_always_on_even_without_collector(self):
        disable_tracing()
        fr = get_flight_recorder()
        fr.clear()
        with span("background"):
            pass
        snap = fr.snapshot()
        assert [s["name"] for s in snap["spans"]] == ["background"]
        fr.clear()

    def test_warn_logs_captured_and_dumped(self, collector):
        logger = logging.getLogger("ipc_proofs.test_obs")
        logger.addHandler(FlightLogHandler())
        try:
            logger.warning("disk on fire")
        finally:
            logger.handlers.clear()
        snap = get_flight_recorder().snapshot()
        assert any("disk on fire" in l["msg"] for l in snap["logs"])

        import io

        buf = io.StringIO()
        get_flight_recorder().dump(buf)
        assert "disk on fire" in buf.getvalue()

    def test_ring_is_bounded(self, collector):
        fr = get_flight_recorder()
        cap = fr.snapshot()["span_capacity"]
        for i in range(cap + 50):
            with span(f"s{i}"):
                pass
        assert len(fr.snapshot()["spans"]) == cap

    def test_slow_request_logging(self):
        from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
        from ipc_proofs_tpu.proofs.trust import TrustPolicy
        from ipc_proofs_tpu.serve import ProofService, ServiceConfig

        class _Capture(logging.Handler):
            def __init__(self):
                super().__init__(logging.WARNING)
                self.messages: list[str] = []

            def emit(self, record):
                self.messages.append(record.getMessage())

        get_flight_recorder().clear()
        m = Metrics()
        svc = ProofService(
            trust_policy=TrustPolicy.accept_all(),
            config=ServiceConfig(max_batch=2, max_wait_ms=1.0,
                                 slow_request_ms=0.0),  # everything is slow
            metrics=m,
        )
        bundle = UnifiedProofBundle(storage_proofs=[], event_proofs=[], blocks=[])
        cap = _Capture()
        logging.getLogger("ipc_proofs").addHandler(cap)
        try:
            with root_span("http.verify"):
                resp = svc.verify(bundle)
        finally:
            logging.getLogger("ipc_proofs").removeHandler(cap)
            svc.drain()
        assert resp.trace_id
        assert m.snapshot()["counters"]["serve.slow_requests"] >= 1
        slow = [msg for msg in cap.messages if "slow verify" in msg]
        assert slow and resp.trace_id in slow[0]


class TestJsonLog:
    def test_json_formatter_carries_trace_context(self, collector):
        from ipc_proofs_tpu.utils.log import JsonLineFormatter

        rec = logging.LogRecord(
            "ipc_proofs.x", logging.WARNING, __file__, 1, "boom %d", (7,), None
        )
        with span("ctx") as sp:
            line = JsonLineFormatter().format(rec)
        obj = json.loads(line)
        assert obj["msg"] == "boom 7"
        assert obj["level"] == "WARNING"
        assert obj["trace_id"] == sp.trace_id

    def test_json_formatter_without_context(self):
        from ipc_proofs_tpu.utils.log import JsonLineFormatter

        rec = logging.LogRecord(
            "ipc_proofs.x", logging.INFO, __file__, 1, "plain", (), None
        )
        obj = json.loads(JsonLineFormatter().format(rec))
        assert "trace_id" not in obj


# --------------------------------------------------------------------------
# metrics additions
# --------------------------------------------------------------------------


class TestMetricsObservability:
    def test_uptime_monotone(self):
        m = Metrics()
        snap = m.snapshot()
        assert snap["uptime_s"] >= 0
        time.sleep(0.01)
        assert m.snapshot()["uptime_s"] >= snap["uptime_s"]

    def test_observability_counters_registered(self, collector):
        assert "trace.spans_recorded" in OBSERVABILITY_COUNTERS
        assert "trace.spans_dropped" in OBSERVABILITY_COUNTERS
        assert "serve.slow_requests" in OBSERVABILITY_COUNTERS
        m = Metrics()
        c = enable_tracing(metrics=m)
        with span("counted"):
            pass
        assert m.snapshot()["counters"]["trace.spans_recorded"] == 1

    def test_spans_for_trace_reads_flight_ring(self, collector):
        with root_span("r") as root:
            with span("c"):
                pass
        found = spans_for_trace(root.trace_id)
        assert [s.name for s in found] == ["r", "c"]  # start-ordered


# --------------------------------------------------------------------------
# traceview
# --------------------------------------------------------------------------


class TestTraceview:
    def test_summarize_critical_path(self, collector, tmp_path):
        import sys

        sys.path.insert(0, "tools")
        try:
            from traceview import load_events, summarize
        finally:
            sys.path.pop(0)

        with root_span("req"):
            with span("stage_a"):
                with span("stage_b"):
                    time.sleep(0.002)
            with span("stage_c"):
                pass
        path = tmp_path / "t.json"
        write_chrome_trace(str(path), collector.snapshot())

        summary = summarize(load_events(str(path)))
        assert summary["n_traces"] == 1
        assert set(summary["stages"]) == {"req", "stage_a", "stage_b", "stage_c"}
        trace = summary["traces"][0]
        assert trace["root"] == "req"
        # widest child at each hop: req → stage_a → stage_b
        assert [h["name"] for h in trace["critical_path"]] == [
            "req", "stage_a", "stage_b",
        ]
        assert all(h["self_us"] >= 0 for h in trace["critical_path"])
        assert trace["widest"][0]["name"] == "req"
        # stage totals reconcile with the raw spans
        spans = {s.name: s.dur_us for s in collector.snapshot()}
        assert summary["stages"]["stage_b"]["total_us"] == max(
            1, spans["stage_b"]
        )

    def test_stitch_merges_captures_into_one_tree(self, tmp_path, capsys):
        """Golden: router + two shard captures of one scatter — span ids
        collide across processes (both counters start at 1), yet the
        stitched result is ONE rooted tree with zero orphans."""
        import sys

        sys.path.insert(0, "tools")
        try:
            from traceview import load_events, main, stitch, summarize
        finally:
            sys.path.pop(0)

        def ev(name, ts, dur, sid, parent, tid="t1"):
            return {
                "ph": "X", "name": name, "cat": "span", "ts": ts, "dur": dur,
                "args": {"trace_id": tid, "span_id": sid, "parent_id": parent},
            }

        router = [
            ev("cluster.generate_range", 0, 1000, "1", None),
            ev("cluster.dispatch", 10, 400, "2", "1"),
            ev("cluster.dispatch", 10, 500, "3", "1"),
        ]
        # each shard's http span adopted the router's root id "1" as its
        # wire parent — which collides with the shard's OWN first span id
        shard0 = [
            ev("http.generate_range", 20, 300, "1", "1"),
            ev("serve.generate_range", 30, 250, "2", "1"),
        ]
        shard1 = [
            ev("http.generate_range", 20, 380, "1", "1"),
            ev("serve.generate_range", 30, 320, "2", "1"),
        ]
        paths = []
        for i, events in enumerate((router, shard0, shard1)):
            p = tmp_path / f"cap{i}.json"
            p.write_text(json.dumps({"traceEvents": events}))
            paths.append(str(p))

        merged = stitch([load_events(p) for p in paths])
        assert len(merged) == 7
        ids = {e["args"]["span_id"] for e in merged}
        orphans = [
            e for e in merged
            if e["args"]["parent_id"] is not None
            and e["args"]["parent_id"] not in ids
        ]
        assert not orphans
        roots = [e for e in merged if e["args"]["parent_id"] is None]
        assert [e["name"] for e in roots] == ["cluster.generate_range"]
        # the adopted spans grafted onto the ROUTER's root, not themselves
        for e in merged:
            if e["name"] == "http.generate_range":
                assert e["args"]["parent_id"] == "f0:1"
            assert e["args"]["span_id"] != e["args"]["parent_id"]

        # the CLI round-trips: --stitch --out writes a loadable merged file
        out = tmp_path / "fleet.json"
        assert main(["--stitch", *paths, "--out", str(out), "--json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["n_events"] == 7 and summary["n_traces"] == 1
        assert summary["traces"][0]["root"] == "cluster.generate_range"
        restitched = summarize(load_events(str(out)))
        assert restitched["traces"][0]["spans"] == 7


# --------------------------------------------------------------------------
# OTLP POST (--trace-otlp-url)
# --------------------------------------------------------------------------


class TestOtlpPost:
    """`post_otlp_trace`: bounded full-jitter retry against an injectable
    opener/sleep/rng — no network, no clock, fully deterministic."""

    def _post(self, script, spans, metrics, **kwargs):
        """Run one post; ``script`` lists per-attempt outcomes (int status
        or an exception to raise). Returns (ok, request bodies, sleeps)."""
        import random as _random

        from ipc_proofs_tpu.obs import post_otlp_trace

        script = list(script)
        calls, sleeps = [], []

        def opener(url, body, timeout_s):
            assert url == "http://collector:4318/v1/traces"
            calls.append(body)
            action = script.pop(0) if script else 200
            if isinstance(action, Exception):
                raise action
            return action

        ok = post_otlp_trace(
            "http://collector:4318/v1/traces", spans, metrics=metrics,
            opener=opener, sleep=sleeps.append, rng=_random.Random(7),
            **kwargs,
        )
        return ok, calls, sleeps

    def test_success_counts_and_posts_valid_otlp(self, collector):
        spans = _make_spans(collector)
        m = Metrics()
        ok, calls, sleeps = self._post([200], spans, m)
        assert ok and len(calls) == 1 and sleeps == []
        counters = m.snapshot()["counters"]
        assert counters["trace.otlp_posts"] == 1
        assert "trace.otlp_post_failures" not in counters
        body = json.loads(calls[0].decode("utf-8"))
        assert len(body["resourceSpans"][0]["scopeSpans"][0]["spans"]) == len(spans)

    def test_5xx_retries_until_success(self, collector):
        spans = _make_spans(collector)
        m = Metrics()
        ok, calls, sleeps = self._post([500, 503], spans, m)
        assert ok and len(calls) == 3 and len(sleeps) == 2
        assert m.snapshot()["counters"]["trace.otlp_posts"] == 1

    def test_exhausted_retries_fail_soft(self, collector):
        spans = _make_spans(collector)
        m = Metrics()
        ok, calls, sleeps = self._post([503, 503, 503, 503], spans, m)
        assert not ok and len(calls) == 4 and len(sleeps) == 3
        counters = m.snapshot()["counters"]
        assert counters["trace.otlp_post_failures"] == 1
        assert "trace.otlp_posts" not in counters

    def test_4xx_is_terminal_no_retry(self, collector):
        spans = _make_spans(collector)
        m = Metrics()
        ok, calls, sleeps = self._post([400, 200], spans, m)
        assert not ok and len(calls) == 1 and sleeps == []
        assert m.snapshot()["counters"]["trace.otlp_post_failures"] == 1

    def test_429_is_retryable(self, collector):
        spans = _make_spans(collector)
        m = Metrics()
        ok, calls, _ = self._post([429, 200], spans, m)
        assert ok and len(calls) == 2

    def test_connection_errors_retry(self, collector):
        spans = _make_spans(collector)
        m = Metrics()
        ok, calls, _ = self._post(
            [OSError("refused"), OSError("reset"), 200], spans, m
        )
        assert ok and len(calls) == 3
        assert m.snapshot()["counters"]["trace.otlp_posts"] == 1

    def test_http_error_exception_maps_to_status(self, collector):
        import urllib.error

        spans = _make_spans(collector)
        m = Metrics()
        err = urllib.error.HTTPError(
            "http://collector:4318/v1/traces", 503, "unavailable", {}, None
        )
        ok, calls, _ = self._post([err, 200], spans, m)
        assert ok and len(calls) == 2

    def test_backoff_is_bounded_full_jitter(self, collector):
        spans = _make_spans(collector)
        m = Metrics()
        ok, _, sleeps = self._post(
            [503] * 5, spans, m,
            max_attempts=5, base_delay_s=1.0, max_delay_s=2.0,
        )
        assert not ok and len(sleeps) == 4
        # full jitter: uniform(0, min(max_delay, base * 2**(attempt-1)))
        for i, s in enumerate(sleeps):
            assert 0.0 <= s <= min(2.0, 1.0 * 2**i)
