"""Standing queries: registry, delivery log, push fan-out, matcher, e2e.

Covers the streaming plane end to end:

- `SubscriptionRegistry` — filter/target normalization, durable replay,
  torn-tail recovery, idempotent re-registration;
- `DeliveryLog` — monotonic cursors, idempotency dedup, duplicate-ack
  guard, content-addressed payload frames, long-poll wakeup, byte-capped
  compaction that never drops an unacked delivery, ENOSPC fail-soft;
- `PushDelivery` — transient-failure convergence with bounded full-jitter
  retry, terminal 4xx fail-fast, exhausted-then-repush convergence;
- `ChainFollower` satellites — jittered poll delay bounds, poll counter +
  last-finalized gauge, raising-hook fail-soft, unchanged-head idempotence;
- `StandingQueryMatcher` — one generation per distinct (pair, filter),
  fan-out to every subscriber, replay dedup, per-filter fail-soft;
- the serve plane — /v1/subscribe|subscriptions|deliveries routes,
  /healthz merge, and SIGTERM-mid-push shutdown ordering (delivery
  workers drain before the service);
- the 4-assertion end-to-end: a real `ChainFollower` over a seeded
  `LocalLotusSession` driving fan-out byte-identical to the
  request/response path, generate-once accounting, transient-webhook
  convergence without duplicate acks, and SIGKILL/restart survival;
- cluster failover: a dead shard's subscription arc re-registers on the
  survivor under the ORIGINAL sub ids.
"""

import json
import os
import random
import signal
import threading
import time
import urllib.request

import pytest

from ipc_proofs_tpu.cluster import ClusterRouter, LocalShard
from ipc_proofs_tpu.cluster.hashring import HashRing
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.jobs.journal import read_journal_entries
from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked
from ipc_proofs_tpu.witness import apply_delta
from ipc_proofs_tpu.serve.httpd import ProofHTTPServer
from ipc_proofs_tpu.serve.service import ProofService, ServiceConfig
from ipc_proofs_tpu.store.faults import LocalLotusSession
from ipc_proofs_tpu.store.rpc import LotusClient
from ipc_proofs_tpu.storex import ChainFollower
from ipc_proofs_tpu.subs import (
    DeliveryLog,
    PushDelivery,
    StandingQueries,
    StandingQueryMatcher,
    Subscription,
    SubscriptionRegistry,
    filter_key,
    normalize_filter,
    normalize_target,
    subscription_ring_key,
)
from ipc_proofs_tpu.subs.delivery import DELIVERY_JOURNAL
from ipc_proofs_tpu.subs.matcher import _bundle_digest
from ipc_proofs_tpu.utils.metrics import Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001

FILTER_A = {"signature": SIG, "topic1": SUBNET}
FILTER_B = {"signature": SIG, "topic1": SUBNET, "actor_id": ACTOR}

_NOSLEEP = lambda s: None  # noqa: E731 — push retry seam: no real sleeps


@pytest.fixture(scope="module")
def world():
    return build_range_world(
        4,
        receipts_per_pair=6,
        events_per_receipt=3,
        match_rate=0.5,
        signature=SIG,
        topic1=SUBNET,
        actor_id=ACTOR,
        base_height=41_000,
    )


def _counters(m):
    return m.snapshot()["counters"]


def _gauges(m):
    return m.snapshot().get("gauges", {})


def _wait_until(cond, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def _expected(store, pair, filt):
    """The request/response path's bundle for (pair, filter) — the byte
    oracle every pushed/pulled delivery must match exactly."""
    spec = EventProofSpec(
        event_signature=filt["signature"],
        topic_1=filt["topic1"],
        actor_id_filter=filt.get("actor_id"),
    )
    bundle = generate_event_proofs_for_range_chunked(
        store, [pair], spec, chunk_size=8
    )
    obj = bundle.to_json_obj()
    return obj, _bundle_digest(obj)


class _RecordingOpener:
    """Webhook seam: records every POST, answers via ``behavior(obj)``."""

    def __init__(self, behavior=None):
        self._lock = threading.Lock()
        self._calls = []
        self._behavior = behavior

    def __call__(self, url, body, timeout_s):
        obj = json.loads(body)
        with self._lock:
            self._calls.append((url, body, obj))
        return 200 if self._behavior is None else self._behavior(obj)

    def calls(self, sub_id=None):
        with self._lock:
            out = list(self._calls)
        if sub_id is None:
            return out
        return [c for c in out if c[2]["sub_id"] == sub_id]


class _BrokenFile:
    """A file handle on a full/readonly filesystem (mirrors test_jobs)."""

    def __init__(self, err=28):  # ENOSPC
        self._err = err

    def write(self, data):
        raise OSError(self._err, os.strerror(self._err))

    def flush(self):
        raise OSError(self._err, os.strerror(self._err))

    def fileno(self):
        raise OSError(self._err, os.strerror(self._err))

    def close(self):
        pass


def _tipset_api_json(tipset):
    return {
        "Cids": [{"/": str(c)} for c in tipset.cids],
        "Height": tipset.height,
        "Blocks": [
            {
                "Parents": [{"/": str(p)} for p in header.parents],
                "Height": header.height,
                "ParentStateRoot": {"/": str(header.parent_state_root)},
                "ParentMessageReceipts": {
                    "/": str(header.parent_message_receipts)
                },
                "Messages": {"/": str(header.messages)},
                "Timestamp": header.timestamp,
            }
            for header in tipset.blocks
        ],
    }


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------


class TestFilterNormalization:
    def test_minimal_filter_normalizes(self):
        filt = normalize_filter({"signature": SIG, "topic1": SUBNET})
        assert filt == {"signature": SIG, "topic1": SUBNET}

    def test_actor_and_slot_pass_through(self):
        filt = normalize_filter(dict(FILTER_B, slot="ab" * 32))
        assert filt["actor_id"] == ACTOR
        assert filt["slot"] == "ab" * 32

    @pytest.mark.parametrize(
        "bad",
        [
            {"topic1": SUBNET},  # signature required
            {"signature": SIG},  # topic1 required (EventMatcher needs it)
            dict(FILTER_A, actor_id=True),  # bool is not an actor id
            dict(FILTER_A, slot="ab" * 32),  # slot requires actor_id
            dict(FILTER_B, slot="xyz"),  # slot must be 64-hex
            dict(FILTER_A, surprise=1),  # unknown keys rejected
            "not a dict",
            None,
        ],
    )
    def test_bad_filters_rejected(self, bad):
        with pytest.raises(ValueError):
            normalize_filter(bad)

    def test_target_normalization(self):
        assert normalize_target(None)["mode"] == "poll"
        t = normalize_target({"url": "http://hooks/x"})
        assert t["mode"] == "webhook" and t["url"] == "http://hooks/x"
        with pytest.raises(ValueError):
            normalize_target({"mode": "webhook"})  # webhook needs a url
        with pytest.raises(ValueError):
            normalize_target({"mode": "webhook", "url": "no-scheme"})

    def test_filter_key_is_order_canonical(self):
        a = {"signature": SIG, "topic1": SUBNET, "actor_id": ACTOR}
        b = {"actor_id": ACTOR, "topic1": SUBNET, "signature": SIG}
        assert filter_key(normalize_filter(a)) == filter_key(normalize_filter(b))
        assert subscription_ring_key(normalize_filter(a)).startswith("subs:")


class TestSubscriptionRegistry:
    def test_register_unsubscribe_roundtrip(self, tmp_path):
        m = Metrics()
        reg = SubscriptionRegistry(str(tmp_path), metrics=m, fsync=False)
        sub, created = reg.subscribe(FILTER_A, {"url": "http://h/1"}, sub_id="s1")
        assert created and sub.sub_id == "s1"
        assert sub.target["mode"] == "webhook"
        # duplicate id absorbs idempotently — the failover/replay guarantee
        again, created2 = reg.subscribe(FILTER_B, None, sub_id="s1")
        assert not created2 and again.filter == sub.filter
        assert _counters(m)["subs.replays_absorbed"] == 1
        assert len(reg) == 1
        assert reg.unsubscribe("s1") and not reg.unsubscribe("s1")
        assert reg.active() == []
        reg.close()

    def test_restart_replays_registrations(self, tmp_path):
        reg = SubscriptionRegistry(str(tmp_path), metrics=Metrics(), fsync=False)
        for i in range(3):
            reg.subscribe(FILTER_A if i % 2 else FILTER_B, None, sub_id=f"s{i}")
        reg.unsubscribe("s1")
        reg.close()

        reg2 = SubscriptionRegistry(str(tmp_path), metrics=Metrics(), fsync=False)
        assert sorted(s.sub_id for s in reg2.active()) == ["s0", "s2"]
        assert reg2.replayed == 4  # 3 sub frames + 1 unsub frame
        assert reg2.get("s0").filter == normalize_filter(FILTER_B)
        reg2.close()

    def test_torn_tail_is_truncated_not_fatal(self, tmp_path):
        reg = SubscriptionRegistry(str(tmp_path), metrics=Metrics(), fsync=False)
        reg.subscribe(FILTER_A, None, sub_id="keep")
        reg.close()
        from ipc_proofs_tpu.jobs.journal import frame_record

        half = frame_record({"op": "sub", "id": "lost", "filter": FILTER_A})
        with open(reg.path, "ab") as fh:
            fh.write(half[: len(half) // 2])  # crash mid-write: torn frame
        reg2 = SubscriptionRegistry(str(tmp_path), metrics=Metrics(), fsync=False)
        assert [s.sub_id for s in reg2.active()] == ["keep"]
        # and the journal is clean again: a third open replays fine
        reg2.subscribe(FILTER_B, None, sub_id="k2")
        reg2.close()
        reg3 = SubscriptionRegistry(str(tmp_path), metrics=Metrics(), fsync=False)
        assert len(reg3) == 2
        reg3.close()

    def test_enospc_fail_soft(self, tmp_path):
        m = Metrics()
        reg = SubscriptionRegistry(str(tmp_path), metrics=m, fsync=False)
        reg._writer._fh = _BrokenFile()
        sub, created = reg.subscribe(FILTER_A, None, sub_id="mem-only")
        assert created and reg.get("mem-only") is sub  # run completes in-memory
        assert reg.degraded
        assert _counters(m)["subs.log_failures"] >= 1
        reg.close()


# --------------------------------------------------------------------------
# delivery log
# --------------------------------------------------------------------------


class TestDeliveryLog:
    def test_cursors_dedup_and_duplicate_ack_guard(self, tmp_path):
        m = Metrics()
        log = DeliveryLog(str(tmp_path), metrics=m, fsync=False)
        pay = {"bundle": {"n": 1}}
        d1 = log.append("s1", 100, "aa" * 16, pay)
        d2 = log.append("s1", 101, "bb" * 16, pay)
        assert (d1.cursor, d2.cursor) == (1, 2)
        assert log.append("s1", 100, "aa" * 16, pay) is None  # idempotent
        assert _counters(m)["subs.delivery_dedup"] == 1
        assert log.pending_total() == 2
        assert log.ack("s1", 1) is True
        assert log.ack("s1", 1) is False  # duplicate-ack guard
        assert _counters(m)["subs.duplicate_acks"] == 1
        assert [d.cursor for d in log.pending("s1")] == [2]
        assert log.ack_through("s1", 10) == 1
        assert log.pending_total() == 0
        log.close()

    def test_restart_resolves_content_addressed_payloads(self, tmp_path):
        log = DeliveryLog(str(tmp_path), metrics=Metrics(), fsync=False)
        shared = {"bundle": {"blocks": ["cc" * 64], "n": 7}}
        dg = "d1" * 16
        log.append("s1", 100, dg, shared)
        log.append("s2", 100, dg, shared)  # same proof, second subscriber
        log.append("s1", 101, "e2" * 16, {"bundle": {"n": 8}})
        log.ack("s2", 1)
        log.close()

        entries, _, torn = read_journal_entries(
            os.path.join(str(tmp_path), DELIVERY_JOURNAL)
        )
        assert not torn
        pays = [r for r, _, _ in entries if r.get("op") == "pay"]
        assert len(pays) == 2  # one frame per digest, NOT per subscriber
        dlvs = [r for r, _, _ in entries if r.get("op") == "dlv"]
        assert all("payload" not in r for r in dlvs)

        log2 = DeliveryLog(str(tmp_path), metrics=Metrics(), fsync=False)
        assert log2.pending_total() == 2
        assert log2.pending("s1")[0].payload == shared  # digest resolved
        assert log2.pending("s1")[1].payload == {"bundle": {"n": 8}}
        assert log2.pending("s2") == []
        # idempotency keys survive: the matcher replaying this (pair,
        # filter) after restart dedups instead of double-delivering
        assert log2.append("s1", 100, dg, shared) is None
        log2.close()

    def test_long_poll_wakes_on_append(self, tmp_path):
        log = DeliveryLog(str(tmp_path), metrics=Metrics(), fsync=False)
        out = {}

        def waiter():
            t0 = time.monotonic()
            out["entries"] = log.entries_after("s1", 0, wait_s=10.0)
            out["elapsed"] = time.monotonic() - t0

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.15)
        log.append("s1", 1, "aa" * 16, {"bundle": {"n": 1}})
        t.join(timeout=8.0)
        assert not t.is_alive()
        assert [e.cursor for e in out["entries"]] == [1]
        assert out["elapsed"] < 8.0  # woken by the append, not the timeout
        log.close()

    def test_compaction_caps_bytes_without_losing_unacked(self, tmp_path):
        m = Metrics()
        # cap_bytes clamps to the 64 KiB floor; ~4 KiB payloads overflow it
        log = DeliveryLog(str(tmp_path), metrics=m, cap_bytes=1, fsync=False)
        blob = {"bundle": {"x": "ab" * 2048}}
        for i in range(40):
            d = log.append("s1", i, f"{i:02d}" * 16, blob)
            if i < 37:
                log.ack("s1", d.cursor)
        assert _counters(m)["subs.log_compactions"] >= 1
        assert [d.cursor for d in log.pending("s1")] == [38, 39, 40]
        log.close()

        log2 = DeliveryLog(str(tmp_path), metrics=Metrics(), fsync=False)
        # truncation only ever dropped entries below the acked cursor
        assert [d.cursor for d in log2.pending("s1")] == [38, 39, 40]
        assert log2.pending("s1")[0].payload == blob
        # acked history is gone from disk but its dedup window is not
        assert log2.append("s1", 5, "05" * 16, blob) is None
        assert log2.journal_bytes < 40 * 4200
        log2.close()

    def test_enospc_fail_soft_serves_from_memory(self, tmp_path):
        m = Metrics()
        log = DeliveryLog(str(tmp_path), metrics=m, fsync=False)
        log.append("s1", 1, "aa" * 16, {"bundle": {"n": 1}})
        log._writer._fh = _BrokenFile()
        d = log.append("s1", 2, "bb" * 16, {"bundle": {"n": 2}})
        assert d is not None and d.cursor == 2  # the run completes
        assert log.degraded
        assert _counters(m)["subs.log_failures"] >= 1
        assert [e.cursor for e in log.entries_after("s1", 0)] == [1, 2]
        assert log.ack("s1", 2) is True  # acks keep working in-memory
        assert [e.cursor for e in log.pending("s1")] == [1]
        log.close()


# --------------------------------------------------------------------------
# webhook push
# --------------------------------------------------------------------------


def _webhook_sub(sub_id="w1", filt=FILTER_A, url="http://hooks/w1"):
    return Subscription(
        sub_id=sub_id,
        filter=normalize_filter(filt),
        target={"mode": "webhook", "url": url},
    )


class TestPushDelivery:
    def test_transient_failure_converges_without_duplicate_ack(self, tmp_path):
        m = Metrics()
        log = DeliveryLog(str(tmp_path), metrics=m, fsync=False)
        codes = iter([503, 503, 200])
        opener = _RecordingOpener(lambda obj: next(codes, 200))
        push = PushDelivery(
            log, metrics=m, max_attempts=4, base_delay_s=0.01, max_delay_s=0.02,
            opener=opener, sleep=_NOSLEEP, rng=random.Random(0),
        )
        sub = _webhook_sub()
        d = log.append("w1", 7, "aa" * 16, {"bundle": {"n": 1}})
        fut = push.push(sub, d)
        assert fut.result(timeout=30) is True
        c = _counters(m)
        assert c["subs.push_retries"] == 2
        assert c["subs.pushes"] == 1 and c["subs.acks"] == 1
        assert "subs.duplicate_acks" not in c
        assert log.pending("w1") == []
        push.drain()
        log.close()

    def test_terminal_client_error_fails_fast(self, tmp_path):
        m = Metrics()
        log = DeliveryLog(str(tmp_path), metrics=m, fsync=False)
        opener = _RecordingOpener(lambda obj: 400)
        push = PushDelivery(
            log, metrics=m, max_attempts=4, opener=opener,
            sleep=_NOSLEEP, rng=random.Random(0),
        )
        d = log.append("w1", 7, "aa" * 16, {"bundle": {"n": 1}})
        assert push.push(_webhook_sub(), d).result(timeout=30) is False
        c = _counters(m)
        assert c["subs.push_failures"] == 1
        assert "subs.push_retries" not in c  # 4xx never retries
        assert len(log.pending("w1")) == 1  # unacked: long-poll still owns it
        push.drain()
        log.close()

    def test_exhausted_push_converges_via_repush(self, tmp_path):
        m = Metrics()
        log = DeliveryLog(str(tmp_path), metrics=m, fsync=False)
        state = {"code": 503}
        opener = _RecordingOpener(lambda obj: state["code"])
        push = PushDelivery(
            log, metrics=m, max_attempts=2, base_delay_s=0.01, max_delay_s=0.02,
            opener=opener, sleep=_NOSLEEP, rng=random.Random(0),
        )
        reg = SubscriptionRegistry(str(tmp_path), metrics=m, fsync=False)
        reg.subscribe(FILTER_A, {"url": "http://hooks/w1"}, sub_id="w1")
        d = log.append("w1", 7, "aa" * 16, {"bundle": {"n": 1}})
        assert push.push(reg.get("w1"), d).result(timeout=30) is False
        assert _counters(m)["subs.push_failures"] == 1
        assert len(log.pending("w1")) == 1

        state["code"] = 200  # webhook endpoint recovers
        assert push.repush_pending(reg) == 1
        assert _wait_until(lambda: not log.pending("w1"))
        c = _counters(m)
        assert c["subs.acks"] == 1 and "subs.duplicate_acks" not in c
        push.drain()
        log.close()
        reg.close()

    def test_poll_targets_are_never_pushed(self, tmp_path):
        log = DeliveryLog(str(tmp_path), metrics=Metrics(), fsync=False)
        push = PushDelivery(log, metrics=Metrics(), opener=_RecordingOpener())
        sub = Subscription(
            sub_id="p1", filter=normalize_filter(FILTER_A), target={"mode": "poll"}
        )
        d = log.append("p1", 7, "aa" * 16, {"bundle": {"n": 1}})
        assert push.push(sub, d) is None
        push.drain()
        log.close()


# --------------------------------------------------------------------------
# follower satellites
# --------------------------------------------------------------------------


def _follow_client(bs, responses, m):
    return LotusClient(
        "http://test-follow",
        session=LocalLotusSession(bs, responses=responses),
        metrics=m,
    )


class TestFollowerSatellites:
    def test_poll_delay_is_jittered_and_bounded(self, world):
        bs, _, _ = world
        f = ChainFollower(object(), bs, poll_s=10.0, rng=random.Random(0))
        delays = [f._poll_delay() for _ in range(64)]
        assert all(9.0 <= d <= 11.0 for d in delays)  # poll_s * (1 ± 0.1)
        assert len(set(delays)) > 1  # actually jittered, not constant
        assert ChainFollower(
            object(), bs, poll_s=10.0, poll_jitter=0.0
        )._poll_delay() == 10.0
        # absurd jitter clamps to 0.9: the delay can never hit zero
        clamped = ChainFollower(object(), bs, poll_s=10.0, poll_jitter=5.0)
        assert clamped.poll_jitter == 0.9
        assert all(1.0 <= clamped._poll_delay() <= 19.0 for _ in range(64))

    def test_poll_counter_and_finalized_gauge(self, world):
        bs, pairs, _ = world
        child = pairs[0].child
        responses = {
            "Filecoin.ChainHead": {
                "Height": child.height + 1,
                "Cids": [{"/": str(c)} for c in child.cids],
            },
            "Filecoin.ChainGetTipSetByHeight": _tipset_api_json(child),
        }
        m = Metrics()
        follower = ChainFollower(_follow_client(bs, responses, m), bs, metrics=m, lag=1)
        assert follower.poll_once() == 1
        # unchanged head: counted poll, no re-processing — idempotent
        assert follower.poll_once() == 0
        c = _counters(m)
        assert c["follow.polls"] == 2
        assert c["follow.tipsets"] == 1
        assert _gauges(m)["follow.last_finalized_epoch"] == child.height

    def test_raising_hook_is_fail_soft(self, world):
        bs, pairs, _ = world
        child = pairs[0].child
        responses = {
            "Filecoin.ChainHead": {
                "Height": child.height + 1,
                "Cids": [{"/": str(c)} for c in child.cids],
            },
            "Filecoin.ChainGetTipSetByHeight": _tipset_api_json(child),
        }
        m = Metrics()
        follower = ChainFollower(_follow_client(bs, responses, m), bs, metrics=m, lag=1)
        seen = []
        follower.add_finalized_hook(lambda ts: 1 / 0)
        follower.add_finalized_hook(lambda ts: seen.append(ts.height))
        assert follower.poll_once() == 1  # the tipset still lands
        assert seen == [child.height]  # later hooks still fire
        assert _counters(m)["follow.errors"] >= 1


# --------------------------------------------------------------------------
# matcher
# --------------------------------------------------------------------------


def _stack(root, store, opener, m=None):
    m = m if m is not None else Metrics()
    reg = SubscriptionRegistry(root, metrics=m, fsync=False)
    log = DeliveryLog(root, metrics=m, fsync=False)
    push = PushDelivery(
        log, metrics=m, max_attempts=3, base_delay_s=0.01, max_delay_s=0.02,
        opener=opener, sleep=_NOSLEEP, rng=random.Random(0),
    )
    matcher = StandingQueryMatcher(reg, log, push, store, metrics=m, chunk_size=8)
    return m, reg, log, push, matcher


def _drain_stack(reg, log, push, matcher):
    matcher.drain()
    push.drain()
    log.close()
    reg.close()


class TestStandingQueryMatcher:
    def test_generate_once_fans_out_byte_identical(self, tmp_path, world):
        store, pairs, _ = world
        opener = _RecordingOpener()
        m, reg, log, push, matcher = _stack(str(tmp_path), store, opener)
        reg.subscribe(FILTER_A, {"url": "http://h/a1"}, sub_id="w-a1")
        reg.subscribe(FILTER_A, {"url": "http://h/a2"}, sub_id="w-a2")
        reg.subscribe(FILTER_B, {"url": "http://h/b1"}, sub_id="w-b1")
        try:
            assert matcher.match_pair(pairs[0]) == 3
            # 3 subscribers, 2 distinct filters, exactly 2 generations
            assert _counters(m)["subs.generations"] == 2
            assert _wait_until(lambda: log.pending_total() == 0)
            assert _counters(m)["subs.pushes"] == 3
            for sub_id, filt in (("w-a1", FILTER_A), ("w-a2", FILTER_A),
                                 ("w-b1", FILTER_B)):
                obj, digest = _expected(store, pairs[0], normalize_filter(filt))
                calls = opener.calls(sub_id)
                assert len(calls) == 1
                _url, body, envelope = calls[0]
                assert envelope["digest"] == digest
                assert envelope["tipset"] == pairs[0].child.height
                # byte identity with the request/response path's bundle
                raw = json.dumps(obj, sort_keys=True)
                assert body.decode("utf-8").endswith(', "bundle": ' + raw + "}")
        finally:
            _drain_stack(reg, log, push, matcher)

    def test_on_tipset_pairs_and_replay_dedups(self, tmp_path, world):
        store, pairs, _ = world
        m, reg, log, push, matcher = _stack(
            str(tmp_path), store, _RecordingOpener()
        )
        reg.subscribe(FILTER_A, None, sub_id="p-a")  # poll target
        try:
            assert matcher.on_tipset(pairs[0].parent) == 0  # first: no pair yet
            assert matcher.on_tipset(pairs[0].child) == 1
            # a replayed height is a no-op, not a re-delivery
            assert matcher.on_tipset(pairs[0].child) == 0
            # replaying the full matching cycle dedups on the idempotency key
            assert matcher.match_pair(pairs[0]) == 0
            assert _counters(m)["subs.delivery_dedup"] >= 1
            assert log.pending_total() == 1
        finally:
            _drain_stack(reg, log, push, matcher)

    def test_one_failing_filter_does_not_starve_the_rest(
        self, tmp_path, world, monkeypatch
    ):
        store, pairs, _ = world
        import ipc_proofs_tpu.proofs.range as range_mod

        real = range_mod.generate_event_proofs_for_range_chunked

        def boom_for_filter_a(store_, pairs_, spec, **kw):
            if spec.actor_id_filter is None:  # FILTER_A has no actor_id
                raise RuntimeError("seeded generation fault")
            return real(store_, pairs_, spec, **kw)

        monkeypatch.setattr(
            range_mod, "generate_event_proofs_for_range_chunked", boom_for_filter_a
        )
        opener = _RecordingOpener()
        m, reg, log, push, matcher = _stack(str(tmp_path), store, opener)
        reg.subscribe(FILTER_A, {"url": "http://h/a"}, sub_id="w-a")
        reg.subscribe(FILTER_B, {"url": "http://h/b"}, sub_id="w-b")
        try:
            assert matcher.match_pair(pairs[0]) == 1  # B delivered
            assert _counters(m)["subs.errors"] == 1  # A counted, not raised
            assert _wait_until(lambda: len(opener.calls("w-b")) == 1)
            assert opener.calls("w-a") == []
        finally:
            _drain_stack(reg, log, push, matcher)


# --------------------------------------------------------------------------
# serve plane: HTTP routes, healthz, shutdown ordering
# --------------------------------------------------------------------------


def _http_json(url, body=None, timeout=30):
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(
        url,
        data=data,
        headers={"Content-Type": "application/json"} if data else {},
        method="POST" if data else "GET",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


class TestServePlane:
    def test_subscription_routes_and_healthz(self, tmp_path, world):
        store, pairs, _ = world
        svc = ProofService(
            store=store,
            spec=EventProofSpec(SIG, SUBNET),
            config=ServiceConfig(max_batch=4, max_wait_ms=5.0, workers=1),
        )
        sq = StandingQueries(
            str(tmp_path), store=store, fsync=False,
            opener=_RecordingOpener(), sleep=_NOSLEEP, rng=random.Random(0),
        )
        httpd = ProofHTTPServer(svc, port=0, pairs=pairs, subs=sq).start()
        try:
            status, obj = _http_json(
                httpd.address + "/v1/subscribe",
                {"filter": FILTER_A, "sub_id": "http-1"},
            )
            assert status == 200 and obj == {"sub_id": "http-1", "created": True}
            status, obj = _http_json(httpd.address + "/v1/subscriptions")
            assert status == 200 and obj["count"] == 1
            assert obj["subscriptions"][0]["sub_id"] == "http-1"

            sq.matcher.match_pair(pairs[0])
            status, obj = _http_json(
                httpd.address + "/v1/deliveries?sub=http-1&cursor=0"
            )
            assert status == 200 and len(obj["deliveries"]) == 1
            expect, digest = _expected(store, pairs[0], normalize_filter(FILTER_A))
            assert obj["deliveries"][0]["digest"] == digest
            assert obj["deliveries"][0]["payload"]["bundle"] == expect

            status, health = _http_json(httpd.address + "/healthz")
            assert health["subscriptions"] == 1
            assert health["pending_deliveries"] == 1
            assert health["subs_degraded"] is False

            status, obj = _http_json(
                httpd.address + "/v1/unsubscribe", {"sub_id": "http-1"}
            )
            assert status == 200 and obj == {"removed": True}
        finally:
            httpd.shutdown(timeout=30)

    def test_sigterm_mid_push_drains_workers_before_service(
        self, tmp_path, world
    ):
        """The shutdown-ordering regression: a SIGTERM landing while a
        webhook POST is in flight must drain the delivery workers (the
        push completes and acks) BEFORE the proof service closes."""
        store, pairs, _ = world
        entered = threading.Event()
        release = threading.Event()

        def blocking_opener(url, body, timeout_s):
            entered.set()
            assert release.wait(timeout=30)
            return 200

        m = Metrics()
        svc = ProofService(
            store=store,
            spec=EventProofSpec(SIG, SUBNET),
            config=ServiceConfig(max_batch=4, max_wait_ms=5.0, workers=1),
        )
        sq = StandingQueries(
            str(tmp_path), store=store, metrics=m, fsync=False,
            opener=blocking_opener, sleep=_NOSLEEP, rng=random.Random(0),
        )
        httpd = ProofHTTPServer(svc, port=0, pairs=pairs, subs=sq).start()

        order = []
        orig_subs_drain, orig_svc_drain = sq.drain, svc.drain
        sq.drain = lambda: (order.append("subs"), orig_subs_drain())[-1]
        svc.drain = lambda *a, **k: (
            order.append("service"), orig_svc_drain(*a, **k)
        )[-1]

        sub, _ = sq.registry.subscribe(
            FILTER_A, {"url": "http://hooks/block"}, sub_id="wh-block"
        )
        d = sq.log.append("wh-block", 41_001, "aa" * 16, {"bundle": {"n": 1}})
        sq.push.push(sub, d)
        assert entered.wait(timeout=10)  # the POST is now mid-flight

        def _raise_kbd(signum, frame):
            raise KeyboardInterrupt  # what the serve CLI's handler does

        old = signal.signal(signal.SIGTERM, _raise_kbd)
        try:
            releaser = threading.Timer(0.3, release.set)
            releaser.start()
            with pytest.raises(KeyboardInterrupt):
                os.kill(os.getpid(), signal.SIGTERM)
                time.sleep(10)
            httpd.shutdown(timeout=30)  # the CLI's finally block
            releaser.join()
        finally:
            signal.signal(signal.SIGTERM, old)
        assert order == ["subs", "service"]
        assert sq.log.pending("wh-block") == []  # in-flight push landed+acked
        assert _counters(m)["subs.pushes"] == 1


# --------------------------------------------------------------------------
# end to end: follower → matcher → fan-out → restart
# --------------------------------------------------------------------------


class TestEndToEndStanding:
    def test_follow_match_push_restart(self, tmp_path, world):
        store, pairs, _ = world
        root = str(tmp_path / "subs")
        m = Metrics()

        # webhook behavior: wh-flaky's endpoint is down for the whole first
        # life of the daemon; wh-a1's endpoint drops exactly one request
        # (transient); everything else is healthy.
        flaky_lock = threading.Lock()
        state = {"wh-a1-drops": 1}

        def behavior(envelope):
            if envelope["sub_id"] == "wh-flaky":
                return 503
            with flaky_lock:
                if envelope["sub_id"] == "wh-a1" and state["wh-a1-drops"]:
                    state["wh-a1-drops"] -= 1
                    return 503
            return 200

        opener = _RecordingOpener(behavior)
        sq = StandingQueries(
            root, store=store, metrics=m, fsync=False, push_max_inflight=2,
            opener=opener, sleep=_NOSLEEP, rng=random.Random(0),
        )
        for sub_id, filt, url in (
            ("wh-a1", FILTER_A, "http://hooks/a1"),
            ("wh-a2", FILTER_A, "http://hooks/a2"),
            ("poll-a", FILTER_A, None),
            ("wh-b1", FILTER_B, "http://hooks/b1"),
            ("wh-flaky", FILTER_B, "http://hooks/flaky"),
            ("poll-b", FILTER_B, None),
        ):
            body = {"filter": filt, "sub_id": sub_id}
            if url:
                body["target"] = {"url": url}
            assert sq.subscribe(body)["created"]

        # a real follower over a seeded local session; head advances one
        # height per poll so each poll finalizes exactly one tipset
        session = LocalLotusSession(store)
        client = LotusClient("http://test-follow", session=session, metrics=m)
        follower = ChainFollower(client, store, metrics=m, lag=1)
        follower.add_finalized_hook(sq.on_tipset)
        feed = []
        for p in pairs[:3]:
            feed.extend([p.parent, p.child])
        for ts in feed:
            session._responses["Filecoin.ChainHead"] = {
                "Height": ts.height + 1,
                "Cids": [{"/": str(c)} for c in ts.cids],
            }
            session._responses["Filecoin.ChainGetTipSetByHeight"] = (
                _tipset_api_json(ts)
            )
            assert follower.poll_once() == 1

        # convergence: 3 healthy webhook subs × 3 matched pairs all acked;
        # pending = 2 poll subs × 3 + wh-flaky's 3 stranded deliveries
        assert _wait_until(
            lambda: _counters(m).get("subs.pushes", 0) == 9
            and sq.log.pending_total() == 9
            and _gauges(m).get("subs.push_inflight") == 0
        ), _counters(m)

        c = _counters(m)
        # (2) exactly one generation per distinct (pair, filter): the
        # follower observed 5 pairs (3 real + 2 parent-gap pairs with no
        # receipts) and 2 distinct filters were registered throughout
        assert c["subs.tipsets_matched"] == 5
        assert c["subs.generations"] == 5 * 2
        assert c["subs.empty_matches"] == 2 * 2
        assert c["subs.notifications"] == 6 * 3  # every subscriber, every pair
        # (3) the transient wh-a1 failure converged via in-push retry and
        # nothing ever acked twice
        assert c["subs.push_retries"] >= 1
        assert "subs.duplicate_acks" not in c
        assert c["subs.push_failures"] >= 3  # wh-flaky exhausted each pair

        # (1) every delivery expands byte-identical to the request/response
        # path's bundle for the same (pair, filter) — full pushes carry
        # the verbatim bundle; delta pushes (the subscriber acked an
        # earlier epoch's bundle) expand through the witness plane against
        # the base they name, digest-checked
        expected_by_digest = {}
        for filt in (FILTER_A, FILTER_B):
            for pair in pairs[:3]:
                obj, digest = _expected(store, pair, normalize_filter(filt))
                expected_by_digest[digest] = obj
        for sub_id, filt in (("wh-a1", FILTER_A), ("wh-b1", FILTER_B)):
            for pair in pairs[:3]:
                obj, digest = _expected(store, pair, normalize_filter(filt))
                raw = json.dumps(obj, sort_keys=True)
                acked = [
                    (u, b, env)
                    for (u, b, env) in opener.calls(sub_id)
                    if env["tipset"] == pair.child.height
                ]
                assert acked, (sub_id, pair.child.height)
                for _u, body, env in acked:
                    assert env["digest"] == digest
                    if "bundle" in env:
                        assert body.decode("utf-8").endswith(
                            ', "bundle": ' + raw + "}"
                        )
                    else:
                        base = UnifiedProofBundle.from_json_obj(
                            expected_by_digest[env["bundle_delta"]["base_digest"]]
                        )
                        assert (
                            apply_delta(env["bundle_delta"], base).to_json_obj()
                            == obj
                        )
        polled = sq.deliveries("poll-a", cursor=0)
        assert [e["tipset"] for e in polled["deliveries"]] == [
            p.child.height for p in pairs[:3]
        ]
        for entry, pair in zip(polled["deliveries"], pairs[:3]):
            obj, digest = _expected(store, pair, normalize_filter(FILTER_A))
            assert entry["digest"] == digest
            assert entry["payload"]["bundle"] == obj

        # (4) SIGKILL: no drain, no close — just abandon the instance and
        # replay the journals. Registrations and unacked deliveries
        # survive; the constructor's repush converges wh-flaky now that
        # its endpoint is back.
        m2 = Metrics()
        opener2 = _RecordingOpener()
        sq2 = StandingQueries(
            root, store=store, metrics=m2, fsync=False,
            opener=opener2, sleep=_NOSLEEP, rng=random.Random(1),
        )
        try:
            assert len(sq2.registry) == 6
            assert sorted(s.sub_id for s in sq2.registry.active()) == [
                "poll-a", "poll-b", "wh-a1", "wh-a2", "wh-b1", "wh-flaky",
            ]
            assert _wait_until(lambda: sq2.log.pending_total() == 6)
            assert len(opener2.calls("wh-flaky")) == 3
            c2 = _counters(m2)
            assert c2["subs.acks"] == 3 and "subs.duplicate_acks" not in c2
            # the poll subscribers' cursors survived verbatim
            polled2 = sq2.deliveries("poll-b", cursor=0)
            assert [e["tipset"] for e in polled2["deliveries"]] == [
                p.child.height for p in pairs[:3]
            ]
            obj, _ = _expected(store, pairs[0], normalize_filter(FILTER_B))
            assert polled2["deliveries"][0]["payload"]["bundle"] == obj
            # acking through the long-poll cursor releases them for good
            last = polled2["cursor"]
            assert sq2.deliveries("poll-b", cursor=last)["deliveries"] == []
            assert sq2.log.pending("poll-b") == []
        finally:
            sq2.drain()
            sq.drain()  # post-mortem cleanup of the "killed" instance


# --------------------------------------------------------------------------
# cluster failover
# --------------------------------------------------------------------------


class TestClusterStandingFailover:
    def test_dead_shard_arc_rearcs_under_original_ids(self, tmp_path, world):
        store, pairs, _ = world
        shards, sqs = [], []
        for i in range(2):
            sq = StandingQueries(
                str(tmp_path / f"subs{i}"), store=store, fsync=False,
                opener=_RecordingOpener(), sleep=_NOSLEEP, rng=random.Random(i),
            )
            shard = LocalShard(
                f"s{i}", store, pairs, EventProofSpec(SIG, SUBNET),
                config=ServiceConfig(max_batch=4, max_wait_ms=5.0, workers=1),
                subs=sq,
            ).start()
            shards.append(shard)
            sqs.append(sq)
        rm = Metrics()
        router = ClusterRouter(
            {s.name: s.url for s in shards}, pairs, metrics=rm
        )
        try:
            sub_ids = [f"sub-{i}" for i in range(6)]
            filters = {
                sid: (FILTER_A if i % 2 == 0 else FILTER_B)
                for i, sid in enumerate(sub_ids)
            }
            for sid in sub_ids:
                status, obj = router.subscribe(
                    {"filter": filters[sid], "sub_id": sid}
                )
                assert status == 200 and obj["sub_id"] == sid
            status, obj = router.subscriptions()
            assert status == 200 and obj["count"] == 6

            # the router places by filter ring key — recompute the owners
            ring = HashRing()
            ring.add("s0")
            ring.add("s1")
            owner = {
                sid: ring.node_for(
                    subscription_ring_key(normalize_filter(filters[sid]))
                )
                for sid in sub_ids
            }
            dead_name = owner["sub-0"]  # the shard holding FILTER_A's arc
            dead_idx = int(dead_name[1:])
            surv_idx = 1 - dead_idx

            # a matched pair on the owning shard streams through the router
            sqs[dead_idx].matcher.match_pair(pairs[0])
            status, obj = router.deliveries("sub-0", cursor=0)
            assert status == 200
            expect, digest = _expected(store, pairs[0], normalize_filter(FILTER_A))
            assert [e["digest"] for e in obj["deliveries"]] == [digest]
            assert obj["deliveries"][0]["payload"]["bundle"] == expect

            shards[dead_idx].kill()  # crash: port refuses, nothing drained

            # failover: aggregation marks the arc dead and re-registers its
            # subscriptions on the survivor under the ORIGINAL ids
            def _recovered():
                status, obj = router.subscriptions()
                return status == 200 and obj["count"] == 6

            assert _wait_until(_recovered, timeout=30.0)
            status, obj = router.subscriptions()
            assert sorted(s["sub_id"] for s in obj["subscriptions"]) == sub_ids
            assert obj["shards"] == {f"s{surv_idx}": 6}
            n_moved = sum(1 for sid in sub_ids if owner[sid] == dead_name)
            assert _counters(rm).get("cluster.subs_rearced", 0) == n_moved

            # the survivor's matcher now serves the re-homed subscribers
            sqs[surv_idx].matcher.match_pair(pairs[1])
            status, obj = router.deliveries("sub-0", cursor=0)
            assert status == 200
            assert pairs[1].child.height in [
                e["tipset"] for e in obj["deliveries"]
            ]
        finally:
            router.close()
            for s in shards:
                try:
                    s.stop(timeout=10)
                except Exception:
                    pass
            for sq in sqs:
                try:
                    sq.drain()
                except Exception:
                    pass
