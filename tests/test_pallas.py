"""Pallas kernel equivalence tests (interpreter mode on CPU hosts)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ipc_proofs_tpu.core.hashes import blake2b_256, keccak256  # noqa: E402
from ipc_proofs_tpu.ops.pack import digests_to_bytes  # noqa: E402
from ipc_proofs_tpu.ops.pallas_kernels import (  # noqa: E402
    blake2b256_single_block_pallas,
    keccak256_single_block_pallas,
    pack_single_block_blake2b,
    pack_single_block_keccak,
)

INTERPRET = jax.devices()[0].platform != "tpu"

KECCAK_MSGS = [
    b"",
    b"abc",
    b"Transfer(address,address,uint256)",
    b"\xaa" * 64,  # mapping-slot preimage shape
    b"\x42" * 135,  # max single-block
]

BLAKE_MSGS = [b"", b"abc", b"\x11" * 64, b"\x22" * 127, b"\x33" * 128]


class TestPallasKeccak:
    def test_matches_golden(self):
        blo, bhi, n = pack_single_block_keccak(KECCAK_MSGS)
        out = keccak256_single_block_pallas(
            jnp.asarray(blo), jnp.asarray(bhi), interpret=INTERPRET
        )
        digests = digests_to_bytes(out[:n])
        for msg, digest in zip(KECCAK_MSGS, digests):
            assert digest == keccak256(msg), f"len={len(msg)}"

    def test_rejects_multiblock(self):
        with pytest.raises(ValueError):
            pack_single_block_keccak([b"\x00" * 136])

    def test_full_tile_batch(self):
        msgs = [f"slot-{i}".encode() * 3 for i in range(300)]
        blo, bhi, n = pack_single_block_keccak(msgs)
        assert blo.shape[0] == 512  # padded to TILE multiple
        out = keccak256_single_block_pallas(
            jnp.asarray(blo), jnp.asarray(bhi), interpret=INTERPRET
        )
        digests = digests_to_bytes(out[:n])
        for msg, digest in zip(msgs, digests):
            assert digest == keccak256(msg)


class TestPallasBlake2b:
    def test_matches_golden(self):
        mlo, mhi, lengths, n = pack_single_block_blake2b(BLAKE_MSGS)
        out = blake2b256_single_block_pallas(
            jnp.asarray(mlo), jnp.asarray(mhi), jnp.asarray(lengths), interpret=INTERPRET
        )
        digests = digests_to_bytes(out[:n])
        for msg, digest in zip(BLAKE_MSGS, digests):
            assert digest == blake2b_256(msg), f"len={len(msg)}"

    def test_rejects_multiblock(self):
        with pytest.raises(ValueError):
            pack_single_block_blake2b([b"\x00" * 129])

    def test_cid_digest_batch(self):
        from ipc_proofs_tpu.core.cid import CID

        payloads = [f"ipld-node-{i}".encode() * 2 for i in range(64)]
        mlo, mhi, lengths, n = pack_single_block_blake2b(payloads)
        out = blake2b256_single_block_pallas(
            jnp.asarray(mlo), jnp.asarray(mhi), jnp.asarray(lengths), interpret=INTERPRET
        )
        digests = digests_to_bytes(out[:n])
        for payload, digest in zip(payloads, digests):
            assert CID.hash_of(payload).digest == digest


class TestPallasBlake2bTwoBlock:
    MSGS = [
        b"",
        b"abc",
        b"\x22" * 127,
        b"\x33" * 128,  # exactly one block — single-compression select path
        b"\x44" * 129,  # first two-block length
        b"\x55" * 200,  # BASELINE config 4's IPLD node size
        b"\x66" * 255,
        b"\x77" * 256,  # max
    ]

    def test_matches_golden(self):
        from ipc_proofs_tpu.ops.pallas_kernels import (
            blake2b256_two_block_pallas,
            pack_two_block_blake2b,
        )

        mlo, mhi, lengths, n = pack_two_block_blake2b(self.MSGS)
        out = blake2b256_two_block_pallas(
            jnp.asarray(mlo), jnp.asarray(mhi), jnp.asarray(lengths), interpret=INTERPRET
        )
        digests = digests_to_bytes(out[:n])
        for msg, digest in zip(self.MSGS, digests):
            assert digest == blake2b_256(msg), f"len={len(msg)}"

    def test_random_mixed_lengths(self):
        import random

        from ipc_proofs_tpu.ops.pallas_kernels import (
            blake2b256_two_block_pallas,
            pack_two_block_blake2b,
        )

        rng = random.Random(99)
        msgs = [
            bytes(rng.randrange(256) for _ in range(rng.randrange(257)))
            for _ in range(40)
        ]
        mlo, mhi, lengths, n = pack_two_block_blake2b(msgs)
        out = blake2b256_two_block_pallas(
            jnp.asarray(mlo), jnp.asarray(mhi), jnp.asarray(lengths), interpret=INTERPRET
        )
        digests = digests_to_bytes(out[:n])
        for msg, digest in zip(msgs, digests):
            assert digest == blake2b_256(msg), f"len={len(msg)}"

    def test_rejects_over_256(self):
        from ipc_proofs_tpu.ops.pallas_kernels import pack_two_block_blake2b

        with pytest.raises(ValueError):
            pack_two_block_blake2b([b"\x00" * 257])
