"""Batch storage-proof driver tests."""

import pytest

from ipc_proofs_tpu.backend import get_backend
from ipc_proofs_tpu.fixtures import ContractFixture, build_chain
from ipc_proofs_tpu.proofs.generator import StorageProofSpec, generate_proof_bundle
from ipc_proofs_tpu.proofs.storage_batch import (
    MappingSlotSpec,
    generate_storage_proofs_batch,
)
from ipc_proofs_tpu.proofs.trust import TrustPolicy
from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle
from ipc_proofs_tpu.state.storage import calculate_storage_slot
from ipc_proofs_tpu.utils.metrics import Metrics


def _world(n_contracts=3, n_slots=5):
    contracts = []
    for c in range(n_contracts):
        actor_id = 1000 + c
        storage = {
            calculate_storage_slot(f"subnet-{c}-{s}", 0): (c * 16 + s + 1).to_bytes(1, "big")
            for s in range(n_slots)
        }
        contracts.append(ContractFixture(actor_id=actor_id, storage=storage))
    return build_chain(contracts, [[]]), n_contracts, n_slots


class TestStorageBatch:
    def _specs(self, n_contracts, n_slots):
        return [
            MappingSlotSpec(actor_id=1000 + c, key=f"subnet-{c}-{s}", slot_index=0)
            for c in range(n_contracts)
            for s in range(n_slots)
        ]

    def test_batch_matches_per_spec_generator(self):
        world, nc, ns = _world()
        specs = self._specs(nc, ns)
        batch = generate_storage_proofs_batch(world.store, world.parent, world.child, specs)
        # the one-at-a-time path (reference architecture)
        singles = generate_proof_bundle(
            world.store,
            world.parent,
            world.child,
            [
                StorageProofSpec(
                    actor_id=s.actor_id, slot=calculate_storage_slot(s.key, s.slot_index)
                )
                for s in specs
            ],
            [],
        )
        assert [p.to_json_obj() for p in batch.storage_proofs] == [
            p.to_json_obj() for p in singles.storage_proofs
        ]
        # merged witness must be identical too (same traversals, same dedup)
        assert [str(b.cid) for b in batch.blocks] == [str(b.cid) for b in singles.blocks]

    def test_batch_verifies(self):
        world, nc, ns = _world()
        specs = self._specs(nc, ns)
        for backend in (None, get_backend("cpu")):
            bundle = generate_storage_proofs_batch(
                world.store, world.parent, world.child, specs, hash_backend=backend
            )
            result = verify_proof_bundle(bundle, TrustPolicy.accept_all())
            assert result.storage_results == [True] * (nc * ns)

    def test_tpu_backend_same_slots(self):
        pytest.importorskip("jax")
        world, nc, ns = _world(2, 3)
        specs = self._specs(2, 3)
        cpu = generate_storage_proofs_batch(
            world.store, world.parent, world.child, specs, hash_backend=get_backend("cpu")
        )
        tpu = generate_storage_proofs_batch(
            world.store, world.parent, world.child, specs, hash_backend=get_backend("tpu")
        )
        assert cpu.to_json() == tpu.to_json()

    def test_absent_slots_prove_zero(self):
        world, _, _ = _world(1, 1)
        specs = [MappingSlotSpec(actor_id=1000, key="no-such-key", slot_index=9)]
        bundle = generate_storage_proofs_batch(world.store, world.parent, world.child, specs)
        assert bundle.storage_proofs[0].value == "0x" + "00" * 32
        assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).all_valid()

    def test_metrics(self):
        world, nc, ns = _world()
        metrics = Metrics()
        generate_storage_proofs_batch(
            world.store, world.parent, world.child, self._specs(nc, ns), metrics=metrics
        )
        snap = metrics.snapshot()
        assert snap["counters"]["batch_slots"] == nc * ns
        assert snap["counters"]["batch_contracts"] == nc

    def test_raw_bytes_key(self):
        world, _, _ = _world(1, 2)
        from ipc_proofs_tpu.state.events import ascii_to_bytes32

        specs = [MappingSlotSpec(actor_id=1000, key=ascii_to_bytes32("subnet-0-0"))]
        bundle = generate_storage_proofs_batch(world.store, world.parent, world.child, specs)
        assert bundle.storage_proofs[0].value.endswith("01")


class TestRangeBatchedStorageGeneration:
    """generate_storage_proofs_for_pairs must emit bundles BIT-IDENTICAL to
    the per-pair scalar loop (claims field-for-field, witness block-for-
    block) across encodings, and the range drivers must round-trip."""

    def _native_or_skip(self):
        from ipc_proofs_tpu.ipld.hamt import hamt_get_batch
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

        if hamt_get_batch(MemoryBlockstore(), [], [], []) is None:
            pytest.skip("native hamt_lookup_batch unavailable")

    def test_bit_identical_to_per_pair_loop(self, monkeypatch):
        # range worlds build 'direct'-encoded storage; the other encodings
        # are covered by test_single_pair_all_encodings_bit_identical
        self._native_or_skip()
        from ipc_proofs_tpu.backend import get_backend
        from ipc_proofs_tpu.fixtures import build_range_world
        from ipc_proofs_tpu.proofs.generator import EventProofSpec
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range
        from ipc_proofs_tpu.proofs.storage_batch import MappingSlotSpec
        from ipc_proofs_tpu.proofs.trust import TrustPolicy
        from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle

        bs, pairs, _ = build_range_world(12, 4, 2, 0.3)
        spec = EventProofSpec(
            event_signature="NewTopDownMessage(bytes32,uint256)",
            topic_1="calib-subnet-1",
            actor_id_filter=1001,
        )
        specs = [
            MappingSlotSpec(actor_id=1001, key=f"calib-subnet-{k}", slot_index=0)
            for k in range(3)
        ]
        backend = get_backend("cpu")
        batched = generate_event_proofs_for_range(
            bs, pairs, spec, match_backend=backend, storage_specs=specs
        )
        # force the per-pair scalar path by hiding the batched generator
        import ipc_proofs_tpu.proofs.storage_batch as sb

        monkeypatch.setattr(
            sb, "generate_storage_proofs_for_pairs", lambda *a, **k: None
        )
        scalar = generate_event_proofs_for_range(
            bs, pairs, spec, match_backend=backend, storage_specs=specs
        )
        assert batched.to_json() == scalar.to_json()
        result = verify_proof_bundle(
            batched, TrustPolicy.accept_all(), verify_witness_cids=True
        )
        assert result.all_valid()
        assert len(batched.storage_proofs) == len(pairs) * len(specs)

    @pytest.mark.parametrize(
        "encoding", ["direct", "wrapper_tuple", "wrapper_map", "inline"]
    )
    def test_single_pair_all_encodings_bit_identical(self, encoding):
        self._native_or_skip()
        from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
        from ipc_proofs_tpu.proofs.range import TipsetPair, _storage_for_pairs
        from ipc_proofs_tpu.proofs.storage_batch import (
            MappingSlotSpec,
            generate_storage_proofs_batch,
            hash_slot_specs,
        )
        from ipc_proofs_tpu.state.storage import calculate_storage_slot
        from ipc_proofs_tpu.store.blockstore import CachedBlockstore, MemoryBlockstore

        bs = MemoryBlockstore()
        storage = {
            calculate_storage_slot(f"s-{i}", 0): (i + 1).to_bytes(2, "big")
            for i in range(5)
        }
        world = build_chain(
            [ContractFixture(actor_id=55, storage=storage, storage_encoding=encoding)],
            [[EventFixture(emitter=55, signature="E()", topic1="t")]],
            store=bs,
        )
        specs = [MappingSlotSpec(actor_id=55, key=f"s-{i}", slot_index=0) for i in range(5)]
        specs.append(MappingSlotSpec(actor_id=55, key="absent", slot_index=3))
        pairs = [TipsetPair(parent=world.parent, child=world.child)]
        cached = CachedBlockstore(bs)
        proofs, witness_bytes, fb = _storage_for_pairs(cached, pairs, specs, None)
        assert fb == [] and witness_bytes  # batched path ran
        slots = hash_slot_specs(specs)
        scalar_bundle = generate_storage_proofs_batch(
            bs, world.parent, world.child, specs, precomputed_slots=slots
        )
        assert [p.__dict__ for p in proofs] == [
            p.__dict__ for p in scalar_bundle.storage_proofs
        ]
        assert sorted(witness_bytes) == sorted(
            b.cid.to_bytes() for b in scalar_bundle.blocks
        )


class TestRandomizedStorageDifferential:
    """Seeded random storage worlds — random encodings, value sizes, absent
    slots, multiple contracts — where the range-batched generator must emit
    bit-identical bundles to the scalar loop and the batched verifier must
    agree with the scalar verifier (including under random tampering)."""

    def test_random_worlds_round_trip(self):
        import numpy as np

        from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
        from ipc_proofs_tpu.ipld.hamt import hamt_get_batch
        from ipc_proofs_tpu.proofs.range import TipsetPair, _storage_for_pairs
        from ipc_proofs_tpu.proofs.storage_batch import (
            MappingSlotSpec,
            generate_storage_proofs_batch,
            hash_slot_specs,
        )
        from ipc_proofs_tpu.proofs.storage_verifier import (
            verify_storage_proof,
            verify_storage_proofs_batch,
        )
        from ipc_proofs_tpu.proofs.witness import load_witness_store
        from ipc_proofs_tpu.state.storage import calculate_storage_slot
        from ipc_proofs_tpu.store.blockstore import CachedBlockstore, MemoryBlockstore

        from ipc_proofs_tpu.core.cid import CID

        if hamt_get_batch(MemoryBlockstore(), [], [], []) is None:
            pytest.skip("native hamt_lookup_batch unavailable")
        rng = np.random.default_rng(422)
        encodings = ["direct", "wrapper_tuple", "wrapper_map", "inline"]
        accept = lambda *_: True
        for trial in range(12):
            bs = MemoryBlockstore()
            contracts, specs = [], []
            n_contracts = int(rng.integers(1, 4))
            for c in range(n_contracts):
                n_slots = int(rng.integers(0, 8))
                storage = {}
                slot_indices = []
                for i in range(n_slots):
                    idx = int(rng.integers(0, 3))
                    slot_indices.append(idx)
                    slot = calculate_storage_slot(f"t{trial}-c{c}-s{i}", idx)
                    storage[slot] = bytes(
                        rng.integers(0, 256, size=int(rng.integers(1, 40)), dtype="uint8")
                    )
                contracts.append(
                    ContractFixture(
                        actor_id=200 + c,
                        storage=storage,
                        storage_encoding=str(rng.choice(encodings)),
                    )
                )
                for i in range(n_slots):
                    specs.append(  # same index the value was stored under
                        MappingSlotSpec(
                            actor_id=200 + c,
                            key=f"t{trial}-c{c}-s{i}",
                            slot_index=slot_indices[i],
                        )
                    )
                specs.append(  # an absent probe per contract
                    MappingSlotSpec(actor_id=200 + c, key=f"t{trial}-c{c}-nope")
                )
            world = build_chain(
                contracts,
                [[EventFixture(emitter=200, signature="E()", topic1="x")]],
                store=bs,
            )
            pairs = [TipsetPair(parent=world.parent, child=world.child)]
            cached = CachedBlockstore(bs)
            proofs, wbytes, fb = _storage_for_pairs(cached, pairs, specs, None)
            assert fb == []
            slots = hash_slot_specs(specs)
            scalar_bundle = generate_storage_proofs_batch(
                bs, world.parent, world.child, specs, precomputed_slots=slots
            )
            assert [p.__dict__ for p in proofs] == [
                p.__dict__ for p in scalar_bundle.storage_proofs
            ], trial
            assert sorted(wbytes) == sorted(
                b.cid.to_bytes() for b in scalar_bundle.blocks
            ), trial

            # verify: batch vs scalar, valid + randomly tampered claims
            store = load_witness_store(scalar_bundle.blocks, verify_cids=False)
            tampered = list(scalar_bundle.storage_proofs)
            if tampered and rng.random() < 0.7:
                import dataclasses as dc

                j = int(rng.integers(0, len(tampered)))
                field = str(rng.choice(["value", "actor_id", "storage_root"]))
                if field == "value":
                    tampered[j] = dc.replace(tampered[j], value="0x" + "fe" * 32)
                elif field == "actor_id":
                    tampered[j] = dc.replace(tampered[j], actor_id=999999)
                else:
                    tampered[j] = dc.replace(
                        tampered[j], storage_root=str(CID.hash_of(b"zz"))
                    )
            scalar_v = [
                verify_storage_proof(p, scalar_bundle.blocks, accept, store=store)
                for p in tampered
            ]
            batch_v = verify_storage_proofs_batch(store, tampered, accept)
            assert scalar_v == batch_v, trial
