"""Mesh-sharded event matching tests: the 1×1-mesh bit-identity grid
(pjit/NamedSharding path vs the host reference and the plain single-device
path), coalescer dispatch-bucket padding (pow-2, mesh-divisible,
valid=False filler, `range_match_retraces` growing O(log n)), the
mesh-aware backend registry, and the range-driver coalescer enablement.
Runs on the CPU backend of jax (JAX_PLATFORMS=cpu — the mesh is real, the
chips are not), so everything here is hermetic tier-1."""

import numpy as np
import pytest

from ipc_proofs_tpu.parallel.pipeline import MatchCoalescer
from ipc_proofs_tpu.proofs.scan_native import match_mask_fp_np, topic_fingerprint
from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature
from ipc_proofs_tpu.utils.metrics import Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"
TOPIC0 = hash_event_signature(SIG)
TOPIC1 = ascii_to_bytes32("calib-subnet-1")
ACTOR = 1001


def _mesh_backend():
    from ipc_proofs_tpu.backend.tpu import TpuBackend
    from ipc_proofs_tpu.parallel.mesh import make_mesh

    return TpuBackend(mesh=make_mesh(1))


def _arrays(n: int, seed: int, match_rate: float = 0.1):
    rng = np.random.default_rng(seed)
    fp = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    hit = rng.random(n) < match_rate
    fp[hit] = np.uint64(topic_fingerprint(TOPIC0, TOPIC1))
    n_topics = rng.integers(0, 4, size=n).astype(np.int32)
    emitters = rng.integers(ACTOR - 2, ACTOR + 3, size=n).astype(np.int64)
    valid = rng.random(n) < 0.9
    return fp, n_topics, emitters, valid


class TestMeshBitIdentity:
    @pytest.mark.parametrize("n", [1, 5, 255, 256, 257, 1000])
    @pytest.mark.parametrize("actor", [None, ACTOR])
    def test_mesh_path_equals_host_reference(self, n, actor):
        backend = _mesh_backend()
        fp, n_topics, emitters, valid = _arrays(n, seed=n)
        got = np.asarray(
            backend.event_match_mask_fp(
                fp, n_topics, emitters, valid, TOPIC0, TOPIC1, actor
            )
        )[:n]
        want = np.asarray(
            match_mask_fp_np(
                fp, n_topics, emitters, valid, TOPIC0, TOPIC1, actor
            )
        )[:n]
        assert np.array_equal(got, want)

    def test_mesh_forces_the_device_path(self):
        # a plain TpuBackend host-crossovers small batches; a meshed one
        # must never (the sharded pipeline wants the mask where it runs)
        backend = _mesh_backend()
        assert backend._match_on_device(1) is True

    def test_planted_matches_are_found(self):
        backend = _mesh_backend()
        fp, n_topics, emitters, valid = _arrays(512, seed=3, match_rate=0.5)
        n_topics[:] = 2
        valid[:] = True
        got = np.asarray(
            backend.event_match_mask_fp(
                fp, n_topics, emitters, valid, TOPIC0, TOPIC1, None
            )
        )[:512]
        planted = fp == np.uint64(topic_fingerprint(TOPIC0, TOPIC1))
        assert np.array_equal(got, planted)
        assert planted.any()


class TestCoalescerDispatchPadding:
    def test_coalescer_identical_to_direct_call(self):
        backend = _mesh_backend()
        m = Metrics()
        co = MatchCoalescer(backend, metrics=m)
        for n in (1, 37, 300):
            fp, n_topics, emitters, valid = _arrays(n, seed=n)
            got = np.asarray(
                co.match_fp(fp, n_topics, emitters, valid, TOPIC0, TOPIC1, ACTOR)
            )[:n]
            want = match_mask_fp_np(
                fp, n_topics, emitters, valid, TOPIC0, TOPIC1, ACTOR
            )[:n]
            assert np.array_equal(got, want)

    def test_dispatch_shapes_are_bucketed_and_mesh_divisible(self):
        backend = _mesh_backend()
        co = MatchCoalescer(backend, metrics=Metrics())
        for n in (1, 7, 200, 300, 513):
            fp, n_topics, emitters, valid = _arrays(n, seed=n)
            co.match_fp(fp, n_topics, emitters, valid, TOPIC0, TOPIC1, None)
        for bucket in co._shapes:
            assert bucket % backend.mesh.size == 0
            assert bucket & (bucket - 1) == 0, f"{bucket} is not a power of two"

    def test_retraces_grow_logarithmically(self):
        """63 distinct request sizes under the 256 minimum bucket must
        compile ONE shape; pushing past it adds one shape per octave."""
        backend = _mesh_backend()
        m = Metrics()
        co = MatchCoalescer(backend, metrics=m)
        for n in range(1, 64):
            fp, n_topics, emitters, valid = _arrays(n, seed=n)
            co.match_fp(fp, n_topics, emitters, valid, TOPIC0, TOPIC1, None)
        assert m.counter_value("range_match_retraces") == 1
        fp, n_topics, emitters, valid = _arrays(300, seed=0)
        co.match_fp(fp, n_topics, emitters, valid, TOPIC0, TOPIC1, None)
        assert m.counter_value("range_match_retraces") == 2

    def test_padding_rows_never_match(self):
        # the filler is valid=False zeros: a batch whose every row matches
        # must come back all-True in its first n rows and the result must
        # be sliced correctly regardless of the padding that followed
        backend = _mesh_backend()
        co = MatchCoalescer(backend, metrics=Metrics())
        n = 10
        fp = np.full(n, np.uint64(topic_fingerprint(TOPIC0, TOPIC1)), dtype=np.uint64)
        n_topics = np.full(n, 2, dtype=np.int32)
        emitters = np.full(n, ACTOR, dtype=np.int64)
        valid = np.ones(n, dtype=bool)
        got = np.asarray(
            co.match_fp(fp, n_topics, emitters, valid, TOPIC0, TOPIC1, ACTOR)
        )
        assert got[:n].all()


class TestBackendRegistry:
    def test_mesh_variant_caches_separately(self):
        from ipc_proofs_tpu.backend import get_backend

        plain = get_backend("tpu")
        meshed = get_backend("tpu", mesh_devices=1)
        assert plain is not meshed
        assert plain.mesh is None
        assert meshed.mesh is not None and meshed.mesh.size == 1
        assert get_backend("tpu", mesh_devices=1) is meshed  # cached

    def test_cpu_with_mesh_is_an_error(self):
        from ipc_proofs_tpu.backend import get_backend

        with pytest.raises(ValueError, match="mesh_devices"):
            get_backend("cpu", mesh_devices=1)

    def test_cpu_backend_carries_no_mesh(self):
        from ipc_proofs_tpu.backend import get_backend

        assert getattr(get_backend("cpu"), "mesh", "missing") is None


class TestRangeDriverEnablement:
    def test_mesh_backend_enables_coalescer_at_one_scan_worker(self):
        """A meshed backend routes every chunk's match through the
        coalescer even with one scan worker — the coalescer's bucket
        padding is what keeps dispatch shapes mesh-divisible — and the
        bundle stays bit-identical to the no-backend run."""
        from ipc_proofs_tpu.fixtures import build_range_world
        from ipc_proofs_tpu.proofs.generator import EventProofSpec
        from ipc_proofs_tpu.proofs.range import (
            generate_event_proofs_for_range,
            generate_event_proofs_for_range_pipelined,
        )

        bs, pairs, _ = build_range_world(
            4, 4, 2, 0.3, signature=SIG, topic1="calib-subnet-1", actor_id=ACTOR
        )
        spec = EventProofSpec(
            event_signature=SIG, topic_1="calib-subnet-1", actor_id_filter=ACTOR
        )
        reference = generate_event_proofs_for_range(bs, pairs, spec).to_json()
        m = Metrics()
        got = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=2, match_backend=_mesh_backend(),
            metrics=m, scan_threads=1, force_pipeline=True,
        ).to_json()
        assert got == reference
        # the coalescer really ran: its bucketed dispatch shapes ticked
        assert m.counter_value("range_match_retraces") >= 1
