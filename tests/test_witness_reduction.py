"""The two-pass witness claim, asserted: the filtered (two-pass) witness is
strictly smaller than the single-pass counterfactual that records every block
the scan touches. `bench.py --leg witness` reports the same comparison as
`witness_reduction_pct`; this test pins the sign so the bench field can never
silently go negative.
"""

from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.proofs.event_generator import single_pass_witness_cids
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"


def test_two_pass_witness_smaller_than_single_pass():
    bs, pairs, n_matching = build_range_world(
        8, receipts_per_pair=16, events_per_receipt=4, match_rate=0.1,
    )
    assert n_matching > 0  # sparse but non-empty: the regime the claim targets

    bundle = generate_event_proofs_for_range(
        bs, pairs, EventProofSpec(event_signature=SIG, topic_1=SUBNET)
    )
    two_pass_bytes = bundle.witness_bytes()
    assert two_pass_bytes > 0

    # union across pairs before summing: the two-pass bundle deduplicates
    # range-wide, so the counterfactual must too
    single_pass = set()
    for pair in pairs:
        single_pass |= single_pass_witness_cids(bs, pair.parent, pair.child)
    single_pass_bytes = sum(len(bs.get(cid)) for cid in single_pass)

    # soundness of the comparison: everything the two-pass witness ships,
    # the single-pass scan also touched
    assert {b.cid for b in bundle.blocks} <= single_pass

    reduction_pct = 100.0 * (1.0 - two_pass_bytes / single_pass_bytes)
    assert reduction_pct > 0.0, (
        f"two-pass witness ({two_pass_bytes} B) should undercut single-pass "
        f"({single_pass_bytes} B)"
    )
    # the README/BASELINE claim is ~60 % for sparse matches; leave headroom
    # but catch a collapse of the filtering win
    assert reduction_pct > 30.0
