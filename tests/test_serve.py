"""Serving daemon tests: micro-batching correctness, backpressure, drain,
deadlines, the closed-loop ≥2× batching win, HTTP endpoints, and the
long-lived block cache.

Everything is hermetic (MemoryBlockstore worlds, ephemeral localhost ports,
no egress) and tier-1.
"""

import json
import threading
import time
from http.client import HTTPConnection

import pytest

from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
from ipc_proofs_tpu.proofs.bundle import ProofBlock, UnifiedProofBundle
from ipc_proofs_tpu.proofs.generator import (
    EventProofSpec,
    StorageProofSpec,
    generate_proof_bundle,
)
from ipc_proofs_tpu.proofs.range import TipsetPair
from ipc_proofs_tpu.serve import (
    DeadlineExceededError,
    MicroBatcher,
    ProofHTTPServer,
    ProofService,
    QueueFullError,
    ServiceClosedError,
    ServiceConfig,
    sequential_verify_baseline,
)
from ipc_proofs_tpu.state.storage import calculate_storage_slot
from ipc_proofs_tpu.store.blockstore import BlockCache, CachedBlockstore, MemoryBlockstore
from ipc_proofs_tpu.utils.metrics import Histogram, Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001
SLOT = calculate_storage_slot(SUBNET, 0)


@pytest.fixture(scope="module")
def world():
    contracts = [
        ContractFixture(actor_id=ACTOR, storage={SLOT: (42).to_bytes(2, "big")})
    ]
    events = [
        [EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET,
                      data=i.to_bytes(32, "big"))]
        for i in range(16)
    ]
    return build_chain(contracts, events)


@pytest.fixture(scope="module")
def full_bundle(world):
    return generate_proof_bundle(
        world.store, world.parent, world.child,
        [StorageProofSpec(actor_id=ACTOR, slot=SLOT)],
        [EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)],
    )


def _requests(full, n):
    """n single-proof request bundles (the per-client request shape), mixing
    event and storage proofs, all sharing the generated witness."""
    reqs = []
    for i in range(n):
        if i % 5 == 4:
            reqs.append(UnifiedProofBundle(
                storage_proofs=list(full.storage_proofs), event_proofs=[],
                blocks=full.blocks,
            ))
        else:
            reqs.append(UnifiedProofBundle(
                storage_proofs=[],
                event_proofs=[full.event_proofs[i % len(full.event_proofs)]],
                blocks=full.blocks,
            ))
    return reqs


class TestVerifyBatching:
    def test_concurrent_mixed_requests_bit_identical_to_sequential(self, world, full_bundle):
        reqs = _requests(full_bundle, 24)
        expected = sequential_verify_baseline(reqs)
        with ProofService(config=ServiceConfig(max_batch=8, max_wait_ms=15.0,
                                               workers=2)) as svc:
            results = [None] * len(reqs)

            def client(i):
                results[i] = svc.verify(reqs[i])

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(reqs))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for got, want in zip(results, expected):
            assert got.storage_results == want.storage_results
            assert got.event_results == want.event_results
        # coalescing actually happened (not 24 batches of one)
        assert any(r.batch_size > 1 for r in results)

    def test_tampered_request_fails_without_poisoning_neighbors(self, full_bundle):
        good = UnifiedProofBundle(
            storage_proofs=[], event_proofs=[full_bundle.event_proofs[0]],
            blocks=full_bundle.blocks,
        )
        bad_proof = json.loads(json.dumps(full_bundle.event_proofs[1].to_json_obj()))
        bad_proof["event_data"]["data"] = "0x" + "ff" * 32  # forged payload
        from ipc_proofs_tpu.proofs.bundle import EventProof

        bad = UnifiedProofBundle(
            storage_proofs=[],
            event_proofs=[EventProof.from_json_obj(bad_proof)],
            blocks=full_bundle.blocks,
        )
        with ProofService(config=ServiceConfig(max_batch=4, max_wait_ms=25.0)) as svc:
            pendings = [svc.submit_verify(b) for b in (good, bad, good)]
            got = [p.result(timeout=30) for p in pendings]
        assert got[0].event_results == [True]
        assert got[1].event_results == [False]
        assert got[2].event_results == [True]

    def test_conflicting_witness_blocks_split_into_sub_merges(self, full_bundle):
        """Two requests claiming different bytes for the same CID must not
        share a merged witness — each is judged on its own blocks."""
        honest = UnifiedProofBundle(
            storage_proofs=[], event_proofs=[full_bundle.event_proofs[0]],
            blocks=full_bundle.blocks,
        )
        # same CIDs, one block's bytes corrupted: a lying witness
        liar_blocks = [
            ProofBlock._make(b.cid, b"\x00" * len(b.data)) if i == 0 else b
            for i, b in enumerate(full_bundle.blocks)
        ]
        liar = UnifiedProofBundle(
            storage_proofs=[], event_proofs=[full_bundle.event_proofs[0]],
            blocks=liar_blocks,
        )
        with ProofService(config=ServiceConfig(max_batch=4, max_wait_ms=25.0)) as svc:
            pendings = [svc.submit_verify(b) for b in (honest, liar)]
            honest_resp = pendings[0].result(timeout=30)
            # the liar's replay may fail or error; the honest request must
            # be unaffected either way
            try:
                liar_resp = pendings[1].result(timeout=30)
                assert liar_resp.event_results != [True] or True
            except Exception:
                pass
        assert honest_resp.event_results == [True]


class TestBackpressure:
    def test_full_queue_rejects_immediately_and_never_blocks(self):
        gate = threading.Event()
        flushed = []

        def slow_flush(batch):
            gate.wait(30)
            for p in batch:
                p.complete("ok")
                flushed.append(p)

        batcher = MicroBatcher(slow_flush, max_batch=1, max_wait_ms=0.0,
                               capacity=2, name="bp")
        first = batcher.submit("r0")
        # wait until the batcher thread has taken r0 into the (blocked) flush
        deadline = time.monotonic() + 10
        while batcher.depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        queued = [batcher.submit(f"r{i}") for i in (1, 2)]  # fills capacity
        t0 = time.monotonic()
        with pytest.raises(QueueFullError) as exc_info:
            batcher.submit("r3")
        assert time.monotonic() - t0 < 1.0  # rejected, not blocked
        assert exc_info.value.retry_after_s > 0
        gate.set()
        batcher.close(drain=True, timeout=30)
        assert first.result(timeout=5) == "ok"
        for p in queued:
            assert p.result(timeout=5) == "ok"

    def test_rejection_counter_exported(self):
        metrics = Metrics()
        gate = threading.Event()
        batcher = MicroBatcher(
            lambda batch: (gate.wait(30), [p.complete(1) for p in batch]),
            max_batch=1, max_wait_ms=0.0, capacity=1, name="rej", metrics=metrics,
        )
        batcher.submit("a")
        deadline = time.monotonic() + 10
        while batcher.depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        batcher.submit("b")
        for _ in range(3):
            with pytest.raises(QueueFullError):
                batcher.submit("c")
        assert metrics.snapshot()["counters"]["serve.rejected_full.rej"] == 3
        gate.set()
        batcher.close(drain=True, timeout=30)

    def test_closed_service_rejects_with_service_closed(self, full_bundle):
        svc = ProofService(config=ServiceConfig(max_batch=4))
        svc.drain()
        req = UnifiedProofBundle(storage_proofs=[], event_proofs=[],
                                 blocks=full_bundle.blocks)
        with pytest.raises(ServiceClosedError):
            svc.submit_verify(req)


class TestDrain:
    def test_drain_loses_zero_accepted_requests(self, full_bundle):
        reqs = _requests(full_bundle, 20)
        expected = sequential_verify_baseline(reqs)
        # long wait + big batch: most requests are still queued when drain
        # starts, so drain itself must flush them
        svc = ProofService(config=ServiceConfig(max_batch=64, max_wait_ms=5000.0,
                                                workers=2))
        pendings = [svc.submit_verify(r) for r in reqs]
        svc.drain(timeout=60)
        for pending, want in zip(pendings, expected):
            got = pending.result(timeout=1)  # already complete post-drain
            assert got.storage_results == want.storage_results
            assert got.event_results == want.event_results

    def test_drain_is_idempotent(self):
        svc = ProofService()
        svc.drain()
        svc.drain()


class TestDeadlines:
    def test_deadline_exceeded_while_queued(self, full_bundle):
        req = UnifiedProofBundle(
            storage_proofs=[], event_proofs=[full_bundle.event_proofs[0]],
            blocks=full_bundle.blocks,
        )
        # the lone request waits max_wait_ms for batch-mates; its 10 ms
        # deadline expires long before the 300 ms window closes
        with ProofService(config=ServiceConfig(max_batch=64,
                                               max_wait_ms=300.0)) as svc:
            pending = svc.submit_verify(req, timeout_s=0.01)
            with pytest.raises(DeadlineExceededError):
                pending.result(timeout=30)

    def test_no_deadline_means_no_expiry(self, full_bundle):
        req = UnifiedProofBundle(
            storage_proofs=[], event_proofs=[full_bundle.event_proofs[0]],
            blocks=full_bundle.blocks,
        )
        with ProofService(config=ServiceConfig(max_batch=4,
                                               max_wait_ms=30.0)) as svc:
            assert svc.verify(req).event_results == [True]


class TestBatchingSpeedup:
    def test_microbatched_2x_sequential_at_concurrency_32(self):
        """The tentpole acceptance: closed-loop micro-batched throughput at
        concurrency 32 ≥ 2× per-request sequential, with queue-depth and
        p99-latency metrics exported. Shape mirrors bench.py's serve leg:
        enough messages that the shared group work (witness load, header
        decode, exec-order reconstruction) dominates per-proof replay."""
        n_events = 768
        world = build_chain(
            [ContractFixture(actor_id=ACTOR, storage={SLOT: (42).to_bytes(2, "big")})],
            [
                [EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET,
                              data=i.to_bytes(32, "big"))]
                for i in range(n_events)
            ],
        )
        full = generate_proof_bundle(
            world.store, world.parent, world.child, [],
            [EventProofSpec(event_signature=SIG, topic_1=SUBNET,
                            actor_id_filter=ACTOR)],
        )
        n_requests = 96
        reqs = [
            UnifiedProofBundle(
                storage_proofs=[],
                event_proofs=[full.event_proofs[i % n_events]],
                blocks=full.blocks,
            )
            for i in range(n_requests)
        ]

        failures = []

        def closed_loop(svc):
            it = iter(range(n_requests))
            lock = threading.Lock()

            def client():
                while True:
                    with lock:
                        i = next(it, None)
                    if i is None:
                        return
                    if not svc.verify(reqs[i]).all_valid():
                        failures.append(i)

            threads = [threading.Thread(target=client) for _ in range(32)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.perf_counter() - t0

        # warm both paths (extension load, thread-pool spin-up, allocator),
        # then best-of-2 each side so one scheduler hiccup can't flip the
        # verdict — mirrors bench.py's warm/best-of-N e2e policy
        sequential_verify_baseline(reqs[:4])
        t_seq = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            seq = sequential_verify_baseline(reqs)
            t_seq = min(t_seq, time.perf_counter() - t0)
        assert all(r.all_valid() for r in seq)

        svc = ProofService(config=ServiceConfig(
            max_batch=32, max_wait_ms=4.0, queue_capacity=1024, workers=2,
        ))
        closed_loop(svc)  # warm pass
        t_batched = min(closed_loop(svc), closed_loop(svc))
        snap = svc.metrics_snapshot()
        svc.drain()

        assert not failures
        speedup = t_seq / t_batched
        assert speedup >= 2.0, (
            f"micro-batched {n_requests / t_batched:.0f} req/s is only "
            f"{speedup:.2f}x the sequential {n_requests / t_seq:.0f} req/s"
        )
        # the acceptance metrics are exported
        assert "serve.queue_depth.verify" in snap["gauges"]
        assert "p99" in snap["histograms"]["serve.latency_ms.verify"]
        assert snap["histograms"]["serve.batch_size.verify"]["mean"] > 1.0


class TestGenerate:
    def test_generate_responses_match_solo_generation(self, world):
        from ipc_proofs_tpu.fixtures import build_range_world
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range
        from ipc_proofs_tpu.proofs.trust import TrustPolicy
        from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle

        bs, pairs, _ = build_range_world(6, receipts_per_pair=8,
                                         events_per_receipt=2, match_rate=0.2)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET)
        with ProofService(
            store=bs, spec=spec,
            config=ServiceConfig(max_batch=8, max_wait_ms=20.0, workers=2),
        ) as svc:
            results = [None] * len(pairs)

            def client(i):
                results[i] = svc.generate(TipsetPair(parent=pairs[i].parent,
                                                     child=pairs[i].child))

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(pairs))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        assert any(r.batch_size > 1 for r in results)
        for i, resp in enumerate(results):
            solo = generate_event_proofs_for_range(bs, [pairs[i]], spec)
            # claims are bit-identical to generating the pair alone
            assert (
                [p.to_json_obj() for p in resp.bundle.event_proofs]
                == [p.to_json_obj() for p in solo.event_proofs]
            )
            # the response bundle (own claims + batch-shared witness) is
            # independently verifiable
            result = verify_proof_bundle(resp.bundle, TrustPolicy.accept_all())
            assert result.all_valid()
            assert len(result.event_results) == len(solo.event_proofs)

    def test_generate_disabled_without_store(self):
        with ProofService() as svc:
            with pytest.raises(RuntimeError, match="generate path disabled"):
                svc.submit_generate(None)

    def test_job_dir_generate_reports_journal_ms(self, tmp_path):
        """With range_job_dir set, generate batches run through the
        write-ahead journal: Server-Timing grows journal_ms, the journal
        counter moves, and the proofs stay bit-identical to the plain
        driver."""
        from ipc_proofs_tpu.fixtures import build_range_world
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range

        bs, pairs, _ = build_range_world(4, receipts_per_pair=4,
                                         events_per_receipt=2, match_rate=0.5)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET)
        with ProofService(
            store=bs, spec=spec,
            config=ServiceConfig(max_batch=8, max_wait_ms=5.0, workers=1,
                                 range_job_dir=str(tmp_path)),
        ) as svc:
            resp = svc.generate(TipsetPair(parent=pairs[0].parent,
                                           child=pairs[0].child))
            assert resp.server_timing.get("journal_ms", 0) > 0
            assert svc.metrics.counter_value("jobs.chunk_journal_us") > 0

            # a different batch (multi-pair → pipelined driver) lands in its
            # own per-batch job dir rather than colliding with the first
            results = [None] * len(pairs)

            def client(i):
                results[i] = svc.generate(TipsetPair(parent=pairs[i].parent,
                                                     child=pairs[i].child))

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(pairs))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        for i, r in enumerate(results):
            solo = generate_event_proofs_for_range(bs, [pairs[i]], spec)
            assert (
                [p.to_json_obj() for p in r.bundle.event_proofs]
                == [p.to_json_obj() for p in solo.event_proofs]
            )


class TestFetchPlaneWiring:
    """The fetch-plane interposition in ProofService.__init__: an RPC-fed
    store gets a plane whose local tier IS the service's layered store, in
    both memory-cache and disk-tier (`store_dir`) modes, and landings
    deposit so warm repeats stay at zero RPC."""

    def _rpc_world(self):
        from ipc_proofs_tpu.fixtures import build_range_world
        from ipc_proofs_tpu.store.faults import LocalLotusSession
        from ipc_proofs_tpu.store.rpc import LotusClient, RpcBlockstore

        bs, pairs, _ = build_range_world(3, receipts_per_pair=4,
                                         events_per_receipt=2, match_rate=0.5)
        m = Metrics()
        session = LocalLotusSession(bs)
        store = RpcBlockstore(
            LotusClient("http://serve-plane", session=session, metrics=m),
            metrics=m,
        )
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET)
        return bs, pairs, spec, store, session

    def test_memory_mode_plane_local_is_cached_store(self):
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range

        bs, pairs, spec, store, session = self._rpc_world()
        with ProofService(
            store=store, spec=spec,
            config=ServiceConfig(max_batch=8, max_wait_ms=5.0, workers=1),
        ) as svc:
            assert svc.fetch_plane is not None
            assert isinstance(svc._store, CachedBlockstore)
            # the plane short-circuits through the SAME local tier the
            # walkers populate (CachedBlockstore exposes get_local/
            # has_local/put_local that never touch its inner store)
            assert svc.fetch_plane._local is svc._store
            pair = TipsetPair(parent=pairs[0].parent, child=pairs[0].child)
            resp = svc.generate(pair)
            solo = generate_event_proofs_for_range(bs, [pairs[0]], spec)
            assert (
                [p.to_json_obj() for p in resp.bundle.event_proofs]
                == [p.to_json_obj() for p in solo.event_proofs]
            )
            # landings deposited: a warm repeat makes no new RPC calls
            cold_calls = session.calls
            assert cold_calls > 0
            resp2 = svc.generate(pair)
            assert session.calls == cold_calls
            assert (
                [p.to_json_obj() for p in resp2.bundle.event_proofs]
                == [p.to_json_obj() for p in resp.bundle.event_proofs]
            )

    def test_disk_mode_plane_local_is_tiered_store(self, tmp_path):
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range
        from ipc_proofs_tpu.storex import TieredBlockstore

        bs, pairs, spec, store, session = self._rpc_world()
        with ProofService(
            store=store, spec=spec,
            config=ServiceConfig(max_batch=8, max_wait_ms=5.0, workers=1,
                                 store_dir=str(tmp_path)),
        ) as svc:
            assert svc.fetch_plane is not None
            assert isinstance(svc._store, TieredBlockstore)
            assert svc.fetch_plane._local is svc._store
            resp = svc.generate(TipsetPair(parent=pairs[0].parent,
                                           child=pairs[0].child))
            solo = generate_event_proofs_for_range(bs, [pairs[0]], spec)
            assert (
                [p.to_json_obj() for p in resp.bundle.event_proofs]
                == [p.to_json_obj() for p in solo.event_proofs]
            )
            # fetched blocks persisted through put_local into the disk tier
            assert svc._disk_store.stats()["entries"] > 0

    def test_batch_rpc_false_keeps_direct_path(self):
        bs, pairs, spec, store, _ = self._rpc_world()
        with ProofService(
            store=store, spec=spec,
            config=ServiceConfig(max_batch=8, max_wait_ms=5.0, workers=1,
                                 batch_rpc=False),
        ) as svc:
            assert svc.fetch_plane is None
            resp = svc.generate(TipsetPair(parent=pairs[0].parent,
                                           child=pairs[0].child))
            assert resp.bundle.event_proofs


class TestHTTP:
    @pytest.fixture()
    def server(self, world, full_bundle):
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET,
                              actor_id_filter=ACTOR)
        svc = ProofService(
            store=world.store, spec=spec,
            config=ServiceConfig(max_batch=8, max_wait_ms=5.0, workers=2),
        )
        pair = TipsetPair(parent=world.parent, child=world.child)
        httpd = ProofHTTPServer(svc, pairs=[pair]).start()
        yield httpd
        httpd.shutdown(timeout=30)

    def _post(self, server, path, obj):
        conn = HTTPConnection("127.0.0.1", server.port, timeout=30)
        conn.request("POST", path, json.dumps(obj),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), json.loads(resp.read())

    def _get(self, server, path):
        conn = HTTPConnection("127.0.0.1", server.port, timeout=30)
        conn.request("GET", path, None, {})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())

    def test_verify_roundtrip(self, server, full_bundle):
        req = UnifiedProofBundle(
            storage_proofs=[], event_proofs=[full_bundle.event_proofs[0]],
            blocks=full_bundle.blocks,
        )
        status, _, out = self._post(server, "/v1/verify",
                                    {"bundle": req.to_json_obj()})
        assert status == 200
        assert out["all_valid"] is True
        assert out["event_results"] == [True]

    def test_generate_roundtrip(self, server, full_bundle):
        status, _, out = self._post(server, "/v1/generate", {"pair_index": 0})
        assert status == 200
        assert out["n_event_proofs"] == len(full_bundle.event_proofs)
        got = UnifiedProofBundle.from_json_obj(out["bundle"])
        assert (
            [p.to_json_obj() for p in got.event_proofs]
            == [p.to_json_obj() for p in full_bundle.event_proofs]
        )

    def test_streamed_timing_gains_stream_ms_and_still_sums_to_wall(
        self, server, full_bundle
    ):
        from ipc_proofs_tpu.witness.stream import decode_bundle_stream

        t0 = time.monotonic()
        conn = HTTPConnection("127.0.0.1", server.port, timeout=30)
        conn.request(
            "POST", "/v1/generate",
            json.dumps({"pair_index": 0, "stream": True}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        raw = resp.read()
        wall_ms = (time.monotonic() - t0) * 1000.0
        conn.close()
        assert resp.status == 200
        out = decode_bundle_stream(raw)
        timing = out["server_timing"]
        # the streamed transport adds its own accounted stage…
        assert set(timing) >= {"queue_ms", "batch_wait_ms",
                               "generate_ms", "stream_ms"}
        assert all(v >= 0 for v in timing.values())
        # …and the stages still cover admission→completion, which the
        # client-observed wall strictly contains (same pin as test_obs)
        assert sum(timing.values()) <= wall_ms
        assert out["n_event_proofs"] == len(full_bundle.event_proofs)

    def test_metrics_and_healthz(self, server, full_bundle):
        req = UnifiedProofBundle(
            storage_proofs=[], event_proofs=[full_bundle.event_proofs[0]],
            blocks=full_bundle.blocks,
        )
        self._post(server, "/v1/verify", {"bundle": req.to_json_obj()})
        status, snap = self._get(server, "/metrics")
        assert status == 200
        assert "serve.queue_depth.verify" in snap["gauges"]
        assert "serve.latency_ms.verify" in snap["histograms"]
        assert "block_cache" in snap
        status, health = self._get(server, "/healthz")
        assert (status, health["status"]) == (200, "ok")

    def test_malformed_bundle_400(self, server):
        status, _, out = self._post(server, "/v1/verify",
                                    {"bundle": {"nonsense": 1}})
        assert status == 400
        assert "error" in out

    def test_bad_pair_index_400(self, server):
        for bad in (5, -1, "x"):
            status, _, _ = self._post(server, "/v1/generate", {"pair_index": bad})
            assert status == 400

    def test_unknown_path_404(self, server):
        assert self._get(server, "/nope")[0] == 404
        assert self._post(server, "/v1/nope", {})[0] == 404

    def test_draining_healthz_and_503(self, world, full_bundle):
        svc = ProofService(config=ServiceConfig(max_batch=4))
        httpd = ProofHTTPServer(svc).start()
        try:
            svc.drain()
            status, health = self._get(httpd, "/healthz")
            assert (status, health["status"]) == (503, "draining")
            req = UnifiedProofBundle(
                storage_proofs=[], event_proofs=[full_bundle.event_proofs[0]],
                blocks=full_bundle.blocks,
            )
            status, _, out = self._post(httpd, "/v1/verify",
                                        {"bundle": req.to_json_obj()})
            assert status == 503
        finally:
            httpd.shutdown(timeout=30)


class TestBlockCache:
    def test_lru_eviction_under_byte_budget(self):
        cache = BlockCache(max_bytes=100)
        from ipc_proofs_tpu.core.cid import CID

        c1, c2, c3 = (CID.hash_of(bytes([i])) for i in range(3))
        cache.put(c1, b"a" * 40)
        cache.put(c2, b"b" * 40)
        assert cache.get(c1) is not None  # touch: c2 is now LRU
        cache.put(c3, b"c" * 40)
        assert cache.get(c2) is None
        assert cache.get(c1) is not None and cache.get(c3) is not None
        assert cache.stats()["evictions"] == 1
        assert cache.stats()["bytes"] <= 100

    def test_ttl_expiry(self):
        clock = [0.0]
        cache = BlockCache(max_bytes=1000, ttl_s=5.0, clock=lambda: clock[0])
        from ipc_proofs_tpu.core.cid import CID

        cid = CID.hash_of(b"ttl")
        cache.put(cid, b"data")
        assert cache.get(cid) == b"data"
        clock[0] = 6.0
        assert cache.get(cid) is None
        assert cache.stats()["expirations"] == 1

    def test_oversized_block_never_cached(self):
        cache = BlockCache(max_bytes=10)
        from ipc_proofs_tpu.core.cid import CID

        cache.put(CID.hash_of(b"big"), b"x" * 100)
        assert len(cache) == 0

    def test_cached_blockstore_dispatch(self):
        from ipc_proofs_tpu.core.cid import CID

        inner = MemoryBlockstore()
        cid = CID.hash_of(b"blk")
        inner.put_keyed(cid, b"blk")
        cached = CachedBlockstore(inner, shared_cache=BlockCache(max_bytes=1000))
        assert cached.get(cid) == b"blk" and cached.misses == 1
        assert cached.get(cid) == b"blk" and cached.hits == 1
        assert cached.has(cid)
        assert cached.cache_stats() == (1, 3)

    def test_service_cache_stays_bounded(self, world, full_bundle):
        """A long-lived service's shared cache never exceeds its budget."""
        config = ServiceConfig(max_batch=4, max_wait_ms=5.0,
                               cache_max_bytes=4096)
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET,
                              actor_id_filter=ACTOR)
        with ProofService(store=world.store, spec=spec, config=config) as svc:
            pair = TipsetPair(parent=world.parent, child=world.child)
            for _ in range(3):
                assert svc.generate(pair).n_event_proofs == len(
                    full_bundle.event_proofs
                )
            stats = svc.metrics_snapshot()["block_cache"]
        assert stats["bytes"] <= 4096


class TestHistogram:
    def test_percentiles_nearest_rank(self):
        h = Histogram()
        for v in range(1, 101):
            h.observe(float(v))
        snap = h.snapshot()
        assert snap["count"] == 100
        assert snap["p50"] == 50.0
        assert snap["p99"] == 99.0
        assert snap["mean"] == pytest.approx(50.5)

    def test_ring_buffer_bounds_memory(self):
        h = Histogram(maxlen=10)
        for v in range(1000):
            h.observe(float(v))
        assert h.count == 1000
        assert len(h._ring) == 10
        # window holds only the most recent 10 observations
        assert h.percentiles((0.5,))["p50"] >= 990.0

    def test_empty_histogram(self):
        h = Histogram()
        assert h.percentiles() == {}
        assert h.snapshot() == {"count": 0, "mean": 0.0}
