"""Independent byte-compatibility anchors.

Round-trip tests (encoder ↔ decoder of this repo) cannot catch a
*systematic* divergence from the real Filecoin wire formats — both sides
would share the bug. Every vector in this file is therefore derived
INDEPENDENTLY of the code under test:

- **published digests**: Keccak-256 / SHA-256 / BLAKE2b-256 values published
  in specs and ecosystem test suites (cited inline), plus the canonical
  empty-raw-sha256 IPFS CID;
- **hashlib**: Python's independent BLAKE2b/SHA-256 implementations anchor
  every CID in this file (never this repo's C/JAX/Pallas kernels);
- **hand-derived bytes**: raw CBOR assembled byte-by-byte in this file from
  RFC 8949 and the published DAG-CBOR / go-amt-ipld / go-hamt-ipld /
  fvm_shared wire formats — never produced by calling the encoder under
  test.

What still cannot be anchored in this sandbox (zero network egress): a raw
block header + CID fetched from the live chain, and the go-hamt-ipld /
go-amt-ipld fixture root CIDs (not reproducible from memory with
confidence). The structures those would cover are pinned here instead via
hand-derived node encodings at every layer (empty + populated, v0 + v3).
"""

import hashlib

import pytest

from ipc_proofs_tpu.core.bigint import bigint_from_bytes, bigint_to_bytes
from ipc_proofs_tpu.core.cid import BLAKE2B_256, CID, DAG_CBOR, RAW, SHA2_256
from ipc_proofs_tpu.core.dagcbor import decode_py, encode
from ipc_proofs_tpu.core.hashes import blake2b_256, keccak256
from ipc_proofs_tpu.core.varint import decode_uvarint, encode_uvarint
from ipc_proofs_tpu.ipld.amt import AMT, amt_build, amt_build_v0
from ipc_proofs_tpu.ipld.hamt import HAMT, hamt_build
from ipc_proofs_tpu.state.address import Address
from ipc_proofs_tpu.state.events import Receipt
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore


def b2b(data: bytes) -> bytes:
    """Independent blake2b-256 (hashlib, not this repo's kernels)."""
    return hashlib.blake2b(data, digest_size=32).digest()


def cid_of(block: bytes, codec: int = DAG_CBOR) -> CID:
    """Independently-computed Filecoin chain CID for raw block bytes."""
    return CID(1, codec, BLAKE2B_256, b2b(block))


class TestPublishedDigests:
    """Digest values published outside this repo."""

    def test_keccak256(self):
        # Keccak team test vectors (pre-NIST padding), as used by Ethereum
        assert keccak256(b"").hex() == (
            "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
        )
        assert keccak256(b"abc").hex() == (
            "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
        )

    def test_keccak256_erc20_event_topics(self):
        # The universally-published ERC-20 log topic0 values — any Ethereum
        # explorer shows these for every Transfer/Approval event.
        assert keccak256(b"Transfer(address,address,uint256)").hex() == (
            "ddf252ad1be2c89b69c2b068fc378daa952ba7f163c4a11628f55a4df523b3ef"
        )
        assert keccak256(b"Approval(address,address,uint256)").hex() == (
            "8c5be1e5ebec7d5bd14f71427d1e84f3dd0314c0f7b2291e5b200ac8c7c3b925"
        )

    def test_blake2b_256(self):
        # Published BLAKE2b-256 vectors (RFC 7693 parameterization); also
        # cross-checked against hashlib, an implementation this repo doesn't own.
        assert blake2b_256(b"").hex() == (
            "0e5751c026e543b2e8ab2eb06099daa1d1e5df47778f7787faab45cdf12fe3a8"
        )
        assert blake2b_256(b"abc").hex() == (
            "bddd813c634239723171ef3fee98579b94964e3bb1cb3e427262c8c068d52319"
        )

    def test_blake2b_256_matches_hashlib_on_varied_lengths(self):
        import random

        rng = random.Random(0xF17)
        for n in (0, 1, 31, 32, 33, 63, 64, 65, 127, 128, 129, 1000, 4096):
            data = bytes(rng.getrandbits(8) for _ in range(n))
            assert blake2b_256(data) == b2b(data), f"len={n}"

    def test_famous_empty_raw_cid(self):
        # The canonical CIDv1(raw, sha2-256) of zero bytes — appears across
        # IPFS documentation and test suites.
        assert str(CID.hash_of(b"", codec=RAW, mh_code=SHA2_256)) == (
            "bafkreihdwdcefgh4dqkjv67uzcmw7ojee6xedzdetojuzjevtenxquvyku"
        )

    def test_sha256_nist(self):
        # FIPS 180 "abc" vector through the CID path
        assert CID.hash_of(b"abc", codec=RAW, mh_code=SHA2_256).digest.hex() == (
            "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"
        )


class TestVarint:
    """Unsigned LEB128 (multiformats uvarint), hand-derived."""

    @pytest.mark.parametrize(
        "value,expected",
        [
            (0, "00"),
            (1, "01"),
            (127, "7f"),
            (128, "8001"),
            (255, "ff01"),
            (300, "ac02"),
            (16384, "808001"),
            (0x71, "71"),  # dag-cbor codec
            (0x55, "55"),  # raw codec
            (0xB220, "a0e402"),  # blake2b-256 multihash code
        ],
    )
    def test_encode(self, value, expected):
        assert encode_uvarint(value).hex() == expected
        decoded, off = decode_uvarint(bytes.fromhex(expected))
        assert decoded == value and off == len(expected) // 2


class TestDagCborRfc8949:
    """RFC 8949 appendix-A style vectors, hand-encoded (deterministic form)."""

    @pytest.mark.parametrize(
        "obj,expected",
        [
            (0, "00"),
            (1, "01"),
            (10, "0a"),
            (23, "17"),
            (24, "1818"),
            (25, "1819"),
            (100, "1864"),
            (255, "18ff"),
            (256, "190100"),
            (1000, "1903e8"),
            (65535, "19ffff"),
            (65536, "1a00010000"),
            (1000000, "1a000f4240"),
            (4294967295, "1affffffff"),
            (4294967296, "1b0000000100000000"),
            (18446744073709551615, "1bffffffffffffffff"),
            (-1, "20"),
            (-10, "29"),
            (-24, "37"),
            (-25, "3818"),
            (-100, "3863"),
            (-1000, "3903e7"),
            (b"", "40"),
            (b"\x01\x02\x03\x04", "4401020304"),
            ("", "60"),
            ("a", "6161"),
            ("IETF", "6449455446"),
            ("ü", "62c3bc"),
            ([], "80"),
            ([1, 2, 3], "83010203"),
            ([1, [2, 3], [4, 5]], "8301820203820405"),
            (list(range(1, 26)),
             "98190102030405060708090a0b0c0d0e0f101112131415161718181819"),
            ({}, "a0"),
            ({"a": 1, "b": [2, 3]}, "a26161016162820203"),
            (False, "f4"),
            (True, "f5"),
            (None, "f6"),
            # DAG-CBOR floats are always 64-bit
            (1.1, "fb3ff199999999999a"),
            (1.0e300, "fb7e37e43c8800759c"),
            (-4.1, "fbc010666666666666"),
        ],
    )
    def test_scalar_vectors(self, obj, expected):
        assert encode(obj).hex() == expected
        decoded = decode_py(bytes.fromhex(expected))
        assert decoded == obj and type(decoded) is type(obj)

    def test_canonical_map_ordering_length_first(self):
        # RFC 7049 §3.9 canonical order (length-first, then bytewise) — the
        # ordering DAG-CBOR inherited and go-ipld-cbor ships. "b" < "aa".
        assert encode({"aa": 1, "b": 2}).hex() == "a2616202626161 01".replace(" ", "")
        assert encode({"b": 2, "aa": 1}).hex() == "a2616202626161 01".replace(" ", "")

    def test_cid_tag_42(self):
        # tag(42) wrapping bytes(0x00 ++ cid): D8 2A head, 58 25 byte head
        # (37 = 1 identity prefix + 36 cid bytes), hand-assembled.
        cid = CID.hash_of(b"", codec=RAW, mh_code=SHA2_256)
        cid_bytes = bytes.fromhex("015512 20".replace(" ", "")) + hashlib.sha256(b"").digest()
        assert cid.to_bytes() == cid_bytes
        expected = bytes.fromhex("d82a5825") + b"\x00" + cid_bytes
        assert encode(cid) == expected
        assert decode_py(expected) == cid

    def test_filecoin_chain_cid_shape(self):
        # CIDv1 dag-cbor blake2b-256: 01 71 a0e402 20 ++ digest (hand bytes)
        block = encode([1, 2, 3])
        cid = cid_of(block)
        assert cid.to_bytes() == bytes.fromhex("0171a0e40220") + b2b(block)


class TestBigIntVectors:
    """fvm_shared BigInt byte form: empty=0, else sign byte ++ BE magnitude."""

    @pytest.mark.parametrize(
        "value,expected",
        [
            (0, ""),
            (1, "0001"),
            (255, "00ff"),
            (256, "000100"),
            (10**18, "000de0b6b3a7640000"),  # 1 FIL in attoFIL
            (-1, "0101"),
            (-255, "01ff"),
        ],
    )
    def test_vectors(self, value, expected):
        assert bigint_to_bytes(value).hex() == expected
        assert bigint_from_bytes(bytes.fromhex(expected)) == value


class TestAddressVectors:
    """fvm_shared Address byte form: protocol byte ++ payload (uvarint for ID)."""

    @pytest.mark.parametrize(
        "actor_id,expected",
        [
            (0, "0000"),
            (1, "0001"),
            (100, "0064"),
            (1024, "008008"),
            (18446744073709551615, "00ffffffffffffffffff01"),  # max u64
        ],
    )
    def test_id_address_bytes(self, actor_id, expected):
        assert Address.new_id(actor_id).to_bytes().hex() == expected
        assert Address.from_bytes(bytes.fromhex(expected)).id() == actor_id


class TestAmtNodeLayout:
    """go-amt-ipld wire format, hand-assembled.

    v0 root = [height, count, node]; v3 root = [bitWidth, height, count, node].
    node = [bmap(bytes, LSB-first bits, width/8 bytes), [links], [values]].
    """

    def test_empty_v0(self):
        store = MemoryBlockstore()
        root = amt_build_v0(store, [])
        # [0, 0, [h'00', [], []]] — width 8 ⇒ 1 bitmap byte
        expected = bytes.fromhex("8300008341008080")
        assert store.get(root) == expected
        assert root == cid_of(expected)

    def test_empty_v3_bitwidth5(self):
        store = MemoryBlockstore()
        root = amt_build(store, [], bit_width=5, version=3)
        # [5, 0, 0, [h'00000000', [], []]] — width 32 ⇒ 4 bitmap bytes
        expected = bytes.fromhex("840500008344000000008080")
        assert store.get(root) == expected
        assert root == cid_of(expected)

    def test_two_values_v3(self):
        store = MemoryBlockstore()
        root = amt_build(store, [b"a", b"b"], bit_width=5, version=3)
        # height 0, count 2, bitmap bits {0,1} ⇒ 03 00 00 00 (LSB-first)
        expected = bytes.fromhex("84050002834403000000") + bytes.fromhex("8082416141 62".replace(" ", ""))
        assert store.get(root) == expected
        assert root == cid_of(expected)

    def test_sparse_two_level_v0(self):
        # Index 9 with bit_width 3: height 1; root node links slot 1
        # (9 >> 3 = 1), leaf holds slot 1 (9 & 7 = 1).
        store = MemoryBlockstore()
        root = amt_build_v0(store, {9: 7})
        leaf = bytes.fromhex("8341028081 07".replace(" ", ""))  # [h'02', [], [7]]
        leaf_cid = cid_of(leaf)
        # root node: [h'02', [leaf_cid], []]
        root_node = (
            bytes.fromhex("834102 81".replace(" ", ""))
            + bytes.fromhex("d82a5827") + b"\x00" + leaf_cid.to_bytes()
            + bytes.fromhex("80")
        )
        expected_root = bytes.fromhex("830101") + root_node  # [1, 1, node]
        assert store.get(root) == expected_root
        assert root == cid_of(expected_root)
        # and the reader agrees with the hand layout
        assert AMT.load(store, root).get(9) == 7

    def test_amt_cid_link_head_is_58_27(self):
        # every AMT link encodes as d8 2a 58 27 00 ++ 36 cid bytes: the byte
        # string is 39 = 0x27 long (1 + 36), needing the one-byte length head
        store = MemoryBlockstore()
        inner = amt_build_v0(store, {100: 1})
        raw = store.get(inner)
        assert bytes.fromhex("d82a582700") in raw


class TestHamtNodeLayout:
    """go-hamt-ipld / fvm_ipld_hamt wire format, hand-assembled.

    node = [bitfield (minimal big-endian bytes, b"" for 0), [pointers]];
    pointer = tag-42 link | bucket [[key, value], ...]; key hash = sha256,
    bits MSB-first, 5 at a time.
    """

    def test_empty(self):
        store = MemoryBlockstore()
        root = hamt_build(store, {})
        expected = bytes.fromhex("824080")  # [h'', []]
        assert store.get(root) == expected
        assert root == cid_of(expected)

    def test_single_entry(self):
        store = MemoryBlockstore()
        key = b"k"
        root = hamt_build(store, {key: 42})
        # slot = top 5 bits of sha256("k") — computed via hashlib, not the
        # repo's _hash_bits
        slot = hashlib.sha256(key).digest()[0] >> 3
        bitfield = 1 << slot
        bf_bytes = bitfield.to_bytes((bitfield.bit_length() + 7) // 8, "big")
        expected = (
            bytes([0x82])
            + bytes([0x40 + len(bf_bytes)]) + bf_bytes
            + bytes.fromhex("81")  # one pointer
            + bytes.fromhex("81")  # bucket of one KV
            + bytes.fromhex("82416b182a")  # [h'6b', 42]
        )
        assert store.get(root) == expected
        assert root == cid_of(expected)
        assert HAMT.load(store, root).get(key) == 42

    def test_bucket_order_is_key_bytes(self):
        # two keys that share a top-5-bits slot must sit in one bucket sorted
        # by key bytes; search for such a pair deterministically
        import itertools

        pairs = {}
        collision = None
        for i in itertools.count():
            k = b"g-%d" % i
            slot = hashlib.sha256(k).digest()[0] >> 3
            if slot in pairs:
                collision = (pairs[slot], k)
                break
            pairs[slot] = k
        a, b = sorted(collision)
        store = MemoryBlockstore()
        root = hamt_build(store, {b: 2, a: 1})
        node = decode_py(store.get(root))
        bucket = next(p for p in node[1] if isinstance(p, list))
        assert bucket == [[a, 1], [b, 2]]


class TestFilecoinTupleLayouts:
    """fvm_shared struct tuple layouts, hand-assembled CBOR."""

    def test_receipt_tuple(self):
        store = MemoryBlockstore()
        events_root = cid_of(encode([5, 0, 0, [b"\x00" * 4, [], []]]))
        r = Receipt(exit_code=0, return_data=b"", gas_used=100, events_root=events_root)
        expected = (
            bytes.fromhex("8400401864")  # [0, h'', 100, …
            + bytes.fromhex("d82a5827") + b"\x00" + events_root.to_bytes()
        )
        assert encode(r.to_cbor()) == expected
        back = Receipt.from_cbor(decode_py(expected))
        assert back == r

    def test_actor_state_tuple(self):
        from ipc_proofs_tpu.state.actors import ActorState

        code = cid_of(b"fil/evm-code-block")
        head = cid_of(b"evm-state-block")
        actor = ActorState(code=code, state=head, call_seq_num=7, balance=255)
        link = bytes.fromhex("d82a5827")
        # v10+ 5-field layout: [code, head, call_seq, balance, delegated(null)]
        expected = (
            b"\x85"
            + link + b"\x00" + code.to_bytes()
            + link + b"\x00" + head.to_bytes()
            + b"\x07"
            + bytes.fromhex("4200ff")  # bigint bytes h'00ff'
            + b"\xf6"
        )
        assert encode(actor.to_tuple()) == expected

    def test_state_root_tuple(self):
        from ipc_proofs_tpu.state.actors import StateRoot

        actors = cid_of(encode([b"", []]))
        info = cid_of(encode("state-info"))
        sr = StateRoot(version=5, actors=actors, info=info)
        link = bytes.fromhex("d82a5827")
        expected = (
            b"\x83\x05"
            + link + b"\x00" + actors.to_bytes()
            + link + b"\x00" + info.to_bytes()
        )
        assert encode(sr.to_tuple()) == expected

    def test_stamped_event_tuple(self):
        """[emitter, [[flags, key, codec, value], …]] — the hottest decode
        on the event-scan path (reference `events/generator.rs:215-233`)."""
        from ipc_proofs_tpu.state.events import (
            ActorEvent,
            EventEntry,
            IPLD_RAW,
            StampedEvent,
        )

        t1 = bytes(range(32))
        stamped = StampedEvent(
            emitter=1001,
            event=ActorEvent(entries=[EventEntry(0, "t1", IPLD_RAW, t1)]),
        )
        expected = (
            b"\x82"  # [emitter, event]
            + bytes.fromhex("1903e9")  # 1001
            + b"\x81"  # one entry
            + b"\x84\x00"  # [flags=0,
            + bytes.fromhex("627431")  # "t1"
            + bytes.fromhex("1855")  # codec 0x55
            + bytes.fromhex("5820") + t1  # value bytes(32)
        )
        assert encode(stamped.to_cbor()) == expected
        assert StampedEvent.from_cbor(decode_py(expected)) == stamped

    def test_header_16_tuple_field_positions(self):
        """A minimal header, hand-assembled: parents at index 5, weight 6,
        height 7, state root 8, receipts 9, messages 10, timestamp 12,
        fork_signaling 14 (reference `common/decode.rs:101-118`)."""
        from ipc_proofs_tpu.state.header import BlockHeader, extract_parent_state_root

        p1 = cid_of(b"parent-block")
        state = cid_of(b"state-block")
        rcpts = cid_of(b"receipts-block")
        msgs = cid_of(b"txmeta-block")
        header = BlockHeader(
            parents=[p1],
            height=100,
            parent_state_root=state,
            parent_message_receipts=rcpts,
            messages=msgs,
            timestamp=1700003000,
            miner="f01000",
        )
        link = bytes.fromhex("d82a5827") + b"\x00"
        # assemble explicitly, field by field
        expected = b"".join(
            [
                b"\x90",
                bytes.fromhex("66") + b"f01000",  # 0 miner text(6)
                b"\xf6",  # 1 ticket
                b"\xf6",  # 2 election proof
                b"\x80",  # 3 beacon entries
                b"\x80",  # 4 winpost proofs
                b"\x81" + link + p1.to_bytes(),  # 5 parents
                b"\x40",  # 6 parent weight h''
                b"\x18\x64",  # 7 height 100
                link + state.to_bytes(),  # 8
                link + rcpts.to_bytes(),  # 9
                link + msgs.to_bytes(),  # 10
                b"\xf6",  # 11 bls aggregate
                bytes.fromhex("1a6553fcb8"),  # 12 timestamp 1700003000
                b"\xf6",  # 13 block sig
                b"\x00",  # 14 fork signaling
                b"\x40",  # 15 parent base fee h''
            ]
        )
        raw = header.encode()
        assert raw == expected
        assert header.cid() == cid_of(expected)
        assert str(extract_parent_state_root(raw)) == str(state)
