"""Mesh / sharded-pipeline tests on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from ipc_proofs_tpu.parallel.mesh import make_mesh  # noqa: E402
from ipc_proofs_tpu.parallel.pipeline import (  # noqa: E402
    match_pipeline,
    sharded_match_pipeline,
    synthetic_event_batch,
)
from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature  # noqa: E402

T0 = hash_event_signature("NewTopDownMessage(bytes32,uint256)")
T1 = ascii_to_bytes32("subnet-x")


def _batch(t=8, r=4, e=4, rate=0.25, seed=3):
    return synthetic_event_batch(t, r, e, T0, T1, emitter=1001, match_rate=rate, seed=seed)


class TestVirtualMesh:
    def test_eight_devices_available(self):
        assert len(jax.devices()) == 8

    def test_make_mesh_shapes(self):
        mesh = make_mesh(8, sp=2)
        assert mesh.shape == {"dp": 4, "sp": 2}
        mesh_dp = make_mesh(4, sp=1)
        assert mesh_dp.shape == {"dp": 4, "sp": 1}
        with pytest.raises(ValueError):
            make_mesh(8, sp=3)


class TestShardedPipeline:
    def test_matches_unsharded(self):
        batch = _batch()
        mesh = make_mesh(8, sp=2)
        jitted, shard_batch = sharded_match_pipeline(mesh)
        args = shard_batch(batch, T0, T1, 1001)
        hits_s, mask_s, count_s = jitted(*args)

        import jax.numpy as jnp

        from ipc_proofs_tpu.parallel.pipeline import make_specs_u32

        spec0, spec1 = make_specs_u32(T0, T1)
        hits, mask, count = match_pipeline(
            jnp.asarray(batch.topics),
            jnp.asarray(batch.n_topics),
            jnp.asarray(batch.emitters),
            jnp.asarray(batch.valid),
            jnp.asarray(spec0),
            jnp.asarray(spec1),
            jnp.int32(1001),
        )
        np.testing.assert_array_equal(np.asarray(hits_s), np.asarray(hits))
        np.testing.assert_array_equal(np.asarray(mask_s), np.asarray(mask))
        assert int(count_s) == int(count)
        # sanity: the synthetic batch has ~25% of 32 receipts matching
        assert int(count_s) > 0

    def test_actor_filter_respected(self):
        batch = _batch()
        mesh = make_mesh(8, sp=2)
        jitted, shard_batch = sharded_match_pipeline(mesh)
        _, _, count_all = jitted(*shard_batch(batch, T0, T1, None))
        _, _, count_none = jitted(*shard_batch(batch, T0, T1, 999_999))
        assert int(count_all) > 0
        assert int(count_none) == 0

    def test_matches_scalar_reference(self):
        # Cross-check against a pure-numpy reimplementation
        batch = _batch(t=4, r=4, e=2, rate=0.5, seed=11)
        mesh = make_mesh(4, sp=1)
        jitted, shard_batch = sharded_match_pipeline(mesh)
        _, mask_s, _ = jitted(*shard_batch(batch, T0, T1, 1001))

        from ipc_proofs_tpu.parallel.pipeline import make_specs_u32

        spec0, spec1 = make_specs_u32(T0, T1)
        expected = (
            batch.valid
            & (batch.n_topics >= 2)
            & (batch.topics[..., 0, :] == spec0).all(-1)
            & (batch.topics[..., 1, :] == spec1).all(-1)
            & (batch.emitters == 1001)
        )
        np.testing.assert_array_equal(np.asarray(mask_s), expected)


def test_measure_pass_seconds_slope():
    """Slope timing resolves a real per-pass cost and cancels constants."""
    import jax.numpy as jnp

    from ipc_proofs_tpu.utils.timing import measure_pass_seconds

    x = jnp.arange(4096, dtype=jnp.uint32)

    def body(i, v):
        acc = v ^ i.astype(jnp.uint32)
        return acc.sum(dtype=jnp.uint32).astype(jnp.int32)

    pt = measure_pass_seconds(body, (x,), k_small=2, k_large=42, repeats=2, max_k=202)
    assert pt.seconds > 0
    assert pt.k_large > pt.k_small
    assert pt.per_pass_ms == pt.seconds * 1e3


def test_sharded_range_pipeline_bit_identical():
    """The real range driver with a mesh-sharded match backend must emit a
    bit-identical bundle to the single-device backend (VERDICT r1 item 6)."""
    from ipc_proofs_tpu.backend.tpu import TpuBackend
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.parallel.mesh import make_mesh
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range

    mesh = make_mesh(8, sp=2)
    bs, pairs, n_matching = build_range_world(
        n_pairs=16, receipts_per_pair=4, events_per_receipt=4, match_rate=0.25
    )
    spec = EventProofSpec(
        event_signature="NewTopDownMessage(bytes32,uint256)",
        topic_1="calib-subnet-1",
        actor_id_filter=1001,
    )
    sharded = generate_event_proofs_for_range(
        bs, pairs, spec, match_backend=TpuBackend(mesh=mesh)
    )
    single = generate_event_proofs_for_range(bs, pairs, spec, match_backend=TpuBackend())
    scalar = generate_event_proofs_for_range(bs, pairs, spec, match_backend=None)
    assert sharded.to_json() == single.to_json() == scalar.to_json()
    assert len(sharded.event_proofs) == n_matching
