"""The native (C extension) CID type: interface parity with PurePythonCID.

Since round 5, ``ipc_proofs_tpu.core.cid.CID`` binds to the C-slot type
``ipc_dagcbor_ext.CID`` when the extension builds (the dataclass stays the
correctness reference as ``PurePythonCID``; the full suite runs against it
under ``IPC_PROOFS_NO_NATIVE=1``). This file pins the contract both
implementations must share: constructors, classmethods, comparisons, hash,
string/bytes codecs (strict-canonical, reference ``cid``/``multibase``
crate semantics — SURVEY §2b), pickling, and immutability.
"""

import pickle
import random

import pytest

from ipc_proofs_tpu.core.cid import (
    BLAKE2B_256,
    CID,
    DAG_CBOR,
    IDENTITY,
    PurePythonCID,
    RAW,
    SHA2_256,
)

native_active = CID is not PurePythonCID

pytestmark = pytest.mark.skipif(
    not native_active, reason="native CID type not bound (extension unavailable)"
)


class TestConstructionParity:
    def test_binding_active(self):
        assert CID.__name__ == "CID"
        assert type(CID.hash_of(b"x")) is CID

    def test_positional_and_keyword_construction(self):
        a = CID(1, DAG_CBOR, BLAKE2B_256, b"\x01" * 32)
        b = CID(version=1, codec=DAG_CBOR, mh_code=BLAKE2B_256, digest=b"\x01" * 32)
        p = PurePythonCID(1, DAG_CBOR, BLAKE2B_256, b"\x01" * 32)
        assert a == b == p
        assert a.to_bytes() == p.to_bytes()
        assert str(a) == str(p)

    def test_make_alias(self):
        m = CID._make(1, RAW, SHA2_256, b"\x02" * 32)
        assert m == CID(1, RAW, SHA2_256, b"\x02" * 32)

    def test_field_values(self):
        c = CID.hash_of(b"hello")
        assert (c.version, c.codec, c.mh_code) == (1, DAG_CBOR, BLAKE2B_256)
        assert c.digest == PurePythonCID.hash_of(b"hello").digest

    def test_negative_field_rejected(self):
        with pytest.raises(ValueError):
            CID(-1, DAG_CBOR, BLAKE2B_256, b"\x00" * 32)

    def test_non_int_field_rejected(self):
        with pytest.raises(TypeError):
            CID("1", DAG_CBOR, BLAKE2B_256, b"\x00" * 32)

    def test_hash_of_variants(self):
        for codec, mh in [
            (DAG_CBOR, BLAKE2B_256),
            (RAW, BLAKE2B_256),
            (DAG_CBOR, SHA2_256),
            (RAW, IDENTITY),
        ]:
            n = CID.hash_of(b"payload", codec, mh)
            p = PurePythonCID.hash_of(b"payload", codec, mh)
            assert n.to_bytes() == p.to_bytes(), (codec, mh)

    def test_hash_of_unsupported_mh_rejected(self):
        with pytest.raises(ValueError, match="unsupported multihash code"):
            CID.hash_of(b"x", mh_code=0x99)
        with pytest.raises(ValueError, match="unsupported multihash code"):
            PurePythonCID.hash_of(b"x", mh_code=0x99)

    def test_parse_coercions(self):
        c = CID.hash_of(b"p")
        assert CID.parse(c) is c
        assert CID.parse(c.to_bytes()) == c
        assert CID.parse(str(c)) == c

    def test_parse_accepts_either_implementation(self):
        """Both parse() implementations pass a CID of EITHER type through
        unchanged (code-review finding: the rebind used to make each
        reject the other's instances)."""
        n = CID.hash_of(b"cross")
        p = PurePythonCID.hash_of(b"cross")
        assert CID.parse(p) is p
        assert PurePythonCID.parse(n) is n
        assert PurePythonCID.parse(p) is p

    def test_encode_accepts_either_implementation(self):
        from ipc_proofs_tpu.core import dagcbor

        n = CID.hash_of(b"enc")
        p = PurePythonCID.hash_of(b"enc")
        assert dagcbor.encode({"c": p}) == dagcbor.encode({"c": n})

    def test_field_overflow_rejected(self):
        """>128-bit fields must raise, never silently truncate (the 3.13
        PyLong_AsNativeBytes return-size contract)."""
        with pytest.raises((OverflowError, ValueError)):
            CID(2**128 + 1, DAG_CBOR, BLAKE2B_256, b"\x00" * 32)


class TestCodecParity:
    def test_from_bytes_error_messages(self):
        cases = [
            (b"", "truncated uvarint"),
            (b"\x00\x01", "unsupported CID version 0"),
            (b"\x01\x71", "truncated uvarint"),
            (b"\x01\x71\x12\x20\xaa", "truncated CID multihash digest"),
            (CID.hash_of(b"x").to_bytes() + b"\x00", "trailing bytes after CID"),
            (b"\x80" * 10 + b"\x01", "uvarint too long"),
        ]
        for raw, msg in cases:
            with pytest.raises(ValueError, match=msg):
                CID.from_bytes(raw)
            with pytest.raises(ValueError, match=msg):
                PurePythonCID.from_bytes(raw)

    def test_nonminimal_varint_bytes_rejected_both_impls(self):
        c = CID.hash_of(b"payload")
        noncanon = b"\x01\xf1\x00\xa0\xe4\x02\x20" + c.digest
        with pytest.raises(ValueError, match="non-canonical"):
            CID.from_bytes(noncanon)
        with pytest.raises(ValueError, match="non-canonical"):
            PurePythonCID.from_bytes(noncanon)

    def test_big_identity_cid_roundtrip(self):
        big = CID(1, DAG_CBOR, IDENTITY, bytes(range(256)) + b"x" * 100)
        bigp = PurePythonCID(1, DAG_CBOR, IDENTITY, bytes(range(256)) + b"x" * 100)
        assert str(big) == str(bigp)
        assert CID.from_string(str(big)) == big
        assert CID.from_bytes(big.to_bytes()) == big

    def test_from_string_surfaces_detailed_byte_errors(self):
        """from_string reports the specific from_bytes failure (version /
        truncation / trailing), not the tolerant boundary's generic
        message — message parity with PurePythonCID.from_string."""
        from ipc_proofs_tpu.core.cid import _b32_encode_lower

        c = CID.hash_of(b"payload")
        v2 = b"\x02" + c.to_bytes()[1:]
        s = "b" + _b32_encode_lower(v2)
        with pytest.raises(ValueError, match="unsupported CID version 2"):
            CID.from_string(s)
        with pytest.raises(ValueError, match="unsupported CID version 2"):
            PurePythonCID.from_string(s)

    def test_string_rejections_match(self):
        c = str(CID.hash_of(b"q"))
        bad = ["", "z" + c[1:], "b", c[:-1], c[:-1] + "!", c.upper(), "b0" + c[2:]]
        for s in bad:
            with pytest.raises(ValueError):
                CID.from_string(s)
            with pytest.raises(ValueError):
                PurePythonCID.from_string(s)

    def test_memoization_returns_same_objects(self):
        c = CID.hash_of(b"memo")
        assert c.to_bytes() is c.to_bytes()
        assert str(c) == str(c)
        assert hash(c) == hash(c)


class TestSemanticsParity:
    def test_mixed_equality_and_hash(self):
        n = CID.hash_of(b"same")
        p = PurePythonCID.hash_of(b"same")
        assert n == p and p == n
        assert not (n != p) and not (p != n)
        assert hash(n) == hash(p)
        assert n in {p} and p in {n}
        assert {n: 1}[p] == 1

    def test_inequality_against_non_cid(self):
        c = CID.hash_of(b"x")
        assert c != 42
        assert c != "bafy"
        assert c != b"\x01"
        assert not (c == object())

    def test_ordering_matches_pure(self):
        rng = random.Random(7)
        data = [bytes(rng.randrange(256) for _ in range(8)) for _ in range(64)]
        ns = sorted(CID.hash_of(d) for d in data)
        ps = sorted(PurePythonCID.hash_of(d) for d in data)
        assert [str(a) for a in ns] == [str(a) for a in ps]
        for a, b in zip(ns, ns[1:]):
            assert a < b or a == b
            assert a <= b and b >= a

    def test_repr(self):
        c = CID.hash_of(b"r")
        assert repr(c) == f"CID({c})"
        assert repr(c) == repr(PurePythonCID.hash_of(b"r"))

    def test_pickle_roundtrip(self):
        c = CID.hash_of(b"pickle")
        out = pickle.loads(pickle.dumps(c))
        assert out == c and str(out) == str(c)

    def test_immutable(self):
        c = CID.hash_of(b"frozen")
        with pytest.raises((AttributeError, TypeError)):
            c.digest = b"\x00"
        with pytest.raises((AttributeError, TypeError)):
            c.version = 2

    def test_decoder_link_type(self):
        """Tag-42 links built by the C decoder ARE the module CID type."""
        from ipc_proofs_tpu.backend.native import load_dagcbor_ext
        from ipc_proofs_tpu.core import dagcbor

        ext = load_dagcbor_ext()
        assert ext is not None
        c = CID.hash_of(b"link")
        enc = dagcbor.encode({"l": c, "xs": [c]})
        for decoded in (ext.decode(enc), dagcbor.decode(enc)):
            assert type(decoded["l"]) is CID
            assert decoded["l"] == c and decoded["xs"] == [c]
