"""Direct accept/reject matrix for the shared strict-JSON accessors.

utils/jsonstrict.py guards both untrusted-input boundaries (proof bundles,
F3 certificates); the boundary fuzzes cover it transitively, but each
accessor's exact acceptance deserves direct pinning — especially the
canonical-base64 rule, which exists because even validate=True accepts
non-zero trailing padding bits.
"""

import pytest

from ipc_proofs_tpu.utils.jsonstrict import strict_fields

_S = strict_fields("boundary")


class TestAccessors:
    def test_as_map(self):
        assert _S.as_map({"a": 1}, "x") == {"a": 1}
        for bad in ([], "s", 1, None, True):
            with pytest.raises(ValueError, match="boundary: x must be a JSON"):
                _S.as_map(bad, "x")

    def test_get(self):
        assert _S.get({"k": 0}, "k", "x") == 0
        with pytest.raises(ValueError, match="missing field 'k'"):
            _S.get({}, "k", "x")

    def test_as_int_excludes_bool(self):
        assert _S.as_int(-5, "x") == -5
        assert _S.as_int(2**70, "x") == 2**70
        for bad in (True, False, 1.0, "1", None, []):
            with pytest.raises(ValueError, match="must be an integer"):
                _S.as_int(bad, "x")

    def test_as_str(self):
        assert _S.as_str("", "x") == ""
        for bad in (b"s", 1, None, ["s"]):
            with pytest.raises(ValueError, match="must be a string"):
                _S.as_str(bad, "x")

    def test_as_list_and_str_list(self):
        assert _S.as_list([1], "x") == [1]
        with pytest.raises(ValueError, match="must be a list"):
            _S.as_list((1,), "x")
        assert _S.as_str_list(["a"], "x") == ["a"]
        for bad in ([1], ["a", None], "abc"):
            with pytest.raises(ValueError, match="list of strings"):
                _S.as_str_list(bad, "x")

    def test_as_bytes_forms(self):
        assert _S.as_bytes(b"\x01", "x") == b"\x01"
        assert _S.as_bytes(bytearray(b"\x02"), "x") == b"\x02"
        assert _S.as_bytes([0, 255], "x") == b"\x00\xff"
        assert _S.as_bytes("AA==", "x") == b"\x00"
        for bad in ([256], [-1], [True], 1, None, {"b": 1}):
            with pytest.raises(ValueError, match="must be bytes"):
                _S.as_bytes(bad, "x")

    def test_b64_canonicality(self):
        # garbage characters: lax decode would silently DISCARD them
        with pytest.raises(ValueError, match="bad base64"):
            _S.b64_strict("A!A!E!==", "x")
        # non-zero trailing padding bits: validate=True alone accepts this
        with pytest.raises(ValueError, match="non-canonical base64"):
            _S.b64_strict("AB==", "x")
        # whitespace: discarded by lax decoding, rejected here
        with pytest.raises(ValueError, match="bad base64"):
            _S.b64_strict("AA E=", "x")
        assert _S.b64_strict("AAE=", "x") == b"\x00\x01"
        assert _S.b64_strict("", "x") == b""

    def test_as_cid_str(self):
        assert _S.as_cid_str("bafy", "x") == "bafy"
        assert _S.as_cid_str({"/": "bafy"}, "x") == "bafy"
        for bad in ({"/": 5}, {}, 5, None, ["bafy"]):
            with pytest.raises(ValueError, match="must be a CID string"):
                _S.as_cid_str(bad, "x")

    def test_prefix_appears_in_every_message(self):
        other = strict_fields("malformed widget")
        with pytest.raises(ValueError, match="^malformed widget:"):
            other.as_int("x", "f")
