"""C-extension DAG-CBOR decoder: equivalence fuzzing against pure Python."""

import random

import pytest

from ipc_proofs_tpu.backend.native import load_dagcbor_ext
from ipc_proofs_tpu.core.cid import CID, RAW
from ipc_proofs_tpu.core.dagcbor import decode, decode_py, encode

ext = load_dagcbor_ext()
pytestmark = pytest.mark.skipif(ext is None, reason="native decoder unavailable")


def _random_value(rng: random.Random, depth: int = 0):
    choices = ["int", "bytes", "str", "bool", "none", "cid"]
    if depth < 3:
        choices += ["list", "dict", "list", "dict"]
    kind = rng.choice(choices)
    if kind == "int":
        return rng.choice(
            [0, 1, -1, 23, 24, -24, -25, 255, 65536, 2**32, 2**63 - 1, -(2**63)]
        )
    if kind == "bytes":
        return bytes(rng.randrange(256) for _ in range(rng.randrange(40)))
    if kind == "str":
        return "".join(rng.choice("abcdefémoji🎈xyz ") for _ in range(rng.randrange(20)))
    if kind == "bool":
        return rng.random() < 0.5
    if kind == "none":
        return None
    if kind == "cid":
        return CID.hash_of(bytes(rng.randrange(256) for _ in range(8)), codec=RAW)
    if kind == "list":
        return [_random_value(rng, depth + 1) for _ in range(rng.randrange(5))]
    return {
        f"k{i}-{rng.randrange(100)}": _random_value(rng, depth + 1)
        for i in range(rng.randrange(5))
    }


class TestNativeDecoder:
    def test_fuzz_equivalence(self):
        rng = random.Random(1234)
        for _ in range(300):
            value = _random_value(rng)
            raw = encode(value)
            assert ext.decode(raw) == decode_py(raw) == value

    def test_decode_many(self):
        values = [[1, "two", b"three", CID.hash_of(b"x")], {"a": None}, 42]
        raws = [encode(v) for v in values]
        assert ext.decode_many(raws) == values

    def test_module_decode_dispatches_to_native(self):
        # decode() and decode_py() must agree on real chain structures
        from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain

        world = build_chain(
            [ContractFixture(actor_id=9, storage={b"\x01" * 32: b"\x02"})],
            [[EventFixture(emitter=9, signature="E(uint256)", topic1="s")]],
        )
        for _, data in world.store.items():
            assert decode(data) == decode_py(data)

    def test_errors_match_python(self):
        bad_inputs = [
            b"",  # empty
            b"\x9f\x01\xff",  # indefinite array
            b"\x18",  # truncated head
            b"\x58\x05ab",  # truncated bytes
            encode(1) + b"\x00",  # trailing
            b"\xd8\x2b\x41\x00",  # wrong tag (43)
        ]
        for raw in bad_inputs:
            with pytest.raises(ValueError):
                ext.decode(raw)
            with pytest.raises(ValueError):
                decode_py(raw)

    def test_big_negative_int(self):
        # -1 - 2**64-1 exercises the PyNumber_Subtract path
        raw = b"\x3b" + (2**64 - 1).to_bytes(8, "big")
        assert ext.decode(raw) == decode_py(raw) == -(2**64)

    def test_float64(self):
        raw = encode(3.5)
        assert ext.decode(raw) == 3.5


class TestCSideCidConstruction:
    def test_c_built_cids_match_python(self):
        """Tag-42 links built directly in C (set_cid_class) must be
        indistinguishable from CID.from_bytes results: eq, hash, to_bytes,
        str, and type."""
        from ipc_proofs_tpu.backend.native import load_dagcbor_ext
        from ipc_proofs_tpu.core.cid import CID, RAW
        from ipc_proofs_tpu.core.dagcbor import decode_py, encode

        ext = load_dagcbor_ext()
        if ext is None or not hasattr(ext, "set_cid_class"):
            pytest.skip("native set_cid_class unavailable")
        cids = [CID.hash_of(b"x"), CID.hash_of(b"y", codec=RAW)]
        raw = encode([cids[0], {"k": cids[1]}, [cids[0]] * 3])
        c_obj = ext.decode(raw)
        py_obj = decode_py(raw)
        assert c_obj == py_obj
        c_cid = c_obj[1]["k"]
        assert type(c_cid) is CID
        assert hash(c_cid) == hash(cids[1])
        assert c_cid.to_bytes() == cids[1].to_bytes()
        assert str(c_cid) == str(cids[1])

    def test_nonminimal_varint_cid_rejected_both_decoders(self):
        """A tag-42 CID with a non-minimal varint is a second wire form of
        the same link: both decoders must reject the block (go-varint /
        unsigned-varint parity; round-5 exec-order fuzz find)."""
        from ipc_proofs_tpu.backend.native import load_dagcbor_ext
        from ipc_proofs_tpu.core.cid import CID
        from ipc_proofs_tpu.core.dagcbor import decode_py

        ext = load_dagcbor_ext()
        if ext is None or not hasattr(ext, "set_cid_class"):
            pytest.skip("native set_cid_class unavailable")
        canonical = CID.hash_of(b"payload")
        raw = canonical.to_bytes()
        nonminimal = b"\x01\xf1\x00" + raw[2:]  # codec 0x71 as two bytes
        # wrap in tag 42 with identity multibase prefix
        cbor = b"\xd8\x2a\x58" + bytes([len(nonminimal) + 1]) + b"\x00" + nonminimal
        with pytest.raises(ValueError):
            ext.decode(cbor)
        with pytest.raises(ValueError):
            decode_py(cbor)

    def test_make_cids_batch(self):
        from ipc_proofs_tpu.backend.native import load_dagcbor_ext
        from ipc_proofs_tpu.core.cid import CID, RAW

        ext = load_dagcbor_ext()
        if ext is None or not hasattr(ext, "make_cids"):
            pytest.skip("native make_cids unavailable")
        cids = [CID.hash_of(b"\x01"), CID.hash_of(b"\x02", codec=RAW)]
        raws = [c.to_bytes() for c in cids]
        built = ext.make_cids(raws)
        assert built == cids
        assert [b.to_bytes() for b in built] == raws
        with pytest.raises(ValueError):
            ext.make_cids([b"\x00\x01"])  # CIDv0 / malformed
        with pytest.raises(TypeError):
            ext.make_cids([42])


class TestBatchCidCodecs:
    """cid_strs / cids_from_strs: C batch codecs must match the Python
    int-codec bit-for-bit, including every rejection."""

    def _ext(self):
        from ipc_proofs_tpu.backend.native import load_dagcbor_ext

        ext = load_dagcbor_ext()
        if ext is None or not hasattr(ext, "cid_strs"):
            pytest.skip("native cid codecs unavailable")
        return ext

    def _sample_cids(self):
        from ipc_proofs_tpu.core.cid import CID, DAG_CBOR, RAW, SHA2_256

        cids = [CID.hash_of(bytes([i]) * 3) for i in range(40)]
        cids.append(CID.hash_of(b"raw", codec=RAW))
        cids.append(CID.hash_of(b"sha", codec=DAG_CBOR, mh_code=SHA2_256))
        return cids

    def test_cid_strs_matches_python_str(self):
        ext = self._ext()
        cids = self._sample_cids()
        assert ext.cid_strs([c.to_bytes() for c in cids]) == [str(c) for c in cids]

    def test_cids_from_strs_round_trip(self):
        from ipc_proofs_tpu.core.cid import CID

        ext = self._ext()
        cids = self._sample_cids()
        strs = [str(c) for c in cids]
        parsed = ext.cids_from_strs(strs)
        assert parsed == cids
        # uppercase payload REJECTED, like CID.from_string — multibase 'b'
        # means base32-lower, and accepting both cases would let distinct
        # strings alias one CID
        up = "b" + strs[0][1:].upper()
        with pytest.raises(ValueError):
            ext.cids_from_strs([up])
        with pytest.raises(ValueError):
            CID.from_string(up)

    @pytest.mark.parametrize(
        "bad",
        ["", "zabc", "b" + "a" * 9, "babc!aaaaa", "b"],
    )
    def test_cids_from_strs_rejections_match_python(self, bad):
        from ipc_proofs_tpu.core.cid import CID

        ext = self._ext()
        with pytest.raises((ValueError, TypeError)):
            CID.from_string(bad)
        with pytest.raises((ValueError, TypeError)):
            ext.cids_from_strs([bad])

    def test_helpers_fall_back_identically(self):
        from ipc_proofs_tpu.core.cid import CID, cid_strings, cids_from_strings

        cids = self._sample_cids()
        strs = cid_strings(cids)
        assert strs == [str(c) for c in cids]
        assert cids_from_strings(strs) == cids


class TestDecodeHeaderLite:
    def test_matches_blockheader_decode(self):
        from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
        from ipc_proofs_tpu.state.header import BlockHeader, decode_header_lite
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

        bs = MemoryBlockstore()
        world = build_chain(
            [ContractFixture(actor_id=7)],
            [[EventFixture(emitter=7, signature="E()", topic1="t")]],
            store=bs,
        )
        for header in (*world.parent.blocks, *world.child.blocks):
            raw = bs.get(header.cid())
            full = BlockHeader.decode(raw)
            lite = decode_header_lite(raw)
            assert lite.parents == full.parents
            assert lite.height == full.height
            assert lite.parent_state_root == full.parent_state_root
            assert lite.parent_message_receipts == full.parent_message_receipts
            assert lite.messages == full.messages

    def test_rejects_malformed_like_decode(self):
        from ipc_proofs_tpu.core.dagcbor import encode
        from ipc_proofs_tpu.state.header import BlockHeader, decode_header_lite

        bad = encode([1, 2, 3])  # not a 16-tuple
        with pytest.raises(ValueError):
            BlockHeader.decode(bad)
        with pytest.raises(ValueError):
            decode_header_lite(bad)

    def test_oversized_identity_cid_parity(self):
        # >256-byte decoded CIDs (long identity digests) must parse in C
        # exactly as CID.from_string does — never rejected on size
        from ipc_proofs_tpu.backend.native import load_dagcbor_ext
        from ipc_proofs_tpu.core.cid import CID, DAG_CBOR, IDENTITY

        ext = load_dagcbor_ext()
        if ext is None or not hasattr(ext, "cids_from_strs"):
            pytest.skip("native cid codecs unavailable")
        big = CID(1, DAG_CBOR, IDENTITY, bytes(range(256)) + b"x" * 100)
        s = str(big)
        assert ext.cids_from_strs([s]) == [CID.from_string(s)]
        assert ext.cid_strs([big.to_bytes()]) == [s]


class TestMutationFuzzEquivalence:
    """Witness blocks are attacker-controlled: the C and Python decoders
    must agree byte-for-byte on ACCEPTANCE over corrupted inputs — same
    value when both accept, both rejecting otherwise — or a crafted block
    could verify on one install and not another."""

    def test_truncations_and_flips_agree(self):
        import random

        from ipc_proofs_tpu.backend.native import load_dagcbor_ext
        from ipc_proofs_tpu.core.dagcbor import decode_py, encode

        ext = load_dagcbor_ext()
        if ext is None:
            pytest.skip("native decoder unavailable")
        rng = random.Random(99)
        seeds = []
        for trial in range(30):
            seeds.append(encode(_random_value(rng)))
        from ipc_proofs_tpu.core.cid import CID

        seeds.append(encode([CID.hash_of(b"link"), {"k": [1, b"\x00" * 40]}]))

        checked = agreed_rejects = 0
        for raw in seeds:
            mutations = [raw[:k] for k in range(len(raw))]  # every truncation
            for _ in range(40):  # random byte flips / inserts
                m = bytearray(raw)
                op = rng.randrange(3)
                pos = rng.randrange(len(m)) if m else 0
                if op == 0 and m:
                    m[pos] ^= 1 << rng.randrange(8)
                elif op == 1 and m:
                    del m[pos]
                else:
                    m.insert(pos, rng.randrange(256))
                mutations.append(bytes(m))
            for mut in mutations:
                try:
                    py = ("ok", decode_py(mut))
                except ValueError:
                    py = ("err", None)
                except RecursionError:
                    continue  # depth guard differences are not reachable here
                try:
                    c = ("ok", ext.decode(mut))
                except ValueError:
                    c = ("err", None)
                assert py[0] == c[0], (mut.hex(), py, c)
                if py[0] == "ok":
                    assert py[1] == c[1], mut.hex()
                else:
                    agreed_rejects += 1
                checked += 1
        assert checked > 1000 and agreed_rejects > 100
