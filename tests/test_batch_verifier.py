"""Batch event verifier ↔ scalar verifier equivalence.

The grouped batch replay (native scan + pooled compares) must return exactly
the scalar loop's verdicts — on valid bundles, on every tamper case, and on
pruned/garbled witnesses. Each case asserts both paths agree AND the
expected verdict.
"""

import dataclasses

import pytest

from ipc_proofs_tpu.core.cid import CID, RAW
from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
from ipc_proofs_tpu.proofs.bundle import EventProofBundle
from ipc_proofs_tpu.proofs.event_generator import generate_event_proof
from ipc_proofs_tpu.proofs.event_verifier import create_event_filter, verify_event_proof
from ipc_proofs_tpu.proofs.scan_native import native_scan_available

pytestmark = pytest.mark.skipif(
    not native_scan_available(), reason="native scan extension unavailable"
)

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "batch-subnet"
ACTOR = 321


def make_bundle(n_pairs=3, encoding="compact"):
    from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

    bs = MemoryBlockstore()
    proofs, blocks = [], {}
    for p in range(n_pairs):
        events = [
            [EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET,
                          data=p.to_bytes(32, "big"), encoding=encoding)],
            [EventFixture(emitter=ACTOR, signature="Noise()", topic1="x")],
            [
                EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET,
                             extra_topics=[b"\x05" * 32], encoding=encoding),
                EventFixture(emitter=999, signature=SIG, topic1=SUBNET),
            ],
        ]
        world = build_chain([ContractFixture(actor_id=ACTOR)], events,
                            parent_height=10 + 2 * p, store=bs)
        bundle = generate_event_proof(
            world.store, world.parent, world.child, SIG, SUBNET, actor_id_filter=ACTOR
        )
        proofs.extend(bundle.proofs)
        for b in bundle.blocks:
            blocks[b.cid] = b
    return EventProofBundle(proofs=proofs, blocks=list(blocks.values()))


def both_paths(bundle, check_event=None):
    accept = lambda *_: True
    scalar = verify_event_proof(bundle, accept, accept, check_event=check_event,
                                batch=False)
    batch = verify_event_proof(bundle, accept, accept, check_event=check_event,
                               batch=True)
    assert scalar == batch, f"scalar={scalar} batch={batch}"
    return batch


class TestBatchScalarEquivalence:
    def test_valid_bundle_all_true(self):
        bundle = make_bundle()
        assert all(both_paths(bundle))
        assert len(bundle.proofs) == 6  # 2 matching events x 3 pairs

    def test_concat_encoding_bundle(self):
        bundle = make_bundle(encoding="concat")
        assert all(both_paths(bundle))

    def test_event_filter_paths_agree(self):
        bundle = make_bundle()
        res = both_paths(bundle, check_event=create_event_filter(SIG, SUBNET))
        assert all(res)
        res = both_paths(bundle, check_event=create_event_filter(SIG, "other"))
        assert not any(res)

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda p: dataclasses.replace(p, exec_index=p.exec_index + 1),
            lambda p: dataclasses.replace(p, event_index=p.event_index + 7),
            lambda p: dataclasses.replace(p, child_epoch=p.child_epoch + 1),
            lambda p: dataclasses.replace(p, parent_epoch=p.parent_epoch + 1),
            lambda p: dataclasses.replace(
                p, message_cid=str(CID.hash_of(b"bogus", codec=RAW))
            ),
            lambda p: dataclasses.replace(
                p,
                event_data=dataclasses.replace(p.event_data, emitter=1),
            ),
            lambda p: dataclasses.replace(
                p,
                event_data=dataclasses.replace(
                    p.event_data, data="0x" + "ff" * 32
                ),
            ),
            lambda p: dataclasses.replace(
                p,
                event_data=dataclasses.replace(
                    p.event_data, topics=p.event_data.topics[:1]
                ),
            ),
            lambda p: dataclasses.replace(
                p,
                event_data=dataclasses.replace(
                    p.event_data,
                    topics=[p.event_data.topics[0], "0x" + "ab" * 32],
                ),
            ),
            # malformed hex / missing prefix claims
            lambda p: dataclasses.replace(
                p,
                event_data=dataclasses.replace(
                    p.event_data, topics=[p.event_data.topics[0], "zz" * 32]
                ),
            ),
            lambda p: dataclasses.replace(
                p,
                event_data=dataclasses.replace(
                    p.event_data, data=p.event_data.data.removeprefix("0x")
                ),
            ),
        ],
    )
    def test_tampered_proof_fails_both_paths(self, mutate):
        bundle = make_bundle(n_pairs=1)
        tampered = EventProofBundle(
            proofs=[mutate(bundle.proofs[0]), *bundle.proofs[1:]],
            blocks=bundle.blocks,
        )
        res = both_paths(tampered)
        assert res[0] is False
        assert all(res[1:])  # untouched proofs still verify

    def test_uppercase_hex_claims_accepted(self):
        """Scalar compare is case-insensitive; batch must match."""
        bundle = make_bundle(n_pairs=1)
        p = bundle.proofs[0]
        shouty = dataclasses.replace(
            p,
            event_data=dataclasses.replace(
                p.event_data,
                topics=[t.upper().replace("0X", "0x") for t in p.event_data.topics],
                data=p.event_data.data.upper().replace("0X", "0x"),
            ),
        )
        res = both_paths(
            EventProofBundle(proofs=[shouty, *bundle.proofs[1:]], blocks=bundle.blocks)
        )
        assert res[0] is True

    def test_untrusted_proof_with_missing_child_header_no_raise(self):
        """A proof the trust policy rejects must be False (not a bundle-wide
        KeyError) even when its child header is absent from the witness —
        the scalar path never touches the witness for untrusted proofs."""
        bundle = make_bundle(n_pairs=1)
        bogus = dataclasses.replace(
            bundle.proofs[0],
            child_block_cid=str(CID.hash_of(b"not-in-witness")),
        )
        tampered = EventProofBundle(
            proofs=[bogus, *bundle.proofs[1:]], blocks=bundle.blocks
        )
        reject_child = lambda *_: False
        accept = lambda *_: True
        scalar = verify_event_proof(tampered, accept, reject_child, batch=False)
        batch = verify_event_proof(tampered, accept, reject_child, batch=True)
        assert scalar == batch == [False] * len(tampered.proofs)

    def test_whitespace_hex_claim_rejected_both_paths(self):
        """bytes.fromhex tolerates whitespace; the scalar string compare does
        not — the batch path must reject identically."""
        bundle = make_bundle(n_pairs=1)
        p = bundle.proofs[0]
        topic = p.event_data.topics[1]
        spaced = dataclasses.replace(
            p,
            event_data=dataclasses.replace(
                p.event_data,
                topics=[p.event_data.topics[0], topic[:6] + " " + topic[6:]],
            ),
        )
        res = both_paths(
            EventProofBundle(proofs=[spaced, *bundle.proofs[1:]], blocks=bundle.blocks)
        )
        assert res[0] is False

        spaced_data = dataclasses.replace(
            p,
            event_data=dataclasses.replace(
                p.event_data, data=p.event_data.data[:6] + " " + p.event_data.data[6:]
            ),
        )
        res = both_paths(
            EventProofBundle(
                proofs=[spaced_data, *bundle.proofs[1:]], blocks=bundle.blocks
            )
        )
        assert res[0] is False

    def test_truncated_witness_fails_closed(self):
        bundle = make_bundle(n_pairs=1)
        # remove one block at a time and check both paths agree
        for drop in range(len(bundle.blocks)):
            pruned = [b for i, b in enumerate(bundle.blocks) if i != drop]
            try:
                scalar = verify_event_proof(
                    EventProofBundle(proofs=bundle.proofs, blocks=pruned),
                    lambda *_: True, lambda *_: True, batch=False,
                )
                scalar_raised = None
            except KeyError as exc:
                scalar_raised = type(exc)
            try:
                batch = verify_event_proof(
                    EventProofBundle(proofs=bundle.proofs, blocks=pruned),
                    lambda *_: True, lambda *_: True, batch=True,
                )
                batch_raised = None
            except KeyError as exc:
                batch_raised = type(exc)
            assert scalar_raised == batch_raised
            if scalar_raised is None:
                assert scalar == batch


def test_non_int_claim_indices_rejected_identically():
    """Non-int exec_index / event_index (float 3.0 via json.loads, nan,
    strings) must verify False in BOTH paths — serde parity with the
    reference's u64 claim fields, which reject them at deserialization —
    and never raise (the AMT walk on a float would TypeError)."""
    from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
    from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

    bs = MemoryBlockstore()
    world = build_chain(
        [ContractFixture(actor_id=77)],
        [[EventFixture(emitter=77, signature="Evt(bytes32)", topic1="s")]],
        store=bs,
    )
    bundle = generate_event_proof(
        bs, world.parent, world.child, "Evt(bytes32)", "s", actor_id_filter=77
    )
    ok = lambda *a: True

    for field in ("exec_index", "event_index"):
        good = getattr(bundle.proofs[0], field)
        for forged, expect in [
            (good, True),
            (float(good), False),  # would never deserialize into a u64
            (float(good) + 0.5, False),
            (float("nan"), False),
            (float("inf"), False),
            (str(good), False),
            (good + 10_000, False),  # out of range, still int
        ]:
            setattr(bundle.proofs[0], field, forged)
            got_batch = verify_event_proof(
                EventProofBundle(proofs=bundle.proofs, blocks=bundle.blocks), ok, ok
            )
            got_scalar = verify_event_proof(
                EventProofBundle(proofs=bundle.proofs, blocks=bundle.blocks), ok, ok,
                batch=False,
            )
            assert got_batch == got_scalar == [expect], (
                field, forged, got_batch, got_scalar,
            )
        setattr(bundle.proofs[0], field, good)
