"""Adaptive speculation-depth tests (--speculate-depth auto): the plane
starts at AUTO_START_DEPTH, watches the per-window waste ratio, and
downshifts one level per wasteful window until it bottoms out at 0 —
counted in ``fetch.speculate_depth_downshifts`` and visible in
``stats()``.  Plain integer depths never move.  All hermetic tier-1."""

import time

import pytest

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore
from ipc_proofs_tpu.store.faults import LocalLotusSession
from ipc_proofs_tpu.store.fetchplane import FetchPlane
from ipc_proofs_tpu.store.rpc import LotusClient
from ipc_proofs_tpu.utils.metrics import Metrics


def _blocks(n: int, tag: bytes = b"spec") -> "list[tuple[CID, bytes]]":
    out = []
    for i in range(n):
        data = (tag + b"-%04d-" % i) * (i % 5 + 2)
        out.append((CID.hash_of(data), data))
    return out


def _store_with(blocks) -> MemoryBlockstore:
    bs = MemoryBlockstore()
    for cid, data in blocks:
        bs.put_keyed(cid, data)
    return bs


def _client(bs, metrics=None):
    return LotusClient(
        "http://adaptive-spec-test", session=LocalLotusSession(bs),
        metrics=metrics or Metrics(),
    )


def _wait_until(cond, timeout_s: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return False


class TestAutoDepth:
    def test_auto_starts_at_the_default_depth(self):
        bs = _store_with([])
        with FetchPlane(_client(bs), local={}, speculate_depth="auto") as plane:
            assert plane.adaptive_depth is True
            assert plane.speculate_depth == FetchPlane.AUTO_START_DEPTH
            assert plane.stats()["speculate_depth"] == FetchPlane.AUTO_START_DEPTH

    def test_integer_depth_is_not_adaptive(self):
        bs = _store_with([])
        with FetchPlane(_client(bs), local={}, speculate_depth=3) as plane:
            assert plane.adaptive_depth is False
            assert plane.speculate_depth == 3

    def test_wasteful_windows_downshift_to_zero(self):
        """Two windows of pure waste (speculated, landed, never read) take
        auto depth 2 → 1 → 0; at 0 further speculation is refused."""
        window = 8
        blocks = _blocks(3 * window)
        bs = _store_with(blocks)
        m = Metrics()
        with FetchPlane(
            _client(bs, m), local={}, metrics=m,
            speculate_depth="auto", auto_window=window,
        ) as plane:
            cids = [c for c, _ in blocks]
            plane.speculate(cids[:window])
            assert _wait_until(
                lambda: plane.stats()["speculative_fetched"] >= window
            )
            assert _wait_until(lambda: plane.stats()["speculate_depth"] == 1)
            plane.speculate(cids[window : 2 * window])
            assert _wait_until(
                lambda: plane.stats()["speculative_fetched"] >= 2 * window
            )
            assert _wait_until(lambda: plane.stats()["speculate_depth"] == 0)
            # depth 0: new speculation is dropped at the door
            plane.speculate(cids[2 * window :])
            time.sleep(0.05)
            assert plane.stats()["speculative_fetched"] == 2 * window
        counters = m.snapshot()["counters"]
        assert counters["fetch.speculate_depth_downshifts"] == 2

    def test_useful_windows_hold_the_depth(self):
        """Speculation that is consumed as it lands stays put — the
        window's waste ratio never crosses AUTO_WASTE_THRESHOLD.  Waves of
        two, consumed immediately: when the window check fires at 8
        fetched, at most the newest wave is still unread (ratio ≤ 0.25)."""
        window = 8
        blocks = _blocks(12)  # 1.5 windows
        bs = _store_with(blocks)
        m = Metrics()
        with FetchPlane(
            _client(bs, m), local={}, metrics=m,
            speculate_depth="auto", auto_window=window,
        ) as plane:
            for i in range(0, len(blocks), 2):
                wave = blocks[i : i + 2]
                plane.speculate([c for c, _ in wave])
                assert _wait_until(
                    lambda: plane.stats()["speculative_fetched"] >= i + 2
                )
                for cid, data in wave:
                    assert plane.get(cid) == data
            stats = plane.stats()
            assert stats["speculative_used"] == len(blocks)
            assert stats["speculate_depth"] == FetchPlane.AUTO_START_DEPTH
        assert (
            m.snapshot()["counters"].get("fetch.speculate_depth_downshifts", 0)
            == 0
        )

    def test_integer_depth_never_downshifts(self):
        window = 8
        blocks = _blocks(window)
        bs = _store_with(blocks)
        m = Metrics()
        with FetchPlane(
            _client(bs, m), local={}, metrics=m,
            speculate_depth=2, auto_window=window,
        ) as plane:
            plane.speculate([c for c, _ in blocks])  # pure waste, never read
            assert _wait_until(
                lambda: plane.stats()["speculative_fetched"] >= window
            )
            time.sleep(0.05)
            assert plane.stats()["speculate_depth"] == 2
        assert (
            m.snapshot()["counters"].get("fetch.speculate_depth_downshifts", 0)
            == 0
        )


class TestCliParsing:
    def test_auto_and_integers_parse(self, tmp_path):
        import argparse

        from ipc_proofs_tpu.cli import speculate_depth_arg

        assert speculate_depth_arg("auto") == "auto"
        assert speculate_depth_arg("3") == 3
        assert speculate_depth_arg("0") == 0
        with pytest.raises(argparse.ArgumentTypeError, match="integer or 'auto'"):
            speculate_depth_arg("bogus")
