"""Witness-diet tests: the differential grid, serve negotiation, and the
subs delta plane (ROADMAP item 1).

The system invariant under test: any aggregated / delta / compressed
response, expanded client-side, is byte-identical to the plain canonical
bundle — or fails with a typed error, never a silently different bundle.
The grid pins every combination of aggregation K ∈ {1, 16, 256}, delta
base ∈ {match, stale, missing}, and compression ∈ {off, on}.

Everything is hermetic (build_range_world stores, ephemeral localhost
ports, no egress) and tier-1.
"""

import json
import random
import threading
import time
from http.client import HTTPConnection

import pytest

from ipc_proofs_tpu.cluster.gather import BundleFold, merge_range_bundles
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import (
    generate_event_proofs_for_range_chunked,
)
from ipc_proofs_tpu.proofs.trust import TrustPolicy
from ipc_proofs_tpu.serve.httpd import ProofHTTPServer
from ipc_proofs_tpu.serve.service import ProofService, ServiceConfig
from ipc_proofs_tpu.subs import (
    DeliveryLog,
    PushDelivery,
    StandingQueryMatcher,
    SubscriptionRegistry,
)
from ipc_proofs_tpu.utils.metrics import Metrics
from ipc_proofs_tpu.witness import (
    AggregatedBundle,
    DeltaBaseMismatchError,
    DeltaBaseMissingError,
    WitnessBaseCache,
    WitnessEncodingError,
    WitnessError,
    WitnessIntegrityError,
    WitnessOptions,
    aggregate_range_bundle,
    apply_delta,
    compress_blocks,
    decompress_blocks,
    encode_bundle_fields,
    expand_response_fields,
    negotiate_witness,
    supported_encodings,
    verify_aggregated,
)

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001

FILTER_A = {"signature": SIG, "topic1": SUBNET}

_NOSLEEP = lambda s: None  # noqa: E731 — push retry seam: no real sleeps


@pytest.fixture(scope="module")
def world():
    return build_range_world(
        4,
        receipts_per_pair=6,
        events_per_receipt=3,
        match_rate=0.5,
        signature=SIG,
        topic1=SUBNET,
        actor_id=ACTOR,
        base_height=51_000,
    )


def _range_bundle(store, pairs, idxs):
    spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET)
    return generate_event_proofs_for_range_chunked(
        store, [pairs[i] for i in idxs], spec, chunk_size=8
    )


def _canon(bundle) -> str:
    """Canonical JSON text — THE byte-identity oracle."""
    return json.dumps(bundle.to_json_obj(), sort_keys=True, separators=(",", ":"))


def _counters(m):
    return m.snapshot()["counters"]


def _wait_until(cond, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


# --------------------------------------------------------------------------
# the differential grid: aggregate × delta × compression
# --------------------------------------------------------------------------


class TestDifferentialGrid:
    """Every cell expands byte-identical or fails typed — never silently
    different. The server half is `encode_bundle_fields` (exactly what the
    HTTP layer calls), the client half `expand_response_fields`."""

    DISTINCT = [0, 1, 2, 3]

    @pytest.fixture(scope="class")
    def bundles(self, world):
        store, pairs, _ = world
        cur = _range_bundle(store, pairs, self.DISTINCT)
        base = _range_bundle(store, pairs, [0, 1])  # the client's last epoch
        stale = _range_bundle(store, pairs, [2, 3])  # the WRONG held base
        assert len({cur.digest(), base.digest(), stale.digest()}) == 3
        return store, pairs, cur, base, stale

    @pytest.mark.parametrize("k", [1, 16, 256])
    @pytest.mark.parametrize("base_kind", ["match", "stale", "missing"])
    @pytest.mark.parametrize("encoding", ["identity", "zlib"])
    def test_cell(self, bundles, k, base_kind, encoding):
        _store, pairs, cur, base, stale = bundles
        m = Metrics()
        claim_idxs = [self.DISTINCT[i % len(self.DISTINCT)] for i in range(k)]
        agg = aggregate_range_bundle(
            cur, pairs, self.DISTINCT, claim_indexes=claim_idxs, metrics=m
        )
        assert len(agg.claims) == k
        assert _counters(m)["witness.aggregated_claims"] == k

        bases = WitnessBaseCache(cap=8)
        if base_kind != "missing":
            # the server served (and remembers) the client's base epoch
            bases.register(base.digest(), base.cid_set())
        opts = WitnessOptions(encoding=encoding, base_digest=base.digest())
        fields = encode_bundle_fields(
            cur, opts, bases=bases, metrics=m, claims=agg.claims_json()
        )

        # the chosen encoding is always echoed; the digest always rides
        assert fields["witness_encoding"] == encoding
        assert fields["digest"] == cur.digest()
        assert len(fields["claims"]) == k

        if base_kind == "missing":
            # unknown base ⇒ FULL bundle, counted — the sound degradation
            assert "bundle" in fields and "bundle_delta" not in fields
            assert _counters(m)["witness.delta_fallbacks"] == 1
            if encoding == "zlib":
                assert "blocks_frame" in fields["bundle"]
                assert "blocks" not in fields["bundle"]
            expanded = expand_response_fields(fields)
            assert _canon(expanded) == _canon(cur)
        elif base_kind == "match":
            assert "bundle_delta" in fields
            assert fields["witness_base"] == base.digest()
            dobj = fields["bundle_delta"]
            if encoding == "zlib":
                assert "delta_blocks_frame" in dobj and "delta_blocks" not in dobj
            else:
                # the delta genuinely ships fewer blocks than the full form
                assert len(dobj["delta_blocks"]) < len(cur.blocks)
            assert _counters(m)["witness.delta_hits"] == 1
            assert _counters(m)["witness.delta_blocks_dropped"] > 0
            expanded = expand_response_fields(fields, base=base)
            assert _canon(expanded) == _canon(cur)
        else:  # stale: the client holds a different bundle than declared
            if "bundle_delta" in fields:
                with pytest.raises(DeltaBaseMismatchError):
                    expand_response_fields(fields, base=stale)
                return  # typed failure IS the cell's correct outcome
            expanded = expand_response_fields(fields)
            assert _canon(expanded) == _canon(cur)

        # the claim table survives the wire and re-anchors on the expansion
        back = AggregatedBundle.claims_from_json(fields["claims"], expanded)
        assert [c.to_json_obj() for c in back.claims] == fields["claims"]

    def test_delta_without_base_is_typed(self, bundles):
        _store, _pairs, cur, base, _stale = bundles
        bases = WitnessBaseCache(cap=8)
        bases.register(base.digest(), base.cid_set())
        fields = encode_bundle_fields(
            cur, WitnessOptions(base_digest=base.digest()), bases=bases,
            metrics=Metrics(),
        )
        assert "bundle_delta" in fields
        with pytest.raises(DeltaBaseMissingError):
            expand_response_fields(fields, base=None)

    def test_tampered_delta_blocks_fail_closed(self, bundles):
        """A delta whose blocks were corrupted in flight re-digests wrong
        on expansion — typed error, never different bytes."""
        _store, _pairs, cur, base, _stale = bundles
        from ipc_proofs_tpu.witness.delta import encode_delta

        dobj = encode_delta(cur, base.cid_set(), base.digest())
        assert dobj["delta_blocks"], "grid world must produce a nonempty delta"
        dobj = json.loads(json.dumps(dobj))
        blk = dobj["delta_blocks"][0]
        blk["data"] = "00" + blk["data"][2:] if blk["data"][:2] != "00" else (
            "ff" + blk["data"][2:]
        )
        with pytest.raises(DeltaBaseMismatchError):
            apply_delta(dobj, base)


class TestAggregatedVerify:
    def test_per_claim_verdicts_from_one_replay(self, world):
        store, pairs, _ = world
        idxs = [0, 1, 2, 3]
        cur = _range_bundle(store, pairs, idxs)
        claim_idxs = [idxs[i % 4] for i in range(16)]
        agg = aggregate_range_bundle(
            cur, pairs, idxs, claim_indexes=claim_idxs, metrics=Metrics()
        )
        results = verify_aggregated(agg, TrustPolicy.accept_all())
        assert len(results) == 16
        for c, r in zip(agg.claims, results):
            assert r.all_valid()
            assert len(r.event_results) == c.event_hi - c.event_lo
        # repeated claims for one pair share that pair's span (the whole
        # amortization: proofs and witness serialize once for all K)
        assert agg.claims[0].to_json_obj() == agg.claims[4].to_json_obj()

    def test_aggregate_beats_k_separate_responses(self, world):
        store, pairs, _ = world
        idxs = [0, 1, 2, 3]
        cur = _range_bundle(store, pairs, idxs)
        agg = aggregate_range_bundle(
            cur, pairs, idxs, claim_indexes=[idxs[i % 4] for i in range(16)],
            metrics=Metrics(),
        )
        agg_bytes = len(_canon(cur)) + len(json.dumps(agg.claims_json()))
        solo = {i: len(_canon(_range_bundle(store, pairs, [i]))) for i in idxs}
        separate_bytes = sum(solo[idxs[i % 4]] for i in range(16))
        assert agg_bytes < separate_bytes

    def test_claim_span_validation_is_typed(self, world):
        store, pairs, _ = world
        cur = _range_bundle(store, pairs, [0])
        bad = [{"pair_index": 0, "storage_proofs": [0, 0],
                "event_proofs": [0, len(cur.event_proofs) + 5]}]
        with pytest.raises(WitnessError):
            AggregatedBundle.claims_from_json(bad, cur)
        with pytest.raises(WitnessError):
            aggregate_range_bundle(cur, pairs, [0], claim_indexes=[3],
                                   metrics=Metrics())


class TestFraming:
    def test_zlib_roundtrip_preserves_blocks(self, world):
        store, pairs, _ = world
        cur = _range_bundle(store, pairs, [0, 1])
        m = Metrics()
        frame = compress_blocks(cur.blocks, "zlib", metrics=m)
        assert _counters(m)["witness.compressed_frames"] == 1
        back = decompress_blocks(frame)
        assert [b.to_json_obj() for b in back] == [
            b.to_json_obj() for b in cur.blocks
        ]
        # the frame is an actual diet: canonical ordering lays same-tree
        # interiors adjacent, so zlib compresses below the JSON hex form
        json_bytes = len(json.dumps([b.to_json_obj() for b in cur.blocks]))
        assert len(frame["frame"]) < json_bytes

    def test_corrupt_frame_fails_typed(self, world):
        import base64

        store, pairs, _ = world
        cur = _range_bundle(store, pairs, [0])
        frame = compress_blocks(cur.blocks, "zlib", metrics=Metrics())
        raw = bytearray(base64.b64decode(frame["frame"]))
        raw[len(raw) // 2] ^= 0xFF
        bad = dict(frame, frame=base64.b64encode(bytes(raw)).decode("ascii"))
        with pytest.raises((WitnessIntegrityError, WitnessEncodingError)):
            decompress_blocks(bad)
        # a frame that decompresses but hashes wrong is equally typed
        other = compress_blocks(cur.blocks[:1], "zlib", metrics=Metrics())
        mixed = dict(frame, frame=other["frame"])
        with pytest.raises(WitnessIntegrityError):
            decompress_blocks(mixed)

    def test_unknown_encoding_is_typed_everywhere(self, world):
        store, pairs, _ = world
        cur = _range_bundle(store, pairs, [0])
        with pytest.raises(WitnessEncodingError):
            compress_blocks(cur.blocks, "lz4", metrics=Metrics())
        with pytest.raises(WitnessEncodingError):
            negotiate_witness({"witness_encoding": "lz4"})
        assert supported_encodings()[0] == "identity"
        assert "zlib" in supported_encodings()


class TestBundleFold:
    def test_fold_matches_merge_and_sorts_once(self, world):
        """Satellite: the scatter-gather fold sorts the witness union ONCE
        at seal (witness.merge_sorts == 1), byte-identical to the
        re-sort-per-arrival merge it replaces."""
        store, pairs, _ = world
        idxs = [0, 1, 2, 3]
        subs = [_range_bundle(store, pairs, [i]) for i in idxs]
        reference = merge_range_bundles(subs, pairs, idxs)
        m = Metrics()
        fold = BundleFold(pairs, idxs, metrics=m)
        for b in random.Random(7).sample(subs, len(subs)):  # arrival order ≠ request order
            fold.fold(b)
        merged = fold.seal()
        assert _canon(merged) == _canon(reference)
        assert _counters(m)["witness.merge_sorts"] == 1


# --------------------------------------------------------------------------
# serve plane: negotiation, echo, typed rejects, delta + aggregate over HTTP
# --------------------------------------------------------------------------


class TestServeNegotiation:
    @pytest.fixture()
    def server(self, world):
        store, pairs, _ = world
        svc = ProofService(
            store=store,
            spec=EventProofSpec(event_signature=SIG, topic_1=SUBNET),
            config=ServiceConfig(max_batch=8, max_wait_ms=5.0, workers=2),
        )
        httpd = ProofHTTPServer(svc, pairs=pairs).start()
        yield httpd, store, pairs
        httpd.shutdown(timeout=30)

    def _post(self, server, path, obj, headers=None):
        conn = HTTPConnection("127.0.0.1", server.port, timeout=30)
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        conn.request("POST", path, json.dumps(obj), hdrs)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), json.loads(resp.read())

    def _get(self, server, path):
        conn = HTTPConnection("127.0.0.1", server.port, timeout=30)
        conn.request("GET", path, None, {})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())

    def test_unknown_encoding_typed_400_never_silent_plain(self, server):
        httpd, _store, _pairs = server
        for body, hdrs in (
            ({"pair_index": 0, "witness_encoding": "lz4"}, None),
            ({"pair_index": 0}, {"Accept-Witness-Encoding": "snappy"}),
            ({"pair_indexes": [0], "witness_encoding": "lz4"}, None),
        ):
            path = "/v1/generate" if "pair_index" in body else "/v1/generate_range"
            status, _, out = self._post(httpd, path, body, headers=hdrs)
            assert status == 400
            assert out["error_type"] == "witness_encoding"
            assert "bundle" not in out
        _, snap = self._get(httpd, "/metrics")
        assert snap["counters"]["witness.encoding_rejects"] == 3

    def test_zlib_echoes_and_expands_byte_identical(self, server):
        httpd, _store, _pairs = server
        status, _, plain = self._post(httpd, "/v1/generate", {"pair_index": 0})
        assert status == 200
        status, headers, out = self._post(
            httpd, "/v1/generate", {"pair_index": 0},
            headers={"Accept-Witness-Encoding": "zlib"},
        )
        assert status == 200
        assert headers["Witness-Encoding"] == "zlib"
        assert out["witness_encoding"] == "zlib"
        assert "blocks_frame" in out["bundle"]
        expanded = expand_response_fields(out)
        assert json.dumps(expanded.to_json_obj(), sort_keys=True) == json.dumps(
            plain["bundle"], sort_keys=True
        )

    def test_delta_roundtrip_and_missing_base_fallback(self, server):
        httpd, _store, _pairs = server
        # epoch N: plain full response — the server registers it as a base
        status, _, first = self._post(
            httpd, "/v1/generate_range", {"pair_indexes": [0, 1]}
        )
        assert status == 200
        base_digest = first["digest"]
        base = expand_response_fields(first)
        # epoch N+1 via the If-Witness-Base header → a delta against N
        status, headers, out = self._post(
            httpd, "/v1/generate_range", {"pair_indexes": [0, 1, 2]},
            headers={"If-Witness-Base": base_digest},
        )
        assert status == 200
        assert headers["Witness-Encoding"] == "identity"
        assert out["witness_base"] == base_digest
        assert "bundle_delta" in out and "bundle" not in out
        status2, _, plain = self._post(
            httpd, "/v1/generate_range", {"pair_indexes": [0, 1, 2]}
        )
        assert status2 == 200
        expanded = expand_response_fields(out, base=base)
        assert json.dumps(expanded.to_json_obj(), sort_keys=True) == json.dumps(
            plain["bundle"], sort_keys=True
        )
        # a base this server never saw degrades to FULL, counted
        status, _, fb = self._post(
            httpd, "/v1/generate_range",
            {"pair_indexes": [0, 1], "base_digest": "0" * 64},
        )
        assert status == 200
        assert "bundle" in fb and "bundle_delta" not in fb
        _, snap = self._get(httpd, "/metrics")
        assert snap["counters"]["witness.delta_fallbacks"] >= 1

    def test_aggregate_roundtrip_with_claim_verdicts(self, server):
        httpd, _store, _pairs = server
        idxs = [0, 1, 0, 1, 2, 0]
        status, _, out = self._post(
            httpd, "/v1/generate_range",
            {"pair_indexes": idxs, "aggregate": True},
        )
        assert status == 200
        assert len(out["claims"]) == len(idxs)
        assert out["n_pairs"] == 3  # distinct pairs generated once
        # the aggregated bundle IS the canonical distinct-range bundle
        status2, _, plain = self._post(
            httpd, "/v1/generate_range", {"pair_indexes": [0, 1, 2]}
        )
        assert json.dumps(out["bundle"], sort_keys=True) == json.dumps(
            plain["bundle"], sort_keys=True
        )
        # one shared verify replay → per-claim verdicts
        status, _, ver = self._post(
            httpd, "/v1/verify",
            {"bundle": out["bundle"], "claims": out["claims"]},
        )
        assert status == 200
        assert ver["all_valid"] is True
        assert len(ver["claim_results"]) == len(idxs)
        assert all(c["all_valid"] for c in ver["claim_results"])

    def test_compressed_bundle_accepted_on_verify(self, server):
        httpd, _store, _pairs = server
        status, _, out = self._post(
            httpd, "/v1/generate", {"pair_index": 0, "witness_encoding": "zlib"}
        )
        assert status == 200
        status, _, ver = self._post(httpd, "/v1/verify", {"bundle": out["bundle"]})
        assert status == 200
        assert ver["all_valid"] is True
        # a corrupt frame on the verify path is a typed 400
        bad = json.loads(json.dumps(out["bundle"]))
        bad["blocks_frame"]["uncompressed_digest"] = "0" * 64
        status, _, err = self._post(httpd, "/v1/verify", {"bundle": bad})
        assert status == 400
        assert err["error_type"] == "witness_integrity"

    def test_agg_max_and_disabled_knobs(self, world):
        store, pairs, _ = world
        svc = ProofService(
            store=store,
            spec=EventProofSpec(event_signature=SIG, topic_1=SUBNET),
            config=ServiceConfig(
                max_batch=8, max_wait_ms=5.0, workers=1,
                witness_agg_max=4, witness_compress=False, witness_delta=False,
            ),
        )
        httpd = ProofHTTPServer(svc, pairs=pairs).start()
        try:
            status, _, out = self._post(
                httpd, "/v1/generate_range",
                {"pair_indexes": [0, 1, 0, 1, 0], "aggregate": True},
            )
            assert (status, out["error_type"]) == (400, "witness_agg_max")
            # compression off is a CONTRACT violation → typed 400
            status, _, out = self._post(
                httpd, "/v1/generate",
                {"pair_index": 0, "witness_encoding": "zlib"},
            )
            assert (status, out["error_type"]) == (400, "witness_encoding")
            # delta off is a DEGRADATION → full bundle, no error
            status, _, out = self._post(
                httpd, "/v1/generate",
                {"pair_index": 0, "base_digest": "0" * 64},
            )
            assert status == 200
            assert "bundle" in out and "bundle_delta" not in out
        finally:
            httpd.shutdown(timeout=30)


# --------------------------------------------------------------------------
# subs plane: consecutive-epoch deltas, stale-base fallback, cursor hygiene
# --------------------------------------------------------------------------


class _RecordingOpener:
    def __init__(self, behavior=None):
        self._lock = threading.Lock()
        self._calls = []
        self._behavior = behavior

    def __call__(self, url, body, timeout_s):
        obj = json.loads(body)
        with self._lock:
            self._calls.append(obj)
        return 200 if self._behavior is None else self._behavior(obj)

    def calls(self, sub_id=None):
        with self._lock:
            out = list(self._calls)
        if sub_id is None:
            return out
        return [c for c in out if c["sub_id"] == sub_id]


def _stack(root, store, opener, m=None, delta=True):
    m = m if m is not None else Metrics()
    reg = SubscriptionRegistry(root, metrics=m, fsync=False)
    log = DeliveryLog(root, metrics=m, fsync=False)
    push = PushDelivery(
        log, metrics=m, max_attempts=1, base_delay_s=0.01, max_delay_s=0.02,
        opener=opener, sleep=_NOSLEEP, rng=random.Random(0),
    )
    matcher = StandingQueryMatcher(
        reg, log, push, store, metrics=m, chunk_size=8, delta=delta
    )
    return m, reg, log, push, matcher


def _drain(reg, log, push, matcher):
    matcher.drain()
    push.drain()
    log.close()
    reg.close()


class TestSubsDeltaDelivery:
    def _expected_obj(self, store, pair):
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET)
        return generate_event_proofs_for_range_chunked(
            store, [pair], spec, chunk_size=8
        )

    def test_consecutive_epochs_ship_deltas_stale_base_falls_back(
        self, tmp_path, world
    ):
        """w1 acks every epoch → epochs 2,3 arrive as deltas that expand
        byte-identically. w2's webhook dies at epoch 2, so at epoch 3 its
        acked base is stale → FULL bundle + witness.delta_fallbacks."""
        store, pairs, _ = world
        h2 = pairs[1].child.height

        def behavior(obj):
            return 500 if obj["sub_id"] == "w2" and obj["tipset"] == h2 else 200

        opener = _RecordingOpener(behavior)
        m, reg, log, push, matcher = _stack(str(tmp_path), store, opener)
        reg.subscribe(FILTER_A, {"url": "http://h/w1"}, sub_id="w1")
        reg.subscribe(FILTER_A, {"url": "http://h/w2"}, sub_id="w2")
        try:
            assert matcher.match_pair(pairs[0]) == 2
            assert _wait_until(lambda: len(opener.calls("w1")) == 1)
            assert _wait_until(lambda: log.acked_base("w1") is not None)
            # epoch 1: nothing held yet → full bundles all round
            assert "bundle" in opener.calls("w1")[0]
            d1 = log.acked_base("w1")

            assert matcher.match_pair(pairs[1]) == 2
            assert _wait_until(lambda: log.acked_base("w1") not in (None, d1))
            env = opener.calls("w1")[1]
            assert "bundle_delta" in env
            assert env["bundle_delta"]["base_digest"] == d1
            base = self._expected_obj(store, pairs[0])
            expected2 = self._expected_obj(store, pairs[1])
            expanded = apply_delta(env["bundle_delta"], base)
            assert _canon(expanded) == _canon(expected2)
            assert log.acked_base("w2") == d1  # w2's push failed — still on 1

            # epoch 3: w1 deltas from epoch 2; w2's base is stale → full
            h3 = pairs[2].child.height
            assert matcher.match_pair(pairs[2]) == 2
            assert _wait_until(
                lambda: any(c["tipset"] == h3 for c in opener.calls("w2"))
            )
            assert _wait_until(lambda: len(opener.calls("w1")) == 3)
            env_w1 = opener.calls("w1")[2]
            assert "bundle_delta" in env_w1
            assert _canon(
                apply_delta(env_w1["bundle_delta"], expected2)
            ) == _canon(self._expected_obj(store, pairs[2]))
            env_w2 = [c for c in opener.calls("w2") if c["tipset"] == h3][-1]
            assert "bundle" in env_w2 and "bundle_delta" not in env_w2
            assert _counters(m)["witness.delta_fallbacks"] >= 1
            assert _counters(m)["witness.delta_hits"] >= 2
        finally:
            _drain(reg, log, push, matcher)

    def test_restart_falls_back_to_full_never_wrong_delta(self, tmp_path, world):
        """A restarted matcher has no filter bases: the next epoch ships
        FULL even though the sub's acked base survived in the log."""
        store, pairs, _ = world
        opener = _RecordingOpener()
        m, reg, log, push, matcher = _stack(str(tmp_path), store, opener)
        reg.subscribe(FILTER_A, {"url": "http://h/w1"}, sub_id="w1")
        try:
            assert matcher.match_pair(pairs[0]) == 1
            assert _wait_until(lambda: log.acked_base("w1") is not None)
        finally:
            matcher.drain()
        matcher2 = StandingQueryMatcher(
            reg, log, push, store, metrics=m, chunk_size=8, delta=True
        )
        try:
            assert matcher2.match_pair(pairs[1]) == 1
            assert _wait_until(lambda: len(opener.calls("w1")) == 2)
            env = opener.calls("w1")[1]
            assert "bundle" in env and "bundle_delta" not in env
            assert _counters(m)["witness.delta_fallbacks"] == 1
        finally:
            _drain(reg, log, push, matcher2)

    def test_delta_off_always_ships_full(self, tmp_path, world):
        store, pairs, _ = world
        opener = _RecordingOpener()
        m, reg, log, push, matcher = _stack(
            str(tmp_path), store, opener, delta=False
        )
        reg.subscribe(FILTER_A, {"url": "http://h/w1"}, sub_id="w1")
        try:
            assert matcher.match_pair(pairs[0]) == 1
            assert _wait_until(lambda: log.acked_base("w1") is not None)
            assert matcher.match_pair(pairs[1]) == 1
            assert _wait_until(lambda: len(opener.calls("w1")) == 2)
            assert all("bundle" in c for c in opener.calls("w1"))
            assert "witness.delta_hits" not in _counters(m)
        finally:
            _drain(reg, log, push, matcher)


class TestDeltaCursorHygiene:
    def test_acked_base_survives_compaction_and_restart(self, tmp_path):
        """Satellite: compaction drops an acked delivery's pay frame; the
        base digest must survive in the cursor record so a restarted
        stack never cuts a delta against vanished bytes."""
        m = Metrics()
        log = DeliveryLog(str(tmp_path), metrics=m, cap_bytes=1, fsync=False)
        payload = {"bundle": {"x": "y" * 256}}
        d1 = log.append("s1", 100, "digest-a", payload)
        assert d1 is not None
        log.ack_through("s1", d1.cursor)
        assert log.acked_base("s1") == "digest-a"
        # cap_bytes=1 → every append compacts; the acked pay frame is gone
        log.append("s1", 101, "digest-b", {"bundle": {"x": "z" * 256}})
        log.close()

        log2 = DeliveryLog(str(tmp_path), metrics=Metrics(), fsync=False)
        try:
            # the cursor record carried the base identity across the wipe
            assert log2.acked_base("s1") == "digest-a"
            # and acking the surviving delivery advances it normally
            entries = log2.entries_after("s1", d1.cursor)
            assert [e.digest for e in entries] == ["digest-b"]
            log2.ack_through("s1", entries[0].cursor)
            assert log2.acked_base("s1") == "digest-b"
        finally:
            log2.close()

    def test_delta_payloads_are_content_addressed_separately(self, tmp_path):
        """A delta and its full bundle share the FULL digest (idempotency)
        but not payload bytes — the pay frames must not collide."""
        log = DeliveryLog(str(tmp_path), metrics=Metrics(), fsync=False)
        full = {"bundle": {"k": "full"}}
        delta = {"bundle_delta": {"base_digest": "a", "digest": "dg"}}
        d1 = log.append("s1", 100, "dg", full)
        d2 = log.append("s2", 100, "dg", delta, payload_digest="delta:a:dg")
        assert d1 is not None and d2 is not None
        assert d1.payload == full and d2.payload == delta
        log.close()
        log2 = DeliveryLog(str(tmp_path), metrics=Metrics(), fsync=False)
        try:
            # replay resolves each subscriber's OWN payload bytes
            assert log2.entries_after("s1", 0)[0].payload == full
            assert log2.entries_after("s2", 0)[0].payload == delta
        finally:
            log2.close()
