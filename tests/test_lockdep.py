"""Runtime lockdep witness: the dynamic half of the lock-order discipline.

The grid below pins the violation taxonomy (inversion / reentry / hold),
the cross-primitive graph (thread locks AND flocks feed one order
graph), the fail-soft recording mode, the waiting-is-not-holding
Condition contract, and the zero-overhead-when-disabled factory
behavior. ``tools/check_all.py --lockdep`` re-runs the lock-heavy
tier-1 files (including this one) under ``IPC_LOCKDEP=1``, so every
test here must leave the module state exactly as it found it.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from ipc_proofs_tpu.utils import lockdep
from ipc_proofs_tpu.utils.lockdep import (
    LockOrderError,
    flock_frame,
    named_condition,
    named_lock,
    named_rlock,
    note_flock_acquired,
    order_graph,
    violations,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture
def lockdep_strict():
    """Fresh strict state for one test; restores whatever was active."""
    saved = lockdep._state
    lockdep.enable(strict=True, hold_budget_ms=0)
    yield
    lockdep._state = saved


@pytest.fixture
def lockdep_soft():
    saved = lockdep._state
    lockdep.enable(strict=False, hold_budget_ms=0)
    yield
    lockdep._state = saved


def _in_thread(fn):
    """Run ``fn`` on a fresh thread (fresh per-thread stack), re-raising."""
    box = {}

    def run():
        try:
            fn()
        except BaseException as exc:  # pragma: no cover - only on test failure
            box["exc"] = exc

    t = threading.Thread(target=run)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    if "exc" in box:
        raise box["exc"]


class TestInversion:
    def test_abba_raises_in_strict_mode(self, lockdep_strict):
        a, b = named_lock("T.a"), named_lock("T.b")
        with a:
            with b:
                pass
        with b:
            with pytest.raises(LockOrderError, match="ABBA"):
                a.acquire()

    def test_abba_across_threads(self, lockdep_strict):
        a, b = named_lock("T.a"), named_lock("T.b")

        def forward():
            with a:
                with b:
                    pass

        _in_thread(forward)  # witness a < b on another thread's stack
        with b:
            with pytest.raises(LockOrderError, match="ABBA"):
                a.acquire()

    def test_consistent_order_is_silent(self, lockdep_strict):
        a, b = named_lock("T.a"), named_lock("T.b")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert violations() == []
        assert ("T.a", "T.b") in order_graph()

    def test_trylock_adds_no_edges_and_never_inverts(self, lockdep_strict):
        a, b = named_lock("T.a"), named_lock("T.b")
        with a:
            assert b.acquire(blocking=False)
            b.release()
        assert ("T.a", "T.b") not in order_graph()
        with b:  # would be an ABBA if the trylock had registered an edge
            with a:
                pass
        assert violations() == []


class TestFlockMixedGraph:
    def test_flock_participates_in_the_thread_lock_graph(
        self, lockdep_strict, tmp_path
    ):
        lockfile = str(tmp_path / "x.lock")
        t = named_lock("T.t")
        with t:
            with flock_frame(lockfile, "x"):
                pass
        assert ("T.t", "flock:x") in order_graph()
        with flock_frame(lockfile, "x"):
            with pytest.raises(LockOrderError, match="ABBA"):
                t.acquire()

    def test_nonblocking_flock_is_a_trylock(self, lockdep_strict, tmp_path):
        lockfile = str(tmp_path / "x.lock")
        t = named_lock("T.t")
        with t:
            with flock_frame(lockfile, "x", blocking=False):
                pass
        assert ("T.t", "flock:x") not in order_graph()

    def test_note_flock_acquired_witnesses_a_lease(self, lockdep_strict):
        t = named_lock("T.t")
        with t:
            note_flock_acquired("lease")
        assert ("T.t", "flock:lease") in order_graph()


class TestFailSoft:
    def test_inversion_records_instead_of_raising(self, lockdep_soft):
        a, b = named_lock("T.a"), named_lock("T.b")
        with a:
            with b:
                pass
        with b:
            with a:  # fail-soft: recorded, execution continues
                pass
        kinds = [v["kind"] for v in violations()]
        assert kinds == ["inversion"]

    def test_duplicate_violations_are_deduplicated(self, lockdep_soft):
        a, b = named_lock("T.a"), named_lock("T.b")
        with a:
            with b:
                pass
        for _ in range(3):
            with b:
                with a:
                    pass
        assert len(violations()) == 1

    def test_reentry_raises_even_fail_soft(self, lockdep_soft):
        # proceeding would deadlock the thread on itself; a hung process
        # out-reports no recorder, so re-entry is always fatal
        a = named_lock("T.a")
        a.acquire()
        try:
            with pytest.raises(LockOrderError, match="re-acquired"):
                a.acquire()
        finally:
            a.release()


class TestPrimitives:
    def test_rlock_reentry_is_legal(self, lockdep_strict):
        r = named_rlock("T.r")
        with r:
            with r:
                pass
        assert violations() == []

    def test_condition_wait_is_not_holding(self, lockdep_soft):
        lockdep.enable(strict=False, hold_budget_ms=20)
        cond = named_condition("T.cond")
        with cond:
            cond.wait(timeout=0.2)  # 10x the budget, spent NOT holding
        assert [v for v in violations() if v["kind"] == "hold"] == []

    def test_condition_wait_for_wakes_on_notify(self, lockdep_strict):
        cond = named_condition("T.cond")
        ready = []

        def producer():
            with cond:
                ready.append(True)
                cond.notify_all()

        t = threading.Thread(target=producer)
        with cond:
            t.start()
            assert cond.wait_for(lambda: ready, timeout=5)
        t.join(timeout=5)
        assert violations() == []

    def test_hold_budget_violation_at_release(self, lockdep_soft):
        lockdep.enable(strict=False, hold_budget_ms=10)
        a = named_lock("T.a")
        with a:
            time.sleep(0.05)
        kinds = [v["kind"] for v in violations()]
        assert kinds == ["hold"]


class TestDisabledPath:
    def test_factories_return_plain_primitives(self):
        saved = lockdep._state
        lockdep.disable()
        try:
            assert type(named_lock("x")) is type(threading.Lock())
            assert type(named_rlock("x")) is type(threading.RLock())
            assert isinstance(named_condition("x"), threading.Condition)
            assert violations() == [] and order_graph() == {}
        finally:
            lockdep._state = saved

    def test_enabled_overhead_is_bounded(self, lockdep_strict):
        # smoke bound, not a benchmark: 20k tracked acquire/release pairs
        # must land far under a second, or the opt-in is not shippable
        a = named_lock("T.a")
        t0 = time.perf_counter()
        for _ in range(20_000):
            with a:
                pass
        assert time.perf_counter() - t0 < 2.0


class TestEnvKnob:
    @pytest.mark.parametrize(
        "env_value, expect_strict", [("1", True), ("soft", False)]
    )
    def test_env_enables_at_import(self, env_value, expect_strict):
        code = (
            "from ipc_proofs_tpu.utils import lockdep\n"
            "assert lockdep.enabled()\n"
            f"assert lockdep._state.strict is {expect_strict}\n"
        )
        env = dict(os.environ)
        env["IPC_LOCKDEP"] = env_value
        proc = subprocess.run(
            [sys.executable, "-c", code],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
