"""Hermetic tests for bench.py's per-leg watchdog orchestrator.

The orchestrator exists because the tunneled chip can stall MID-RUN (a
dispatch that never returns), which used to hang the whole benchmark so no
JSON artifact was ever printed. These tests drive the assembly logic with
faked legs — no jax, no subprocesses beyond a stub — and pin the contract:
one stalled leg costs that leg, never the artifact; device legs downgrade
to CPU after a stall; every leg's numbers are labeled with the platform it
actually ran on; and a total failure still emits the full headline schema
with nulls rather than a shrunken dict.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import bench  # noqa: E402


def _args(**overrides):
    argv = []
    for k, v in overrides.items():
        argv += [f"--{k.replace('_', '-')}", str(v)]
    return bench._parse_args(argv)


class TestParseArgs:
    def test_quick_clamps_shapes(self):
        args = bench._parse_args(["--quick", "--tipsets", "4096"])
        assert args.tipsets == 256
        assert args.baseline_pairs == 32
        assert args.kernel_iters == 5

    def test_leg_choices(self):
        for leg in bench.LEGS:
            assert bench._parse_args(["--leg", leg]).leg == leg
        with pytest.raises(SystemExit):
            bench._parse_args(["--leg", "nonsense"])


class _FakeProc:
    def __init__(self, returncode=0, stdout=""):
        self.returncode = returncode
        self.stdout = stdout


class TestRunLeg:
    """_run_leg parses the child's last stdout line and labels status with
    the platform the leg REPORTS (not the one requested)."""

    def test_ok_pops_reported_platform(self, monkeypatch):
        payload = {"device_mask_kernel_events_per_sec": 5.0, "_platform": "tpu"}
        monkeypatch.setattr(
            bench.subprocess, "run",
            lambda *a, **k: _FakeProc(0, "jax noise line\n" + json.dumps(payload)),
        )
        out, status = bench._run_leg("kernel", _args(), "default")
        assert status == "ok:tpu"
        assert out == {"device_mask_kernel_events_per_sec": 5.0}

    def test_ok_without_platform_falls_back_to_requested(self, monkeypatch):
        monkeypatch.setattr(
            bench.subprocess, "run", lambda *a, **k: _FakeProc(0, json.dumps({"x": 1}))
        )
        _out, status = bench._run_leg("kernel", _args(), "cpu")
        assert status == "ok:cpu"

    def test_timeout_and_error_statuses(self, monkeypatch):
        def _raise(*a, **k):
            raise subprocess.TimeoutExpired(cmd="x", timeout=1)

        monkeypatch.setattr(bench.subprocess, "run", _raise)
        out, status = bench._run_leg("e2e", _args(), "default")
        assert out is None and status == "timeout:default"

        monkeypatch.setattr(bench.subprocess, "run", lambda *a, **k: _FakeProc(3, ""))
        out, status = bench._run_leg("e2e", _args(), "default")
        assert out is None and status == "error:default"

        monkeypatch.setattr(
            bench.subprocess, "run", lambda *a, **k: _FakeProc(0, "not json at all")
        )
        out, status = bench._run_leg("e2e", _args(), "cpu")
        assert out is None and status == "error:cpu"

    def test_timeout_scaling(self):
        args = _args(leg_timeout_mult=2.0)
        assert bench._leg_timeout("e2e", args) == pytest.approx(
            bench._LEG_TIMEOUTS["e2e"][0] * 2.0
        )
        args_quick = bench._parse_args(["--quick"])
        assert bench._leg_timeout("cid", args_quick) == pytest.approx(
            bench._LEG_TIMEOUTS["cid"][1]
        )


def _orchestrate_with(monkeypatch, capsys, leg_results, requested=None):
    """Run _orchestrate with faked pick_platform + _run_leg; returns the
    printed JSON artifact. ``leg_results`` maps leg name → list of
    (dict|None, status) consumed per call. ``requested``, if given, collects
    every (leg, platform) the orchestrator asked for — the downgrade
    contract is about REQUESTS, not canned results."""
    calls = {}

    def fake_run_leg(name, args, platform):
        if requested is not None:
            requested.append((name, platform))
        seq = leg_results[name]
        result = seq[min(calls.get(name, 0), len(seq) - 1)]
        calls[name] = calls.get(name, 0) + 1
        return result

    monkeypatch.setattr(bench, "_run_leg", fake_run_leg)
    import ipc_proofs_tpu.utils.platform as plat

    monkeypatch.setattr(plat, "pick_platform", lambda *a, **k: "default")
    bench._orchestrate(_args())
    return json.loads(capsys.readouterr().out.strip())


_SERVE_OK = {
    "serve_batched_rps": 2000.0, "serve_sequential_rps": 800.0,
    "serve_speedup_vs_sequential": 2.5, "serve_concurrency": 32,
    "serve_requests": 256, "serve_p99_latency_ms": 9.5,
    "serve_mean_batch": 24.0, "serve_rejections": 0,
}

_WITNESS_OK = {
    "witness_reduction_pct": 96.0, "witness_two_pass_bytes": 25_000,
    "witness_single_pass_bytes": 650_000, "witness_sample_pairs": 64,
    "witness_bytes_per_proof_k1": 14_800.0,
    "witness_bytes_per_proof_k16": 3_700.0,
    "witness_bytes_per_proof_k256": 290.0,
    "witness_delta_ratio": 0.49, "witness_compressed_ratio": 0.26,
}

_RESILIENCE_OK = {
    "resilience_fault_free_proofs_per_sec": 750.0,
    "integrity_overhead_pct": 1.2,
    "proofs_per_sec_at_fault_rate": 430.0,
    "resilience_fault_rate": 0.1,
    "recovery_ms": 0.05,
}

_DURABILITY_OK = {
    "durability_journal_overhead_pct": 3.5,
    "durability_resume_ms": 25.0,
    "durability_replay_chunks_per_sec": 850.0,
    "durability_journal_bytes": 1_700_000,
    "durability_chunks": 6,
}

_OBSERVABILITY_OK = {
    "trace_overhead_pct": 0.8,
    "spans_per_proof": 0.1,
    "observability_spans_recorded": 19,
    "observability_spans_dropped": 0,
    "observability_pairs": 48,
}

_STORAGE_OK = {
    "cold_vs_warm_speedup": 5.9,
    "disk_hit_ratio": 1.0,
    "prefetch_hit_ratio": 0.18,
    "storage_cold_rpc_calls": 541,
    "storage_warm_rpc_calls": 0,
    "storage_prefetched_blocks": 101,
    "storage_disk_bytes": 260_000,
    "storage_pairs": 12,
}

_ASYNCFETCH_OK = {
    "cold_rpc_roundtrips_per_proof": 3.62,
    "sync_rpc_roundtrips_per_proof": 13.87,
    "cold_speedup_vs_sync_walker": 2.98,
    "speculate_waste_pct": 41.69,
    "asyncfetch_batch_calls": 61,
    "asyncfetch_cold_rpc_calls": 141,
    "asyncfetch_sync_rpc_calls": 541,
    "asyncfetch_pairs": 12,
}

_CLUSTER_OK = {
    "aggregate_proofs_per_sec": 720.0,
    "cluster_linearity_4shard": 0.85,
    "steal_events": 8,
    "cluster_rps_1shard": 430.0,
    "cluster_rps_4shard": 1460.0,
    "cluster_pairs": 16,
    "cluster_requests": 64,
}

_STANDING_OK = {
    "standing_proofs_pushed_per_sec_1k": 5400.0,
    "standing_proofs_pushed_per_sec_10k": 5200.0,
    "standing_delivery_lag_p50_ms": 950.0,
    "standing_delivery_lag_p99_ms": 2200.0,
    "standing_subscriptions": 10_000,
    "standing_tipsets": 3,
    "standing_distinct_filters": 2,
    "standing_generations_per_tipset": 2.0,
}

_FLEETOBS_OK = {
    "fleetobs_overhead_pct": 1.4,
    "fleetobs_rps_plain": 430.0,
    "fleetobs_rps_observed": 424.0,
    "fleetobs_stitched_spans": 16,
    "fleetobs_scrapes": 6,
    "fleetobs_pairs": 16,
    "fleetobs_requests": 64,
}

_ONCHIP_OK = {
    "device_linearity_Nchip": 0.92,
    "batch_verify_speedup": 4.1,
    "onchip_devices": 4,
    "onchip_match_events": 1 << 20,
    "onchip_verify_blocks": 1024,
    "onchip_device_calls": 2,
    "verify_tuned_speedup": 4.0,
    "verify_autotune_scalar_only": False,
    "verify_autotuned_min_bytes": 262144,
}

_ZEROCOPY_OK = {
    "warm_block_bytes_copied_per_resp": 0.0,
    "stream_ttfb_ms": 4.4,
    "qos_light_tenant_p99_ms": 9.0,
    "qos_light_tenant_p50_ms": 3.0,
    "qos_heavy_backlog_drain_ms": 120.0,
    "zerocopy_bytes_per_resp": 2323,
    "zerocopy_responses": 16,
    "qos_heavy_concurrency": 6,
    "qos_heavy_requests": 800,
    "zerocopy_host_cpus": 4,
}

_HOSTKILL_OK = {
    "aggregate_proofs_per_sec_2host": 514.6,
    "replica_repair_hit_rate": 1.0,
    "kill_recovery_ms": 99.3,
    "hostkill_pairs": 8,
    "hostkill_requests": 64,
    "hostkill_failovers": 2,
}

_OVERLOAD_OK = {
    "goodput_ratio_at_2x": 0.97,
    "shed_rate": 0.41,
    "light_tenant_p99_ms_overload": 18.5,
    "cancel_reclaim_pct": 62.0,
    "overload_capacity_rps": 540.0,
    "overload_goodput_rps": 1048.0,
    "overload_requests": 1270,
    "overload_doomed_requests": 54,
    "overload_admit_limit_final": 61,
    "overload_host_cpus": 4,
}

_REGISTRY_OK = {
    "registry_append_overhead_pct": 0.6,
    "registry_append_us": 26.7,
    "registry_inclusion_proof_ms": 2.3,
    "fleet_delta_hit_rate": 1.0,
    "fleet_delta_baseline_hit_rate": 0.19,
    "registry_chain_records": 2048,
    "registry_serve_requests": 96,
    "registry_shards": 4,
    "registry_lookups": 32,
}

_BACKFILL_OK = {
    "backfill_epochs_per_sec": 95.0,
    "backfill_epochs_per_sec_1shard": 30.0,
    "backfill_ttfc_ms": 140.0,
    "backfill_total_ms": 670.0,
    "backfill_occupancy_pct": 61.0,
    "backfill_windows": 8,
    "backfill_epochs": 64,
    "backfill_shards": 4,
}

_E2E_OK = {
    "metric": "event_proofs_per_sec_4k_range_e2e",
    "value": 5000.0,
    "unit": "proofs/s",
    "platform": "cpu",
    "devices": 1,
    "host_cores": 1,
    "scan_threads": 1,
    "pipeline_chunk": 4096,
    "events_per_sec_e2e": 2e6,
    "proofs": 656,
    "stages_ms": {"scan": 50.0},
    "stages_overlap": False,
}


class TestOrchestrate:
    def test_happy_path_ratios(self, monkeypatch, capsys):
        out = _orchestrate_with(monkeypatch, capsys, {
            "e2e": [(dict(_E2E_OK, platform="tpu"), "ok:tpu")],
            "kernel": [({"device_mask_kernel_events_per_sec": 6e9}, "ok:tpu")],
            "cid": [({"witness_cid_kernel_per_sec": 1e8}, "ok:tpu")],
            "onchip": [(dict(_ONCHIP_OK), "ok:tpu")],
            "baseline": [({"scalar_baseline_proofs_per_sec": 125.0}, "ok:cpu")],
            "native_baseline": [({"native_baseline_proofs_per_sec": 1000.0}, "ok:cpu")],
            "serve": [(dict(_SERVE_OK), "ok:cpu")],
            "witness": [(dict(_WITNESS_OK), "ok:cpu")],
            "resilience": [(dict(_RESILIENCE_OK), "ok:cpu")],
            "durability": [(dict(_DURABILITY_OK), "ok:cpu")],
            "observability": [(dict(_OBSERVABILITY_OK), "ok:cpu")],
            "storage": [(dict(_STORAGE_OK), "ok:cpu")],
            "asyncfetch": [(dict(_ASYNCFETCH_OK), "ok:cpu")],
            "cluster": [(dict(_CLUSTER_OK), "ok:cpu")],
            "standing": [(dict(_STANDING_OK), "ok:cpu")],
            "fleetobs": [(dict(_FLEETOBS_OK), "ok:cpu")],
            "backfill": [(dict(_BACKFILL_OK), "ok:cpu")],
            "zerocopy": [(dict(_ZEROCOPY_OK), "ok:cpu")],
            "hostkill": [(dict(_HOSTKILL_OK), "ok:cpu")],
            "overload": [(dict(_OVERLOAD_OK), "ok:cpu")],
            "registry": [(dict(_REGISTRY_OK), "ok:cpu")],
        })
        assert out["value"] == 5000.0
        assert out["vs_baseline"] == 40.0
        assert out["vs_native_baseline"] == 5.0
        assert out["watchdog_fallback"] is False
        assert out["legs"]["e2e"] == "ok:tpu"
        assert out["legs"]["serve"] == "ok:cpu"
        assert out["legs"]["resilience"] == "ok:cpu"
        assert out["legs"]["durability"] == "ok:cpu"
        assert out["serve_speedup_vs_sequential"] == 2.5
        assert out["witness_reduction_pct"] == 96.0
        assert out["integrity_overhead_pct"] == 1.2
        assert out["proofs_per_sec_at_fault_rate"] == 430.0
        assert out["durability_journal_overhead_pct"] == 3.5
        assert out["legs"]["observability"] == "ok:cpu"
        assert out["trace_overhead_pct"] == 0.8
        assert out["spans_per_proof"] == 0.1
        assert out["legs"]["storage"] == "ok:cpu"
        assert out["cold_vs_warm_speedup"] == 5.9
        assert out["storage_warm_rpc_calls"] == 0
        assert out["legs"]["cluster"] == "ok:cpu"
        assert out["cluster_linearity_4shard"] == 0.85
        assert out["aggregate_proofs_per_sec"] == 720.0
        assert out["steal_events"] == 8
        assert out["legs"]["asyncfetch"] == "ok:cpu"
        assert out["cold_rpc_roundtrips_per_proof"] == 3.62
        assert out["sync_rpc_roundtrips_per_proof"] == 13.87
        assert out["cold_speedup_vs_sync_walker"] == 2.98
        assert out["speculate_waste_pct"] == 41.69
        assert out["legs"]["onchip"] == "ok:tpu"
        assert out["device_linearity_Nchip"] == 0.92
        assert out["batch_verify_speedup"] == 4.1
        assert out["onchip_devices"] == 4
        assert out["legs"]["standing"] == "ok:cpu"
        assert out["standing_proofs_pushed_per_sec_10k"] == 5200.0
        assert out["standing_generations_per_tipset"] == 2.0
        assert out["legs"]["fleetobs"] == "ok:cpu"
        assert out["fleetobs_overhead_pct"] == 1.4
        assert out["fleetobs_stitched_spans"] == 16
        assert out["legs"]["backfill"] == "ok:cpu"
        assert out["backfill_epochs_per_sec"] == 95.0
        assert out["backfill_ttfc_ms"] == 140.0
        assert out["verify_tuned_speedup"] == 4.0
        assert out["verify_autotune_scalar_only"] is False
        assert out["legs"]["zerocopy"] == "ok:cpu"
        assert out["warm_block_bytes_copied_per_resp"] == 0.0
        assert out["stream_ttfb_ms"] == 4.4
        assert out["qos_light_tenant_p99_ms"] == 9.0
        assert out["legs"]["hostkill"] == "ok:cpu"
        assert out["aggregate_proofs_per_sec_2host"] == 514.6
        assert out["replica_repair_hit_rate"] == 1.0
        assert out["kill_recovery_ms"] == 99.3
        assert out["legs"]["registry"] == "ok:cpu"
        assert out["registry_append_overhead_pct"] == 0.6
        assert out["registry_inclusion_proof_ms"] == 2.3
        assert out["fleet_delta_hit_rate"] == 1.0
        assert out["fleet_delta_baseline_hit_rate"] == 0.19

    def test_stalled_e2e_downgrades_and_retries_on_cpu(self, monkeypatch, capsys):
        requested = []
        out = _orchestrate_with(monkeypatch, capsys, {
            "e2e": [(None, "timeout:default"), (dict(_E2E_OK), "ok:cpu")],
            "kernel": [({"device_mask_kernel_events_per_sec": 1e8}, "ok:cpu")],
            "cid": [({"witness_cid_kernel_per_sec": 1e4}, "ok:cpu")],
            "onchip": [(dict(_ONCHIP_OK, onchip_devices=1), "ok:cpu")],
            "baseline": [({"scalar_baseline_proofs_per_sec": 100.0}, "ok:cpu")],
            "native_baseline": [({"native_baseline_proofs_per_sec": 800.0}, "ok:cpu")],
            "serve": [(dict(_SERVE_OK), "ok:cpu")],
            "witness": [(dict(_WITNESS_OK), "ok:cpu")],
            "resilience": [(dict(_RESILIENCE_OK), "ok:cpu")],
            "durability": [(dict(_DURABILITY_OK), "ok:cpu")],
            "observability": [(dict(_OBSERVABILITY_OK), "ok:cpu")],
            "storage": [(dict(_STORAGE_OK), "ok:cpu")],
            "asyncfetch": [(dict(_ASYNCFETCH_OK), "ok:cpu")],
            "cluster": [(dict(_CLUSTER_OK), "ok:cpu")],
            "standing": [(dict(_STANDING_OK), "ok:cpu")],
            "fleetobs": [(dict(_FLEETOBS_OK), "ok:cpu")],
            "backfill": [(dict(_BACKFILL_OK), "ok:cpu")],
            "zerocopy": [(dict(_ZEROCOPY_OK), "ok:cpu")],
            "hostkill": [(dict(_HOSTKILL_OK), "ok:cpu")],
            "overload": [(dict(_OVERLOAD_OK), "ok:cpu")],
            "registry": [(dict(_REGISTRY_OK), "ok:cpu")],
        }, requested=requested)
        assert out["watchdog_fallback"] is True
        assert out["legs"]["e2e"] == "timeout:default → ok:cpu"
        assert out["value"] == 5000.0
        assert out["vs_baseline"] == 50.0
        # after the e2e STALL the device legs must actually be REQUESTED on
        # cpu (not just reported as cpu by the canned results)
        assert requested == [
            ("e2e", "default"), ("e2e", "cpu"), ("kernel", "cpu"),
            ("cid", "cpu"), ("onchip", "cpu"), ("baseline", "cpu"),
            ("native_baseline", "cpu"), ("serve", "cpu"), ("witness", "cpu"),
            ("resilience", "cpu"), ("durability", "cpu"),
            ("observability", "cpu"), ("storage", "cpu"),
            ("asyncfetch", "cpu"), ("cluster", "cpu"), ("standing", "cpu"),
            ("fleetobs", "cpu"), ("backfill", "cpu"), ("zerocopy", "cpu"),
            ("hostkill", "cpu"), ("overload", "cpu"), ("registry", "cpu"),
        ]

    def test_stalled_secondary_leg_costs_only_itself(self, monkeypatch, capsys):
        out = _orchestrate_with(monkeypatch, capsys, {
            "e2e": [(dict(_E2E_OK, platform="tpu"), "ok:tpu")],
            "kernel": [(None, "timeout:default")],
            "cid": [({"witness_cid_kernel_per_sec": 1e4}, "ok:cpu")],
            "onchip": [(dict(_ONCHIP_OK), "ok:cpu")],
            "baseline": [({"scalar_baseline_proofs_per_sec": 100.0}, "ok:cpu")],
            "native_baseline": [({"native_baseline_proofs_per_sec": 800.0}, "ok:cpu")],
            "serve": [(dict(_SERVE_OK), "ok:cpu")],
            "witness": [(dict(_WITNESS_OK), "ok:cpu")],
            "resilience": [(dict(_RESILIENCE_OK), "ok:cpu")],
            "durability": [(dict(_DURABILITY_OK), "ok:cpu")],
            "observability": [(dict(_OBSERVABILITY_OK), "ok:cpu")],
            "storage": [(dict(_STORAGE_OK), "ok:cpu")],
            "asyncfetch": [(dict(_ASYNCFETCH_OK), "ok:cpu")],
            "cluster": [(dict(_CLUSTER_OK), "ok:cpu")],
            "standing": [(dict(_STANDING_OK), "ok:cpu")],
            "fleetobs": [(dict(_FLEETOBS_OK), "ok:cpu")],
            "backfill": [(dict(_BACKFILL_OK), "ok:cpu")],
            "zerocopy": [(dict(_ZEROCOPY_OK), "ok:cpu")],
            "hostkill": [(dict(_HOSTKILL_OK), "ok:cpu")],
            "overload": [(dict(_OVERLOAD_OK), "ok:cpu")],
            "registry": [(dict(_REGISTRY_OK), "ok:cpu")],
        })
        assert out["value"] == 5000.0  # headline survives
        assert out["device_mask_kernel_events_per_sec"] is None
        assert out["witness_cid_kernel_per_sec"] == 1e4
        assert out["watchdog_fallback"] is True

    def test_fast_crash_keeps_the_chip(self, monkeypatch, capsys):
        """A leg that CRASHES quickly (rc!=0) is not a tunnel stall: later
        device legs must still be requested on the chip platform and
        watchdog_fallback must stay False."""
        requested = []

        def fake_run_leg(name, args, platform):
            requested.append((name, platform))
            if name == "kernel":
                return None, f"error:{platform}"
            if name == "e2e":
                return dict(_E2E_OK, platform="tpu"), "ok:tpu"
            if name == "cid":
                return {"witness_cid_kernel_per_sec": 1e8}, "ok:tpu"
            return {f"{'scalar' if name == 'baseline' else 'native'}_baseline_proofs_per_sec": 100.0}, "ok:cpu"

        monkeypatch.setattr(bench, "_run_leg", fake_run_leg)
        import ipc_proofs_tpu.utils.platform as plat

        monkeypatch.setattr(plat, "pick_platform", lambda *a, **k: "default")
        bench._orchestrate(_args())
        out = json.loads(capsys.readouterr().out.strip())
        assert ("cid", "default") in requested  # chip NOT forfeited
        assert out["watchdog_fallback"] is False
        assert out["device_mask_kernel_events_per_sec"] is None
        assert out["legs"]["kernel"] == "error:default"

    def test_total_failure_emits_full_null_schema(self, monkeypatch, capsys):
        out = _orchestrate_with(monkeypatch, capsys, {
            "e2e": [(None, "timeout:default"), (None, "timeout:cpu")],
            "kernel": [(None, "timeout:cpu")],
            "cid": [(None, "timeout:cpu")],
            "onchip": [(None, "timeout:cpu")],
            "baseline": [(None, "error:cpu")],
            "native_baseline": [(None, "error:cpu")],
            "serve": [(None, "error:cpu")],
            "witness": [(None, "error:cpu")],
            "resilience": [(None, "error:cpu")],
            "durability": [(None, "error:cpu")],
            "observability": [(None, "error:cpu")],
            "storage": [(None, "error:cpu")],
            "asyncfetch": [(None, "error:cpu")],
            "cluster": [(None, "error:cpu")],
            "standing": [(None, "error:cpu")],
            "fleetobs": [(None, "error:cpu")],
            "backfill": [(None, "error:cpu")],
            "zerocopy": [(None, "error:cpu")],
            "hostkill": [(None, "error:cpu")],
            "overload": [(None, "error:cpu")],
            "registry": [(None, "error:cpu")],
        })
        # the artifact still prints, with every headline key present + null
        for key in (
            "value", "platform", "devices", "host_cores", "scan_threads",
            "pipeline_chunk", "events_per_sec_e2e", "proofs", "stages_ms",
            "stages_overlap", "vs_baseline", "vs_native_baseline",
            "device_mask_kernel_events_per_sec", "witness_cid_kernel_per_sec",
            "serve_speedup_vs_sequential", "serve_batched_rps",
            "witness_reduction_pct", "integrity_overhead_pct",
            "proofs_per_sec_at_fault_rate", "recovery_ms",
            "durability_journal_overhead_pct", "durability_resume_ms",
            "trace_overhead_pct", "spans_per_proof",
            "cold_vs_warm_speedup", "disk_hit_ratio", "prefetch_hit_ratio",
            "cold_rpc_roundtrips_per_proof", "sync_rpc_roundtrips_per_proof",
            "cold_speedup_vs_sync_walker", "speculate_waste_pct",
            "cluster_linearity_4shard", "aggregate_proofs_per_sec",
            "steal_events", "device_linearity_Nchip", "batch_verify_speedup",
            "standing_proofs_pushed_per_sec_1k",
            "standing_proofs_pushed_per_sec_10k",
            "standing_delivery_lag_p50_ms", "standing_delivery_lag_p99_ms",
            "standing_generations_per_tipset",
            "fleetobs_overhead_pct", "fleetobs_rps_plain",
            "fleetobs_rps_observed", "fleetobs_stitched_spans",
            "verify_tuned_speedup", "verify_autotune_scalar_only",
            "verify_autotuned_min_bytes", "backfill_epochs_per_sec",
            "backfill_ttfc_ms", "backfill_total_ms",
            "backfill_occupancy_pct", "warm_block_bytes_copied_per_resp",
            "stream_ttfb_ms", "qos_light_tenant_p99_ms",
            "zerocopy_bytes_per_resp",
            "aggregate_proofs_per_sec_2host", "replica_repair_hit_rate",
            "kill_recovery_ms",
            "goodput_ratio_at_2x", "shed_rate",
            "light_tenant_p99_ms_overload", "cancel_reclaim_pct",
            "overload_capacity_rps", "overload_goodput_rps",
            "registry_append_overhead_pct", "registry_inclusion_proof_ms",
            "fleet_delta_hit_rate", "fleet_delta_baseline_hit_rate",
        ):
            assert key in out and out[key] is None, key
        assert out["legs"]["e2e"] == "timeout:default → timeout:cpu"
        assert out["watchdog_fallback"] is True
