"""Blockstore stack tests: memory, recording, cached, fake-RPC."""

import pytest

from ipc_proofs_tpu.core.cid import CID, RAW
from ipc_proofs_tpu.store.blockstore import (
    CachedBlockstore,
    MemoryBlockstore,
    RecordingBlockstore,
    put_cbor,
)
from ipc_proofs_tpu.store.rpc import RpcBlockstore
from ipc_proofs_tpu.store.testing import FakeLotusClient


def _put(store, data: bytes) -> CID:
    cid = CID.hash_of(data, codec=RAW)
    store.put_keyed(cid, data)
    return cid


class TestMemoryBlockstore:
    def test_put_get_has(self):
        bs = MemoryBlockstore()
        cid = _put(bs, b"hello")
        assert bs.get(cid) == b"hello"
        assert bs.has(cid)
        assert not bs.has(CID.hash_of(b"other"))
        assert bs.get(CID.hash_of(b"other")) is None

    def test_verify_cids_rejects_mismatch(self):
        bs = MemoryBlockstore(verify_cids=True)
        wrong_cid = CID.hash_of(b"not this data", codec=RAW)
        with pytest.raises(ValueError):
            bs.put_keyed(wrong_cid, b"actual data")

    def test_verify_cids_accepts_match(self):
        bs = MemoryBlockstore(verify_cids=True)
        cid = _put(bs, b"ok")
        assert bs.get(cid) == b"ok"


class TestRecordingBlockstore:
    def test_records_gets_only(self):
        inner = MemoryBlockstore()
        c1 = _put(inner, b"one")
        c2 = _put(inner, b"two")
        rec = RecordingBlockstore(inner)
        rec.get(c1)
        rec.get(c1)  # duplicate
        missing = CID.hash_of(b"missing")
        rec.get(missing)  # even misses are recorded (matches reference)
        seen = rec.take_seen()
        assert seen == {c1, missing}
        assert c2 not in seen
        # drained
        assert rec.take_seen() == set()

    def test_passthrough(self):
        inner = MemoryBlockstore()
        rec = RecordingBlockstore(inner)
        cid = _put(rec, b"through")
        assert inner.get(cid) == b"through"


class TestCachedBlockstore:
    def test_hit_miss_accounting(self):
        inner = MemoryBlockstore()
        cid = _put(inner, b"data")
        cached = CachedBlockstore(inner)
        assert cached.get(cid) == b"data"
        assert cached.get(cid) == b"data"
        assert cached.hits == 1 and cached.misses == 1

    def test_shared_cache_across_instances(self):
        inner1 = MemoryBlockstore()
        cid = _put(inner1, b"payload")
        c1 = CachedBlockstore(inner1)
        c1.get(cid)
        # second instance over an EMPTY inner store, sharing the cache
        c2 = CachedBlockstore.with_shared_cache(MemoryBlockstore(), c1.shared_cache())
        assert c2.get(cid) == b"payload"
        assert c2.hits == 1 and c2.misses == 0

    def test_cache_stats(self):
        inner = MemoryBlockstore()
        cid = _put(inner, b"12345")
        cached = CachedBlockstore(inner)
        cached.get(cid)
        entries, total = cached.cache_stats()
        assert entries == 1 and total == 5


class TestFakeRpc:
    def test_chain_read_obj_roundtrip(self):
        backing = MemoryBlockstore()
        cid = put_cbor(backing, [1, 2, 3])
        client = FakeLotusClient(backing)
        bs = RpcBlockstore(client)
        data = bs.get(cid)
        assert data is not None
        assert CID.hash_of(data) == cid

    def test_canned_responses(self):
        client = FakeLotusClient(MemoryBlockstore(), responses={"Filecoin.StateLookupID": "f0123"})
        assert client.request("Filecoin.StateLookupID", ["f410f...", None]) == "f0123"
        assert client.calls[-1][0] == "Filecoin.StateLookupID"

    def test_rpc_blockstore_readonly(self):
        bs = RpcBlockstore(FakeLotusClient(MemoryBlockstore()))
        with pytest.raises(NotImplementedError):
            bs.put_keyed(CID.hash_of(b"x"), b"x")


class TestPutCbor:
    def test_txmeta_style_recompute(self):
        bs = MemoryBlockstore()
        c1 = CID.hash_of(b"bls")
        c2 = CID.hash_of(b"secp")
        txmeta_cid = put_cbor(bs, (c1, c2))
        raw = bs.get(txmeta_cid)
        assert raw is not None
        assert CID.hash_of(raw) == txmeta_cid


class TestBulkLoadBlocks:
    """C bulk loader ≡ the Python loop: same maps, same partial-load-on-
    error semantics, same acceptance of buffer-protocol data."""

    def test_matches_python_loop_and_mutation_counter(self):
        from ipc_proofs_tpu.backend.native import load_scan_ext
        from ipc_proofs_tpu.core.cid import CID
        from ipc_proofs_tpu.proofs.bundle import ProofBlock
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

        ext = load_scan_ext()
        if ext is None or not hasattr(ext, "bulk_load_blocks"):
            import pytest

            pytest.skip("extension predates bulk_load_blocks")
        blocks = [
            ProofBlock._make(CID.hash_of(bytes([i])), bytes([i]) * 3)
            for i in range(50)
        ]
        fast = MemoryBlockstore()
        v0 = fast._mutations
        fast.put_many_trusted(blocks)
        assert fast._mutations > v0  # snapshot invalidation happened
        slow = MemoryBlockstore()
        cid_map, raw_map = slow._blocks, slow._raw
        for b in blocks:
            data = bytes(b.data)
            cid_map[b.cid] = data
            raw_map[b.cid.to_bytes()] = data
        assert fast._blocks == slow._blocks
        assert fast._raw == slow._raw

    def test_memoryview_data_and_bad_data_type(self):
        """Both the C fast path and the Python fallback accept buffer-
        protocol data and reject int data with TypeError (bytes(int) would
        silently mean 'n zero bytes'), leaving blocks BEFORE the failing
        one loaded — partial-load-on-error parity."""
        import pytest

        from ipc_proofs_tpu.core.cid import CID
        from ipc_proofs_tpu.proofs.bundle import ProofBlock
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

        cid = CID.hash_of(b"mv")
        bs = MemoryBlockstore()
        bs.put_many_trusted([ProofBlock._make(cid, memoryview(b"mv-data"))])
        assert bs.get(cid) == b"mv-data"
        v = bs._mutations
        good = ProofBlock._make(CID.hash_of(b"good"), b"good-data")
        with pytest.raises(TypeError):
            bs.put_many_trusted([good, ProofBlock._make(CID.hash_of(b"x"), 123)])
        assert bs._mutations > v  # even a failed load invalidates
        assert bs.get(good.cid) == b"good-data"  # prefix landed (both paths)

    def test_bytes_subclass_stored_as_exact_bytes(self):
        """A bytes SUBCLASS must round-trip through the loader as plain
        bytes, not be trusted as-is: PyBytes_Check alone would let a
        subclass with overridden behavior sit in the store and break the
        `fast._blocks == slow._blocks`-style equality the scan relies on.
        The C path gates on PyBytes_CheckExact and falls through to
        PyBytes_FromObject for everything else."""
        from ipc_proofs_tpu.core.cid import CID
        from ipc_proofs_tpu.proofs.bundle import ProofBlock
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

        class TaggedBytes(bytes):
            pass

        cid = CID.hash_of(b"sub")
        bs = MemoryBlockstore()
        bs.put_many_trusted([ProofBlock._make(cid, TaggedBytes(b"sub-data"))])
        got = bs.get(cid)
        assert got == b"sub-data"
        assert type(got) is bytes  # normalized, not the subclass
