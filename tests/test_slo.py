"""SLO burn-rate watchdog: deterministic burn grids under an injected
clock — no sleeps, no threads (except the lifecycle test), no network.

The grid tests drive `SloWatchdog.sample()` by hand: tick counters on a
private `Metrics`, advance the fake clock, and assert the ok → warn →
burning ladder, the hysteretic recovery, the zero-tolerance integrity
target, and the anomaly signatures — exactly the transitions the serving
daemon's `/healthz` ``slo`` block surfaces.
"""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from ipc_proofs_tpu.obs.flight import get_flight_recorder
from ipc_proofs_tpu.obs.slo import SloTarget, SloWatchdog, default_targets
from ipc_proofs_tpu.utils.metrics import Metrics


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _watchdog(metrics, clock, **kw):
    kw.setdefault("fast_window_s", 300.0)
    kw.setdefault("slow_window_s", 3600.0)
    return SloWatchdog(
        metrics=metrics, clock=clock, recovery_samples=3, **kw
    )


@pytest.fixture(autouse=True)
def _clean_flight_ring():
    get_flight_recorder().clear()
    yield
    get_flight_recorder().clear()


# --------------------------------------------------------------------------
# ratio target: the ok → warn → burning grid
# --------------------------------------------------------------------------


class TestRatioBurnGrid:
    def _availability(self):
        return SloTarget(
            name="availability",
            kind="ratio",
            objective=0.999,  # 0.1 % error budget
            bad=("serve.rejected_full.*",),
            total=("serve.accepted.*", "serve.rejected_full.*"),
        )

    def test_all_good_stays_ok(self):
        m, clock = Metrics(), FakeClock()
        dog = _watchdog(m, clock, targets=[self._availability()])
        for _ in range(5):
            m.count("serve.accepted.verify", 100)
            status = dog.sample(clock.advance(10))
        assert status["status"] == "ok"
        assert status["targets"]["availability"]["fast_burn"] == 0.0
        assert m.counter_value("slo.evaluations") == 5

    def test_moderate_errors_warn(self):
        m, clock = Metrics(), FakeClock()
        dog = _watchdog(m, clock, targets=[self._availability()])
        dog.sample(clock.t)  # baseline
        # 0.5 % bad over a 0.1 % budget → burn 5× in both windows:
        # fast ≥ warn(2) but < page(10) → warn
        m.count("serve.accepted.verify", 995)
        m.count("serve.rejected_full.verify", 5)
        status = dog.sample(clock.advance(10))
        target = status["targets"]["availability"]
        assert target["state"] == "warn"
        assert target["fast_burn"] == pytest.approx(5.0, rel=1e-3)
        assert status["status"] == "warn"
        assert m.counter_value("slo.warn_transitions") == 1

    def test_sharp_sustained_errors_burn(self):
        m, clock = Metrics(), FakeClock()
        dog = _watchdog(m, clock, targets=[self._availability()])
        dog.sample(clock.t)
        # 5 % bad → burn 50×: fast ≥ page AND slow ≥ warn → burning
        m.count("serve.accepted.verify", 950)
        m.count("serve.rejected_full.verify", 50)
        status = dog.sample(clock.advance(10))
        assert status["targets"]["availability"]["state"] == "burning"
        assert m.counter_value("slo.burn_transitions") == 1
        # escalation leaves a WARNING in the flight ring
        logs = get_flight_recorder().snapshot()["logs"]
        assert any(
            "availability -> burning" in e["msg"] and e["level"] == "WARNING"
            for e in logs
        )

    def test_single_sample_window_burns_zero(self):
        m, clock = Metrics(), FakeClock()
        dog = _watchdog(m, clock, targets=[self._availability()])
        m.count("serve.rejected_full.verify", 1000)  # before ANY baseline
        status = dog.sample(clock.t)
        # one sample = no delta = no verdict; never fires off the bat
        assert status["status"] == "ok"

    def test_recovery_is_hysteretic(self):
        m, clock = Metrics(), FakeClock()
        dog = _watchdog(m, clock, targets=[self._availability()],
                        fast_window_s=30.0, slow_window_s=60.0)
        dog.sample(clock.t)
        m.count("serve.accepted.verify", 950)
        m.count("serve.rejected_full.verify", 50)
        assert (
            dog.sample(clock.advance(10))["targets"]["availability"]["state"]
            == "burning"
        )
        # quiet evals AFTER the bad delta ages out of both windows:
        # two are not enough (recovery_samples=3)…
        for _ in range(2):
            m.count("serve.accepted.verify", 100)
            status = dog.sample(clock.advance(40))
            assert status["targets"]["availability"]["state"] == "burning"
        # …the third closes the loop, straight back to ok
        m.count("serve.accepted.verify", 100)
        status = dog.sample(clock.advance(40))
        assert status["targets"]["availability"]["state"] == "ok"
        assert m.counter_value("slo.recoveries") == 1

    def test_flap_resets_recovery_streak(self):
        m, clock = Metrics(), FakeClock()
        dog = _watchdog(m, clock, targets=[self._availability()],
                        fast_window_s=30.0, slow_window_s=60.0)
        dog.sample(clock.t)
        m.count("serve.accepted.verify", 950)
        m.count("serve.rejected_full.verify", 50)
        dog.sample(clock.advance(10))
        # two quiet evals…
        for _ in range(2):
            m.count("serve.accepted.verify", 100)
            dog.sample(clock.advance(40))
        # …then the signal flaps back: the streak must reset
        m.count("serve.accepted.verify", 950)
        m.count("serve.rejected_full.verify", 50)
        assert (
            dog.sample(clock.advance(10))["targets"]["availability"]["state"]
            == "burning"
        )
        for _ in range(2):
            m.count("serve.accepted.verify", 100)
            status = dog.sample(clock.advance(40))
            assert status["targets"]["availability"]["state"] == "burning"


# --------------------------------------------------------------------------
# quantile + zero-tolerance targets
# --------------------------------------------------------------------------


class TestQuantileAndZeroTargets:
    def test_p99_breach_warns(self):
        m, clock = Metrics(), FakeClock()
        target = SloTarget(
            name="generate_p99", kind="quantile", objective=0.99,
            hist="serve.latency_ms.generate", quantile="p99", limit_ms=100.0,
        )
        dog = _watchdog(m, clock, targets=[target])
        dog.sample(clock.t)
        # bulk fast, tail slow: p99 over the limit, p50/p90 under →
        # conservative 2 % bad over a 1 % budget = burn 2.0 → warn
        for _ in range(100):
            m.observe("serve.latency_ms.generate", 10.0)
        for _ in range(2):
            m.observe("serve.latency_ms.generate", 500.0)
        status = dog.sample(clock.advance(10))
        tgt = status["targets"]["generate_p99"]
        assert tgt["state"] == "warn"
        assert tgt["fast_burn"] == pytest.approx(2.0)

    def test_median_breach_burns(self):
        m, clock = Metrics(), FakeClock()
        target = SloTarget(
            name="generate_p99", kind="quantile", objective=0.99,
            hist="serve.latency_ms.generate", quantile="p99", limit_ms=100.0,
        )
        dog = _watchdog(m, clock, targets=[target])
        dog.sample(clock.t)
        for _ in range(50):
            m.observe("serve.latency_ms.generate", 500.0)
        status = dog.sample(clock.advance(10))
        # p50 over the limit → ≥ 50 % bad → burn 50× → page
        assert status["targets"]["generate_p99"]["state"] == "burning"

    def test_quantile_needs_new_observations(self):
        m, clock = Metrics(), FakeClock()
        target = SloTarget(
            name="generate_p99", kind="quantile", objective=0.99,
            hist="serve.latency_ms.generate", quantile="p99", limit_ms=100.0,
        )
        dog = _watchdog(m, clock, targets=[target])
        for _ in range(50):
            m.observe("serve.latency_ms.generate", 500.0)
        dog.sample(clock.t)
        # the breach predates the window's oldest sample; with NO new
        # observations between samples the count delta is zero → no burn
        status = dog.sample(clock.advance(10))
        assert status["targets"]["generate_p99"]["state"] == "ok"

    def test_integrity_zero_tolerance_first_tick_burns(self):
        m, clock = Metrics(), FakeClock()
        dog = _watchdog(m, clock, targets=list(default_targets()))
        dog.sample(clock.t)
        assert dog.status()["targets"]["integrity"]["state"] == "ok"
        m.count("rpc.integrity_failures")  # ONE tick
        status = dog.sample(clock.advance(5))
        assert status["targets"]["integrity"]["state"] == "burning"
        assert status["status"] == "burning"
        assert m.counter_value("slo.burn_transitions") == 1

    def test_integrity_recovers_after_window_drains(self):
        m, clock = Metrics(), FakeClock()
        dog = _watchdog(m, clock, targets=list(default_targets()),
                        fast_window_s=30.0, slow_window_s=60.0)
        dog.sample(clock.t)
        m.count("storex.integrity_evictions")
        assert (
            dog.sample(clock.advance(5))["targets"]["integrity"]["state"]
            == "burning"
        )
        for _ in range(2):
            assert (
                dog.sample(clock.advance(40))["targets"]["integrity"]["state"]
                == "burning"
            )
        assert (
            dog.sample(clock.advance(40))["targets"]["integrity"]["state"]
            == "ok"
        )


# --------------------------------------------------------------------------
# anomaly signatures
# --------------------------------------------------------------------------


class TestAnomalies:
    def test_breaker_flap_storm_fires_once_per_onset(self):
        m, clock = Metrics(), FakeClock()
        dog = _watchdog(m, clock, targets=[])
        dog.sample(clock.t)
        m.count("failover.breaker_open", 5)
        status = dog.sample(clock.advance(10))
        assert status["anomalies"] == ["breaker_flap_storm"]
        assert m.counter_value("slo.anomalies") == 1
        # still active next eval, but the onset counted only once
        status = dog.sample(clock.advance(10))
        assert status["anomalies"] == ["breaker_flap_storm"]
        assert m.counter_value("slo.anomalies") == 1
        logs = get_flight_recorder().snapshot()["logs"]
        assert sum("breaker_flap_storm" in e["msg"] for e in logs) == 1

    def test_anomaly_clears_when_window_drains(self):
        m, clock = Metrics(), FakeClock()
        dog = _watchdog(m, clock, targets=[], fast_window_s=30.0)
        dog.sample(clock.t)
        m.count("storex.evictions", 150)
        assert dog.sample(clock.advance(10))["anomalies"] == ["eviction_storm"]
        assert dog.sample(clock.advance(60))["anomalies"] == []

    def test_speculation_waste_needs_volume(self):
        m, clock = Metrics(), FakeClock()
        dog = _watchdog(m, clock, targets=[])
        dog.sample(clock.t)
        # 100 % waste but below the minimum want volume: not a spike
        m.count("fetch.speculative_wants", 5)
        m.count("fetch.speculative_wasted", 5)
        assert dog.sample(clock.advance(10))["anomalies"] == []
        m.count("fetch.speculative_wants", 40)
        m.count("fetch.speculative_wasted", 38)
        assert dog.sample(clock.advance(10))["anomalies"] == [
            "speculation_waste_spike"
        ]


# --------------------------------------------------------------------------
# lifecycle + healthz surface
# --------------------------------------------------------------------------


class TestLifecycleAndHealthz:
    def test_daemon_thread_samples_and_stops(self):
        m = Metrics()
        dog = SloWatchdog(metrics=m, targets=list(default_targets()),
                          interval_s=0.02)
        dog.start()
        try:
            deadline = time.monotonic() + 5.0
            while (
                m.counter_value("slo.evaluations") < 2
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            assert m.counter_value("slo.evaluations") >= 2
        finally:
            dog.stop()
        assert dog._thread is None  # joined; the leak sentinel agrees

    def test_healthz_carries_slo_block(self):
        from ipc_proofs_tpu.fixtures import build_range_world
        from ipc_proofs_tpu.proofs.generator import EventProofSpec
        from ipc_proofs_tpu.proofs.trust import TrustPolicy
        from ipc_proofs_tpu.serve import (
            ProofHTTPServer,
            ProofService,
            ServiceConfig,
        )

        sig, topic1 = "NewTopDownMessage(bytes32,uint256)", "calib-subnet-1"
        store, pairs, _ = build_range_world(2, signature=sig, topic1=topic1)
        metrics = Metrics()
        svc = ProofService(
            store=store,
            spec=EventProofSpec(event_signature=sig, topic_1=topic1),
            trust_policy=TrustPolicy.accept_all(),
            config=ServiceConfig(max_batch=4, workers=1),
            metrics=metrics,
        )
        clock = FakeClock()
        dog = SloWatchdog(metrics=metrics, targets=list(default_targets()),
                          clock=clock)
        dog.sample(clock.t)
        m2 = metrics
        m2.count("rpc.integrity_failures")
        dog.sample(clock.advance(5))
        httpd = ProofHTTPServer(svc, port=0, pairs=pairs, slo=dog).start()
        try:
            with urllib.request.urlopen(
                f"{httpd.address}/healthz", timeout=10
            ) as resp:
                health = json.load(resp)
            assert health["slo"]["status"] == "burning"
            assert health["slo"]["targets"]["integrity"]["state"] == "burning"
            assert set(health["slo"]["targets"]) == {
                "availability", "generate_p99", "delivery_lag_p99", "integrity",
            }
        finally:
            httpd.shutdown(timeout=10)
        # ProofHTTPServer.shutdown stops an attached watchdog
        assert dog._thread is None
