"""Filecoin RLE+ bitfields: vectors, strict canonicality, roundtrip fuzz.

The signers field of a go-f3 certificate is an RLE+ bitfield
(go-bitfield's wire format); `crypto/rleplus.py` implements it with the
spec's minimality rules. The decisive property, pinned by fuzz here: every
byte string either rejects or decodes to a value whose re-encoding is the
input — one serialization per bitfield, no malleability.
"""

import random

import pytest

from ipc_proofs_tpu.crypto.rleplus import decode_rleplus, encode_rleplus


class TestVectors:
    def test_empty(self):
        # go-bitfield's encoder emits the bare version header for an empty
        # bitfield; its decoder rejects zero-length input
        assert encode_rleplus([]) == bytes([0x00])
        assert decode_rleplus(bytes([0x00])) == []
        with pytest.raises(ValueError):
            decode_rleplus(b"")

    def test_bit_zero(self):
        # bits (LSB-first): 00 version, 1 first-run-value, 1 single-run
        assert encode_rleplus([0]) == bytes([0x0C])
        assert decode_rleplus(bytes([0x0C])) == [0]

    def test_bit_one(self):
        # 00 version, 0 first=zeros, 1 single zero-run, 1 single one-run
        assert encode_rleplus([1]) == bytes([0x18])
        assert decode_rleplus(bytes([0x18])) == [1]

    def test_short_and_long_blocks(self):
        idxs = list(range(2, 18))  # 0-run of 2 (short), 1-run of 16 (long)
        assert decode_rleplus(encode_rleplus(idxs)) == idxs

    def test_sparse_large(self):
        idxs = [0, 1000, 100000]
        assert decode_rleplus(encode_rleplus(idxs)) == idxs


class TestStrictness:
    @pytest.mark.parametrize(
        "bad",
        [
            bytes([0x01]),        # version bit 1
            bytes([0x02]),        # version bit 2
            bytes([0x04]),        # first=1 but no runs: non-minimal empty
            bytes([0x00, 0x00]),  # empty bitfield padded past one byte
        ],
    )
    def test_invalid_headers_rejected(self, bad):
        with pytest.raises(ValueError):
            decode_rleplus(bad)

    def test_max_bits_cap(self):
        huge = encode_rleplus([10**6])
        with pytest.raises(ValueError, match="exceeds"):
            decode_rleplus(huge, max_bits=1000)

    def test_unsorted_and_duplicate_indices_rejected(self):
        with pytest.raises(ValueError):
            encode_rleplus([3, 2])
        with pytest.raises(ValueError):
            encode_rleplus([2, 2])
        with pytest.raises(ValueError):
            encode_rleplus([-1])


class TestCanonicality:
    def test_roundtrip_fuzz(self):
        rng = random.Random(7)
        for _ in range(2000):
            idxs = sorted(rng.sample(range(300), rng.randrange(0, 50)))
            assert decode_rleplus(encode_rleplus(idxs)) == idxs

    def test_every_accepted_string_is_canonical(self):
        """Random blobs: accepted ⇒ re-encode equals input exactly."""
        rng = random.Random(8)
        accepted = rejected = 0
        for _ in range(20000):
            blob = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 10)))
            try:
                idxs = decode_rleplus(blob, max_bits=1 << 20)
            except ValueError:
                rejected += 1
                continue
            accepted += 1
            assert encode_rleplus(idxs) == blob, blob.hex()
        assert accepted and rejected
