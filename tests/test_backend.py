"""Backend seam tests: CPU (native + fallback) vs TPU(JAX) equivalence."""

import pytest

from ipc_proofs_tpu.backend import get_backend
from ipc_proofs_tpu.backend.cpu import CpuBackend
from ipc_proofs_tpu.core.hashes import blake2b_256, keccak256
from ipc_proofs_tpu.fixtures import EventFixture
from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature

MESSAGES = [b"", b"abc", b"x" * 135, b"y" * 136, b"z" * 1000, bytes(range(256))]

SIG = "NewTopDownMessage(bytes32,uint256)"
T0 = hash_event_signature(SIG)
T1 = ascii_to_bytes32("subnet-a")


def _events():
    return [
        EventFixture(emitter=7, signature=SIG, topic1="subnet-a").to_stamped(),
        EventFixture(emitter=7, signature=SIG, topic1="subnet-b").to_stamped(),
        EventFixture(emitter=9, signature=SIG, topic1="subnet-a").to_stamped(),
        EventFixture(emitter=7, signature="Other()", topic1="subnet-a").to_stamped(),
        EventFixture(emitter=7, signature=SIG, topic1="subnet-a", encoding="concat").to_stamped(),
    ]


class TestCpuBackend:
    def test_hashes_match_reference(self):
        backend = get_backend("cpu")
        assert backend.keccak256_batch(MESSAGES) == [keccak256(m) for m in MESSAGES]
        assert backend.blake2b256_batch(MESSAGES) == [blake2b_256(m) for m in MESSAGES]

    def test_python_fallback_matches_native(self):
        native = CpuBackend(use_native=True)
        fallback = CpuBackend(use_native=False)
        assert native.keccak256_batch(MESSAGES) == fallback.keccak256_batch(MESSAGES)
        assert native.blake2b256_batch(MESSAGES) == fallback.blake2b256_batch(MESSAGES)

    def test_native_available(self):
        import os

        if os.environ.get("IPC_PROOFS_NO_NATIVE"):
            pytest.skip("native paths disabled by IPC_PROOFS_NO_NATIVE")
        # g++ is baked into the image; the native path should build.
        assert CpuBackend().has_native

    def test_verify_block_cids(self):
        backend = get_backend("cpu")
        blocks = [b"block-a", b"block-b"]
        digests = [blake2b_256(b) for b in blocks]
        assert backend.verify_block_cids(digests, blocks)
        assert not backend.verify_block_cids(digests, [b"block-a", b"tampered"])

    def test_event_mask(self):
        backend = get_backend("cpu")
        mask = backend.event_match_mask(_events(), T0, T1, actor_id_filter=7)
        assert mask == [True, False, False, False, True]
        assert backend.any_event_matches(_events(), T0, T1, 7)
        assert not backend.any_event_matches(_events()[1:4], T0, T1, 7)


class TestTpuBackendEquivalence:
    @pytest.fixture(scope="class")
    def tpu(self):
        pytest.importorskip("jax")
        return get_backend("tpu")

    def test_hashes_match_cpu(self, tpu):
        cpu = get_backend("cpu")
        assert tpu.keccak256_batch(MESSAGES) == cpu.keccak256_batch(MESSAGES)
        assert tpu.blake2b256_batch(MESSAGES) == cpu.blake2b256_batch(MESSAGES)

    def test_event_mask_matches_cpu(self, tpu):
        cpu = get_backend("cpu")
        events = _events()
        for actor_filter in (None, 7, 9, 12345):
            assert tpu.event_match_mask(events, T0, T1, actor_filter) == cpu.event_match_mask(
                events, T0, T1, actor_filter
            ), f"filter={actor_filter}"

    def test_verify_block_cids(self, tpu):
        blocks = [b"block-%d" % i * (i + 1) for i in range(20)]
        digests = [blake2b_256(b) for b in blocks]
        assert tpu.verify_block_cids(digests, blocks)
        bad = list(blocks)
        bad[7] = b"evil"
        assert not tpu.verify_block_cids(digests, bad)

    def test_empty_batches(self, tpu):
        assert tpu.keccak256_batch([]) == []
        assert tpu.event_match_mask([], T0, T1, None) == []

    def test_match_crossover_host_vs_device_identical(self, tpu, monkeypatch):
        """The small-batch host crossover must produce bit-identical masks to
        the device kernels (both the full-width and fingerprint paths)."""
        import numpy as np

        from ipc_proofs_tpu.proofs.scan_native import topic_fingerprint

        rng = np.random.default_rng(7)
        n = 503  # odd, off-bucket size
        topics = rng.integers(0, 2**32, size=(n, 2, 8), dtype=np.uint32)
        # plant exact spec-topic hits in a random subset
        t0 = np.frombuffer(T0, dtype="<u4")
        t1 = np.frombuffer(T1, dtype="<u4")
        hit_rows = rng.choice(n, size=40, replace=False)
        topics[hit_rows, 0] = t0
        topics[hit_rows, 1] = t1
        n_topics = rng.integers(0, 4, size=n).astype(np.int32)
        emitters = rng.integers(0, 10, size=n).astype(np.uint64)
        valid = rng.random(n) < 0.9
        fp = np.array(
            [
                topic_fingerprint(topics[i, 0].tobytes(), topics[i, 1].tobytes())
                for i in range(n)
            ],
            dtype=np.uint64,
        )

        for actor in (None, 7):
            monkeypatch.setenv("IPC_TPU_MATCH_MIN_EVENTS", "1")
            dev_flat = np.asarray(
                tpu.event_match_mask_flat(topics, n_topics, emitters, valid, T0, T1, actor)
            )[:n]
            dev_fp = np.asarray(
                tpu.event_match_mask_fp(fp, n_topics, emitters, valid, T0, T1, actor)
            )[:n]
            monkeypatch.setenv("IPC_TPU_MATCH_MIN_EVENTS", str(1 << 40))
            host_flat = np.asarray(
                tpu.event_match_mask_flat(topics, n_topics, emitters, valid, T0, T1, actor)
            )[:n]
            host_fp = np.asarray(
                tpu.event_match_mask_fp(fp, n_topics, emitters, valid, T0, T1, actor)
            )[:n]
            assert (host_flat == dev_flat).all()
            assert (host_fp == dev_fp).all()
            assert (host_flat == host_fp).all()  # fp is injective over these rows


class TestBackendInProofGeneration:
    def test_event_generation_same_proofs_cpu_vs_tpu(self):
        pytest.importorskip("jax")
        from ipc_proofs_tpu.fixtures import ContractFixture, build_chain
        from ipc_proofs_tpu.proofs.generator import EventProofSpec, generate_proof_bundle

        events = [
            [EventFixture(emitter=500, signature=SIG, topic1="subnet-a")],
            [EventFixture(emitter=500, signature=SIG, topic1="other")],
            [],
            [EventFixture(emitter=501, signature=SIG, topic1="subnet-a")],
        ]
        world = build_chain([ContractFixture(actor_id=500)], events)
        spec = [EventProofSpec(event_signature=SIG, topic_1="subnet-a", actor_id_filter=500)]

        bundle_cpu = generate_proof_bundle(
            world.store, world.parent, world.child, [], spec, match_backend=get_backend("cpu")
        )
        bundle_tpu = generate_proof_bundle(
            world.store, world.parent, world.child, [], spec, match_backend=get_backend("tpu")
        )
        bundle_scalar = generate_proof_bundle(
            world.store, world.parent, world.child, [], spec, match_backend=None
        )
        assert bundle_cpu.to_json() == bundle_tpu.to_json() == bundle_scalar.to_json()
        assert len(bundle_cpu.event_proofs) == 1


def test_keccak_crossover_paths_agree(monkeypatch):
    """TpuBackend.keccak256_batch must return identical digests whether the
    batch crosses over to the host C++ path (default for small batches) or
    is forced onto the device/XLA kernel (IPC_TPU_KECCAK_MIN_BYTES=0)."""
    from ipc_proofs_tpu.backend.cpu import CpuBackend
    from ipc_proofs_tpu.backend.tpu import TpuBackend
    from ipc_proofs_tpu.core.hashes import keccak256

    msgs = [bytes([i]) * (7 + i) for i in range(20)] + [b"", b"x" * 200]
    expected = [keccak256(m) for m in msgs]
    tpu = TpuBackend()
    monkeypatch.delenv("IPC_TPU_KECCAK_MIN_BYTES", raising=False)
    assert tpu.keccak256_batch(msgs) == expected  # host-crossover side
    monkeypatch.setenv("IPC_TPU_KECCAK_MIN_BYTES", "0")
    assert tpu.keccak256_batch(msgs) == expected  # device/XLA side
    assert CpuBackend().keccak256_batch(msgs) == expected


class TestScanExtBatchVerify:
    """The scan-ext in-place batch verify (verify_blake2b_blocks) — the
    preferred verify_block_cids path — pinned against hashlib across block
    sizes, including the multi-block compression loop real witness nodes
    exercise (>128 B, exact multiples, 1 MB)."""

    def _ext(self):
        import pytest

        from ipc_proofs_tpu.backend.native import load_scan_ext

        ext = load_scan_ext()
        if ext is None or not hasattr(ext, "verify_blake2b_blocks"):
            pytest.skip("scan-ext batch verify unavailable")
        return ext

    def test_sizes_vs_hashlib(self):
        import hashlib

        ext = self._ext()
        sizes = [0, 1, 31, 64, 127, 128, 129, 200, 255, 256, 257, 384,
                 512, 1024, 4096, 1 << 20]
        blocks = [bytes((i * 7 + j) & 0xFF for j in range(s)) for i, s in enumerate(sizes)]
        digests = [hashlib.blake2b(b, digest_size=32).digest() for b in blocks]
        assert ext.verify_blake2b_blocks(digests, blocks) is True

    def test_tamper_detected_at_every_position(self):
        import hashlib

        ext = self._ext()
        blocks = [bytes([i]) * (80 + 60 * i) for i in range(8)]
        digests = [hashlib.blake2b(b, digest_size=32).digest() for b in blocks]
        for k in range(len(blocks)):
            bad = list(digests)
            bad[k] = bytes(32)
            assert ext.verify_blake2b_blocks(bad, blocks) is False, k
            flipped = list(blocks)
            flipped[k] = blocks[k][:-1] + bytes([blocks[k][-1] ^ 1])
            assert ext.verify_blake2b_blocks(digests, flipped) is False, k

    def test_buffer_protocol_inputs(self):
        import hashlib

        ext = self._ext()
        block = b"witness-node" * 20
        digest = hashlib.blake2b(block, digest_size=32).digest()
        assert ext.verify_blake2b_blocks(
            [bytearray(digest)], [memoryview(block)]
        ) is True

    def test_bad_inputs_raise_value_error(self):
        import pytest

        ext = self._ext()
        with pytest.raises(ValueError):
            ext.verify_blake2b_blocks([b"\x00" * 16], [b"x"])  # short digest
        with pytest.raises(ValueError):
            ext.verify_blake2b_blocks([b"\x00" * 32], [b"x", b"y"])  # length mismatch
        with pytest.raises(ValueError):
            ext.verify_blake2b_blocks([object()], [b"x"])  # non-buffer

    def test_backend_routes_through_it(self):
        import hashlib

        from ipc_proofs_tpu.backend.cpu import CpuBackend

        ext = self._ext()
        backend = CpuBackend()
        assert backend._scan_verify is not None
        blocks = [bytes([i]) * 200 for i in range(64)]
        digests = [hashlib.blake2b(b, digest_size=32).digest() for b in blocks]
        assert backend.verify_block_cids(digests, blocks) is True
