"""F3 BLS trust boundary: aggregate signatures, quorum, table commitments.

Covers the round-4 closure of the reference's open TODOs
(`src/proofs/trust/mod.rs:58,72`): bad-signature / short-quorum /
wrong-table certificates rejected, well-formed certificates accepted.
Pairing-level math (bilinearity) is asserted once — it underwrites
everything above it.
"""

import base64

import pytest

from ipc_proofs_tpu.crypto import bls
from ipc_proofs_tpu.proofs.cert import (
    ECTipSet,
    FinalityCertificate,
    FinalityCertificateChain,
    PowerTableDelta,
    PowerTableEntry,
    SupplementalData,
    power_table_cid,
)
from ipc_proofs_tpu.proofs.trust import TrustPolicy

SKS = [11111, 22222, 33333, 44444]
PKS = [bls.sk_to_pk(sk) for sk in SKS]
KEY_STRS = [base64.b64encode(bls.g1_compress(pk)).decode() for pk in PKS]
POPS = [base64.b64encode(bls.g2_compress(bls.pop_prove(sk))).decode() for sk in SKS]
POWERS = [30, 30, 30, 10]


def _table():
    return [
        PowerTableEntry(
            participant_id=i, power=POWERS[i], signing_key=KEY_STRS[i], pop=POPS[i]
        )
        for i in range(4)
    ]



def _cid(tag: str) -> str:
    """A real CID string for a test label (the go-f3 payload layout
    marshals raw CID bytes, so keys must parse as CIDs)."""
    from ipc_proofs_tpu.core.cid import CID

    return str(CID.hash_of(tag.encode()))

def _cert(signer_ids, instance=0, tamper_sig=False, signers_as_bitmap=False):
    cert = FinalityCertificate(
        instance=instance,
        ec_chain=[
            ECTipSet(key=[_cid("bafy-parent")], epoch=100, power_table=_cid("pt-cid")),
            ECTipSet(key=[_cid("bafy-head")], epoch=101, power_table=_cid("pt-cid")),
        ],
        supplemental_data=SupplementalData(power_table=_cid("bafy-next-table")),
    )
    payload = cert.signing_payload()
    sig = bls.aggregate_signatures([bls.sign(SKS[i], payload) for i in signer_ids])
    if tamper_sig:
        sig = bls.aggregate_signatures([sig, bls.g2_generator()])
    cert.signature = bls.g2_compress(sig)
    if signers_as_bitmap:
        from ipc_proofs_tpu.crypto.rleplus import encode_rleplus

        cert.signers = encode_rleplus(sorted(signer_ids))
    else:
        cert.signers = list(signer_ids)
    return cert


class TestPairing:
    def test_bilinearity(self):
        from ipc_proofs_tpu.crypto.bls import (
            _G1,
            _G2,
            _OPS1,
            _OPS2,
            _f12_pow,
            _F12_ONE,
            _pt_mul,
            pairing,
        )

        e = pairing(_G1, _G2)
        assert e != _F12_ONE  # non-degenerate
        assert pairing(_pt_mul(_OPS1, _G1, 5), _G2) == _f12_pow(e, 5)
        assert pairing(_G1, _pt_mul(_OPS2, _G2, 7)) == _f12_pow(e, 7)

    def test_compression_roundtrip_and_subgroup_rejection(self):
        pk = PKS[0]
        assert bls.g1_decompress(bls.g1_compress(pk)) == pk
        sig = bls.sign(SKS[0], b"m")
        assert bls.g2_decompress(bls.g2_compress(sig)) == sig
        assert bls.g1_decompress(bls.g1_compress(None)) is None
        with pytest.raises(ValueError):
            bls.g1_decompress(b"\x00" * 48)  # no compression flag
        with pytest.raises(ValueError):
            bls.g2_decompress(b"\xc0" + b"\x01" * 95)  # malformed infinity


class TestCertificateSignature:
    def test_well_formed_passes(self):
        _cert([0, 1, 2]).verify_signature(_table())  # no raise

    def test_bitmap_signers_equivalent(self):
        _cert([0, 1, 2], signers_as_bitmap=True).verify_signature(_table())

    def test_bad_signature_rejected(self):
        with pytest.raises(ValueError, match="signature is invalid"):
            _cert([0, 1, 2], tamper_sig=True).verify_signature(_table())

    def test_missing_signer_key_rejected(self):
        # signature claims signers {0,1,2} but only {0,1} actually signed
        cert = _cert([0, 1])
        cert.signers = [0, 1, 2]
        with pytest.raises(ValueError, match="signature is invalid"):
            cert.verify_signature(_table())

    def test_short_quorum_rejected(self):
        # 60 of 100 power — above half, below the 2/3 strong quorum
        with pytest.raises(ValueError, match="strong"):
            _cert([0, 1]).verify_signature(_table())

    def test_exact_two_thirds_rejected(self):
        # quorum must be STRICTLY greater than 2/3: 60 of 90
        table = _table()[:3]  # powers 30/30/30
        with pytest.raises(ValueError, match="strong"):
            _cert([0, 1]).verify_signature(table)

    def test_out_of_range_signer_rejected(self):
        cert = _cert([0, 1, 2])
        cert.signers = [0, 1, 5]
        with pytest.raises(ValueError, match="out of range"):
            cert.verify_signature(_table())

    def test_duplicate_signers_rejected(self):
        cert = _cert([0, 1, 2])
        cert.signers = [0, 0, 1, 2]
        with pytest.raises(ValueError, match="duplicate"):
            cert.verify_signature(_table())

    def test_rogue_key_attack_rejected(self):
        """Same-message aggregation is forgeable WITHOUT proof of
        possession: pk_evil = t·G1 − Σ pk_honest makes the aggregate key
        t·G1, so sig = t·H(payload) verifies over ALL signers. The PoP
        requirement must stop it (the attacker cannot produce a PoP for
        pk_evil without its discrete log)."""
        from ipc_proofs_tpu.crypto.bls import (
            _G1,
            _OPS1,
            _OPS2,
            _pt_add,
            _pt_mul,
            _pt_neg,
        )

        t = 987654321
        evil_pk = _pt_add(
            _OPS1,
            _pt_mul(_OPS1, _G1, t),
            _pt_neg(_OPS1, bls.aggregate_pubkeys(PKS[:3])),
        )
        table = _table()[:3]
        table.append(
            PowerTableEntry(
                participant_id=3,
                power=10,
                signing_key=base64.b64encode(bls.g1_compress(evil_pk)).decode(),
                pop=POPS[0],  # forged: someone else's PoP — must not validate
            )
        )
        cert = FinalityCertificate(
            instance=0,
            ec_chain=[ECTipSet(key=[_cid("bafy-a")], epoch=100, power_table=_cid("pt"))],
        )
        cert.signers = [0, 1, 2, 3]
        cert.signature = bls.g2_compress(
            _pt_mul(_OPS2, bls.hash_to_g2(cert.signing_payload()), t)
        )
        # the forged aggregate WOULD pass the raw pairing check:
        assert bls.verify_aggregate_same_message(
            PKS[:3] + [evil_pk],
            cert.signing_payload(),
            bls.g2_decompress(cert.signature),
        )
        # ...but PoP enforcement rejects it
        with pytest.raises(ValueError, match="possession"):
            cert.verify_signature(table)

    def test_missing_pop_rejected(self):
        table = _table()
        table[1].pop = ""
        with pytest.raises(ValueError, match="no proof of possession"):
            _cert([0, 1, 2]).verify_signature(table)

    def test_identity_pubkey_signer_rejected(self):
        """Quorum-bypass regression: an identity (infinity) G1 key in the
        table must not let its power count toward quorum. Here signers
        {0, 1, identity-row} would reach 70/110 > 2/3 with only rows 0+1
        actually signing — the identity key must be rejected outright."""
        table = _table()
        table.append(
            PowerTableEntry(
                participant_id=4,
                power=40,  # signers {0,1,4} = 100 of 140 > 2/3 — quorum met
                signing_key=base64.b64encode(bls.g1_compress(None)).decode(),
            )
        )
        cert = _cert([0, 1])  # only 0 and 1 really sign
        cert.signers = [0, 1, 4]
        with pytest.raises(ValueError, match="identity"):
            cert.verify_signature(table)

    def test_payload_binds_instance_and_chain(self):
        # a signature over instance 0's payload must not validate a cert
        # re-labeled as instance 1 (payload includes the instance)
        cert = _cert([0, 1, 2])
        cert.instance = 1
        with pytest.raises(ValueError, match="signature is invalid"):
            cert.verify_signature(_table())


class TestTrustPolicyPlumbing:
    def test_verify_signature_at_construction(self):
        cert = _cert([0, 1, 2])
        TrustPolicy.with_f3_certificate(
            cert, verify_signature=True, power_table=_table()
        )  # no raise

    def test_forged_cert_rejected_at_construction(self):
        cert = _cert([0, 1, 2], tamper_sig=True)
        with pytest.raises(ValueError, match="signature is invalid"):
            TrustPolicy.with_f3_certificate(
                cert, verify_signature=True, power_table=_table()
            )

    def test_requires_power_table(self):
        with pytest.raises(ValueError, match="power_table"):
            TrustPolicy.with_f3_certificate(_cert([0, 1, 2]), verify_signature=True)


class TestChainWithSignaturesAndTableCids:
    def test_chain_validates_and_checks_table_commitments(self):
        table0 = _table()
        # cert 0: no delta; commits to the (unchanged) table CID
        cert0 = _cert([0, 1, 2], instance=0)
        cert0.supplemental_data = SupplementalData(
            power_table=str(power_table_cid(table0))
        )
        # re-sign: supplemental data is part of the payload
        payload = cert0.signing_payload()
        cert0.signature = bls.g2_compress(
            bls.aggregate_signatures([bls.sign(SKS[i], payload) for i in (0, 1, 2)])
        )
        # cert 1: participant 3 gains 20 power; base = cert 0's head
        delta = [PowerTableDelta(participant_id=3, power_delta="20", signing_key="")]
        table1 = [
            PowerTableEntry(e.participant_id, e.power + (20 if e.participant_id == 3 else 0), e.signing_key)
            for e in table0
        ]
        cert1 = FinalityCertificate(
            instance=1,
            ec_chain=[
                ECTipSet(key=[_cid("bafy-head")], epoch=101, power_table=_cid("pt-cid")),
                ECTipSet(key=[_cid("bafy-next")], epoch=102, power_table=_cid("pt-cid")),
            ],
            supplemental_data=SupplementalData(power_table=str(power_table_cid(table1))),
            power_table_delta=delta,
        )
        payload1 = cert1.signing_payload()
        cert1.signers = [0, 1, 2]
        cert1.signature = bls.g2_compress(
            bls.aggregate_signatures([bls.sign(SKS[i], payload1) for i in (0, 1, 2)])
        )
        chain = FinalityCertificateChain([cert0, cert1])
        final = chain.validate(
            table0, verify_signatures=True, verify_table_cids=True
        )
        assert [e.power for e in final] == [30, 30, 30, 30]

    def test_committee_churn_with_delta_pops(self):
        """A delta-added participant carries its PoP in the delta, so a
        later certificate signed by the new committee member verifies —
        committee churn must not brick chain verification."""
        table0 = _table()[:3]  # powers 30/30/30
        new_sk = 55555
        new_key = base64.b64encode(bls.g1_compress(bls.sk_to_pk(new_sk))).decode()
        new_pop = base64.b64encode(bls.g2_compress(bls.pop_prove(new_sk))).decode()
        table1 = table0 + [PowerTableEntry(9, 30, new_key, new_pop)]

        cert0 = FinalityCertificate(
            instance=0,
            ec_chain=[
                ECTipSet(key=[_cid("bafy-a")], epoch=100, power_table=_cid("pt")),
                ECTipSet(key=[_cid("bafy-b")], epoch=101, power_table=_cid("pt")),
            ],
            supplemental_data=SupplementalData(power_table=str(power_table_cid(table1))),
            power_table_delta=[
                PowerTableDelta(
                    participant_id=9, power_delta="30",
                    signing_key=new_key, pop=new_pop,
                )
            ],
        )
        cert0.signers = [0, 1, 2]
        payload0 = cert0.signing_payload()
        cert0.signature = bls.g2_compress(
            bls.aggregate_signatures([bls.sign(SKS[i], payload0) for i in (0, 1, 2)])
        )

        cert1 = FinalityCertificate(
            instance=1,
            ec_chain=[
                ECTipSet(key=[_cid("bafy-b")], epoch=101, power_table=_cid("pt")),
                ECTipSet(key=[_cid("bafy-c")], epoch=102, power_table=_cid("pt")),
            ],
            supplemental_data=SupplementalData(power_table=str(power_table_cid(table1))),
        )
        # rows sorted by id: 0,1,2,9 → the new member is row 3
        cert1.signers = [0, 1, 3]
        payload1 = cert1.signing_payload()
        cert1.signature = bls.g2_compress(
            bls.aggregate_signatures(
                [bls.sign(SKS[0], payload1), bls.sign(SKS[1], payload1), bls.sign(new_sk, payload1)]
            )
        )
        final = FinalityCertificateChain([cert0, cert1]).validate(
            table0, verify_signatures=True
        )
        assert [e.participant_id for e in final] == [0, 1, 2, 9]

    def test_wrong_table_commitment_rejected(self):
        table0 = _table()
        cert0 = _cert([0, 1, 2], instance=0)
        cert0.supplemental_data = SupplementalData(power_table=_cid("bafy-wrong"))
        payload = cert0.signing_payload()
        cert0.signature = bls.g2_compress(
            bls.aggregate_signatures([bls.sign(SKS[i], payload) for i in (0, 1, 2)])
        )
        chain = FinalityCertificateChain([cert0])
        with pytest.raises(ValueError, match="commitment mismatch"):
            chain.validate(table0, verify_signatures=True, verify_table_cids=True)

    def test_requires_initial_table(self):
        with pytest.raises(ValueError, match="initial_power_table"):
            FinalityCertificateChain([_cert([0, 1, 2])]).validate(
                verify_signatures=True
            )

    def test_forged_delta_rejected_under_signatures_alone(self):
        """The signature payload does not cover the delta; the table
        commitment is the delta's only authentication, so
        verify_signatures=True must enforce it without a separate flag."""
        table0 = _table()
        cert = _cert([0, 1, 2], instance=0)
        cert.supplemental_data = SupplementalData(
            power_table=str(power_table_cid(table0))
        )
        payload = cert.signing_payload()
        cert.signature = bls.g2_compress(
            bls.aggregate_signatures([bls.sign(SKS[i], payload) for i in (0, 1, 2)])
        )
        # attacker splices in a power grab after signing
        cert.power_table_delta = [
            PowerTableDelta(participant_id=3, power_delta="1000", signing_key="")
        ]
        with pytest.raises(ValueError, match="commitment mismatch"):
            FinalityCertificateChain([cert]).validate(
                table0, verify_signatures=True
            )

    def test_missing_commitment_rejected_under_signatures(self):
        cert = _cert([0, 1, 2], instance=0)
        cert.supplemental_data = SupplementalData(power_table="")
        payload = cert.signing_payload()
        cert.signature = bls.g2_compress(
            bls.aggregate_signatures([bls.sign(SKS[i], payload) for i in (0, 1, 2)])
        )
        with pytest.raises(ValueError, match="no power-table commitment"):
            FinalityCertificateChain([cert]).validate(
                _table(), verify_signatures=True
            )


class TestCertificateJsonParsing:
    """`FinalityCertificate.from_json_obj` consumes UNTRUSTED JSON (CLI
    cert files, RPC). It must reject every malformed shape as ValueError —
    a trust boundary failing with KeyError/TypeError/AttributeError leaks
    shape assumptions and previously did exactly that (pre-hardening:
    `from_json_obj([1,2])` raised AttributeError)."""

    VALID = {
        "GPBFTInstance": 7,
        "ECChain": [
            {"Epoch": 10, "Key": [{"/": "bafyaa"}], "PowerTable": {"/": "bafypt"}},
            {"Epoch": 11, "Key": ["bafybb"], "PowerTable": "bafypt"},
        ],
        "SupplementalData": {"PowerTable": {"/": "bafypt2"}, "Commitments": [0] * 4},
        "Signers": "AAE=",
        "Signature": "",
        "PowerTableDelta": [
            {"ParticipantID": 3, "PowerDelta": "100", "SigningKey": "", "Pop": ""}
        ],
    }

    def test_valid_shapes_parse(self):
        cert = FinalityCertificate.from_json_obj(self.VALID)
        assert cert.instance == 7
        assert cert.ec_chain[0].key == ["bafyaa"]
        assert cert.power_table_delta[0].participant_id == 3

    def test_non_object_roots_rejected(self):
        for garbage in ([1, 2], "str", None, 42, 3.5, True):
            with pytest.raises(ValueError, match="malformed F3 certificate"):
                FinalityCertificate.from_json_obj(garbage)

    @pytest.mark.parametrize("seed", [1, 99])
    def test_randomized_structural_garbage_never_leaks(self, seed):
        import copy
        import random

        rng = random.Random(seed)
        garbage_values = [
            None, True, False, 0, -1, 3.5, "x", "", [], {}, [None], {"/": 5},
            {"/": None}, [["nested"]], "not-base64!!", {"Epoch": None}, 2**70,
        ]

        def mutate(obj):
            """Replace one random node of a deep-copied VALID cert obj."""
            doc = copy.deepcopy(obj)
            # collect (container, key) sites
            sites = []

            def walk(node):
                if isinstance(node, dict):
                    for k in node:
                        sites.append((node, k))
                        walk(node[k])
                elif isinstance(node, list):
                    for i in range(len(node)):
                        sites.append((node, i))
                        walk(node[i])

            walk(doc)
            container, key = rng.choice(sites)
            action = rng.randrange(3)
            if action == 0:
                container[key] = rng.choice(garbage_values)
            elif action == 1 and isinstance(container, dict):
                del container[key]
            else:
                container[key] = rng.choice(garbage_values)
            return doc

        parsed = rejected = 0
        for _ in range(300):
            doc = mutate(self.VALID)
            if rng.random() < 0.3:
                doc = mutate(doc)
            try:
                FinalityCertificate.from_json_obj(doc)
                parsed += 1
            except ValueError:
                rejected += 1
            # any other exception type propagates and fails the test
        assert parsed and rejected  # both regimes exercised
