"""Byte-compat vectors harness: capture with a canned fake RPC, verify;
consume a live-captured fixtures file when one is present.

Set ``IPC_VECTORS_FILE=/path/to/vectors.json`` (written by
``ipc-proofs vectors --endpoint … --height …``) to run the byte-compat
checks against real chain bytes; without it the live test skips.
"""

import json
import os

import pytest

from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
from ipc_proofs_tpu.proofs.vectors import (
    FORMAT,
    capture_vectors,
    check_vectors,
    load_vectors,
    write_vectors,
)
from ipc_proofs_tpu.store.testing import FakeLotusClient

SIG = "NewTopDownMessage(bytes32,uint256)"


def _tipset_json(ts):
    return {
        "Cids": [{"/": str(c)} for c in ts.cids],
        "Blocks": [
            {
                "Parents": [{"/": str(c)} for c in h.parents],
                "Height": h.height,
                "ParentStateRoot": {"/": str(h.parent_state_root)},
                "ParentMessageReceipts": {"/": str(h.parent_message_receipts)},
                "Messages": {"/": str(h.messages)},
                "Timestamp": h.timestamp,
            }
            for h in ts.blocks
        ],
        "Height": ts.height,
    }


def _fake_client():
    world = build_chain(
        [ContractFixture(actor_id=900)],
        [[EventFixture(emitter=900, signature=SIG, topic1="vec-subnet")]],
        parent_height=500,
    )
    client = FakeLotusClient(
        world.store,
        responses={
            "Filecoin.ChainGetTipSetByHeight": lambda params: _tipset_json(
                world.parent if params[0] == world.parent.height else world.child
            ),
        },
    )
    return client, world


class TestVectorsHarness:
    def test_capture_and_check_roundtrip(self, tmp_path):
        client, world = _fake_client()
        doc = capture_vectors(client, world.parent.height)
        assert doc["format"] == FORMAT
        kinds = [v["kind"] for v in doc["vectors"]]
        assert kinds.count("header") == len(world.parent.cids) + 1
        assert "txmeta" in kinds and "amt_node" in kinds
        n = check_vectors(doc)
        assert n == len(doc["vectors"]) >= 4
        path = tmp_path / "vectors.json"
        write_vectors(doc, str(path))
        assert check_vectors(load_vectors(str(path))) == n

    def test_cli_vectors_command(self, tmp_path, monkeypatch):
        """The `vectors` subcommand end-to-end against the fake RPC."""
        from ipc_proofs_tpu import cli

        client, world = _fake_client()
        monkeypatch.setattr(
            "ipc_proofs_tpu.store.rpc.LotusClient",
            lambda *a, **kw: client,
        )
        out = tmp_path / "v.json"
        rc = cli.main(
            [
                "vectors",
                "--endpoint",
                "http://fake",
                "--height",
                str(world.parent.height),
                "-o",
                str(out),
            ]
        )
        assert rc == 0
        doc = json.loads(out.read_text())
        assert check_vectors(doc) >= 4

    def test_check_rejects_tampered_bytes(self):
        client, world = _fake_client()
        doc = capture_vectors(client, world.parent.height)
        import base64

        bad = json.loads(json.dumps(doc))
        raw = bytearray(base64.b64decode(bad["vectors"][0]["data"]))
        raw[-1] ^= 1
        bad["vectors"][0]["data"] = base64.b64encode(bytes(raw)).decode()
        with pytest.raises(ValueError, match="diverges from the chain"):
            check_vectors(bad)

    def test_check_rejects_tampered_expectations(self):
        client, world = _fake_client()
        doc = capture_vectors(client, world.parent.height)
        bad = json.loads(json.dumps(doc))
        header_vec = next(v for v in bad["vectors"] if v["kind"] == "header")
        header_vec["expect"]["height"] += 1
        with pytest.raises(ValueError, match="header fields diverge"):
            check_vectors(bad)


class TestLiveVectors:
    def test_live_captured_vectors_if_present(self):
        """Byte-compat against REAL chain bytes — runs only when a captured
        fixtures file is provided (zero-egress CI skips)."""
        path = os.environ.get("IPC_VECTORS_FILE", "tests/vectors/live_vectors.json")
        if not os.path.exists(path):
            pytest.skip(f"no captured vectors at {path} (run `ipc-proofs vectors`)")
        n = check_vectors(load_vectors(path))
        assert n >= 4
