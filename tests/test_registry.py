"""Proof provenance plane: hash-linked registry, Merkle proofs, fleet
base directory (ipc_proofs_tpu/registry/).

Four layers under test, bottom-up:

- the RFC 6962 tree (`registry.mmr`) against a from-scratch recursive
  reference — every inclusion and consistency proof for every (size,
  index) in a grid, plus negative cases;
- the IPR1 frame log (`registry.log`): torn tails truncate, and EVERY
  single-bit flip anywhere in the file is caught typed or surfaces as a
  strictly-shorter log (checkpoint-detectable) — never a silent
  same-length parse of different bytes;
- `ProvenanceRegistry`: append/proof/reopen, idempotent base acks,
  sibling scans, fail-soft degrade with the in-memory head frozen;
- the serving stack: a differential grid (buffered × streamed ×
  aggregated HTTP, delta pushes) where every served bundle gets a
  verifying inclusion + consistency proof, registry write failure leaves
  responses bit-identical, and a killed shard's subscriber still gets a
  valid delta from the fleet base directory.
"""

import hashlib
import json
import random
import time
from http.client import HTTPConnection

import pytest

from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import TipsetPair, generate_event_proofs_for_range_chunked
from ipc_proofs_tpu.registry import (
    MerkleLog,
    ProvenanceRegistry,
    RegistryError,
    frame_registry_record,
    leaf_hash,
    node_hash,
    read_registry_frames,
    record_digest,
    verify_chain,
    verify_consistency,
    verify_inclusion,
)
from ipc_proofs_tpu.serve.httpd import ProofHTTPServer
from ipc_proofs_tpu.serve.service import ProofService, ServiceConfig
from ipc_proofs_tpu.subs import StandingQueries, filter_key, normalize_filter
from ipc_proofs_tpu.utils.metrics import Metrics
from ipc_proofs_tpu.witness import apply_delta
from ipc_proofs_tpu.witness.bases import FleetBaseCache, WitnessBaseCache

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001
FILTER_A = {"signature": SIG, "topic1": SUBNET}

_NOSLEEP = lambda s: None  # noqa: E731


def _counters(m):
    return m.snapshot()["counters"]


def _wait_until(cond, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


# --------------------------------------------------------------------------
# Merkle tree vs a from-scratch recursive reference
# --------------------------------------------------------------------------


def _ref_mth(leaves):
    """RFC 6962 MTH, recursively — the independent oracle."""
    n = len(leaves)
    if n == 0:
        return hashlib.sha256(b"").digest()
    if n == 1:
        return leaves[0]
    k = 1
    while k * 2 < n:
        k *= 2
    return node_hash(_ref_mth(leaves[:k]), _ref_mth(leaves[k:]))


def _leaves(n):
    return [leaf_hash(f"leaf-{i}".encode()) for i in range(n)]


class TestMerkle:
    def test_roots_match_recursive_reference(self):
        for n in range(0, 17):
            assert MerkleLog(_leaves(n)).root() == _ref_mth(_leaves(n)), n

    def test_incremental_append_equals_batch(self):
        tree = MerkleLog()
        for i in range(16):
            assert tree.append(leaf_hash(f"leaf-{i}".encode())) == i
            assert tree.root() == _ref_mth(_leaves(i + 1))
            assert tree.size == i + 1

    def test_every_inclusion_proof_verifies(self):
        for n in range(1, 17):
            tree = MerkleLog(_leaves(n))
            root = tree.root()
            for i in range(n):
                path = tree.inclusion_path(i)
                assert verify_inclusion(tree.leaves[i], i, n, path, root), (n, i)
                # wrong leaf, wrong index, wrong root: all must fail
                bad = leaf_hash(b"not-this-leaf")
                assert not verify_inclusion(bad, i, n, path, root)
                if n > 1:
                    j = (i + 1) % n
                    assert not verify_inclusion(tree.leaves[i], j, n, path, root)
                assert not verify_inclusion(
                    tree.leaves[i], i, n, path, hashlib.sha256(b"x").digest()
                )

    def test_every_consistency_proof_verifies(self):
        for n in range(1, 17):
            tree = MerkleLog(_leaves(n))
            for m in range(0, n + 1):
                old_root = tree.root_at(m)
                assert old_root == _ref_mth(_leaves(m)), (m, n)
                proof = tree.consistency_path(m) if 0 < m < n else []
                assert verify_consistency(m, n, old_root, tree.root(), proof), (m, n)
                # a forked history (different old root) must not verify
                if m > 0:
                    forked = _ref_mth(
                        [leaf_hash(f"fork-{i}".encode()) for i in range(m)]
                    )
                    assert not verify_consistency(
                        m, n, forked, tree.root(), proof
                    ), (m, n)


# --------------------------------------------------------------------------
# IPR1 frame log + the single-bit tamper grid
# --------------------------------------------------------------------------


def _write_frames(path, objs):
    payloads = []
    prev = ""
    with open(path, "wb") as fh:
        for obj in objs:
            rec = dict(obj, prev=prev)
            frame = frame_registry_record(rec)
            payloads.append(frame[12:])
            prev = record_digest(frame[12:])
            fh.write(frame)
    return payloads


def _sample_objs(n):
    out = []
    for i in range(n):
        if i % 3 == 2:
            out.append(
                {"kind": "base", "fleet": "f", "key": "k", "sub": f"s{i}",
                 "digest": f"d{i}", "cursor": i, "t": float(i)}
            )
        else:
            out.append(
                {"kind": "serve", "digest": f"d{i}", "trace": f"t{i}",
                 "tenant": "", "key": f"pair:{i}", "verdict": "valid",
                 "t": float(i), "cids": [f"{i:02x}aa", f"{i:02x}bb"]}
            )
    return out


class TestRegistryLog:
    def test_roundtrip_and_chain(self, tmp_path):
        path = str(tmp_path / "reg-a.log")
        payloads = _write_frames(path, _sample_objs(5))
        entries, good, torn = read_registry_frames(path)
        assert [p for _r, p, _o in entries] == payloads
        assert not torn
        assert verify_chain(entries) == record_digest(payloads[-1])

    def test_missing_file_reads_empty(self, tmp_path):
        entries, good, torn = read_registry_frames(str(tmp_path / "nope.log"))
        assert (entries, torn) == ([], False)

    def test_torn_tail_at_every_cut(self, tmp_path):
        """Truncating the file anywhere inside the LAST frame is crash
        residue: the complete prefix reads back, torn=True, no error."""
        path = str(tmp_path / "reg-a.log")
        _write_frames(path, _sample_objs(3))
        full = open(path, "rb").read()
        entries_all, _good, _ = read_registry_frames(path)
        last_off = entries_all[-1][2]
        for cut in range(last_off + 1, len(full)):
            with open(path, "wb") as fh:
                fh.write(full[:cut])
            entries, good, torn = read_registry_frames(path)
            assert torn and len(entries) == 2, cut
            assert good == last_off

    def test_broken_prev_link_typed(self, tmp_path):
        path = str(tmp_path / "reg-a.log")
        objs = _sample_objs(3)
        with open(path, "wb") as fh:
            prev = ""
            for i, obj in enumerate(objs):
                rec = dict(obj, prev=("bogus" if i == 2 else prev))
                frame = frame_registry_record(rec)
                prev = record_digest(frame[12:])
                fh.write(frame)
        entries, _good, _torn = read_registry_frames(path)
        with pytest.raises(RegistryError, match="chain broken"):
            verify_chain(entries)
        with pytest.raises(RegistryError, match="chain broken"):
            ProvenanceRegistry(str(tmp_path), owner="a")

    def test_every_single_bit_flip_is_detected(self, tmp_path):
        """The acceptance tamper grid: flip ONE bit at EVERY byte of the
        log — magic, length, CRC, payload, prev-link chars, all of it.
        Every flip must either raise the typed `RegistryError` (on read
        or on chain verification) or strictly shorten the readable log
        (which a pinned checkpoint catches: old_size > new size). No flip
        may ever yield a clean same-length parse of different bytes."""
        path = str(tmp_path / "reg-a.log")
        payloads = _write_frames(path, _sample_objs(5))
        clean = open(path, "rb").read()
        n_clean = len(payloads)
        outcomes = {"typed": 0, "shorter": 0}
        for off in range(len(clean)):
            for bit in (0, 7):
                tampered = bytearray(clean)
                tampered[off] ^= 1 << bit
                with open(path, "wb") as fh:
                    fh.write(bytes(tampered))
                try:
                    entries, _good, torn = read_registry_frames(path)
                    verify_chain(entries)
                except RegistryError:
                    outcomes["typed"] += 1
                    continue
                # no typed error: the only acceptable story is a shorter
                # log (a length-field flip making the tail look torn)
                assert torn and len(entries) < n_clean, (off, bit)
                assert [p for _r, p, _o in entries] == payloads[: len(entries)]
                outcomes["shorter"] += 1
        assert outcomes["typed"] > 0 and outcomes["shorter"] > 0
        # typed detection must dominate: only tail-length flips truncate
        assert outcomes["typed"] > outcomes["shorter"] * 10


# --------------------------------------------------------------------------
# ProvenanceRegistry
# --------------------------------------------------------------------------


def _digest(i):
    return hashlib.sha256(f"bundle-{i}".encode()).hexdigest()


def _cids(i, k=3):
    return frozenset(
        hashlib.sha256(f"cid-{i}-{j}".encode()).digest() for j in range(k)
    )


class TestProvenanceRegistry:
    def test_append_proof_reopen_roundtrip(self, tmp_path):
        m = Metrics()
        reg = ProvenanceRegistry(str(tmp_path), owner="a", metrics=m)
        for i in range(7):
            assert reg.append_served(
                _digest(i), trace=f"t{i}", key=f"pair:{i}", verdict="valid",
                cids=_cids(i),
            ) == i
        head = reg.head()
        assert (head["owner"], head["size"], head["degraded"]) == ("a", 7, False)

        # every record: inclusion proof verifies against the head root
        for i in range(7):
            assert reg.seq_of(_digest(i)) == i
            proof = reg.inclusion_proof(i)
            assert verify_inclusion(
                bytes.fromhex(proof["leaf"]), i, proof["size"],
                [bytes.fromhex(h) for h in proof["path"]],
                bytes.fromhex(head["root"]),
            ), i
            assert proof["record"]["digest"] == _digest(i)
        # every checkpoint: consistency proof verifies against the head
        for old in range(0, 8):
            c = reg.consistency(old)
            assert verify_consistency(
                old, c["size"], bytes.fromhex(c["old_root"]),
                bytes.fromhex(c["root"]),
                [bytes.fromhex(h) for h in c["path"]],
            ), old
        assert _counters(m)["registry.appends"] == 7
        reg.close()

        # reopen: same head, chain continues (no re-append, no divergence)
        reg2 = ProvenanceRegistry(str(tmp_path), owner="a", metrics=m)
        assert reg2.head() == dict(head, log_bytes=reg2.head()["log_bytes"])
        assert reg2.append_served(_digest(7), cids=_cids(7)) == 7
        c = reg2.consistency(7)
        assert c["old_root"] == head["root"]
        reg2.close()

    def test_torn_tail_truncated_on_open(self, tmp_path):
        m = Metrics()
        reg = ProvenanceRegistry(str(tmp_path), owner="a")
        for i in range(3):
            reg.append_served(_digest(i))
        reg.close()
        with open(reg.path, "ab") as fh:
            fh.write(b"IPR1\x99\x00")  # torn header: crash residue
        reg2 = ProvenanceRegistry(str(tmp_path), owner="a", metrics=m)
        assert len(reg2) == 3
        assert _counters(m)["registry.torn_tails"] == 1
        # the residue is gone: the next append lands on a clean tail
        reg2.append_served(_digest(3))
        reg2.close()
        entries, _g, torn = read_registry_frames(reg2.path)
        assert len(entries) == 4 and not torn
        verify_chain(entries)

    def test_base_acks_idempotent_and_common_base(self, tmp_path):
        reg = ProvenanceRegistry(str(tmp_path), owner="a")
        reg.append_served(_digest(0), cids=_cids(0))
        reg.append_served(_digest(1), cids=_cids(1))
        assert reg.append_base_ack("f", "k", "s1", _digest(0), 1) is not None
        # replaying the same latest ack (restart sweep) grows nothing
        n = len(reg)
        assert reg.append_base_ack("f", "k", "s1", _digest(0), 1) is None
        assert len(reg) == n
        # one member → its base IS the common base
        assert reg.newest_common_base("f", "k") == _digest(0)
        assert reg.fleet_acked_base("f", "k", "s1") == _digest(0)
        # second member appears, still on the old base
        reg.append_base_ack("f", "k", "s2", _digest(0), 1)
        # s1 advances alone: common stays at the old digest…
        reg.append_base_ack("f", "k", "s1", _digest(1), 2)
        assert reg.newest_common_base("f", "k") == _digest(0)
        # …until s2 follows
        reg.append_base_ack("f", "k", "s2", _digest(1), 2)
        assert reg.newest_common_base("f", "k") == _digest(1)
        assert reg.lookup_base(_digest(1)) == _cids(1)
        reg.close()

    def test_sibling_scan_and_corrupt_sibling_fail_soft(self, tmp_path):
        m = Metrics()
        a = ProvenanceRegistry(str(tmp_path), owner="a")
        a.append_served(_digest(0), cids=_cids(0))
        a.append_base_ack("f", "k", "s1", _digest(0), 1)
        a.close()
        b = ProvenanceRegistry(str(tmp_path), owner="b", metrics=m)
        # b's directory sees a's serve record AND a's fleet acks
        assert b.lookup_base(_digest(0)) == _cids(0)
        assert b.fleet_acked_base("f", "k", "s1") == _digest(0)
        assert b.newest_common_base("f", "k") == _digest(0)
        # a sibling going corrupt is counted, never fatal
        with open(a.path, "r+b") as fh:
            fh.seek(20)
            byte = fh.read(1)
            fh.seek(20)
            fh.write(bytes([byte[0] ^ 0x01]))
        c = ProvenanceRegistry(str(tmp_path), owner="c", metrics=m)
        assert c.lookup_base(_digest(0)) is None  # miss, not a crash
        assert _counters(m)["registry.fleet_refresh_errors"] >= 1
        b.close()
        c.close()

    def test_write_failure_degrades_head_frozen(self, tmp_path):
        m = Metrics()
        reg = ProvenanceRegistry(str(tmp_path), owner="a", metrics=m)
        reg.append_served(_digest(0))
        head = reg.head()
        # swap the log handle for a read-only one: the next write raises
        # OSError exactly like ENOSPC/EROFS would
        reg._writer._fh.close()
        reg._writer._fh = open(reg.path, "rb")
        assert reg.append_served(_digest(1)) is None
        assert reg.degraded and reg.head()["degraded"]
        # the in-memory head NEVER advanced on the failed write
        assert reg.head()["size"] == head["size"]
        assert reg.head()["root"] == head["root"]
        assert reg.append_served(_digest(2)) is None  # permanently degraded
        assert _counters(m)["registry.append_failures"] == 2
        # the on-disk chain is still the clean prefix
        entries, _g, _t = read_registry_frames(reg.path)
        assert len(entries) == 1
        verify_chain(entries)


class TestFleetBaseCache:
    def test_local_hit_fleet_hit_and_miss(self, tmp_path):
        m = Metrics()
        a = ProvenanceRegistry(str(tmp_path), owner="a")
        a.append_served(_digest(0), cids=_cids(0))
        a.close()
        b = ProvenanceRegistry(str(tmp_path), owner="b")
        local = WitnessBaseCache(cap=4)
        cache = FleetBaseCache(local, b, metrics=m)
        # local miss → fleet hit (a's serve record), then local is seeded
        assert cache.lookup(_digest(0)) == _cids(0)
        assert _counters(m)["witness.fleet_base_hits"] == 1
        assert local.lookup(_digest(0)) == _cids(0)
        assert cache.lookup(_digest(0)) == _cids(0)  # local now, no recount
        assert _counters(m)["witness.fleet_base_hits"] == 1
        assert cache.lookup("ffff") is None
        assert _counters(m)["witness.fleet_base_misses"] == 1
        assert len(cache) == len(local)
        b.close()


# --------------------------------------------------------------------------
# serving stack: differential grid + fail-soft + failover delta
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def world():
    return build_range_world(
        4, receipts_per_pair=6, events_per_receipt=3, match_rate=0.5,
        signature=SIG, topic1=SUBNET, actor_id=ACTOR, base_height=41_000,
    )


def _get(port, path):
    conn = HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path, None, {})
    resp = conn.getresponse()
    return resp.status, json.loads(resp.read())


def _post(port, path, obj):
    conn = HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", path, json.dumps(obj),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    return resp.status, resp.read()


def _check_served(port, digest):
    """The acceptance predicate: the served digest has an inclusion proof
    verifying against the live head, and the head extends checkpoint 1."""
    status, head = _get(port, "/v1/registry/head")
    assert status == 200
    status, proof = _get(port, f"/v1/registry/proof?digest={digest}")
    assert status == 200, proof
    assert verify_inclusion(
        bytes.fromhex(proof["leaf"]), proof["seq"], proof["size"],
        [bytes.fromhex(h) for h in proof["path"]],
        bytes.fromhex(head["root"]),
    ), digest
    assert proof["record"]["digest"] == digest
    status, c = _get(port, "/v1/registry/consistency?old_size=1")
    assert status == 200
    assert verify_consistency(
        1, c["size"], bytes.fromhex(c["old_root"]), bytes.fromhex(c["root"]),
        [bytes.fromhex(h) for h in c["path"]],
    )


class TestServeDifferentialGrid:
    def test_every_served_bundle_proves_inclusion(self, world, tmp_path):
        """Buffered × streamed × aggregated: each response seals exactly
        one serve record whose inclusion proof verifies against the head
        the daemon publishes right after."""
        from ipc_proofs_tpu.witness.stream import decode_bundle_stream

        store, pairs, _ = world
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET,
                              actor_id_filter=ACTOR)
        svc = ProofService(
            store=store, spec=spec,
            config=ServiceConfig(max_batch=8, max_wait_ms=5.0, workers=2,
                                 registry_dir=str(tmp_path), registry_owner="t"),
        )
        httpd = ProofHTTPServer(svc, pairs=pairs).start()
        try:
            served = []
            # buffered generate
            status, raw = _post(httpd.port, "/v1/generate", {"pair_index": 0})
            assert status == 200
            out = json.loads(raw)
            served.append(out["digest"])
            # streamed generate
            status, raw = _post(
                httpd.port, "/v1/generate", {"pair_index": 1, "stream": True}
            )
            assert status == 200
            sout = decode_bundle_stream(raw)
            served.append(sout["digest"])
            # aggregated range (buffered)
            status, raw = _post(httpd.port, "/v1/generate_range",
                                {"pair_indexes": [0, 1]})
            assert status == 200
            served.append(json.loads(raw)["digest"])
            # aggregated range (streamed)
            status, raw = _post(
                httpd.port, "/v1/generate_range",
                {"pair_indexes": [2, 3], "stream": True},
            )
            assert status == 200
            served.append(decode_bundle_stream(raw)["digest"])

            status, head = _get(httpd.port, "/v1/registry/head")
            assert (status, head["size"]) == (200, 4)
            for digest in served:
                assert digest
                _check_served(httpd.port, digest)
            # the sealed kinds/keys tell the story
            status, e0 = _get(httpd.port, "/v1/registry/entry?seq=0")
            assert (e0["kind"], e0["key"]) == ("serve", "pair:0")
            # health carries the registry head
            status, health = _get(httpd.port, "/healthz")
            assert health["registry"] == "ok"
            assert health["registry_head"]["size"] == 4
        finally:
            httpd.shutdown(timeout=30)

    def test_registry_failure_is_fail_soft(self, world, tmp_path):
        """Force the writer into OSError-degrade mid-flight: responses
        stay bit-identical to a registry-less service, the counter and
        /healthz tell the operator, serving never blocks."""
        store, pairs, _ = world
        spec = EventProofSpec(event_signature=SIG, topic_1=SUBNET,
                              actor_id_filter=ACTOR)
        m_plain = Metrics()
        svc_plain = ProofService(
            store=store, spec=spec, metrics=m_plain,
            config=ServiceConfig(max_batch=8, max_wait_ms=5.0, workers=2),
        )
        httpd_plain = ProofHTTPServer(svc_plain, pairs=pairs).start()
        m = Metrics()
        svc = ProofService(
            store=store, spec=spec, metrics=m,
            config=ServiceConfig(max_batch=8, max_wait_ms=5.0, workers=2,
                                 registry_dir=str(tmp_path), registry_owner="t"),
        )
        httpd = ProofHTTPServer(svc, pairs=pairs).start()
        try:
            from ipc_proofs_tpu.witness.stream import decode_bundle_stream

            # break the log handle: every append from here raises OSError
            svc.registry._writer._fh.close()
            svc.registry._writer._fh = open(svc.registry.path, "rb")
            for req in ({"pair_index": 0}, {"pair_index": 1, "stream": True}):
                status, raw = _post(httpd.port, "/v1/generate", dict(req))
                status_p, raw_p = _post(httpd_plain.port, "/v1/generate", dict(req))
                assert status == status_p == 200
                dec = decode_bundle_stream if req.get("stream") else json.loads
                out, out_p = dec(raw), dec(raw_p)
                # the proof payload is bit-identical; only wall-clock
                # timing fields may differ between the two instances
                assert out["digest"] == out_p["digest"]
                assert out["bundle"] == out_p["bundle"]
            assert _counters(m)["registry.append_failures"] >= 2
            status, health = _get(httpd.port, "/healthz")
            assert (status, health["registry"]) == (200, "degraded")
            assert health["status"] == "ok"  # serving itself is fine
        finally:
            httpd.shutdown(timeout=30)
            httpd_plain.shutdown(timeout=30)


class _RecordingOpener:
    def __init__(self):
        self.sent = []

    def __call__(self, url, body, timeout_s):
        env = json.loads(body)
        self.sent.append((url, env))
        return 200

    def envelopes(self):
        return [env for _u, env in self.sent]


def _expected(store, pair, filt):
    spec = EventProofSpec(
        event_signature=filt["signature"], topic_1=filt["topic1"],
        actor_id_filter=filt.get("actor_id"),
    )
    bundle = generate_event_proofs_for_range_chunked(store, [pair], spec,
                                                     chunk_size=8)
    obj = bundle.to_json_obj()
    from ipc_proofs_tpu.subs.matcher import _bundle_digest

    return obj, _bundle_digest(obj)


class TestFleetFailoverDelta:
    def test_replacement_shard_serves_delta_from_fleet_directory(
        self, world, tmp_path
    ):
        """Kill-a-shard: shard A pushes pair 0 to a webhook subscriber
        (who acks), then dies taking its delivery log with it. Shard B —
        fresh subs root, same shared registry dir — pushes pair 1. The
        fleet directory supplies both the base the subscriber acked AND
        its CID set, so B ships a DELTA that expands byte-identical; the
        per-shard-cache baseline (no registry) degrades to full."""
        store, pairs, _ = world
        regroot = str(tmp_path / "reg")
        fkey = filter_key(normalize_filter(FILTER_A))

        # shard A: serve pair 0, subscriber acks (webhook 200 auto-acks)
        m_a = Metrics()
        opener_a = _RecordingOpener()
        reg_a = ProvenanceRegistry(regroot, owner="shard-a", metrics=m_a)
        sq_a = StandingQueries(
            str(tmp_path / "subs-a"), store=store, metrics=m_a, fsync=False,
            opener=opener_a, sleep=_NOSLEEP, rng=random.Random(0),
            provenance=reg_a, fleet="pool",
        )
        sq_a.subscribe({"filter": FILTER_A, "target": {"url": "http://h/w1"},
                        "sub_id": "w1"})
        assert sq_a.matcher.match_pair(pairs[0]) == 1
        assert _wait_until(lambda: sq_a.log.pending_total() == 0)
        obj0, digest0 = _expected(store, pairs[0], normalize_filter(FILTER_A))
        assert opener_a.envelopes()[0]["digest"] == digest0
        # the ack reporter sealed the base record for the fleet
        assert reg_a.fleet_acked_base("pool", fkey, "w1") == digest0
        sq_a.drain()
        reg_a.close()  # shard A is dead; only its log file remains

        # shard B: fresh subs root — local acked state is EMPTY
        m_b = Metrics()
        opener_b = _RecordingOpener()
        reg_b = ProvenanceRegistry(regroot, owner="shard-b", metrics=m_b)
        sq_b = StandingQueries(
            str(tmp_path / "subs-b"), store=store, metrics=m_b, fsync=False,
            opener=opener_b, sleep=_NOSLEEP, rng=random.Random(1),
            provenance=reg_b, fleet="pool",
        )
        sq_b.subscribe({"filter": FILTER_A, "target": {"url": "http://h/w1"},
                        "sub_id": "w1"})
        try:
            assert sq_b.matcher.match_pair(pairs[1]) == 1
            assert _wait_until(lambda: sq_b.log.pending_total() == 0)
            obj1, digest1 = _expected(store, pairs[1],
                                      normalize_filter(FILTER_A))
            env = opener_b.envelopes()[0]
            assert env["digest"] == digest1
            # the point: a DELTA against the base the dead shard recorded
            assert "bundle_delta" in env, env.keys()
            assert env["bundle_delta"]["base_digest"] == digest0
            base = UnifiedProofBundle.from_json_obj(obj0)
            assert apply_delta(env["bundle_delta"], base).to_json_obj() == obj1
            c = _counters(m_b)
            assert c["witness.fleet_base_hits"] >= 1
            assert c.get("witness.delta_fallbacks", 0) == 0
        finally:
            sq_b.drain()
            reg_b.close()

    def test_baseline_without_directory_degrades_to_full(self, world, tmp_path):
        """Same failover, no registry: the replacement shard can only
        ship the full bundle — the measured gap the bench leg gates."""
        store, pairs, _ = world
        m_a = Metrics()
        opener_a = _RecordingOpener()
        sq_a = StandingQueries(
            str(tmp_path / "subs-a"), store=store, metrics=m_a, fsync=False,
            opener=opener_a, sleep=_NOSLEEP, rng=random.Random(0),
        )
        sq_a.subscribe({"filter": FILTER_A, "target": {"url": "http://h/w1"},
                        "sub_id": "w1"})
        assert sq_a.matcher.match_pair(pairs[0]) == 1
        assert _wait_until(lambda: sq_a.log.pending_total() == 0)
        sq_a.drain()

        m_b = Metrics()
        opener_b = _RecordingOpener()
        sq_b = StandingQueries(
            str(tmp_path / "subs-b"), store=store, metrics=m_b, fsync=False,
            opener=opener_b, sleep=_NOSLEEP, rng=random.Random(1),
        )
        sq_b.subscribe({"filter": FILTER_A, "target": {"url": "http://h/w1"},
                        "sub_id": "w1"})
        try:
            assert sq_b.matcher.match_pair(pairs[1]) == 1
            assert _wait_until(lambda: sq_b.log.pending_total() == 0)
            env = opener_b.envelopes()[0]
            assert "bundle" in env and "bundle_delta" not in env
        finally:
            sq_b.drain()

    def test_unknown_subscriber_never_gets_unsound_delta(self, world, tmp_path):
        """Soundness guard: a subscriber the fleet directory has NEVER
        seen ack anything must get the full bundle — a delta against a
        base it doesn't hold would be wrong, not slow."""
        store, pairs, _ = world
        regroot = str(tmp_path / "reg")
        # someone else's acks are on the chain under the same filter
        reg_seed = ProvenanceRegistry(regroot, owner="seed")
        fkey = filter_key(normalize_filter(FILTER_A))
        obj0, digest0 = _expected(store, pairs[0], normalize_filter(FILTER_A))
        reg_seed.append_served(digest0, key=fkey, cids=_cids(0))
        reg_seed.append_base_ack("pool", fkey, "other-sub", digest0, 1)
        reg_seed.close()

        m = Metrics()
        opener = _RecordingOpener()
        reg = ProvenanceRegistry(regroot, owner="shard-b", metrics=m)
        sq = StandingQueries(
            str(tmp_path / "subs-b"), store=store, metrics=m, fsync=False,
            opener=opener, sleep=_NOSLEEP, rng=random.Random(1),
            provenance=reg, fleet="pool",
        )
        sq.subscribe({"filter": FILTER_A, "target": {"url": "http://h/new"},
                      "sub_id": "never-acked"})
        try:
            assert sq.matcher.match_pair(pairs[1]) == 1
            assert _wait_until(lambda: sq.log.pending_total() == 0)
            env = opener.envelopes()[0]
            assert "bundle" in env and "bundle_delta" not in env
        finally:
            sq.drain()
            reg.close()
