"""Differential tests for the stage-overlapped range driver: the pipelined
engine must emit byte-identical bundles to the chunked driver across the
(scan_threads × pipeline_depth × chunk_size) grid, survive empty ranges,
and propagate worker exceptions without deadlocking the executor."""

import threading

import pytest

from ipc_proofs_tpu.backend import get_backend
from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import (
    TipsetPair,
    generate_and_verify_range_overlapped,
    generate_event_proofs_for_range_chunked,
    generate_event_proofs_for_range_pipelined,
)
from ipc_proofs_tpu.proofs.trust import TrustPolicy
from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore
from ipc_proofs_tpu.utils.metrics import Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "pipe-subnet"
ACTOR = 777


def _make_range(n_pairs=4):
    """n_pairs independent synthetic worlds sharing one blockstore."""
    bs = MemoryBlockstore()
    pairs = []
    expected = 0
    for p in range(n_pairs):
        events = [
            [EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET,
                          data=p.to_bytes(32, "big"))] if p % 2 == 0 else [],
            [EventFixture(emitter=ACTOR, signature="Noise()", topic1=SUBNET)],
        ]
        if p % 2 == 0:
            expected += 1
        world = build_chain(
            [ContractFixture(actor_id=ACTOR)],
            events,
            parent_height=100 + 2 * p,
            store=bs,
        )
        pairs.append(TipsetPair(parent=world.parent, child=world.child))
    return bs, pairs, expected


SPEC = dict(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)


class TestDifferentialGrid:
    @pytest.mark.parametrize("scan_threads", [1, 4])
    @pytest.mark.parametrize("pipeline_depth", [1, 3])
    @pytest.mark.parametrize("chunk_size", [1, 7, 512])
    def test_pipelined_matches_chunked(self, scan_threads, pipeline_depth, chunk_size):
        bs, pairs, expected = _make_range(7)
        spec = EventProofSpec(**SPEC)
        reference = generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=chunk_size
        ).to_json()
        for backend in (None, get_backend("cpu")):
            piped = generate_event_proofs_for_range_pipelined(
                bs, pairs, spec,
                chunk_size=chunk_size,
                match_backend=backend,
                scan_threads=scan_threads,
                pipeline_depth=pipeline_depth,
            )
            assert piped.to_json() == reference, (backend, scan_threads, pipeline_depth)
        assert len(piped.event_proofs) == expected

    @pytest.mark.parametrize("scan_threads", [1, 4])
    def test_integrated_verify_matches_chunked(self, scan_threads):
        """verify-while-generate: merged bundle identical to the chunked
        driver, per-chunk verdicts equal to whole-bundle verification."""
        bs, pairs, expected = _make_range(7)
        spec = EventProofSpec(**SPEC)

        def verify_chunk(bundle):
            return verify_proof_bundle(bundle, TrustPolicy.accept_all()).event_results

        for chunk_size in (1, 3, 512):
            reference = generate_event_proofs_for_range_chunked(
                bs, pairs, spec, chunk_size=chunk_size
            )
            merged, chunk_results = generate_and_verify_range_overlapped(
                bs, pairs, spec, chunk_size=chunk_size,
                verify_chunk=verify_chunk, scan_threads=scan_threads,
            )
            assert merged.to_json() == reference.to_json(), chunk_size
            flat = [r for res in chunk_results for r in res]
            whole = verify_proof_bundle(merged, TrustPolicy.accept_all()).event_results
            assert flat == whole, chunk_size
            assert all(flat) and len(flat) == expected

    @pytest.mark.parametrize("record_workers", [1, 3])
    @pytest.mark.parametrize("verify_workers", [1, 2])
    @pytest.mark.parametrize("chunk_size", [1, 3, 512])
    def test_worker_grid_bit_identical(self, record_workers, verify_workers, chunk_size):
        """The parallel record/verify engine is a pure perf change: every
        (record_workers × verify_workers × chunk_size) point must emit the
        byte-identical bundle AND the identical in-order verdict stream the
        chunked driver produces."""
        bs, pairs, expected = _make_range(7)
        spec = EventProofSpec(**SPEC)
        reference = generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=chunk_size
        ).to_json()
        results: list = []
        piped = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec,
            chunk_size=chunk_size,
            record_workers=record_workers,
            verify_workers=verify_workers,
            verify_chunk=lambda b: len(b.event_proofs),
            verify_results=results,
        )
        assert piped.to_json() == reference, (record_workers, verify_workers)
        assert sum(results) == expected
        # verdicts arrive in chunk order even with parallel verify workers
        n_chunks = (len(pairs) + chunk_size - 1) // chunk_size
        assert len(results) == n_chunks

    @pytest.mark.parametrize("record_workers", [1, 3])
    def test_worker_grid_with_storage_specs(self, record_workers):
        """Storage chunks now flow THROUGH the pipeline (not a post-pipeline
        range-wide pass): parallel record workers must still concatenate
        storage proofs in (pair, spec) order and fold one deduplicated
        CID-sorted witness."""
        from ipc_proofs_tpu.proofs.storage_batch import MappingSlotSpec
        from ipc_proofs_tpu.state.storage import calculate_storage_slot

        bs = MemoryBlockstore()
        pairs = []
        for p in range(5):
            world = build_chain(
                [ContractFixture(
                    actor_id=ACTOR,
                    storage={calculate_storage_slot("subnet-x", 0): bytes([p + 1])},
                )],
                [[EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET)]],
                parent_height=100 + 2 * p,
                store=bs,
            )
            pairs.append(TipsetPair(parent=world.parent, child=world.child))
        spec = EventProofSpec(**SPEC)
        storage_specs = [MappingSlotSpec(actor_id=ACTOR, key="subnet-x", slot_index=0)]
        backend = get_backend("cpu")
        reference = generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=2,
            match_backend=backend, storage_specs=storage_specs,
        )
        piped = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=2,
            match_backend=backend, storage_specs=storage_specs,
            record_workers=record_workers, scan_threads=2,
        )
        assert len(piped.storage_proofs) == 5
        assert [str(b.cid) for b in piped.blocks] == [str(b.cid) for b in reference.blocks]
        assert verify_proof_bundle(piped, TrustPolicy.accept_all()).all_valid()

    def test_unified_threads_knob_drives_workers(self):
        """threads= resolves one shared budget; the result is still
        bit-identical to the serial reference (the budget only changes WHO
        does the work, never what is emitted)."""
        bs, pairs, _ = _make_range(6)
        spec = EventProofSpec(**SPEC)
        reference = generate_event_proofs_for_range_chunked(
            bs, pairs, spec, chunk_size=2
        ).to_json()
        piped = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=2, threads=4,
        )
        assert piped.to_json() == reference

    def test_empty_range(self):
        bs, _, _ = _make_range(1)
        spec = EventProofSpec(**SPEC)
        bundle = generate_event_proofs_for_range_pipelined(
            bs, [], spec, scan_threads=4, pipeline_depth=3
        )
        assert bundle.event_proofs == [] and bundle.blocks == []
        results: list = []
        bundle = generate_event_proofs_for_range_pipelined(
            bs, [], spec, verify_chunk=lambda b: ["ran"], verify_results=results
        )
        assert bundle.event_proofs == [] and results == []


class TestWorkerFailure:
    def _drive_with_deadline(self, fn, seconds=30.0):
        out: dict = {}

        def target():
            try:
                out["result"] = fn()
            except BaseException as exc:  # noqa: BLE001
                out["exc"] = exc

        t = threading.Thread(target=target, daemon=True)
        t.start()
        t.join(seconds)
        assert not t.is_alive(), "pipelined driver deadlocked on worker failure"
        if "exc" in out:
            raise out["exc"]
        return out["result"]

    def test_scan_worker_exception_propagates(self, monkeypatch):
        import ipc_proofs_tpu.proofs.range as range_mod

        bs, pairs, _ = _make_range(6)
        spec = EventProofSpec(**SPEC)
        real = range_mod._scan_and_match
        calls = []

        def flaky(cached, chunk, *a, **kw):
            calls.append(chunk)
            if len(calls) == 3:
                raise RuntimeError("scan worker died mid-range")
            return real(cached, chunk, *a, **kw)

        monkeypatch.setattr(range_mod, "_scan_and_match", flaky)

        def run():
            with pytest.raises(RuntimeError, match="scan worker died"):
                generate_event_proofs_for_range_pipelined(
                    bs, pairs, spec, chunk_size=1, scan_threads=4,
                    pipeline_depth=2, scan_retries=0,
                )

        self._drive_with_deadline(run)

    def test_transient_scan_failure_is_retried(self, monkeypatch):
        # with the default retry budget a one-off scan fault self-heals and
        # the bundle is byte-identical to the clean run (persistent faults
        # still propagate — pinned above with scan_retries=0)
        import ipc_proofs_tpu.proofs.range as range_mod

        bs, pairs, _ = _make_range(6)
        spec = EventProofSpec(**SPEC)
        reference = generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=1, scan_threads=4, pipeline_depth=2
        )
        real = range_mod._scan_and_match
        calls = []

        def flaky(cached, chunk, *a, **kw):
            calls.append(chunk)
            if len(calls) == 3:
                raise RuntimeError("scan worker died once")
            return real(cached, chunk, *a, **kw)

        monkeypatch.setattr(range_mod, "_scan_and_match", flaky)

        def run():
            return generate_event_proofs_for_range_pipelined(
                bs, pairs, spec, chunk_size=1, scan_threads=4, pipeline_depth=2
            )

        bundle = self._drive_with_deadline(run)
        assert bundle.to_json() == reference.to_json()
        assert len(calls) > len(pairs)  # the failed chunk really re-scanned

    def test_record_worker_exception_propagates(self, monkeypatch):
        import ipc_proofs_tpu.proofs.range as range_mod

        bs, pairs, _ = _make_range(6)
        spec = EventProofSpec(**SPEC)

        def boom(*a, **kw):
            raise ValueError("record stage died")

        monkeypatch.setattr(range_mod, "_record_chunk", boom)

        def run():
            with pytest.raises(ValueError, match="record stage died"):
                generate_event_proofs_for_range_pipelined(
                    bs, pairs, spec, chunk_size=2, scan_threads=2
                )

        self._drive_with_deadline(run)

    def test_verify_stage_exception_propagates(self):
        bs, pairs, _ = _make_range(4)
        spec = EventProofSpec(**SPEC)

        def bad_verify(bundle):
            raise KeyError("verifier rejected chunk")

        def run():
            with pytest.raises(KeyError, match="verifier rejected chunk"):
                generate_event_proofs_for_range_pipelined(
                    bs, pairs, spec, chunk_size=1, verify_chunk=bad_verify
                )

        self._drive_with_deadline(run)


class TestPipelineMetrics:
    def test_stage_timers_and_overlap_efficiency(self):
        bs, pairs, expected = _make_range(6)
        spec = EventProofSpec(**SPEC)
        m = Metrics()
        results: list = []
        generate_event_proofs_for_range_pipelined(
            bs, pairs, spec, chunk_size=2, scan_threads=2,
            verify_chunk=lambda b: len(b.event_proofs), verify_results=results,
            metrics=m,
        )
        assert sum(results) == expected
        snap = m.snapshot()
        for stage in ("range_scan", "range_record", "range_verify"):
            assert stage in snap["timers"], stage
            assert snap["timers"][stage]["wall_s"] <= snap["timers"][stage]["total_s"] + 1e-6
        assert snap["counters"]["range_proofs"] == expected
        assert "overlap_efficiency" in snap
