"""Offline registry auditor (tools/auditview.py): full-chain verify,
inclusion proof for a served digest, checkpoint diff — all from nothing
but the log file, no daemon."""

import hashlib
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools"))

import auditview  # noqa: E402

from ipc_proofs_tpu.registry import ProvenanceRegistry  # noqa: E402


def _digest(i):
    return hashlib.sha256(f"bundle-{i}".encode()).hexdigest()


@pytest.fixture()
def reg_log(tmp_path):
    reg = ProvenanceRegistry(str(tmp_path), owner="a")
    for i in range(5):
        reg.append_served(
            _digest(i), trace=f"t{i}", key=f"pair:{i}", verdict="valid",
            cids=frozenset({hashlib.sha256(f"c{i}".encode()).digest()}),
        )
    reg.append_base_ack("pool", "k", "s1", _digest(2), 3)
    head = reg.head()
    reg.close()
    return reg.path, head


class TestVerify:
    def test_clean_log_verifies(self, reg_log):
        path, head = reg_log
        out = auditview.verify_log(path)
        assert out["ok"], out
        assert out["records"] == 6
        assert out["kinds"] == {"serve": 5, "base": 1}
        # the offline root/tip equal what the daemon published
        assert out["root"] == head["root"]
        assert out["tip"] == head["tip"]
        assert not out["torn_tail"]

    def test_torn_tail_reported_but_passes(self, reg_log):
        path, _head = reg_log
        with open(path, "ab") as fh:
            fh.write(b"IPR1\xff")
        out = auditview.verify_log(path)
        assert out["ok"] and out["torn_tail"]
        assert out["records"] == 6

    def test_flipped_bit_fails_typed(self, reg_log):
        path, _head = reg_log
        with open(path, "r+b") as fh:
            fh.seek(30)
            b = fh.read(1)
            fh.seek(30)
            fh.write(bytes([b[0] ^ 0x10]))
        out = auditview.verify_log(path)
        assert not out["ok"]
        assert "error" in out


class TestProve:
    def test_inclusion_for_served_digest(self, reg_log):
        path, head = reg_log
        out = auditview.prove_digest(path, _digest(3))
        assert out["ok"], out
        assert out["seq"] == 3 and out["size"] == 6
        assert out["root"] == head["root"]

    def test_pinned_root_binds_log_to_checkpoint(self, reg_log):
        path, head = reg_log
        assert auditview.prove_digest(path, _digest(0), root_hex=head["root"])["ok"]
        # against someone else's root the proof must NOT verify
        bad = hashlib.sha256(b"forged").hexdigest()
        assert not auditview.prove_digest(path, _digest(0), root_hex=bad)["ok"]

    def test_unknown_digest(self, reg_log):
        path, _head = reg_log
        out = auditview.prove_digest(path, "ff" * 32)
        assert not out["ok"] and "no serve record" in out["error"]


class TestDiff:
    def test_head_extends_checkpoint(self, reg_log):
        path, _head = reg_log
        for old in range(0, 7):
            out = auditview.diff_checkpoints(path, old)
            assert out["ok"], (old, out)
            assert len(out["appended"]) == 6 - old
        out = auditview.diff_checkpoints(path, 2)
        assert [r["seq"] for r in out["appended"]] == [2, 3, 4, 5]

    def test_forked_old_root_fails(self, reg_log):
        path, _head = reg_log
        forged = hashlib.sha256(b"other-history").hexdigest()
        out = auditview.diff_checkpoints(path, 3, old_root_hex=forged)
        assert not out["ok"]
        assert "NOT an append-only extension" in out["error"]

    def test_out_of_range(self, reg_log):
        path, _head = reg_log
        assert not auditview.diff_checkpoints(path, 99)["ok"]


class TestCLI:
    def test_verify_exit_codes(self, reg_log, capsys):
        path, head = reg_log
        assert auditview.main(["verify", path]) == 0
        assert "OK:" in capsys.readouterr().out
        assert auditview.main(
            ["prove", path, "--digest", _digest(1), "--root", head["root"]]
        ) == 0
        assert auditview.main(["diff", path, "--old-size", "2", "--json"]) == 0
        out = capsys.readouterr().out
        assert '"ok": true' in out
        # a tampered log exits 1 from every subcommand
        with open(path, "r+b") as fh:
            fh.seek(40)
            b = fh.read(1)
            fh.seek(40)
            fh.write(bytes([b[0] ^ 0x01]))
        assert auditview.main(["verify", path]) == 1
        assert "FAIL" in capsys.readouterr().out
