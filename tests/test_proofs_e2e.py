"""End-to-end proof round-trip tests over synthetic chains, plus tamper tests.

This is the correctness anchor: generate → serialize → verify offline, then
every tamper case must fail verification (SURVEY.md §4's capability gap).
"""

import pytest

from ipc_proofs_tpu.core.cid import CID, RAW
from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
from ipc_proofs_tpu.proofs.event_verifier import create_event_filter
from ipc_proofs_tpu.proofs.generator import (
    EventProofSpec,
    StorageProofSpec,
    generate_proof_bundle,
)
from ipc_proofs_tpu.proofs.trust import MockTrustVerifier, TrustPolicy
from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle
from ipc_proofs_tpu.state.storage import calculate_storage_slot

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001
SLOT = calculate_storage_slot(SUBNET, 0)


def make_world(**kwargs):
    contracts = [ContractFixture(actor_id=ACTOR, storage={SLOT: (42).to_bytes(2, "big")})]
    events = [
        [],  # msg 0: no events
        [EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET, data=b"\x01" * 32)],
        [EventFixture(emitter=999, signature=SIG, topic1=SUBNET)],  # wrong emitter
        [EventFixture(emitter=ACTOR, signature="Other(uint256)", topic1=SUBNET)],
        [
            EventFixture(emitter=ACTOR, signature=SIG, topic1="other-subnet"),
            EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET, data=b"\x02" * 32),
        ],
    ]
    return build_chain(contracts, events, **kwargs)


def generate(world, match_backend=None):
    return generate_proof_bundle(
        world.store,
        world.parent,
        world.child,
        [StorageProofSpec(actor_id=ACTOR, slot=SLOT)],
        [EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)],
        match_backend=match_backend,
    )


class TestRoundTrip:
    def test_generate_and_verify(self):
        world = make_world()
        bundle = generate(world)
        assert len(bundle.storage_proofs) == 1
        # two matching events: msg 1, and the second event of msg 4
        assert len(bundle.event_proofs) == 2
        assert bundle.storage_proofs[0].value == "0x" + (42).to_bytes(32, "big").hex()
        assert {p.exec_index for p in bundle.event_proofs} == {1, 4}
        assert bundle.event_proofs[1].event_index == 1  # second event in msg 4's AMT

        result = verify_proof_bundle(
            bundle,
            TrustPolicy.accept_all(),
            event_filter=create_event_filter(SIG, SUBNET),
        )
        assert result.storage_results == [True]
        assert result.event_results == [True, True]
        assert result.all_valid()

    def test_verify_with_cid_recompute(self):
        world = make_world()
        bundle = generate(world)
        result = verify_proof_bundle(
            bundle, TrustPolicy.accept_all(), verify_witness_cids=True
        )
        assert result.all_valid()

    def test_bundle_verify_loads_witness_store_once(self, monkeypatch):
        """Perf regression: an N-proof bundle must load (and CID-verify) the
        witness exactly once, not once per proof (the reference reloads per
        storage proof, `storage/verifier.rs:68-78`)."""
        import ipc_proofs_tpu.proofs.verifier as verifier_mod
        from ipc_proofs_tpu.proofs import witness as witness_mod

        world = make_world()
        bundle = generate_proof_bundle(
            world.store,
            world.parent,
            world.child,
            [StorageProofSpec(actor_id=ACTOR, slot=SLOT)] * 4,
            [EventProofSpec(event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR)],
        )
        assert len(bundle.storage_proofs) == 4

        calls = {"n": 0}
        real_load = witness_mod.load_witness_store

        def counting_load(blocks, verify_cids=False):
            calls["n"] += 1
            return real_load(blocks, verify_cids=verify_cids)

        import ipc_proofs_tpu.proofs.event_verifier as ev_mod
        import ipc_proofs_tpu.proofs.storage_verifier as sv_mod

        monkeypatch.setattr(witness_mod, "load_witness_store", counting_load)
        monkeypatch.setattr(sv_mod, "load_witness_store", counting_load)
        monkeypatch.setattr(ev_mod, "load_witness_store", counting_load)
        result = verify_proof_bundle(
            bundle, TrustPolicy.accept_all(), verify_witness_cids=True
        )
        assert result.all_valid()
        assert calls["n"] == 1

    def test_json_wire_roundtrip(self):
        world = make_world()
        bundle = generate(world)
        restored = UnifiedProofBundle.from_json(bundle.to_json())
        assert restored.to_json() == bundle.to_json()
        result = verify_proof_bundle(restored, TrustPolicy.accept_all())
        assert result.all_valid()

    def test_multi_block_parent(self):
        world = make_world(n_parent_blocks=3)
        bundle = generate(world)
        assert len(bundle.event_proofs) == 2
        result = verify_proof_bundle(bundle, TrustPolicy.accept_all())
        assert result.all_valid()

    def test_zero_slot_for_absent_key(self):
        world = make_world()
        absent = calculate_storage_slot("no-such-subnet", 7)
        bundle = generate_proof_bundle(
            world.store,
            world.parent,
            world.child,
            [StorageProofSpec(actor_id=ACTOR, slot=absent)],
            [],
        )
        assert bundle.storage_proofs[0].value == "0x" + "00" * 32
        assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).all_valid()

    def test_storage_encodings(self):
        for encoding in ("direct", "wrapper_tuple", "wrapper_map", "inline"):
            contracts = [
                ContractFixture(
                    actor_id=ACTOR,
                    storage={SLOT: b"\x07"},
                    storage_encoding=encoding,
                )
            ]
            world = build_chain(contracts, [[]])
            bundle = generate_proof_bundle(
                world.store,
                world.parent,
                world.child,
                [StorageProofSpec(actor_id=ACTOR, slot=SLOT)],
                [],
            )
            assert bundle.storage_proofs[0].value.endswith("07"), encoding
            assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).all_valid(), encoding

    def test_concat_event_encoding(self):
        events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET, encoding="concat")]]
        world = build_chain([ContractFixture(actor_id=ACTOR)], events)
        bundle = generate_proof_bundle(
            world.store,
            world.parent,
            world.child,
            [],
            [EventProofSpec(event_signature=SIG, topic_1=SUBNET)],
        )
        assert len(bundle.event_proofs) == 1
        assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).all_valid()

    def test_failed_message_has_no_events(self):
        events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1=SUBNET)]]
        world = build_chain(
            [ContractFixture(actor_id=ACTOR)], events, failed_message_indices={0}
        )
        bundle = generate_proof_bundle(
            world.store,
            world.parent,
            world.child,
            [],
            [EventProofSpec(event_signature=SIG, topic_1=SUBNET)],
        )
        assert bundle.event_proofs == []

    def test_preloaded_store_rejects_verify_witness_cids_flag(self):
        # the flag would be silently dropped with a pre-loaded store — must raise
        from ipc_proofs_tpu.proofs.event_verifier import verify_event_proof
        from ipc_proofs_tpu.proofs.storage_verifier import verify_storage_proof
        from ipc_proofs_tpu.proofs.bundle import EventProofBundle
        from ipc_proofs_tpu.proofs.witness import load_witness_store

        world = make_world()
        bundle = generate(world)
        store = load_witness_store(bundle.blocks)
        with pytest.raises(ValueError, match="pre-loaded store"):
            verify_storage_proof(
                bundle.storage_proofs[0], bundle.blocks, lambda e, c: True,
                verify_witness_cids=True, store=store,
            )
        with pytest.raises(ValueError, match="pre-loaded store"):
            verify_event_proof(
                EventProofBundle(proofs=bundle.event_proofs, blocks=bundle.blocks),
                lambda e, c: True, lambda e, c: True,
                verify_witness_cids=True, store=store,
            )

    def test_witness_is_deduplicated_and_sorted(self):
        world = make_world()
        bundle = generate(world)
        cids = [b.cid for b in bundle.blocks]
        assert cids == sorted(cids)
        assert len(cids) == len(set(cids))

    def test_witness_smaller_than_world(self):
        # Two-pass filtering: witness must exclude untouched event AMTs
        world = make_world()
        bundle = generate(world)
        total_world = sum(len(d) for _, d in world.store.items())
        assert bundle.witness_bytes() < total_world


class TestTrustPolicies:
    def test_mock_verifier_gates(self):
        world = make_world()
        bundle = generate(world)
        ok = verify_proof_bundle(
            bundle, TrustPolicy.with_custom_verifier(MockTrustVerifier(True, True))
        )
        assert ok.all_valid()
        bad_child = verify_proof_bundle(
            bundle, TrustPolicy.with_custom_verifier(MockTrustVerifier(True, False))
        )
        assert not any(bad_child.storage_results) and not any(bad_child.event_results)
        bad_parent = verify_proof_bundle(
            bundle, TrustPolicy.with_custom_verifier(MockTrustVerifier(False, True))
        )
        assert all(bad_parent.storage_results)  # storage only anchors the child
        assert not any(bad_parent.event_results)

    def test_f3_certificate_epoch_range(self):
        # bind_tipsets=False — the reference's epoch-only stub semantics
        # (`trust/mod.rs:53-78`).
        from ipc_proofs_tpu.proofs.cert import ECTipSet, FinalityCertificate

        world = make_world()
        bundle = generate(world)
        covering = FinalityCertificate(
            instance=1,
            ec_chain=[
                ECTipSet(key=[], epoch=world.parent.height, power_table=""),
                ECTipSet(key=[], epoch=world.child.height, power_table=""),
            ],
        )
        assert verify_proof_bundle(
            bundle, TrustPolicy.with_f3_certificate(covering, bind_tipsets=False)
        ).all_valid()
        not_covering = FinalityCertificate(
            instance=1, ec_chain=[ECTipSet(key=[], epoch=5, power_table="")]
        )
        result = verify_proof_bundle(
            bundle, TrustPolicy.with_f3_certificate(not_covering, bind_tipsets=False)
        )
        assert not result.all_valid()
        empty = FinalityCertificate(instance=1, ec_chain=[])
        assert not verify_proof_bundle(
            bundle, TrustPolicy.with_f3_certificate(empty, bind_tipsets=False)
        ).all_valid()

    def _cert_for_world(self, world, parent_key=None, child_key=None):
        from ipc_proofs_tpu.proofs.cert import ECTipSet, FinalityCertificate

        return FinalityCertificate(
            instance=1,
            ec_chain=[
                ECTipSet(
                    key=parent_key if parent_key is not None
                    else [str(c) for c in world.parent.cids],
                    epoch=world.parent.height,
                    power_table="",
                ),
                ECTipSet(
                    key=child_key if child_key is not None
                    else [str(c) for c in world.child.cids],
                    epoch=world.child.height,
                    power_table="",
                ),
            ],
        )

    def test_f3_tipset_binding_accepts_real_tipsets(self):
        world = make_world()
        bundle = generate(world)
        cert = self._cert_for_world(world)
        assert verify_proof_bundle(bundle, TrustPolicy.with_f3_certificate(cert)).all_valid()

    def test_f3_tipset_binding_rejects_forged_tipsets(self):
        # The VERDICT tamper case: right epochs, wrong tipset CIDs. The
        # epoch-only stub would accept this; the bound policy must not.
        from ipc_proofs_tpu.core.cid import CID, RAW

        world = make_world()
        bundle = generate(world)
        forged = str(CID.hash_of(b"forged-block", codec=RAW))
        wrong_parent = self._cert_for_world(world, parent_key=[forged])
        result = verify_proof_bundle(bundle, TrustPolicy.with_f3_certificate(wrong_parent))
        assert not any(result.event_results)  # events anchor the parent tipset
        wrong_child = self._cert_for_world(world, child_key=[forged])
        result = verify_proof_bundle(bundle, TrustPolicy.with_f3_certificate(wrong_child))
        assert not result.all_valid()
        assert not any(result.storage_results) and not any(result.event_results)

    def test_f3_tipset_binding_is_order_sensitive_for_parent(self):
        world = make_world(n_parent_blocks=2)
        bundle = generate(world)
        real_key = [str(c) for c in world.parent.cids]
        assert len(real_key) == 2
        cert = self._cert_for_world(world, parent_key=list(reversed(real_key)))
        result = verify_proof_bundle(bundle, TrustPolicy.with_f3_certificate(cert))
        assert not any(result.event_results)

    def test_f3_power_table_delta_chain(self):
        from ipc_proofs_tpu.proofs.cert import (
            ECTipSet,
            FinalityCertificate,
            FinalityCertificateChain,
            PowerTableDelta,
            PowerTableEntry,
            apply_power_table_delta,
        )

        table = [
            PowerTableEntry(1, 100, "k1"),
            PowerTableEntry(2, 50, "k2"),
        ]
        # add participant 3, remove participant 2, bump participant 1
        deltas = [
            PowerTableDelta(1, "25", ""),
            PowerTableDelta(2, "-50", ""),
            PowerTableDelta(3, "10", "k3"),
        ]
        out = apply_power_table_delta(table, deltas)
        assert [(e.participant_id, e.power) for e in out] == [(1, 125), (3, 10)]

        import pytest

        with pytest.raises(ValueError):  # new participant needs a key
            apply_power_table_delta(table, [PowerTableDelta(9, "5", "")])
        with pytest.raises(ValueError):  # power can't go negative
            apply_power_table_delta(table, [PowerTableDelta(2, "-60", "")])
        with pytest.raises(ValueError):  # deltas must be sorted by id (go-f3)
            apply_power_table_delta(
                table, [PowerTableDelta(2, "1", ""), PowerTableDelta(1, "1", "")]
            )
        with pytest.raises(ValueError):  # duplicate participant forbidden
            apply_power_table_delta(
                table, [PowerTableDelta(3, "10", "k3"), PowerTableDelta(3, "-10", "")]
            )

        def ects(epoch):
            return ECTipSet(key=[f"c{epoch}"], epoch=epoch, power_table="")

        def cert(instance, epochs, delta=()):
            return FinalityCertificate(
                instance=instance,
                ec_chain=[ects(e) for e in epochs],
                power_table_delta=list(delta),
            )

        # go-f3 form: cert 2's base repeats cert 1's head (epoch 10)
        chain = FinalityCertificateChain(
            [cert(1, [10], [PowerTableDelta(3, "10", "k3")]), cert(2, [10, 11])]
        )
        final = chain.validate(table)
        assert [e.participant_id for e in final] == [1, 2, 3]

        with pytest.raises(ValueError):  # instance gap
            FinalityCertificateChain([cert(1, [10]), cert(3, [10, 11])]).validate()
        with pytest.raises(ValueError):  # missing base: chain gap
            FinalityCertificateChain([cert(1, [10]), cert(2, [11])]).validate()
        with pytest.raises(ValueError):  # empty EC chain
            FinalityCertificateChain(
                [FinalityCertificate(instance=1, ec_chain=[])]
            ).validate()

    def test_f3_chain_repeated_base_continuity(self):
        # real go-f3/Forest certificates repeat the previous instance's head
        # tipset as the next certificate's BASE; only the suffix is new
        from ipc_proofs_tpu.proofs.cert import (
            ECTipSet,
            FinalityCertificate,
            FinalityCertificateChain,
        )

        def ts(epoch, key, pt="pt"):
            return ECTipSet(key=list(key), epoch=epoch, power_table=pt)

        def cert(instance, chain):
            return FinalityCertificate(instance=instance, ec_chain=chain)

        head1 = ts(12, ["b12"])
        good = FinalityCertificateChain(
            [
                cert(1, [ts(10, ["b10"]), ts(11, ["b11"]), head1]),
                cert(2, [ts(12, ["b12"]), ts(13, ["b13"])]),  # base == head1
            ]
        )
        assert good.validate() is None  # no power table: structural only

        # a stall certificate (instance decided the base, no EC progress)
        # is valid and carries the head forward
        stall = FinalityCertificateChain(
            [
                cert(1, [head1]),
                cert(2, [ts(12, ["b12"])]),  # ECChain == [base] only
                cert(3, [ts(12, ["b12"]), ts(13, ["b13"])]),
            ]
        )
        assert stall.validate() is None

        import pytest

        # same-epoch base with a DIFFERENT key is a fork, not a base
        with pytest.raises(ValueError, match="must equal the previous"):
            FinalityCertificateChain(
                [
                    cert(1, [head1]),
                    cert(2, [ts(12, ["forked"]), ts(13, ["b13"])]),
                ]
            ).validate()
        # same-epoch base with a different power table likewise
        with pytest.raises(ValueError, match="must equal the previous"):
            FinalityCertificateChain(
                [
                    cert(1, [head1]),
                    cert(2, [ts(12, ["b12"], pt="other"), ts(13, ["b13"])]),
                ]
            ).validate()
        # skipping the base entirely (epoch gap) cannot descend from the head
        with pytest.raises(ValueError, match="must equal the previous"):
            FinalityCertificateChain(
                [cert(1, [head1]), cert(2, [ts(13, ["b13"]), ts(14, ["b14"])])]
            ).validate()
        # starting BEFORE the previous head is always a regression
        with pytest.raises(ValueError, match="must equal the previous"):
            FinalityCertificateChain(
                [cert(1, [head1]), cert(2, [ts(11, ["b11"]), ts(13, ["b13"])])]
            ).validate()

    def test_event_filter_rejects_other_events(self):
        world = make_world()
        bundle = generate(world)
        wrong_filter = create_event_filter(SIG, "totally-other-subnet")
        result = verify_proof_bundle(bundle, TrustPolicy.accept_all(), event_filter=wrong_filter)
        assert result.event_results == [False, False]


class TestTamper:
    def _bundle(self):
        world = make_world()
        return generate(world)

    def test_flipped_storage_value(self):
        bundle = self._bundle()
        bundle.storage_proofs[0].value = "0x" + "99" * 32
        assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).storage_results == [False]

    def test_wrong_actor_state_cid(self):
        bundle = self._bundle()
        bundle.storage_proofs[0].actor_state_cid = str(CID.hash_of(b"forged"))
        assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).storage_results == [False]

    def test_wrong_exec_index(self):
        bundle = self._bundle()
        bundle.event_proofs[0].exec_index += 1
        result = verify_proof_bundle(bundle, TrustPolicy.accept_all())
        assert result.event_results[0] is False

    def test_wrong_message_cid(self):
        bundle = self._bundle()
        bundle.event_proofs[0].message_cid = str(CID.hash_of(b"not-a-real-msg", codec=RAW))
        assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).event_results[0] is False

    def test_tampered_event_data(self):
        bundle = self._bundle()
        bundle.event_proofs[0].event_data.data = "0x" + "ff" * 32
        assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).event_results[0] is False

    def test_tampered_topics(self):
        bundle = self._bundle()
        bundle.event_proofs[0].event_data.topics[1] = "0x" + "aa" * 32
        assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).event_results[0] is False

    def test_wrong_emitter(self):
        bundle = self._bundle()
        bundle.event_proofs[0].event_data.emitter = 4242
        assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).event_results[0] is False

    def test_truncated_witness_fails_closed(self):
        bundle = self._bundle()
        # Drop the largest witness block (some structural node)
        biggest = max(range(len(bundle.blocks)), key=lambda i: len(bundle.blocks[i].data))
        del bundle.blocks[biggest]
        try:
            result = verify_proof_bundle(bundle, TrustPolicy.accept_all())
            assert not result.all_valid()
        except KeyError:
            pass  # missing-witness error is also acceptable fail-closed behavior

    def test_swapped_witness_bytes_detected_with_cid_verify(self):
        bundle = self._bundle()
        from ipc_proofs_tpu.proofs.bundle import ProofBlock

        victim = 0
        tampered = ProofBlock(cid=bundle.blocks[victim].cid, data=b"\x82\x00\x01")
        bundle.blocks[victim] = tampered
        with pytest.raises(ValueError):
            verify_proof_bundle(bundle, TrustPolicy.accept_all(), verify_witness_cids=True)

    def test_wrong_child_epoch(self):
        bundle = self._bundle()
        bundle.event_proofs[0].child_epoch += 5
        assert verify_proof_bundle(bundle, TrustPolicy.accept_all()).event_results[0] is False

    def test_wrong_parent_tipset_cids(self):
        bundle = self._bundle()
        bundle.event_proofs[0].parent_tipset_cids = [str(CID.hash_of(b"fake-parent"))]
        result = verify_proof_bundle(bundle, TrustPolicy.accept_all())
        assert result.event_results[0] is False


class TestEthResolution:
    def test_resolve_via_fake_rpc(self):
        from ipc_proofs_tpu.proofs.address import resolve_eth_address_to_actor_id
        from ipc_proofs_tpu.state.address import Address
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore
        from ipc_proofs_tpu.store.testing import FakeLotusClient

        eth = "0x52f864e96e8c85836c2df262ae34d2dc4df5953a"
        f410 = str(Address.from_eth_address(eth))
        client = FakeLotusClient(
            MemoryBlockstore(),
            responses={
                "Filecoin.EthAddressToFilecoinAddress": f410,
                "Filecoin.StateLookupID": "f01001",
            },
        )
        assert resolve_eth_address_to_actor_id(client, eth) == 1001

    def test_resolve_id_address_directly(self):
        from ipc_proofs_tpu.proofs.address import resolve_eth_address_to_actor_id
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore
        from ipc_proofs_tpu.store.testing import FakeLotusClient

        client = FakeLotusClient(
            MemoryBlockstore(),
            responses={"Filecoin.EthAddressToFilecoinAddress": "t0777"},
        )
        assert (
            resolve_eth_address_to_actor_id(client, "0x" + "ab" * 20) == 777
        )
