"""Overload-survival acceptance tests: the deadline differential grid
(deadline shape × path × door → byte-identical bundle or typed
``deadline`` error, never a silently partial one), cooperative
cancellation reclaiming queued work on client disconnect, and degraded
serve mode (every upstream breaker open → warm-tier requests still
bit-identical with ZERO rpc calls, cold requests fail fast typed
``degraded``, recovery without a restart). All hermetic and tier-1."""

import base64
import json
import socket
import time

import pytest

from http.client import HTTPConnection

from ipc_proofs_tpu.cluster import ClusterRouter, LocalShard
from ipc_proofs_tpu.cluster.router import RouterHTTPServer
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.serve import ProofService, ServiceConfig
from ipc_proofs_tpu.serve.httpd import ProofHTTPServer
from ipc_proofs_tpu.store.blockstore import (
    CachedBlockstore,
    MemoryBlockstore,
    RecordingBlockstore,
)
from ipc_proofs_tpu.store.failover import DegradedError, EndpointPool
from ipc_proofs_tpu.store.faults import LocalLotusSession
from ipc_proofs_tpu.store.rpc import LotusClient, RpcBlockstore
from ipc_proofs_tpu.utils.metrics import Metrics
from ipc_proofs_tpu.witness.stream import (
    STREAM_CONTENT_TYPE,
    StreamAbortError,
    decode_bundle_stream,
)

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001

# per-request envelope fields — not part of the proof payload, legitimately
# vary run to run (batch coalescing, timing, trace ids)
_ENVELOPE = ("trace_id", "server_timing", "batch_size")

# every refusal the serve plane may answer with under deadline pressure;
# anything else (or a divergent 200) is a grid violation
_TYPED_DEADLINE = {"deadline", "cancelled"}


@pytest.fixture(scope="module")
def world():
    return build_range_world(
        4, receipts_per_pair=6, events_per_receipt=3, match_rate=0.5,
        signature=SIG, topic1=SUBNET, actor_id=ACTOR, base_height=61_000,
    )


def _spec():
    return EventProofSpec(
        event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR
    )


def _canon(doc: dict) -> str:
    payload = {k: v for k, v in doc.items() if k not in _ENVELOPE}
    return json.dumps(payload, sort_keys=True, separators=(",", ":"))


def _post(port, path, obj, headers=None, timeout=60):
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", path, json.dumps(obj), hdrs)
    resp = conn.getresponse()
    data = resp.read()
    ctype = resp.headers.get("Content-Type", "")
    conn.close()
    return resp.status, ctype, data


def _get(port, path):
    conn = HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, json.loads(data)


# --------------------------------------------------------------------------
# the deadline differential grid
# --------------------------------------------------------------------------

# deadline shapes: ample must succeed; tight (below the 5 ms admission
# floor) must refuse at the door; mid may land either way depending on the
# host's speed — the grid's law is the DICHOTOMY, not the outcome
AMPLE_MS = 60_000.0
TIGHT_MS = 1.0
MID_MS = 25.0


def _classify(status, ctype, data, reference):
    """Map one grid response to its verdict: ``identical`` (200, payload
    byte-equal to the fault-free reference), ``typed`` (a deadline-family
    refusal, buffered 504 or in-stream abort), or a violation string."""
    if STREAM_CONTENT_TYPE in ctype:
        try:
            doc = decode_bundle_stream(data)
        except StreamAbortError as exc:
            if exc.remote_error_type in _TYPED_DEADLINE:
                return "typed"
            return f"stream abort with wrong type: {exc.remote_error_type}"
        if status != 200:
            return f"streamed non-200: {status}"
        if _canon(doc) != reference:
            return "divergent streamed bundle"
        return "identical"
    if status == 200:
        if _canon(json.loads(data)) != reference:
            return "divergent buffered bundle"
        return "identical"
    obj = json.loads(data)
    if status == 504 and obj.get("error_type") in _TYPED_DEADLINE:
        return "typed"
    return f"untyped refusal: {status} {obj}"


class TestDeadlineGridSingleDaemon:
    @pytest.fixture(scope="class")
    def server(self, world):
        store, pairs, _ = world
        service = ProofService(
            store=store, spec=_spec(),
            config=ServiceConfig(max_batch=8, max_wait_ms=2.0, workers=2),
        )
        httpd = ProofHTTPServer(service, pairs=pairs).start()
        yield httpd, service
        httpd.shutdown(timeout=30)

    @pytest.fixture(scope="class")
    def references(self, server):
        """Fault-free per-(pair, door) canonical payloads."""
        httpd, _ = server
        refs = {}
        for i in range(2):
            st, _, data = _post(httpd.port, "/v1/generate", {"pair_index": i})
            assert st == 200, data[:200]
            refs[(i, "buffered")] = _canon(json.loads(data))
            st, ctype, data = _post(
                httpd.port, "/v1/generate", {"pair_index": i, "stream": True}
            )
            assert st == 200 and STREAM_CONTENT_TYPE in ctype
            refs[(i, "stream")] = _canon(decode_bundle_stream(data))
        return refs

    @pytest.mark.parametrize("door", ["buffered", "stream"])
    @pytest.mark.parametrize(
        "deadline_ms,expect",
        [(AMPLE_MS, {"identical"}), (TIGHT_MS, {"typed"}),
         (MID_MS, {"identical", "typed"})],
        ids=["ample", "tight", "mid-expiry"],
    )
    def test_grid_identical_or_typed(
        self, server, references, door, deadline_ms, expect
    ):
        httpd, _ = server
        for i in range(2):
            body = {"pair_index": i, "deadline_ms": deadline_ms}
            if door == "stream":
                body["stream"] = True
            st, ctype, data = _post(httpd.port, "/v1/generate", body)
            verdict = _classify(st, ctype, data, references[(i, door)])
            assert verdict in expect, (door, deadline_ms, i, verdict)

    def test_header_carries_the_budget_too(self, server, references):
        """``X-IPC-Deadline-Ms`` is the same contract as the body field:
        tight refuses typed at the door, ample succeeds identically."""
        httpd, service = server
        rejects0 = service.metrics_snapshot()["counters"].get(
            "serve.deadline_rejects", 0
        )
        st, _, data = _post(
            httpd.port, "/v1/generate", {"pair_index": 0},
            headers={"X-IPC-Deadline-Ms": "1"},
        )
        assert st == 504
        assert json.loads(data)["error_type"] == "deadline"
        st, _, data = _post(
            httpd.port, "/v1/generate", {"pair_index": 0},
            headers={"X-IPC-Deadline-Ms": "60000"},
        )
        assert st == 200
        assert _canon(json.loads(data)) == references[(0, "buffered")]
        c = service.metrics_snapshot()["counters"]
        assert c.get("serve.deadline_rejects", 0) > rejects0
        assert c.get("deadline.rejects.httpd", 0) >= 1


class TestDeadlineGridRouter:
    @pytest.fixture(scope="class")
    def cluster(self, world):
        store, pairs, _ = world
        shards = [
            LocalShard(f"s{i}", store, pairs, _spec()).start()
            for i in range(2)
        ]
        router = ClusterRouter({s.name: s.url for s in shards}, pairs)
        server = RouterHTTPServer(router).start()
        yield server, router
        server.shutdown(timeout=30)
        for s in shards:
            try:
                s.stop(timeout=10)
            except Exception:
                pass

    @pytest.fixture(scope="class")
    def references(self, cluster):
        server, _ = cluster
        body = {"pair_indexes": [0, 1, 2, 3], "chunk_size": 2}
        st, _, data = _post(server.port, "/v1/generate_range", body)
        assert st == 200, data[:200]
        refs = {"buffered": _canon(json.loads(data)["bundle"])}
        st, ctype, data = _post(
            server.port, "/v1/generate_range", dict(body, stream=True)
        )
        assert st == 200 and STREAM_CONTENT_TYPE in ctype
        doc = decode_bundle_stream(data)
        refs["stream"] = _canon(doc)
        return refs

    def _classify_range(self, st, ctype, data, reference):
        if STREAM_CONTENT_TYPE in ctype:
            try:
                doc = decode_bundle_stream(data)
            except StreamAbortError as exc:
                if exc.remote_error_type in _TYPED_DEADLINE:
                    return "typed"
                return f"stream abort with wrong type: {exc.remote_error_type}"
            if _canon(doc) != reference:
                return "divergent streamed bundle"
            return "identical"
        obj = json.loads(data)
        if st == 200:
            if _canon(obj["bundle"]) != reference:
                return "divergent buffered bundle"
            return "identical"
        if st == 504 and obj.get("error_type") in _TYPED_DEADLINE:
            return "typed"
        return f"untyped refusal: {st} {obj}"

    @pytest.mark.parametrize("door", ["buffered", "stream"])
    @pytest.mark.parametrize(
        "deadline_ms,expect",
        [(AMPLE_MS, {"identical"}), (TIGHT_MS, {"typed"}),
         (MID_MS, {"identical", "typed"})],
        ids=["ample", "tight", "mid-expiry"],
    )
    def test_grid_identical_or_typed(
        self, cluster, references, door, deadline_ms, expect
    ):
        server, _ = cluster
        body = {
            "pair_indexes": [0, 1, 2, 3], "chunk_size": 2,
            "deadline_ms": deadline_ms,
        }
        if door == "stream":
            body["stream"] = True
        st, ctype, data = _post(server.port, "/v1/generate_range", body)
        verdict = self._classify_range(st, ctype, data, references[door])
        assert verdict in expect, (door, deadline_ms, verdict)

    def test_router_floor_reject_is_counted(self, cluster, references):
        server, router = cluster
        st, _, data = _post(
            server.port, "/v1/generate_range",
            {"pair_indexes": [0], "deadline_ms": 1},
        )
        assert st == 504
        assert json.loads(data)["error_type"] == "deadline"
        c = router.metrics.snapshot()["counters"]
        assert c.get("serve.deadline_rejects", 0) >= 1
        assert c.get("deadline.rejects.router", 0) >= 1


# --------------------------------------------------------------------------
# cooperative cancellation: a dead client's queued work is reclaimed
# --------------------------------------------------------------------------

class TestDisconnectCancellation:
    def test_disconnect_while_queued_reclaims_the_slot(self, world):
        """Send a generate request, hang up before the batch window
        closes: the disconnect watcher cancels the scope and the batcher
        drops the request at dispatch (``serve.cancelled_inflight``)
        instead of generating into a dead socket."""
        store, pairs, _ = world
        service = ProofService(
            store=store, spec=_spec(),
            config=ServiceConfig(max_batch=8, max_wait_ms=400.0, workers=1),
        )
        httpd = ProofHTTPServer(service, pairs=pairs).start()
        try:
            body = json.dumps({"pair_index": 0}).encode()
            sock = socket.create_connection(("127.0.0.1", httpd.port), timeout=10)
            sock.sendall(
                b"POST /v1/generate HTTP/1.1\r\n"
                b"Host: localhost\r\nContent-Type: application/json\r\n"
                b"Content-Length: %d\r\n\r\n%s" % (len(body), body)
            )
            time.sleep(0.05)  # let the handler enqueue it
            sock.close()  # ...then vanish while it's still queued
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                c = service.metrics_snapshot()["counters"]
                if c.get("serve.cancelled_inflight", 0) >= 1:
                    break
                time.sleep(0.02)
            c = service.metrics_snapshot()["counters"]
            assert c.get("serve.cancelled_inflight", 0) >= 1
            assert c.get("deadline.reclaimed_ms", 0) >= 1
        finally:
            httpd.shutdown(timeout=30)


# --------------------------------------------------------------------------
# degraded serve mode: lotus_down end to end
# --------------------------------------------------------------------------

class _FlippableSession:
    """A LocalLotusSession that can be killed and revived mid-test."""

    def __init__(self, store, dead=True):
        self._inner = LocalLotusSession(store)
        self.dead = dead
        self.calls = 0

    def post(self, url, data=None, headers=None, timeout=None):
        self.calls += 1
        if self.dead:
            raise ConnectionError("endpoint down")
        return self._inner.post(url, data=data, headers=headers, timeout=timeout)


class TestDegradedServe:
    def _build(self, world):
        """A serve plane whose store is warm for pair 0 only, with every
        upstream endpoint initially dead."""
        full_store, pairs, _ = world
        # record exactly the blocks pair 0's generation touches — that set
        # IS the warm tier
        recording = RecordingBlockstore(full_store)
        probe = ProofService(store=recording, spec=_spec())
        try:
            reference = probe.submit_generate(pairs[0]).result(timeout=60)
        finally:
            probe.drain()
        warm = {
            cid: full_store.get(cid) for cid in recording.peek_seen()
        }
        sessions = [
            _FlippableSession(full_store), _FlippableSession(full_store)
        ]
        metrics = Metrics()
        pool = EndpointPool(
            [
                LotusClient("http://ep", session=s, max_retries=1)
                for s in sessions
            ],
            breaker_threshold=1, breaker_reset_s=0.05, metrics=metrics,
        )
        serve_store = CachedBlockstore(
            RpcBlockstore(pool, metrics=metrics), shared_cache=dict(warm)
        )
        service = ProofService(
            store=serve_store, spec=_spec(), metrics=metrics,
            endpoint_pool=pool,
            config=ServiceConfig(max_batch=2, max_wait_ms=1.0, workers=1),
        )
        return service, pool, sessions, reference, warm

    def test_warm_identical_cold_typed_then_recovery(self, world):
        _, pairs, _ = world
        service, pool, sessions, reference, warm = self._build(world)
        httpd = ProofHTTPServer(service, pairs=pairs).start()
        try:
            # enter lotus_down: one pool read trips both dead endpoints
            some_cid = next(iter(warm))
            with pytest.raises((DegradedError, RuntimeError)):
                pool.chain_read_obj(some_cid)
            assert pool.lotus_down
            st, health = _get(httpd.port, "/healthz")
            assert health["status"] == "degraded"
            assert health.get("mode") == "lotus_down"

            # warm request: bit-identical, zero upstream calls
            calls0 = sum(s.calls for s in sessions)
            st, _, data = _post(httpd.port, "/v1/generate", {"pair_index": 0})
            assert st == 200
            got = json.loads(data)
            assert (
                [p["child_block_cid"] for p in got["bundle"]["event_proofs"]]
                == [p.child_block_cid for p in reference.bundle.event_proofs]
            )
            assert sum(s.calls for s in sessions) == calls0  # rpc.calls == 0
            c = service.metrics_snapshot()["counters"]
            assert c.get("degraded.warm_served", 0) >= 1

            # cold request: typed `degraded`, fast — never a stacked
            # retry-timeout wait
            t0 = time.monotonic()
            st, _, data = _post(httpd.port, "/v1/generate", {"pair_index": 1})
            elapsed = time.monotonic() - t0
            assert st == 503
            assert json.loads(data)["error_type"] == "degraded"
            assert elapsed < 1.0

            # recovery: endpoints come back; the next probe that the
            # backoff gate admits closes the loop — no restart
            for s in sessions:
                s.dead = False
            deadline = time.monotonic() + 10
            st = None
            while time.monotonic() < deadline:
                st, _, data = _post(
                    httpd.port, "/v1/generate", {"pair_index": 1}
                )
                if st == 200:
                    break
                time.sleep(0.05)
            assert st == 200, data[:200]
            assert not pool.lotus_down
            c = service.metrics_snapshot()["counters"]
            assert c.get("degraded.entered", 0) >= 1
            assert c.get("degraded.exited", 0) >= 1
            st, health = _get(httpd.port, "/healthz")
            assert health["status"] in ("ok", "degraded")
            assert health.get("mode") != "lotus_down"
        finally:
            httpd.shutdown(timeout=30)
