"""AMT / HAMT round-trip and structure tests."""

import random

import pytest

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.ipld.amt import AMT, amt_build, amt_build_v0
from ipc_proofs_tpu.ipld.hamt import HAMT, hamt_build
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore, RecordingBlockstore


class TestAmtV3:
    def test_dense_roundtrip(self):
        bs = MemoryBlockstore()
        values = [f"value-{i}" for i in range(100)]
        root = amt_build(bs, values, bit_width=5)
        amt = AMT.load(bs, root)
        assert amt.version == 3
        assert amt.count == 100
        for i, v in enumerate(values):
            assert amt.get(i) == v
        assert amt.get(100) is None
        assert amt.get(10**9) is None

    def test_sparse_roundtrip(self):
        bs = MemoryBlockstore()
        entries = {0: "a", 7: "b", 31: "c", 32: "d", 1024: "e", 123456: "f"}
        root = amt_build(bs, entries, bit_width=5)
        amt = AMT.load(bs, root)
        assert amt.count == len(entries)
        for i, v in entries.items():
            assert amt.get(i) == v
        assert amt.get(5) is None

    def test_for_each_is_ordered(self):
        bs = MemoryBlockstore()
        entries = {i: i * 10 for i in random.Random(0).sample(range(10_000), 200)}
        root = amt_build(bs, entries)
        amt = AMT.load(bs, root)
        seen = []
        amt.for_each(lambda i, v: seen.append((i, v)))
        assert seen == sorted(entries.items())

    def test_empty(self):
        bs = MemoryBlockstore()
        root = amt_build(bs, [])
        amt = AMT.load(bs, root)
        assert amt.count == 0
        assert amt.get(0) is None
        assert list(amt.items()) == []

    def test_heights(self):
        bs = MemoryBlockstore()
        # bit_width 5 → width 32; 33 elements forces height 1
        root = amt_build(bs, list(range(33)), bit_width=5)
        assert AMT.load(bs, root).height == 1
        root2 = amt_build(bs, {32 * 32: "deep"}, bit_width=5)
        assert AMT.load(bs, root2).height == 2


class TestAmtV0:
    def test_roundtrip_and_arity(self):
        bs = MemoryBlockstore()
        cids = [CID.hash_of(f"msg-{i}".encode()) for i in range(20)]
        root = amt_build_v0(bs, cids)
        amt = AMT.load(bs, root)
        assert amt.version == 0
        assert amt.bit_width == 3
        for i, c in enumerate(cids):
            assert amt.get(i) == c
        # root block must be a 3-tuple (no bit_width field)
        from ipc_proofs_tpu.core.dagcbor import decode

        assert len(decode(bs.get(root))) == 3

    def test_version_check(self):
        bs = MemoryBlockstore()
        root_v0 = amt_build_v0(bs, [1, 2, 3])
        AMT.load(bs, root_v0, expected_version=0)
        with pytest.raises(ValueError):
            AMT.load(bs, root_v0, expected_version=3)

    def test_width8_height(self):
        bs = MemoryBlockstore()
        root = amt_build_v0(bs, list(range(9)))  # 9 > 8 → height 1
        assert AMT.load(bs, root).height == 1


class TestAmtRecording:
    def test_get_touches_single_path(self):
        bs = MemoryBlockstore()
        root = amt_build(bs, list(range(1000)), bit_width=3)
        rec = RecordingBlockstore(bs)
        amt = AMT.load(rec, root)
        amt.get(999)
        path_len = len(rec.take_seen())
        # height = 3 for 1000 entries at width 8 (8^3=512 < 1000 <= 8^4)
        assert amt.height == 3
        # root + 3 internal/leaf nodes on the path
        assert path_len == 1 + amt.height

    def test_for_each_touches_all_nodes(self):
        bs = MemoryBlockstore()
        root = amt_build(bs, list(range(100)), bit_width=3)
        rec = RecordingBlockstore(bs)
        AMT.load(rec, root).for_each(lambda i, v: None)
        assert len(rec.take_seen()) == len(bs)


class TestHamt:
    def test_small_roundtrip(self):
        bs = MemoryBlockstore()
        entries = {f"key-{i}".encode(): f"val-{i}" for i in range(10)}
        root = hamt_build(bs, entries)
        hamt = HAMT.load(bs, root)
        for k, v in entries.items():
            assert hamt.get(k) == v
        assert hamt.get(b"absent") is None

    def test_large_roundtrip_forces_splits(self):
        bs = MemoryBlockstore()
        entries = {f"key-{i}".encode(): i for i in range(2000)}
        root = hamt_build(bs, entries)
        hamt = HAMT.load(bs, root)
        for k, v in entries.items():
            assert hamt.get(k) == v
        assert len(bs) > 1  # must have split into child nodes
        assert dict(hamt.items()) == entries

    def test_bitwidth_variants(self):
        for bw in (2, 3, 5, 8):
            bs = MemoryBlockstore()
            entries = {bytes([i, i + 1]): i for i in range(50)}
            root = hamt_build(bs, entries, bit_width=bw)
            hamt = HAMT.load(bs, root, bit_width=bw)
            for k, v in entries.items():
                assert hamt.get(k) == v

    def test_wrong_bitwidth_misses(self):
        bs = MemoryBlockstore()
        entries = {f"k{i}".encode(): i for i in range(500)}
        root = hamt_build(bs, entries, bit_width=5)
        bad = HAMT.load(bs, root, bit_width=3)
        # With the wrong bitwidth most lookups miss or err — structure is
        # hash-dependent, so just assert it does NOT behave like bw=5.
        misses = 0
        for k in list(entries)[:50]:
            try:
                if bad.get(k) != entries[k]:
                    misses += 1
            except (KeyError, ValueError):
                misses += 1
        assert misses > 0

    def test_get_touches_single_path(self):
        bs = MemoryBlockstore()
        entries = {f"key-{i}".encode(): i for i in range(5000)}
        root = hamt_build(bs, entries)
        rec = RecordingBlockstore(bs)
        hamt = HAMT.load(rec, root)
        hamt.get(b"key-123")
        touched = len(rec.take_seen())
        assert 1 <= touched <= 4  # root + at most a few levels
        assert touched < len(bs) / 10

    def test_values_can_be_structured(self):
        bs = MemoryBlockstore()
        c = CID.hash_of(b"linked")
        entries = {b"actor": [c, c, 5, b"\x00\x01"]}
        root = hamt_build(bs, entries)
        assert HAMT.load(bs, root).get(b"actor") == [c, c, 5, b"\x00\x01"]

    def test_deterministic_roots(self):
        bs1, bs2 = MemoryBlockstore(), MemoryBlockstore()
        entries = {f"key-{i}".encode(): i for i in range(100)}
        shuffled = dict(sorted(entries.items(), key=lambda kv: hash(kv[0])))
        assert hamt_build(bs1, entries) == hamt_build(bs2, shuffled)


class TestHamtBatchLookup:
    """hamt_get_batch (C walker) ↔ scalar HAMT.get equivalence."""

    def _ext_or_skip(self):
        from ipc_proofs_tpu.backend.native import load_scan_ext

        ext = load_scan_ext()
        if ext is None or not hasattr(ext, "hamt_lookup_batch"):
            pytest.skip("native hamt_lookup_batch unavailable")

    def test_matches_scalar_across_roots_and_absent_keys(self):
        self._ext_or_skip()
        import hashlib

        from ipc_proofs_tpu.ipld.hamt import HAMT, hamt_build, hamt_get_batch
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

        bs = MemoryBlockstore()
        roots, keysets = [], []
        for c in range(5):
            # enough keys to force multi-level nodes and full buckets
            entries = {
                hashlib.sha256(f"{c}:{i}".encode()).digest(): f"v{c}:{i}".encode()
                for i in range(120)
            }
            # one structured value too (values are arbitrary CBOR)
            entries[hashlib.sha256(f"{c}:struct".encode()).digest()] = [1, b"x", {"k": 2}]
            roots.append(hamt_build(bs, entries))
            keysets.append(list(entries))
        owners, keys = [], []
        for c, ks in enumerate(keysets):
            for k in ks:
                owners.append(c)
                keys.append(k)
            owners.append(c)
            keys.append(hashlib.sha256(f"{c}:absent".encode()).digest())
        got = hamt_get_batch(bs, roots, owners, keys)
        assert got is not None
        hamts = [HAMT.load(bs, r) for r in roots]
        expected = [hamts[o].get(k) for o, k in zip(owners, keys)]
        assert got == expected
        assert sum(v is None for v in got) == 5  # exactly the absent probes

    def test_bitwidth_variants_match(self):
        self._ext_or_skip()
        from ipc_proofs_tpu.ipld.hamt import HAMT, hamt_build, hamt_get_batch
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

        for bw in (3, 5, 8):
            bs = MemoryBlockstore()
            entries = {f"key-{i}".encode(): i.to_bytes(2, "big") for i in range(40)}
            root = hamt_build(bs, entries, bit_width=bw)
            keys = list(entries) + [b"nope"]
            got = hamt_get_batch(bs, [root], [0] * len(keys), keys, bit_width=bw)
            hamt = HAMT.load(bs, root, bit_width=bw)
            assert got == [hamt.get(k) for k in keys]

    def test_missing_node_raises_keyerror(self):
        self._ext_or_skip()
        from ipc_proofs_tpu.core.cid import CID
        from ipc_proofs_tpu.ipld.hamt import hamt_get_batch
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

        bs = MemoryBlockstore()
        bogus = CID.hash_of(b"missing-hamt-root")
        with pytest.raises(KeyError):
            hamt_get_batch(bs, [bogus], [0], [b"k"])

    def test_malformed_node_raises_valueerror(self):
        self._ext_or_skip()
        from ipc_proofs_tpu.ipld.hamt import hamt_get_batch
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore, put_cbor

        bs = MemoryBlockstore()
        bad = put_cbor(bs, [1, 2, 3])  # not a [bitfield, pointers] node
        with pytest.raises(ValueError):
            hamt_get_batch(bs, [bad], [0], [b"k"])

    def test_owner_index_validation(self):
        self._ext_or_skip()
        from ipc_proofs_tpu.ipld.hamt import hamt_build, hamt_get_batch
        from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

        bs = MemoryBlockstore()
        root = hamt_build(bs, {b"a": b"1"})
        with pytest.raises(ValueError):
            hamt_get_batch(bs, [root], [3], [b"a"])


class TestRandomShapeEquivalence:
    """Seeded random tree shapes — in-suite slice of the round-5 soak
    (10k HAMTs + 10k AMTs, clean): writer -> reader round-trips, and the
    C batch HAMT walker agrees with the scalar reader on every key."""

    @pytest.mark.parametrize("seed", [0x7EE5, 901144])
    def test_random_hamts_batch_equals_scalar(self, seed):
        from ipc_proofs_tpu.backend.native import load_scan_ext
        from ipc_proofs_tpu.ipld.hamt import hamt_get_batch

        ext = load_scan_ext()
        if ext is None or not hasattr(ext, "hamt_lookup_batch"):
            pytest.skip("native hamt_lookup_batch unavailable")
        rng = random.Random(seed)
        for _ in range(40):
            bw = rng.choice([2, 3, 4, 5, 6, 8])
            kv = {
                rng.randbytes(rng.randrange(1, 40)): rng.randbytes(rng.randrange(0, 40))
                for _ in range(rng.randrange(1, 120))
            }
            bs = MemoryBlockstore()
            root = hamt_build(bs, kv, bit_width=bw)
            h = HAMT.load(bs, root, bit_width=bw)
            keys = list(kv) + [rng.randbytes(8) for _ in range(10)]
            rng.shuffle(keys)
            out = hamt_get_batch(bs, [root], [0] * len(keys), keys, bit_width=bw)
            assert out is not None
            for k, v in zip(keys, out):
                assert h.get(k) == v, (bw, k.hex())
            assert dict(h.items()) == kv

    @pytest.mark.parametrize("seed", [0xA321, 550901])
    def test_random_amts_roundtrip(self, seed):
        rng = random.Random(seed)
        for _ in range(40):
            v0 = rng.random() < 0.5
            bw = 3 if v0 else rng.choice([1, 2, 3, 4, 5, 8])
            hi = rng.choice([50, 1000, 100000])
            entries = {
                rng.randrange(hi): rng.randbytes(rng.randrange(0, 30))
                for _ in range(rng.randrange(0, 150))
            }
            bs = MemoryBlockstore()
            if v0:
                root = amt_build_v0(bs, entries)
                a = AMT.load(bs, root, expected_version=0)
            else:
                root = amt_build(bs, entries, bit_width=bw)
                a = AMT.load(bs, root, expected_version=3)
            got = {}
            a.for_each(lambda i, v: got.__setitem__(i, v))
            assert got == entries
            for probe in list(entries)[:10] + [rng.randrange(hi) for _ in range(5)]:
                assert a.get(probe) == entries.get(probe)
