"""Sharded serve plane tests: hash-ring determinism and redistribution,
scatter-gather bit-identity across a shards × chunk_size grid (including
with a seeded-fault shard in the cluster), kill-a-shard failover with
idempotent re-dispatch, single-follower leader election, shared-store
cross-process eviction, work-steal accounting, and durable
generate_range idempotency. All hermetic and tier-1."""

import json
import os

import pytest

from ipc_proofs_tpu.cluster import (
    ClusterRouter,
    HashRing,
    LocalShard,
    MergeConflictError,
    NoShardsError,
    ShardClient,
    merge_range_bundles,
    pair_ring_key,
    partition_indexes,
)
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked
from ipc_proofs_tpu.store.faults import FaultPlan, FaultyBlockstore
from ipc_proofs_tpu.storex import FollowLeaderLock, SegmentStore
from ipc_proofs_tpu.utils.metrics import Metrics

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001


@pytest.fixture(scope="module")
def world():
    return build_range_world(
        6, 6, 3, 0.3, signature=SIG, topic1=SUBNET, actor_id=ACTOR,
        base_height=51_000,
    )


def _spec():
    return EventProofSpec(
        event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR
    )


def _canonical(bundle: UnifiedProofBundle) -> str:
    return json.dumps(bundle.to_json_obj(), sort_keys=True)


@pytest.fixture(scope="module")
def direct_bundle(world):
    """The single-process comparator: chunked driver over ALL pairs."""
    store, pairs, _ = world
    return generate_event_proofs_for_range_chunked(
        store, list(pairs), _spec(), chunk_size=3
    )


class TestHashRing:
    def test_deterministic_across_instances(self):
        a = HashRing(["s0", "s1", "s2"])
        b = HashRing(["s2", "s0", "s1"])  # insertion order must not matter
        keys = [f"key-{i}" for i in range(200)]
        assert [a.node_for(k) for k in keys] == [b.node_for(k) for k in keys]

    def test_removal_only_moves_the_removed_arc(self):
        ring = HashRing(["s0", "s1", "s2", "s3"])
        keys = [f"key-{i}" for i in range(400)]
        before = {k: ring.node_for(k) for k in keys}
        ring.remove("s2")
        moved = wrong = 0
        for k in keys:
            after = ring.node_for(k)
            if before[k] == "s2":
                moved += 1
                assert after != "s2"
            elif after != before[k]:
                wrong += 1
        assert moved > 0  # s2 owned something
        assert wrong == 0  # nobody else's keys moved

    def test_all_nodes_own_keys(self):
        ring = HashRing(["s0", "s1", "s2"], vnodes=64)
        owners = {ring.node_for(f"key-{i}") for i in range(500)}
        assert owners == {"s0", "s1", "s2"}

    def test_empty_ring_raises(self):
        ring = HashRing()
        with pytest.raises(ValueError, match="empty"):
            ring.node_for("anything")
        with pytest.raises(ValueError):
            HashRing(vnodes=0)

    def test_pair_ring_key_deterministic(self, world):
        _, pairs, _ = world
        keys = [pair_ring_key(p) for p in pairs]
        assert len(set(keys)) == len(keys)  # distinct pairs, distinct keys
        assert keys == [pair_ring_key(p) for p in pairs]


class TestGatherLaws:
    def test_partition_preserves_request_order(self):
        assign = {0: "a", 1: "b", 2: "a", 3: "b", 4: "a"}
        groups = partition_indexes([4, 0, 3, 1, 2], assign)
        assert groups == {"a": [4, 0, 2], "b": [3, 1]}

    def test_merge_rejects_conflicting_witness_bytes(self, world, direct_bundle):
        _, pairs, _ = world
        idxs = list(range(len(pairs)))
        good = direct_bundle
        # forge a sub-bundle whose first witness block lies about its bytes
        block = good.blocks[0]
        forged = UnifiedProofBundle(
            storage_proofs=[],
            event_proofs=[],
            blocks=[type(block)(cid=block.cid, data=block.data + b"x")],
        )
        with pytest.raises(MergeConflictError, match="conflicting"):
            merge_range_bundles([good, forged], pairs, idxs)

    def test_merge_rejects_foreign_proofs(self, world, direct_bundle):
        _, pairs, _ = world
        # a proof for a pair outside the requested index set must not merge
        with pytest.raises(MergeConflictError, match="unknown child"):
            merge_range_bundles([direct_bundle], pairs, [0])


def _shards_up(world, n, store_wrapper_for=None, queue_dir_root=None):
    store, pairs, _ = world
    shards = []
    for i in range(n):
        wrapper = store_wrapper_for(i) if store_wrapper_for else None
        shards.append(
            LocalShard(
                f"s{i}",
                store,
                pairs,
                _spec(),
                queue_dir=(
                    os.path.join(queue_dir_root, f"s{i}")
                    if queue_dir_root
                    else None
                ),
                store_wrapper=wrapper,
            ).start()
        )
    return shards


def _teardown(router, shards):
    router.close()
    for s in shards:
        try:
            s.stop(timeout=10)
        except Exception:
            pass


class TestScatterGatherIdentity:
    @pytest.mark.parametrize("n_shards", [1, 2, 3])
    @pytest.mark.parametrize("chunk_size", [1, 3, 8])
    def test_grid_bit_identical_to_single_process(
        self, world, direct_bundle, n_shards, chunk_size
    ):
        """ANY shard partition × ANY chunking merges to the exact bytes
        the single daemon produces — the cluster's correctness law."""
        _, pairs, _ = world
        shards = _shards_up(world, n_shards)
        router = ClusterRouter({s.name: s.url for s in shards}, pairs)
        try:
            status, obj = router.generate_range(
                list(range(len(pairs))), chunk_size=chunk_size
            )
            assert status == 200, obj
            merged = UnifiedProofBundle.from_json_obj(obj["bundle"])
            assert _canonical(merged) == _canonical(direct_bundle)
            if n_shards > 1:
                assert obj["n_groups"] > 1  # it actually scattered
        finally:
            _teardown(router, shards)

    def test_subset_and_order_identity(self, world):
        """A permuted subset request matches the single-process run over
        the same list — order comes from the request, not the shards."""
        store, pairs, _ = world
        idxs = [4, 1, 3]
        expect = generate_event_proofs_for_range_chunked(
            store, [pairs[i] for i in idxs], _spec(), chunk_size=2
        )
        shards = _shards_up(world, 2)
        router = ClusterRouter({s.name: s.url for s in shards}, pairs)
        try:
            status, obj = router.generate_range(idxs, chunk_size=2)
            assert status == 200, obj
            got = UnifiedProofBundle.from_json_obj(obj["bundle"])
            assert _canonical(got) == _canonical(expect)
        finally:
            _teardown(router, shards)

    @pytest.mark.parametrize("seed", [7, 23])
    def test_identity_with_a_faulty_shard(self, world, direct_bundle, seed):
        """One shard's store injects seeded faults: every scatter must end
        in a typed error OR the exact single-process bytes — never a
        silently wrong bundle."""
        _, pairs, _ = world

        def wrapper_for(i):
            if i != 0:
                return None
            plan = FaultPlan(seed, fault_rate=0.15)
            return lambda s: FaultyBlockstore(s, plan)

        shards = _shards_up(world, 2, store_wrapper_for=wrapper_for)
        m = Metrics()
        router = ClusterRouter(
            {s.name: s.url for s in shards}, pairs, metrics=m
        )
        try:
            for _ in range(3):
                try:
                    status, obj = router.generate_range(
                        list(range(len(pairs))), chunk_size=3
                    )
                except NoShardsError:
                    continue  # both shards condemned — a typed outcome
                if status == 200:
                    got = UnifiedProofBundle.from_json_obj(obj["bundle"])
                    assert _canonical(got) == _canonical(direct_bundle)
                else:
                    assert status in (400, 500, 502, 503, 504)
                    assert "error" in obj
        finally:
            _teardown(router, shards)


class TestFailover:
    def test_kill_a_shard_requests_still_succeed(self, world, direct_bundle):
        _, pairs, _ = world
        shards = _shards_up(world, 2)
        m = Metrics()
        router = ClusterRouter(
            {s.name: s.url for s in shards}, pairs, metrics=m
        )
        try:
            # route once so both shards are warm/known-good
            status, _obj = router.generate_range(list(range(len(pairs))))
            assert status == 200
            victim = router.alive_shards()[0]
            next(s for s in shards if s.name == victim).kill()
            # every request must still succeed, re-dispatched to survivors
            for idx in range(len(pairs)):
                status, obj = router.generate(idx)
                assert status == 200, obj
            status, obj = router.generate_range(list(range(len(pairs))))
            assert status == 200, obj
            got = UnifiedProofBundle.from_json_obj(obj["bundle"])
            assert _canonical(got) == _canonical(direct_bundle)
            assert m.counter_value("cluster.shard_failovers") > 0
            assert router.alive_shards() == sorted(
                s.name for s in shards if s.name != victim
            )
        finally:
            _teardown(router, shards)

    def test_all_shards_dead_is_typed(self, world):
        _, pairs, _ = world
        shards = _shards_up(world, 1)
        router = ClusterRouter({s.name: s.url for s in shards}, pairs)
        try:
            shards[0].kill()
            with pytest.raises(NoShardsError):
                router.generate_range([0, 1])
            status, obj = router.generate(0)
            assert status == 503 or "error" in obj or True
        except NoShardsError:
            pass  # generate may also raise once the ring is empty — typed
        finally:
            _teardown(router, shards)

    def test_revive_restores_routing(self, world):
        _, pairs, _ = world
        shards = _shards_up(world, 2)
        m = Metrics()
        router = ClusterRouter(
            {s.name: s.url for s in shards}, pairs, metrics=m
        )
        try:
            router._mark_dead("s0")
            assert router.alive_shards() == ["s1"]
            router.revive("s0")
            assert router.alive_shards() == ["s0", "s1"]
            status, _ = router.generate(0)
            assert status == 200
        finally:
            _teardown(router, shards)


class TestWorkStealing:
    def test_steal_triggers_on_imbalance(self, world):
        _, pairs, _ = world
        m = Metrics()
        # URLs never dialed: placement is decided before any I/O
        router = ClusterRouter(
            {"s0": "http://127.0.0.1:1", "s1": "http://127.0.0.1:2"},
            pairs,
            steal_threshold=3,
            metrics=m,
        )
        key = pair_ring_key(pairs[0])
        with router._lock:
            affine = router._affinity_locked(key)
        other = "s1" if affine == "s0" else "s0"
        # below threshold: affinity wins despite imbalance
        with router._lock:
            router._shards[affine].inflight = 2
        assert router._acquire(key)[0] == affine
        router._release(affine)
        # at threshold: the least-loaded shard steals it
        with router._lock:
            router._shards[affine].inflight = 3
        assert router._acquire(key)[0] == other
        assert m.counter_value("cluster.steals") == 1
        assert m.snapshot()["gauges"][f"cluster.inflight.{other}"] == 1
        router.close()


class TestLeaderElection:
    def test_single_winner_and_succession(self, tmp_path):
        m = Metrics()
        a = FollowLeaderLock(str(tmp_path))
        b = FollowLeaderLock(str(tmp_path))
        assert a.try_acquire(metrics=m) is True
        assert a.held
        assert b.try_acquire(metrics=m) is False  # flock conflicts across fds
        assert not b.held
        assert a.try_acquire(metrics=m) is True  # idempotent for the holder
        assert m.counter_value("follow.leader_elections") == 1
        a.release()
        assert b.try_acquire(metrics=m) is True  # succession after release
        assert m.counter_value("follow.leader_elections") == 2
        b.release()


class TestSharedStore:
    @staticmethod
    def _block(tag: bytes, i: int):
        from ipc_proofs_tpu.core.cid import CID

        data = (b"%s-%04d-" % (tag, i)) * 40
        return CID.hash_of(data), data

    def test_two_owners_coordinate_eviction(self, tmp_path):
        m = Metrics()
        a = SegmentStore(
            str(tmp_path), cap_bytes=4000, segment_max_bytes=800,
            metrics=m, owner="sa",
        )
        b = SegmentStore(
            str(tmp_path), cap_bytes=4000, segment_max_bytes=800,
            metrics=m, owner="sb",
        )
        written = []
        for i in range(12):
            c, d = self._block(b"aa", i)
            assert a.put(c, d)
            written.append((a, c, d))
            c, d = self._block(b"bb", i)
            assert b.put(c, d)
            written.append((b, c, d))
        assert m.counter_value("storex.shared_evictions") > 0
        names = [n for n in os.listdir(str(tmp_path)) if n.endswith(".blk")]
        # both owners' ACTIVE tails survive coordinated eviction
        owners_left = {n.split(".")[0] for n in names}
        assert owners_left == {"seg-sa", "seg-sb"}
        # directory stays near cap (bounded overshoot, not unbounded growth)
        total = sum(
            os.path.getsize(os.path.join(str(tmp_path), n)) for n in names
        )
        assert total <= 4000 + 2 * 800
        # an evicted block reads as a plain miss; survivors verify
        for store, c, d in written:
            got = store.get(c)
            assert got is None or got == d
        a.close()
        b.close()

    def test_reopen_indexes_all_owners(self, tmp_path):
        a = SegmentStore(str(tmp_path), owner="sa")
        b = SegmentStore(str(tmp_path), owner="sb")
        ca, da = self._block(b"aa", 1)
        cb, db = self._block(b"bb", 1)
        a.put(ca, da)
        b.put(cb, db)
        a.close()
        b.close()
        # a third owner joining the directory sees everyone's blocks
        c = SegmentStore(str(tmp_path), owner="sc")
        assert c.get(ca) == da
        assert c.get(cb) == db
        assert c.stats()["shared"] is True
        c.close()

    def test_owner_token_validation(self, tmp_path):
        from ipc_proofs_tpu.storex import SegmentStoreError

        with pytest.raises(SegmentStoreError, match="owner token"):
            SegmentStore(str(tmp_path), owner="bad/owner")
        with pytest.raises(SegmentStoreError, match="owner token"):
            SegmentStore(str(tmp_path), owner="")


class TestDurableCluster:
    def test_generate_range_idempotency(self, world, tmp_path):
        """The property failover leans on: a retried generate_range with
        the same idempotency key is served from the journal, not re-run."""
        _, pairs, _ = world
        shards = _shards_up(world, 1, queue_dir_root=str(tmp_path))
        client = ShardClient("s0", shards[0].url)
        try:
            body = {"pair_indexes": [0, 2], "idempotency_key": "retry-1"}
            st1, first = client.post("/v1/generate_range", body)
            st2, second = client.post("/v1/generate_range", body)
            assert st1 == st2 == 200
            assert first["cached"] is False
            assert second["cached"] is True
            assert first["result"] == second["result"]
        finally:
            for s in shards:
                s.stop(timeout=10)

    def test_generate_range_validation(self, world):
        _, pairs, _ = world
        shards = _shards_up(world, 1)
        client = ShardClient("s0", shards[0].url)
        try:
            for bad in ([], [999], [True], ["0"], None):
                st, obj = client.post(
                    "/v1/generate_range", {"pair_indexes": bad}
                )
                assert st == 400, (bad, obj)
            st, obj = client.post(
                "/v1/generate_range", {"pair_indexes": [0], "chunk_size": 0}
            )
            assert st == 400
        finally:
            for s in shards:
                s.stop(timeout=10)


class TestClusterTracing:
    def test_one_trace_covers_the_scatter(self, world):
        """Shard-side spans adopt the router's carrier: the whole
        scatter-gather shares one trace id."""
        from ipc_proofs_tpu.obs import disable_tracing, enable_tracing

        _, pairs, _ = world
        shards = _shards_up(world, 2)
        router = ClusterRouter({s.name: s.url for s in shards}, pairs)
        collector = enable_tracing(metrics=Metrics())
        try:
            status, obj = router.generate_range(list(range(len(pairs))))
            assert status == 200
            trace_id = obj["trace_id"]
            spans = [
                s for s in collector.snapshot() if s.trace_id == trace_id
            ]
            names = {s.name for s in spans}
            # router root + dispatches + shard-side adopted request spans
            assert "cluster.generate_range" in names
            assert "cluster.dispatch" in names
            assert "http.generate_range" in names
        finally:
            disable_tracing()
            _teardown(router, shards)
