"""Native Phase-A scanner ↔ pure-Python scan equivalence.

The C scanner (backend/native/scan_ext.c) must produce exactly the arrays
that scan_receipt_events + flatten_events produce, over every event-encoding
case and AMT shape, so the device mask sees identical inputs either way.
"""

import numpy as np
import pytest

from ipc_proofs_tpu.backend.tpu import flatten_events
from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
from ipc_proofs_tpu.proofs.event_generator import scan_receipt_events
from ipc_proofs_tpu.proofs.scan_native import native_scan_available, scan_events_flat
from ipc_proofs_tpu.store.blockstore import MemoryBlockstore

pytestmark = pytest.mark.skipif(
    not native_scan_available(), reason="native scan extension unavailable"
)

SIG = "NewTopDownMessage(bytes32,uint256)"
ACTOR = 4242


def _python_reference(store, roots):
    """The existing Python path, flattened the same way."""
    topics, n_topics, emitters, valid = [], [], [], []
    pair_ids, exec_idx, event_idx = [], [], []
    n_receipts = 0
    for pair_pos, root in enumerate(roots):
        for i, _receipt, events in scan_receipt_events(store, root):
            n_receipts += 1
            t, nt, em, va = flatten_events(events)
            topics.append(t)
            n_topics.append(nt)
            emitters.append(em)
            valid.append(va)
            pair_ids.extend([pair_pos] * len(events))
            exec_idx.extend([i] * len(events))
            event_idx.extend(range(len(events)))
    if topics:
        return (
            np.concatenate(topics),
            np.concatenate(n_topics),
            np.concatenate(emitters).astype(np.uint64),
            np.concatenate(valid),
            np.array(pair_ids, np.int32),
            np.array(exec_idx, np.int32),
            np.array(event_idx, np.int32),
            n_receipts,
        )
    return (
        np.zeros((0, 2, 8), np.uint32), np.zeros(0, np.int32),
        np.zeros(0, np.uint64), np.zeros(0, bool),
        np.zeros(0, np.int32), np.zeros(0, np.int32), np.zeros(0, np.int32), 0,
    )


def assert_scan_matches(store, roots):
    batch = scan_events_flat(store, roots)
    assert batch is not None
    t, nt, em, va, pi, xi, ei, nr = _python_reference(store, roots)
    np.testing.assert_array_equal(batch.topics, t)
    np.testing.assert_array_equal(batch.n_topics, nt)
    np.testing.assert_array_equal(batch.emitters, em)
    np.testing.assert_array_equal(batch.valid, va)
    np.testing.assert_array_equal(batch.pair_ids, pi)
    np.testing.assert_array_equal(batch.exec_idx, xi)
    np.testing.assert_array_equal(batch.event_idx, ei)
    assert batch.n_receipts == nr


class TestNativeScan:
    def test_mixed_events_multi_pair(self):
        bs = MemoryBlockstore()
        roots = []
        for p in range(5):
            events = [
                [EventFixture(emitter=ACTOR, signature=SIG, topic1=f"net-{p}")],
                [],  # receipt without events
                [
                    EventFixture(emitter=9, signature="Noise()", topic1="x"),
                    EventFixture(emitter=ACTOR, signature=SIG, topic1="other",
                                 data=b"\x07" * 32),
                ],
            ]
            world = build_chain(
                [ContractFixture(actor_id=ACTOR)], events,
                parent_height=50 + p, store=bs,
            )
            roots.append(world.child.blocks[0].parent_message_receipts)
        assert_scan_matches(bs, roots)

    def test_concat_topics_encoding(self):
        """Case A: explicit concatenated topics entry (>2 topics too)."""
        bs = MemoryBlockstore()
        events = [[
            EventFixture(emitter=1, signature=SIG, topic1="s",
                         extra_topics=[b"\x01" * 32, b"\x02" * 32]),
            EventFixture(emitter=2, signature=SIG, topic1="s", encoding="concat"),
            EventFixture(emitter=3, signature=SIG, topic1="s", encoding="concat",
                         extra_topics=[b"\x03" * 32]),
        ]]
        world = build_chain([ContractFixture(actor_id=1)], events, store=bs)
        assert_scan_matches(bs, [world.child.blocks[0].parent_message_receipts])

    def test_large_receipt_count_multilevel_amt(self):
        """>8 receipts forces a multi-level v0 AMT; >8 events a v3 one."""
        bs = MemoryBlockstore()
        events = [
            [EventFixture(emitter=ACTOR, signature=SIG, topic1=f"m{m}")
             for _ in range(m % 3)]
            for m in range(30)
        ]
        world = build_chain([ContractFixture(actor_id=ACTOR)], events, store=bs)
        assert_scan_matches(bs, [world.child.blocks[0].parent_message_receipts])

    def test_many_events_one_receipt(self):
        bs = MemoryBlockstore()
        events = [[
            EventFixture(emitter=ACTOR, signature=SIG, topic1=f"t{i}")
            for i in range(20)
        ]]
        world = build_chain([ContractFixture(actor_id=ACTOR)], events, store=bs)
        assert_scan_matches(bs, [world.child.blocks[0].parent_message_receipts])

    def test_empty_root_list(self):
        bs = MemoryBlockstore()
        batch = scan_events_flat(bs, [])
        assert batch is not None and batch.n_events == 0

    def test_missing_block_raises(self):
        from ipc_proofs_tpu.core.cid import CID

        bs = MemoryBlockstore()
        bogus = CID.hash_of(b"nope")
        with pytest.raises(KeyError):
            scan_events_flat(bs, [bogus])

    def test_fallback_get_path(self):
        """Stores without a raw map go through the callable fallback."""

        class OpaqueStore:
            def __init__(self, inner):
                self._inner = inner
                self.gets = 0

            def get(self, cid):
                self.gets += 1
                return self._inner.get(cid)

            def put_keyed(self, cid, data):
                self._inner.put_keyed(cid, data)

            def has(self, cid):
                return self._inner.has(cid)

        bs = MemoryBlockstore()
        events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1="f")]]
        world = build_chain([ContractFixture(actor_id=ACTOR)], events, store=bs)
        opaque = OpaqueStore(bs)
        root = world.child.blocks[0].parent_message_receipts
        batch = scan_events_flat(opaque, [root])
        assert batch is not None and batch.n_events == 1
        assert opaque.gets > 0
        assert_scan_matches(bs, [root])  # same answer as the raw-map path


class TestParallelScan:
    """The pthread fan-out must be byte-identical to the sequential walk
    (contiguous chunk concatenation preserves emission order) and must
    surface the same exception for a bad root."""

    def _big_world(self, n_roots=96):
        bs = MemoryBlockstore()
        roots = []
        for p in range(n_roots):
            events = [
                [
                    EventFixture(emitter=ACTOR, signature=SIG, topic1=f"n{p}"),
                    EventFixture(emitter=9, signature="Other()", topic1="x"),
                ],
                [],
                [EventFixture(emitter=ACTOR, signature=SIG, topic1=f"m{p}")],
            ]
            world = build_chain(
                [ContractFixture(actor_id=ACTOR)],
                events,
                parent_height=1000 + 2 * p,
                store=bs,
            )
            roots.append(world.child.blocks[0].parent_message_receipts)
        return bs, roots

    def test_parallel_matches_sequential(self, monkeypatch):
        import os

        bs, roots = self._big_world()
        # true sequential (Python-dict walk) as the reference side — the
        # snapshot path is otherwise taken even at one thread
        monkeypatch.setenv("IPC_SCAN_NO_SNAPSHOT", "1")
        seq = scan_events_flat(bs, roots, want_payload=True)
        monkeypatch.delenv("IPC_SCAN_NO_SNAPSHOT")
        # BOTH snapshot variants against the dict-walk reference: the
        # single-chunk GIL-held inline path AND the pthread fan-out
        for threads in ("1", "8"):
            monkeypatch.setenv("IPC_SCAN_THREADS", threads)
            par = scan_events_flat(bs, roots, want_payload=True)
            assert par.n_events == seq.n_events and par.n_receipts == seq.n_receipts
            np.testing.assert_array_equal(par.topics, seq.topics)
            np.testing.assert_array_equal(par.fp, seq.fp)
            np.testing.assert_array_equal(par.n_topics, seq.n_topics)
            np.testing.assert_array_equal(par.emitters, seq.emitters)
            np.testing.assert_array_equal(par.valid, seq.valid)
            np.testing.assert_array_equal(par.pair_ids, seq.pair_ids)
            np.testing.assert_array_equal(par.exec_idx, seq.exec_idx)
            np.testing.assert_array_equal(par.event_idx, seq.event_idx)
            # pools are chunk-rebased; per-event payload slices must agree
            for r in range(seq.n_events):
                assert par.event_topics(r) == seq.event_topics(r)
                assert par.event_data(r) == seq.event_data(r)

    def test_parallel_missing_block_raises_keyerror(self, monkeypatch):
        bs, roots = self._big_world()
        raw = bs.raw_map()
        # drop one late root so a non-first chunk hits the error
        del raw[roots[-3].to_bytes()]
        for env in (("IPC_SCAN_THREADS", "8"), ("IPC_SCAN_THREADS", "1"),
                    ("IPC_SCAN_NO_SNAPSHOT", "1")):
            monkeypatch.setenv(*env)
            with pytest.raises(KeyError):
                scan_events_flat(bs, roots)

    def test_parallel_malformed_block_raises_valueerror(self, monkeypatch):
        # a corrupted AMT block on a worker thread must surface as the same
        # ValueError as the sequential walk (never touch PyErr off-GIL)
        bs, roots = self._big_world()
        raw = bs.raw_map()
        raw[roots[-5].to_bytes()] = b"\x83\x00\x01"  # not an AMT root
        for env in (("IPC_SCAN_THREADS", "8"), ("IPC_SCAN_THREADS", "1"),
                    ("IPC_SCAN_NO_SNAPSHOT", "1")):
            monkeypatch.setenv(*env)
            with pytest.raises(ValueError):
                scan_events_flat(bs, roots)

    def test_parallel_skip_missing_prunes_identically(self, monkeypatch):
        bs, roots = self._big_world()
        raw = bs.raw_map()
        del raw[roots[10].to_bytes()]
        monkeypatch.setenv("IPC_SCAN_NO_SNAPSHOT", "1")
        seq = scan_events_flat(bs, roots, skip_missing=True)
        monkeypatch.delenv("IPC_SCAN_NO_SNAPSHOT")
        monkeypatch.setenv("IPC_SCAN_THREADS", "8")
        par = scan_events_flat(bs, roots, skip_missing=True)
        np.testing.assert_array_equal(par.pair_ids, seq.pair_ids)
        np.testing.assert_array_equal(par.fp, seq.fp)
        assert par.n_receipts == seq.n_receipts


class TestForgedInputs:
    """Adversarial witness blocks must fail cleanly, never overflow."""

    def test_forged_deep_amt_root_rejected(self):
        # v0 root [21, 1, node]: passes the height<=64 check but
        # 8^21 = 2^63 would overflow the int64 span — must raise cleanly
        from ipc_proofs_tpu.store.blockstore import put_cbor

        bs = MemoryBlockstore()
        node = [b"\x01", [], [1]]
        root = put_cbor(bs, [21, 1, node])
        with pytest.raises(ValueError, match="too deep"):
            scan_events_flat(bs, [root])

    def test_forged_u64_height_must_not_wrap(self):
        # height 2^32 would truncate to 0 through a naive (int) cast and
        # walk the node as a leaf; the raw u64 must be range-checked first
        from ipc_proofs_tpu.store.blockstore import put_cbor

        bs = MemoryBlockstore()
        node = [b"\x01", [], [1]]
        root = put_cbor(bs, [2**32, 1, node])
        with pytest.raises(ValueError, match="invalid AMT height"):
            scan_events_flat(bs, [root])

    def test_forged_u64_bit_width_must_not_wrap(self):
        # v3 events root with bit_width 2^32+3: wraps to 3 through a naive
        # (int) cast; must be rejected on the raw u64 instead. Reached via a
        # valid v0 receipts AMT whose single receipt links the forged root.
        from ipc_proofs_tpu.store.blockstore import put_cbor

        bs = MemoryBlockstore()
        ev_node = [b"\x01", [], [[1, []]]]
        forged_events = put_cbor(bs, [2**32 + 3, 0, 1, ev_node])
        receipt = [0, b"", 0, forged_events]
        rcpt_node = [b"\x01", [], [receipt]]
        receipts_root = put_cbor(bs, [0, 1, rcpt_node])
        with pytest.raises(ValueError, match="invalid AMT bit width"):
            scan_events_flat(bs, [receipts_root])

    def test_deep_but_valid_python_amt_still_errors_consistently(self):
        # the Python reader tolerates any height; the native scanner bounds
        # it — build a legitimate shallow AMT and confirm both agree first
        bs = MemoryBlockstore()
        events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1="x")]]
        world = build_chain([ContractFixture(actor_id=ACTOR)], events, store=bs)
        assert_scan_matches(bs, [world.child.blocks[0].parent_message_receipts])


class TestFingerprint:
    def test_c_fingerprint_matches_python_target(self):
        from ipc_proofs_tpu.proofs.scan_native import topic_fingerprint
        from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature

        bs = MemoryBlockstore()
        events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1="fp-sub")]]
        world = build_chain([ContractFixture(actor_id=ACTOR)], events, store=bs)
        batch = scan_events_flat(bs, [world.child.blocks[0].parent_message_receipts])
        assert batch.n_events == 1
        expected = topic_fingerprint(hash_event_signature(SIG), ascii_to_bytes32("fp-sub"))
        assert int(batch.fp[0]) == expected

    def test_fp_mask_equals_full_width_mask(self):
        import numpy as np

        from ipc_proofs_tpu.ops.match_jax import (
            event_match_mask_fp_jit,
            event_match_mask_jit,
        )
        from ipc_proofs_tpu.proofs.scan_native import topic_fingerprint
        from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature

        bs = MemoryBlockstore()
        events = [
            [
                EventFixture(emitter=ACTOR, signature=SIG, topic1="match-me"),
                EventFixture(emitter=ACTOR, signature=SIG, topic1="not-me"),
                EventFixture(emitter=99, signature=SIG, topic1="match-me"),
                EventFixture(emitter=ACTOR, signature="Noise()", topic1="match-me"),
            ]
        ]
        world = build_chain([ContractFixture(actor_id=ACTOR)], events, store=bs)
        batch = scan_events_flat(bs, [world.child.blocks[0].parent_message_receipts])
        t0, t1 = hash_event_signature(SIG), ascii_to_bytes32("match-me")
        full = np.asarray(
            event_match_mask_jit(
                batch.topics, batch.n_topics, batch.emitters, batch.valid,
                np.frombuffer(t0, "<u4"), np.frombuffer(t1, "<u4"), ACTOR,
            )
        )[: batch.n_events]
        fp = np.asarray(
            event_match_mask_fp_jit(
                batch.fp, batch.n_topics, batch.emitters, batch.valid,
                topic_fingerprint(t0, t1), ACTOR,
            )
        )[: batch.n_events]
        assert (full == fp).all()
        assert fp.tolist() == [True, False, False, False]


class TestSplitPooled:
    def test_matches_python_slicing(self):
        import numpy as np

        from ipc_proofs_tpu.proofs.scan_native import split_pooled

        items = [b"", b"a", b"hello", b"x" * 100]
        pool = b"".join(items)
        off, pos = [], 0
        for it in items:
            off.append(pos)
            pos += len(it)
        off_a = np.asarray(off, dtype="<i4")
        len_a = np.asarray([len(it) for it in items], dtype="<i4")
        assert split_pooled(pool, off_a, len_a) == items
        assert split_pooled(pool, off_a.tobytes(), len_a.tobytes()) == items

    def test_native_rejects_misaligned_buffers(self):
        from ipc_proofs_tpu.backend.native import load_scan_ext

        ext = load_scan_ext()
        if ext is None or not hasattr(ext, "split_pool"):
            pytest.skip("native split_pool unavailable")
        with pytest.raises(ValueError):
            ext.split_pool(b"abc", b"\x00" * 7, b"\x00" * 5)  # not i32-aligned
        with pytest.raises(ValueError):
            ext.split_pool(b"abc", b"\x00" * 8, b"\x00" * 4)  # length mismatch
        with pytest.raises(ValueError):
            # out-of-bounds slice must raise, not read past the pool
            ext.split_pool(b"abc", (0).to_bytes(4, "little"), (9).to_bytes(4, "little"))


class TestFusedMatchHits:
    """scan_match_hits: the fused scan+match walk must agree with the
    unfused scan→fp-mask pipeline exactly, including error behavior."""

    def _world(self):
        from ipc_proofs_tpu.fixtures import build_range_world

        return build_range_world(24, 4, 3, 0.25, base_height=777_000)

    def test_hits_match_unfused_mask(self):
        if not native_scan_available():
            pytest.skip("native scan unavailable")
        from ipc_proofs_tpu.proofs.scan_native import scan_match_hits, topic_fingerprint
        from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature

        bs, pairs, _ = self._world()
        roots = [p.child.blocks[0].parent_message_receipts for p in pairs]
        t0 = hash_event_signature("NewTopDownMessage(bytes32,uint256)")
        t1 = ascii_to_bytes32("calib-subnet-1")
        for actor in (1001, None):
            n_events, hp, he = scan_match_hits(bs, roots, t0, t1, actor)
            batch = scan_events_flat(bs, roots)
            assert n_events == batch.n_events
            mask = batch.valid & (batch.n_topics >= 2)
            mask &= batch.fp == np.uint64(topic_fingerprint(t0, t1))
            if actor is not None:
                mask &= batch.emitters == np.uint64(actor)
            sel = np.nonzero(mask)[0]
            expected = list(zip(batch.pair_ids[sel].tolist(), batch.exec_idx[sel].tolist()))
            assert list(zip(hp.tolist(), he.tolist())) == expected
            assert len(expected) > 0  # the fixture world has matches

    def test_hits_walk_order_adjacent_duplicates(self):
        if not native_scan_available():
            pytest.skip("native scan unavailable")
        from ipc_proofs_tpu.proofs.scan_native import scan_match_hits
        from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature

        bs = MemoryBlockstore()
        # one receipt emitting THREE matching events -> three adjacent hits
        events = [[
            EventFixture(emitter=ACTOR, signature=SIG, topic1="dup"),
            EventFixture(emitter=ACTOR, signature=SIG, topic1="dup"),
            EventFixture(emitter=ACTOR, signature=SIG, topic1="dup"),
        ]]
        world = build_chain([ContractFixture(actor_id=ACTOR)], events, store=bs)
        t0, t1 = hash_event_signature(SIG), ascii_to_bytes32("dup")
        n_events, hp, he = scan_match_hits(
            bs, [world.child.blocks[0].parent_message_receipts], t0, t1, ACTOR
        )
        assert n_events == 3
        assert hp.tolist() == [0, 0, 0] and he.tolist() == [0, 0, 0]

    def test_missing_block_raises_like_unfused(self):
        if not native_scan_available():
            pytest.skip("native scan unavailable")
        from ipc_proofs_tpu.proofs.scan_native import scan_match_hits
        from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature

        bs = MemoryBlockstore()
        events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1="x")]]
        world = build_chain([ContractFixture(actor_id=ACTOR)], events, store=bs)
        root = world.child.blocks[0].parent_message_receipts
        bs.raw_map().pop(root.to_bytes())
        t0, t1 = hash_event_signature(SIG), ascii_to_bytes32("x")
        with pytest.raises(KeyError):
            scan_match_hits(bs, [root], t0, t1, ACTOR)
        with pytest.raises(KeyError):
            scan_events_flat(bs, [root])

    def test_match_mode_rejects_want_payload(self):
        from ipc_proofs_tpu.backend.native import load_scan_ext

        ext = load_scan_ext()
        if ext is None:
            pytest.skip("native scan unavailable")
        with pytest.raises(ValueError):
            ext.scan_events_batch({}, [], None, want_payload=True, match_fp=7)

    def test_range_driver_fused_vs_forced_unfused(self, monkeypatch):
        if not native_scan_available():
            pytest.skip("native scan unavailable")
        from ipc_proofs_tpu.backend import get_backend
        from ipc_proofs_tpu.proofs.generator import EventProofSpec
        from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range

        bs, pairs, _ = self._world()
        spec = EventProofSpec(
            event_signature="NewTopDownMessage(bytes32,uint256)",
            topic_1="calib-subnet-1",
            actor_id_filter=1001,
        )
        backend = get_backend("cpu")
        fused = generate_event_proofs_for_range(bs, pairs, spec, match_backend=backend)
        monkeypatch.setenv("IPC_SCAN_FUSED_MATCH", "0")
        unfused = generate_event_proofs_for_range(bs, pairs, spec, match_backend=backend)
        assert fused.to_json() == unfused.to_json()
        assert len(fused.event_proofs) > 0


class TestFusedMatchRandomizedDifferential:
    """Seeded random worlds — varied encodings, topic counts, emitters,
    multi-block parents, failed messages — where the fused C scan+match,
    the unfused scan→mask pipeline, and the full generate→verify round
    trip must all agree exactly."""

    SIG = "Rand(bytes32,uint256)"
    TOPIC = "rand-subnet"

    def _random_world(self, rng, bs):
        sigs = [self.SIG, "Other(bytes32)", "Noise()"]
        topics = [self.TOPIC, "other", "x"]
        n_msgs = rng.integers(1, 9)
        events = []
        for _ in range(n_msgs):
            row = []
            for _ in range(int(rng.integers(0, 5))):
                row.append(
                    EventFixture(
                        emitter=int(rng.choice([ACTOR, 7, 99])),
                        signature=str(rng.choice(sigs)),
                        topic1=str(rng.choice(topics)),
                        extra_topics=[bytes([int(rng.integers(0, 256))]) * 32]
                        * int(rng.integers(0, 3)),
                        data=bytes(rng.integers(0, 256, size=int(rng.integers(0, 80)), dtype="uint8")),
                        encoding=str(rng.choice(["compact", "concat"])),
                    )
                )
            events.append(row)
        failed = set()
        for m in range(n_msgs):
            if rng.random() < 0.15:
                failed.add(m)
        return build_chain(
            [ContractFixture(actor_id=ACTOR)],
            events,
            parent_height=int(rng.integers(10, 10_000)),
            n_parent_blocks=int(rng.integers(1, 4)),
            store=bs,
            failed_message_indices=failed or None,
        )

    def test_fused_matches_mask_and_round_trips(self):
        if not native_scan_available():
            pytest.skip("native scan unavailable")
        from ipc_proofs_tpu.backend import get_backend
        from ipc_proofs_tpu.proofs.event_generator import generate_event_proof
        from ipc_proofs_tpu.proofs.event_verifier import verify_event_proof
        from ipc_proofs_tpu.proofs.scan_native import scan_match_hits, topic_fingerprint
        from ipc_proofs_tpu.state.events import ascii_to_bytes32, hash_event_signature

        rng = np.random.default_rng(20260730)
        t0 = hash_event_signature(self.SIG)
        t1 = ascii_to_bytes32(self.TOPIC)
        backend = get_backend("cpu")
        n_bundles = 0
        for trial in range(25):
            bs = MemoryBlockstore()
            world = self._random_world(rng, bs)
            roots = [world.child.blocks[0].parent_message_receipts]
            actor = ACTOR if rng.random() < 0.5 else None
            n_events, hp, he = scan_match_hits(bs, roots, t0, t1, actor)
            batch = scan_events_flat(bs, roots)
            assert n_events == batch.n_events, trial
            mask = batch.valid & (batch.n_topics >= 2)
            mask &= batch.fp == np.uint64(topic_fingerprint(t0, t1))
            if actor is not None:
                mask &= batch.emitters == np.uint64(actor)
            sel = np.nonzero(mask)[0]
            assert list(zip(hp.tolist(), he.tolist())) == list(
                zip(batch.pair_ids[sel].tolist(), batch.exec_idx[sel].tolist())
            ), trial
            # full round trip: generate (uses the fused path via the range
            # driver machinery or scalar here) and verify on both paths
            bundle = generate_event_proof(
                bs, world.parent, world.child, self.SIG, self.TOPIC,
                actor_id_filter=actor, match_backend=backend,
            )
            ok = lambda *a: True
            scalar = verify_event_proof(bundle, ok, ok, batch=False)
            fast = verify_event_proof(bundle, ok, ok, batch=True)
            assert scalar == fast == [True] * len(bundle.proofs), trial
            n_bundles += len(bundle.proofs)
        assert n_bundles > 0  # the sweep actually exercised matches


class TestBlockSnapshot:
    """Persistent snapshot semantics: identical outputs, safe staleness
    (content-addressed stores only add blocks — hits stay valid, misses
    fall through to the live dict), strong refs across value replacement,
    and strict misuse errors."""

    def _world(self, n_pairs=6):
        bs = MemoryBlockstore()
        roots = []
        for p in range(n_pairs):
            events = [
                [EventFixture(emitter=ACTOR, signature=SIG, topic1=f"net-{p}")],
                [EventFixture(emitter=9, signature="Noise()", topic1="x")],
            ]
            world = build_chain(
                [ContractFixture(actor_id=ACTOR)], events,
                parent_height=70 + p, store=bs,
            )
            roots.append(world.child.blocks[0].parent_message_receipts)
        return bs, roots

    def test_snapshot_scan_identical(self):
        from ipc_proofs_tpu.backend.native import load_scan_ext

        ext = load_scan_ext()
        if not hasattr(ext, "make_snapshot"):
            pytest.skip("extension predates snapshots")
        bs, roots = self._world()
        raw = bs.raw_map()
        snap = ext.make_snapshot(raw)
        rb = [c.to_bytes() for c in roots]
        plain = ext.scan_events_batch(raw, rb, None)
        snapped = ext.scan_events_batch(raw, rb, None, snapshot=snap)
        assert plain == snapped
        assert snap.n_blocks == len(raw)

    def test_stale_snapshot_falls_through_to_dict(self):
        from ipc_proofs_tpu.backend.native import load_scan_ext

        ext = load_scan_ext()
        if not hasattr(ext, "make_snapshot"):
            pytest.skip("extension predates snapshots")
        bs, roots = self._world(2)
        raw = bs.raw_map()
        snap = ext.make_snapshot(raw)
        # grow the store AFTER the snapshot: new pair's blocks are only in
        # the dict; the stale snapshot must still scan them correctly
        events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1="late")]]
        world = build_chain(
            [ContractFixture(actor_id=ACTOR)], events,
            parent_height=99, store=bs,
        )
        roots = roots + [world.child.blocks[0].parent_message_receipts]
        rb = [c.to_bytes() for c in roots]
        assert snap.n_blocks < len(raw)
        plain = ext.scan_events_batch(raw, rb, None)
        snapped = ext.scan_events_batch(raw, rb, None, snapshot=snap)
        assert plain == snapped

    def test_value_replacement_keeps_old_object_alive(self):
        """put_keyed overwrites swap in NEW equal-content bytes objects; a
        cached snapshot must hold strong refs so its hit pointers never
        dangle (and content-addressing makes the stale value equal)."""
        from ipc_proofs_tpu.backend.native import load_scan_ext

        ext = load_scan_ext()
        if not hasattr(ext, "make_snapshot"):
            pytest.skip("extension predates snapshots")
        bs, roots = self._world(2)
        raw = bs.raw_map()
        snap = ext.make_snapshot(raw)
        rb = [c.to_bytes() for c in roots]
        before = ext.scan_events_batch(raw, rb, None, snapshot=snap)
        # replace every value object (equal content) — old objects would be
        # freed if the snapshot borrowed instead of owning
        for k in list(raw):
            raw[k] = bytes(bytearray(raw[k]))
        import gc

        gc.collect()
        after = ext.scan_events_batch(raw, rb, None, snapshot=snap)
        assert before == after

    def test_wrong_dict_and_wrong_type_rejected(self):
        from ipc_proofs_tpu.backend.native import load_scan_ext

        ext = load_scan_ext()
        if not hasattr(ext, "make_snapshot"):
            pytest.skip("extension predates snapshots")
        bs, roots = self._world(1)
        raw = bs.raw_map()
        snap = ext.make_snapshot(dict(raw))  # different dict object
        rb = [c.to_bytes() for c in roots]
        with pytest.raises(ValueError):
            ext.scan_events_batch(raw, rb, None, snapshot=snap)
        with pytest.raises(TypeError):
            ext.scan_events_batch(raw, rb, None, snapshot=object())
        with pytest.raises(TypeError):
            ext.make_snapshot([("a", "b")])

    def test_wrapper_caches_and_rebuilds(self):
        from ipc_proofs_tpu.backend.native import load_scan_ext
        from ipc_proofs_tpu.proofs.scan_native import _raw_view, _snapshot_of

        ext = load_scan_ext()
        if not hasattr(ext, "make_snapshot"):
            pytest.skip("extension predates snapshots")
        bs, roots = self._world(2)
        raw, _ = _raw_view(bs)
        s1 = _snapshot_of(bs, raw)
        s2 = _snapshot_of(bs, raw)
        assert s1 is s2  # cached while the store is unchanged
        events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1="grow")]]
        build_chain(
            [ContractFixture(actor_id=ACTOR)], events,
            parent_height=120, store=bs,
        )
        s3 = _snapshot_of(bs, raw)
        assert s3 is not s1 and s3.n_blocks == len(raw)

    @pytest.mark.parametrize("stale", [False, True])
    def test_threaded_fanout_with_snapshot(self, monkeypatch, stale):
        """>=64 roots + IPC_SCAN_THREADS>1 exercises the provided-snapshot
        threaded arm (complete snapshot) and, when stale, the downgrade to
        a transient build — both must match the sequential dict walk."""
        from ipc_proofs_tpu.backend.native import load_scan_ext

        ext = load_scan_ext()
        if not hasattr(ext, "make_snapshot"):
            pytest.skip("extension predates snapshots")
        bs = MemoryBlockstore()
        roots = []
        for p in range(96):
            events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1=f"t{p}")]]
            world = build_chain(
                [ContractFixture(actor_id=ACTOR)], events,
                parent_height=300 + p, store=bs,
            )
            roots.append(world.child.blocks[0].parent_message_receipts)
        raw = bs.raw_map()
        if stale:
            snap = ext.make_snapshot(dict(list(raw.items())[: len(raw) // 2]))
            # a half-dict snapshot of a DIFFERENT dict is rejected; build a
            # stale one properly: snapshot, then grow the store
            snap = ext.make_snapshot(raw)
            world = build_chain(
                [ContractFixture(actor_id=ACTOR)],
                [[EventFixture(emitter=ACTOR, signature=SIG, topic1="zz")]],
                parent_height=500, store=bs,
            )
            roots.append(world.child.blocks[0].parent_message_receipts)
            assert snap.n_blocks < len(raw)
        else:
            snap = ext.make_snapshot(raw)
        rb = [c.to_bytes() for c in roots]
        monkeypatch.setenv("IPC_SCAN_THREADS", "4")
        threaded = ext.scan_events_batch(raw, rb, None, snapshot=snap)
        monkeypatch.setenv("IPC_SCAN_THREADS", "1")
        monkeypatch.setenv("IPC_SCAN_NO_SNAPSHOT", "1")
        sequential = ext.scan_events_batch(raw, rb, None)
        assert threaded == sequential

    def test_no_snapshot_env_disables(self, monkeypatch):
        from ipc_proofs_tpu.backend.native import load_scan_ext
        from ipc_proofs_tpu.proofs.scan_native import _raw_view, _snapshot_of

        ext = load_scan_ext()
        if not hasattr(ext, "make_snapshot"):
            pytest.skip("extension predates snapshots")
        bs, _ = self._world(1)
        raw, _ = _raw_view(bs)
        monkeypatch.setenv("IPC_SCAN_NO_SNAPSHOT", "1")
        assert _snapshot_of(bs, raw) is None


class TestMaterializeBlocks:
    """C witness materialization ≡ the Python loop: same blocks, same
    order, same type/frozen semantics, same errors."""

    def _witness(self):
        bs = MemoryBlockstore()
        events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1="m")]]
        build_chain([ContractFixture(actor_id=ACTOR)], events,
                    parent_height=10, store=bs)
        return bs, sorted(bs.raw_map())

    def test_identical_to_python_loop(self):
        from ipc_proofs_tpu.backend.native import load_dagcbor_ext, load_scan_ext
        from ipc_proofs_tpu.proofs.bundle import ProofBlock

        ext = load_scan_ext()
        dext = load_dagcbor_ext()
        if not hasattr(ext, "materialize_blocks") or dext is None:
            pytest.skip("extension predates materialize_blocks")
        bs, todo = self._witness()
        raw = bs.raw_map()
        import random

        shuffled = list(todo)
        random.Random(7).shuffle(shuffled)  # C sorts internally
        out = ext.materialize_blocks(raw, shuffled, dext.make_cids, ProofBlock)
        cids = dext.make_cids(todo)
        ref = [ProofBlock._make(c, raw[b]) for c, b in zip(cids, todo)]
        assert len(out) == len(ref)
        for a, b in zip(out, ref):
            assert type(a) is ProofBlock and a.cid == b.cid and a.data == b.data

    def test_frozen_and_missing_semantics(self):
        import dataclasses

        from ipc_proofs_tpu.backend.native import load_dagcbor_ext, load_scan_ext
        from ipc_proofs_tpu.core.cid import CID
        from ipc_proofs_tpu.proofs.bundle import ProofBlock

        ext = load_scan_ext()
        dext = load_dagcbor_ext()
        if not hasattr(ext, "materialize_blocks") or dext is None:
            pytest.skip("extension predates materialize_blocks")
        cid = CID.hash_of(b"x")
        raw = {cid.to_bytes(): b"x"}
        (block,) = ext.materialize_blocks(raw, [cid.to_bytes()], dext.make_cids, ProofBlock)
        with pytest.raises(dataclasses.FrozenInstanceError):
            block.cid = None
        absent = CID.hash_of(b"absent").to_bytes()
        with pytest.raises(KeyError):
            ext.materialize_blocks(raw, [absent], dext.make_cids, ProofBlock)
        # fallback path: absent blocks resolved by the callable
        blocks = ext.materialize_blocks(
            raw, [absent], dext.make_cids, ProofBlock,
            lambda cid_obj: b"fetched",
        )
        assert blocks[0].data == b"fetched"
        with pytest.raises(TypeError):
            ext.materialize_blocks(raw, [b"ok", "not-bytes"], dext.make_cids, ProofBlock)
        with pytest.raises(ValueError):
            ext.materialize_blocks(raw, [b"\x00garbage"], dext.make_cids, ProofBlock)

    def test_raw_map_grab_invalidates_cached_snapshot(self):
        """Direct mutation through raw_map() (how tests model corruption)
        cannot be seen by the put_keyed mutation counter — so grabbing the
        mutable view must itself invalidate the cached snapshot, or a
        forged block would be scanned with its pre-mutation bytes."""
        from ipc_proofs_tpu.backend.native import load_scan_ext
        from ipc_proofs_tpu.proofs.scan_native import _raw_view, _snapshot_of

        ext = load_scan_ext()
        if not hasattr(ext, "make_snapshot"):
            pytest.skip("extension predates snapshots")
        bs, _todo = self._witness()
        raw, _ = _raw_view(bs)
        s1 = _snapshot_of(bs, raw)
        assert s1 is not None
        # the grab alone (before any mutation) must force a rebuild
        view = bs.raw_map()
        s2 = _snapshot_of(bs, raw)
        assert s2 is not s1
        # and a mutation through the grabbed view is visible to the next
        # walk because the NEXT grab invalidates again
        key = next(iter(view))
        bs.raw_map()[key] = view[key]
        s3 = _snapshot_of(bs, raw)
        assert s3 is not s2


class TestReceiptBatchErrorOrder:
    """The batched receipts-leaf pipeline parses ahead of the walks; error
    PRECEDENCE must still be the sequential loop's — an earlier receipt's
    events-walk failure (here: missing block, KeyError) outranks a later
    receipt's parse error (ValueError) even though the batch discovers the
    parse error first."""

    def test_earlier_walk_error_beats_later_parse_error(self, monkeypatch):
        from ipc_proofs_tpu.backend.native import load_scan_ext
        from ipc_proofs_tpu.ipld.amt import AMT

        ext = load_scan_ext()
        if not hasattr(ext, "make_snapshot"):
            pytest.skip("extension predates snapshots")
        bs = MemoryBlockstore()
        events = [
            [EventFixture(emitter=ACTOR, signature=SIG, topic1="a")],
            [EventFixture(emitter=ACTOR, signature=SIG, topic1="b")],
        ]
        world = build_chain([ContractFixture(actor_id=ACTOR)], events, store=bs)
        root = world.child.blocks[0].parent_message_receipts
        receipts = dict(AMT.load(bs, root, expected_version=0).items())
        ev_root_0 = receipts[0][3]  # receipt 0's events root CID

        d = dict(bs.raw_map())
        del d[ev_root_0.to_bytes()]  # receipt 0's events walk: KeyError
        # truncate the receipts root block inside receipt 1's tuple tail:
        # its parse now fails with a truncation ValueError
        d[root.to_bytes()] = d[root.to_bytes()][:-2]
        rb = [root.to_bytes()]

        monkeypatch.setenv("IPC_SCAN_NO_SNAPSHOT", "1")
        with pytest.raises((KeyError, ValueError)) as seq_err:
            ext.scan_events_batch(d, rb, None)
        monkeypatch.delenv("IPC_SCAN_NO_SNAPSHOT")
        snap = ext.make_snapshot(d)
        with pytest.raises((KeyError, ValueError)) as batch_err:
            ext.scan_events_batch(d, rb, None, snapshot=snap)
        assert type(batch_err.value) is type(seq_err.value)
        assert str(batch_err.value) == str(seq_err.value)
        # and the sequential error really is the earlier receipt's walk error
        assert isinstance(seq_err.value, KeyError)

    def test_threaded_batch_with_malformed_receipt(self, monkeypatch):
        """A malformed receipt on the GIL-free threaded snapshot path must
        surface as the proper error (not crash): the deferred-error restore
        runs on worker threads with no Python thread state."""
        from ipc_proofs_tpu.backend.native import load_scan_ext
        from ipc_proofs_tpu.ipld.amt import AMT

        ext = load_scan_ext()
        if not hasattr(ext, "make_snapshot"):
            pytest.skip("extension predates snapshots")
        bs = MemoryBlockstore()
        roots = []
        for p in range(96):
            events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1=f"x{p}")],
                      [EventFixture(emitter=ACTOR, signature=SIG, topic1=f"y{p}")]]
            world = build_chain(
                [ContractFixture(actor_id=ACTOR)], events,
                parent_height=2000 + p, store=bs,
            )
            roots.append(world.child.blocks[0].parent_message_receipts)
        d = dict(bs.raw_map())
        # truncate one mid-range receipts root inside its second receipt
        bad = roots[40]
        d[bad.to_bytes()] = d[bad.to_bytes()][:-2]
        snap = ext.make_snapshot(d)
        rb = [c.to_bytes() for c in roots]
        monkeypatch.setenv("IPC_SCAN_THREADS", "4")
        with pytest.raises(ValueError):
            ext.scan_events_batch(d, rb, None, snapshot=snap)

    def test_exec_orders_generator_groups_with_snapshot(self):
        """collect_exec_orders accepts one-shot iterables for groups; the
        next-group prefetch peek must not exhaust them."""
        from ipc_proofs_tpu.backend.native import load_scan_ext

        ext = load_scan_ext()
        if not hasattr(ext, "make_snapshot"):
            pytest.skip("extension predates snapshots")
        bs = MemoryBlockstore()
        tx_groups = []
        for p in range(3):
            events = [[EventFixture(emitter=ACTOR, signature=SIG, topic1=f"g{p}")]]
            world = build_chain(
                [ContractFixture(actor_id=ACTOR)], events,
                parent_height=3000 + p, store=bs,
            )
            tx_groups.append([h.messages.to_bytes() for h in world.parent.blocks])
        raw = bs.raw_map()
        snap = ext.make_snapshot(raw)
        lists = ext.collect_exec_orders(raw, tx_groups, None, headers=False)
        gens = ext.collect_exec_orders(
            raw, [iter(g) for g in tx_groups], None, headers=False,
            snapshot=snap,
        )
        assert lists == gens
