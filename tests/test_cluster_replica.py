"""Multi-host cluster tests: replicated segment tier through the router,
kill-a-host recovery, cut-through streamed relay, and remote members.

The system invariants under test:

- **Kill-a-host**: with ``replication_factor=2``, killing ANY one shard
  mid-load yields complete, byte-identical ``/v1/generate_range``
  bundles (failover re-dispatch), and one supervision pass restores R
  full copies of the dead owner's segment files on the survivors.
- **Read-repair beats Lotus**: a corrupt local frame on one shard
  repairs from its replica peer — the scatter stays byte-identical with
  ZERO new RPC block fetches (``rpc.calls`` delta pinned 0,
  ``storex.replica_repairs`` == integrity evictions).
- **Cut-through relay**: the streamed router door forwards shard Block
  chunks as they arrive — byte-identical to the buffered scatter, at a
  measurably lower router peak memory (tracemalloc).
- **Mid-stream shard death**: a shard dying after its first Block chunk
  ends in a deduped failover retry (byte-identical) or a typed in-band
  Error chunk — never torn buffered-vs-streamed divergence.
- **Remote members**: a `RemoteShard` admitted by URL probes healthy and
  serves ring arcs exactly like a spawned shard.

All hermetic (in-process shards on ephemeral localhost ports) and
tier-1.
"""

import io
import json
import os
import tracemalloc
from http.client import HTTPConnection

import pytest

from ipc_proofs_tpu.cluster import (
    ClusterRouter,
    LocalShard,
    RemoteShard,
    RouterHTTPServer,
    ShardClient,
)
from ipc_proofs_tpu.fixtures import build_range_world
from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
from ipc_proofs_tpu.proofs.generator import EventProofSpec
from ipc_proofs_tpu.proofs.range import generate_event_proofs_for_range_chunked
from ipc_proofs_tpu.serve.service import ServiceConfig
from ipc_proofs_tpu.store.faults import LocalLotusSession
from ipc_proofs_tpu.store.rpc import LotusClient, RpcBlockstore
from ipc_proofs_tpu.utils.metrics import Metrics
from ipc_proofs_tpu.witness.errors import StreamAbortError
from ipc_proofs_tpu.witness.stream import (
    CHUNK_BLOCK,
    STREAM_CONTENT_TYPE,
    BundleStreamWriter,
    decode_bundle_stream,
    iter_stream_chunks,
)

SIG = "NewTopDownMessage(bytes32,uint256)"
SUBNET = "calib-subnet-1"
ACTOR = 1001


@pytest.fixture(scope="module")
def world():
    return build_range_world(
        6, 6, 3, 0.3, signature=SIG, topic1=SUBNET, actor_id=ACTOR,
        base_height=51_000,
    )


def _spec():
    return EventProofSpec(
        event_signature=SIG, topic_1=SUBNET, actor_id_filter=ACTOR
    )


def _canonical_obj(obj) -> str:
    return json.dumps(obj, sort_keys=True)


@pytest.fixture(scope="module")
def direct_bundle(world):
    store, pairs, _ = world
    return generate_event_proofs_for_range_chunked(
        store, list(pairs), _spec(), chunk_size=3
    )


def _disk_shards_up(world, root, n):
    """N shards, each with its OWN disk tier (1-byte roll threshold so
    every spilled block is a pullable rolled segment immediately), a
    tiny tier-1 cache (so repeat reads actually hit disk), and its own
    RPC-counted inner store — the Lotus stand-in whose ``rpc.calls``
    the repair tests pin."""
    bs, pairs, _ = world
    shards, metrics = [], []
    for i in range(n):
        m = Metrics()
        inner = RpcBlockstore(
            LotusClient(
                "http://test-cluster-replica",
                session=LocalLotusSession(bs),
                metrics=m,
            )
        )
        shards.append(
            LocalShard(
                f"s{i}",
                inner,
                pairs,
                _spec(),
                config=ServiceConfig(
                    max_batch=8, max_wait_ms=5.0, workers=1,
                    store_dir=os.path.join(str(root), f"s{i}"),
                    store_owner=f"s{i}",
                    store_segment_max_bytes=1,
                    cache_max_bytes=1,
                    batch_rpc=False,
                ),
                metrics=m,
            ).start()
        )
        metrics.append(m)
    return shards, metrics


def _teardown(router, shards):
    router.close()
    for s in shards:
        try:
            s.stop(timeout=10)
        except Exception:
            pass


def _rpc_calls(m: Metrics) -> int:
    return m.snapshot()["counters"].get("rpc.calls", 0)


def _owned_segments(shard, owner: str) -> "set[str]":
    return {
        d["name"]
        for d in shard.service.disk_store.segment_files()
        if d["owner"] == owner and not d["active"]
    }


class TestKillAHostGrid:
    @pytest.mark.parametrize("victim_idx", [0, 1, 2])
    def test_kill_any_host_yields_identical_bytes_and_restores_r(
        self, world, direct_bundle, tmp_path, victim_idx
    ):
        """R=2, three hosts: warm the tier, replicate, kill ONE host —
        the next scatter must fail over to byte-identical bundles, and a
        supervision pass must re-replicate the dead owner's arcs onto
        BOTH survivors (a dead owner needs R full copies: its own copy
        died with it)."""
        _, pairs, _ = world
        shards, _metrics = _disk_shards_up(world, tmp_path, 3)
        m = Metrics()
        router = ClusterRouter(
            {s.name: s.url for s in shards}, pairs,
            replication_factor=2, metrics=m, scrape_interval_s=60.0,
        )
        try:
            status, obj = router.generate_range(
                list(range(len(pairs))), chunk_size=3
            )
            assert status == 200, obj
            summary = router.replicate_now()
            assert not summary["errors"], summary
            victim = shards[victim_idx]
            victim_segs = _owned_segments(victim, victim.name)
            assert victim_segs  # the warm scatter spilled segments
            victim.kill()

            status, obj = router.generate_range(
                list(range(len(pairs))), chunk_size=3
            )
            assert status == 200, obj
            got = UnifiedProofBundle.from_json_obj(obj["bundle"])
            assert _canonical_obj(got.to_json_obj()) == _canonical_obj(
                direct_bundle.to_json_obj()
            )
            assert m.counter_value("cluster.shard_failovers") > 0
            assert router.alive_shards() == sorted(
                s.name for s in shards if s is not victim
            )

            # R restored: every survivor now holds the dead owner's FULL
            # rolled segment set (pulled peer-to-peer, never from Lotus)
            summary = router.replicate_now()
            assert not summary["errors"], summary
            for survivor in shards:
                if survivor is victim:
                    continue
                assert victim_segs <= _owned_segments(survivor, victim.name)
        finally:
            _teardown(router, shards)


def _flip_last_byte(path: str) -> None:
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(size - 1)
        b = fh.read(1)
        fh.seek(size - 1)
        fh.write(bytes([b[0] ^ 0x40]))


class TestClusterReadRepair:
    def test_corrupt_frames_repair_from_replica_with_zero_rpc(
        self, world, direct_bundle, tmp_path
    ):
        """Corrupt EVERY rolled frame on one shard's disk: the next
        scatter must stay byte-identical, every integrity eviction must
        repair from the replica peer, and the RPC (Lotus) call count
        must not move on either shard."""
        _, pairs, _ = world
        shards, metrics = _disk_shards_up(world, tmp_path, 2)
        m = Metrics()
        router = ClusterRouter(
            {s.name: s.url for s in shards}, pairs,
            replication_factor=2, metrics=m, scrape_interval_s=60.0,
        )
        try:
            status, _obj = router.generate_range(
                list(range(len(pairs))), chunk_size=3
            )
            assert status == 200
            summary = router.replicate_now()
            assert not summary["errors"], summary
            assert summary["under_replicated"] == []
            # each owner's plan names the other shard — 2 hosts, R=2
            assert summary["plan"] == {"s0": ["s1"], "s1": ["s0"]}
            rpc_before = [_rpc_calls(mm) for mm in metrics]

            s0_dir = os.path.join(str(tmp_path), "s0")
            flipped = 0
            for name in sorted(os.listdir(s0_dir)):
                if name.endswith(".blk"):
                    _flip_last_byte(os.path.join(s0_dir, name))
                    flipped += 1
            assert flipped > 0

            status, obj = router.generate_range(
                list(range(len(pairs))), chunk_size=3
            )
            assert status == 200, obj
            got = UnifiedProofBundle.from_json_obj(obj["bundle"])
            assert _canonical_obj(got.to_json_obj()) == _canonical_obj(
                direct_bundle.to_json_obj()
            )
            # Lotus was never consulted — the repair plane absorbed every
            # corrupt frame, and repairs account for ALL evictions
            assert [_rpc_calls(mm) for mm in metrics] == rpc_before
            c0 = metrics[0].snapshot()["counters"]
            assert c0.get("storex.integrity_evictions", 0) > 0
            assert c0.get("storex.replica_repairs", 0) == c0.get(
                "storex.integrity_evictions", 0
            )
            assert "storex.replica_repair_misses" not in c0
            # the supervision pass is visible in cluster_status
            status, cs = router.cluster_status()
            assert status == 200
            assert cs["replication"]["factor"] == 2
            assert cs["replication"]["last_pass"]["plan"] == summary["plan"]
        finally:
            _teardown(router, shards)


class TestReplicationPlan:
    def test_plan_deterministic_and_dead_owner_needs_full_r(self, world):
        _, pairs, _ = world
        router = ClusterRouter(
            {f"s{i}": f"http://127.0.0.1:{9000 + i}" for i in range(3)},
            pairs, replication_factor=2, scrape_interval_s=60.0,
        )
        try:
            with router._lock:
                plan1 = router._replication_plan_locked()
                plan2 = router._replication_plan_locked()
            assert plan1 == plan2  # pure function of membership
            for owner, replicas in plan1.items():
                assert len(replicas) == 1  # live owner: R-1 mirrors
                assert owner not in replicas
            # a dead owner's token needs R FULL copies elsewhere
            router._shards["s0"].alive = False
            with router._lock:
                plan3 = router._replication_plan_locked()
            assert len(plan3["s0"]) == 2
            assert "s0" not in plan3["s0"]
        finally:
            router.close()

    def test_factor_one_is_off(self, world):
        _, pairs, _ = world
        router = ClusterRouter(
            {"s0": "http://127.0.0.1:9000"}, pairs, scrape_interval_s=60.0
        )
        try:
            summary = router.replicate_now()
            assert summary["factor"] == 1
            assert summary["plan"] == {}
        finally:
            router.close()


def _post_http(port, path, obj, headers=None, raw=False):
    conn = HTTPConnection("127.0.0.1", port, timeout=120)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", path, json.dumps(obj), hdrs)
    resp = conn.getresponse()
    data = resp.read()
    headers_out = dict(resp.getheaders())
    conn.close()
    return resp.status, headers_out, (data if raw else json.loads(data))


class TestCutThroughRelay:
    @pytest.fixture()
    def cluster(self, world):
        store, pairs, _ = world
        shards = [
            LocalShard(f"s{i}", store, pairs, _spec()).start()
            for i in range(2)
        ]
        m = Metrics()
        router = ClusterRouter(
            {s.name: s.url for s in shards}, pairs,
            metrics=m, scrape_interval_s=60.0,
        )
        server = RouterHTTPServer(router).start()
        yield server, router, shards, m
        server.shutdown(timeout=10)
        _teardown(router, shards)

    def test_streamed_scatter_is_byte_identical_and_cut_through(
        self, cluster, world, direct_bundle
    ):
        _, pairs, _ = world
        server, _router, _shards, m = cluster
        idxs = list(range(len(pairs)))
        st, _, buffered = _post_http(
            server.port, "/v1/generate_range", {"pair_indexes": idxs}
        )
        assert st == 200, buffered
        st, hdrs, raw = _post_http(
            server.port, "/v1/generate_range", {"pair_indexes": idxs},
            headers={"Accept": STREAM_CONTENT_TYPE}, raw=True,
        )
        assert st == 200
        assert hdrs.get("Content-Type") == STREAM_CONTENT_TYPE
        fields = decode_bundle_stream(raw)  # digest-checked reassembly
        assert _canonical_obj(fields["bundle"]) == _canonical_obj(
            buffered["bundle"]
        )
        assert _canonical_obj(fields["bundle"]) == _canonical_obj(
            direct_bundle.to_json_obj()
        )
        # every shard group streamed — none fell back to buffered JSON
        assert m.counter_value("cluster.stream_cut_through") == fields[
            "n_groups"
        ]

    def test_cut_through_drops_router_peak_memory(self):
        """The satellite pin: the same streamed scatter, relayed
        cut-through, peaks measurably below the store-and-forward
        router (which buffers each shard's whole sub-response). A
        larger-than-module world so the payload dominates the peak
        rather than fixed per-request overheads."""
        store, pairs, _ = build_range_world(
            8, 12, 6, 0.6, signature=SIG, topic1=SUBNET, actor_id=ACTOR,
            base_height=51_000,
        )
        shards = [
            LocalShard(f"s{i}", store, pairs, _spec()).start()
            for i in range(2)
        ]
        routers = {
            on: ClusterRouter(
                {s.name: s.url for s in shards}, pairs,
                cut_through=on, scrape_interval_s=60.0,
            )
            for on in (False, True)
        }
        idxs = list(range(len(pairs)))

        def run(router):
            out = router.generate_range(
                idxs,
                chunk_size=3,
                writer_factory=lambda: BundleStreamWriter(
                    lambda buffers: None, metrics=Metrics()
                ),
            )
            assert out is None  # streamed to completion

        def peak(router):
            run(router)  # warm (imports, caches) outside the window
            tracemalloc.start()
            run(router)
            _cur, peak_bytes = tracemalloc.get_traced_memory()
            tracemalloc.stop()
            return peak_bytes

        try:
            peak_buffered = peak(routers[False])
            peak_cut = peak(routers[True])
            assert peak_cut < peak_buffered, (peak_cut, peak_buffered)
            # The in-process LocalShards share this heap, so the
            # shard-side bundle build is a fixed floor under BOTH
            # numbers; the measurable delta is exactly the router's
            # store-and-forward copy (full sub-response text + parsed
            # JSON). Pin at least a 10% total-process drop (measured
            # ~20% on this world).
            assert peak_cut < peak_buffered * 0.9, (
                peak_cut, peak_buffered,
            )
        finally:
            for router in routers.values():
                router.close()
            for s in shards:
                try:
                    s.stop(timeout=10)
                except Exception:
                    pass


class _Tee:
    """File-like wrapper that records every byte read through it."""

    def __init__(self, fp):
        self.fp = fp
        self.buf = bytearray()

    def read(self, n=-1):
        got = self.fp.read(n)
        if got:
            self.buf.extend(got)
        return got


class _DiesAfterFirstBlock(ShardClient):
    """A shard whose stream cleanly dies right after its first Block
    chunk — the wire shape of a host killed mid-stream (the router sees
    EOF with no trailer, a transport-level truncation)."""

    def post_stream(self, path, body):
        kind, payload = super().post_stream(path, body)
        if kind != "stream":
            return kind, payload
        tee = _Tee(payload)
        for chunk_kind, _chunk in iter_stream_chunks(tee):
            if chunk_kind == CHUNK_BLOCK:
                break
        try:
            payload.close()
        except OSError:
            pass
        return "stream", io.BytesIO(bytes(tee.buf))


class TestShardDeathMidStream:
    def test_death_after_first_block_fails_over_deduped(
        self, world, direct_bundle
    ):
        """The shard dies with one Block chunk already relayed to the
        client. The failover retry (same idempotency key, surviving
        shard) re-sends that block; the fold's first-sight dedup absorbs
        it, and the reassembled stream is byte-identical — never torn."""
        store, pairs, _ = world
        shards = [
            LocalShard(f"s{i}", store, pairs, _spec()).start()
            for i in range(2)
        ]
        m = Metrics()
        router = ClusterRouter(
            {
                "s0": _DiesAfterFirstBlock("s0", shards[0].url),
                "s1": ShardClient("s1", shards[1].url),
            },
            pairs, metrics=m, scrape_interval_s=60.0,
        )
        server = RouterHTTPServer(router).start()
        try:
            st, hdrs, raw = _post_http(
                server.port, "/v1/generate_range",
                {"pair_indexes": list(range(len(pairs)))},
                headers={"Accept": STREAM_CONTENT_TYPE}, raw=True,
            )
            assert st == 200
            assert hdrs.get("Content-Type") == STREAM_CONTENT_TYPE
            fields = decode_bundle_stream(raw)
            assert _canonical_obj(fields["bundle"]) == _canonical_obj(
                direct_bundle.to_json_obj()
            )
            assert m.counter_value("cluster.shard_failovers") >= 1
            # the already-forwarded block came again on the retry and was
            # absorbed, not duplicated on the client wire
            assert m.counter_value("cluster.stream_blocks_deduped") >= 1
        finally:
            server.shutdown(timeout=10)
            _teardown(router, shards)

    def test_death_with_no_survivor_is_a_typed_error_chunk(self, world):
        """No failover target: the stream must end in a typed in-band
        Error chunk the client decoder raises on — never a torn
        partial document."""
        store, pairs, _ = world
        shards = [LocalShard("s0", store, pairs, _spec()).start()]
        router = ClusterRouter(
            {"s0": _DiesAfterFirstBlock("s0", shards[0].url)},
            pairs, scrape_interval_s=60.0,
        )
        server = RouterHTTPServer(router).start()
        try:
            st, hdrs, raw = _post_http(
                server.port, "/v1/generate_range",
                {"pair_indexes": list(range(len(pairs)))},
                headers={"Accept": STREAM_CONTENT_TYPE}, raw=True,
            )
            assert st == 200  # committed before the death — error is in-band
            assert hdrs.get("Content-Type") == STREAM_CONTENT_TYPE
            with pytest.raises(StreamAbortError):
                decode_bundle_stream(raw)
        finally:
            server.shutdown(timeout=10)
            _teardown(router, shards)


class TestRemoteShardMembers:
    def test_remote_member_probes_and_serves(self, world, direct_bundle):
        """A shard admitted by URL (`RemoteShard`) — the multi-host door:
        health-probed at admission, then a full ring member."""
        store, pairs, _ = world
        backing = [
            LocalShard(f"b{i}", store, pairs, _spec()).start()
            for i in range(2)
        ]
        try:
            remote = RemoteShard(backing[0].url)
            health = remote.probe()
            assert isinstance(health, dict)
            assert remote.alive
            router = ClusterRouter(
                {remote.name: remote.url, "s1": backing[1].url},
                pairs, scrape_interval_s=60.0,
            )
            try:
                status, obj = router.generate_range(
                    list(range(len(pairs))), chunk_size=3
                )
                assert status == 200, obj
                got = UnifiedProofBundle.from_json_obj(obj["bundle"])
                assert _canonical_obj(got.to_json_obj()) == _canonical_obj(
                    direct_bundle.to_json_obj()
                )
            finally:
                router.close()
        finally:
            for s in backing:
                try:
                    s.stop(timeout=10)
                except Exception:
                    pass

    def test_dead_remote_probe_is_none(self):
        assert RemoteShard("http://127.0.0.1:1", timeout_s=0.5).probe() is None
