"""go-f3 gpbft signing payload: golden layout bytes + certificate wiring.

The golden test constructs the expected `Payload.MarshalForSigning` byte
string independently (field by field, straight from the documented layout)
and pins `proofs/gpbft.py` against it, so any accidental reordering or
width change breaks loudly. NOTES_r05.md records why live go-f3 fixtures
are unavailable; the layout's derivation is documented in the module.
"""

import struct

import pytest

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.proofs import gpbft
from ipc_proofs_tpu.proofs.cert import ECTipSet, FinalityCertificate, SupplementalData


def _cid(tag: str) -> CID:
    return CID.hash_of(tag.encode())


class TestLayout:
    def test_golden_payload_bytes(self):
        pt0, pt1, ptn = _cid("pt-0"), _cid("pt-1"), _cid("pt-next")
        blk_a, blk_b, blk_c = _cid("blk-a"), _cid("blk-b"), _cid("blk-c")
        chain = [
            ECTipSet(key=[str(blk_a), str(blk_b)], epoch=100, power_table=str(pt0)),
            ECTipSet(key=[str(blk_c)], epoch=101, power_table=str(pt1),
                     commitments=b"\x11" * 32),
        ]
        got = gpbft.payload_marshal_for_signing(
            instance=7,
            ec_chain=chain,
            supplemental_commitments=b"\x22" * 32,
            supplemental_power_table=str(ptn),
            network="filecoin",
        )

        key0 = blk_a.to_bytes() + blk_b.to_bytes()
        key1 = blk_c.to_bytes()
        expected = (
            b"GPBFT:filecoin:"
            + struct.pack(">Q", 7)      # instance
            + struct.pack(">Q", 0)      # round (DECIDE)
            + struct.pack(">B", 5)      # phase = DECIDE
            + b"\x22" * 32              # supplemental commitments
            # ECChain.Key():
            + struct.pack(">q", 100) + bytes(32)
            + struct.pack(">I", len(key0)) + key0 + pt0.to_bytes()
            + struct.pack(">q", 101) + b"\x11" * 32
            + struct.pack(">I", len(key1)) + key1 + pt1.to_bytes()
            + ptn.to_bytes()            # supplemental power table CID
        )
        assert got == expected

    def test_field_sensitivity(self):
        """Every field perturbs the payload (nothing silently ignored)."""
        chain = [ECTipSet(key=[str(_cid("b"))], epoch=5, power_table=str(_cid("p")))]
        base = dict(
            instance=1,
            ec_chain=chain,
            supplemental_commitments=b"",
            supplemental_power_table=str(_cid("n")),
        )
        ref = gpbft.payload_marshal_for_signing(**base)
        assert gpbft.payload_marshal_for_signing(**{**base, "instance": 2}) != ref
        assert gpbft.payload_marshal_for_signing(**{**base, "round_": 1}) != ref
        assert gpbft.payload_marshal_for_signing(**{**base, "phase": 4}) != ref
        assert gpbft.payload_marshal_for_signing(**{**base, "network": "calibnet"}) != ref
        assert (
            gpbft.payload_marshal_for_signing(
                **{**base, "supplemental_commitments": b"\x01" + bytes(31)}
            )
            != ref
        )
        other_chain = [
            ECTipSet(key=[str(_cid("b"))], epoch=6, power_table=str(_cid("p")))
        ]
        assert gpbft.payload_marshal_for_signing(**{**base, "ec_chain": other_chain}) != ref

    def test_negative_epoch_and_bad_commitments(self):
        chain = [ECTipSet(key=[str(_cid("b"))], epoch=-1, power_table=str(_cid("p")))]
        out = gpbft.payload_marshal_for_signing(
            instance=0, ec_chain=chain, supplemental_commitments=b"",
            supplemental_power_table="",
        )
        assert struct.pack(">q", -1) in out  # int64, not uint64
        bad = [ECTipSet(key=[str(_cid("b"))], epoch=0, power_table=str(_cid("p")),
                        commitments=b"\x01\x02")]
        with pytest.raises(ValueError, match="32 bytes"):
            gpbft.payload_marshal_for_signing(
                instance=0, ec_chain=bad, supplemental_commitments=b"",
                supplemental_power_table="",
            )


class TestCertificateWiring:
    def test_signing_payload_uses_gpbft_layout(self):
        chain = [ECTipSet(key=[str(_cid("b"))], epoch=9, power_table=str(_cid("p")))]
        cert = FinalityCertificate(
            instance=3,
            ec_chain=chain,
            supplemental_data=SupplementalData(power_table=str(_cid("n"))),
        )
        assert cert.signing_payload() == gpbft.payload_marshal_for_signing(
            instance=3,
            ec_chain=chain,
            supplemental_commitments=b"",
            supplemental_power_table=str(_cid("n")),
        )
        # network override flows through
        assert cert.signing_payload(network="calibnet") != cert.signing_payload()

    def test_rleplus_signers_roundtrip(self):
        from ipc_proofs_tpu.crypto.rleplus import encode_rleplus

        cert = FinalityCertificate(instance=0, signers=encode_rleplus([0, 2, 5]))
        assert cert.signer_indices() == [0, 2, 5]

    def test_malformed_rleplus_signers_rejected(self):
        cert = FinalityCertificate(instance=0, signers=bytes([0x01]))
        with pytest.raises(ValueError):
            cert.signer_indices()

    def test_empty_signers_conventions(self):
        # b"" = unset dataclass default → no signers; b"\x00" = wire-level
        # empty bitfield (go-bitfield's encoder output for zero runs)
        assert FinalityCertificate(instance=0, signers=b"").signer_indices() == []
        assert FinalityCertificate(instance=0, signers=b"\x00").signer_indices() == []

    def test_wide_bitfield_bounded_by_table_size(self):
        """A few-byte certificate encoding a 2^24-bit run must be rejected
        by the width bound, not materialized (memory-amplification DoS)."""
        from ipc_proofs_tpu.crypto.rleplus import encode_rleplus

        wide = encode_rleplus([1 << 22])  # ~4M-bit bitfield, 6 bytes
        cert = FinalityCertificate(instance=0, signers=wide)
        with pytest.raises(ValueError, match="exceeds"):
            cert.signer_indices(max_index=16)

    def test_cert_cli_validates_signed_cbor_chain(self, tmp_path, capsys):
        """`cli.py cert`: a go-f3-CBOR certificate with a correct table
        commitment and an aggregate signature from a >2/3 quorum validates
        end-to-end (delta replay + commitment + BLS); tampering the
        signature flips the verdict."""
        import json

        from ipc_proofs_tpu import cli
        from ipc_proofs_tpu.crypto import bls
        from ipc_proofs_tpu.crypto.rleplus import encode_rleplus
        from ipc_proofs_tpu.proofs.cert import power_table_cid
        from ipc_proofs_tpu.proofs.cert_cbor import certificate_to_cbor
        from tests.test_bls import KEY_STRS, POPS, POWERS, SKS, _table

        table_rows = _table()
        cert = FinalityCertificate(
            instance=0,
            ec_chain=[
                ECTipSet(key=[str(_cid("b0"))], epoch=100, power_table=str(_cid("pt"))),
                ECTipSet(key=[str(_cid("b1"))], epoch=101, power_table=str(_cid("pt"))),
            ],
            supplemental_data=SupplementalData(
                power_table=str(power_table_cid(table_rows))  # no deltas
            ),
            signers=encode_rleplus([0, 1, 2]),
        )
        payload = cert.signing_payload()
        sig = bls.aggregate_signatures([bls.sign(SKS[i], payload) for i in (0, 1, 2)])
        cert.signature = bls.g2_compress(sig)

        cert_path = tmp_path / "cert.cbor"
        cert_path.write_bytes(certificate_to_cbor(cert))
        table_path = tmp_path / "table.json"
        table_path.write_text(
            json.dumps(
                [
                    {"ParticipantID": i, "Power": POWERS[i],
                     "SigningKey": KEY_STRS[i], "Pop": POPS[i]}
                    for i in range(4)
                ]
            )
        )
        rc = cli.main(
            ["cert", str(cert_path), "--power-table", str(table_path),
             "--verify-signatures"]
        )
        out = json.loads(capsys.readouterr().out)
        assert rc == 0 and out["status"] == "ok", out
        assert out["signatures_verified"] is True
        assert out["final_power_table_rows"] == 4

        # tampered signature must flip the verdict (the encoder emits the
        # signature bytes verbatim; rejection happens at verification)
        bad = FinalityCertificate(**{**cert.__dict__})
        bad.signature = bytes(96)
        bad_path = tmp_path / "bad.cbor"
        bad_path.write_bytes(certificate_to_cbor(bad))
        rc = cli.main(
            ["cert", str(bad_path), "--power-table", str(table_path),
             "--verify-signatures"]
        )
        out = json.loads(capsys.readouterr().out)
        assert rc == 1 and out["status"] == "invalid"

    def test_network_threads_through_verification(self):
        """verify_signature(network=...) verifies a certificate signed for
        a non-default network name (code-review finding: the parameter
        did not thread through, so only 'filecoin' ever verified)."""
        import base64

        from ipc_proofs_tpu.crypto import bls
        from ipc_proofs_tpu.proofs.cert import PowerTableEntry

        sk = 424242
        pk = bls.sk_to_pk(sk)
        table = [
            PowerTableEntry(
                participant_id=0,
                power=10,
                signing_key=base64.b64encode(bls.g1_compress(pk)).decode(),
                pop=base64.b64encode(bls.g2_compress(bls.pop_prove(sk))).decode(),
            )
        ]
        cert = FinalityCertificate(
            instance=1,
            ec_chain=[ECTipSet(key=[str(_cid("b"))], epoch=1, power_table=str(_cid("p")))],
            supplemental_data=SupplementalData(power_table=str(_cid("n"))),
            signers=[0],
        )
        sig = bls.sign(sk, cert.signing_payload(network="calibnet"))
        cert.signature = bls.g2_compress(sig)
        cert.verify_signature(table, network="calibnet")  # verifies
        with pytest.raises(ValueError, match="signature is invalid"):
            cert.verify_signature(table)  # default network: payload differs
