"""Metrics stage-timer concurrency tests: the pipeline executor hammers one
`Metrics` from many worker threads, so `stage()` must accumulate under a
lock, nest re-entrantly per thread, and report honest wall-clock (interval
union) next to additive busy time."""

import threading
import time

from ipc_proofs_tpu.utils.metrics import Metrics


class TestStageThreadSafety:
    def test_eight_threads_hammering_one_stage(self):
        """8 threads × 200 entries each: calls and busy totals must come out
        exact (no lost updates), and the stage wall must not exceed the run's
        real wall-clock."""
        m = Metrics()
        n_threads, n_iters = 8, 200
        barrier = threading.Barrier(n_threads)

        def worker():
            barrier.wait()
            for _ in range(n_iters):
                with m.stage("hammer"):
                    pass
                m.count("hits")

        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        run_wall = time.perf_counter() - t0

        snap = m.snapshot()
        timer = snap["timers"]["hammer"]
        assert timer["calls"] == n_threads * n_iters
        assert snap["counters"]["hits"] == n_threads * n_iters
        assert timer["total_s"] >= 0.0
        # interval union can never exceed the real elapsed wall (+ slack)
        assert timer["wall_s"] <= run_wall + 0.05

    def test_concurrent_stages_report_union_wall(self):
        """N workers sleeping concurrently in one stage: busy sums the per
        -thread elapsed (~N × sleep) while wall_s stays ~one sleep."""
        m = Metrics()
        n_threads, sleep_s = 4, 0.05
        barrier = threading.Barrier(n_threads)

        def worker():
            barrier.wait()
            with m.stage("overlapped"):
                time.sleep(sleep_s)

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        timer = m.snapshot()["timers"]["overlapped"]
        assert timer["total_s"] >= n_threads * sleep_s * 0.9
        assert timer["wall_s"] < n_threads * sleep_s * 0.9  # genuinely unioned
        assert timer["wall_s"] >= sleep_s * 0.9

        eff = m.overlap_efficiency()
        assert eff is not None and eff > 1.5  # 4-way overlap, generous floor

    def test_same_thread_reentry_counts_outermost_only(self):
        """Nested same-name stages on one thread must not double-count: the
        recursive inner spans are already inside the outer interval."""
        m = Metrics()
        with m.stage("recursive"):
            with m.stage("recursive"):
                with m.stage("recursive"):
                    time.sleep(0.02)
        timer = m.snapshot()["timers"]["recursive"]
        assert timer["calls"] == 1
        assert 0.015 <= timer["total_s"] < 0.2
        # busy and wall agree for a single-threaded span
        assert abs(timer["total_s"] - timer["wall_s"]) < 1e-3

    def test_distinct_stage_names_nest_normally(self):
        m = Metrics()
        with m.stage("outer"):
            with m.stage("inner"):
                time.sleep(0.01)
        snap = m.snapshot()["timers"]
        assert snap["outer"]["calls"] == 1 and snap["inner"]["calls"] == 1
        assert snap["outer"]["total_s"] >= snap["inner"]["total_s"]

    def test_serial_stages_efficiency_near_one(self):
        m = Metrics()
        for _ in range(3):
            with m.stage("a"):
                time.sleep(0.01)
            with m.stage("b"):
                time.sleep(0.01)
        eff = m.overlap_efficiency()
        assert eff is not None and 0.9 <= eff <= 1.1
        assert m.snapshot()["overlap_efficiency"] == round(eff, 4)

    def test_no_stages_yet(self):
        m = Metrics()
        assert m.overlap_efficiency() is None
        assert "overlap_efficiency" not in m.snapshot()
